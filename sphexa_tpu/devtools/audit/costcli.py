"""sphexa-audit cost: the static roofline cost gate.

    sphexa-audit cost [--entries ...] [--device v5e] [--json]

Retraces every registered entry (trace-only — no execution, no chip),
walks the jaxpr through the per-primitive cost rules, attributes each
eqn to the step-phase taxonomy via its ``sphexa/<phase>`` name-stack
scope, and classifies the per-phase FLOP / HBM-byte / ICI-byte totals
against a device model into a predicted-ms roofline table. On top of
the table it runs the three cost rules: JXA301 (phase coverage), JXA302
(predicted ms vs the committed ``COST_BUDGET.json`` ceiling) and JXA303
(declared-compute-bound phase below the ridge point), plus the JXA303
REPORT section listing every memory-bound phase — the static ranking of
ROADMAP item-2's fusion/cadence candidates.

Exit codes mirror sphexa-audit: 0 = clean, 1 = findings or entry
errors, 2 = usage error. Calibration against a real capture lives in
``sphexa-telemetry trace <dir> --predict`` (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import traceback
from typing import Any, Dict, List, Optional

from sphexa_tpu.devtools.common import (
    Baseline,
    Finding,
    finish_cli,
    render_table,
)

_COST_RULES = ("JXA301", "JXA302", "JXA303")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-audit cost",
        description="static per-phase roofline cost model: per-primitive "
                    "FLOP/HBM/ICI accounting over the registered entries' "
                    "jaxprs, classified against a device model, gated by "
                    "rules JXA301-JXA303. Chip-free.",
    )
    ap.add_argument("targets", nargs="*", default=["sphexa_tpu"],
                    help="registry modules (default: the package registry)")
    ap.add_argument("--device", default="v5e", metavar="NAME",
                    help="device model to classify against "
                         "(see devtools/audit/devices.py; default: v5e)")
    ap.add_argument("--entries", metavar="NAMES",
                    help="comma-separated entry names (default: all)")
    ap.add_argument("--budget", metavar="FILE",
                    help="COST_BUDGET.json path for JXA302 "
                         "(default: COST_BUDGET.json if present)")
    ap.add_argument("--coverage-min", type=float, metavar="F",
                    help="override the JXA301 phase-coverage floor")
    ap.add_argument("--top", type=int, default=0, metavar="K",
                    help="print only the K heaviest phases per entry "
                         "(default: all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full machine-readable payload (per-entry "
                         "per-phase rows + findings) instead of the table")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="findings render for the non---json path")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline with the current findings")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list suppressed/baselined findings")
    ap.add_argument("--cpu-devices", type=int,
                    default=int(os.environ.get("SPHEXA_AUDIT_DEVICES", "2")),
                    metavar="N",
                    help="bootstrap an N-virtual-device CPU backend so "
                         "sharded entries trace (default: "
                         "$SPHEXA_AUDIT_DEVICES or 2; 0 = ambient backend)")
    return ap


def _fmt_flops(f: float) -> str:
    if f >= 1e9:
        return f"{f / 1e9:.2f}G"
    if f >= 1e6:
        return f"{f / 1e6:.2f}M"
    if f >= 1e3:
        return f"{f / 1e3:.1f}K"
    return f"{f:.0f}"


def _entry_payload(name: str, pred) -> Dict[str, Any]:
    return {
        "entry": name,
        "device": pred.device,
        "coverage": pred.coverage,
        "total_ms": pred.total_ms,
        "total_ms_upper": pred.total_ms_upper,
        "unknown_scopes": list(pred.unknown_scopes),
        "unattributed": pred.unattributed.as_dict(),
        "phases": [r.as_dict() for r in pred.rows],
    }


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.update_baseline and not args.baseline:
        print("sphexa-audit cost: --update-baseline requires --baseline",
              file=sys.stderr)
        return 2

    from sphexa_tpu.devtools.audit.devices import device_names, get_device

    try:
        dev = get_device(args.device)
    except ValueError:
        print(f"sphexa-audit cost: unknown device {args.device!r} "
              f"(known: {', '.join(device_names())})", file=sys.stderr)
        return 2

    if args.cpu_devices and args.cpu_devices > 0:
        from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

        try:
            force_cpu_mesh(args.cpu_devices)
        except RuntimeError as e:
            print(f"sphexa-audit cost: note: CPU-mesh bootstrap skipped "
                  f"({e})", file=sys.stderr)

    from sphexa_tpu.devtools.audit.cli import _load_target
    from sphexa_tpu.devtools.audit.core import (
        Auditor,
        EntrySkip,
        EntryTrace,
        audit_context,
        entries_from_namespace,
        set_audit_context,
    )
    from sphexa_tpu.devtools.audit.costmodel import (
        cost_report,
        memory_bound_phases,
        predict,
    )

    ctx = dataclasses.replace(
        audit_context(),
        cost_device=dev.name,
        **({"cost_budget_path": args.budget} if args.budget else {}),
        **({"phase_coverage_min": args.coverage_min}
           if args.coverage_min is not None else {}),
        **({"mesh_size": args.cpu_devices} if args.cpu_devices > 2 else {}),
    )
    prev = set_audit_context(ctx)
    try:
        entries = []
        for target in args.targets:
            try:
                mod = _load_target(target)
            except (ImportError, OSError, SyntaxError) as e:
                print(f"sphexa-audit cost: cannot load target {target!r}: "
                      f"{e}", file=sys.stderr)
                return 2
            entries += entries_from_namespace(vars(mod))
        if args.entries:
            want = {s.strip() for s in args.entries.split(",") if s.strip()}
            unknown = want - {e.name for e in entries}
            if unknown:
                print(f"sphexa-audit cost: unknown entry name(s): "
                      f"{sorted(unknown)}", file=sys.stderr)
                return 2
            entries = [e for e in entries if e.name in want]

        auditor = Auditor(select=list(_COST_RULES))
        active: List[Finding] = []
        suppressed: List[Finding] = []
        errors: List[Finding] = []
        skipped: List[str] = []
        rows: List[tuple] = []
        payload: List[Dict[str, Any]] = []
        mem_bound: List[str] = []
        # one loop that keeps the traces, so the table and the three
        # rules share a single (expensive) retrace per entry
        for entry in entries:
            try:
                case = entry.build()
            except EntrySkip as e:
                skipped.append(f"{entry.name}: {e}")
                continue
            except Exception as e:  # noqa: BLE001 - reported as JXA000
                errors.append(Finding(
                    rule="JXA000", path=entry.path, line=entry.line, col=0,
                    message=f"[{entry.name}] entry build failed: "
                            f"{e.__class__.__name__}: {e}",
                ))
                continue
            trace = EntryTrace(entry, case)
            table = auditor._suppression_table(entry.path)
            failed = False
            for rule in auditor.rules.values():
                try:
                    found = rule.check(trace)
                except Exception as e:  # noqa: BLE001 - reported as JXA000
                    tb = traceback.format_exc(limit=3)
                    errors.append(Finding(
                        rule="JXA000", path=entry.path, line=entry.line,
                        col=0,
                        message=f"[{entry.name}] {rule.id} crashed: "
                                f"{e.__class__.__name__}: {e}\n{tb}",
                    ))
                    failed = True
                    continue
                for f in found:
                    if table.is_suppressed(f.rule, f.line):
                        suppressed.append(f)
                    else:
                        active.append(f)
            if failed:
                continue
            try:
                pred = predict(cost_report(trace, ctx), dev)
            except Exception as e:  # noqa: BLE001 - reported as JXA000
                errors.append(Finding(
                    rule="JXA000", path=entry.path, line=entry.line, col=0,
                    message=f"[{entry.name}] cost model failed: "
                            f"{e.__class__.__name__}: {e}",
                ))
                continue
            payload.append(_entry_payload(entry.name, pred))
            mem_bound += [f"{entry.name}/{r.phase}"
                          for r in memory_bound_phases(pred, dev)]
            shown = pred.rows[:args.top] if args.top > 0 else pred.rows
            for r in shown:
                rows.append((
                    entry.name, r.phase, r.dtype, _fmt_flops(r.flops),
                    f"{r.ai:.2f}", f"{r.ms:.4f}", r.bound,
                ))
            rows.append((
                entry.name, "= total", "-", _fmt_flops(
                    sum(r.flops for r in pred.rows)
                    + pred.unattributed.flops),
                "-", f"{pred.total_ms:.4f}",
                f"cov={pred.coverage:.3f}",
            ))

        key = lambda f: (f.path, f.line, f.rule, f.message)
        active.sort(key=key)
        suppressed.sort(key=key)
        errors.sort(key=key)

        for note in skipped:
            print(f"sphexa-audit cost: skipped {note}", file=sys.stderr)

        if args.json:
            # machine-readable path: full payload, findings inline
            try:
                baseline = Baseline.load(args.baseline) if args.baseline \
                    else Baseline.empty()
            except (ValueError, OSError) as e:
                print(f"sphexa-audit cost: cannot read baseline "
                      f"{args.baseline}: {e}", file=sys.stderr)
                return 2
            new, grandfathered = baseline.filter_new(active)
            print(json.dumps({
                "tool": "jaxcost",
                "device": dev.name,
                "ridge_f32": dev.ridge("float32"),
                "entries": payload,
                "memory_bound": mem_bound,
                "findings": [f.to_json() for f in new],
                "grandfathered": [f.to_json() for f in grandfathered],
                "suppressed": [f.to_json() for f in suppressed],
                "errors": [f.to_json() for f in errors],
                "skipped": skipped,
            }, indent=2, sort_keys=True))
            return 1 if (new or errors) else 0

        print(render_table(rows, headers=(
            "entry", "phase", "dtype", "flops", "AI", "ms", "bound")))
        print(f"device: {dev.name} (ridge {dev.ridge('float32'):.1f} "
              f"FLOP/B @ float32); predicted ms = "
              f"max(compute, HBM-lower, ICI)")
        if mem_bound:
            print(f"memory-bound phases (AI < ridge): "
                  f"{', '.join(mem_bound)}")
        return finish_cli("sphexa-audit cost", "jaxcost", args,
                          active, suppressed, errors)
    finally:
        set_audit_context(prev)


if __name__ == "__main__":
    sys.exit(main())
