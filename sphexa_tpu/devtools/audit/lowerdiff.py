"""jaxdiff: canonical lowering fingerprints, the committed lock, and the
structural jaxpr differ.

    sphexa-audit lowering [targets] [--lock F] [--diff] [--write]
                          [--entries ...] [--json]

The fifth static-analysis layer (docs/STATIC_ANALYSIS.md): every
registered audit entry's jaxpr is canonicalized — variables renamed in
traversal order, params rendered address-free with nested jaxprs
expanded inline depth-first, consts hashed by shape/dtype/value — and
digested into a ``LoweringFingerprint``: one whole-program digest, one
per-canonical-eqn hash stream, and per-phase sub-digests keyed by the
``util/phases.py`` ``sphexa/<phase>`` name-stack taxonomy (the same
attribution jaxcost and traceview join on). The fingerprints for the
whole registry live in the committed ``LOWERING_LOCK.json``; a digest
mismatch exits 1 with a *structural* diff — first-divergence equation,
per-phase added/removed eqn counts, collective/const deltas — so an
intentional lowering change is reviewed as a diff and re-locked with
``--write``, and an unintentional one never survives to a chip round.

The same canonicalizer powers the JXA402 knob-inertness meta-rule:
``production_knob_probes()`` builds, for every ``KnobSpec`` carrying an
``off_sentinel``, a tiny probe ``Simulation`` with ``tuned={knob: off}``
and compares its step fingerprint against the never-mentioned baseline —
the generalization of the hand-written dt_bins=None / grav_window=0
byte-identity pins to the whole registry with zero per-knob test code.

Alpha-stability contract: two traces of the same program produce
identical fingerprints in the same environment (same jax build, same
virtual device count); tests/test_lowerdiff.py pins this, and ONE raw
``as_text()`` byte-identity pin stays behind in tests/test_parallel.py
as the guard on the canonicalizer itself.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import functools
import hashlib
import json
import os
import re
import sys
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from sphexa_tpu.devtools.audit.core import all_closed_jaxprs
from sphexa_tpu.devtools.audit.spmd import COLLECTIVE_PRIMS

__all__ = [
    "LOCK_VERSION",
    "DEFAULT_LOCK_PATH",
    "LockError",
    "PhaseFingerprint",
    "LoweringFingerprint",
    "fingerprint_closed_jaxpr",
    "fingerprint_callable",
    "lowering_fingerprint",
    "load_lock",
    "write_lock",
    "structural_diff",
    "KnobProbe",
    "production_knob_probes",
    "main",
]

LOCK_VERSION = 1
DEFAULT_LOCK_PATH = "LOWERING_LOCK.json"

#: hex chars per canonical-eqn hash in the lock's eqn streams
_HASH_W = 8
#: phase key for eqns outside every ``sphexa/<phase>`` scope
UNATTRIBUTED = "(unattributed)"

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
# "<lambda> at /path/to/file.py:761" inside source-info reprs: keep the
# name, drop the location, so an unrelated line shift cannot drift the
# lock
_SRCLOC_RE = re.compile(r" at [^\s,()<>]+:\d+")


def _sha(data) -> str:
    if isinstance(data, str):
        data = data.encode()
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------


def _is_jaxprish(v) -> bool:
    return hasattr(v, "eqns") or (
        hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"))


def _eqn_subjaxprs(eqn) -> List[Any]:
    """Raw sub-jaxprs of one eqn, in param order (params sorted by key
    so the inline expansion order is canonical)."""
    subs: List[Any] = []
    for key in sorted(eqn.params, key=str):
        v = eqn.params[key]
        for w in (v if isinstance(v, (list, tuple)) else (v,)):
            # ClosedJaxpr forwards .eqns, so unwrap it FIRST
            if hasattr(w, "jaxpr") and hasattr(getattr(w, "jaxpr"), "eqns"):
                subs.append(w.jaxpr)
            elif hasattr(w, "eqns"):
                subs.append(w)
    return subs


def _aux_jaxpr_digest(v) -> str:
    """Alpha-invariant digest of a jaxpr buried inside a non-jaxpr param
    (e.g. a pallas GridMapping's index_map_jaxpr). These are NOT
    expanded inline by the walk, so their content enters the line as a
    digest of their own canonical rendering — a plain repr would carry
    jax's global pretty-print var counter and drift between traces of
    the same program in one process."""
    raw = getattr(v, "jaxpr", v)
    c = _Canonicalizer()
    c.walk(raw, "")
    sig = ",".join(str(x.aval) for x in
                   tuple(raw.constvars) + tuple(raw.invars))
    return f"jaxpr:{_sha(sig + chr(10) + chr(10).join(c.lines))[:16]}"


def _canon_value(v, inline: bool = False) -> str:
    """Render one param value position-independently: no object
    addresses, dicts sorted, arrays by shape/dtype/value-digest.

    ``inline`` is True exactly where ``_eqn_subjaxprs`` expands jaxpr
    values after the call eqn (direct param values and items of
    list/tuple params) — there a jaxpr renders as a marker; everywhere
    else (dict values, dataclass fields) it renders as an
    alpha-invariant digest."""
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return repr(v)
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, type):
        return f"type:{v.__name__}"
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_canon_value(x, inline) for x in v) + ")"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_canon_value(v[k])}" for k in sorted(v, key=str)) + "}"
    if _is_jaxprish(v):
        return "<jaxpr>" if inline else _aux_jaxpr_digest(v)
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        try:
            a = np.asarray(v)
            # np.asarray wraps ANY object into a 0-d object array whose
            # bytes are its memory address — only hash real numerics
            if a.dtype != np.dtype(object):
                return f"arr({a.shape},{a.dtype},{_sha(a.tobytes())[:16]})"
        except Exception:  # noqa: BLE001 - fall through to repr
            pass
    if dataclasses.is_dataclass(v):
        return f"{type(v).__name__}(" + ",".join(
            f"{f.name}={_canon_value(getattr(v, f.name))}"
            for f in dataclasses.fields(v)) + ")"
    if callable(v):
        return f"fn:{getattr(v, '__name__', type(v).__name__)}"
    return _SRCLOC_RE.sub(" at ·", _ADDR_RE.sub("0x·", repr(v)))


class _Canonicalizer:
    """One walk over a ClosedJaxpr producing canonical per-eqn lines.

    Variables are renamed ``v0, v1, ...`` in traversal order (binders
    first: constvars/invars at jaxpr entry, outvars at their defining
    eqn), so the digest is alpha-invariant. Nested jaxprs (pjit bodies,
    scan/while/cond branches, shard_map bodies) expand inline
    depth-first after their call eqn's own line, inheriting its phase —
    the costmodel._walk convention, so the per-phase sub-digests group
    exactly like the jaxcost/traceview taxonomy.
    """

    def __init__(self):
        self._names: Dict[int, str] = {}
        self.lines: List[str] = []
        self.line_phases: List[str] = []
        self.collectives = 0

    def _name(self, v) -> str:
        return self._names.setdefault(id(v), f"v{len(self._names)}")

    def _atom(self, v) -> str:
        if hasattr(v, "val"):  # Literal
            return f"lit({_canon_value(v.val)}:{getattr(v, 'aval', '?')})"
        return self._name(v)

    def _eqn_line(self, eqn, phase: str) -> str:
        prim = eqn.primitive.name
        params = ",".join(
            f"{k}={_canon_value(eqn.params[k], inline=True)}"
            for k in sorted(eqn.params, key=str))
        ins = " ".join(self._atom(v) for v in eqn.invars)
        outs = " ".join(f"{self._name(v)}:{v.aval}" for v in eqn.outvars)
        return f"{phase}|{outs} = {prim}[{params}] {ins}"

    def walk(self, jaxpr, inherited: str) -> None:
        from sphexa_tpu.devtools.audit.costmodel import _phase_of

        for v in tuple(jaxpr.constvars) + tuple(jaxpr.invars):
            self._name(v)
        for eqn in jaxpr.eqns:
            phase = _phase_of(eqn, inherited)
            self.lines.append(self._eqn_line(eqn, phase))
            self.line_phases.append(phase or UNATTRIBUTED)
            prim = eqn.primitive.name
            # count shard_map's rebound variants too (psum -> psum2)
            if prim in COLLECTIVE_PRIMS or (
                    prim.endswith("2") and prim[:-1] in COLLECTIVE_PRIMS):
                self.collectives += 1
            for sub in _eqn_subjaxprs(eqn):
                self.walk(sub, phase)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PhaseFingerprint:
    digest: str
    eqns: int
    eqn_hashes: str      # _HASH_W hex chars per eqn, traversal order


@dataclasses.dataclass(frozen=True)
class LoweringFingerprint:
    digest: str          # whole-program: canonical lines + consts
    eqns: int
    collectives: int
    const_bytes: int
    consts_digest: str
    phases: Dict[str, PhaseFingerprint]
    eqn_hashes: str      # global per-eqn hash stream, traversal order
    # in-memory only (not persisted in the lock): the canonical lines
    # and their phases, for the structural diff's first-divergence text
    lines: Tuple[str, ...] = dataclasses.field(default=(), repr=False)
    line_phases: Tuple[str, ...] = dataclasses.field(default=(), repr=False)

    def lock_payload(self) -> Dict[str, Any]:
        # the per-phase hash streams are NOT stored: they reconstruct
        # from the global stream + the run-length phase map (phases are
        # contiguous runs in traversal order), halving the lock size
        runs: List[List[Any]] = []
        for ph in self.line_phases:
            if runs and runs[-1][0] == ph:
                runs[-1][1] += 1
            else:
                runs.append([ph, 1])
        return {
            "digest": self.digest,
            "eqns": self.eqns,
            "collectives": self.collectives,
            "const_bytes": self.const_bytes,
            "consts_digest": self.consts_digest,
            "eqn_hashes": self.eqn_hashes,
            "phase_runs": runs,
            "phases": {
                name: {"digest": p.digest, "eqns": p.eqns}
                for name, p in sorted(self.phases.items())
            },
        }


def _consts_fingerprint(closed) -> Tuple[str, int]:
    """(digest, total bytes) over every const of every nested
    ClosedJaxpr, in traversal order — a swapped const is a change even
    when shapes agree."""
    import numpy as np

    h = hashlib.sha256()
    total = 0
    for cj in all_closed_jaxprs(closed):
        for c in cj.consts:
            try:
                a = np.asarray(c)
                if a.dtype == np.dtype(object):  # address bytes — no
                    raise TypeError("object const")
                h.update(f"{a.shape}:{a.dtype}:".encode())
                h.update(a.tobytes())
                total += a.nbytes
            except Exception:  # noqa: BLE001 - non-array const
                h.update(_canon_value(c).encode())
    return h.hexdigest()[:32], total


def fingerprint_closed_jaxpr(closed) -> LoweringFingerprint:
    """Canonicalize + digest one ClosedJaxpr (the tentpole primitive)."""
    canon = _Canonicalizer()
    canon.walk(closed.jaxpr, "")
    line_hashes = [_sha(ln)[:_HASH_W] for ln in canon.lines]
    consts_digest, const_bytes = _consts_fingerprint(closed)
    by_phase: Dict[str, List[str]] = collections.defaultdict(list)
    by_phase_h: Dict[str, List[str]] = collections.defaultdict(list)
    for ln, ph, lh in zip(canon.lines, canon.line_phases, line_hashes):
        by_phase[ph].append(ln)
        by_phase_h[ph].append(lh)
    phases = {
        ph: PhaseFingerprint(
            digest=_sha("\n".join(lns))[:32],
            eqns=len(lns),
            eqn_hashes="".join(by_phase_h[ph]),
        )
        for ph, lns in by_phase.items()
    }
    digest = _sha("\n".join(canon.lines) + "\n#" + consts_digest)[:32]
    return LoweringFingerprint(
        digest=digest,
        eqns=len(canon.lines),
        collectives=canon.collectives,
        const_bytes=const_bytes,
        consts_digest=consts_digest,
        phases=phases,
        eqn_hashes="".join(line_hashes),
        lines=tuple(canon.lines),
        line_phases=tuple(canon.line_phases),
    )


def fingerprint_callable(fn: Callable, *args) -> LoweringFingerprint:
    """Trace ``fn(*args)`` and fingerprint it — the shared helper the
    migrated byte-identity pins (tests/test_blockdt.py,
    tests/test_parallel.py) and the knob probes build on."""
    import jax

    return fingerprint_closed_jaxpr(jax.make_jaxpr(fn)(*args))


def lowering_fingerprint(trace) -> LoweringFingerprint:
    """Cached per-entry fingerprint (the spmd_report/cost_report cache
    contract: one canonical walk per EntryTrace, shared by the lock CLI
    and the JXA4xx rules)."""
    cached = getattr(trace, "_lowering_fp", None)
    if cached is not None:
        return cached
    fp = fingerprint_closed_jaxpr(trace.closed_jaxpr)
    trace._lowering_fp = fp
    return fp


# ---------------------------------------------------------------------------
# lock IO
# ---------------------------------------------------------------------------


class LockError(ValueError):
    """Unreadable/corrupt/wrong-version lock file (CLI exit 2)."""


def load_lock(path) -> Dict[str, Dict[str, Any]]:
    p = Path(path)
    try:
        payload = json.loads(p.read_text())
    except OSError as e:
        raise LockError(f"cannot read lock {p}: {e}") from e
    except json.JSONDecodeError as e:
        raise LockError(f"corrupt lock {p}: {e}") from e
    if not isinstance(payload, dict) or "entries" not in payload:
        raise LockError(f"corrupt lock {p}: no 'entries' object")
    if payload.get("version") != LOCK_VERSION:
        raise LockError(
            f"lock {p} has version {payload.get('version')!r}, this tool "
            f"writes {LOCK_VERSION} (regenerate with --write)")
    return payload["entries"]


def write_lock(path, entries: Dict[str, Dict[str, Any]]) -> None:
    p = Path(path)
    payload = {
        "version": LOCK_VERSION,
        "tool": "jaxdiff",
        "comment": "canonical lowering fingerprints per audit entry; "
                   "regenerate with: sphexa-audit lowering --write",
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    p.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")


# ---------------------------------------------------------------------------
# structural diff
# ---------------------------------------------------------------------------


def _chunks(stream: str) -> List[str]:
    return [stream[i:i + _HASH_W] for i in range(0, len(stream), _HASH_W)]


def _locked_phase_hashes(locked: Dict[str, Any]) -> Dict[str, List[str]]:
    """Per-phase eqn-hash lists of a locked row, reconstructed from the
    global stream + the run-length phase map."""
    out: Dict[str, List[str]] = collections.defaultdict(list)
    chunks = _chunks(locked.get("eqn_hashes", ""))
    i = 0
    for ph, n in locked.get("phase_runs", []):
        out[ph] += chunks[i:i + int(n)]
        i += int(n)
    return out


def structural_diff(name: str, locked: Dict[str, Any],
                    fp: LoweringFingerprint,
                    verbose: bool = False) -> List[str]:
    """Human-readable structural diff of one entry vs its locked row —
    the PR-review artifact an intentional lowering change produces."""
    out: List[str] = []
    out.append(f"entry {name}: lowering drifted from the lock")
    out.append(f"  digest: {locked.get('digest')} -> {fp.digest}")
    for field in ("eqns", "collectives", "const_bytes"):
        old = locked.get(field)
        new = getattr(fp, field)
        delta = ""
        if isinstance(old, int):
            d = new - old
            delta = f"  ({d:+d})" if d else ""
        if old != new or delta:
            out.append(f"  {field}: {old} -> {new}{delta}")
    if locked.get("consts_digest") != fp.consts_digest:
        out.append(f"  consts: {locked.get('consts_digest')} -> "
                   f"{fp.consts_digest}")

    old_stream = _chunks(locked.get("eqn_hashes", ""))
    new_stream = _chunks(fp.eqn_hashes)
    div = next((i for i, (a, b) in enumerate(zip(old_stream, new_stream))
                if a != b), None)
    if div is None and len(old_stream) != len(new_stream):
        div = min(len(old_stream), len(new_stream))
    if div is None:
        out.append("  no per-eqn divergence (consts changed, or the lock "
                   "digest itself was edited)")
    else:
        phase = (fp.line_phases[div] if div < len(fp.line_phases)
                 else "(past end of current program)")
        out.append(f"  first divergence: eqn #{div} (phase {phase})")
        if div < len(fp.lines):
            out.append(f"    now: {fp.lines[div]}")
        else:
            out.append(f"    now: <program ends at eqn "
                       f"#{len(fp.lines) - 1}; locked stream continues>")

    # per-phase added/removed counts via eqn-hash multiset difference
    locked_phases = locked.get("phases", {})
    locked_hashes = _locked_phase_hashes(locked)
    all_phases = sorted(set(locked_phases) | set(fp.phases))
    phase_rows: List[str] = []
    for ph in all_phases:
        lp = locked_phases.get(ph)
        np_ = fp.phases.get(ph)
        if lp is None:
            phase_rows.append(f"    + {ph}: added ({np_.eqns} eqns)")
            continue
        if np_ is None:
            phase_rows.append(f"    - {ph}: removed ({lp.get('eqns')} eqns)")
            continue
        if lp.get("digest") == np_.digest:
            continue
        old_c = collections.Counter(locked_hashes.get(ph, []))
        new_c = collections.Counter(_chunks(np_.eqn_hashes))
        added = sum((new_c - old_c).values())
        removed = sum((old_c - new_c).values())
        note = "reordered" if not (added or removed) else \
            f"+{added}/-{removed} eqns"
        phase_rows.append(f"    ~ {ph}: {note} "
                          f"({lp.get('eqns')} -> {np_.eqns})")
    if phase_rows:
        out.append("  phases:")
        out += phase_rows
    if verbose and div is not None:
        lo = max(0, div - 2)
        hi = min(len(fp.lines), div + 6)
        out.append(f"  canonical context (current program, eqns "
                   f"#{lo}-#{hi - 1}):")
        out += [f"    {i}: {fp.lines[i]}" for i in range(lo, hi)]
    return out


def _deltas(locked: Dict[str, Any], fp: LoweringFingerprint
            ) -> Dict[str, Any]:
    """Machine-readable mismatch summary for the --json payload."""
    old_stream = _chunks(locked.get("eqn_hashes", ""))
    new_stream = _chunks(fp.eqn_hashes)
    div = next((i for i, (a, b) in enumerate(zip(old_stream, new_stream))
                if a != b), None)
    if div is None and len(old_stream) != len(new_stream):
        div = min(len(old_stream), len(new_stream))
    locked_phases = locked.get("phases", {})
    return {
        "eqns": fp.eqns - int(locked.get("eqns", 0)),
        "collectives": fp.collectives - int(locked.get("collectives", 0)),
        "const_bytes": fp.const_bytes - int(locked.get("const_bytes", 0)),
        "consts_changed": locked.get("consts_digest") != fp.consts_digest,
        "first_divergence": div,
        "first_divergence_phase": (
            fp.line_phases[div]
            if div is not None and div < len(fp.line_phases) else None),
        "phases_added": sorted(set(fp.phases) - set(locked_phases)),
        "phases_removed": sorted(set(locked_phases) - set(fp.phases)),
        "phases_changed": sorted(
            ph for ph in set(fp.phases) & set(locked_phases)
            if locked_phases[ph].get("digest") != fp.phases[ph].digest),
    }


# ---------------------------------------------------------------------------
# JXA402 knob-inertness probes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KnobProbe:
    """One off-vs-unset comparison: the knob, its off value, and the
    two fingerprints the JXA402 rule compares."""

    knob: str
    off_value: object
    base: LoweringFingerprint
    off: LoweringFingerprint
    detail: str = ""


#: probe workload sides: big enough for a real neighbor grid / gravity
#: tree (the registry's tiny-but-nondegenerate convention)
_PROBE_SIDE = 6


@functools.lru_cache(maxsize=None)
def _probe_fp(prop_name: str, tuned_items: Tuple[Tuple[str, Any], ...]
              ) -> LoweringFingerprint:
    """Fingerprint of the step program a probe Simulation would launch.

    The fingerprinted callable is ``sim._step_fn(donated=
    sim._donate_active)`` — the EXACT launch routing, so a knob that
    silently re-routes the step (donate twins, a leaked blockdt branch)
    shows up even when the per-eqn bodies agree.
    """
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.simulation import Simulation

    case = "evrard" if prop_name == "nbody" else "sedov"
    state, box, const = make_initializer(case)(_PROBE_SIDE)
    tuned = dict(tuned_items)
    sim = Simulation(state, box, const, prop=prop_name,
                     tuned=tuned or None)
    if sim._blockdt:
        raise RuntimeError(
            "knob probe unexpectedly activated block time steps "
            f"(tuned={tuned!r}) — off sentinels must stay on the "
            "baseline path")
    cfg = sim._cfg
    fn = sim._step_fn(donated=sim._donate_active)
    if prop_name == "nbody":
        return fingerprint_callable(
            lambda s, b, g: fn(s, b, cfg, g),
            sim.state, sim.box, sim._gtree)
    return fingerprint_callable(
        lambda s, b: fn(s, b, cfg, None), sim.state, sim.box)


def production_knob_probes() -> List[KnobProbe]:
    """Off-vs-unset probes for every off-sentinel KnobSpec — the JXA402
    payload of the ``knob_inertness`` registry entry. Driven entirely by
    the tuning knob registry: a new knob declares ``off_sentinel=...``
    and is probed here with zero per-knob code. GravityConfig-owned
    knobs probe the nbody step (the std probe has no gravity stage to
    leak into); everything else probes the std step."""
    from sphexa_tpu.tuning.knobs import (
        off_sentinel_knobs,
        validate_off_sentinels,
    )

    # fail LOUDLY on a renamed resolution site before trusting any
    # probe result (the satellite-6 contract)
    validate_off_sentinels()
    probes: List[KnobProbe] = []
    for spec in off_sentinel_knobs():
        prop_name = "nbody" if spec.owner == "GravityConfig" else "std"
        base = _probe_fp(prop_name, ())
        off = _probe_fp(prop_name, ((spec.name, spec.off_sentinel),))
        probes.append(KnobProbe(
            knob=spec.name, off_value=spec.off_sentinel,
            base=base, off=off,
            detail=f"prop={prop_name} side={_PROBE_SIDE} "
                   f"tuned={{{spec.name}: {spec.off_sentinel!r}}} vs unset",
        ))
    return probes


# ---------------------------------------------------------------------------
# CLI: sphexa-audit lowering
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-audit lowering",
        description="jaxdiff: verify every registered entry's canonical "
                    "lowering fingerprint against the committed "
                    "LOWERING_LOCK.json; mismatches exit 1 with a "
                    "phase-attributed structural diff. Re-lock an "
                    "intentional change with --write.",
    )
    ap.add_argument("targets", nargs="*", default=["sphexa_tpu"],
                    help="registry modules (default: the package registry)")
    ap.add_argument("--lock", default=DEFAULT_LOCK_PATH, metavar="FILE",
                    help=f"lock file (default: {DEFAULT_LOCK_PATH})")
    ap.add_argument("--write", action="store_true",
                    help="rewrite the lock from the current fingerprints "
                         "(merges over rows of entries not audited in "
                         "this run) and exit 0")
    ap.add_argument("--diff", action="store_true",
                    help="print canonical-eqn context around the first "
                         "divergence of each mismatching entry")
    ap.add_argument("--entries", metavar="NAMES",
                    help="comma-separated entry names (default: all; "
                         "staleness of lock rows is only checked on "
                         "full-registry runs)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable payload (per-entry "
                         "digest/deltas) instead of the text report")
    ap.add_argument("--cpu-devices", type=int,
                    default=int(os.environ.get("SPHEXA_AUDIT_DEVICES", "2")),
                    metavar="N",
                    help="bootstrap an N-virtual-device CPU backend so "
                         "sharded entries trace (default: "
                         "$SPHEXA_AUDIT_DEVICES or 2; 0 = ambient "
                         "backend). The committed lock is written at "
                         "the default mesh.")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.cpu_devices and args.cpu_devices > 0:
        from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

        try:
            force_cpu_mesh(args.cpu_devices)
        except RuntimeError as e:
            print(f"sphexa-audit lowering: note: CPU-mesh bootstrap "
                  f"skipped ({e})", file=sys.stderr)

    import dataclasses as _dc

    from sphexa_tpu.devtools.audit.cli import _load_target
    from sphexa_tpu.devtools.audit.core import (
        EntrySkip,
        EntryTrace,
        audit_context,
        entries_from_namespace,
        set_audit_context,
    )

    ctx = audit_context()
    if args.cpu_devices > 2:
        ctx = _dc.replace(ctx, mesh_size=args.cpu_devices)
    prev = set_audit_context(ctx)
    try:
        entries = []
        for target in args.targets:
            try:
                mod = _load_target(target)
            except (ImportError, OSError, SyntaxError) as e:
                print(f"sphexa-audit lowering: cannot load target "
                      f"{target!r}: {e}", file=sys.stderr)
                return 2
            entries += entries_from_namespace(vars(mod))
        filtered = bool(args.entries)
        if filtered:
            want = {s.strip() for s in args.entries.split(",") if s.strip()}
            unknown = want - {e.name for e in entries}
            if unknown:
                print(f"sphexa-audit lowering: unknown entry name(s): "
                      f"{sorted(unknown)}", file=sys.stderr)
                return 2
            entries = [e for e in entries if e.name in want]

        locked: Dict[str, Dict[str, Any]] = {}
        if not args.write or Path(args.lock).exists():
            try:
                locked = load_lock(args.lock)
            except LockError as e:
                if args.write and not Path(args.lock).exists():
                    locked = {}
                else:
                    print(f"sphexa-audit lowering: {e}", file=sys.stderr)
                    return 2

        current: Dict[str, LoweringFingerprint] = {}
        errors: List[str] = []
        skipped: List[str] = []
        for entry in entries:
            try:
                case = entry.build()
                current[entry.name] = lowering_fingerprint(
                    EntryTrace(entry, case))
            except EntrySkip as e:
                skipped.append(f"{entry.name}: {e}")
            except Exception as e:  # noqa: BLE001 - reported, exit 1
                errors.append(f"{entry.name}: {e.__class__.__name__}: {e}")

        if args.write:
            merged = dict(locked)
            for name, fp in current.items():
                merged[name] = fp.lock_payload()
            write_lock(args.lock, merged)
            print(f"sphexa-audit lowering: wrote {len(current)} "
                  f"fingerprint(s) to {args.lock} "
                  f"({len(merged)} total)")
            for note in skipped:
                print(f"sphexa-audit lowering: skipped {note}",
                      file=sys.stderr)
            return 1 if errors else 0

        mismatched: List[str] = []
        missing: List[str] = []
        stale: List[str] = []
        report: List[str] = []
        payload: List[Dict[str, Any]] = []
        for name, fp in current.items():
            row = locked.get(name)
            if row is None:
                missing.append(name)
                payload.append({"entry": name, "digest": fp.digest,
                                "locked_digest": None, "match": False,
                                "eqns": fp.eqns, "deltas": None})
                continue
            match = row.get("digest") == fp.digest
            payload.append({
                "entry": name, "digest": fp.digest,
                "locked_digest": row.get("digest"), "match": match,
                "eqns": fp.eqns, "collectives": fp.collectives,
                "const_bytes": fp.const_bytes,
                "deltas": None if match else _deltas(row, fp),
            })
            if not match:
                mismatched.append(name)
                report += structural_diff(name, row, fp,
                                          verbose=args.diff)
        if not filtered:
            audited = set(current) | {s.split(":", 1)[0] for s in skipped}
            stale = sorted(set(locked) - audited)

        bad = bool(mismatched or missing or stale or errors)
        if args.json:
            print(json.dumps({
                "tool": "jaxdiff",
                "lock": str(args.lock),
                "entries": payload,
                "mismatched": sorted(mismatched),
                "missing_from_lock": sorted(missing),
                "stale_lock_rows": stale,
                "errors": errors,
                "skipped": skipped,
            }, indent=2, sort_keys=True))
            return 1 if bad else 0

        for note in skipped:
            print(f"sphexa-audit lowering: skipped {note}", file=sys.stderr)
        for line in report:
            print(line)
        for name in missing:
            print(f"entry {name}: not in the lock (re-lock with --write)")
        for name in stale:
            print(f"lock row {name}: no such registry entry (stale — "
                  f"re-lock with --write)")
        for err in errors:
            print(f"entry error: {err}", file=sys.stderr)
        ok = len(current) - len(mismatched) - len(missing)
        print(f"sphexa-audit lowering: {ok}/{len(current)} entries match "
              f"{args.lock}"
              + (f"; {len(mismatched)} mismatched" if mismatched else "")
              + (f"; {len(missing)} unlocked" if missing else "")
              + (f"; {len(stale)} stale" if stale else "")
              + (f"; {len(errors)} errors" if errors else ""))
        return 1 if bad else 0
    finally:
        set_audit_context(prev)


if __name__ == "__main__":
    sys.exit(main())
