"""sphexa-audit preflight: the SPMD campaign gate.

    sphexa-audit preflight [--mesh P] [--n N] [--hbm-budget BYTES]

Bootstraps a P-virtual-device CPU mesh, retraces every registered entry
on it, runs the three shardcheck rules (JXA201 collective order, JXA202
peak-HBM liveness vs budget, JXA203 sharding propagation), and prints a
per-entry table: collectives traced, chain status, estimated peak HBM
per device at the toy N and rescaled to campaign shapes, replicated
particle bytes, and measured exchange bytes vs the analytic budget.

Exit codes mirror sphexa-audit: 0 = clean, 1 = findings or entry
errors, 2 = usage error. Run it before burning chip minutes — every
failure class it gates (the PR-5 rendezvous race, a per-device OOM at
64M/P=16, a partitioner-inserted all-gather of particle fields) is
cheaper to catch here than on the first campaign launch.
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback
from typing import List, Optional

from sphexa_tpu.devtools.common import Finding, finish_cli, render_table

_PREFLIGHT_RULES = ("JXA201", "JXA202", "JXA203")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-audit preflight",
        description="SPMD preflight auditor: collective-order races, "
                    "donation-aware peak-HBM vs device budget, and "
                    "sharding-propagation over the registered sharded "
                    "entries, chip-free on a virtual CPU mesh.",
    )
    ap.add_argument("targets", nargs="*", default=["sphexa_tpu"],
                    help="registry modules (default: the package registry)")
    ap.add_argument("--mesh", type=int, metavar="P",
                    default=int(os.environ.get("SPHEXA_AUDIT_DEVICES", "4")),
                    help="virtual CPU mesh size the sharded entries trace "
                         "on (default: $SPHEXA_AUDIT_DEVICES or 4)")
    ap.add_argument("--n", type=int, default=64_000_000, metavar="N",
                    help="campaign particle count for the JXA202 rescale "
                         "(default: 64M)")
    ap.add_argument("--devices", type=int, default=16, metavar="P",
                    help="campaign device count (default: 16, v5e-16)")
    ap.add_argument("--hbm-budget", type=int, default=16 << 30,
                    metavar="BYTES",
                    help="per-device HBM budget in bytes (default: 16 GiB)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--json", action="store_true",
                    help="emit the full machine-readable payload (per-entry "
                         "rows + campaign parameters + findings) instead of "
                         "the table; supersedes --format")
    ap.add_argument("--entries", metavar="NAMES",
                    help="comma-separated entry names (default: all)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline with the current findings")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list suppressed/baselined findings")
    return ap


def _row(name: str, rep) -> tuple:
    from sphexa_tpu.devtools.audit.spmd import format_bytes

    chain = ("ok" if not rep.unordered_pairs
             else f"RACE({len(rep.unordered_pairs)})")
    repl = sum(r.campaign_bytes for r in rep.replicated)
    return (
        name,
        len(rep.collectives),
        chain,
        format_bytes(rep.toy_peak_bytes),
        format_bytes(rep.campaign_peak_bytes),
        format_bytes(repl) if rep.replicated else "-",
        format_bytes(rep.collective_out_bytes) if rep.collectives else "-",
    )


def _entry_payload(name: str, rep, case) -> dict:
    """Machine-readable per-entry preflight row (the --json contract:
    everything the text table shows, in bytes, plus the declared
    exchange budget the text table folds into JXA203)."""
    return {
        "entry": name,
        "mesh_size": rep.mesh_size,
        "collectives": len(rep.collectives),
        "chain": "ok" if not rep.unordered_pairs else "race",
        "unordered_pairs": len(rep.unordered_pairs),
        "toy_peak_bytes": rep.toy_peak_bytes,
        "campaign_peak_bytes": rep.campaign_peak_bytes,
        "toy_slab_rows": rep.toy_slab_rows,
        "campaign_ratio": rep.campaign_ratio,
        "n_global": rep.n_global,
        "replicated_campaign_bytes":
            sum(r.campaign_bytes for r in rep.replicated),
        "exchange_bytes": rep.collective_out_bytes,
        "exchange_budget_bytes": getattr(case, "exchange_budget_bytes",
                                         None),
    }


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.mesh < 2:
        print("sphexa-audit preflight: --mesh must be >= 2", file=sys.stderr)
        return 2
    if args.update_baseline and not args.baseline:
        print("sphexa-audit preflight: --update-baseline requires "
              "--baseline", file=sys.stderr)
        return 2

    from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

    try:
        force_cpu_mesh(args.mesh)
    except RuntimeError as e:
        # in-process use with the backend already up: sharded entries
        # skip themselves if the ambient mesh can't host --mesh devices
        print(f"sphexa-audit preflight: note: CPU-mesh bootstrap skipped "
              f"({e})", file=sys.stderr)

    from sphexa_tpu.devtools.audit.cli import _load_target
    from sphexa_tpu.devtools.audit.core import (
        AuditContext,
        Auditor,
        EntrySkip,
        EntryTrace,
        entries_from_namespace,
        set_audit_context,
    )
    from sphexa_tpu.devtools.audit.spmd import spmd_report

    ctx = AuditContext(
        mesh_size=args.mesh, campaign_n=args.n,
        campaign_devices=args.devices, hbm_budget_bytes=args.hbm_budget,
    )
    prev = set_audit_context(ctx)
    try:
        entries = []
        for target in args.targets:
            try:
                mod = _load_target(target)
            except (ImportError, OSError, SyntaxError) as e:
                print(f"sphexa-audit preflight: cannot load target "
                      f"{target!r}: {e}", file=sys.stderr)
                return 2
            entries += entries_from_namespace(vars(mod))
        if args.entries:
            want = {s.strip() for s in args.entries.split(",") if s.strip()}
            unknown = want - {e.name for e in entries}
            if unknown:
                print(f"sphexa-audit preflight: unknown entry name(s): "
                      f"{sorted(unknown)}", file=sys.stderr)
                return 2
            entries = [e for e in entries if e.name in want]

        auditor = Auditor(select=list(_PREFLIGHT_RULES))
        active: List[Finding] = []
        suppressed: List[Finding] = []
        errors: List[Finding] = []
        skipped: List[str] = []
        rows: List[tuple] = []
        payload: List[dict] = []
        # one loop that keeps the traces, so the table and the three
        # rules share a single (expensive) retrace per entry
        for entry in entries:
            try:
                case = entry.build()
            except EntrySkip as e:
                skipped.append(f"{entry.name}: {e}")
                continue
            except Exception as e:  # noqa: BLE001 - reported as JXA000
                errors.append(Finding(
                    rule="JXA000", path=entry.path, line=entry.line, col=0,
                    message=f"[{entry.name}] entry build failed: "
                            f"{e.__class__.__name__}: {e}",
                ))
                continue
            trace = EntryTrace(entry, case)
            table = auditor._suppression_table(entry.path)
            failed = False
            for rule in auditor.rules.values():
                try:
                    found = rule.check(trace)
                except Exception as e:  # noqa: BLE001 - reported as JXA000
                    tb = traceback.format_exc(limit=3)
                    errors.append(Finding(
                        rule="JXA000", path=entry.path, line=entry.line,
                        col=0,
                        message=f"[{entry.name}] {rule.id} crashed: "
                                f"{e.__class__.__name__}: {e}\n{tb}",
                    ))
                    failed = True
                    continue
                for f in found:
                    if table.is_suppressed(f.rule, f.line):
                        suppressed.append(f)
                    else:
                        active.append(f)
            if not failed:
                rep = spmd_report(trace, ctx)
                rows.append(_row(entry.name, rep))
                payload.append(_entry_payload(entry.name, rep, case))

        key = lambda f: (f.path, f.line, f.rule, f.message)
        active.sort(key=key)
        suppressed.sort(key=key)
        errors.sort(key=key)

        for note in skipped:
            print(f"sphexa-audit preflight: skipped {note}",
                  file=sys.stderr)

        if args.json:
            # machine-readable path: per-entry rows, campaign
            # parameters, and the findings, one document
            import json

            from sphexa_tpu.devtools.common import Baseline

            try:
                baseline = Baseline.load(args.baseline) if args.baseline \
                    else Baseline.empty()
            except (ValueError, OSError) as e:
                print(f"sphexa-audit preflight: cannot read baseline "
                      f"{args.baseline}: {e}", file=sys.stderr)
                return 2
            new, grandfathered = baseline.filter_new(active)
            print(json.dumps({
                "tool": "jaxaudit-preflight",
                "campaign": {
                    "n": args.n, "devices": args.devices,
                    "hbm_budget_bytes": args.hbm_budget,
                    "traced_mesh": args.mesh,
                },
                "entries": payload,
                "findings": [f.to_json() for f in new],
                "grandfathered": [f.to_json() for f in grandfathered],
                "suppressed": [f.to_json() for f in suppressed],
                "errors": [f.to_json() for f in errors],
                "skipped": skipped,
            }, indent=2, sort_keys=True))
            return 1 if (new or errors) else 0

        if args.format == "text":
            print(render_table(rows, headers=(
                "entry", "coll", "chain", "peak/dev",
                f"peak/dev@{args.n}/{args.devices}", "replicated",
                "exchange")))
            print(f"campaign: N={args.n} P={args.devices} "
                  f"budget={args.hbm_budget} B/device; traced mesh "
                  f"P={args.mesh}")
        return finish_cli("sphexa-audit preflight", "jaxaudit", args,
                          active, suppressed, errors)
    finally:
        set_audit_context(prev)


if __name__ == "__main__":
    sys.exit(main())
