"""jaxlint CLI.

    python -m sphexa_tpu.devtools.lint sphexa_tpu
    sphexa-lint sphexa_tpu --format json
    sphexa-lint sphexa_tpu --baseline jaxlint_baseline.json --update-baseline

Exit status: 0 = clean (no non-baselined findings), 1 = findings or
parse errors, 2 = usage error. Pure stdlib + ast: does not import jax or
any scanned module, so it is safe in pre-device-setup contexts (CI
images without an accelerator, pre-commit hooks).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from sphexa_tpu.devtools.common import finish_cli
from sphexa_tpu.devtools.lint.core import Analyzer, all_rules


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-lint",
        description="jaxlint: AST static analysis for jit/tracer/dtype/"
                    "Pallas hygiene (rules JXL001-JXL005).",
    )
    ap.add_argument("paths", nargs="*", default=["sphexa_tpu"],
                    help="files or directories to scan "
                         "(default: sphexa_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline with the current findings "
                         "and exit 0")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list inline-suppressed and baselined "
                         "findings (text format)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules().values():
            print(f"{rule.id}  {rule.name}: {rule.description}")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        analyzer = Analyzer(select=select)
    except ValueError as e:
        print(f"sphexa-lint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline and not args.baseline:
        print("sphexa-lint: --update-baseline requires --baseline",
              file=sys.stderr)
        return 2

    active, suppressed, errors = analyzer.run_paths(args.paths)
    return finish_cli("sphexa-lint", "jaxlint", args, active, suppressed,
                      errors)


if __name__ == "__main__":
    sys.exit(main())
