"""Analyzer scaffolding: parsed-module model, rule registry, suppressions.

Design notes
------------
- One ``ModuleInfo`` per file: source, AST, import-alias map, and the
  suppression table parsed from comments. Rules are stateless visitors
  that take a ``ModuleInfo`` and return ``Finding``s; the analyzer owns
  filtering (suppressions, rule selection, baseline happens in the CLI).
- Alias resolution is syntactic: ``import jax.numpy as jnp`` makes the
  name ``jnp`` resolve to ``jax.numpy``, so rules match on canonical
  dotted paths (``jax.numpy.zeros``) and survive local import styles
  (``from jax.experimental import pallas as pl``). No code is imported.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from sphexa_tpu.devtools.common import (
    Finding,
    SuppressionTable,
    make_disable_re,
)
from sphexa_tpu.devtools.common import (
    parse_suppressions as _parse_suppressions,
)

__all__ = [
    "Finding",
    "ModuleInfo",
    "Rule",
    "register",
    "all_rules",
    "Analyzer",
    "lint_paths",
]

# the Finding / SuppressionTable / Baseline machinery is shared with the
# trace-level auditor (devtools/common.py); only the directive tool name
# differs between the two gates
_DISABLE_RE = make_disable_re("jaxlint")


def parse_suppressions(source: str) -> SuppressionTable:
    return _parse_suppressions(source, _DISABLE_RE)


# ---------------------------------------------------------------------------
# parsed module + alias resolution
# ---------------------------------------------------------------------------


class ModuleInfo:
    """A parsed source file plus the lookups every rule needs."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.suppressions = parse_suppressions(source)
        self.aliases = self._collect_aliases(tree)

    @classmethod
    def from_file(cls, path: str) -> "ModuleInfo":
        source = Path(path).read_text()
        tree = ast.parse(source, filename=path)
        return cls(Path(path).as_posix(), source, tree)

    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        """Map local names to canonical dotted module/attribute paths,
        from every import statement in the file (any nesting level —
        this repo imports jnp inside functions routinely)."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:          # relative import: keep it unresolved
                    continue
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, resolving the
        root through the import-alias map; None for non-name expressions
        (calls, subscripts) anywhere in the chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.line_at(getattr(node, "lineno", 1)),
        )


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    check: Callable[[ModuleInfo], List[Finding]]


_REGISTRY: Dict[str, Rule] = {}


def register(id: str, name: str, description: str):
    """Decorator: register ``check(module) -> [Finding]`` under a rule id."""

    def deco(fn: Callable[[ModuleInfo], List[Finding]]):
        if id in _REGISTRY:
            raise ValueError(f"duplicate rule id {id}")
        _REGISTRY[id] = Rule(id=id, name=name, description=description,
                             check=fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    # importing the rules package populates the registry
    import sphexa_tpu.devtools.lint.rules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# analyzer
# ---------------------------------------------------------------------------


class Analyzer:
    def __init__(self, select: Optional[Sequence[str]] = None):
        rules = all_rules()
        if select:
            unknown = set(select) - set(rules)
            if unknown:
                raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
            rules = {k: v for k, v in rules.items() if k in select}
        self.rules = rules

    def run_module(self, module: ModuleInfo) -> Tuple[List[Finding],
                                                      List[Finding]]:
        """(active, suppressed) findings for one parsed module."""
        active: List[Finding] = []
        suppressed: List[Finding] = []
        for rule in self.rules.values():
            for f in rule.check(module):
                if module.suppressions.is_suppressed(f.rule, f.line):
                    suppressed.append(f)
                else:
                    active.append(f)
        key = lambda f: (f.path, f.line, f.col, f.rule)
        return sorted(active, key=key), sorted(suppressed, key=key)

    def run_paths(self, paths: Iterable[str]) -> Tuple[List[Finding],
                                                       List[Finding],
                                                       List[Finding]]:
        """(active, suppressed, errors) over files and directory trees.

        Unparseable files become pseudo-findings with rule ``JXL000`` so a
        syntax error can't silently shrink coverage.
        """
        active: List[Finding] = []
        suppressed: List[Finding] = []
        errors: List[Finding] = []
        for path in sorted(self._expand(paths)):
            try:
                module = ModuleInfo.from_file(path)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                errors.append(Finding(
                    rule="JXL000", path=Path(path).as_posix(),
                    line=getattr(e, "lineno", None) or 1, col=0,
                    message=f"could not parse: {e.__class__.__name__}: {e}",
                ))
                continue
            a, s = self.run_module(module)
            active += a
            suppressed += s
        return active, suppressed, errors

    @staticmethod
    def _expand(paths: Iterable[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            pp = Path(p)
            if pp.is_dir():
                out += [str(f) for f in pp.rglob("*.py")
                        if "__pycache__" not in f.parts]
            else:
                out.append(str(pp))
        return out


def lint_paths(paths: Iterable[str], select: Optional[Sequence[str]] = None):
    """One-call convenience: (active, suppressed, errors)."""
    return Analyzer(select=select).run_paths(paths)
