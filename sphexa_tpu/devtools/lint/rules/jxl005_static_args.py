"""JXL005: jax.jit / shard_map static-argument hazards.

Three concrete failure modes, all of which bite at call time (or worse,
per-call) rather than at definition time:

- ``static_argnames`` naming a parameter that does not exist (typo):
  jax raises only when the name would matter, so the typo can sit dark
  until a call-site change.
- an unhashable (list/dict/set) default on a static parameter: the
  first defaulted call dies with ``TypeError: unhashable type``; a
  mutable default on a TRACED parameter instead bakes one abstract
  value per identity and is a retrace hazard.
- a config-like parameter (``cfg`` / ``*_cfg`` / ``config``) that is
  NOT static: frozen config dataclasses flow through this codebase as
  compile-time constants (every propagator entry point does
  ``static_argnames=("cfg",)``); passing one positionally as a traced
  arg either fails flatten-time or retraces on every new instance.
"""

from __future__ import annotations

import ast
import re
from typing import List

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register
from sphexa_tpu.devtools.lint.trace_scope import (
    _jit_call_of_decorator,
    declared_statics,
)

_CONFIG_NAME = re.compile(r"(^|_)(cfg|config)$")
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
# decorators whose static_argnames/nums semantics we validate
_JIT_LIKE = {"jax.jit", "jax.pmap", "shard_map",
             "jax.experimental.shard_map.shard_map", "jax.shard_map",
             "sphexa_tpu.propagator.shard_map"}


@register(
    "JXL005",
    "jit-static-args",
    "jax.jit/shard_map static-argument hazards: unknown static_argnames, "
    "unhashable/mutable defaults, config dataclasses passed as traced args",
)
def check(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            hit = _jit_call_of_decorator(dec, mod)
            if hit is None or hit[0] not in _JIT_LIKE:
                continue
            transform, call = hit
            a = node.args
            positional = [p.arg for p in a.posonlyargs + a.args]
            all_params = set(positional) | {p.arg for p in a.kwonlyargs}
            names, nums = declared_statics(call)

            for name in sorted(names - all_params):
                out.append(mod.finding(
                    "JXL005", dec,
                    f"static_argnames entry '{name}' does not match any "
                    f"parameter of `{node.name}` "
                    f"({', '.join(sorted(all_params)) or 'no params'}): "
                    f"dead typo, the intended argument is traced.",
                ))
            # negative indices resolve from the end, as jax does
            for num in nums:
                if not (-len(positional) <= num < len(positional)):
                    out.append(mod.finding(
                        "JXL005", dec,
                        f"static_argnums entry {num} is out of range for "
                        f"`{node.name}` ({len(positional)} positional "
                        f"parameters).",
                    ))
            static = names | {positional[i] for i in nums
                              if -len(positional) <= i < len(positional)}

            # defaults: align right-to-left with positional params
            defaults = list(zip(positional[::-1], a.defaults[::-1]))
            defaults += [(p.arg, d) for p, d in zip(a.kwonlyargs,
                                                    a.kw_defaults) if d]
            for pname, dflt in defaults:
                if isinstance(dflt, _MUTABLE_LITERALS):
                    if pname in static:
                        out.append(mod.finding(
                            "JXL005", dflt,
                            f"unhashable default for static arg '{pname}' "
                            f"of `{node.name}`: the first defaulted call "
                            f"raises TypeError (static args are cache "
                            f"keys). Use a tuple/frozen value.",
                        ))
                    else:
                        out.append(mod.finding(
                            "JXL005", dflt,
                            f"mutable default for traced arg '{pname}' of "
                            f"jitted `{node.name}`: one shared instance "
                            f"across calls is a retrace/aliasing hazard. "
                            f"Use None + in-body construction.",
                        ))

            # config-like params must be static (the repo-wide idiom)
            for pname in positional + [p.arg for p in a.kwonlyargs]:
                if _CONFIG_NAME.search(pname) and pname not in static:
                    out.append(mod.finding(
                        "JXL005", dec,
                        f"config-like parameter '{pname}' of `{node.name}` "
                        f"is traced under {transform}: frozen config "
                        f"dataclasses are compile-time constants here — "
                        f"add it to static_argnames (or rename if it "
                        f"really is a traced pytree).",
                    ))
    return out
