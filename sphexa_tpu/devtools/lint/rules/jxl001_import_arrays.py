"""JXL001: module-level ``jnp``/``jax.numpy`` array construction.

A jnp call at import time places a buffer on the default device before
the application configures platforms/meshes, and — the bug PR 1 fixed by
hand in parallel/exchange.py — a module first imported while a trace is
live builds a TRACER, not an array, which then leaks into every later
trace that touches the constant. Dtype ALIASES (``KEY_DTYPE =
jnp.uint32``) are fine: only calls are flagged.

Import-time scope = module body + class bodies + default-argument
expressions of module/class-level defs. Code inside function bodies or
lambdas only runs when called and is exempt.
"""

from __future__ import annotations

import ast
from typing import List

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register

# attribute-style jnp calls are matched by the jax.numpy. prefix below;
# this covers array-building jax.* entry points outside that namespace
_EXTRA_CONSTRUCTORS = {
    "jax.device_put",
}


def _is_jnp_call(mod: ModuleInfo, call: ast.Call) -> bool:
    q = mod.qualname(call.func)
    if q is None:
        return False
    return q.startswith("jax.numpy.") or q in _EXTRA_CONSTRUCTORS


def _scan_expr(mod: ModuleInfo, expr: ast.AST, out: List[Finding]):
    """Flag jnp calls in an import-time-evaluated expression, without
    descending into lambda bodies (deferred execution)."""
    if isinstance(expr, ast.Lambda):
        return
    if isinstance(expr, ast.Call) and _is_jnp_call(mod, expr):
        q = mod.qualname(expr.func)
        out.append(mod.finding(
            "JXL001", expr,
            f"`{q}(...)` runs at import time: builds a device buffer "
            f"before platform setup and leaks a tracer if the first "
            f"import happens under a trace. Use a Python/numpy constant "
            f"or construct lazily inside the function.",
        ))
    for child in ast.iter_child_nodes(expr):
        _scan_expr(mod, child, out)


def _scan_children(mod: ModuleInfo, node: ast.AST, out: List[Finding]):
    """Recurse through control-flow scaffolding (withitem, excepthandler)
    routing stmts back to _scan_body and exprs to _scan_expr."""
    for sub in ast.iter_child_nodes(node):
        if isinstance(sub, ast.stmt):
            _scan_body(mod, [sub], out)
        elif isinstance(sub, ast.expr):
            _scan_expr(mod, sub, out)
        else:
            _scan_children(mod, sub, out)


def _scan_body(mod: ModuleInfo, body: List[ast.stmt], out: List[Finding]):
    for st in body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # decorators and default-arg expressions evaluate at def time
            for dec in st.decorator_list:
                _scan_expr(mod, dec, out)
            for d in st.args.defaults + [d for d in st.args.kw_defaults if d]:
                _scan_expr(mod, d, out)
            continue
        if isinstance(st, ast.ClassDef):
            for dec in st.decorator_list:
                _scan_expr(mod, dec, out)
            _scan_body(mod, st.body, out)
            continue
        if isinstance(st, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
            # module-level control flow still executes at import
            _scan_children(mod, st, out)
            continue
        _scan_expr(mod, st, out)


@register(
    "JXL001",
    "module-level-jnp",
    "jnp/jax.numpy array construction at import time (device placement "
    "before setup; tracer leak if first-imported under a trace)",
)
def check(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    _scan_body(mod, mod.tree.body, out)
    return out
