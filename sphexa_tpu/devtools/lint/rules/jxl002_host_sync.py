"""JXL002: host synchronization inside jit-reachable code.

``.item()`` / ``float()`` / ``int()`` / ``bool()`` / ``np.asarray`` on a
traced value either raises a ConcretizationTypeError at trace time or —
worse, when the value happens to be concrete on the first call — silently
re-triggers compilation and stalls the device pipeline on every step.
The fixed-shape Cornerstone/Bonsai-style kernels this repo is built on
only stay fast if nothing syncs the host mid-step.

Scope comes from ``trace_scope.TraceScopes`` (jit decorators, functions
passed to jax transforms / lax control flow / pallas_call, intra-module
call-graph propagation). Conversions are only flagged when their
argument derives from a NON-static parameter of the enclosing traced
function — ``float(const.K)`` under ``static_argnames=("const",)`` and
``int(x.shape[0])`` are static and stay legal.
"""

from __future__ import annotations

import ast
from typing import List, Set

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register
from sphexa_tpu.devtools.lint.trace_scope import (
    TraceScopes,
    build_parent_map,
    touches_dynamic,
)

_CONVERTERS = {"float", "int", "bool", "complex"}
_NP_MATERIALIZERS = {
    "numpy.asarray", "numpy.array", "numpy.asanyarray", "numpy.ascontiguousarray",
}
_ALWAYS_BAD_CALLS = {"jax.device_get"}
_ALWAYS_BAD_METHODS = {"item", "block_until_ready", "tolist", "__array__"}


@register(
    "JXL002",
    "host-sync-in-jit",
    "host synchronization (.item(), float()/int()/bool() on traced values,"
    " np.asarray on device arrays, device_get) inside jit-reachable code",
)
def check(mod: ModuleInfo) -> List[Finding]:
    scopes = TraceScopes(mod)
    if not scopes.traced:
        return []
    parents = build_parent_map(mod.tree)
    out: List[Finding] = []

    def dynamic_params_of(node: ast.AST) -> Set[str]:
        """Union of dynamic params over the chain of enclosing traced
        functions (closures over an outer traced arg still trace)."""
        dyn: Set[str] = set()
        cur = parents.get(node)
        while cur is not None:
            tf = scopes.traced.get(cur)
            if tf is not None:
                dyn |= tf.dynamic_params()
            cur = parents.get(cur)
        return dyn

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        owner = scopes.traced_owner(node, parents)
        if owner is None:
            continue

        # .item() / .block_until_ready() / .tolist(): always a sync
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _ALWAYS_BAD_METHODS
                and not node.args):
            via = owner.name or "<lambda>"
            out.append(mod.finding(
                "JXL002",
                node,
                f"`.{node.func.attr}()` inside jit-reachable "
                f"`{via}` ({owner.via}) forces a device->host sync or "
                f"fails on a tracer; hoist it out of the traced region.",
            ))
            continue

        q = mod.qualname(node.func)
        if q in _ALWAYS_BAD_CALLS:
            via = owner.name or "<lambda>"
            out.append(mod.finding(
                "JXL002",
                node,
                f"`{q}(...)` inside jit-reachable `{via}` ({owner.via}) "
                f"is a host transfer; return the value instead and fetch "
                f"it outside the jit boundary.",
            ))
            continue

        # conversions: only when fed (a derivative of) a traced parameter
        if q in _CONVERTERS or q in _NP_MATERIALIZERS:
            if not node.args:
                continue
            dyn = dynamic_params_of(node)
            if dyn and touches_dynamic(mod, node.args[0], dyn):
                via = owner.name or "<lambda>"
                out.append(mod.finding(
                    "JXL002",
                    node,
                    f"`{q}(...)` on a value derived from traced argument(s)"
                    f" of `{via}` ({owner.via}): concretizes a tracer "
                    f"(ConcretizationTypeError) or re-compiles per value. "
                    f"Keep it as a jnp op, or mark the argument static.",
                ))
    return out
