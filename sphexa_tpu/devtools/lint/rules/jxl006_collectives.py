"""JXL006: direct lax collectives outside the exchange layer.

Collectives rendezvous by program order, not by name: two collectives
with no data dependency between them may be scheduled in different
interleavings on different devices — garbage or deadlock on XLA:CPU
meshes (the PR-5 race), an ICI stall hazard on chips. The repo's
contract is that cross-shard communication routes through
``parallel/exchange.py``, whose ``chain_after`` pins a total order via
``optimization_barrier``.

This rule flags a direct ``jax.lax`` collective call (``psum``,
``ppermute``, ``all_gather``, ``all_to_all``, ...) in any other module
when no enclosing function also calls ``exchange.chain_after`` — a
function that threads a chain token is visibly participating in the
ordering protocol and is trusted (the trace-level JXA201 audit then
PROVES the order on the jaxpr). Purely data-chained collective pyramids
(e.g. the multipole upsweep) suppress inline with a reason.
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import Dict, List

from sphexa_tpu.devtools.audit.spmd import COLLECTIVE_PRIMS
from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register
from sphexa_tpu.devtools.lint.trace_scope import build_parent_map

_CHAIN = "sphexa_tpu.parallel.exchange.chain_after"
_COLLECTIVE_QUALNAMES = {f"jax.lax.{p}" for p in COLLECTIVE_PRIMS}
_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@register(
    "JXL006",
    "unchained-collective",
    "direct jax.lax collective outside parallel/exchange.py in a function "
    "that never pins order with exchange.chain_after",
)
def check(mod: ModuleInfo) -> List[Finding]:
    if PurePosixPath(mod.path).parts[-2:] == ("parallel", "exchange.py"):
        return []
    parents = build_parent_map(mod.tree)
    chains: Dict[ast.AST, bool] = {}

    def calls_chain_after(fn: ast.AST) -> bool:
        if fn not in chains:
            chains[fn] = any(
                isinstance(sub, ast.Call)
                and mod.qualname(sub.func) == _CHAIN
                for sub in ast.walk(fn)
            )
        return chains[fn]

    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        q = mod.qualname(node.func)
        if q not in _COLLECTIVE_QUALNAMES:
            continue
        cur = parents.get(node)
        exempt = False
        while cur is not None:
            if isinstance(cur, _FUNCTION_NODES) and calls_chain_after(cur):
                exempt = True
                break
            cur = parents.get(cur)
        if exempt:
            continue
        out.append(mod.finding(
            "JXL006",
            node,
            f"direct `{q}(...)` outside parallel/exchange.py with no "
            f"exchange.chain_after in the enclosing function: an "
            f"order-unconstrained collective is the XLA rendezvous-race "
            f"class. Thread a chain token through "
            f"exchange.chain_after, or suppress with a reason if data "
            f"dependencies already pin a total order.",
        ))
    return out
