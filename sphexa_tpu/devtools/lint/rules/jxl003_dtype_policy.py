"""JXL003: dtype-policy bypass in state-constructing modules.

``sphexa_tpu/dtypes.py`` is the single switch for the framework's
precision policy (f32 TPU-native today; a future mixed-precision PR
flips it in ONE place). That only works if the modules that build
particle state, SFC keys and snapshots spell dtypes through the policy
names — a literal ``jnp.float32`` there silently pins the old policy.

Scoped to the modules where state is born (init/, sfc/, io/,
sph/particles.py): numerics kernels legitimately use explicit working
precisions (e.g. a deliberate f32 accumulator inside a Pallas kernel)
and are not policed.
"""

from __future__ import annotations

import ast
from typing import List

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register

# path fragments that opt a module INTO the policy check
POLICY_PATHS = (
    "sphexa_tpu/init/",
    "sphexa_tpu/sfc/",
    "sphexa_tpu/io/",
    "sphexa_tpu/sph/particles.py",
    "lint_fixtures/numerics",   # fixture hook for tests/test_lint.py
)

# the policy module itself defines the aliases and is exempt
EXEMPT_PATHS = ("sphexa_tpu/dtypes.py",)

_SUGGESTION = {
    "float32": "COORD_DTYPE/HYDRO_DTYPE",
    "int32": "INDEX_DTYPE",
    "uint32": "KEY_DTYPE",
    "float64": "a policy dtype (f64 is not TPU-native)",
    "int64": "INDEX_DTYPE (i64 is not TPU-native)",
    "uint64": "KEY_DTYPE (u64 is not TPU-native)",
    "float16": "HYDRO_DTYPE",
    "bfloat16": "HYDRO_DTYPE",
}


def applies_to(path: str) -> bool:
    if any(path.endswith(e) for e in EXEMPT_PATHS):
        return False
    return any(frag in path for frag in POLICY_PATHS)


@register(
    "JXL003",
    "dtype-policy-bypass",
    "literal jnp dtype (jnp.float32/int32/uint32/...) in a "
    "state-constructing module instead of the dtypes.py policy names",
)
def check(mod: ModuleInfo) -> List[Finding]:
    if not applies_to(mod.path):
        return []
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Attribute):
            continue
        if node.attr not in _SUGGESTION:
            continue
        q = mod.qualname(node)
        if q != f"jax.numpy.{node.attr}":
            continue
        out.append(mod.finding(
            "JXL003",
            node,
            f"literal `jnp.{node.attr}` in a state-constructing module "
            f"bypasses the dtype policy; use {_SUGGESTION[node.attr]} "
            f"from sphexa_tpu.dtypes.",
        ))
    return out
