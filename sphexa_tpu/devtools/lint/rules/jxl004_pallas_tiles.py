"""JXL004: Pallas VMEM tile shapes off the (8, 128) register grid.

Mosaic lays VMEM out in (8, 128) f32 tiles (sublane x lane; see the
Pallas TPU docs). A BlockSpec whose trailing dimension is not a multiple
of 128, or whose second-to-last literal dimension is neither 1 nor a
multiple of 8, either fails to lower or lowers with silent padding that
wastes VMEM and vector issue slots — the exact overhead the fixed-shape
kernel design exists to avoid.

Only LITERAL dims are judged (symbolic sizes like ``(1, 1, G)`` are the
caller's contract), and only for tiled memory spaces: ``memory_space=``
SMEM/ANY/HOST specs are scalar/untiled and exempt. ``pltpu.VMEM`` scratch
shapes are held to the same grid.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register

_BLOCKSPEC = "jax.experimental.pallas.BlockSpec"
_VMEM_SCRATCH = (
    "jax.experimental.pallas.tpu.VMEM",
    "jax.experimental.pallas.mosaic.VMEM",
)
_UNTILED_SPACES = ("SMEM", "ANY", "HOST")


def _literal_dims(node: ast.AST) -> Optional[List[Optional[int]]]:
    """Tuple/List literal -> [int or None per dim]; None if not a
    sequence literal at all."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims: List[Optional[int]] = []
    for el in node.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, int):
            dims.append(el.value)
        else:
            dims.append(None)
    return dims


def _check_dims(mod: ModuleInfo, node: ast.AST, dims: List[Optional[int]],
                what: str, out: List[Finding]):
    if not dims:
        return
    last = dims[-1]
    if last is not None and last % 128 != 0:
        out.append(mod.finding(
            "JXL004",
            node,
            f"{what} trailing dim {last} is not a multiple of 128 "
            f"(Mosaic lane width); the block is padded to "
            f"{-(-last // 128) * 128} lanes on chip.",
        ))
    if len(dims) >= 2:
        second = dims[-2]
        if second is not None and second != 1 and second % 8 != 0:
            out.append(mod.finding(
                "JXL004",
                node,
                f"{what} sublane dim {second} is neither 1 nor a multiple "
                f"of 8 (f32 sublane count); pad the block to "
                f"{-(-second // 8) * 8} rows or fold it into the grid.",
            ))


@register(
    "JXL004",
    "pallas-tile-shape",
    "Pallas BlockSpec / VMEM scratch literal tile shape not aligned to "
    "the (8, 128) Mosaic register grid",
)
def check(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        q = mod.qualname(node.func)
        if q == _BLOCKSPEC:
            space = next((kw.value for kw in node.keywords
                          if kw.arg == "memory_space"), None)
            if space is not None:
                sq = mod.qualname(space) or ""
                if sq.rsplit(".", 1)[-1] in _UNTILED_SPACES:
                    continue
            shape = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "block_shape"), None)
            if shape is None:
                continue
            dims = _literal_dims(shape)
            if dims is not None:
                _check_dims(mod, node, dims, "BlockSpec", out)
        elif q in _VMEM_SCRATCH and node.args:
            dims = _literal_dims(node.args[0])
            if dims is not None:
                _check_dims(mod, node, dims, "VMEM scratch", out)
    return out
