"""Rule modules register themselves on import (core.register)."""

from sphexa_tpu.devtools.lint.rules import (  # noqa: F401
    jxl001_import_arrays,
    jxl002_host_sync,
    jxl003_dtype_policy,
    jxl004_pallas_tiles,
    jxl005_static_args,
    jxl006_collectives,
    jxl007_pytree_registration,
)
