"""JXL007: pytree-registration hygiene for ``register_dataclass``.

``jax.tree_util.register_dataclass`` flattens EVERY undeclared field as
a traced child. A config-shaped field (str / bool / tuple / dict /
``*Config``) silently becomes a leaf: the tracer either dies
flatten-time on the non-array, or — worse — bakes the value in as a
weak-typed scalar leaf and every new instance retraces. The repo's
idiom is explicit: static metadata is declared per field
(``dataclasses.field(metadata=dict(static=True))`` — sfc/box.py's
``boundaries``) or per class (``meta_fields=`` on the decorator call).
This rule makes the declaration non-optional:

- a field whose ANNOTATION is static-shaped (str, bool, bytes,
  tuple/dict/set family, type, Callable, or a ``*Config``/``*Spec``
  class name) but is not declared static — the silent-leaf trap;
- a DECLARED-static field annotated with an unhashable container
  (list/dict/set): static fields are jit cache keys, the first traced
  call raises TypeError;
- a mutable literal default (list/dict/set displays or comprehensions,
  bare or as ``field(default=...)``): one shared instance across every
  constructed state is an aliasing hazard on top of dataclasses' own
  (bypassed-by-field) guard.

Purely structural — the AST pass never imports jax, so a registration
bug cannot crash the linter that reports it.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from sphexa_tpu.devtools.lint.core import Finding, ModuleInfo, register

_REGISTER = "jax.tree_util.register_dataclass"
_CONFIG_NAME = re.compile(r"(Config|Spec)$")
_STATIC_HEADS = {
    "str", "bool", "bytes", "type", "Type", "Callable",
    "tuple", "Tuple", "dict", "Dict", "list", "List",
    "set", "Set", "frozenset", "FrozenSet",
}
_UNHASHABLE_HEADS = {"list", "List", "dict", "Dict", "set", "Set"}
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _ann_head(ann: ast.AST) -> Optional[str]:
    """Outermost type name of an annotation, unwrapping Optional[...]
    (an Optional static field is still static) and string annotations."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        head = _ann_head(ann.value)
        if head == "Optional":
            return _ann_head(ann.slice)
        return head
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Name):
        return ann.id
    return None


def _registered_classes(mod: ModuleInfo):
    """(ClassDef, decorator node, decorator-declared meta field names)
    for every register_dataclass class in the module."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            target = call.func if call else dec
            if mod.qualname(target) != _REGISTER:
                # the kwargs form rides functools.partial:
                # @partial(register_dataclass, meta_fields=(...))
                if not (call and mod.qualname(target) in
                        ("functools.partial", "partial") and call.args
                        and mod.qualname(call.args[0]) == _REGISTER):
                    continue
            meta: Set[str] = set()
            if call:
                for kw in call.keywords:
                    if kw.arg == "meta_fields" and isinstance(
                            kw.value, (ast.List, ast.Tuple, ast.Set)):
                        meta |= {
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        }
            yield node, dec, meta
            break


def _field_call(value: ast.AST) -> Optional[ast.Call]:
    if isinstance(value, ast.Call):
        name = value.func.attr if isinstance(value.func, ast.Attribute) \
            else getattr(value.func, "id", None)
        if name == "field":
            return value
    return None


def _declares_static(call: ast.Call) -> bool:
    """``field(metadata=dict(static=True))`` / ``{"static": True}``."""
    for kw in call.keywords:
        if kw.arg != "metadata":
            continue
        v = kw.value
        if isinstance(v, ast.Call):
            return any(k.arg == "static" for k in v.keywords)
        if isinstance(v, ast.Dict):
            return any(isinstance(k, ast.Constant) and k.value == "static"
                       for k in v.keys)
    return False


@register(
    "JXL007",
    "pytree-registration",
    "register_dataclass hygiene: static-shaped fields must be DECLARED "
    "static (field metadata or meta_fields); declared statics must be "
    "hashable; no mutable literal defaults",
)
def check(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for cls, _dec, meta in _registered_classes(mod):
        for stmt in cls.body:
            if not isinstance(stmt, ast.AnnAssign) or \
                    not isinstance(stmt.target, ast.Name):
                continue
            fname = stmt.target.id
            head = _ann_head(stmt.annotation)
            fcall = _field_call(stmt.value) if stmt.value else None
            static = fname in meta or (fcall is not None
                                       and _declares_static(fcall))

            looks_static = head is not None and (
                head in _STATIC_HEADS or _CONFIG_NAME.search(head))
            if looks_static and not static:
                out.append(mod.finding(
                    "JXL007", stmt,
                    f"field '{fname}: {head}' of registered dataclass "
                    f"`{cls.name}` looks static but is flattened as a "
                    f"TRACED pytree child — declare it "
                    f"`dataclasses.field(metadata=dict(static=True))` "
                    f"(or list it in meta_fields), or it traces as a "
                    f"leaf and every new value retraces.",
                ))
            if static and head in _UNHASHABLE_HEADS:
                out.append(mod.finding(
                    "JXL007", stmt,
                    f"static field '{fname}: {head}' of `{cls.name}` is "
                    f"unhashable: static fields are jit cache keys, the "
                    f"first traced call raises TypeError. Use a "
                    f"tuple/frozen container.",
                ))

            default = stmt.value
            if fcall is not None:
                default = next((kw.value for kw in fcall.keywords
                                if kw.arg == "default"), None)
            if isinstance(default, _MUTABLE_LITERALS):
                out.append(mod.finding(
                    "JXL007", default,
                    f"mutable literal default for field '{fname}' of "
                    f"registered dataclass `{cls.name}`: one shared "
                    f"instance aliases across every constructed state. "
                    f"Use default_factory or a frozen value.",
                ))
    return out
