"""Committed baseline of grandfathered findings.

Entries key on (rule, path, snippet-hash) with a count — NOT on line
numbers, so unrelated edits above a grandfathered site don't churn the
file. Matching is consuming: N baselined copies of an identical line
absorb at most N findings; the N+1st is new and fails the gate.

The acceptance state for this repo is an EMPTY baseline (every finding
fixed or carrying an inline suppression with a reason); the mechanism
exists so a future rule can land before its fix sweep completes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Tuple

from sphexa_tpu.devtools.lint.core import Finding

_VERSION = 1


def _key(f: Finding) -> Tuple[str, str, str]:
    digest = hashlib.sha256(f.snippet.encode()).hexdigest()[:16]
    return (f.rule, f.path, digest)


@dataclasses.dataclass
class Baseline:
    entries: Counter  # (rule, path, snippet_hash) -> count

    @classmethod
    def empty(cls) -> "Baseline":
        return cls(entries=Counter())

    @classmethod
    def from_findings(cls, findings: List[Finding]) -> "Baseline":
        return cls(entries=Counter(_key(f) for f in findings))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls.empty()
        data = json.loads(p.read_text())
        if data.get("version") != _VERSION:
            raise ValueError(
                f"baseline {path}: unsupported version {data.get('version')}"
            )
        entries: Counter = Counter()
        for e in data.get("entries", []):
            entries[(e["rule"], e["path"], e["snippet_hash"])] = int(
                e.get("count", 1)
            )
        return cls(entries=entries)

    def save(self, path: str) -> None:
        entries = [
            {"rule": r, "path": p, "snippet_hash": h, "count": c}
            for (r, p, h), c in sorted(self.entries.items())
            if c > 0
        ]
        Path(path).write_text(
            json.dumps({"version": _VERSION, "entries": entries}, indent=2)
            + "\n"
        )

    def filter_new(self, findings: List[Finding]
                   ) -> Tuple[List[Finding], List[Finding]]:
        """(new, grandfathered): consume baseline credit per finding."""
        budget = Counter(self.entries)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            k = _key(f)
            if budget[k] > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old
