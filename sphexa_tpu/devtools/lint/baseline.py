"""Committed baseline of grandfathered lint findings.

The format and the consuming (rule, path, snippet-hash) matching live in
``devtools/common.py`` and are shared with the jaxaudit baseline; see the
docstring there. The acceptance state for this repo is an EMPTY baseline
(every finding fixed or carrying an inline suppression with a reason).
"""

from __future__ import annotations

from sphexa_tpu.devtools.common import Baseline  # noqa: F401
from sphexa_tpu.devtools.common import baseline_key as _key  # noqa: F401
