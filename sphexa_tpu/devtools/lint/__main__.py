"""``python -m sphexa_tpu.devtools.lint`` entry point."""

import sys

from sphexa_tpu.devtools.lint.cli import main

sys.exit(main())
