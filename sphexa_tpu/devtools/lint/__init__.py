"""jaxlint: in-repo AST static analysis for jit/tracer/dtype/Pallas hygiene.

The bug classes the rules target are ones this codebase has actually hit
(see docs/STATIC_ANALYSIS.md for the catalog and the war stories):

- JXL001  module-level ``jnp``/``jax.numpy`` array construction
          (import-time device placement / tracer leak)
- JXL002  host sync inside jit-reachable code
- JXL003  dtype-policy bypass in state-constructing modules
- JXL004  Pallas BlockSpec tile shapes off the (8, 128) grid
- JXL005  jit/shard_map static-argument hazards

Usage::

    python -m sphexa_tpu.devtools.lint sphexa_tpu
    sphexa-lint sphexa_tpu --format json

Suppress a single finding with an inline comment carrying a reason::

    x = host_only_thing()  # jaxlint: disable=JXL002 -- driver-loop sync

The analyzer is pure stdlib (``ast`` + ``tokenize``): it never imports the
code it scans, so it is safe to run on modules whose import would grab a
device.
"""

from sphexa_tpu.devtools.lint.core import (  # noqa: F401
    Analyzer,
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    lint_paths,
)
from sphexa_tpu.devtools.lint.baseline import Baseline  # noqa: F401
