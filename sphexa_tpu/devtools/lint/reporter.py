"""Text and JSON reporters for lint results."""

from __future__ import annotations

import json
from typing import List, Optional

from sphexa_tpu.devtools.lint.core import Finding


def render_text(new: List[Finding], grandfathered: List[Finding],
                suppressed: List[Finding], errors: List[Finding],
                show_suppressed: bool = False) -> str:
    lines: List[str] = []
    for f in errors:
        lines.append(f.format())
    for f in new:
        lines.append(f.format())
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if show_suppressed:
        for f in suppressed:
            lines.append(f"[suppressed] {f.format()}")
        for f in grandfathered:
            lines.append(f"[baseline] {f.format()}")
    n_new = len(new) + len(errors)
    summary = (
        f"jaxlint: {n_new} finding(s)"
        + (f", {len(grandfathered)} baselined" if grandfathered else "")
        + (f", {len(suppressed)} suppressed inline" if suppressed else "")
    )
    lines.append(summary)
    return "\n".join(lines)


def render_json(new: List[Finding], grandfathered: List[Finding],
                suppressed: List[Finding], errors: List[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.to_json() for f in new],
            "errors": [f.to_json() for f in errors],
            "baselined": [f.to_json() for f in grandfathered],
            "suppressed": [f.to_json() for f in suppressed],
        },
        indent=2,
    )
