"""Text and JSON reporters for lint results (shared devtools renderers)."""

from __future__ import annotations

import functools

from sphexa_tpu.devtools.common import render_json  # noqa: F401
from sphexa_tpu.devtools.common import render_text as _render_text

render_text = functools.partial(_render_text, tool="jaxlint")
