"""Which functions in a module run under a JAX trace, and which of their
parameters carry tracers — the shared scope model behind JXL002/JXL005.

"Jit-reachable" is computed per module, syntactically:

1. roots: functions decorated with a tracing transform (``jax.jit``,
   ``functools.partial(jax.jit, ...)``, ``jax.vmap`` ...) or passed by
   name/lambda to a trace-inducing callable (``jax.jit(f)``,
   ``jax.lax.fori_loop(0, n, body, x)``, ``pl.pallas_call(kernel, ...)``,
   ``shard_map(f, ...)``). ``jax.lax`` control flow and ``pallas_call``
   ALWAYS trace their function arguments, even when called from host
   code, so they root reachability unconditionally.
2. propagation: a plain-name call inside a traced function marks the
   same-module function of that name traced too, and maps the call's
   arguments onto the callee's parameters: a parameter is DYNAMIC
   (tracer-carrying) only if some traced call site feeds it an
   expression derived from a dynamic value. Arguments built from
   ``static_argnames`` parameters, closure variables, or constants are
   concrete at trace time, so ``float(cfg.x)`` in a helper stays legal
   when every caller passes a static config. The transfer function is
   monotone (dynamic sets only grow), so the worklist converges.

Cross-module reachability is out of scope — each module is analyzed
against its own roots. The model errs toward under-reporting rather
than flooding host-side planner code with false positives; the fixture
tests pin the contract.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple, Union

from sphexa_tpu.devtools.lint.core import ModuleInfo

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# transforms whose FIRST function argument is traced when called
TRACING_CALLABLES = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.map",
    "jax.experimental.pallas.pallas_call",
    "jax.experimental.shard_map.shard_map",
    "jax.shard_map",
    # repo-local version shim around shard_map (propagator.shard_map)
    "shard_map",
    "sphexa_tpu.propagator.shard_map",
}

# jax.lax control flow: (canonical name, indices of traced function args)
LAX_FN_ARGS = {
    "jax.lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.scan": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": None,   # every arg after the index may be a branch
    "jax.lax.associative_scan": (0,),
    "jax.lax.custom_root": (0, 1, 2),
}

# decorators that make the decorated function a trace root
TRACING_DECORATORS = TRACING_CALLABLES | {
    "jax.custom_jvp",
    "jax.custom_vjp",
}

# attribute reads that are static under tracing even on traced arrays
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "weak_type", "sharding"}


def touches_dynamic(mod: ModuleInfo, expr: ast.AST, dyn: Set[str]) -> bool:
    """Does ``expr`` (syntactically) derive from a name in ``dyn``?
    Accesses routed through static attributes (``x.shape``) and ``len()``
    don't count — those are concrete under tracing."""
    if isinstance(expr, ast.Name):
        return expr.id in dyn
    if isinstance(expr, ast.Attribute):
        if expr.attr in STATIC_ATTRS:
            return False
        return touches_dynamic(mod, expr.value, dyn)
    if isinstance(expr, ast.Call):
        q = mod.qualname(expr.func)
        if q == "len":
            return False
        args = list(expr.args) + [kw.value for kw in expr.keywords]
        # a method call on a traced value is itself traced
        if isinstance(expr.func, ast.Attribute):
            args.append(expr.func.value)
        return any(touches_dynamic(mod, a, dyn) for a in args)
    return any(touches_dynamic(mod, c, dyn)
               for c in ast.iter_child_nodes(expr))


@dataclasses.dataclass
class TracedFunction:
    node: FunctionNode
    name: Optional[str]            # None for lambdas
    dynamic: Set[str]              # params that carry tracers
    via: str                       # how it became traced (for messages)

    def dynamic_params(self) -> Set[str]:
        return set(self.dynamic)


def _literal_ints(node: ast.AST) -> List[int]:
    """Int literals in a (possibly nested) expression, honoring a unary
    minus — ``ast.walk`` alone would strip the sign off ``-1``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, int)):
        return [-node.operand.value]
    out: List[int] = []
    for child in ast.iter_child_nodes(node):
        out += _literal_ints(child)
    return out


def declared_statics(call: Optional[ast.Call]) -> Tuple[Set[str], List[int]]:
    """(static_argnames strings, static_argnums ints — sign preserved)
    declared on a jit(...) / functools.partial(jax.jit, ...) call,
    unvalidated."""
    names: Set[str] = set()
    nums: List[int] = []
    if call is None:
        return names, nums
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            nums += _literal_ints(kw.value)
    return names, nums


def _static_names_from_call(call: ast.Call, mod: ModuleInfo,
                            fn: Optional[FunctionNode]) -> Set[str]:
    """Param names made static by a jit(...) call's static_argnames /
    static_argnums (negative nums resolve from the end, as jax does)."""
    positional: List[str] = []
    if fn is not None:
        a = fn.args
        positional = [p.arg for p in a.posonlyargs + a.args]
    names, nums = declared_statics(call)
    out = set(names)
    for i in nums:
        if -len(positional) <= i < len(positional):
            out.add(positional[i])
    return out


def _jit_call_of_decorator(dec: ast.expr, mod: ModuleInfo
                           ) -> Optional[Tuple[str, Optional[ast.Call]]]:
    """(transform qualname, call-with-kwargs or None) when ``dec`` is a
    tracing decorator: bare ``@jax.jit``, ``@jax.jit(...)`` (jit as a
    decorator factory), or ``@functools.partial(jax.jit, ...)``."""
    if isinstance(dec, (ast.Name, ast.Attribute)):
        q = mod.qualname(dec)
        if q in TRACING_DECORATORS:
            return q, None
        return None
    if not isinstance(dec, ast.Call):
        return None
    q = mod.qualname(dec.func)
    if q in TRACING_DECORATORS:
        return q, dec
    if q == "functools.partial" and dec.args:
        inner = mod.qualname(dec.args[0])
        if inner in TRACING_DECORATORS:
            return inner, dec
    return None


class TraceScopes:
    """Traced-function table for one module. Query with ``traced_owner``."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.traced: Dict[FunctionNode, TracedFunction] = {}
        self._all_functions: Dict[str, List[FunctionNode]] = {}
        self._fn_parents: Dict[FunctionNode, Optional[FunctionNode]] = {}
        self._collect_functions(mod.tree, None)
        self._seed_roots()
        self._propagate()

    # -- construction -----------------------------------------------------

    def _collect_functions(self, node: ast.AST,
                           parent: Optional[FunctionNode]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                self._fn_parents[child] = parent
                name = getattr(child, "name", None)
                if name:
                    self._all_functions.setdefault(name, []).append(child)
                self._collect_functions(child, child)
            else:
                self._collect_functions(child, parent)

    def _mark(self, fn: FunctionNode, via: str, dynamic: Set[str]) -> bool:
        """Record fn as traced / widen its dynamic set. True if changed."""
        tf = self.traced.get(fn)
        if tf is None:
            self.traced[fn] = TracedFunction(
                node=fn, name=getattr(fn, "name", None),
                dynamic=set(dynamic), via=via,
            )
            return True
        if not dynamic <= tf.dynamic:
            tf.dynamic |= dynamic
            return True
        return False

    @staticmethod
    def _all_param_names(fn: FunctionNode) -> Set[str]:
        a = fn.args
        names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        return names

    def _seed_roots(self):
        mod = self.mod
        # decorated roots: every non-static param carries tracers
        for fn in self._fn_parents:
            for dec in getattr(fn, "decorator_list", []):
                hit = _jit_call_of_decorator(dec, mod)
                if hit:
                    q, call = hit
                    static = (_static_names_from_call(call, mod, fn)
                              if call is not None else set())
                    self._mark(fn, f"@{q}",
                               self._all_param_names(fn) - static)
        # functions/lambdas passed to tracing callables
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            q = mod.qualname(node.func)
            if q in TRACING_CALLABLES:
                if node.args:
                    self._root_fn_arg(node.args[0], q, node)
            elif q in LAX_FN_ARGS:
                idxs = LAX_FN_ARGS[q]
                if idxs is None:
                    idxs = range(1, len(node.args))
                for i in idxs:
                    if i < len(node.args):
                        self._root_fn_arg(node.args[i], q, None)

    def _root_fn_arg(self, arg: ast.expr, via: str,
                     jit_call: Optional[ast.Call]):
        targets: List[FunctionNode] = []
        if isinstance(arg, ast.Lambda):
            targets = [arg]
        elif isinstance(arg, ast.Name):
            targets = self._all_functions.get(arg.id, [])
        for fn in targets:
            static: Set[str] = set()
            if jit_call is not None:
                static = _static_names_from_call(jit_call, self.mod, fn)
            self._mark(fn, f"passed to {via}",
                       self._all_param_names(fn) - static)

    # -- dataflow ---------------------------------------------------------

    def _dyn_env(self, fn: FunctionNode) -> Set[str]:
        """Dynamic names visible in fn's body: its own dynamic params plus
        those of enclosing traced functions (closures over tracers)."""
        dyn: Set[str] = set()
        cur: Optional[FunctionNode] = fn
        while cur is not None:
            tf = self.traced.get(cur)
            if tf is not None:
                dyn |= tf.dynamic
            cur = self._fn_parents.get(cur)
        return dyn

    def _site_dynamic_params(self, call: ast.Call, callee: FunctionNode,
                             dyn_env: Set[str]) -> Set[str]:
        """Callee params that receive a dynamic-derived expression at this
        call site. Starred/unmappable sites degrade to all params."""
        a = callee.args
        positional = [p.arg for p in a.posonlyargs + a.args]
        if any(isinstance(x, ast.Starred) for x in call.args) or any(
                kw.arg is None for kw in call.keywords):
            if any(touches_dynamic(self.mod, x.value
                                   if isinstance(x, ast.Starred) else x,
                                   dyn_env)
                   for x in list(call.args)
                   + [kw.value for kw in call.keywords]):
                return self._all_param_names(callee)
            return set()
        out: Set[str] = set()
        for i, arg in enumerate(call.args):
            if touches_dynamic(self.mod, arg, dyn_env):
                if i < len(positional):
                    out.add(positional[i])
                elif a.vararg:
                    out.add(a.vararg.arg)
        valid_kw = {p.arg for p in a.args + a.posonlyargs + a.kwonlyargs}
        for kw in call.keywords:
            if touches_dynamic(self.mod, kw.value, dyn_env):
                out.add(kw.arg if kw.arg in valid_kw
                        else (a.kwarg.arg if a.kwarg else kw.arg))
        return out

    def _propagate(self):
        """Worklist over the intra-module call graph + lexical nesting,
        mapping dynamic-ness of call arguments onto callee params."""
        work = list(self.traced)
        while work:
            fn = work.pop()
            tf = self.traced.get(fn)
            if tf is None:
                continue
            via_name = tf.name or "<lambda>"
            dyn_env = self._dyn_env(fn)
            changed: Set[FunctionNode] = set()
            for node in ast.walk(fn):
                # nested defs/lambdas run under the same trace; their
                # params' dynamic-ness comes from call sites / lax roots
                if (node is not fn and node in self._fn_parents
                        and self._fn_parents[node] is fn):
                    if self._mark(node, f"nested in traced {via_name}",
                                  set()):
                        changed.add(node)
                # plain-name calls reach same-module functions
                if isinstance(node, ast.Call) and isinstance(node.func,
                                                             ast.Name):
                    for callee in self._all_functions.get(node.func.id, []):
                        site_dyn = self._site_dynamic_params(
                            node, callee, dyn_env)
                        if self._mark(callee,
                                      f"called from traced {via_name}",
                                      site_dyn):
                            changed.add(callee)
            for c in changed:
                work.append(c)
                # widening a function's params re-dirties its transitive
                # callees via the worklist when it is reprocessed

    # -- queries ----------------------------------------------------------

    def traced_owner(self, node: ast.AST,
                     parents: Dict[ast.AST, ast.AST]
                     ) -> Optional[TracedFunction]:
        """Innermost traced function whose body contains ``node``."""
        cur = parents.get(node)
        while cur is not None:
            if cur in self.traced:
                return self.traced[cur]
            cur = parents.get(cur)
        return None


def build_parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents
