"""Machinery shared by the devtools analyzers (jaxlint, jaxaudit).

Both tools emit the same ``Finding`` shape, honor the same inline
suppression grammar (``# <tool>: disable=XYZ123 -- reason``), consume the
same snippet-hash baseline format, and render through the same text/JSON
reporters. Factoring it here keeps the two gates behaviorally identical:
a workflow learned on one tool (suppression reasons, baseline updates,
exit codes) transfers verbatim to the other.

Baseline entries key on (rule, path, snippet-hash) with a count — NOT on
line numbers, so unrelated edits above a grandfathered site don't churn
the file. Matching is consuming: N baselined copies of an identical line
absorb at most N findings; the N+1st is new and fails the gate. The
acceptance state for this repo is an EMPTY baseline for both tools.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import re
import tokenize
from collections import Counter
from pathlib import Path
from typing import Dict, List, Optional, Pattern, Tuple

__all__ = [
    "Finding",
    "make_disable_re",
    "SuppressionTable",
    "parse_suppressions",
    "Baseline",
    "baseline_key",
    "render_text",
    "render_json",
    "render_table",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str          # "JXL001" / "JXA103"
    path: str          # posix path as given to the analyzer
    line: int          # 1-based
    col: int           # 0-based
    message: str
    snippet: str = ""  # stripped source line, for reports and baseline keys

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


def make_disable_re(tool: str) -> Pattern:
    """Compiled ``# <tool>: disable[-file]=CODES [-- reason]`` directive."""
    return re.compile(
        rf"#\s*{re.escape(tool)}:\s*disable(?P<file>-file)?\s*=\s*"
        r"(?P<codes>[A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
        r"(?:\s*--\s*(?P<reason>.*))?"
    )


@dataclasses.dataclass
class SuppressionTable:
    """Per-line and file-wide ``disable=`` directives.

    A finding at line L is suppressed when its rule code appears in a
    directive on line L itself, in a stand-alone comment in the run of
    comment-only lines directly above L (plain explanatory comments in
    the run don't break it), or in a ``disable-file=`` directive
    anywhere in the file.
    """

    by_line: Dict[int, set]          # line -> {codes} (directive ON that line)
    comment_only: Dict[int, set]     # comment-only DIRECTIVE lines
    comment_lines: set               # ALL comment-only lines (any content)
    file_wide: set

    def is_suppressed(self, code: str, line: int) -> bool:
        if code in self.file_wide:
            return True
        if code in self.by_line.get(line, ()):
            return True
        # run of comment-only lines directly above the finding
        lookup = line - 1
        while lookup in self.comment_lines:
            if code in self.comment_only.get(lookup, ()):
                return True
            lookup -= 1
        return False


def parse_suppressions(source: str, directive_re: Pattern) -> SuppressionTable:
    by_line: Dict[int, set] = {}
    comment_only: Dict[int, set] = {}
    comment_lines: set = set()
    file_wide: set = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        line = tok.start[0]
        standalone = tok.line[: tok.start[1]].strip() == ""
        if standalone:
            comment_lines.add(line)
        m = directive_re.search(tok.string)
        if not m:
            continue
        codes = {c.strip() for c in m.group("codes").split(",")}
        if m.group("file"):
            file_wide |= codes
            continue
        by_line.setdefault(line, set()).update(codes)
        if standalone:
            comment_only.setdefault(line, set()).update(codes)
    return SuppressionTable(by_line, comment_only, comment_lines, file_wide)


# ---------------------------------------------------------------------------
# committed baseline of grandfathered findings
# ---------------------------------------------------------------------------

_VERSION = 1


def baseline_key(f: Finding) -> Tuple[str, str, str]:
    digest = hashlib.sha256(f.snippet.encode()).hexdigest()[:16]
    return (f.rule, f.path, digest)


@dataclasses.dataclass
class Baseline:
    entries: Counter  # (rule, path, snippet_hash) -> count

    @classmethod
    def empty(cls) -> "Baseline":
        return cls(entries=Counter())

    @classmethod
    def from_findings(cls, findings: List[Finding]) -> "Baseline":
        return cls(entries=Counter(baseline_key(f) for f in findings))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls.empty()
        data = json.loads(p.read_text())
        if data.get("version") != _VERSION:
            raise ValueError(
                f"baseline {path}: unsupported version {data.get('version')}"
            )
        entries: Counter = Counter()
        for e in data.get("entries", []):
            entries[(e["rule"], e["path"], e["snippet_hash"])] = int(
                e.get("count", 1)
            )
        return cls(entries=entries)

    def save(self, path: str) -> None:
        entries = [
            {"rule": r, "path": p, "snippet_hash": h, "count": c}
            for (r, p, h), c in sorted(self.entries.items())
            if c > 0
        ]
        Path(path).write_text(
            json.dumps({"version": _VERSION, "entries": entries}, indent=2)
            + "\n"
        )

    def filter_new(self, findings: List[Finding]
                   ) -> Tuple[List[Finding], List[Finding]]:
        """(new, grandfathered): consume baseline credit per finding."""
        budget = Counter(self.entries)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            k = baseline_key(f)
            if budget[k] > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def render_table(rows: List[Tuple], headers: Optional[Tuple] = None) -> str:
    """Column-aligned plain-text table (cells str()-ed, left-justified).

    Lives here, next to the devtools reporters, as the one table
    renderer in-repo CLIs share; current consumer is the
    ``sphexa-telemetry`` summary/diff output.
    """
    srows = [tuple(str(c) for c in r) for r in rows]
    if headers is not None:
        srows = [tuple(str(c) for c in headers)] + srows
    if not srows:
        return ""
    ncol = max(len(r) for r in srows)
    srows = [r + ("",) * (ncol - len(r)) for r in srows]
    widths = [max(len(r[i]) for r in srows) for i in range(ncol)]
    lines = [
        "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in srows
    ]
    if headers is not None:
        lines.insert(1, "  ".join("-" * w for w in widths).rstrip())
    return "\n".join(lines)


def render_text(new: List[Finding], grandfathered: List[Finding],
                suppressed: List[Finding], errors: List[Finding],
                tool: str, show_suppressed: bool = False) -> str:
    lines: List[str] = []
    for f in errors:
        lines.append(f.format())
    for f in new:
        lines.append(f.format())
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if show_suppressed:
        for f in suppressed:
            lines.append(f"[suppressed] {f.format()}")
        for f in grandfathered:
            lines.append(f"[baseline] {f.format()}")
    n_new = len(new) + len(errors)
    summary = (
        f"{tool}: {n_new} finding(s)"
        + (f", {len(grandfathered)} baselined" if grandfathered else "")
        + (f", {len(suppressed)} suppressed inline" if suppressed else "")
    )
    lines.append(summary)
    return "\n".join(lines)


def render_json(new: List[Finding], grandfathered: List[Finding],
                suppressed: List[Finding], errors: List[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.to_json() for f in new],
            "errors": [f.to_json() for f in errors],
            "baselined": [f.to_json() for f in grandfathered],
            "suppressed": [f.to_json() for f in suppressed],
        },
        indent=2,
    )


def finish_cli(prog: str, tool: str, args, active: List[Finding],
               suppressed: List[Finding], errors: List[Finding]) -> int:
    """Shared CLI tail for both analyzers: --update-baseline writing,
    baseline filtering, text/JSON rendering, exit code. One copy so the
    two gates' contracts (messages, exception handling, exit codes:
    0 clean / 1 findings-or-errors / 2 usage) can never drift apart.

    ``args`` needs the common argparse fields: baseline, update_baseline,
    format, show_suppressed.
    """
    import sys

    if args.update_baseline:
        Baseline.from_findings(active).save(args.baseline)
        print(f"{prog}: wrote {len(active)} entr"
              f"{'y' if len(active) == 1 else 'ies'} to {args.baseline}")
        return 0

    try:
        baseline = Baseline.load(args.baseline) if args.baseline \
            else Baseline.empty()
    except (ValueError, OSError) as e:
        print(f"{prog}: cannot read baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 2
    new, grandfathered = baseline.filter_new(active)

    if args.format == "json":
        print(render_json(new, grandfathered, suppressed, errors))
    else:
        print(render_text(new, grandfathered, suppressed, errors,
                          tool=tool, show_suppressed=args.show_suppressed))
    return 1 if (new or errors) else 0
