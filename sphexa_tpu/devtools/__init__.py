"""Developer tooling that ships inside the package so CI and tests can
import it without a separate install (jaxlint lives here)."""
