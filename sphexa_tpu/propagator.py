"""Propagators: the per-step orchestration of SPH ops.

TPU-native counterpart of the reference's ``main/src/propagator/``
(ipropagator.hpp, std_hydro.hpp, ve_hydro.hpp): a propagator owns the
sequence of kernel calls for one time step. Where the reference interleaves
MPI halo exchanges between kernels, the jitted step here operates on the
full (sharded) arrays and XLA materializes whatever communication the
shardings imply; the host never orchestrates communication.

The whole step — SFC sort, neighbor search, hydro pipeline, time step,
integration — is ONE jitted function of the ParticleState pytree, so XLA
sees the complete dataflow and can fuse/schedule across op boundaries.
"""

import dataclasses
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.neighbors.cell_list import NeighborConfig, find_neighbors
from sphexa_tpu.sfc.box import Box, make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.sph import hydro_std, hydro_ve
from sphexa_tpu.sph.kernels import update_h
from sphexa_tpu.sph.particles import ParticleState, SimConstants
from sphexa_tpu.sph.positions import compute_positions
from sphexa_tpu.sph.timestep import compute_timestep, rho_timestep


@dataclasses.dataclass(frozen=True)
class PropagatorConfig:
    """Static per-run configuration: physics constants + neighbor search."""

    const: SimConstants
    nbr: NeighborConfig
    curve: str = "hilbert"
    block: int = 2048
    av_clean: bool = False


def _sort_by_keys(state: ParticleState, box: Box, curve: str):
    """Global SFC sort: the analog of domain.sync()'s keygen + radix sort
    (cstone/domain/assignment.hpp:84-122). Every field array is gathered
    into key order; scalars pass through untouched.
    """
    keys = compute_sfc_keys(state.x, state.y, state.z, box, curve=curve)
    order = jnp.argsort(keys)
    sorted_keys = keys[order]

    def maybe_gather(leaf):
        return leaf[order] if leaf.ndim == 1 and leaf.shape[0] == state.n else leaf

    return jax.tree.map(maybe_gather, state), sorted_keys


def _integrate_and_finish(
    state: ParticleState, box: Box, const: SimConstants,
    ax, ay, az, du, dt, nc, occ, rho, extra=None,
):
    """Shared step tail: drift/kick + PBC wrap, smoothing-length nudge,
    state rebuild, diagnostics. Every propagator's force stage funnels
    through here (the analog of the common trailing sequence of
    std_hydro.hpp/ve_hydro.hpp step())."""
    fields = (state.x, state.y, state.z, state.x_m1, state.y_m1, state.z_m1,
              state.vx, state.vy, state.vz, state.h, state.temp, du, state.du_m1)
    (nx, ny, nz, dxm, dym, dzm, vx, vy, vz, h, temp, du, du_m1) = compute_positions(
        fields, ax, ay, az, dt, state.min_dt, box, const
    )
    new_h = update_h(const.ng0, nc + 1, h)
    new_state = dataclasses.replace(
        state,
        x=nx, y=ny, z=nz, x_m1=dxm, y_m1=dym, z_m1=dzm,
        vx=vx, vy=vy, vz=vz, h=new_h, temp=temp, du=du, du_m1=du_m1,
        ttot=state.ttot + dt, min_dt=dt, min_dt_m1=state.min_dt,
        **(extra or {}),
    )
    diagnostics = {
        "dt": dt,
        "nc_mean": jnp.mean(nc.astype(jnp.float32)) + 1.0,
        "nc_max": jnp.max(nc) + 1,
        "occupancy": occ,
        "rho_max": jnp.max(rho),
    }
    return new_state, box, diagnostics


@functools.partial(jax.jit, static_argnames=("cfg",))
def step_hydro_std(
    state: ParticleState, box: Box, cfg: PropagatorConfig
) -> Tuple[ParticleState, Box, Dict[str, jax.Array]]:
    """One standard-SPH time step (std_hydro.hpp:123-175 sequence).

    box regrow -> sort -> neighbors -> density -> EOS -> IAD ->
    momentum/energy -> timestep -> positions -> smoothing-length update.
    Returns (new_state, new_box, diagnostics).
    """
    const = cfg.const
    # grow open-boundary dims to fit drifted particles (box_mpi.hpp role);
    # box limits are traced values, so this never recompiles
    box = make_global_box(state.x, state.y, state.z, box)
    state, keys = _sort_by_keys(state, box, cfg.curve)
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m

    nidx, nmask, nc, occ = find_neighbors(x, y, z, h, keys, box, cfg.nbr)

    rho = hydro_std.compute_density(x, y, z, h, m, nidx, nmask, box, const, cfg.block)
    p, c = hydro_std.compute_eos_std(state.temp, rho, const)
    c11, c12, c13, c22, c23, c33 = hydro_std.compute_iad(
        x, y, z, h, m / rho, nidx, nmask, box, const, cfg.block
    )
    ax, ay, az, du, dt_courant = hydro_std.compute_momentum_energy_std(
        x, y, z, state.vx, state.vy, state.vz, h, m, rho, p, c,
        c11, c12, c13, c22, c23, c33, nidx, nmask, box, const, cfg.block,
    )

    dt = compute_timestep(state.min_dt, dt_courant, const=const)
    return _integrate_and_finish(state, box, const, ax, ay, az, du, dt, nc, occ, rho)


@functools.partial(jax.jit, static_argnames=("cfg",))
def step_hydro_ve(
    state: ParticleState, box: Box, cfg: PropagatorConfig
) -> Tuple[ParticleState, Box, Dict[str, jax.Array]]:
    """One generalized-volume-element SPH time step.

    Mirrors HydroVeProp::computeForces (ve_hydro.hpp:131-208): sort ->
    neighbors -> xmass -> ve_def_gradh -> EOS -> IAD -> divv/curlv ->
    AV switches -> momentum/energy [avClean] -> timestep -> positions ->
    smoothing-length update. The reference's halo exchanges between stages
    vanish: XLA materializes whatever communication the shardings imply.
    """
    const = cfg.const
    box = make_global_box(state.x, state.y, state.z, box)
    state, keys = _sort_by_keys(state, box, cfg.curve)
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m
    vx, vy, vz = state.vx, state.vy, state.vz

    nidx, nmask, nc, occ = find_neighbors(x, y, z, h, keys, box, cfg.nbr)

    xm = hydro_ve.compute_xmass(x, y, z, h, m, nidx, nmask, box, const, cfg.block)
    kx, gradh = hydro_ve.compute_ve_def_gradh(
        x, y, z, h, m, xm, nidx, nmask, box, const, cfg.block
    )
    prho, c, rho, p = hydro_ve.compute_eos_ve(state.temp, m, kx, xm, gradh, const)

    c11, c12, c13, c22, c23, c33 = hydro_std.compute_iad(
        x, y, z, h, xm / kx, nidx, nmask, box, const, cfg.block
    )
    dvout = hydro_ve.compute_iad_divv_curlv(
        x, y, z, vx, vy, vz, h, kx, xm,
        c11, c12, c13, c22, c23, c33,
        nidx, nmask, box, const, cfg.block, with_gradv=cfg.av_clean,
    )
    if cfg.av_clean:
        divv, curlv, *gradv = dvout
        gradv = tuple(gradv)
    else:
        divv, curlv = dvout
        gradv = None

    dt_rho = rho_timestep(divv, const)

    alpha = hydro_ve.compute_av_switches(
        x, y, z, vx, vy, vz, h, c, kx, xm, divv, state.alpha,
        c11, c12, c13, c22, c23, c33,
        nidx, nmask, box, state.min_dt, const, cfg.block,
    )

    ax, ay, az, du, dt_courant = hydro_ve.compute_momentum_energy_ve(
        x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha,
        c11, c12, c13, c22, c23, c33,
        nidx, nmask, nc, box, const, cfg.block, gradv=gradv,
    )

    dt = compute_timestep(state.min_dt, dt_courant, dt_rho, const=const)
    return _integrate_and_finish(
        state, box, const, ax, ay, az, du, dt, nc, occ, rho,
        extra={"alpha": alpha},
    )
