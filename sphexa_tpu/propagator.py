"""Propagators: the per-step orchestration of SPH ops.

TPU-native counterpart of the reference's ``main/src/propagator/``
(ipropagator.hpp, std_hydro.hpp, ve_hydro.hpp): a propagator owns the
sequence of kernel calls for one time step. Where the reference interleaves
MPI halo exchanges between kernels, the jitted step here operates on the
full (sharded) arrays and XLA materializes whatever communication the
shardings imply; the host never orchestrates communication.

The whole step — SFC sort, neighbor search, hydro pipeline, time step,
integration — is ONE jitted function of the ParticleState pytree, so XLA
sees the complete dataflow and can fuse/schedule across op boundaries.
"""

import dataclasses
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.neighbors.cell_list import NeighborConfig, find_neighbors
from sphexa_tpu.sfc.box import Box, make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph.kernels import update_h
from sphexa_tpu.sph.particles import ParticleState, SimConstants
from sphexa_tpu.sph.positions import compute_positions
from sphexa_tpu.sph.timestep import compute_timestep


@dataclasses.dataclass(frozen=True)
class PropagatorConfig:
    """Static per-run configuration: physics constants + neighbor search."""

    const: SimConstants
    nbr: NeighborConfig
    curve: str = "hilbert"
    block: int = 2048


def _sort_by_keys(state: ParticleState, box: Box, curve: str):
    """Global SFC sort: the analog of domain.sync()'s keygen + radix sort
    (cstone/domain/assignment.hpp:84-122). Every field array is gathered
    into key order; scalars pass through untouched.
    """
    keys = compute_sfc_keys(state.x, state.y, state.z, box, curve=curve)
    order = jnp.argsort(keys)
    sorted_keys = keys[order]

    def maybe_gather(leaf):
        return leaf[order] if leaf.ndim == 1 and leaf.shape[0] == state.n else leaf

    return jax.tree.map(maybe_gather, state), sorted_keys


@functools.partial(jax.jit, static_argnames=("cfg",))
def step_hydro_std(
    state: ParticleState, box: Box, cfg: PropagatorConfig
) -> Tuple[ParticleState, Dict[str, jax.Array]]:
    """One standard-SPH time step (std_hydro.hpp:123-175 sequence).

    box regrow -> sort -> neighbors -> density -> EOS -> IAD ->
    momentum/energy -> timestep -> positions -> smoothing-length update.
    Returns (new_state, new_box, diagnostics).
    """
    const = cfg.const
    # grow open-boundary dims to fit drifted particles (box_mpi.hpp role);
    # box limits are traced values, so this never recompiles
    box = make_global_box(state.x, state.y, state.z, box)
    state, keys = _sort_by_keys(state, box, cfg.curve)
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m

    nidx, nmask, nc, occ = find_neighbors(x, y, z, h, keys, box, cfg.nbr)

    rho = hydro_std.compute_density(x, y, z, h, m, nidx, nmask, box, const, cfg.block)
    p, c = hydro_std.compute_eos_std(state.temp, rho, const)
    c11, c12, c13, c22, c23, c33 = hydro_std.compute_iad(
        x, y, z, h, m / rho, nidx, nmask, box, const, cfg.block
    )
    ax, ay, az, du, dt_courant = hydro_std.compute_momentum_energy_std(
        x, y, z, state.vx, state.vy, state.vz, h, m, rho, p, c,
        c11, c12, c13, c22, c23, c33, nidx, nmask, box, const, cfg.block,
    )

    dt = compute_timestep(state.min_dt, dt_courant, const=const)

    fields = (x, y, z, state.x_m1, state.y_m1, state.z_m1,
              state.vx, state.vy, state.vz, h, state.temp, du, state.du_m1)
    (nx, ny, nz, dxm, dym, dzm, vx, vy, vz, h, temp, du, du_m1) = compute_positions(
        fields, ax, ay, az, dt, state.min_dt, box, const
    )

    new_h = update_h(const.ng0, nc + 1, h)

    new_state = dataclasses.replace(
        state,
        x=nx, y=ny, z=nz, x_m1=dxm, y_m1=dym, z_m1=dzm,
        vx=vx, vy=vy, vz=vz, h=new_h, temp=temp, du=du, du_m1=du_m1,
        ttot=state.ttot + dt, min_dt=dt, min_dt_m1=state.min_dt,
    )
    diagnostics = {
        "dt": dt,
        "nc_mean": jnp.mean(nc.astype(jnp.float32)) + 1.0,
        "nc_max": jnp.max(nc) + 1,
        "occupancy": occ,
        "rho_max": jnp.max(rho),
    }
    return new_state, box, diagnostics
