"""Propagators: the per-step orchestration of SPH ops.

TPU-native counterpart of the reference's ``main/src/propagator/``
(ipropagator.hpp, std_hydro.hpp, ve_hydro.hpp): a propagator owns the
sequence of kernel calls for one time step. Where the reference interleaves
MPI halo exchanges between kernels, the jitted step here operates on the
full (sharded) arrays and XLA materializes whatever communication the
shardings imply; the host never orchestrates communication.

The whole step — SFC sort, neighbor search, hydro pipeline, time step,
integration — is ONE jitted function of the ParticleState pytree, so XLA
sees the complete dataflow and can fuse/schedule across op boundaries.
"""

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.gravity.ewald import EwaldConfig, compute_gravity_ewald
from sphexa_tpu.gravity.traversal import GravityConfig, compute_gravity
from sphexa_tpu.gravity.tree import GravityTree, GravityTreeMeta
from sphexa_tpu.neighbors.cell_list import NeighborConfig, find_neighbors
from sphexa_tpu.observables.ledger import (
    NUM_DIAG_KEYS,
    OBS_DIAG_KEYS,
    ObservableSpec,
    ledger_diagnostics,
)
from sphexa_tpu.observables.snapshot import (
    SNAP_DIAG_KEYS,
    SnapshotSpec,
    snapshot_diagnostics,
)
from sphexa_tpu.sfc.box import Box, make_global_box, put_in_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.sph import blockdt as bdt
from sphexa_tpu.sph import hydro_std, hydro_ve
from sphexa_tpu.sph.kernels import update_h
from sphexa_tpu.sph.particles import ParticleState, SimConstants
from sphexa_tpu.sph.positions import compute_positions
from sphexa_tpu.sph.timestep import (
    acceleration_timestep,
    compute_timestep,
    rho_timestep,
)
from sphexa_tpu.util.phases import phase_scope

try:  # jax >= 0.6 exports shard_map at the top level
    from jax import shard_map as _jax_shard_map
except ImportError:  # older jax keeps it in the experimental namespace
    from jax.experimental.shard_map import shard_map as _jax_shard_map

import inspect as _inspect

_SHARD_MAP_PARAMS = frozenset(
    _inspect.signature(_jax_shard_map).parameters
)

#: Canonical scalar diagnostics every propagator's step emits — the
#: naming contract between the step functions, the Simulation driver's
#: overflow checks, and the telemetry layer (sphexa_tpu/telemetry/).
#: ``_integrate_and_finish`` is the single producer; propagator-specific
#: extras (egrav, dt_cool, list_slack, ...) ride alongside but consumers
#: must ``.get()`` them — only THESE keys may be assumed present.
STEP_DIAG_KEYS = ("dt", "nc_mean", "nc_max", "occupancy", "rho_max",
                  "h_max")

#: Per-shard (P,) diagnostics the SHARDED force stages ride alongside the
#: scalars — the distributed-telemetry contract (schema-v2 ``exchange`` /
#: ``shard_load`` events). All are cheap in-graph reductions all_gathered
#: to O(P) replicated arrays; the Simulation fetches them at its existing
#: flush boundary, so they add ZERO host syncs to the deferred happy path
#: (pinned by tests/test_telemetry.py). Present only on mesh runs through
#: the pallas fast path; consumers must .get() them.
SHARD_DIAG_KEYS = ("shard_rows", "shard_occ", "shard_work", "shard_trips")

#: gravity-stage analog of SHARD_DIAG_KEYS: per-shard (P,) TRUE remote
#: row need + per-distance cap occupancy of the MAC-sized sparse gravity
#: near-field exchange (schema-v7 ``stage="gravity"`` exchange /
#: shard_load events). Present only when ``cfg.grav_cells`` sizes the
#: sparse serve — the windowed / full-slab gravity path emits neither,
#: keeping its lowering byte-identical.
GRAV_SHARD_DIAG_KEYS = ("gshard_rows", "gshard_occ")

#: OBS_DIAG_KEYS / NUM_DIAG_KEYS (imported above) complete the diag-key
#: families: the in-graph science ledger's conservation and
#: numerics-health scalars (observables/ledger.py) ride the diagnostics
#: dict and are fetched at the existing check/flush boundary exactly
#: like SHARD_DIAG_KEYS — zero added host syncs under deferral.

#: timestep-limiter attribution: ``diagnostics["dt_limiter"]`` indexes
#: this tuple — WHICH candidate bound the step's dt (growth = the 1.1x
#: previous-dt cap, then courant/rho/cool/accel as compute_timestep
#: combines them, timestep.hpp:97-112). One global order across all
#: propagators; inactive candidates rank as +inf.
DT_LIMITERS = ("growth", "courant", "rho", "cool", "accel")

#: block-timestep diagnostics the *_blockdt step builders ride alongside
#: STEP_DIAG_KEYS (consumers must .get() them): active-row count, the
#: (dt_bins,) bin occupancy histogram, the substep just executed, the
#: drift-aware resort decision + its inversion count, and the
#: active-rows neighbor-work proxy gathered through the compaction list.
BLOCKDT_DIAG_KEYS = ("bdt_active", "bdt_pop", "bdt_substep", "bdt_resort",
                     "bdt_drift", "bdt_work")


def _dt_limiter(min_dt_prev, const: SimConstants, courant=None, rho=None,
                cool=None, accel=None):
    """Index into DT_LIMITERS of the binding dt candidate — the in-graph
    attribution of ``compute_timestep``'s min-reduction (ties resolve to
    the earlier name, matching jnp.argmin)."""
    inf = jnp.asarray(jnp.inf, jnp.float32)
    cands = [const.max_dt_increase * min_dt_prev, courant, rho, cool, accel]
    stack = jnp.stack([inf if c is None else jnp.asarray(c, jnp.float32)
                       for c in cands])
    return jnp.argmin(stack).astype(jnp.int32)


def shard_map(*args, **kwargs):
    """Version-compat shard_map: the replication check kwarg was renamed
    check_rep -> check_vma across jax releases; translate so the same
    call sites run on both."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _jax_shard_map(*args, **kwargs)


@dataclasses.dataclass(frozen=True)
class PropagatorConfig:
    """Static per-run configuration: physics constants + neighbor search.

    When self-gravity is on (const.g != 0), ``gravity`` holds the static
    solver caps and ``grav_meta`` the (hashable) tree-structure metadata;
    the matching GravityTree arrays are passed to the step function as a
    pytree argument (the structure is host-rebuilt at reconfiguration
    granularity, like the neighbor cell grid).
    """

    const: SimConstants
    nbr: NeighborConfig
    curve: str = "hilbert"
    block: int = 2048
    av_clean: bool = False
    gravity: Optional[GravityConfig] = None
    grav_meta: Optional[GravityTreeMeta] = None
    # periodic-box gravity: when set, the Barnes-Hut solve goes through the
    # Ewald path (replica near field + real/k-space corrections)
    ewald: Optional[EwaldConfig] = None
    # include the per-particle accelerations in the step diagnostics (the
    # gravitational-wave observable consumes them, gravitational_waves.hpp)
    keep_accels: bool = False
    # include per-particle rho and sound speed c in the diagnostics (the
    # field-consuming observables read them, avoiding a second full
    # density/EOS pass per step); arrays are in the post-step state order
    keep_fields: bool = False
    # 'pallas': fused search+op TPU kernels for the std pipeline
    # (sph/pallas_pairs.py); 'xla': portable gather-based path
    backend: str = "xla"
    # multi-chip fast path: when set (with backend='pallas'), the pair-op
    # stage runs under shard_map over ``mesh`` — each device executes the
    # Mosaic engine on its SFC slab, with the windowed all_to_all halo
    # exchange supplying the j-side candidates (parallel/exchange.py)
    mesh: Optional[object] = None
    shard_axis: Optional[str] = None
    # per-peer halo window rows (Wmax). 0 = full peer slabs (the safe
    # all_gather-equivalent); sized tighter by estimate_halo_window
    halo_window: int = 0
    # sparse cell-granular halo exchange: P-1 per-DISTANCE row caps
    # (parallel/exchange.shard_halo_stage_sparse). Non-empty takes
    # precedence over halo_window for the SPH stages; comm volume is
    # sum(halo_cells) rows per serve and tracks the halo surface instead
    # of degenerating to whole slabs (docs/NEXT.md round-4 measurement)
    halo_cells: Tuple[int, ...] = ()
    # MAC-sized sparse gravity near-field exchange: P-1 per-DISTANCE row
    # caps (parallel/sizing.device_gravity_halo) for the leaf-granular
    # serve inside compute_gravity's shard path. () = full peer slabs
    # (the grav_window=0 fallback and the escape-retry ceiling)
    grav_cells: Tuple[int, ...] = ()
    # persistent-neighbor-list mode (sph/pair_lists.py): > 0 enables it
    # with this per-group chunk-slot budget; steady steps then skip the
    # global sort AND the candidate prologue, momentum ops lane-compact,
    # cheap ops chunk-skip. Sized at configure time like every cap.
    list_slot_cap: int = 0
    # case observable computed in-graph alongside the conservation
    # ledger (observables/ledger.py); None = energies only
    obs: Optional[ObservableSpec] = None
    # in-graph downsampled field-grid snapshot (observables/snapshot.py);
    # None is never read by the step builders, so unset leaves every
    # lowering byte-identical (the dt_bins pattern)
    snap: Optional[SnapshotSpec] = None
    # Verlet skin as a fraction of the 2*h_max search radius: larger =
    # fewer rebuilds but more candidate lanes per target
    list_skin_rel: float = 0.2
    # hierarchical block time steps (sph/blockdt.py): number of
    # power-of-two Δt bins the *_blockdt step builders use. None = the
    # global-dt path, bitwise unchanged (the field is never read outside
    # the blockdt builders); 1 = blockdt machinery with every particle
    # due every substep, pinned bitwise-equal to the global path
    dt_bins: Optional[int] = None
    # re-bin cadence in CYCLES at the sync substep (1 = every cycle);
    # larger amortizes the bin assignment at the cost of staler bins
    bin_sync_every: int = 1
    # drift-aware resort threshold: the blockdt sort keeps the incoming
    # order when the folded-key inversion count is <= this fraction of n
    # (0.0 = keep only when already perfectly sorted — exact)
    bin_resort_drift: float = 0.0


def _sort_by_keys(state: ParticleState, box: Box, curve: str, aux=None,
                  bins=None, resort_drift: float = 0.0):
    """Global SFC sort: the analog of domain.sync()'s keygen + radix sort
    (cstone/domain/assignment.hpp:84-122). Every field array is gathered
    into key order; scalars pass through untouched. ``aux``: an optional
    extra pytree of per-particle arrays (e.g. ChemistryData) permuted
    identically so it stays aligned with the persisted sorted state.

    ``bins``: block-timestep path — the bin index is folded below the
    spatial bits (blockdt.fold_bin_key) so one argsort groups equal-key
    particles by bin, and the permute goes DRIFT-AWARE: a cheap in-graph
    inversion count over the folded keys decides resort-now vs keep
    (``resort_drift`` = tolerated inversion fraction; ROADMAP item 2b —
    fixed resort cadence measured net-negative, the check is the new
    idea). Returns ``(state, keys, aux, resorted, inversions)``; the
    plain path keeps its 3-tuple and its lowering byte-identical.
    """
    # sphexa/sort: the whole keygen + argsort + permute program is one
    # attribution phase (profiler traces; util/phases.py taxonomy)
    with phase_scope("sort"):
        keys = compute_sfc_keys(state.x, state.y, state.z, box, curve=curve)
        if bins is None:
            order = jnp.argsort(keys)
            sorted_keys = keys[order]
    n = state.n

    def permute_tree(tree, order):
        """Permute every (n,) leaf. Same-dtype leaves are stacked into one
        (n, F) matrix and gathered by ROW: XLA's TPU gather moves F
        contiguous elements per index, ~18x faster than F separate 1-D
        gathers (the reference's analogous trick is the byte-packed
        multi-array exchange, domaindecomp_mpi.hpp:62)."""
        if tree is None:
            return None
        leaves, treedef = jax.tree.flatten(tree)
        per_dtype: Dict = {}
        for i, a in enumerate(leaves):
            if getattr(a, "ndim", -1) == 1 and a.shape[0] == n:
                per_dtype.setdefault(a.dtype, []).append(i)
        for dtype, idxs in per_dtype.items():
            if len(idxs) == 1:
                leaves[idxs[0]] = leaves[idxs[0]][order]
                continue
            mat = jnp.stack([leaves[i] for i in idxs], axis=1)[order]
            for k, i in enumerate(idxs):
                leaves[i] = mat[:, k]
        return jax.tree.unflatten(treedef, leaves)

    if bins is None:
        with phase_scope("sort"):
            return (permute_tree(state, order), sorted_keys,
                    permute_tree(aux, order))

    with phase_scope("dt-bins"):
        skey = bdt.fold_bin_key(keys, bins)
        inv = jnp.sum((skey[1:] < skey[:-1]).astype(jnp.int32))
        # static threshold: resort_drift and n are trace-time constants
        resort = inv > jnp.int32(int(resort_drift * n))

    def do_resort(state, keys, aux):
        with phase_scope("sort"):
            order = jnp.argsort(skey)
            return permute_tree(state, order), keys[order], \
                permute_tree(aux, order)

    def keep(state, keys, aux):
        return state, keys, aux

    # only the taken branch executes at runtime — the keep branch skips
    # the whole argsort + row-gather program, which is the entire point
    with phase_scope("sort"):
        state, keys, aux = jax.lax.cond(resort, do_resort, keep,
                                        state, keys, aux)
    return state, keys, aux, resort.astype(jnp.int32), inv


@functools.partial(jax.jit, static_argnames=("cfg",))
def rebuild_pair_lists(state: ParticleState, box: Box,
                       cfg: PropagatorConfig, aux=None):
    """Persistent-list rebuild: box regrow + global SFC sort + list build
    (sph/pair_lists.py). The returned state is the FROZEN sorted order
    every steady step runs in until the next rebuild; ``aux`` (e.g.
    ChemistryData) is permuted identically. The skin re-derives from the
    current h_max, so it tracks the evolving resolution."""
    from sphexa_tpu.sph.pair_lists import build_pair_lists

    with phase_scope("sort"):
        box = make_global_box(state.x, state.y, state.z, box)
    state, keys, aux = _sort_by_keys(state, box, cfg.curve, aux=aux)
    with phase_scope("neighbors"):
        skin = jnp.float32(cfg.list_skin_rel) * 2.0 * jnp.max(state.h)
        lists = build_pair_lists(
            state.x, state.y, state.z, state.h, keys, box, cfg.nbr,
            skin, cfg.list_slot_cap, interpret=_pallas_interpret(),
        )
    return state, box, lists, aux


def _chain_stage_reductions(egrav, diag, axis):
    """Pin the gravity stage-tail reductions into one total order.

    egrav/diag arrive per-shard; the psum + diagnostic pmaxes that
    normalize them are otherwise mutually order-free (and unordered
    against the traversal's exchange collectives for pure-constant
    diagnostics like compact_width) — the XLA:CPU rendezvous-race class
    JXA201 gates. diag["p2p_max"] carries the traversal + exchange
    ancestry, so seeding the chain there orders the whole tail after
    the halo all_to_all as well.
    """
    from sphexa_tpu.parallel.exchange import chain_after

    tok = diag.get("p2p_max", egrav)
    egrav = jax.lax.psum(chain_after(egrav, tok), axis)
    tok = egrav
    out = {}
    for k in sorted(diag):
        v = jax.lax.pmax(chain_after(diag[k], tok), axis)
        out[k] = v
        tok = v
    return egrav, out


def _gravity_sharded_stage(state, box, cfg, gtree, keys):
    """Distributed gravity under shard_map: psum multipole upsweep (the
    global_multipole.hpp allreduce analog — O(tree) comm, no particle
    replication), per-shard MAC/M2P on the replicated coarse tree, and
    the near field through the windowed halo exchange. Covers the open
    Barnes-Hut solve (any multipole order) and the periodic Ewald path
    (cartesian quadrupole, traversal_ewald_cpu.hpp parity)."""
    from jax.sharding import PartitionSpec
    from sphexa_tpu.gravity.traversal import compute_multipoles_sharded

    axis = cfg.shard_axis
    P = cfg.mesh.shape[axis]
    S_shard = state.x.shape[0] // P
    # near-field halo sizing: cfg.grav_cells (MAC-need per-distance row
    # caps from sizing.device_gravity_halo — the Warren-Salmon essential
    # set) selects the sparse leaf-granular serve; empty falls back to
    # full-slab windows, which are always correct and are the
    # escape-retry ceiling. cfg.halo_window is never reused here: it is
    # sized from SPH 2h candidate spans while the near field reaches the
    # MAC radius (~2*leaf_edge/theta >> 2h), so an SPH-sized window
    # would escape persistently and the retry loop could not converge.
    if cfg.grav_cells:
        win = tuple(min(int(c), S_shard) for c in cfg.grav_cells)
    else:
        win = S_shard
    gcfg = dataclasses.replace(cfg.gravity, G=cfg.const.g, use_pallas=True)

    def _finish(gx, gy, gz, egrav, diag):
        # per-shard exchange telemetry rides OUTSIDE the pmax fold (the
        # schema-v7 gravity-stage events need the (P,) vectors, not the
        # max); the all_gather chains on diag["p2p_max"] — the LAST link
        # of _chain_stage_reductions' sorted chain — extending the
        # JXA201 total order instead of forking it
        grows = diag.pop("halo_rows", None)
        gocc = diag.pop("halo_occ", None)
        egrav, diag = _chain_stage_reductions(egrav, diag, axis)
        if grows is not None:
            from sphexa_tpu.parallel.exchange import chain_after

            packed = jnp.stack([grows.astype(jnp.float32), gocc])
            g = jax.lax.all_gather(
                chain_after(packed, diag["p2p_max"]), axis
            )
            diag["gshard_rows"] = g[:, 0].astype(jnp.int32)
            diag["gshard_occ"] = g[:, 1]
        return gx, gy, gz, egrav, diag

    if cfg.ewald is not None:

        def stage(box, keys, x, y, z, m, h):
            gx, gy, gz, egrav, diag = compute_gravity_ewald(
                x, y, z, m, h, keys, box, gtree, cfg.grav_meta, gcfg,
                cfg.ewald, shard=(axis, P, win),
            )
            return _finish(gx, gy, gz, egrav, diag)

        dspec = {"m2p_max": PartitionSpec(), "p2p_max": PartitionSpec(),
                 "leaf_occ": PartitionSpec(), "c_max": PartitionSpec(),
                 "let_max": PartitionSpec(),
                 "compact_width": PartitionSpec()}
    else:

        def stage(box, keys, x, y, z, m, h):
            mpc = compute_multipoles_sharded(
                x, y, z, m, keys, gtree, cfg.grav_meta, axis,
                order=gcfg.multipole_order,
            )
            gx, gy, gz, egrav, diag = compute_gravity(
                x, y, z, m, h, keys, box, gtree, cfg.grav_meta, gcfg,
                mp_cache=mpc, shard=(axis, P, win),
            )
            return _finish(gx, gy, gz, egrav, diag)

        dspec = {"m2p_max": PartitionSpec(), "p2p_max": PartitionSpec(),
                 "leaf_occ": PartitionSpec(), "c_max": PartitionSpec(),
                 "let_max": PartitionSpec(),
                 "compact_width": PartitionSpec(),
                 "mac_work_ratio": PartitionSpec()}
    if isinstance(win, tuple):
        dspec = dict(dspec, **{k: PartitionSpec()
                               for k in GRAV_SHARD_DIAG_KEYS})

    Pp, Pr = PartitionSpec(axis), PartitionSpec()
    return shard_map(
        stage,
        mesh=cfg.mesh,
        in_specs=(Pr, Pp, Pp, Pp, Pp, Pp, Pp),
        out_specs=(Pp, Pp, Pp, Pr, dspec),
        check_vma=False,
    )(box, keys, state.x, state.y, state.z, state.m, state.h)


def _add_gravity(state, box, keys, cfg, gtree, ax, ay, az):
    """Self-gravity coupling: Barnes-Hut accel added to the hydro accel.

    The analog of mHolder_.upsweep + traverse inside computeForces
    (main/src/propagator/gravity_wrapper.hpp:97-123): runs on the
    SFC-sorted arrays the step just produced. Returns updated accels,
    egrav, the acceleration dt candidate, and solver diagnostics.
    """
    if cfg.shard_axis is not None:
        gx, gy, gz, egrav, gdiag = _gravity_sharded_stage(
            state, box, cfg, gtree, keys
        )
    elif cfg.ewald is not None:
        gcfg = dataclasses.replace(cfg.gravity, G=cfg.const.g)
        gx, gy, gz, egrav, gdiag = compute_gravity_ewald(
            state.x, state.y, state.z, state.m, state.h, keys, box,
            gtree, cfg.grav_meta, gcfg, cfg.ewald,
        )
    else:
        gcfg = dataclasses.replace(cfg.gravity, G=cfg.const.g)
        gx, gy, gz, egrav, gdiag = compute_gravity(
            state.x, state.y, state.z, state.m, state.h, keys, box,
            gtree, cfg.grav_meta, gcfg,
        )
    ax, ay, az = ax + gx, ay + gy, az + gz
    with phase_scope("timestep"):
        dt_acc = acceleration_timestep(ax, ay, az, cfg.const)
    return ax, ay, az, egrav, dt_acc, gdiag


def _integrate_and_finish(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    ax, ay, az, du, dt, nc, occ, rho, extra=None, extra_diag=None,
    update_smoothing=True, c=None, dt_limiter=None,
):
    """Shared step tail: drift/kick + PBC wrap, smoothing-length nudge,
    state rebuild, diagnostics. Every propagator's force stage funnels
    through here (the analog of the common trailing sequence of
    std_hydro.hpp/ve_hydro.hpp step()); the diagnostics dict it builds
    carries exactly the STEP_DIAG_KEYS scalars, the in-graph science
    ledger (OBS_DIAG_KEYS + NUM_DIAG_KEYS, observables/ledger.py — the
    reference's per-iteration conserved_quantities sweep moved inside
    the step program) plus whatever extras the caller rides along."""
    const = cfg.const
    with phase_scope("integrate"):
        fields = (state.x, state.y, state.z, state.x_m1, state.y_m1,
                  state.z_m1, state.vx, state.vy, state.vz, state.h,
                  state.temp, state.temp_lo, du, state.du_m1)
        (nx, ny, nz, dxm, dym, dzm, vx, vy, vz, h, temp, temp_lo, du,
         du_m1) = compute_positions(
            fields, ax, ay, az, dt, state.min_dt, box, const
        )
        new_h = update_h(const.ng0, nc + 1, h) if update_smoothing else h
        new_state = dataclasses.replace(
            state,
            x=nx, y=ny, z=nz, x_m1=dxm, y_m1=dym, z_m1=dzm,
            vx=vx, vy=vy, vz=vz, h=new_h, temp=temp, temp_lo=temp_lo,
            du=du, du_m1=du_m1,
            ttot=state.ttot + dt, min_dt=dt, min_dt_m1=state.min_dt,
            **(extra or {}),
        )
        diagnostics = {
            "dt": dt,
            "nc_mean": jnp.mean(nc.astype(jnp.float32)) + 1.0,
            "nc_max": jnp.max(nc) + 1,
            "occupancy": occ,
            "rho_max": jnp.max(rho),
            # computed in-step so the host never launches a separate
            # reduction (device->host round trips are expensive over
            # remote links)
            "h_max": jnp.max(new_h),
        }
    # conservation + numerics-health ledger over the post-integration
    # state (the pairing the app's eager recompute used: new positions/
    # velocities/temp with the force stage's rho/c); egrav is the force
    # stage's value, like the reference adds it to etot in-sweep.
    # Conditional like SHARD_DIAG_KEYS/keep_fields: cfg.obs = None skips
    # it (bare library steps stay ledger-free and compile leaner); the
    # app/bench always configure a spec, so every science-facing run
    # carries the full ledger
    if cfg.obs is not None:
        ed = extra_diag or {}
        diagnostics.update(ledger_diagnostics(
            new_state, rho, nc, const, cfg.nbr.ngmax, spec=cfg.obs,
            egrav=ed.get("egrav", 0.0), box=box, c=c,
            smoothing=update_smoothing,
            # sharded force stages chain their collectives and finish on
            # the shard-metrics gather (SHARD_DIAG_KEYS) — anchor the
            # ledger's reductions after it so the two collective families
            # stay totally ordered (the XLA:CPU rendezvous guard)
            token=ed.get("shard_trips"),
        ))
    # in-graph snapshot deposit over the same post-integration state
    # (observables/snapshot.py). Conditional exactly like cfg.obs: None
    # leaves the lowering byte-identical. Chained after the ledger's
    # last min sweep (rho_min) when the ledger runs, else after the
    # shard-metrics gather, keeping one total collective order
    if cfg.snap is not None:
        ed = extra_diag or {}
        diagnostics.update(snapshot_diagnostics(
            new_state, rho, box, cfg.snap,
            token=diagnostics.get("rho_min", ed.get("shard_trips")),
        ))
    if dt_limiter is not None:
        diagnostics["dt_limiter"] = dt_limiter
    if cfg.keep_accels:
        diagnostics.update({"ax": ax, "ay": ay, "az": az})
    if cfg.keep_fields:
        diagnostics["rho"] = rho
        diagnostics["c"] = c if c is not None else jnp.zeros_like(rho)
    diagnostics.update(extra_diag or {})
    return new_state, box, diagnostics


def _halo_stage_fn(cfg: PropagatorConfig, nbr, P: int, S_shard: int):
    """Choose the SPH stages' halo-exchange flavor: sparse cell-granular
    (cfg.halo_cells, the default sized by the Simulation) or contiguous
    per-peer windows (cfg.halo_window; also the 0 = full-slab fallback)."""
    from sphexa_tpu.parallel import exchange as ex

    axis = cfg.shard_axis
    if cfg.halo_cells:
        hmax = tuple(min(c, S_shard) for c in cfg.halo_cells)
        return lambda *a: ex.shard_halo_stage_sparse(*a, nbr, P, hmax, axis)
    Wmax = min(cfg.halo_window, S_shard) or S_shard
    return lambda *a: ex.shard_halo_stage(*a, nbr, P, Wmax, axis)


def exchange_fields_per_step(prop: str, av_clean: bool = False) -> int:
    """Total f32 fields served per step by the sharded force stage — the
    static multiplier that turns shipped rows into bytes/step
    (telemetry ``exchange.bytes_per_step``). Counts the serve() rounds:
    std/std-cooling = 4 (x,y,z,m) + 1 (m/rho) + 13 (h,v*,rho,p,c,cs*6);
    ve/turb-ve = 5 (x,y,z,h,m) + 1 (xm) + 6 (kx,prho,c,v*) + 1 (divv) +
    7 (alpha,cs*6), +3 with av_clean (gradv). Propagators without a
    sharded pair stage (nbody) ship through GSPMD: 0 here."""
    base = {"std": 18, "std-cooling": 18, "ve": 20, "turb-ve": 20}
    n = base.get(prop, 0)
    if av_clean and prop in ("ve", "turb-ve"):
        n += 3
    return n


def _shard_metrics(ranges, escaped, metrics, axis: str, token=None):
    """(P,) replicated per-shard telemetry arrays (SHARD_DIAG_KEYS) from
    one force stage's halo-exchange products: the four per-shard scalars
    are stacked and shipped in ONE all_gather — O(4P) floats over ICI,
    the Warren-Salmon per-processor work accounting riding the step's
    diagnostics. ``shard_work`` is the candidate rows this shard streams
    per pair op (the pair-stage work proxy); everything travels as f32
    (exact up to 2^24 — far beyond any CI-scale count, and an
    observability quantity beyond that). ``token``: optional predecessor
    value the gather chains on (exchange.chain_after — the XLA:CPU
    collective-rendezvous guard; see parallel/exchange.py)."""
    from sphexa_tpu.parallel.exchange import chain_after

    with phase_scope("shard-metrics"):
        work = jnp.sum(ranges.lens.astype(jnp.float32))
        packed = jnp.stack([
            metrics["halo_rows"].astype(jnp.float32),
            metrics["halo_occ"].astype(jnp.float32),
            work,
            jnp.asarray(escaped, jnp.float32),
        ])
        if token is not None:
            packed = chain_after(packed, token)
        g = jax.lax.all_gather(packed, axis)  # (P, 4) replicated
        return {
            "shard_rows": g[:, 0].astype(jnp.int32),
            "shard_occ": g[:, 1],
            "shard_work": g[:, 2],
            "shard_trips": g[:, 3].astype(jnp.int32),
        }


def _std_forces_sharded(state, box, cfg: PropagatorConfig, keys):
    """std pair-op stage under shard_map: per-device Mosaic kernels on the
    device's SFC slab, halos via the windowed all_to_all exchange.

    The arrays arrive GLOBALLY sorted and slab-sharded (the sort is the
    domain redistribution, parallel/mesh.py). The shared prologue runs on
    the local slab against the psum-built global cell table; candidate
    runs outside the slab are served by SFC-peer shards through per-peer
    row windows (parallel/exchange.py — the exchangeHalos analog,
    std_hydro.hpp:131-151). Freshly computed fields the next op reads on
    the j side are re-exchanged over the SAME windows, mirroring the
    reference's per-stage halo choreography. Scalar guards/timesteps are
    pmax/pmin-reduced so every shard returns identical values.
    """
    from jax.sharding import PartitionSpec
    from sphexa_tpu.parallel import exchange as ex
    from sphexa_tpu.sph import pallas_pairs as pp

    axis = cfg.shard_axis
    const = cfg.const
    nbr = cfg.nbr
    interpret = _pallas_interpret()
    P = cfg.mesh.shape[cfg.shard_axis]
    S_shard = state.x.shape[0] // P
    # a merged run must fit in one source slab so the boundary split pass
    # leaves at most one remainder per run (exchange._split_runs); a raw
    # CELL wider than a slab still crosses and trips the split-overflow
    # sentinel instead (pathological at any realistic shard size)
    if nbr.run_cap > S_shard:
        nbr = dataclasses.replace(nbr, run_cap=S_shard)

    stage = _halo_stage_fn(cfg, nbr, P, S_shard)

    def forces(box, keys, x, y, z, h, m, vx, vy, vz, temp):
        ranges, serve, jbuf, escaped, hmetrics = stage(x, y, z, h, keys, box)

        halo1 = serve((x, y, z, m))
        rho, nc, occ = pp.pallas_density(
            x, y, z, h, m, None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, m), halo1), interpret=interpret,
        )
        p, c = hydro_std.compute_eos_std(temp, rho, const)
        halo2 = serve((m / rho,))
        cs, _ = pp.pallas_iad(
            x, y, z, h, m / rho, None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, m / rho), (halo1[0], halo1[1], halo1[2],
                                            halo2[0])),
            interpret=interpret,
        )
        halo3 = serve((h, vx, vy, vz, rho, p, c, *cs))
        ax, ay, az, du, dt_c, _ = pp.pallas_momentum_energy_std(
            x, y, z, vx, vy, vz, h, m, rho, p, c, *cs,
            None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, h, vx, vy, vz, m, rho, p, c, *cs),
                       (halo1[0], halo1[1], halo1[2], halo3[0], halo3[1],
                        halo3[2], halo3[3], halo1[3], halo3[4], halo3[5],
                        halo3[6], *halo3[7:])),
            interpret=interpret,
        )
        # tail collectives (pmin, pmax, metrics gather) are mutually
        # independent — chain them into one order (rendezvous guard)
        dt_c = jax.lax.pmin(dt_c, axis)
        occ = ex.fold_escape_sentinel(
            ex.chain_after(occ, dt_c), escaped, cfg.nbr.cap, axis)
        smetrics = _shard_metrics(ranges, escaped, hmetrics, axis,
                                  token=occ)
        return rho, c, nc, occ, ax, ay, az, du, dt_c, smetrics

    Pp, Pr = PartitionSpec(axis), PartitionSpec()
    # check_vma=False: pallas_call's out_shape carries no varying-axis
    # metadata, which the checker (correctly) refuses to infer; the pmax/
    # pmin reductions above guarantee the replicated outputs really are
    out = shard_map(
        forces,
        mesh=cfg.mesh,
        in_specs=(Pr, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp),
        out_specs=(Pp, Pp, Pp, Pr, Pp, Pp, Pp, Pp, Pr,
                   {k: Pr for k in SHARD_DIAG_KEYS}),
        check_vma=False,
    )(box, keys, state.x, state.y, state.z, state.h, state.m,
      state.vx, state.vy, state.vz, state.temp)
    return out


def _ve_forces_sharded(state, box, cfg: PropagatorConfig, keys):
    """VE pair-op stage under shard_map — the flagship propagator on the
    multi-chip fast path (HydroVeProp::computeForces, ve_hydro.hpp:131-208).

    Same structure as _std_forces_sharded: shared prologue on the local
    slab against the psum-built global cell table, candidate halos via
    the windowed all_to_all exchange, one serve round per reference halo
    epoch (xm; kx/prho/c/v; divv; alpha/gradv — ve_hydro.hpp:154-188).
    """
    from jax.sharding import PartitionSpec
    from sphexa_tpu.parallel import exchange as ex
    from sphexa_tpu.sph import pallas_pairs as pp

    axis = cfg.shard_axis
    const = cfg.const
    nbr = cfg.nbr
    interpret = _pallas_interpret()
    P = cfg.mesh.shape[cfg.shard_axis]
    S_shard = state.x.shape[0] // P
    if nbr.run_cap > S_shard:
        nbr = dataclasses.replace(nbr, run_cap=S_shard)

    stage = _halo_stage_fn(cfg, nbr, P, S_shard)

    def forces(box, min_dt, keys, x, y, z, h, m, vx, vy, vz, temp, alpha0):
        ranges, serve, jbuf, escaped, hmetrics = stage(x, y, z, h, keys, box)

        hx, hy, hz, hh, hm = serve((x, y, z, h, m))
        xm, nc, occ = pp.pallas_xmass(
            x, y, z, h, m, None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, m), (hx, hy, hz, hm)), interpret=interpret,
        )
        (hxm,) = serve((xm,))
        (kx, gradh), _ = pp.pallas_ve_def_gradh(
            x, y, z, h, m, xm, None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, m, xm), (hx, hy, hz, hm, hxm)),
            interpret=interpret,
        )
        prho, c, rho, p = hydro_ve.compute_eos_ve(temp, m, kx, xm, gradh, const)
        hkx, hprho, hc, hvx, hvy, hvz = serve((kx, prho, c, vx, vy, vz))
        cs, _ = pp.pallas_iad(
            x, y, z, h, xm / kx, None, box, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, xm / kx), (hx, hy, hz, hxm / hkx)),
            interpret=interpret,
        )
        c11, c12, c13, c22, c23, c33 = cs
        dvout, _ = pp.pallas_iad_divv_curlv(
            x, y, z, vx, vy, vz, h, kx, xm, *cs,
            None, box, const, nbr, ranges=ranges,
            with_gradv=cfg.av_clean,
            jdata=jbuf((x, y, z, xm, vx, vy, vz),
                       (hx, hy, hz, hxm, hvx, hvy, hvz)),
            interpret=interpret,
        )
        divv, curlv, gradv = _split_dvout(dvout, cfg.av_clean)
        dt_rho = rho_timestep(divv, const)
        (hdivv,) = serve((divv,))
        alpha = pp.pallas_av_switches(
            x, y, z, vx, vy, vz, h, c, kx, xm, divv, alpha0, *cs,
            None, box, min_dt, const, nbr, ranges=ranges,
            jdata=jbuf((x, y, z, c, vx, vy, vz, xm / kx, divv),
                       (hx, hy, hz, hc, hvx, hvy, hvz, hxm / hkx, hdivv)),
            interpret=interpret,
        )[0]
        halo5 = serve((alpha, *cs) + tuple(gradv or ()))
        halpha, *hcs_gv = halo5
        hcs, hgv = hcs_gv[:6], hcs_gv[6:]
        ax, ay, az, du, dt_c, _ = pp.pallas_momentum_energy_ve(
            x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha, *cs,
            None, box, const, nbr, nc=nc, gradv=gradv, ranges=ranges,
            jdata=jbuf(
                (x, y, z, h, vx, vy, vz, c, alpha, m, xm, kx, prho, *cs)
                + tuple(gradv or ()),
                (hx, hy, hz, hh, hvx, hvy, hvz, hc, halpha, hm, hxm, hkx,
                 hprho, *hcs) + tuple(hgv),
            ),
            interpret=interpret,
        )
        # tail collectives (2x pmin, pmax, metrics gather) are mutually
        # independent — chain them into one order (rendezvous guard)
        dt_c = jax.lax.pmin(dt_c, axis)
        dt_rho = jax.lax.pmin(ex.chain_after(dt_rho, dt_c), axis)
        occ = ex.fold_escape_sentinel(
            ex.chain_after(occ, dt_rho), escaped, cfg.nbr.cap, axis)
        smetrics = _shard_metrics(ranges, escaped, hmetrics, axis,
                                  token=occ)
        return rho, c, nc, occ, ax, ay, az, du, dt_c, dt_rho, alpha, smetrics

    Pp, Pr = PartitionSpec(axis), PartitionSpec()
    out = shard_map(
        forces,
        mesh=cfg.mesh,
        in_specs=(Pr, Pr, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp, Pp),
        out_specs=(Pp, Pp, Pp, Pr, Pp, Pp, Pp, Pp, Pr, Pr, Pp,
                   {k: Pr for k in SHARD_DIAG_KEYS}),
        check_vma=False,
    )(box, state.min_dt, keys, state.x, state.y, state.z, state.h, state.m,
      state.vx, state.vy, state.vz, state.temp, state.alpha)
    return out


def _force_stage_prologue(state, box, cfg: PropagatorConfig, lists, aux=None,
                          keys=None):
    """Shared head of the force stages: list mode (frozen order, validity
    diagnostics) vs per-step box regrow + global sort. Returns
    (state, box, keys, ldiag, aux); keys is None in list mode.

    ``keys`` non-None: the caller already regrew the box and sorted (the
    blockdt builders run the bin-folded drift-aware sort themselves) —
    pass everything through untouched."""
    if keys is not None:
        return state, box, keys, None, aux
    if lists is not None:
        from sphexa_tpu.sph.pair_lists import list_slack

        if cfg.gravity is not None or cfg.shard_axis is not None:
            raise NotImplementedError(
                "persistent lists compose with single-device gravity-off "
                "steps; gravity/sharded runs rebuild per step")
        with phase_scope("neighbors"):
            slack = list_slack(state.x, state.y, state.z, state.h, lists)
            ldiag = {"list_slack": slack,
                     "list_ok": (slack >= 0.0).astype(jnp.int32)}
        return state, box, None, ldiag, aux
    # grow open-boundary dims to fit drifted particles (box_mpi.hpp
    # role); box limits are traced values, so this never recompiles
    with phase_scope("sort"):
        box = make_global_box(state.x, state.y, state.z, box)
    state, keys, aux = _sort_by_keys(state, box, cfg.curve, aux=aux)
    return state, box, keys, None, aux


def _std_forces(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree], aux=None, lists=None, keys=None,
):
    """The std-SPH force stage shared by the plain and cooling propagators
    (HydroProp::computeForces, std_hydro.hpp:123-157): box regrow -> sort ->
    neighbors -> density -> EOS -> IAD -> momentum/energy [-> gravity].
    ``aux`` is an optional per-particle pytree sorted along with the state
    and returned last.

    ``lists``: persistent PairLists — the steady-step fast path: NO box
    regrow, NO sort (the order is frozen at the last rebuild), NO
    prologue; a ``list_ok`` diagnostic reports the Verlet-skin validity
    of THIS step's input positions (an invalid step is discarded and
    replayed by the driver, like a cap overflow)."""
    const = cfg.const
    state, box, keys, ldiag, aux = _force_stage_prologue(
        state, box, cfg, lists, aux, keys=keys
    )
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m

    sdiag = None
    if cfg.backend == "pallas" and cfg.shard_axis is not None:
        # multi-chip fast path: per-shard Mosaic kernels under shard_map
        (rho, c, nc, occ, ax, ay, az, du, dt_courant,
         sdiag) = _std_forces_sharded(state, box, cfg, keys)
    elif cfg.backend == "pallas":
        # fused search+op TPU kernels: one shared cell-range prologue,
        # neighbor lists never materialize (sph/pallas_pairs.py)
        from sphexa_tpu.sph import pallas_pairs as pp

        interp = _pallas_interpret()
        if lists is not None:
            ranges = None
            occ = lists.ranges.occupancy
        else:
            ranges = pp.group_cell_ranges(x, y, z, h, keys, box, cfg.nbr)
            occ = ranges.occupancy
        rho, nc, _ = pp.pallas_density(
            x, y, z, h, m, keys, box, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        p, c = hydro_std.compute_eos_std(state.temp, rho, const)
        (c11, c12, c13, c22, c23, c33), _ = pp.pallas_iad(
            x, y, z, h, m / rho, keys, box, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        ax, ay, az, du, dt_courant, _ = pp.pallas_momentum_energy_std(
            x, y, z, state.vx, state.vy, state.vz, h, m, rho, p, c,
            c11, c12, c13, c22, c23, c33, keys, box, const, cfg.nbr,
            ranges=ranges, interpret=interp, lists=lists,
        )
    else:
        nidx, nmask, nc, occ = find_neighbors(x, y, z, h, keys, box, cfg.nbr)

        rho = hydro_std.compute_density(
            x, y, z, h, m, nidx, nmask, box, const, cfg.block
        )
        p, c = hydro_std.compute_eos_std(state.temp, rho, const)
        c11, c12, c13, c22, c23, c33 = hydro_std.compute_iad(
            x, y, z, h, m / rho, nidx, nmask, box, const, cfg.block
        )
        ax, ay, az, du, dt_courant = hydro_std.compute_momentum_energy_std(
            x, y, z, state.vx, state.vy, state.vz, h, m, rho, p, c,
            c11, c12, c13, c22, c23, c33, nidx, nmask, box, const, cfg.block,
        )

    extra_dts, gdiag = (), None
    if cfg.gravity is not None:
        ax, ay, az, egrav, dt_acc, gdiag = _add_gravity(
            state, box, keys, cfg, gtree, ax, ay, az
        )
        extra_dts, gdiag = (dt_acc,), {**gdiag, "egrav": egrav}
    if ldiag is not None:
        gdiag = {**(gdiag or {}), **ldiag}
    if sdiag is not None:
        gdiag = {**(gdiag or {}), **sdiag}

    return (state, box, ax, ay, az, du, dt_courant, extra_dts, nc, occ,
            rho, c, gdiag, aux)


def _step_hydro_std(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree] = None, lists=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array]]:
    """One standard-SPH time step (std_hydro.hpp:123-175 sequence).

    Force stage -> timestep -> positions -> smoothing-length update.
    Returns (new_state, new_box, diagnostics).
    """
    (state, box, ax, ay, az, du, dt_courant, extra_dts, nc, occ, rho, c,
     gdiag, _) = _std_forces(state, box, cfg, gtree, lists=lists)
    with phase_scope("timestep"):
        dt = compute_timestep(state.min_dt, dt_courant, *extra_dts,
                              const=cfg.const)
        limiter = _dt_limiter(state.min_dt, cfg.const, courant=dt_courant,
                              accel=extra_dts[0] if extra_dts else None)
    return _integrate_and_finish(
        state, box, cfg, ax, ay, az, du, dt, nc, occ, rho, extra_diag=gdiag,
        c=c, dt_limiter=limiter,
    )


def _step_hydro_std_cooling(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree], chem, cool_cfg, lists=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array], object]:
    """One std-SPH step with radiative cooling
    (HydroGrackleProp::step, std_hydro_grackle.hpp:193-233): force stage ->
    timestep with the cooling-time limiter -> integrate the cooling source
    into du -> positions -> smoothing-length update.

    The per-particle chemistry rides the step's SFC sort and the permuted
    ChemistryData is returned so it stays aligned with the persisted state.
    """
    from sphexa_tpu.physics.cooling import cool_step, cool_timestep

    const = cfg.const
    (state, box, ax, ay, az, du, dt_courant, extra_dts, nc, occ, rho, c,
     gdiag, chem) = _std_forces(state, box, cfg, gtree, aux=chem,
                                lists=lists)

    with phase_scope("cooling"):
        u = const.cv * state.temp
        dt_cool = cool_timestep(rho, u, chem, cool_cfg)
    with phase_scope("timestep"):
        dt = compute_timestep(
            state.min_dt, dt_courant, dt_cool, *extra_dts, const=const
        )
    # evolved-network mode advances the species alongside u
    # (solve_chemistry, cooler.cpp:313); CIE mode passes chem through
    with phase_scope("cooling"):
        du_cool, chem = cool_step(dt, rho, u, chem, cool_cfg)
        du = du + du_cool

    gdiag = {**(gdiag or {}), "dt_cool": dt_cool,
             "du_cool_min": jnp.min(du_cool)}
    with phase_scope("timestep"):
        limiter = _dt_limiter(state.min_dt, const, courant=dt_courant,
                              cool=dt_cool,
                              accel=extra_dts[0] if extra_dts else None)
    new_state, box, diag = _integrate_and_finish(
        state, box, cfg, ax, ay, az, du, dt, nc, occ, rho, extra_diag=gdiag,
        c=c, dt_limiter=limiter,
    )
    return new_state, box, diag, chem


def _pallas_interpret() -> bool:
    """Run Mosaic kernels in interpret mode off-TPU (delegates to the
    engine's single policy)."""
    from sphexa_tpu.sph.pallas_pairs import pallas_interpret

    return pallas_interpret()


def _split_dvout(dvout, av_clean: bool):
    """Unpack the divv/curlv op's outputs (shared by both VE backends)."""
    if av_clean:
        divv, curlv, *gradv = dvout
        return divv, curlv, tuple(gradv)
    divv, curlv = dvout
    return divv, curlv, None


def _ve_forces(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree], lists=None, keys=None,
    raw_dts: bool = False,
):
    """The VE force stage shared by the plain and turbulence-stirred
    propagators (HydroVeProp::computeForces, ve_hydro.hpp:131-208):
    box regrow -> sort -> neighbors -> xmass -> ve_def_gradh -> EOS ->
    IAD -> divv/curlv -> AV switches -> momentum/energy [-> gravity].
    Returns the sorted state plus everything the step tail needs.
    ``lists``: persistent-list steady-step fast path (see _std_forces).
    """
    const = cfg.const
    state, box, keys, ldiag, _ = _force_stage_prologue(
        state, box, cfg, lists, keys=keys
    )
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m
    vx, vy, vz = state.vx, state.vy, state.vz

    sdiag = None
    if cfg.backend == "pallas" and cfg.shard_axis is not None:
        # multi-chip fast path: per-shard Mosaic kernels + windowed halos
        (rho, c, nc, occ, ax, ay, az, du, dt_courant, dt_rho,
         alpha, sdiag) = _ve_forces_sharded(state, box, cfg, keys)
    elif cfg.backend == "pallas":
        # fused search+op TPU engine for the full VE sequence — the
        # reference's flagship propagator (ve_hydro.hpp:131-208) on the
        # fast path, sharing one cell-range prologue across all six ops
        from sphexa_tpu.sph import pallas_pairs as pp

        interp = _pallas_interpret()
        if lists is not None:
            ranges = None
            occ = lists.ranges.occupancy
        else:
            ranges = pp.group_cell_ranges(x, y, z, h, keys, box, cfg.nbr)
            occ = ranges.occupancy
        xm, nc, _ = pp.pallas_xmass(
            x, y, z, h, m, keys, box, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        (kx, gradh), _ = pp.pallas_ve_def_gradh(
            x, y, z, h, m, xm, keys, box, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        prho, c, rho, p = hydro_ve.compute_eos_ve(
            state.temp, m, kx, xm, gradh, const
        )
        (c11, c12, c13, c22, c23, c33), _ = pp.pallas_iad(
            x, y, z, h, xm / kx, keys, box, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        dvout, _ = pp.pallas_iad_divv_curlv(
            x, y, z, vx, vy, vz, h, kx, xm,
            c11, c12, c13, c22, c23, c33,
            keys, box, const, cfg.nbr, ranges=ranges,
            with_gradv=cfg.av_clean, interpret=interp, lists=lists,
        )
        divv, curlv, gradv = _split_dvout(dvout, cfg.av_clean)
        dt_rho = rho_timestep(divv, const)

        alpha, _ = pp.pallas_av_switches(
            x, y, z, vx, vy, vz, h, c, kx, xm, divv, state.alpha,
            c11, c12, c13, c22, c23, c33,
            keys, box, state.min_dt, const, cfg.nbr, ranges=ranges,
            interpret=interp, lists=lists,
        )
        ax, ay, az, du, dt_courant, _ = pp.pallas_momentum_energy_ve(
            x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha,
            c11, c12, c13, c22, c23, c33,
            keys, box, const, cfg.nbr, nc=nc, gradv=gradv, ranges=ranges,
            interpret=interp, lists=lists,
        )
    else:
        nidx, nmask, nc, occ = find_neighbors(x, y, z, h, keys, box, cfg.nbr)

        xm = hydro_ve.compute_xmass(x, y, z, h, m, nidx, nmask, box, const, cfg.block)
        kx, gradh = hydro_ve.compute_ve_def_gradh(
            x, y, z, h, m, xm, nidx, nmask, box, const, cfg.block
        )
        prho, c, rho, p = hydro_ve.compute_eos_ve(state.temp, m, kx, xm, gradh, const)

        c11, c12, c13, c22, c23, c33 = hydro_std.compute_iad(
            x, y, z, h, xm / kx, nidx, nmask, box, const, cfg.block
        )
        dvout = hydro_ve.compute_iad_divv_curlv(
            x, y, z, vx, vy, vz, h, kx, xm,
            c11, c12, c13, c22, c23, c33,
            nidx, nmask, box, const, cfg.block, with_gradv=cfg.av_clean,
        )
        divv, curlv, gradv = _split_dvout(dvout, cfg.av_clean)
        dt_rho = rho_timestep(divv, const)

        alpha = hydro_ve.compute_av_switches(
            x, y, z, vx, vy, vz, h, c, kx, xm, divv, state.alpha,
            c11, c12, c13, c22, c23, c33,
            nidx, nmask, box, state.min_dt, const, cfg.block,
        )

        ax, ay, az, du, dt_courant = hydro_ve.compute_momentum_energy_ve(
            x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha,
            c11, c12, c13, c22, c23, c33,
            nidx, nmask, nc, box, const, cfg.block, gradv=gradv,
        )

    extra_dts, gdiag = (), None
    if cfg.gravity is not None:
        ax, ay, az, egrav, dt_acc, gdiag = _add_gravity(
            state, box, keys, cfg, gtree, ax, ay, az
        )
        extra_dts, gdiag = (dt_acc,), {**gdiag, "egrav": egrav}
    if ldiag is not None:
        gdiag = {**(gdiag or {}), **ldiag}
    if sdiag is not None:
        gdiag = {**(gdiag or {}), **sdiag}

    if raw_dts:
        # blockdt builders combine the candidates themselves (only at
        # the sync substep); hand them back uncombined in the dt slot
        return (state, box, ax, ay, az, du, (dt_courant, dt_rho, extra_dts),
                alpha, nc, occ, rho, c, gdiag)
    with phase_scope("timestep"):
        dt = compute_timestep(state.min_dt, dt_courant, dt_rho, *extra_dts,
                              const=const)
        # limiter attribution rides gdiag into the step diagnostics (the
        # ve builders hand gdiag to the shared tail as extra_diag)
        gdiag = {**(gdiag or {}), "dt_limiter": _dt_limiter(
            state.min_dt, const, courant=dt_courant, rho=dt_rho,
            accel=extra_dts[0] if extra_dts else None)}
    return state, box, ax, ay, az, du, dt, alpha, nc, occ, rho, c, gdiag


def _step_hydro_ve(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree] = None, lists=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array]]:
    """One generalized-volume-element SPH time step.

    Mirrors HydroVeProp::step (ve_hydro.hpp:210-223): the VE force stage,
    then timestep -> positions -> smoothing-length update. The reference's
    halo exchanges between stages vanish: XLA materializes whatever
    communication the shardings imply.
    """
    (state, box, ax, ay, az, du, dt, alpha, nc, occ, rho, c, gdiag) = _ve_forces(
        state, box, cfg, gtree, lists=lists
    )
    return _integrate_and_finish(
        state, box, cfg, ax, ay, az, du, dt, nc, occ, rho,
        extra={"alpha": alpha}, extra_diag=gdiag, c=c,
    )


def _step_turb_ve(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree], turb, turb_cfg, lists=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array], object]:
    """One stirred VE step (TurbVeProp::step, turb_ve.hpp:70-86): VE forces
    -> timestep -> OU-driven stirring accelerations -> positions ->
    smoothing-length update. Returns the advanced TurbulenceState too."""
    from sphexa_tpu.sph.hydro_turb import drive_turbulence

    (state, box, ax, ay, az, du, dt, alpha, nc, occ, rho, c, gdiag) = _ve_forces(
        state, box, cfg, gtree, lists=lists
    )
    with phase_scope("turbulence"):
        ax, ay, az, turb = drive_turbulence(
            state.x, state.y, state.z, ax, ay, az, dt, turb, turb_cfg
        )
    new_state, box, diag = _integrate_and_finish(
        state, box, cfg, ax, ay, az, du, dt, nc, occ, rho,
        extra={"alpha": alpha}, extra_diag=gdiag, c=c,
    )
    return new_state, box, diag, turb


def _step_nbody(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree] = None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array]]:
    """One gravity-only N-body step (main/src/propagator/nbody.hpp:51-156).

    sort -> multipole upsweep -> Barnes-Hut traversal -> acceleration
    timestep -> position update. No hydro fields are touched (du = 0).
    """
    const = cfg.const
    with phase_scope("sort"):
        box = make_global_box(state.x, state.y, state.z, box)
    state, keys, _ = _sort_by_keys(state, box, cfg.curve)

    zero = jnp.zeros_like(state.x)
    ax, ay, az, egrav, dt_acc, gdiag = _add_gravity(
        state, box, keys, cfg, gtree, zero, zero, zero
    )
    with phase_scope("timestep"):
        dt = compute_timestep(state.min_dt, dt_acc, const=const)
        limiter = _dt_limiter(state.min_dt, const, accel=dt_acc)

    nc = jnp.zeros_like(state.x, dtype=jnp.int32)
    return _integrate_and_finish(
        state, box, cfg, ax, ay, az, zero, dt, nc, jnp.int32(0), zero,
        extra_diag={**gdiag, "egrav": egrav}, update_smoothing=False,
        dt_limiter=limiter,
    )


# ---------------------------------------------------------------------------
# hierarchical block time steps (sph/blockdt.py)
# ---------------------------------------------------------------------------


def _integrate_and_finish_blockdt(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    ax, ay, az, du, dt_min, dt_prev, due, bins, dt_eff, nc, occ, rho,
    extra=None, extra_diag=None, c=None, dt_limiter=None,
):
    """Block-timestep twin of _integrate_and_finish: the Press update is
    evaluated with PER-PARTICLE dt arrays (compute_positions is fully
    elementwise in dt/dt_m1) and applied to DUE rows only; inactive rows
    get the KDK-consistent drift ``x += v * dt_min`` (PBC-folded) with
    every other field frozen.  Due rows first rebase away the drift
    accumulated since their last kick, so the update runs from the
    kick-time position with the full ``dt_eff = dt_min * 2**k``.

    The conservation ledger still runs over ALL rows (deviation from the
    ISSUE's active-rows wording, by design: the energy totals need the
    frozen rows' contributions every substep — the active-rows saving is
    the UPDATE reduction, which is exactly what bdt_active records).
    """
    const = cfg.const
    with phase_scope("integrate"):
        # bins>0 gate: at k=0 the rebase term is exactly zero, but
        # a - 0.0 is not a bitwise identity for a = -0.0 and dt_bins=1
        # pins bitwise equality with the global path
        rebase = due & (bins > 0)
        dr = dt_eff - dt_min
        bx = jnp.where(rebase, state.x - state.vx * dr, state.x)
        by = jnp.where(rebase, state.y - state.vy * dr, state.y)
        bz = jnp.where(rebase, state.z - state.vz * dr, state.z)
        fields = (bx, by, bz, state.x_m1, state.y_m1, state.z_m1,
                  state.vx, state.vy, state.vz, state.h,
                  state.temp, state.temp_lo, du, state.du_m1)
        (nx, ny, nz, dxm, dym, dzm, vx, vy, vz, h, temp, temp_lo, ndu,
         du_m1) = compute_positions(
            fields, ax, ay, az, dt_eff, dt_prev, box, const
        )
        drift = put_in_box(box, jnp.stack(
            [state.x + state.vx * dt_min,
             state.y + state.vy * dt_min,
             state.z + state.vz * dt_min], axis=-1))
        sel = lambda a, b: jnp.where(due, a, b)
        new_h = sel(update_h(const.ng0, nc + 1, h), state.h)
        new_state = dataclasses.replace(
            state,
            x=sel(nx, drift[:, 0]), y=sel(ny, drift[:, 1]),
            z=sel(nz, drift[:, 2]),
            x_m1=sel(dxm, state.x_m1), y_m1=sel(dym, state.y_m1),
            z_m1=sel(dzm, state.z_m1),
            vx=sel(vx, state.vx), vy=sel(vy, state.vy),
            vz=sel(vz, state.vz),
            h=new_h, temp=sel(temp, state.temp),
            temp_lo=sel(temp_lo, state.temp_lo),
            du=sel(ndu, state.du), du_m1=sel(du_m1, state.du_m1),
            ttot=state.ttot + dt_min, min_dt=dt_min,
            min_dt_m1=state.min_dt,
            **(extra or {}),
        )
        diagnostics = {
            "dt": dt_min,
            "nc_mean": jnp.mean(nc.astype(jnp.float32)) + 1.0,
            "nc_max": jnp.max(nc) + 1,
            "occupancy": occ,
            "rho_max": jnp.max(rho),
            "h_max": jnp.max(new_h),
        }
    if cfg.obs is not None:
        ed = extra_diag or {}
        diagnostics.update(ledger_diagnostics(
            new_state, rho, nc, const, cfg.nbr.ngmax, spec=cfg.obs,
            egrav=ed.get("egrav", 0.0), box=box, c=c,
            smoothing=True,
            token=ed.get("shard_trips"),
        ))
    # snapshot deposit, conditional like cfg.obs (see
    # _integrate_and_finish); runs over ALL rows like the ledger — the
    # frame must show the frozen rows too
    if cfg.snap is not None:
        ed = extra_diag or {}
        diagnostics.update(snapshot_diagnostics(
            new_state, rho, box, cfg.snap,
            token=diagnostics.get("rho_min", ed.get("shard_trips")),
        ))
    if dt_limiter is not None:
        diagnostics["dt_limiter"] = dt_limiter
    if cfg.keep_accels:
        diagnostics.update({"ax": ax, "ay": ay, "az": az})
    if cfg.keep_fields:
        diagnostics["rho"] = rho
        diagnostics["c"] = c if c is not None else jnp.zeros_like(rho)
    diagnostics.update(extra_diag or {})
    return new_state, box, diagnostics


def _blockdt_prologue(state, box, cfg: PropagatorConfig, bst):
    """Box regrow + the blockdt sort.  dt_bins = 1 routes through the
    PLAIN _sort_by_keys call (no fold, no resort cond) so the whole step
    stays bitwise-identical to the global-dt path; deeper stacks get the
    bin-folded drift-aware sort.  The BlockDtState rides the aux channel
    (its (n,) leaves permute, its scalars pass through)."""
    with phase_scope("sort"):
        box = make_global_box(state.x, state.y, state.z, box)
    if cfg.dt_bins == 1:
        state, keys, bst = _sort_by_keys(state, box, cfg.curve, aux=bst)
        return state, box, keys, bst, jnp.int32(1), jnp.int32(0)
    state, keys, bst, resorted, inv = _sort_by_keys(
        state, box, cfg.curve, aux=bst, bins=bst.bins,
        resort_drift=cfg.bin_resort_drift)
    return state, box, keys, bst, resorted, inv


def _blockdt_tail(state, box, cfg: PropagatorConfig, ax, ay, az, du,
                  dt_sync, bst, resorted, inv, nc, occ, rho, c=None,
                  dt_limiter=None, gdiag=None, alpha=None):
    """Shared bin bookkeeping + due-rows integration of the blockdt step
    builders: sync-substep dt_min/bin refresh, due mask, bitmask-rank
    active compaction, BlockDtState advance, then the blockdt integrate
    tail.  All of it is elementwise or global-reduction math OUTSIDE
    shard_map — on mesh runs GSPMD partitions it and the shard_map
    collective order the JXA201 rule pins is untouched."""
    const = cfg.const
    B = cfg.dt_bins
    C = bdt.cycle_length(B)
    with phase_scope("dt-bins"):
        is_sync = bst.substep == 0
        dt_min = jnp.where(is_sync, dt_sync, bst.dt_min)
        grav = cfg.gravity is not None
        cand = bdt.particle_dt_candidates(
            state.h, c, const,
            ax=ax if grav else None, ay=ay if grav else None,
            az=az if grav else None)
        rebin = is_sync & (bst.cycle % cfg.bin_sync_every == 0)
        bins = jnp.where(rebin, bdt.assign_bins(cand, dt_min, B), bst.bins)
        due = bdt.due_mask(bins, bst.substep)
        # exact power-of-two scale: integer shift -> f32 (exp2 may not
        # hit integer points exactly on every backend; 1 << k does)
        dt_eff = dt_min * jnp.left_shift(1, bins).astype(jnp.float32)
        use_kernel = cfg.backend == "pallas" and cfg.shard_axis is None
        idx_act, n_active = bdt.compact_active(
            due, use_kernel=use_kernel, interpret=_pallas_interpret())
        pop = bdt.bin_populations(bins, B)
        lane = jnp.arange(state.n, dtype=jnp.int32)
        work = jnp.sum(jnp.where(lane < n_active,
                                 nc[idx_act], 0).astype(jnp.float32))
        bdiag = {"bdt_active": n_active, "bdt_pop": pop,
                 "bdt_substep": bst.substep, "bdt_resort": resorted,
                 "bdt_drift": inv, "bdt_work": work}
        wrap = bst.substep + 1 >= C
        new_bst = dataclasses.replace(
            bst, bins=bins,
            dt_prev=jnp.where(due, dt_eff, bst.dt_prev),
            substep=jnp.where(wrap, 0, bst.substep + 1),
            cycle=bst.cycle + wrap.astype(jnp.int32),
            dt_min=dt_min)
    extra = None if alpha is None else {
        "alpha": jnp.where(due, alpha, state.alpha)}
    # B == 1: feed compute_positions the SCALARS the global path feeds it
    # — a broadcast (n,) operand changes XLA's FMA formation and would
    # break the bitwise dt_bins=1 pin even at identical values
    if B == 1:
        cp_dt, cp_dtm1 = dt_min, state.min_dt
    else:
        cp_dt, cp_dtm1 = dt_eff, bst.dt_prev
    new_state, box, diag = _integrate_and_finish_blockdt(
        state, box, cfg, ax, ay, az, du, dt_min, cp_dtm1, due, bins,
        cp_dt, nc, occ, rho, extra=extra,
        extra_diag={**(gdiag or {}), **bdiag}, c=c, dt_limiter=dt_limiter)
    return new_state, box, diag, new_bst


def _step_hydro_std_blockdt(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree] = None, bst=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array], object]:
    """One std-SPH step under hierarchical block time steps (Bonsai's
    block scheme, Bédorf et al. 2014 §3.4; sph/blockdt.py).

    Bin-folded drift-aware sort -> full-shape force sweep (inactive
    particles are sources at drifted positions; the fixed-shape engines
    are untouched) -> sync-substep dt_min refresh + re-binning -> active
    compaction -> due-rows-only integration.  The update REDUCTION is
    what bdt_active/bdt_pop record — the chip-free complexity proxy
    (docs/NEXT.md round 12).  Returns (state, box, diagnostics, bst).
    """
    const = cfg.const
    state, box, keys, bst, resorted, inv = _blockdt_prologue(
        state, box, cfg, bst)
    (state, box, ax, ay, az, du, dt_courant, extra_dts, nc, occ, rho, c,
     gdiag, _) = _std_forces(state, box, cfg, gtree, keys=keys)
    with phase_scope("timestep"):
        dt_sync = compute_timestep(state.min_dt, dt_courant, *extra_dts,
                                   const=const)
        limiter = _dt_limiter(state.min_dt, const, courant=dt_courant,
                              accel=extra_dts[0] if extra_dts else None)
    return _blockdt_tail(state, box, cfg, ax, ay, az, du, dt_sync, bst,
                         resorted, inv, nc, occ, rho, c=c,
                         dt_limiter=limiter, gdiag=gdiag)


def _step_hydro_ve_blockdt(
    state: ParticleState, box: Box, cfg: PropagatorConfig,
    gtree: Optional[GravityTree] = None, bst=None,
) -> Tuple[ParticleState, Box, Dict[str, jax.Array], object]:
    """One VE-SPH step under hierarchical block time steps — the same
    scheme as _step_hydro_std_blockdt over the VE force stage (raw dt
    candidates; the sync-substep combination below is the same
    compute_timestep expression the global ve path uses).  AV alpha
    freezes on inactive rows like every other evolved field."""
    const = cfg.const
    state, box, keys, bst, resorted, inv = _blockdt_prologue(
        state, box, cfg, bst)
    (state, box, ax, ay, az, du, (dt_courant, dt_rho, extra_dts), alpha,
     nc, occ, rho, c, gdiag) = _ve_forces(
        state, box, cfg, gtree, keys=keys, raw_dts=True)
    with phase_scope("timestep"):
        dt_sync = compute_timestep(state.min_dt, dt_courant, dt_rho,
                                   *extra_dts, const=const)
        limiter = _dt_limiter(state.min_dt, const, courant=dt_courant,
                              rho=dt_rho,
                              accel=extra_dts[0] if extra_dts else None)
    return _blockdt_tail(state, box, cfg, ax, ay, az, du, dt_sync, bst,
                         resorted, inv, nc, occ, rho, c=c,
                         dt_limiter=limiter, gdiag=gdiag, alpha=alpha)


# ---------------------------------------------------------------------------
# jitted step variants
# ---------------------------------------------------------------------------
# Every step builder ships as a PAIR of jits over the same impl:
#
# - the plain variant keeps every input alive: the Simulation's
#   discard-and-replay contract (cap overflow, expired lists, deferred
#   rollback) re-launches from the SAME state object, so the checked path
#   must never consume its input;
# - the ``*_donated`` twin donates the particle-state pytree, letting XLA
#   alias the step's output into the input buffers — no double-buffering
#   of the MB/GB-scale state, which is what bounds the largest runnable N
#   per chip. It is only launched on paths that can never need the input
#   again (Simulation deferred happy-path windows, which pin a COPY for
#   rollback) and is the variant the jaxaudit donation rule (JXA103)
#   holds the registry to.


def _step_pair(impl, static):
    plain = jax.jit(impl, static_argnames=static)
    donated = jax.jit(impl, static_argnames=static,
                      donate_argnames=("state",))
    return plain, donated


step_hydro_std, step_hydro_std_donated = _step_pair(
    _step_hydro_std, ("cfg",))
step_hydro_std_cooling, step_hydro_std_cooling_donated = _step_pair(
    _step_hydro_std_cooling, ("cfg", "cool_cfg"))
step_hydro_ve, step_hydro_ve_donated = _step_pair(
    _step_hydro_ve, ("cfg",))
step_turb_ve, step_turb_ve_donated = _step_pair(
    _step_turb_ve, ("cfg", "turb_cfg"))
step_nbody, step_nbody_donated = _step_pair(_step_nbody, ("cfg",))
# blockdt pairs donate the ParticleState only: the BlockDtState carry is
# small and the rollback window keeps the SAME object across a replay
step_hydro_std_blockdt, step_hydro_std_blockdt_donated = _step_pair(
    _step_hydro_std_blockdt, ("cfg",))
step_hydro_ve_blockdt, step_hydro_ve_blockdt_donated = _step_pair(
    _step_hydro_ve_blockdt, ("cfg",))


# ---------------------------------------------------------------------------
# the unified SimState carry contract
# ---------------------------------------------------------------------------
# Each family's step keeps its historical positional signature (the
# lowering lock pins those byte-identical), but the DISPATCH onto them is
# one table + one adapter: which SimState aux slot a step function
# carries, and whether it takes a static aux config. The driver
# (simulation.py), the sharded stepper (parallel/mesh.py) and the audit
# registry all route through this mapping, so the carry structure cannot
# drift per call site.

#: step function -> SimState aux slot it consumes/produces (absent =
#: plain 3-tuple family with no aux carry)
STEP_AUX_SLOT = {
    step_turb_ve: "turb",
    step_turb_ve_donated: "turb",
    step_hydro_std_cooling: "chem",
    step_hydro_std_cooling_donated: "chem",
    step_hydro_std_blockdt: "bdt",
    step_hydro_std_blockdt_donated: "bdt",
    step_hydro_ve_blockdt: "bdt",
    step_hydro_ve_blockdt_donated: "bdt",
}

#: aux-carrying steps that ALSO take a static aux config positional
#: (turbulence / cooling); the blockdt twins carry state only
STEP_AUX_CFG = {
    step_turb_ve,
    step_turb_ve_donated,
    step_hydro_std_cooling,
    step_hydro_std_cooling_donated,
}


def step_sim_state(step_fn, sim, cfg, gtree=None, aux_cfg=None, **kw):
    """Advance one step on a ``state.SimState`` carry.

    Maps the unified carry onto ``step_fn``'s positional contract and
    folds the outputs back: ``(new_sim, diagnostics)``. Only the slot
    ``step_fn`` owns is replaced — inactive slots pass through untouched,
    so the carry treedef is closed under stepping (the JXA503
    invariant). Pure and trace-safe: usable inside jit/vmap as well as
    from the host driver.
    """
    slot = STEP_AUX_SLOT.get(step_fn)
    if slot is None:
        s, b, diag = step_fn(sim.particles, sim.box, cfg, gtree, **kw)
        return sim.with_slot(None, None, particles=s, box=b), diag
    aux = getattr(sim, slot)
    if step_fn in STEP_AUX_CFG:
        s, b, diag, new_aux = step_fn(
            sim.particles, sim.box, cfg, gtree, aux, aux_cfg, **kw
        )
    else:
        s, b, diag, new_aux = step_fn(
            sim.particles, sim.box, cfg, gtree, aux, **kw
        )
    return sim.with_slot(slot, new_aux, particles=s, box=b), diag
