"""``sphexa-tune``: the sweep driver CLI.

Replays a workload (named init case, or reconstructed from a telemetry
run's manifest), sweeps a knob subset under a candidate budget, and
leaves the same artifacts a production run does: the sweep dir is a
telemetry run dir (manifest.json + events.jsonl with one schema-v5
``sweep`` event per candidate, flight-recorder armed so a hard death
leaves blackbox.json), and ``--write-table`` commits the winner into a
TUNING_TABLE.json entry with provenance. Exit codes follow the other
CLIs: 0 = sweep completed with a usable measurement, 1 = no candidate
measured ok (the gate failure), 2 = unusable input.
"""

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-tune",
        description="workload-replay autotuner scored by telemetry "
                    "(docs/TUNING.md)",
    )
    src = p.add_mutually_exclusive_group()
    src.add_argument("--case", default=None,
                     help="named init case to replay (sedov, evrard, ...)")
    src.add_argument("--from-run", default=None, dest="from_run",
                     help="telemetry run dir: replay the workload its "
                          "manifest describes")
    p.add_argument("--side", type=int, default=20,
                   help="particles per cube side with --case (N = side^3)")
    p.add_argument("--prop", default="std", help="propagator with --case")
    p.add_argument("--backend", default="auto",
                   choices=("auto", "pallas", "xla"))
    p.add_argument("--theta", type=float, default=0.5)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--knobs", default="target_block,blocks_per_chunk,"
                                      "cell_target,gap",
                   help="comma-separated knob subset to sweep "
                        "(registry names, sphexa_tpu/tuning/knobs.py)")
    p.add_argument("--budget", type=int, default=16,
                   help="max measured candidates, baseline included")
    p.add_argument("--steps", type=int, default=6,
                   help="measured steps per candidate (one deferred "
                        "window unless check_every is being swept)")
    p.add_argument("--warmup", type=int, default=1,
                   help="unmeasured warmup windows per candidate")
    p.add_argument("--objective", default="per_step_s",
                   help="per_step_s; phase:<name> to score one phase of "
                        "the device-time table (runs under a trace); or "
                        "static-cost:<name> to score the phase's static "
                        "roofline prediction CHIP-FREE (jaxcost; see "
                        "docs/STATIC_ANALYSIS.md for the calibration "
                        "caveat)")
    p.add_argument("--cost-device", default="v5e", dest="cost_device",
                   help="device model a static-cost objective predicts "
                        "against (devtools/audit/devices.py) [v5e]")
    p.add_argument("--out", default="tune-out",
                   help="sweep run dir (events.jsonl / manifest / "
                        "blackbox land here)")
    p.add_argument("--write-table", default=None, dest="write_table",
                   help="TUNING_TABLE.json to upsert the result into")
    p.add_argument("--commit", default="improved",
                   choices=("improved", "best", "none"),
                   help="what --write-table commits: 'improved' only a "
                        "knob set that beat the baseline; 'best' the "
                        "best ok candidate even at zero/negative win "
                        "(pin a measured config; CI smoke); 'none' dry "
                        "run")
    p.add_argument("--workload", default=None,
                   help="table workload class (default: the case name)")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--format", default="text", choices=("text", "json"))
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # resolving the spec before touching jax keeps bad input cheap
    from sphexa_tpu.tuning import (
        ReplaySpec, domains_for, make_entry, load_table, measure_candidate,
        new_table, run_sweep, save_table, spec_from_manifest,
        static_cost_candidate, upsert_entry,
    )

    try:
        if args.from_run:
            spec = spec_from_manifest(args.from_run)
        else:
            from sphexa_tpu.init import CASES, split_case_spec

            case = args.case or "sedov"
            base, _ = split_case_spec(case)
            if base not in CASES:
                raise ValueError(f"unknown case {case!r} "
                                 f"(known: {sorted(CASES)})")
            spec = ReplaySpec(case=case, side=args.side, prop=args.prop,
                              backend=args.backend, theta=args.theta,
                              devices=args.devices)
        domains = domains_for(
            [k for k in args.knobs.split(",") if k])
    except (FileNotFoundError, ValueError, KeyError, OSError,
            json.JSONDecodeError) as e:
        print(f"sphexa-tune: {e}", file=sys.stderr)
        return 2

    from sphexa_tpu.telemetry import (
        FlightRecorder, JsonlSink, Telemetry, write_manifest,
    )

    os.makedirs(args.out, exist_ok=True)
    telemetry = Telemetry(sinks=[JsonlSink(
        os.path.join(args.out, "events.jsonl"))])
    recorder = FlightRecorder(args.out, telemetry=telemetry)
    telemetry.sinks.append(recorder.sink)
    recorder.install()
    recorder.manifest = write_manifest(
        args.out,
        config={"case": spec.case, "side": spec.side, "prop": spec.prop,
                "backend": spec.backend, "theta": spec.theta,
                "devices": spec.devices, "knobs": args.knobs,
                "budget": args.budget, "steps": args.steps,
                "warmup": args.warmup, "objective": args.objective},
        particles=spec.n,
        extra={"case": spec.case, "prop": spec.prop, "sweep": True},
    )

    say = (lambda s: None) if args.quiet else \
        (lambda s: print(f"# tune {s}"))
    trace_root = os.path.join(args.out, "trace")
    counter = {"i": 0}

    def measure(knobs):
        if args.objective.startswith("static-cost:"):
            # chip-free: rank by the jaxcost roofline prediction of one
            # phase — no steps run, no trace captured
            return static_cost_candidate(
                spec, knobs, args.objective.split(":", 1)[1],
                device=args.cost_device)
        td = None
        if args.objective.startswith("phase:"):
            td = os.path.join(trace_root, f"cand{counter['i']}")
        counter["i"] += 1
        return measure_candidate(spec, knobs, steps=args.steps,
                                 warmup=args.warmup,
                                 objective=args.objective, trace_dir=td)

    result = run_sweep(measure, domains, args.budget,
                       telemetry=telemetry, objective=args.objective,
                       log=say)

    base = result["baseline"]
    best = result["best"]
    usable = base is not None and base.get("status") == "ok"
    win = None
    if usable and result["improved"]:
        win = (base["value"] - best["value"]) / base["value"]

    import jax

    backend = spec.backend if spec.backend != "auto" else (
        "pallas" if jax.default_backend() == "tpu" else "xla")
    workload = args.workload or spec.case
    # the decision event: what the sweep concluded, in the same stream
    # as the per-candidate evidence
    telemetry.event(
        "tuning", source="sweep", workload=workload, backend=backend,
        n=spec.n, p=spec.devices or 1, objective=args.objective,
        knobs=best["knobs"], improved=result["improved"],
        candidates=result["candidates"],
        **({"win": round(win, 4)} if win is not None else {}),
    )

    wrote = None
    commit_knobs = best["knobs"]
    if args.write_table and args.commit == "best" and not commit_knobs:
        # baseline won but the caller wants a pinned measured config:
        # commit the best-scoring non-empty ok candidate
        ok = [r for r in result["history"]
              if r.get("status") == "ok" and r["knobs"]
              and isinstance(r.get("value"), (int, float))]
        if ok:
            commit_knobs = min(ok, key=lambda r: r["value"])["knobs"]
    if (args.write_table and args.commit != "none" and commit_knobs
            and (result["improved"] or args.commit == "best")):
        try:
            table = load_table(args.write_table)
        except (FileNotFoundError, ValueError):
            table = new_table()
        cand = next(r for r in result["history"]
                    if r["knobs"] == commit_knobs)
        entry = make_entry(
            workload, spec.n, spec.devices or 1, backend, commit_knobs,
            provenance={
                "source_run": os.path.abspath(args.out),
                "created": time.strftime("%Y-%m-%d"),
                "objective": args.objective,
                "baseline": base.get("value") if usable else None,
                "best": cand.get("value"),
                "win": round(win, 4) if win is not None else None,
            },
        )
        upsert_entry(table, entry)
        save_table(args.write_table, table)
        wrote = args.write_table

    recorder.close()
    telemetry.close()

    if args.format == "json":
        print(json.dumps({"spec": vars(args), "baseline": base,
                          "best": best if result["improved"] else None,
                          "win": win, "candidates": result["candidates"],
                          "table": wrote}, default=str))
    else:
        if usable:
            say(f"baseline {args.objective}={base['value']:.6g}")
        if result["improved"]:
            say(f"best {best['knobs']} -> {best['value']:.6g} "
                f"(win {100 * win:.1f}%)")
        else:
            say("no candidate beat the baseline")
        if wrote:
            say(f"table entry written to {wrote}")
    ok_any = any(r.get("status") == "ok" for r in result["history"])
    return 0 if ok_any else 1


if __name__ == "__main__":
    sys.exit(main())
