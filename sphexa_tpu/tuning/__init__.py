"""Autotuning: the observe→decide loop over the telemetry stack.

PRs 4-7 built the measurement side (sync-free step timing, per-phase
device-time attribution, history/regression evidence); this package
spends it: a typed knob registry (knobs.py), a workload replay harness
scored by the existing telemetry clocks (replay.py), a budgeted search
driver (search.py), and a committed per-(workload, N bucket, P,
backend) tuning table (table.py, TUNING_TABLE.json) that Simulation
resolves at configure time via ``tuned="auto"``. See docs/TUNING.md.

Importing this package validates the knob registry against the LIVE
config dataclasses/signatures — a renamed field fails here, loudly, at
the first ``import sphexa_tpu.tuning``, instead of a committed table
silently de-tuning every future run. The import therefore drags in the
config modules (and jax) — the one documented exception to the
telemetry CLI's jax-free rule (its ``tuning`` subcommand imports this
package lazily, inside the branch that needs it).
"""

from sphexa_tpu.tuning.knobs import (
    BLOCKDT_KNOBS,
    COST_RECONFIGURE,
    COST_STATIC,
    GRAVITY_KNOBS,
    KNOBS,
    NEIGHBOR_KNOBS,
    SIMULATION_KNOBS,
    KnobSpec,
    knob_names,
    validate_registry,
)

validate_registry()

from sphexa_tpu.tuning.replay import (  # noqa: E402
    ReplaySpec,
    build_case,
    measure_candidate,
    spec_from_manifest,
    static_cost_candidate,
)
from sphexa_tpu.tuning.search import domains_for, run_sweep  # noqa: E402
from sphexa_tpu.tuning.table import (  # noqa: E402
    TABLE_SCHEMA,
    coverage,
    default_table_path,
    load_table,
    make_entry,
    n_bucket,
    new_table,
    resolve_entry,
    resolve_knobs,
    save_table,
    upsert_entry,
    validate_table,
)

__all__ = [
    "KnobSpec", "KNOBS", "knob_names", "validate_registry",
    "COST_STATIC", "COST_RECONFIGURE",
    "GRAVITY_KNOBS", "NEIGHBOR_KNOBS", "SIMULATION_KNOBS",
    "BLOCKDT_KNOBS",
    "ReplaySpec", "spec_from_manifest", "build_case", "measure_candidate",
    "static_cost_candidate",
    "domains_for", "run_sweep",
    "TABLE_SCHEMA", "default_table_path", "n_bucket", "new_table",
    "load_table", "save_table", "validate_table", "resolve_entry",
    "resolve_knobs", "upsert_entry", "make_entry", "coverage",
]
