"""The knob registry: every tunable the autotuner may touch, typed.

Each ``KnobSpec`` names the knob, the config surface that OWNS it (a
frozen dataclass field or a constructor/factory keyword), the candidate
domain the search driver sweeps, and what changing it costs at runtime
(``static`` = a fresh ``Simulation``; ``reconfigure`` = applied at the
existing reconfigure granularity — a recompile, not a rebuild). The
registry is the single vocabulary shared by the sweep driver, the
committed ``TUNING_TABLE.json`` and the ``Simulation(tuned=...)``
resolution path: a knob name outside it is a stale table, not a typo
to guess around (``sphexa-telemetry tuning`` exits 1 on it).

``validate_registry()`` checks every spec against the REAL owning
dataclass/signature; ``sphexa_tpu.tuning`` (the package ``__init__``)
calls it at import so a renamed config field fails loudly at the first
``import sphexa_tpu.tuning`` instead of silently de-tuning a run. This
module itself stays import-light (no jax, no config modules) so the
table tooling can read knob NAMES without dragging in a backend — the
owning modules are imported only inside ``validate_registry()``.
"""

import dataclasses
from typing import Dict, Tuple

#: where a knob's new value takes effect
COST_STATIC = "static"          # construction-time only (new Simulation)
COST_RECONFIGURE = "reconfigure"  # applied at reconfigure granularity


class _NoOff:
    """Marker: the knob has no off sentinel (``None`` IS a real sentinel
    for dt_bins, so absence needs its own type)."""

    def __repr__(self):  # pragma: no cover - cosmetic
        return "NO_OFF"


NO_OFF = _NoOff()


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One tunable: identity + owning surface + search domain + cost."""

    name: str
    #: owning config surface, one of the keys of _OWNERS below
    owner: str
    #: field/parameter name on the owner (usually == name)
    field: str
    #: candidate values the search driver sweeps, in preference order
    #: (first = the safe/most-common default)
    domain: Tuple
    #: COST_STATIC or COST_RECONFIGURE
    cost: str
    description: str = ""
    #: the value that turns the knob's FEATURE OFF (NO_OFF = the knob
    #: has no off state). Contract enforced by jaxaudit JXA402: setting
    #: the knob to this value through ``tuned=`` must leave the probe
    #: simulation's step lowering fingerprint-identical to never
    #: mentioning the knob at all — the meta-rule that generalizes the
    #: hand-written dt_bins=None / grav_window=0 byte-identity pins.
    off_sentinel: object = NO_OFF

    @property
    def has_off_sentinel(self) -> bool:
        return self.off_sentinel is not NO_OFF


#: every registered knob, keyed by name. Domains are the measured
#: candidate sets from the past sweeps (scripts/sweep_engine.py /
#: profile_grid.py, docs/NEXT.md rounds 4-6) — the staged search seeds
#: from them, it does not invent values.
KNOBS: Dict[str, KnobSpec] = {
    spec.name: spec
    for spec in (
        # -- gravity solver shape (GravityConfig) -------------------------
        KnobSpec("target_block", "GravityConfig", "target_block",
                 (64, 128, 256), COST_RECONFIGURE,
                 "bodies per traversal block (MAC shared per block)"),
        KnobSpec("blocks_per_chunk", "GravityConfig", "blocks_per_chunk",
                 (32, 16, 8), COST_RECONFIGURE,
                 "traversal blocks batched per classification chunk"),
        KnobSpec("super_factor", "GravityConfig", "super_factor",
                 (0, 4, 8, 16), COST_RECONFIGURE,
                 "superblock size in blocks for the two-level "
                 "classification (0 = flat; > 0 implies the bitmask "
                 "compaction on the pallas backend)",
                 off_sentinel=0),
        KnobSpec("m2p_cap_margin", "GravityConfig", "m2p_cap_margin",
                 (1.3, 1.15, 1.5), COST_RECONFIGURE,
                 "M2P interaction-list cap margin (eval cost is linear "
                 "in the cap; overflow is guarded and auto-regrown)"),
        # -- neighbor engine (NeighborConfig / make_propagator_config) ----
        KnobSpec("block", "NeighborConfig", "block",
                 (2048, 4096, 8192), COST_STATIC,
                 "particles per processing chunk (memory bound)"),
        KnobSpec("cell_target", "make_propagator_config", "cell_target",
                 (128, 64, 256), COST_RECONFIGURE,
                 "mean cell occupancy the grid level targets"),
        KnobSpec("run_cap", "NeighborConfig", "run_cap",
                 (1536, 1024, 2048), COST_RECONFIGURE,
                 "max slots per merged candidate run (pallas engine)"),
        KnobSpec("gap", "NeighborConfig", "gap",
                 (384, 128, 256, 512), COST_RECONFIGURE,
                 "key-space gap bridged when merging candidate cells"),
        KnobSpec("group", "NeighborConfig", "group",
                 (64, 32, 128), COST_RECONFIGURE,
                 "particles per target group (TravConfig targetSize)"),
        KnobSpec("list_skin_rel", "PropagatorConfig", "list_skin_rel",
                 (0.2, 0.1, 0.3), COST_RECONFIGURE,
                 "Verlet skin as a fraction of the 2h_max search radius "
                 "(persistent-list rebuild cadence)"),
        # -- Simulation driver --------------------------------------------
        KnobSpec("check_every", "Simulation", "check_every",
                 (1, 4, 8), COST_STATIC,
                 "deferred resort/verify window: steps launched between "
                 "batched diagnostic fetches (the resort cadence)",
                 off_sentinel=1),
        KnobSpec("grav_window", "Simulation", "grav_window",
                 (256, 0, 128, 512, 1024), COST_RECONFIGURE,
                 "pad quantum (rows) for the MAC-sized sparse gravity "
                 "near-field exchange; 0 = ship full peer slabs (the "
                 "pre-sizing lowering, byte-identical)",
                 off_sentinel=0),
        KnobSpec("donate", "Simulation", "donate",
                 ("auto", True, False), COST_STATIC,
                 "buffer donation on the single-device launch paths: "
                 "'auto' engages the donated step twins on TPU only, "
                 "True opts in anywhere, False pins the undonated path "
                 "(the discard-and-replay baseline)",
                 off_sentinel=False),
        KnobSpec("grav_window_margin", "Simulation", "grav_window_margin",
                 (1.4, 1.2, 1.7, 2.0), COST_RECONFIGURE,
                 "headroom over the measured MAC-need rows per gravity "
                 "halo cap (escape sentinel trips regrow it; larger = "
                 "fewer trips, more comm volume)"),
        # -- hierarchical block time steps (sph/blockdt.py) ---------------
        # NOTE: dt_bins changes the integration scheme, not just its
        # cost — sweep it only under a conservation-drift budget (the
        # replay driver's science gate), never on wall time alone
        KnobSpec("dt_bins", "PropagatorConfig", "dt_bins",
                 (2, 4, 8), COST_STATIC,
                 "power-of-two per-particle dt bins (None/absent = the "
                 "global-dt path; updates saved scale with occupancy of "
                 "the deep bins)",
                 off_sentinel=None),
        KnobSpec("bin_sync_every", "PropagatorConfig", "bin_sync_every",
                 (1, 2, 4), COST_STATIC,
                 "cycles between bin reassignments at the sync substep "
                 "(higher = fewer rebin passes, staler bins)",
                 off_sentinel=1),
        KnobSpec("bin_resort_drift", "PropagatorConfig",
                 "bin_resort_drift", (0.0, 0.01, 0.05), COST_STATIC,
                 "drift-aware resort threshold: keep the current order "
                 "while folded-key inversions stay under this fraction "
                 "of n (0 = resort whenever any inversion appears)",
                 off_sentinel=0.0),
    )
}

#: owner key -> how to resolve the live surface ("dataclass" validates
#: a field name via dataclasses.fields; "signature" a keyword parameter
#: via inspect.signature). Import paths are resolved lazily inside
#: validate_registry() — see the module docstring.
_OWNERS = {
    "GravityConfig": ("dataclass", "sphexa_tpu.gravity.traversal",
                      "GravityConfig"),
    "NeighborConfig": ("dataclass", "sphexa_tpu.neighbors.cell_list",
                       "NeighborConfig"),
    "PropagatorConfig": ("dataclass", "sphexa_tpu.propagator",
                         "PropagatorConfig"),
    "make_propagator_config": ("signature", "sphexa_tpu.simulation",
                               "make_propagator_config"),
    "Simulation": ("signature", "sphexa_tpu.simulation", "Simulation"),
}

#: knobs applied to GravityConfig via the gravity_tuning override path
GRAVITY_KNOBS = ("target_block", "blocks_per_chunk", "super_factor",
                 "m2p_cap_margin")
#: knobs forwarded into make_propagator_config by Simulation._configure
NEIGHBOR_KNOBS = ("block", "cell_target", "run_cap", "gap", "group",
                  "list_skin_rel")
#: knobs resolved on the Simulation constructor itself
SIMULATION_KNOBS = ("check_every", "grav_window", "grav_window_margin",
                    "donate")
#: block-timestep knobs (also Simulation-constructor-resolved; they land
#: on PropagatorConfig through make_propagator_config)
BLOCKDT_KNOBS = ("dt_bins", "bin_sync_every", "bin_resort_drift")


def knob_names() -> Tuple[str, ...]:
    return tuple(KNOBS)


def off_sentinel_knobs() -> Tuple[KnobSpec, ...]:
    """The specs carrying an off sentinel, in registry order — the
    population jaxaudit's JXA402 knob-inertness meta-rule probes."""
    return tuple(s for s in KNOBS.values() if s.has_off_sentinel)


def validate_off_sentinels() -> None:
    """Check every off-sentinel declaration against the LIVE Simulation
    consumption surface (``simulation.CONSUMED_KNOBS``); raises
    ``RuntimeError`` naming each drifted knob.

    The failure mode this closes: rename a knob's resolution site in the
    Simulation constructor and ``tuned={name: off}`` silently stops
    reaching the lowering — JXA402's off-vs-unset probe then passes
    VACUOUSLY forever. Called from ``validate_registry()`` (so
    ``import sphexa_tpu.tuning`` fails loudly) and again by the JXA402
    probe builder before it trusts a probe result."""
    import importlib

    sim_mod = importlib.import_module("sphexa_tpu.simulation")
    consumed = set(getattr(sim_mod, "CONSUMED_KNOBS", ()))
    problems = []
    for spec in off_sentinel_knobs():
        if spec.name not in consumed:
            problems.append(
                f"{spec.name}: off_sentinel={spec.off_sentinel!r} declared "
                f"but the name is not in simulation.CONSUMED_KNOBS — the "
                f"constructor no longer resolves it, so the JXA402 "
                f"inertness probe would pass vacuously (re-wire the "
                f"resolution site or drop the sentinel)")
        if spec.off_sentinel is not None and spec.domain \
                and type(spec.off_sentinel) not in {type(d) for d in
                                                    spec.domain} | {bool}:
            problems.append(
                f"{spec.name}: off_sentinel {spec.off_sentinel!r} type "
                f"does not match the domain {spec.domain!r}")
    if problems:
        raise RuntimeError(
            "off-sentinel knob declarations drifted from the live "
            "Simulation consumption surface:\n  " + "\n  ".join(problems))


def validate_registry() -> None:
    """Check every spec against its live owning surface; raises
    ``RuntimeError`` naming each drifted knob. Imports the config
    modules (and with them jax) — call sites that only need NAMES use
    the module-level ``KNOBS`` and skip this."""
    import importlib
    import inspect

    problems = []
    for spec in KNOBS.values():
        if spec.owner not in _OWNERS:
            problems.append(f"{spec.name}: unknown owner {spec.owner!r}")
            continue
        kind, module, attr = _OWNERS[spec.owner]
        obj = getattr(importlib.import_module(module), attr)
        if kind == "dataclass":
            fields = {f.name for f in dataclasses.fields(obj)}
        else:
            target = obj.__init__ if inspect.isclass(obj) else obj
            fields = set(inspect.signature(target).parameters)
        if spec.field not in fields:
            problems.append(
                f"{spec.name}: {spec.owner}.{spec.field} no longer "
                f"exists (renamed/removed field — update the KnobSpec "
                f"or the tuning table migration)")
        if spec.cost not in (COST_STATIC, COST_RECONFIGURE):
            problems.append(f"{spec.name}: bad cost {spec.cost!r}")
        if not spec.domain:
            problems.append(f"{spec.name}: empty domain")
    if problems:
        raise RuntimeError(
            "tuning knob registry drifted from the live configs:\n  "
            + "\n  ".join(problems))
    validate_off_sentinels()
