"""The knob registry: every tunable the autotuner may touch, typed.

Each ``KnobSpec`` names the knob, the config surface that OWNS it (a
frozen dataclass field or a constructor/factory keyword), the candidate
domain the search driver sweeps, and what changing it costs at runtime
(``static`` = a fresh ``Simulation``; ``reconfigure`` = applied at the
existing reconfigure granularity — a recompile, not a rebuild). The
registry is the single vocabulary shared by the sweep driver, the
committed ``TUNING_TABLE.json`` and the ``Simulation(tuned=...)``
resolution path: a knob name outside it is a stale table, not a typo
to guess around (``sphexa-telemetry tuning`` exits 1 on it).

``validate_registry()`` checks every spec against the REAL owning
dataclass/signature; ``sphexa_tpu.tuning`` (the package ``__init__``)
calls it at import so a renamed config field fails loudly at the first
``import sphexa_tpu.tuning`` instead of silently de-tuning a run. This
module itself stays import-light (no jax, no config modules) so the
table tooling can read knob NAMES without dragging in a backend — the
owning modules are imported only inside ``validate_registry()``.
"""

import dataclasses
from typing import Dict, Tuple

#: where a knob's new value takes effect
COST_STATIC = "static"          # construction-time only (new Simulation)
COST_RECONFIGURE = "reconfigure"  # applied at reconfigure granularity


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One tunable: identity + owning surface + search domain + cost."""

    name: str
    #: owning config surface, one of the keys of _OWNERS below
    owner: str
    #: field/parameter name on the owner (usually == name)
    field: str
    #: candidate values the search driver sweeps, in preference order
    #: (first = the safe/most-common default)
    domain: Tuple
    #: COST_STATIC or COST_RECONFIGURE
    cost: str
    description: str = ""


#: every registered knob, keyed by name. Domains are the measured
#: candidate sets from the past sweeps (scripts/sweep_engine.py /
#: profile_grid.py, docs/NEXT.md rounds 4-6) — the staged search seeds
#: from them, it does not invent values.
KNOBS: Dict[str, KnobSpec] = {
    spec.name: spec
    for spec in (
        # -- gravity solver shape (GravityConfig) -------------------------
        KnobSpec("target_block", "GravityConfig", "target_block",
                 (64, 128, 256), COST_RECONFIGURE,
                 "bodies per traversal block (MAC shared per block)"),
        KnobSpec("blocks_per_chunk", "GravityConfig", "blocks_per_chunk",
                 (32, 16, 8), COST_RECONFIGURE,
                 "traversal blocks batched per classification chunk"),
        KnobSpec("super_factor", "GravityConfig", "super_factor",
                 (0, 4, 8, 16), COST_RECONFIGURE,
                 "superblock size in blocks for the two-level "
                 "classification (0 = flat; > 0 implies the bitmask "
                 "compaction on the pallas backend)"),
        KnobSpec("m2p_cap_margin", "GravityConfig", "m2p_cap_margin",
                 (1.3, 1.15, 1.5), COST_RECONFIGURE,
                 "M2P interaction-list cap margin (eval cost is linear "
                 "in the cap; overflow is guarded and auto-regrown)"),
        # -- neighbor engine (NeighborConfig / make_propagator_config) ----
        KnobSpec("block", "NeighborConfig", "block",
                 (2048, 4096, 8192), COST_STATIC,
                 "particles per processing chunk (memory bound)"),
        KnobSpec("cell_target", "make_propagator_config", "cell_target",
                 (128, 64, 256), COST_RECONFIGURE,
                 "mean cell occupancy the grid level targets"),
        KnobSpec("run_cap", "NeighborConfig", "run_cap",
                 (1536, 1024, 2048), COST_RECONFIGURE,
                 "max slots per merged candidate run (pallas engine)"),
        KnobSpec("gap", "NeighborConfig", "gap",
                 (384, 128, 256, 512), COST_RECONFIGURE,
                 "key-space gap bridged when merging candidate cells"),
        KnobSpec("group", "NeighborConfig", "group",
                 (64, 32, 128), COST_RECONFIGURE,
                 "particles per target group (TravConfig targetSize)"),
        KnobSpec("list_skin_rel", "PropagatorConfig", "list_skin_rel",
                 (0.2, 0.1, 0.3), COST_RECONFIGURE,
                 "Verlet skin as a fraction of the 2h_max search radius "
                 "(persistent-list rebuild cadence)"),
        # -- Simulation driver --------------------------------------------
        KnobSpec("check_every", "Simulation", "check_every",
                 (1, 4, 8), COST_STATIC,
                 "deferred resort/verify window: steps launched between "
                 "batched diagnostic fetches (the resort cadence)"),
        KnobSpec("grav_window", "Simulation", "grav_window",
                 (256, 0, 128, 512, 1024), COST_RECONFIGURE,
                 "pad quantum (rows) for the MAC-sized sparse gravity "
                 "near-field exchange; 0 = ship full peer slabs (the "
                 "pre-sizing lowering, byte-identical)"),
        KnobSpec("grav_window_margin", "Simulation", "grav_window_margin",
                 (1.4, 1.2, 1.7, 2.0), COST_RECONFIGURE,
                 "headroom over the measured MAC-need rows per gravity "
                 "halo cap (escape sentinel trips regrow it; larger = "
                 "fewer trips, more comm volume)"),
        # -- hierarchical block time steps (sph/blockdt.py) ---------------
        # NOTE: dt_bins changes the integration scheme, not just its
        # cost — sweep it only under a conservation-drift budget (the
        # replay driver's science gate), never on wall time alone
        KnobSpec("dt_bins", "PropagatorConfig", "dt_bins",
                 (2, 4, 8), COST_STATIC,
                 "power-of-two per-particle dt bins (None/absent = the "
                 "global-dt path; updates saved scale with occupancy of "
                 "the deep bins)"),
        KnobSpec("bin_sync_every", "PropagatorConfig", "bin_sync_every",
                 (1, 2, 4), COST_STATIC,
                 "cycles between bin reassignments at the sync substep "
                 "(higher = fewer rebin passes, staler bins)"),
        KnobSpec("bin_resort_drift", "PropagatorConfig",
                 "bin_resort_drift", (0.0, 0.01, 0.05), COST_STATIC,
                 "drift-aware resort threshold: keep the current order "
                 "while folded-key inversions stay under this fraction "
                 "of n (0 = resort whenever any inversion appears)"),
    )
}

#: owner key -> how to resolve the live surface ("dataclass" validates
#: a field name via dataclasses.fields; "signature" a keyword parameter
#: via inspect.signature). Import paths are resolved lazily inside
#: validate_registry() — see the module docstring.
_OWNERS = {
    "GravityConfig": ("dataclass", "sphexa_tpu.gravity.traversal",
                      "GravityConfig"),
    "NeighborConfig": ("dataclass", "sphexa_tpu.neighbors.cell_list",
                       "NeighborConfig"),
    "PropagatorConfig": ("dataclass", "sphexa_tpu.propagator",
                         "PropagatorConfig"),
    "make_propagator_config": ("signature", "sphexa_tpu.simulation",
                               "make_propagator_config"),
    "Simulation": ("signature", "sphexa_tpu.simulation", "Simulation"),
}

#: knobs applied to GravityConfig via the gravity_tuning override path
GRAVITY_KNOBS = ("target_block", "blocks_per_chunk", "super_factor",
                 "m2p_cap_margin")
#: knobs forwarded into make_propagator_config by Simulation._configure
NEIGHBOR_KNOBS = ("block", "cell_target", "run_cap", "gap", "group",
                  "list_skin_rel")
#: knobs resolved on the Simulation constructor itself
SIMULATION_KNOBS = ("check_every", "grav_window", "grav_window_margin")
#: block-timestep knobs (also Simulation-constructor-resolved; they land
#: on PropagatorConfig through make_propagator_config)
BLOCKDT_KNOBS = ("dt_bins", "bin_sync_every", "bin_resort_drift")


def knob_names() -> Tuple[str, ...]:
    return tuple(KNOBS)


def validate_registry() -> None:
    """Check every spec against its live owning surface; raises
    ``RuntimeError`` naming each drifted knob. Imports the config
    modules (and with them jax) — call sites that only need NAMES use
    the module-level ``KNOBS`` and skip this."""
    import importlib
    import inspect

    problems = []
    for spec in KNOBS.values():
        if spec.owner not in _OWNERS:
            problems.append(f"{spec.name}: unknown owner {spec.owner!r}")
            continue
        kind, module, attr = _OWNERS[spec.owner]
        obj = getattr(importlib.import_module(module), attr)
        if kind == "dataclass":
            fields = {f.name for f in dataclasses.fields(obj)}
        else:
            target = obj.__init__ if inspect.isclass(obj) else obj
            fields = set(inspect.signature(target).parameters)
        if spec.field not in fields:
            problems.append(
                f"{spec.name}: {spec.owner}.{spec.field} no longer "
                f"exists (renamed/removed field — update the KnobSpec "
                f"or the tuning table migration)")
        if spec.cost not in (COST_STATIC, COST_RECONFIGURE):
            problems.append(f"{spec.name}: bad cost {spec.cost!r}")
        if not spec.domain:
            problems.append(f"{spec.name}: empty domain")
    if problems:
        raise RuntimeError(
            "tuning knob registry drifted from the live configs:\n  "
            + "\n  ".join(problems))
