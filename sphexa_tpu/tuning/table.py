"""The committed tuning table: measured knob choices as an artifact.

``TUNING_TABLE.json`` (repo root) is keyed by (workload class, N
bucket, device count P, backend) and carries, per entry, the knob dict
the sweep found plus its provenance (source run, date, objective,
measured win) — the Bonsai/exafmm per-architecture tuned-parameter
files (PAPERS.md), but with the evidence trail attached. Resolution
precedence at ``Simulation(tuned=...)`` / ``make_propagator_config``
time is *explicit kwarg > table entry > gravity_tuning/default
heuristic*; the chosen entry is stamped into the run manifest and a
``tuning`` event (schema v5) so a perf diff can attribute a change to
a knob change.

N buckets are decades (``1e4`` = 1e4 <= N < 1e5): knob choices move
on order-of-magnitude scale (the ``gravity_tuning`` threshold is one
such decade edge), and coarser keys mean the committed table actually
covers runs instead of only the exact benchmarked N.

Deliberately jax-free (like telemetry/manifest.py): reading and
validating the table must not drag in a backend — knob-NAME validation
goes against ``knobs.KNOBS``; the live-dataclass drift check is the
tuning package's import-time ``validate_registry()``.
"""

import json
import math
import os
from typing import Dict, List, Optional, Tuple

from sphexa_tpu.tuning.knobs import KNOBS

#: TUNING_TABLE.json schema version (independent of the event schema)
TABLE_SCHEMA = 1

#: key fields every entry must carry
KEY_FIELDS = ("workload", "n_bucket", "p", "backend")

#: the workload-class wildcard an entry may use to cover every case
GENERIC_WORKLOAD = "generic"

#: environment override for the committed table location
TABLE_ENV = "SPHEXA_TUNING_TABLE"


def default_table_path() -> str:
    """The committed table at the repo root (next to TELEMETRY_LOCK
    .json), overridable via ``SPHEXA_TUNING_TABLE``."""
    env = os.environ.get(TABLE_ENV)
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "TUNING_TABLE.json")


def n_bucket(n: int) -> str:
    """Decade bucket of a particle count: ``1e5`` = 1e5 <= n < 1e6."""
    return f"1e{int(math.floor(math.log10(max(int(n), 1))))}"


def entry_key(entry: Dict) -> Tuple:
    return tuple(entry.get(k) for k in KEY_FIELDS)


def load_table(path: Optional[str] = None) -> Dict:
    """Read a table file. Raises ``FileNotFoundError`` when it does not
    exist and ``ValueError`` when it is not a table-shaped JSON object
    — the callers' exit-code contracts depend on telling those apart."""
    path = path or default_table_path()
    with open(path) as f:
        table = json.load(f)
    if not isinstance(table, dict) or "entries" not in table:
        raise ValueError(f"{path}: not a tuning table (no 'entries')")
    return table


def save_table(path: str, table: Dict) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(table, f, indent=2, sort_keys=True, default=str)
        f.write("\n")


def new_table() -> Dict:
    return {"schema": TABLE_SCHEMA, "entries": []}


def validate_table(table: Dict) -> List[str]:
    """Schema problems with one table ([] = valid): version, entry
    shape, duplicate keys, and — the gate's teeth — knob names outside
    the registry (a renamed knob makes the committed entry dead weight
    that would silently stop applying; check.sh exits 1 on it)."""
    problems: List[str] = []
    if not isinstance(table, dict):
        return ["table is not an object"]
    if table.get("schema") != TABLE_SCHEMA:
        problems.append(f"bad table schema {table.get('schema')!r} "
                        f"(expected {TABLE_SCHEMA})")
    entries = table.get("entries")
    if not isinstance(entries, list):
        return problems + ["'entries' is not a list"]
    seen = set()
    for i, e in enumerate(entries):
        tag = f"entry {i}"
        if not isinstance(e, dict):
            problems.append(f"{tag}: not an object")
            continue
        for k in KEY_FIELDS:
            if k not in e:
                problems.append(f"{tag}: missing key field {k!r}")
        key = entry_key(e)
        if key in seen:
            problems.append(f"{tag}: duplicate key {key}")
        seen.add(key)
        knobs = e.get("knobs")
        if not isinstance(knobs, dict) or not knobs:
            problems.append(f"{tag}: missing/empty 'knobs'")
            continue
        for name in knobs:
            if name not in KNOBS:
                problems.append(
                    f"{tag}: stale knob {name!r} (not in the registry "
                    f"— renamed/removed; migrate or drop the entry)")
        if not isinstance(e.get("provenance"), dict):
            problems.append(f"{tag}: missing 'provenance'")
    return problems


def resolve_entry(table: Dict, workload: str, n: int, p: int,
                  backend: str) -> Optional[Dict]:
    """The entry covering (workload, N, P, backend), or None. An exact
    workload match wins over a ``generic`` wildcard entry."""
    want = (str(workload), n_bucket(n), int(p), str(backend))
    fallback = None
    for e in table.get("entries", ()):
        key = entry_key(e)
        if key == want:
            return e
        if key == (GENERIC_WORKLOAD,) + want[1:]:
            fallback = e
    return fallback


def upsert_entry(table: Dict, entry: Dict) -> Dict:
    """Insert/replace the entry with the same key; returns the table."""
    key = entry_key(entry)
    table["entries"] = [e for e in table.get("entries", [])
                        if entry_key(e) != key] + [entry]
    return table


def make_entry(workload: str, n: int, p: int, backend: str,
               knobs: Dict, provenance: Dict) -> Dict:
    bad = sorted(set(knobs) - set(KNOBS))
    if bad:
        raise ValueError(f"unregistered knobs {bad}; add a KnobSpec "
                         f"(sphexa_tpu/tuning/knobs.py) first")
    return {"workload": str(workload), "n_bucket": n_bucket(n),
            "p": int(p), "backend": str(backend),
            "knobs": dict(knobs), "provenance": dict(provenance)}


def resolve_knobs(tuned, workload: Optional[str], n: int, p: int,
                  backend: str,
                  explicit: Dict) -> Tuple[Dict, Dict]:
    """The tuned="auto" resolution: (overrides, provenance).

    ``tuned`` is what the caller passed: None (heuristics only),
    ``"auto"`` (the committed table, silently absent-ok), a table path
    (must exist), a loaded table dict, or a plain knob dict (the replay
    harness's per-candidate path — source ``direct``). ``explicit``
    holds the knobs the caller spelled out as kwargs; they are REMOVED
    from the returned overrides, which is the whole precedence rule —
    explicit kwarg > table entry > heuristic/default — enforced in one
    place. ``overrides`` contains only table/direct values the caller
    should apply on top of its defaults; ``provenance`` names the
    winner per knob and is what gets stamped into the run manifest and
    the ``tuning`` event.
    """
    source, entry, path = "heuristic", None, None
    table_knobs: Dict = {}
    if tuned is None:
        pass
    elif isinstance(tuned, dict) and "entries" not in tuned:
        # a raw knob dict: the sweep's candidate path
        bad = sorted(set(tuned) - set(KNOBS))
        if bad:
            raise ValueError(f"tuned= knob dict has unregistered knobs "
                             f"{bad} (see sphexa_tpu/tuning/knobs.py)")
        table_knobs, source = dict(tuned), "direct"
    else:
        if isinstance(tuned, dict):
            table = tuned
        else:
            path = default_table_path() if tuned == "auto" else str(tuned)
            if tuned == "auto" and not os.path.exists(path):
                # auto is opportunistic: no committed table, no tuning
                table = new_table()
            else:
                table = load_table(path)
        entry = resolve_entry(table, workload or GENERIC_WORKLOAD,
                              n, p, backend)
        if entry is not None:
            table_knobs, source = dict(entry["knobs"]), "table"
    overrides = {k: v for k, v in table_knobs.items() if k not in explicit}
    if source != "heuristic" and not overrides:
        # the caller's kwargs overrode everything the entry offered (or
        # the entry was empty after filtering): nothing tuned is active
        source = "explicit" if explicit else "heuristic"
    provenance = {
        "source": source,
        "key": {"workload": entry.get("workload"),
                "n_bucket": entry.get("n_bucket"),
                "p": entry.get("p"),
                "backend": entry.get("backend")} if entry else None,
        "table": path,
        "knobs": overrides,
        "explicit": sorted(explicit),
        "entry_provenance": entry.get("provenance") if entry else None,
    }
    return overrides, provenance


def coverage(table: Dict) -> Dict:
    """What the table covers: per (workload, backend), the N buckets
    and P counts with entries — the ``sphexa-telemetry tuning`` view
    that makes the gaps visible before a campaign relies on them."""
    cov: Dict[str, Dict] = {}
    for e in table.get("entries", ()):
        k = f"{e.get('workload')}/{e.get('backend')}"
        c = cov.setdefault(k, {"n_buckets": set(), "p": set()})
        c["n_buckets"].add(e.get("n_bucket"))
        c["p"].add(e.get("p"))
    return {k: {"n_buckets": sorted(v["n_buckets"]),
                "p": sorted(v["p"])} for k, v in sorted(cov.items())}
