"""Search driver: staged grid + coordinate descent under a budget.

Deliberately boring and deterministic — the measurement is the
expensive, noisy part, so the driver's job is to spend a fixed
candidate budget well and leave an evidence trail, not to be clever:

* stage 0 measures the BASELINE (empty knob dict = pure heuristics) so
  every reported win is relative to what the run would have done;
* then coordinate-descent passes in registry order: one knob at a
  time, scanning its declared domain around the incumbent, keeping a
  strictly better ``ok`` measurement (``overflow``/``failed``
  candidates are recorded but never become the incumbent);
* passes repeat until a full pass improves nothing or the budget is
  spent.

Every attempt — including dead ones — is emitted as a schema-v5
``sweep`` event, and a candidate that raises becomes a ``failed``
event instead of killing the sweep (the CLI additionally arms the
flight recorder so a hard death still leaves a blackbox). The module
is jax-free and pure over the ``measure`` callable, which is what the
deterministic fake-measurement tests pin.
"""

from typing import Callable, Dict, Optional, Tuple

from sphexa_tpu.tuning.knobs import KNOBS


def domains_for(names) -> Dict[str, Tuple]:
    """Registry domains for a knob-name subset, in registry order (the
    coordinate order — earlier knobs are swept first)."""
    bad = sorted(set(names) - set(KNOBS))
    if bad:
        raise KeyError(f"unknown knobs {bad} (known: {sorted(KNOBS)})")
    want = set(names)
    return {k: spec.domain for k, spec in KNOBS.items() if k in want}


def run_sweep(measure: Callable[[Dict], Dict],
              domains: Dict[str, Tuple],
              budget: int,
              telemetry=None,
              objective: str = "per_step_s",
              log: Callable = lambda s: None) -> Dict:
    """Spend up to ``budget`` measurements of ``measure(knobs) ->
    {status, value, ...}`` (lower value better); returns ``{baseline,
    best, improved, history, candidates}``. ``best`` covers only knobs
    that beat the incumbent — an empty best dict means the heuristics
    already won."""
    history = []
    spent = 0

    def attempt(knobs: Dict) -> Optional[Dict]:
        nonlocal spent
        if spent >= budget:
            return None
        try:
            r = dict(measure(dict(knobs)))
        except Exception as e:  # dead candidate, not dead sweep
            r = {"status": "failed", "value": None,
                 "error": f"{type(e).__name__}: {e}"}
        rec = {"candidate": spent, "knobs": dict(knobs), **r}
        history.append(rec)
        if telemetry is not None:
            telemetry.event(
                "sweep", candidate=spent, knobs=dict(knobs),
                status=rec.get("status"), objective=objective,
                value=rec.get("value"),
                **({"error": rec["error"]} if "error" in rec else {}),
            )
        log(f"candidate {spent}: {knobs or '{baseline}'} -> "
            f"{rec.get('status')} value={rec.get('value')}")
        spent += 1
        return rec

    def usable(rec) -> bool:
        return (rec is not None and rec.get("status") == "ok"
                and isinstance(rec.get("value"), (int, float)))

    baseline = attempt({})
    best_knobs: Dict = {}
    best_value = baseline["value"] if usable(baseline) else float("inf")

    improved_any, improved_pass = False, True
    while improved_pass and spent < budget:
        improved_pass = False
        for name, domain in domains.items():
            incumbent = best_knobs.get(name, domain[0])
            for v in domain:
                if v == incumbent or spent >= budget:
                    continue
                rec = attempt({**best_knobs, name: v})
                if usable(rec) and rec["value"] < best_value:
                    best_knobs = dict(rec["knobs"])
                    best_value = rec["value"]
                    improved_any = improved_pass = True
            if spent >= budget:
                break

    return {
        "baseline": baseline,
        "best": {"knobs": best_knobs, "value": best_value},
        "improved": improved_any,
        "history": history,
        "candidates": spent,
    }
