"""Workload replay: rebuild a run's Simulation and time one candidate.

The harness closes the measurement half of the observe→decide loop: a
``ReplaySpec`` reconstructs a workload either from a telemetry run
manifest (``spec_from_manifest`` — the run that was slow IS the
workload you tune) or from a named init case, and ``measure_candidate``
scores one knob dict on it using the machinery the production driver
already trusts:

* the candidate knobs are applied through the SAME ``tuned=`` path a
  table entry takes (Simulation's direct-dict source), so the sweep
  measures exactly what committing the entry would run;
* timing is the existing sync-free deferred-window clock — the
  candidate runs as one (or more) ``check_every`` windows and the
  objective is the ``window`` event's ``per_step_s``, not a fresh
  ad-hoc ``time.time()`` loop (the scripts/sweep_engine.py pattern
  this module retires);
* optionally the objective is one PHASE of the per-phase device-time
  table (``objective="phase:gravity-mac"``): the measured window runs
  under a jax.profiler trace and traceview's ``summarize_trace``
  attributes it — tune the phase you are losing, not end-to-end.

Exceptions deliberately propagate: the search driver (search.run_sweep)
is the crash boundary that turns a dead candidate into a ``failed``
sweep event instead of a dead sweep.
"""

import dataclasses
import math
from typing import Dict, Optional

from sphexa_tpu.telemetry import MemorySink, Telemetry, read_manifest

#: knob whose value doubles as the measurement window length
_CADENCE = "check_every"


@dataclasses.dataclass(frozen=True)
class ReplaySpec:
    """One reconstructable workload: a named init case at a given scale
    on a given backend/mesh. Snapshot-file workloads are out of scope
    (replay must be buildable on a machine that only has the manifest)."""

    case: str
    side: int
    prop: str = "std"
    backend: str = "auto"
    theta: float = 0.5
    devices: Optional[int] = None

    @property
    def n(self) -> int:
        return self.side ** 3


def spec_from_manifest(run_dir: str) -> ReplaySpec:
    """Rebuild the workload of a telemetry run from its manifest (the
    app stamps ``config`` = CLI args plus top-level ``case``/``prop``
    keys — ``write_manifest`` splats its ``extra`` dict into the
    manifest root). Raises ``FileNotFoundError`` (no manifest) or
    ``ValueError`` (one that does not describe a replayable case run)."""
    m = read_manifest(run_dir)
    if m is None:
        raise FileNotFoundError(f"{run_dir}: no manifest.json "
                                f"(not a telemetry run dir)")
    cfg = m.get("config") or {}
    case = m.get("case") or cfg.get("init")
    side = cfg.get("side")
    if not case or not side:
        raise ValueError(f"{run_dir}: manifest lacks case/side — "
                         f"cannot reconstruct the workload")
    from sphexa_tpu.init import CASES, split_case_spec

    base, _ = split_case_spec(str(case))
    if base not in CASES:
        raise ValueError(f"{run_dir}: case {case!r} is not a named init "
                         f"case (snapshot replays are unsupported)")
    return ReplaySpec(
        case=str(case), side=int(side),
        prop=str(m.get("prop") or cfg.get("prop") or "std"),
        backend=str(cfg.get("backend") or "auto"),
        theta=float(cfg.get("theta") or 0.5),
        devices=cfg.get("devices"),
    )


def build_case(spec: ReplaySpec):
    """(state, box, const) for the spec — one initializer call, shared
    by every candidate (measure_candidate re-invokes it so a candidate
    that corrupts state cannot poison the next one)."""
    from sphexa_tpu.init import make_initializer

    return make_initializer(spec.case)(spec.side)


def measure_candidate(spec: ReplaySpec, knobs: Dict, steps: int = 6,
                      warmup: int = 1,
                      objective: str = "per_step_s",
                      trace_dir: Optional[str] = None) -> Dict:
    """Score one knob dict on the spec's workload; returns
    ``{status, objective, value, per_step_s, steps, windows, rollbacks,
    reconfigures}``. ``status`` is ``ok``, or ``overflow`` when the run
    needed a rollback/replay (the timing then includes recovery — a
    cap-busting candidate is legal but scored at its true cost and
    flagged). Lower value is better for every objective."""
    from sphexa_tpu.simulation import Simulation

    state, box, const = build_case(spec)
    mem = MemorySink()
    inner = Telemetry(sinks=[mem])
    # the candidate's knobs ride the production tuned= path (direct-dict
    # source); check_every is special — it IS the measurement window, so
    # when the candidate does not sweep it we pin the window to the
    # measured step count (one batched fetch per measurement)
    cadence = int(knobs.get(_CADENCE, steps))
    measured = max(cadence, math.ceil(steps / cadence) * cadence)
    sim = Simulation(
        state, box, const, prop=spec.prop, theta=spec.theta,
        backend=spec.backend, num_devices=spec.devices,
        check_every=None if _CADENCE in knobs else measured,
        tuned=dict(knobs) if knobs else None, workload=spec.case,
        telemetry=inner,
    )
    # warmup windows: compile + first-window jitter stay out of the score
    if warmup > 0:
        sim.run(warmup * cadence)
    mem.events.clear()
    base_rollbacks = inner.counters["rollbacks"]
    base_reconfigs = inner.counters["reconfigures"]
    tracing = objective.startswith("phase:")
    if tracing:
        if not trace_dir:
            raise ValueError(f"objective {objective!r} needs trace_dir")
        import jax

        jax.profiler.start_trace(trace_dir)
    try:
        sim.run(measured)
    finally:
        if tracing:
            import jax

            jax.profiler.stop_trace()
    windows = mem.of_kind("window")
    wall = sum(w["wall_s"] for w in windows)
    done = sum(w["steps"] for w in windows)
    per_step = wall / done if done else float("nan")
    rollbacks = int(inner.counters["rollbacks"] - base_rollbacks)
    result = {
        "status": "overflow" if rollbacks else "ok",
        "objective": objective,
        "value": per_step,
        "per_step_s": per_step,
        "steps": int(done),
        "windows": len(windows),
        "rollbacks": rollbacks,
        "reconfigures": int(inner.counters["reconfigures"]
                            - base_reconfigs),
    }
    if tracing:
        from sphexa_tpu.telemetry.traceview import summarize_trace

        want = objective.split(":", 1)[1]
        summary = summarize_trace(trace_dir)
        row = next((p for p in summary.get("phases", ())
                    if p.get("phase") == want), None)
        if row is None:
            raise ValueError(
                f"phase {want!r} absent from the trace (has: "
                f"{[p.get('phase') for p in summary.get('phases', ())]})")
        # per-step device microseconds of the one phase being tuned
        result["value"] = float(row["us"]) / max(done, 1)
        result["phase_us"] = float(row["us"])
    return result


def static_cost_candidate(spec: ReplaySpec, knobs: Dict, phase: str,
                          device: str = "v5e") -> Dict:
    """Score one knob dict CHIP-FREE (``objective="static-cost:<phase>"``).

    The candidate's knobs ride the same production ``tuned=`` path as
    ``measure_candidate``, but instead of running steps the propagator
    step is TRACED to a jaxpr and the value is the static roofline
    prediction (jaxcost, devtools/audit/costmodel.py) of the target
    phase's ms on the named device model — a sweep can rank candidates
    on a machine with no accelerator at all. The ranking is only as
    good as the cost model: run ``sphexa-telemetry trace <capture>
    --predict`` against a real capture before trusting it
    (docs/STATIC_ANALYSIS.md, calibration workflow).
    """
    import jax

    from sphexa_tpu import propagator as prop
    from sphexa_tpu.devtools.audit.costmodel import analyze_jaxpr, predict
    from sphexa_tpu.simulation import Simulation

    state, box, const = build_case(spec)
    sim = Simulation(
        state, box, const, prop=spec.prop, theta=spec.theta,
        backend=spec.backend, num_devices=spec.devices,
        tuned=dict(knobs) if knobs else None, workload=spec.case,
    )
    cfg, gtree = sim._cfg, sim._gtree
    # one closure per propagator, mirroring the audit registry's step
    # builders so the traced program IS the production step
    steps = {
        "std": lambda s, b: prop.step_hydro_std(s, b, cfg, gtree),
        "ve": lambda s, b: prop.step_hydro_ve(s, b, cfg, gtree),
        "nbody": lambda s, b: prop.step_nbody(s, b, cfg, gtree),
        "turb-ve": lambda s, b: prop.step_turb_ve(
            s, b, cfg, gtree, sim.turb_state, sim.turb_cfg),
        "std-cooling": lambda s, b: prop.step_hydro_std_cooling(
            s, b, cfg, gtree, sim.chem, sim.cooling_cfg),
    }
    if spec.prop not in steps:
        raise ValueError(f"static-cost objective has no step builder for "
                         f"prop {spec.prop!r} (has: {sorted(steps)})")
    jaxpr = jax.make_jaxpr(steps[spec.prop])(sim.state, sim.box)
    pred = predict(analyze_jaxpr(jaxpr), device)
    row = pred.row(phase)
    if row is None or row.ms <= 0:
        raise ValueError(
            f"phase {phase!r} absent from the static prediction (has: "
            f"{[r.phase for r in pred.rows]})")
    return {
        "status": "ok",
        "objective": f"static-cost:{phase}",
        "value": row.ms,
        "predicted_ms": row.ms,
        "ai": row.ai,
        "bound": row.bound,
        "device": pred.device,
        "steps": 0, "windows": 0, "rollbacks": 0, "reconfigures": 0,
    }
