"""Evrard adiabatic collapse initial conditions.

Physics-equivalent of the reference's ``main/src/init/evrard_init.hpp``: a
cold, self-gravitating gas sphere with rho ~ 1/r, the standard benchmark
for coupled hydrodynamics + gravity (it collapses, bounces, and a shock
propagates outward).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import contract_rho_profile, cut_sphere, jittered_lattice
from sphexa_tpu.init.utils import build_state, settings_to_constants
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def evrard_constants() -> Dict[str, float]:
    """Test-case settings (evrard_init.hpp evrardConstants)."""
    return {
        "gravConstant": 1.0, "r": 1.0, "mTotal": 1.0, "gamma": 5.0 / 3.0,
        "u0": 0.05, "minDt": 1e-4, "minDt_m1": 1e-4, "mui": 10.0,
        "ng0": 100, "ngmax": 150,
    }


def init_evrard_cooling(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Evrard collapse with radiative cooling enabled (run with
    --prop std-cooling); particle fields are identical to init_evrard.

    The cooling unit system of the reference case (cooling::m_code_in_ms =
    1e16, cooling::l_code_in_kpc = 46400, evrard_cooling_init.hpp:59-60) is
    the single-source default of physics.cooling.CoolingConfig; customize
    by passing Simulation(cooling_cfg=CoolingConfig(...))."""
    return init_evrard(side, overrides)


def init_evrard(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Glass-sphere Evrard setup (evrard_init.hpp EvrardGlassSphere::init):
    uniform sphere of radius r contracted by sqrt(radius) to produce the
    rho ~ 1/r profile; h follows the local concentration c(r) = c0 / r."""
    settings = evrard_constants()
    if overrides:
        settings.update(overrides)
    r = settings["r"]

    x, y, z = jittered_lattice((-r, -r, -r), (r, r, r), (side, side, side))
    x, y, z = cut_sphere(r, x, y, z)
    n = x.shape[0]
    x, y, z = contract_rho_profile(x, y, z)

    const = settings_to_constants(settings)
    m_part = settings["mTotal"] / n

    # local particle concentration after contraction: c(r) = 2/3 n/(V r)
    total_volume = 4.0 * np.pi / 3.0 * r**3
    c0 = 2.0 / 3.0 * n / total_volume
    radius = np.maximum(np.sqrt(x * x + y * y + z * z), 1e-10)
    h = np.cbrt(3.0 / (4 * np.pi) * settings["ng0"] * radius / c0) * 0.5

    cv = ideal_gas_cv(settings["mui"], settings["gamma"])
    temp0 = settings["u0"] / cv

    box = Box.create(-r, r, boundary=BoundaryType.open)
    state = build_state(
        x, y, z, 0.0, 0.0, 0.0, h, m_part, temp0,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
