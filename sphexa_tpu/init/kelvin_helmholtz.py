"""Kelvin-Helmholtz instability initial conditions.

Physics-equivalent of the reference's ``main/src/init/kelvin_helmholtz_init.hpp``:
a dense band (rhoInt = 2, y in [0.25, 0.75]) shearing against a light
background (rhoExt = 1) in a thin periodic slab, seeded with a sinusoidal
vy perturbation; the billow growth rate is the observable
(time_energy_growth.hpp).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import jittered_lattice
from sphexa_tpu.init.utils import build_state, h_from_density, settings_to_constants
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv

_LZ = 0.0625  # slab thickness (kelvin_helmholtz_init.hpp:145)


def kelvin_helmholtz_constants() -> Dict[str, float]:
    """Test-case settings (kelvin_helmholtz_init.hpp)."""
    return {
        "rhoInt": 2.0, "rhoExt": 1.0, "vxExt": 0.5, "vxInt": -0.5,
        "gamma": 5.0 / 3.0, "p": 2.5, "omega0": 0.01, "Kcour": 0.4,
        "ng0": 100, "ngmax": 150, "minDt": 1e-7, "minDt_m1": 1e-7,
        "gravConstant": 0.0, "mui": 10.0, "kelvin-helmholtz": 1.0,
    }


def init_kelvin_helmholtz(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Three-layer slab setup (KelvinHelmholtzGlass::init): the middle band
    carries twice the particle number density of the outer layers
    (equal-mass particles realize the 2:1 density contrast); the shear
    velocity relaxes over ls = 0.025 at the interfaces."""
    settings = kelvin_helmholtz_constants()
    if overrides:
        settings.update(overrides)

    rho_int, rho_ext = settings["rhoInt"], settings["rhoExt"]

    # particle number densities: inner band vs outer layers, total ~ side^3
    v_in = 1.0 * 0.5 * _LZ
    v_out = 1.0 * 0.5 * _LZ
    nd_int = side**3 / (v_in + v_out * rho_ext / rho_int)
    a_int = nd_int ** (-1.0 / 3.0)

    def layer(lo, hi, spacing, seed, keep_fraction=1.0):
        """Lattice at ``spacing``; density contrast is realized by exact
        thinning (integer per-axis counts round too coarsely in a thin
        slab to hit the 2:1 ratio directly)."""
        ext = np.asarray(hi) - np.asarray(lo)
        counts = np.maximum(1, np.round(ext / spacing).astype(int))
        lx, ly, lz = jittered_lattice(lo, hi, counts, seed=seed)
        if keep_fraction < 1.0:
            n = lx.shape[0]
            rng = np.random.default_rng(seed + 1000)
            keep = rng.choice(n, size=round(n * keep_fraction), replace=False)
            lx, ly, lz = lx[keep], ly[keep], lz[keep]
        return lx, ly, lz

    thin = rho_ext / rho_int
    x2, y2, z2 = layer((0, 0.25, 0), (1, 0.75, _LZ), a_int, seed=2)
    x1, y1, z1 = layer((0, 0.0, 0), (1, 0.25, _LZ), a_int, seed=1, keep_fraction=thin)
    x3, y3, z3 = layer((0, 0.75, 0), (1, 1.0, _LZ), a_int, seed=3, keep_fraction=thin)
    x = np.concatenate([x1, x2, x3])
    y = np.concatenate([y1, y2, y3])
    z = np.concatenate([z1, z2, z3])

    n_inner = x2.shape[0]
    m_part = v_in * rho_int / n_inner

    const = settings_to_constants(settings)
    gamma, p = settings["gamma"], settings["p"]
    u_int = p / ((gamma - 1.0) * rho_int)
    u_ext = p / ((gamma - 1.0) * rho_ext)
    vx_int, vx_ext = settings["vxInt"], settings["vxExt"]
    v_dif = 0.5 * (vx_ext - vx_int)
    ls = 0.025
    h_int = h_from_density(settings["ng0"], m_part, rho_int)
    h_ext = h_from_density(settings["ng0"], m_part, rho_ext)

    cv = ideal_gas_cv(settings["mui"], gamma)
    inner = (y > 0.25) & (y < 0.75)

    # velocity shear with exponential relaxation toward the interfaces
    vx_in = vx_int + v_dif * np.where(
        y > 0.5, np.exp((y - 0.75) / ls), np.exp((0.25 - y) / ls)
    )
    vx_out = vx_ext - v_dif * np.where(
        y < 0.25, np.exp((y - 0.25) / ls), np.exp((0.75 - y) / ls)
    )
    vx = np.where(inner, vx_in, vx_out)
    vy = settings["omega0"] * np.sin(4 * np.pi * x)

    # taper h from h_int at the band edge to h_ext two h_ext away
    dist = np.where(y > 0.75, y - 0.75, 0.25 - y)
    far = (y > 0.75 + 2 * h_ext) | (y < 0.25 - 2 * h_ext)
    h_near = h_int * (1 - dist / (2 * h_ext)) + h_ext * dist / (2 * h_ext)
    h = np.where(inner, h_int, np.where(far, h_ext, h_near))
    temp = np.where(inner, u_int, u_ext) / cv

    box = Box.create(0, 1, 0, 1, 0, _LZ, boundary=BoundaryType.periodic)
    state = build_state(
        x, y, z, vx, vy, 0.0, h, m_part, temp,
        settings["minDt"], const.alphamax, settings["minDt_m1"],
    )
    return state, box, const
