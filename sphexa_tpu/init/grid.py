"""Lattice coordinate generators (counterpart of main/src/init/grid.hpp)."""

import numpy as np


def regular_grid(r: float, side: int):
    """Regular cubic lattice centered on the origin, spanning [-r, r)^3.

    Same layout as the reference's regularGrid (grid.hpp:90-130): spacing
    2r/side with a half-step inset so the lattice tiles periodically.
    Returns float32 (x, y, z) of length side**3.
    """
    step = 2.0 * r / side
    line = (-r + 0.5 * step + step * np.arange(side)).astype(np.float32)
    z, y, x = np.meshgrid(line, line, line, indexing="ij")
    return x.ravel(), y.ravel(), z.ravel()
