"""Restart from a snapshot file.

Counterpart of the reference's ``main/src/init/file_init.hpp``: resume a
simulation from a dump written by sphexa_tpu.io (``--init dump.h5:<step>``,
negative step counts from the last dump).
"""

import os
from typing import Optional, Tuple

from sphexa_tpu.io import read_snapshot
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants


def parse_file_spec(spec: str) -> Tuple[str, int]:
    """Split 'path[:step]' (file_init.hpp restart selector); step defaults
    to -1 (the last dump)."""
    path, sep, step = spec.rpartition(":")
    if sep and path and _is_int(step):
        return path, int(step)
    return spec, -1


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def looks_like_file(spec: str) -> bool:
    """Heuristic used by the init factory: a --init argument that names an
    existing file (optionally with :step suffix) is a restart request.
    A sharded dump's BASE path has no file of its own — only
    .partKKKofPPP parts — and is equally a restart request."""
    from sphexa_tpu.io.snapshot import _find_parts

    path, _ = parse_file_spec(spec)
    return os.path.exists(path) or bool(_find_parts(path))


def init_from_file(
    spec: str, side: Optional[int] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Restore (state, box, const) from 'path[:step]'. ``side`` is accepted
    and ignored so the signature matches the generated test cases."""
    path, step = parse_file_spec(spec)
    state, box, const, _extra = read_snapshot(path, step=step)
    return state, box, const


def parse_split_spec(spec: str):
    """Split 'path,N' (the reference's file-split grammar,
    factory.hpp:101) -> (path, N) or None if the spec has no ',N'."""
    path, sep, num = spec.rpartition(",")
    if sep and path and _is_int(num) and int(num) >= 1:
        return path, int(num)
    return None


def init_file_split(
    path: str, num_splits: int, side: Optional[int] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Up-sample a snapshot by an integer particle-split factor
    (``--init dump.h5,N``; file_init.hpp FileSplitInit:105-246).

    Each original particle spawns ``num_splits`` particles: itself plus
    interpolated positions at evenly spaced SFC keys toward the next
    particle's key (so the new particles fill the local key gap), with
    m/N, h/N^(1/3) and all other fields replicated; the clock restarts
    (iteration 1, ttot 0) and minDt is reduced by 100*N like the
    reference.
    """
    import dataclasses

    import numpy as np
    import jax.numpy as jnp

    from sphexa_tpu.dtypes import HYDRO_DTYPE, KEY_BITS, KEY_DTYPE
    from sphexa_tpu.sfc.hilbert import hilbert_decode
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    if num_splits < 1:
        raise ValueError(
            f"number of particle splits must be a positive integer "
            f"(got {num_splits})"
        )
    state, box, const, _extra = read_snapshot(path, step=-1)
    n0 = state.n

    keys = np.asarray(
        compute_sfc_keys(state.x, state.y, state.z, box), dtype=np.uint64
    )
    order = np.argsort(keys)
    keys = keys[order]

    def sorted_np(a):
        return np.asarray(a)[order]

    x0, y0, z0 = sorted_np(state.x), sorted_np(state.y), sorted_np(state.z)

    # interpolated SFC keys between consecutive particles
    # (file_init.hpp:184-195: the last particle interpolates backward)
    key_next = np.empty_like(keys)
    key_next[:-1] = keys[1:]
    key_next[-1] = keys[-1] - (keys[-1] - keys[-2]) if n0 > 1 else keys[-1]
    denom = np.full(n0, num_splits, dtype=np.int64)
    denom[-1] += 1
    delta = (key_next.astype(np.int64) - keys.astype(np.int64)) // denom

    n1 = n0 * num_splits
    xs = np.empty(n1, np.float32)
    ys = np.empty(n1, np.float32)
    zs = np.empty(n1, np.float32)
    xs[::num_splits], ys[::num_splits], zs[::num_splits] = x0, y0, z0
    lo = np.asarray([float(box.lo[0]), float(box.lo[1]), float(box.lo[2])])
    lengths = np.asarray(box.lengths)
    max_coord = float(1 << KEY_BITS)
    for j in range(1, num_splits):
        kj = (keys.astype(np.int64) + j * delta).astype(np.uint64)
        ix, iy, iz = hilbert_decode(jnp.asarray(kj, dtype=KEY_DTYPE))
        xs[j::num_splits] = lo[0] + np.asarray(ix) * lengths[0] / max_coord
        ys[j::num_splits] = lo[1] + np.asarray(iy) * lengths[1] / max_coord
        zs[j::num_splits] = lo[2] + np.asarray(iz) * lengths[2] / max_coord

    def replicate(field, scale=1.0):
        return np.repeat(sorted_np(field) * scale, num_splits)

    inv_cbrt = float(num_splits) ** (-1.0 / 3.0)
    min_dt = float(state.min_dt) / (100.0 * num_splits)
    vx = replicate(state.vx)
    vy = replicate(state.vy)
    vz = replicate(state.vz)
    new_state = dataclasses.replace(
        state,
        x=jnp.asarray(xs), y=jnp.asarray(ys), z=jnp.asarray(zs),
        vx=jnp.asarray(vx), vy=jnp.asarray(vy), vz=jnp.asarray(vz),
        m=jnp.asarray(replicate(state.m, 1.0 / num_splits)),
        h=jnp.asarray(replicate(state.h, inv_cbrt)),
        temp=jnp.asarray(replicate(state.temp)),
        temp_lo=jnp.zeros(n1, HYDRO_DTYPE),
        alpha=jnp.asarray(replicate(state.alpha)),
        du=jnp.zeros(n1, HYDRO_DTYPE),
        du_m1=jnp.zeros(n1, HYDRO_DTYPE),
        x_m1=jnp.asarray(vx * min_dt),
        y_m1=jnp.asarray(vy * min_dt),
        z_m1=jnp.asarray(vz * min_dt),
        ttot=HYDRO_DTYPE(0.0),
        min_dt=HYDRO_DTYPE(min_dt),
        min_dt_m1=HYDRO_DTYPE(min_dt),
    )
    return new_state, box, const
