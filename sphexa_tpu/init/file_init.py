"""Restart from a snapshot file.

Counterpart of the reference's ``main/src/init/file_init.hpp``: resume a
simulation from a dump written by sphexa_tpu.io (``--init dump.h5:<step>``,
negative step counts from the last dump).
"""

import os
from typing import Optional, Tuple

from sphexa_tpu.io import read_snapshot
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants


def parse_file_spec(spec: str) -> Tuple[str, int]:
    """Split 'path[:step]' (file_init.hpp restart selector); step defaults
    to -1 (the last dump)."""
    path, sep, step = spec.rpartition(":")
    if sep and path and _is_int(step):
        return path, int(step)
    return spec, -1


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def looks_like_file(spec: str) -> bool:
    """Heuristic used by the init factory: a --init argument that names an
    existing file (optionally with :step suffix) is a restart request."""
    path, _ = parse_file_spec(spec)
    return os.path.exists(path)


def init_from_file(
    spec: str, side: Optional[int] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Restore (state, box, const) from 'path[:step]'. ``side`` is accepted
    and ignored so the signature matches the generated test cases."""
    path, step = parse_file_spec(spec)
    state, box, const, _extra = read_snapshot(path, step=step)
    return state, box, const
