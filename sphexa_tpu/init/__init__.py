"""Initial conditions for the built-in test cases.

Counterpart of the reference's ``main/src/init/``: each case is a settings
dict + coordinate generation + field initialization, producing a
ParticleState, a Box, and SimConstants. ``make_initializer`` is the factory
(init/factory.hpp:43-111) keyed by the same case names the reference CLI
accepts.
"""

import functools
from typing import Callable, Dict

from sphexa_tpu.init.evrard import (
    evrard_constants,
    init_evrard,
    init_evrard_cooling,
)
from sphexa_tpu.init.gresho_chan import gresho_chan_constants, init_gresho_chan
from sphexa_tpu.init.grid import regular_grid
from sphexa_tpu.init.isobaric_cube import (
    init_isobaric_cube,
    isobaric_cube_constants,
)
from sphexa_tpu.init.kelvin_helmholtz import (
    init_kelvin_helmholtz,
    kelvin_helmholtz_constants,
)
from sphexa_tpu.init.noh import init_noh, noh_constants
from sphexa_tpu.init.sedov import init_sedov, sedov_constants
from sphexa_tpu.init.turbulence import init_turbulence, turbulence_constants
from sphexa_tpu.init.wind_shock import init_wind_shock, wind_shock_constants

# case name -> init function; the name set matches the reference's --init
# choices (main/src/init/factory.hpp:59-100)
CASES: Dict[str, Callable] = {
    "sedov": init_sedov,
    "noh": init_noh,
    "evrard": init_evrard,
    "gresho-chan": init_gresho_chan,
    "isobaric-cube": init_isobaric_cube,
    "kelvin-helmholtz": init_kelvin_helmholtz,
    "wind-shock": init_wind_shock,
    "turbulence": init_turbulence,
    "evrard-cooling": init_evrard_cooling,
}


def split_case_spec(name: str):
    """'case:settings.json' -> (case, settings_path); otherwise (name, None).
    SINGLE source of the spec grammar — main.py keys observables/dump
    metadata on the same parse."""
    if ":" in name:
        case, _, settings_path = name.partition(":")
        if case in CASES:
            return case, settings_path
    return name, None


def make_initializer(name: str) -> Callable:
    """Look up a test case by reference CLI name, or build a file-restart
    initializer for 'path[:step]' arguments (init/factory.hpp:43-111).

    ``case:settings.json`` appends a JSON settings file whose keys override
    the case defaults (the reference's ``--init sedov:my_settings`` path,
    factory.hpp:47-48).
    """
    if name in CASES:
        return CASES[name]

    case, settings_path = split_case_spec(name)
    if settings_path is not None:
        import json

        try:
            with open(settings_path) as f:
                overrides = json.load(f)
        except OSError as e:
            raise ValueError(f"cannot read settings file {settings_path}: {e}")
        except json.JSONDecodeError as e:
            raise ValueError(f"invalid JSON in {settings_path}: {e}")
        if not isinstance(overrides, dict):
            raise ValueError(f"{settings_path} must hold a JSON object")
        return functools.partial(CASES[case], overrides=overrides)

    from sphexa_tpu.init.file_init import (
        init_file_split,
        init_from_file,
        looks_like_file,
        parse_split_spec,
    )

    split = parse_split_spec(name)
    if split is not None and looks_like_file(split[0]):
        # 'path,N' particle-split up-sampling (factory.hpp:101)
        return functools.partial(init_file_split, split[0], split[1])
    if looks_like_file(name):
        return functools.partial(init_from_file, name)
    raise ValueError(
        f"unknown test case '{name}' (not a case name in {sorted(CASES)}, "
        "not 'case:settings.json', not 'file,N' splitting, and not an "
        "existing snapshot file)"
    )


__all__ = [
    "CASES",
    "make_initializer",
    "split_case_spec",
    "regular_grid",
    "init_sedov", "sedov_constants",
    "init_noh", "noh_constants",
    "init_evrard", "evrard_constants",
    "init_evrard_cooling",
    "init_gresho_chan", "gresho_chan_constants",
    "init_isobaric_cube", "isobaric_cube_constants",
    "init_kelvin_helmholtz", "kelvin_helmholtz_constants",
    "init_wind_shock", "wind_shock_constants",
    "init_turbulence", "turbulence_constants",
]
