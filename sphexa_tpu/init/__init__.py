"""Initial conditions for the built-in test cases.

Counterpart of the reference's ``main/src/init/``: each case is a settings
dict + coordinate generation + field initialization, producing a
ParticleState, a Box, and SimConstants.
"""

from sphexa_tpu.init.grid import regular_grid
from sphexa_tpu.init.sedov import init_sedov, sedov_constants

__all__ = ["regular_grid", "init_sedov", "sedov_constants"]
