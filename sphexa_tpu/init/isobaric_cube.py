"""Isobaric cube initial conditions.

Physics-equivalent of the reference's ``main/src/init/isobaric_cube_init.hpp``:
a dense cube (rhoInt = 8) in pressure equilibrium with its surroundings
(rhoExt = 1, same p). A perfect scheme keeps it static; spurious surface
tension at the contact discontinuity deforms it.
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import (
    compress_center_cube,
    compute_stretch_factor,
    jittered_lattice,
)
from sphexa_tpu.init.utils import build_state, h_from_density, settings_to_constants
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def isobaric_cube_constants() -> Dict[str, float]:
    """Test-case settings (isobaric_cube_init.hpp IsobaricCubeConstants)."""
    return {
        "r": 0.25, "rDelta": 0.25, "dim": 3, "gamma": 5.0 / 3.0,
        "rhoExt": 1.0, "rhoInt": 8.0, "pIsobaric": 2.5,
        "minDt": 1e-4, "minDt_m1": 1e-4, "epsilon": 1e-15,
        "pairInstability": 0.0, "mui": 10.0, "gravConstant": 0.0,
        "ng0": 100, "ngmax": 150,
    }


def init_isobaric_cube(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Setup per IsobaricCubeGlass::init: uniform fill of the periodic box
    [-2r, 2r]^3, then compress the center [-s, s]^3 into [-r, r]^3 so the
    density contrast is rhoInt/rhoExt; equal-mass particles throughout."""
    settings = isobaric_cube_constants()
    if overrides:
        settings.update(overrides)

    r = settings["r"]
    r_ext = 2 * r
    rho_int, rho_ext = settings["rhoInt"], settings["rhoExt"]

    x, y, z = jittered_lattice(
        (-r_ext, -r_ext, -r_ext), (r_ext, r_ext, r_ext), (side, side, side)
    )
    n = x.shape[0]

    s = compute_stretch_factor(r, r_ext, rho_int / rho_ext)
    x, y, z = compress_center_cube(
        x, y, z, r, s, r_ext, eps=settings["pairInstability"]
    )

    n_internal = n * (s / r_ext) ** 3
    m_part = (2 * r) ** 3 * rho_int / n_internal

    const = settings_to_constants(settings)
    h_int = h_from_density(settings["ng0"], m_part, rho_int)
    h_ext = h_from_density(settings["ng0"], m_part, rho_ext)

    gamma = settings["gamma"]
    p_iso = settings["pIsobaric"]
    u_int = p_iso / (gamma - 1.0) / rho_int
    u_ext = p_iso / (gamma - 1.0) / rho_ext
    eps = settings["epsilon"]
    cv = ideal_gas_cv(settings["mui"], gamma)

    ax, ay, az = np.abs(x), np.abs(y), np.abs(z)
    outside = (ax > r + eps) | (ay > r + eps) | (az > r + eps)
    far_out = (ax > r + 2 * h_ext) | (ay > r + 2 * h_ext) | (az > r + 2 * h_ext)
    dist = np.maximum.reduce([ax - r, ay - r, az - r])
    # taper h from h_int at the cube surface to h_ext two h_ext away
    h_near = h_int * (1 - dist / (2 * h_ext)) + h_ext * dist / (2 * h_ext)
    h = np.where(outside, np.where(far_out, h_ext, h_near), h_int)
    temp = np.where(outside, u_ext, u_int) / cv

    box = Box.create(-r_ext, r_ext, boundary=BoundaryType.periodic)
    state = build_state(
        x, y, z, 0.0, 0.0, 0.0, h, m_part, temp,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
