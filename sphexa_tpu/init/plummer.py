"""Synthetic Plummer-sphere sample: the centrally concentrated mass
distribution that stresses Barnes-Hut MAC classification (deep,
strongly non-uniform trees). Not a reference init case — a gravity
benchmark/test IC shared by bench.py and scripts/bench_gravity_scale.py.
"""

import numpy as np


def sample_plummer(n: int, a: float = 1.0, rmax: float = 8.0,
                   seed: int = 3):
    """(x, y, z, m) float32 arrays of an n-particle Plummer sphere with
    scale radius ``a``, radius-clipped at ``rmax`` (total mass 1)."""
    rng = np.random.default_rng(seed)
    u = rng.uniform(0.0, 1.0, n)
    r = a / np.sqrt(np.maximum(u ** (-2.0 / 3.0) - 1.0, 1e-12))
    r = np.minimum(r, rmax)
    cth = rng.uniform(-1.0, 1.0, n)
    sth = np.sqrt(1.0 - cth * cth)
    phi = rng.uniform(0.0, 2.0 * np.pi, n)
    x = (r * sth * np.cos(phi)).astype(np.float32)
    y = (r * sth * np.sin(phi)).astype(np.float32)
    z = (r * cth).astype(np.float32)
    m = np.full(n, 1.0 / n, np.float32)
    return x, y, z, m
