"""Sedov-Taylor blast wave initial conditions.

Physics-equivalent of the reference's ``main/src/init/sedov_init.hpp`` +
``sedov_constants.hpp``: a uniform-density periodic cube with a Gaussian
thermal-energy spike at the origin. The semi-analytic solution makes this
the primary hydrodynamics correctness benchmark (BASELINE.md).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.grid import regular_grid
from sphexa_tpu.init.utils import build_state, settings_to_constants, sphere_h_init
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def sedov_constants() -> Dict[str, float]:
    """Test-case settings (sedov_constants.hpp:11-21)."""
    c = {
        "dim": 3, "gamma": 5.0 / 3.0, "omega": 0.0, "r0": 0.0, "r1": 0.5,
        "mTotal": 1.0, "energyTotal": 1.0, "width": 0.1, "rho0": 1.0,
        "u0": 1e-8, "p0": 0.0, "vr0": 0.0, "cs0": 0.0,
        "minDt": 1e-6, "minDt_m1": 1e-6, "gravConstant": 0.0,
        "ng0": 100, "ngmax": 150, "mui": 10.0,
    }
    c["ener0"] = c["energyTotal"] / np.pi**1.5 / c["width"] ** 3
    return c


def init_sedov(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Build the Sedov grid case for side**3 particles (sedov_init.hpp:48-133)."""
    settings = sedov_constants()
    if overrides:
        settings.update(overrides)
        if "ener0" not in overrides:
            # re-derive the spike amplitude from the (possibly overridden)
            # energyTotal/width — ener0 precomputed in sedov_constants()
            # would silently pin the default blast energy
            settings["ener0"] = (
                settings["energyTotal"] / np.pi**1.5 / settings["width"] ** 3
            )

    n = side**3
    r = settings["r1"]
    box = Box.create(-r, r, boundary=BoundaryType.periodic)

    x, y, z = regular_grid(r, side)

    total_volume = (2 * r) ** 3
    h_init = sphere_h_init(settings["ng0"], total_volume, n)
    m_part = settings["mTotal"] / n

    const = settings_to_constants(settings)

    cv = ideal_gas_cv(settings["mui"], settings["gamma"])
    r2 = x**2 + y**2 + z**2
    u = settings["ener0"] * np.exp(-(r2 / settings["width"] ** 2)) + settings["u0"]
    temp = u / cv

    state = build_state(
        x, y, z, 0.0, 0.0, 0.0, h_init, m_part, temp,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
