"""Wind-shock (blob) initial conditions.

Physics-equivalent of the reference's ``main/src/init/wind_shock_init.hpp``:
a dense spherical cloud (rhoInt = 10) embedded in a supersonic wind
(rhoExt = 1, vx = 2.7); the cloud is ablated and mixed, and the surviving
cloud-mass fraction is the observable (wind_bubble_fraction.hpp).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import jittered_lattice
from sphexa_tpu.init.utils import build_state, h_from_density, settings_to_constants
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def wind_shock_constants() -> Dict[str, float]:
    """Test-case settings (wind_shock_init.hpp WindShockConstants)."""
    return {
        "r": 0.125, "rSphere": 0.025, "rhoInt": 10.0, "rhoExt": 1.0,
        "uExt": 1.5, "vxExt": 2.7, "vyExt": 0.0, "vzExt": 0.0,
        "dim": 3, "gamma": 5.0 / 3.0, "minDt": 1e-10, "minDt_m1": 1e-10,
        "Kcour": 0.4, "epsilon": 0.0, "mui": 10.0, "gravConstant": 0.0,
        "ng0": 100, "ngmax": 150, "wind-shock": 1.0,
    }


def init_wind_shock(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Blob-in-wind setup (WindShockGlass::init): periodic box
    (0,8r) x (0,2r)^2, ambient lattice with the sphere at (r,r,r) carved
    out, refilled by a 10x-denser blob lattice; equal-mass particles."""
    settings = wind_shock_constants()
    if overrides:
        settings.update(overrides)

    r, r_sphere = settings["r"], settings["rSphere"]
    rho_int, rho_ext = settings["rhoInt"], settings["rhoExt"]
    center = (r, r, r)

    # ambient wind region: density rho_ext, ~4*side^3 cells over (8r,2r,2r)
    x, y, z = jittered_lattice(
        (0, 0, 0), (8 * r, 2 * r, 2 * r), (4 * side, side, side), seed=11
    )
    rpos2 = (x - center[0]) ** 2 + (y - center[1]) ** 2 + (z - center[2]) ** 2
    keep = rpos2 > r_sphere**2
    x, y, z = x[keep], y[keep], z[keep]

    # blob: number density rho_int/rho_ext times the ambient one
    ratio = rho_int / rho_ext
    nd_ext = side**3 / (2 * r) ** 3
    a_blob = (nd_ext * ratio) ** (-1.0 / 3.0)
    nb = max(1, round(2 * r_sphere / a_blob))
    xb, yb, zb = jittered_lattice(
        (r - r_sphere,) * 3, (r + r_sphere,) * 3, (nb, nb, nb), seed=12
    )
    rb2 = (xb - center[0]) ** 2 + (yb - center[1]) ** 2 + (zb - center[2]) ** 2
    inside = rb2 < r_sphere**2
    xb, yb, zb = xb[inside], yb[inside], zb[inside]
    n_blob = xb.shape[0]

    x = np.concatenate([x, xb])
    y = np.concatenate([y, yb])
    z = np.concatenate([z, zb])

    blob_volume = 4.0 / 3.0 * np.pi * r_sphere**3
    m_part = blob_volume * rho_int / n_blob

    const = settings_to_constants(settings)
    u_ext = settings["uExt"]
    u_int = u_ext / (rho_int / rho_ext)
    h_int = h_from_density(settings["ng0"], m_part, rho_int)
    h_ext = h_from_density(settings["ng0"], m_part, rho_ext)
    k = settings["ngmax"] / r
    cv = ideal_gas_cv(settings["mui"], settings["gamma"])
    eps = settings["epsilon"]

    rpos = np.sqrt(
        (x - center[0]) ** 2 + (y - center[1]) ** 2 + (z - center[2]) ** 2
    )
    in_cloud = rpos <= r_sphere + eps
    # tanh taper of h just outside the cloud surface (wind_shock_init.hpp:107)
    h_taper = h_int + 0.5 * (h_ext - h_int) * (
        1.0 + np.tanh(k * (rpos - r_sphere - h_ext))
    )
    far = rpos > r_sphere + 2 * h_ext
    h = np.where(in_cloud, h_int, np.where(far, h_ext, h_taper))
    temp = np.where(in_cloud, u_int, u_ext) / cv
    vx = np.where(in_cloud, 0.0, settings["vxExt"])
    vy = np.where(in_cloud, 0.0, settings["vyExt"])
    vz = np.where(in_cloud, 0.0, settings["vzExt"])

    box = Box.create(0, 8 * r, 0, 2 * r, 0, 2 * r, boundary=BoundaryType.periodic)
    state = build_state(
        x, y, z, vx, vy, vz, h, m_part, temp,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
