"""Driven-turbulence box initial conditions.

Physics-equivalent of the reference's ``main/src/init/turbulence_init.hpp``:
a uniform, nearly-isothermal (gamma = 1.001) periodic box at rest; the
TurbVe propagator's OU stirring drives it to a target RMS Mach number
(observable: turbulence_mach_rms.hpp).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import jittered_lattice
from sphexa_tpu.init.utils import build_state, settings_to_constants, sphere_h_init
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def turbulence_constants() -> Dict[str, float]:
    """Test-case settings (turbulence_init.hpp TurbulenceConstants)."""
    return {
        "solWeight": 0.5, "stMaxModes": 100000, "Lbox": 1.0,
        "stEnergyPrefac": 5.0e-3, "stMachVelocity": 0.3,
        "minDt": 1e-4, "minDt_m1": 1e-4,
        "rngSeed": 251299, "stSpectForm": 1, "mTotal": 1.0,
        "powerLawExp": 5.0 / 3.0, "anglesExp": 2.0,
        "gamma": 1.001, "mui": 0.62, "u0": 1000.0, "Kcour": 0.4,
        "gravConstant": 0.0, "ng0": 100, "ngmax": 150, "turbulence": 1.0,
    }


def init_turbulence(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Uniform periodic box [-L/2, L/2]^3 at rest, u = u0
    (initTurbulenceHydroFields)."""
    settings = turbulence_constants()
    if overrides:
        settings.update(overrides)
    lbox = settings["Lbox"]
    half = lbox / 2.0

    x, y, z = jittered_lattice(
        (-half, -half, -half), (half, half, half), (side, side, side),
        seed=int(settings["rngSeed"]) % (2**31),
    )
    n = x.shape[0]

    const = settings_to_constants(settings)
    m_part = settings["mTotal"] / n
    h_init = sphere_h_init(settings["ng0"], lbox**3, n)
    cv = ideal_gas_cv(settings["mui"], settings["gamma"])
    temp0 = settings["u0"] / cv

    box = Box.create(-half, half, boundary=BoundaryType.periodic)
    state = build_state(
        x, y, z, 0.0, 0.0, 0.0, h_init, m_part, temp0,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
