"""Particle-lattice assembly helpers for initial conditions.

Counterpart of the reference's glass-block machinery (main/src/init/
utils.hpp readTemplateBlock + grid.hpp assembleCuboid/cutSphere/
cappedPyramidStretch/computeStretchFactor). The reference tiles a
pre-relaxed 'glass' template read from an HDF5 file; since the template is
an external artifact, this module generates an equivalent irregular-but-
uniform block procedurally: a lattice with deterministic sub-spacing
jitter, which breaks the grid axes' alignment (the property the glass
provides) while keeping the distribution statistically uniform and free of
close pairs.
"""

from typing import Tuple

import numpy as np


def jittered_lattice(
    lo, hi, counts, seed: int = 42, jitter: float = 0.2
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Jittered lattice with ``counts=(nx,ny,nz)`` points spanning the cuboid
    [lo, hi) — the generator form of assembleCuboid (grid.hpp:201) for
    anisotropic boxes (thin slabs, multi-layer setups).

    When a glass template is installed (``set_glass_template``, the CLI's
    --glass flag), the template is tiled instead — every built-in case
    then gets the relaxed glass IC exactly like the reference factory."""
    if _ACTIVE_TEMPLATE is not None:
        return assemble_glass_cuboid(_ACTIVE_TEMPLATE, lo, hi, counts)
    rng = np.random.default_rng(seed)
    lo = np.asarray(lo, np.float64)
    hi = np.asarray(hi, np.float64)
    steps = (hi - lo) / np.asarray(counts, np.float64)
    lines = [
        lo[d] + steps[d] * (0.5 + np.arange(counts[d])) for d in range(3)
    ]
    zz, yy, xx = np.meshgrid(lines[2], lines[1], lines[0], indexing="ij")
    n = int(np.prod(counts))
    out = []
    for d, grid in enumerate((xx, yy, zz)):
        delta = rng.uniform(-jitter, jitter, size=n) * steps[d]
        out.append(lo[d] + np.mod(grid.ravel() + delta - lo[d], hi[d] - lo[d]))
    return out[0], out[1], out[2]


def cut_sphere(r: float, x, y, z, center=None):
    """Keep only particles inside radius r (grid.hpp cutSphere)."""
    if center is None:
        center = (0.0, 0.0, 0.0)
    keep = (x - center[0]) ** 2 + (y - center[1]) ** 2 + (z - center[2]) ** 2 <= r * r
    return x[keep], y[keep], z[keep]


def contract_rho_profile(x, y, z):
    """Multiply coordinates by sqrt(r): uniform sphere -> rho ~ 1/r profile
    (evrard_init.hpp contractRhoProfile)."""
    radius = np.sqrt(x * x + y * y + z * z)
    c = np.sqrt(radius)
    return x * c, y * c, z * c


def compute_stretch_factor(r_int: float, r_ext: float, rho_ratio: float) -> float:
    """Radius s such that contracting [-s,s]^3 into the inner cube and
    expanding the rest yields density ratio rho_ratio (grid.hpp:399-409)."""
    hc = r_int**3
    rc = r_ext**3
    s = np.cbrt(rho_ratio * hc * rc / (rc - hc + rho_ratio * hc))
    assert r_int < s < r_ext
    return float(s)


def capped_pyramid_stretch(x, y, z, r_int: float, s: float, r_ext: float):
    """Vectorized scale factor moving outer-shell points toward the origin
    while keeping density constant (grid.hpp:334-378). Applies to points
    with max|coord| > s; callers mask accordingly."""
    ax = np.stack([np.abs(x), np.abs(y), np.abs(z)])
    mx = np.maximum(ax.max(axis=0), 1e-30)
    radius = np.sqrt((ax**2).sum(axis=0))
    # ray-cube intersection distances: outer cube, stretch cube, inner cube
    rp = radius * (r_ext / mx)
    sp = radius * (s / mx)
    hp = radius * (r_int / mx)
    expo = 0.75
    a = (rp - hp) / np.power(np.maximum(rp - sp, 1e-30), expo)
    new_radius = a * np.power(np.maximum(radius - sp, 0.0), expo) + hp
    return new_radius / radius


def compress_center_cube(x, y, z, r_int: float, s: float, r_ext: float, eps=0.0):
    """Create a high-density center cube: contract [-s,s]^3 by r_int/s and
    pull the surrounding shell inward (isobaric_cube_init.hpp:129-152)."""
    inner = (
        (np.abs(x) - s <= eps) & (np.abs(y) - s <= eps) & (np.abs(z) - s <= eps)
    )
    scale = np.where(
        inner, r_int / s, capped_pyramid_stretch(x, y, z, r_int, s, r_ext)
    )
    return x * scale, y * scale, z * scale


# --- glass-block templates (utils.hpp readTemplateBlock + grid.hpp
# assembleCuboid): an externally relaxed particle block, tiled to the
# requested resolution. The CLI's --glass flag installs one globally
# (matching the reference, where the template applies to whichever case
# is initialized); when none is installed the procedural jittered
# lattice above is used.

_ACTIVE_TEMPLATE = None


def generate_glass_template(
    side: int = 16, relax_steps: int = 40, seed: int = 7,
):
    """Generate a relaxed glass block in [0,1)^3 (the generate-once half
    of the reference's template pipeline; the reference ships pre-relaxed
    HDF5 blocks, main/src/init/utils.hpp:100-168 only reads them).

    Classic damped relaxation: evolve a jittered periodic lattice with
    the std SPH pipeline at uniform internal energy and ZERO the
    velocities after every step — pressure gradients from density
    fluctuations push particles apart until the distribution is glassy
    (uniform density, no lattice axes). Returns (x, y, z) in [0, 1)^3.
    """
    import dataclasses as _dc

    import jax

    from sphexa_tpu.simulation import Simulation
    from sphexa_tpu.init import init_sedov

    # reuse the sedov periodic-box scaffolding at uniform energy: a
    # uniform-pressure periodic gas whose only dynamics is relaxation
    state, box, const = init_sedov(side)
    state = _dc.replace(
        state,
        temp=jax.numpy.ones_like(state.temp),
        du=jax.numpy.zeros_like(state.du),
        du_m1=jax.numpy.zeros_like(state.du_m1),
    )
    sim = Simulation(state, box, const, prop="std", block=2048)
    z3 = lambda a: jax.numpy.zeros_like(a)
    for _ in range(relax_steps):
        sim.step()
        # damp: kill velocities (and energy drift) every step
        sim.state = _dc.replace(
            sim.state,
            vx=z3(sim.state.vx), vy=z3(sim.state.vy), vz=z3(sim.state.vz),
            temp=jax.numpy.ones_like(sim.state.temp),
            du=z3(sim.state.du), du_m1=z3(sim.state.du_m1),
        )
    x = np.asarray(sim.state.x)
    y = np.asarray(sim.state.y)
    z = np.asarray(sim.state.z)
    lo = np.asarray(sim.box.lo, np.float64)
    lengths = np.asarray(sim.box.lengths, np.float64)
    return (
        (x - lo[0]) / lengths[0] % 1.0,
        (y - lo[1]) / lengths[1] % 1.0,
        (z - lo[2]) / lengths[2] % 1.0,
    )


def write_template_block(path: str, x, y, z):
    """Save a template block to HDF5 (readable by read_template_block
    and by the reference's readTemplateBlock)."""
    import h5py

    with h5py.File(path, "w") as f:
        f.create_dataset("x", data=np.asarray(x, np.float64))
        f.create_dataset("y", data=np.asarray(y, np.float64))
        f.create_dataset("z", data=np.asarray(z, np.float64))


def read_template_block(path: str):
    """Read the x/y/z template coordinates from an HDF5 file (either a
    dump with Step#n groups or flat root datasets) and normalize them to
    [0, 1)^3 (readTemplateBlock, main/src/init/utils.hpp:73-86)."""
    import h5py

    with h5py.File(path, "r") as f:
        steps = sorted(
            (k for k in f.keys() if k.startswith("Step#")),
            key=lambda k: int(k.split("#")[1]),
        )
        g = f[steps[-1]] if steps else f
        x = np.asarray(g["x"], np.float64)
        y = np.asarray(g["y"], np.float64)
        z = np.asarray(g["z"], np.float64)
    out = []
    for v in (x, y, z):
        lo, hi = v.min(), v.max()
        extent = max(hi - lo, 1e-30)
        # map into [0,1) with a half-spacing margin so tiled copies don't
        # produce coincident points at tile faces
        n_lin = max(len(v) ** (1.0 / 3.0), 2.0)
        out.append((v - lo) / extent * (1.0 - 1.0 / n_lin) + 0.5 / n_lin)
    return tuple(out)


def set_glass_template(path):
    """Install (or clear, with None) the global glass template consulted
    by ``jittered_lattice``."""
    global _ACTIVE_TEMPLATE
    _ACTIVE_TEMPLATE = read_template_block(path) if path else None


def assemble_glass_cuboid(template, lo, hi, counts):
    """Tile the normalized template into [lo, hi) with per-dimension
    multiplicity chosen to approximate ``counts`` particles
    (assembleCuboid, grid.hpp:201; multiplicity rule factory-side,
    noh_init.hpp:127-129)."""
    tx, ty, tz = template
    b_lin = max(len(tx) ** (1.0 / 3.0), 1.0)
    lo = np.asarray(lo, np.float64)
    hi = np.asarray(hi, np.float64)
    mx, my, mz = (max(1, int(np.rint(c / b_lin))) for c in counts)
    # full 3-D tiling via broadcasting: (mx, my, mz, B) tile offsets
    ox = np.arange(mx)[:, None, None, None]
    oy = np.arange(my)[None, :, None, None]
    oz = np.arange(mz)[None, None, :, None]
    X = lo[0] + (tx[None, None, None, :] + ox) * ((hi[0] - lo[0]) / mx)
    Y = lo[1] + (ty[None, None, None, :] + oy) * ((hi[1] - lo[1]) / my)
    Z = lo[2] + (tz[None, None, None, :] + oz) * ((hi[2] - lo[2]) / mz)
    X, Y, Z = np.broadcast_arrays(X, Y, Z)
    return (
        np.ascontiguousarray(X.ravel()),
        np.ascontiguousarray(Y.ravel()),
        np.ascontiguousarray(Z.ravel()),
    )
