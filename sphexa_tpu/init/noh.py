"""Noh spherical implosion initial conditions.

Physics-equivalent of the reference's ``main/src/init/noh_init.hpp``: a
uniform-density sphere with unit radial inflow velocity; a standing shock
forms at the origin with a known analytic post-shock state, making this the
second hydrodynamics correctness benchmark (BASELINE.md Noh L1 rows).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import cut_sphere, jittered_lattice
from sphexa_tpu.init.utils import build_state, settings_to_constants, sphere_h_init
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv


def noh_constants() -> Dict[str, float]:
    """Test-case settings (noh_init.hpp nohConstants)."""
    return {
        "r0": 0.0, "r1": 0.5, "mTotal": 1.0, "dim": 3, "gamma": 5.0 / 3.0,
        "rho0": 1.0, "u0": 1e-20, "p0": 0.0, "vr0": -1.0, "cs0": 0.0,
        "minDt": 1e-4, "minDt_m1": 1e-4, "gravConstant": 0.0,
        "ng0": 100, "ngmax": 150, "mui": 10.0,
    }


def init_noh(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Glass-sphere Noh setup (noh_init.hpp NohGlassSphere::init): fill the
    cube [-r1, r1]^3 with ~side^3 particles, cut the inscribed sphere, point
    all velocities at the origin."""
    settings = noh_constants()
    if overrides:
        settings.update(overrides)
    r = settings["r1"]

    x, y, z = jittered_lattice((-r, -r, -r), (r, r, r), (side, side, side))
    x, y, z = cut_sphere(r, x, y, z)
    n = x.shape[0]

    const = settings_to_constants(settings)
    total_volume = 4.0 * np.pi / 3.0 * r**3
    h_init = sphere_h_init(settings["ng0"], total_volume, n)
    m_part = settings["mTotal"] / n

    radius = np.maximum(np.sqrt(x * x + y * y + z * z), 1e-10)
    vr0 = settings["vr0"]
    vx, vy, vz = vr0 * x / radius, vr0 * y / radius, vr0 * z / radius

    cv = ideal_gas_cv(settings["mui"], settings["gamma"])
    temp0 = settings["u0"] / cv

    box = Box.create(-r, r, boundary=BoundaryType.open)
    state = build_state(
        x, y, z, vx, vy, vz, h_init, m_part, temp0,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
