"""Gresho-Chan vortex initial conditions.

Physics-equivalent of the reference's ``main/src/init/gresho_chan.hpp``: a
stationary 2D vortex (thin periodic slab in z) whose centrifugal force is
exactly balanced by the pressure gradient — any decay of the azimuthal
velocity profile measures numerical viscosity.
"""

from typing import Dict, Optional, Tuple

import numpy as np

from sphexa_tpu.init.glass import jittered_lattice
from sphexa_tpu.init.utils import build_state, h_from_density, settings_to_constants
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants, ideal_gas_cv

_ZHALF = 0.0555  # slab half-thickness (gresho_chan.hpp:143)


def gresho_chan_constants() -> Dict[str, float]:
    """Test-case settings (gresho_chan.hpp GreshoChanSettings)."""
    return {
        "R1": 0.2, "v0": 1.0, "P0": 5.0, "gamma": 5.0 / 3.0, "mTotal": 1.0,
        "minDt": 1e-7, "minDt_m1": 1e-7, "rho": 1.0, "Kcour": 0.2,
        "ng0": 100, "ngmax": 150, "gravConstant": 0.0, "mui": 10.0,
    }


def init_gresho_chan(
    side: int, overrides: Optional[Dict[str, float]] = None
) -> Tuple[ParticleState, Box, SimConstants]:
    """Thin-slab vortex setup (gresho_chan.hpp:133-161): periodic box
    (-0.5,0.5)^2 x (-zh, zh); azimuthal velocity rises linearly to v0 at
    psi = r/R1 = 1, falls back to 0 at psi = 2; pressure balances."""
    settings = gresho_chan_constants()
    if overrides:
        settings.update(overrides)

    # slab lattice with ~side^3 total particles at isotropic spacing
    lz = 2 * _ZHALF
    spacing = (1.0 * 1.0 * lz / side**3) ** (1.0 / 3.0)
    nx = max(1, round(1.0 / spacing))
    nz = max(1, round(lz / spacing))
    x, y, z = jittered_lattice(
        (-0.5, -0.5, -_ZHALF), (0.5, 0.5, _ZHALF), (nx, nx, nz)
    )
    n = x.shape[0]

    const = settings_to_constants(settings)
    rho = settings["rho"]
    m_part = 1.0 * 1.0 * lz * rho / n
    h_init = h_from_density(settings["ng0"], m_part, rho)

    R1, v0, P0 = settings["R1"], settings["v0"], settings["P0"]
    gamma = settings["gamma"]
    psi = np.sqrt(x * x + y * y) / R1
    theta = np.arctan2(y, x)

    p = np.where(
        psi <= 1.0,
        P0 + 4 * v0 * v0 * psi * psi / 8,
        np.where(
            psi <= 2.0,
            P0 + 4 * v0 * v0 * (psi * psi / 8 - psi + np.log(np.maximum(psi, 1e-30)) + 1),
            P0 + 4 * v0 * v0 * (np.log(2.0) - 0.5),
        ),
    )
    v = np.where(psi <= 1.0, v0 * psi, np.where(psi <= 2.0, v0 * (2 - psi), 0.0))

    cv = ideal_gas_cv(settings["mui"], gamma)
    temp = p / ((gamma - 1.0) * rho) / cv
    vx = -v * np.sin(theta)
    vy = v * np.cos(theta)

    box = Box.create(
        -0.5, 0.5, -0.5, 0.5, -_ZHALF, _ZHALF, boundary=BoundaryType.periodic
    )
    state = build_state(
        x, y, z, vx, vy, 0.0, h_init, m_part, temp,
        settings["minDt"], const.alphamin, settings["minDt_m1"],
    )
    return state, box, const
