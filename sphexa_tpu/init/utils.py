"""Shared initial-condition field assembly.

The counterpart of the common tail of every reference init function
(initXxxFields in main/src/init/*.hpp): fill masses/smoothing lengths,
derive temperature from internal energy, and seed the integrator history
(x_m1 = vx * minDt — positions are advanced from stored deltas,
sph/positions.hpp:66-80).
"""

from typing import Dict, Optional

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.dtypes import HYDRO_DTYPE
from sphexa_tpu.sph.particles import ParticleState, SimConstants


def settings_to_constants(settings: Dict[str, float]) -> SimConstants:
    """Map reference-style settings keys onto SimConstants (the analog of
    BuiltinWriter funneling the settings map into ParticlesData attributes,
    main/src/init/settings.hpp:60-80)."""
    kw = {}
    key_map = {
        "ng0": ("ng0", int),
        "ngmax": ("ngmax", int),
        "gamma": ("gamma", float),
        "mui": ("mui", float),
        "gravConstant": ("g", float),
        "Kcour": ("k_cour", float),
        "Krho": ("k_rho", float),
        "alphamin": ("alphamin", float),
        "alphamax": ("alphamax", float),
    }
    for skey, (field, cast) in key_map.items():
        if skey in settings:
            kw[field] = cast(settings[skey])
    return SimConstants(**kw).normalized()


def build_state(
    x, y, z, vx, vy, vz, h, m, temp, min_dt: float, alpha,
    min_dt_m1: Optional[float] = None,
) -> ParticleState:
    """Assemble a ParticleState from per-particle numpy/jnp fields.

    Scalars for vx/vy/vz/h/m/temp/alpha broadcast to the particle count.
    """
    n = np.asarray(x).shape[0]
    f32 = lambda a: (
        jnp.full(n, float(a), HYDRO_DTYPE)
        if np.ndim(a) == 0
        else jnp.asarray(a, HYDRO_DTYPE)
    )
    vx, vy, vz = f32(vx), f32(vy), f32(vz)
    zeros = jnp.zeros(n, HYDRO_DTYPE)
    return ParticleState(
        x=f32(x), y=f32(y), z=f32(z),
        x_m1=vx * min_dt, y_m1=vy * min_dt, z_m1=vz * min_dt,
        vx=vx, vy=vy, vz=vz,
        h=f32(h), m=f32(m), temp=f32(temp), temp_lo=zeros,
        du=zeros, du_m1=zeros, alpha=f32(alpha),
        ttot=HYDRO_DTYPE(0.0),
        min_dt=HYDRO_DTYPE(min_dt),
        min_dt_m1=HYDRO_DTYPE(min_dt_m1 if min_dt_m1 is not None else min_dt),
    )


def sphere_h_init(ng0: float, volume: float, n: int) -> float:
    """Smoothing length so each particle sees ~ng0 neighbors in a uniform
    distribution of n particles over ``volume`` (the recurring
    0.5 * cbrt(3 ng0 V / (4 pi n)) expression of the init files)."""
    return float(np.cbrt(3.0 / (4 * np.pi) * ng0 * volume / n) * 0.5)


def h_from_density(ng0: float, m_part: float, rho: float) -> float:
    """h for ~ng0 neighbors at mass density rho (0.5 cbrt(3 ng0 m/(4 pi rho)))."""
    return float(0.5 * np.cbrt(3.0 * ng0 * m_part / (4.0 * np.pi * rho)))
