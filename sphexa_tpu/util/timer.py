"""Wall-clock phase timing + profile series.

Counterpart of the reference's ``main/src/util/timer.hpp`` (per-substep
Timer printed each iteration, dumpable as a timing series with --profile,
ipropagator.hpp:80-119). The TPU step is one fused XLA program, so the
measurable phases are coarser: step (device compute incl. any recompile),
observables, output. The profile dump is an npz timeseries instead of the
reference's HDF5 group.
"""

import time
from typing import Dict, List

import numpy as np


class Timer:
    """Accumulates named wall-clock laps within one iteration."""

    def __init__(self):
        self.laps: Dict[str, float] = {}
        self._t = time.perf_counter()

    def start(self):
        self._t = time.perf_counter()

    def step(self, name: str) -> float:
        """Record time since the last mark under ``name`` (timer.hpp:46)."""
        now = time.perf_counter()
        elapsed = now - self._t
        self.laps[name] = self.laps.get(name, 0.0) + elapsed
        self._t = now
        return elapsed

    def pop(self) -> Dict[str, float]:
        out = self.laps
        self.laps = {}
        return out


class ProfileRecorder:
    """Per-iteration timing/metric rows; saved with --profile
    (ipropagator.hpp:83-87 writes the analogous HDF5 series)."""

    def __init__(self):
        self.rows: List[Dict[str, float]] = []

    def record(self, iteration: int, laps: Dict[str, float], **metrics):
        self.rows.append({"iteration": float(iteration), **laps, **metrics})

    def save(self, path: str, substeps=None):
        """Write the per-iteration series (+ optional one-shot substep
        breakdown, stored as substep_<name> scalars)."""
        if not self.rows and not substeps:
            return
        keys = sorted({k for row in self.rows for k in row})
        arrays = {
            k: np.array([row.get(k, np.nan) for row in self.rows]) for k in keys
        }
        for k, v in (substeps or {}).items():
            arrays[f"substep_{k}"] = np.float64(v)
        np.savez(path, **arrays)

    def summary(self) -> Dict[str, float]:
        """Mean seconds per iteration for each recorded phase."""
        if not self.rows:
            return {}
        keys = {k for row in self.rows for k in row} - {"iteration"}
        return {
            k: float(np.nanmean([row.get(k, np.nan) for row in self.rows]))
            for k in sorted(keys)
        }
