"""Wall-clock phase timing + profile series — thin adapters over the
telemetry registry (sphexa_tpu/telemetry/registry.py).

Counterpart of the reference's ``main/src/util/timer.hpp`` (per-substep
Timer printed each iteration, dumpable as a timing series with --profile,
ipropagator.hpp:80-119). The TPU step is one fused XLA program, so the
measurable phases are coarser: step (device compute incl. any recompile),
observables, output. The profile dump is an npz timeseries instead of the
reference's HDF5 group.

The implementations live on the registry (LapTimer / StepSeries) so that
laps recorded here ALSO accumulate in a shared ``Telemetry`` instance
when one is passed — the app loop, Simulation driver and bench then all
report into the same place. These names stay for API stability.
"""

from sphexa_tpu.telemetry.registry import LapTimer, StepSeries


class Timer(LapTimer):
    """Accumulates named wall-clock laps within one iteration
    (``step(name)`` records since the last mark, timer.hpp:46); pass
    ``telemetry=`` to mirror every lap into a registry."""


class ProfileRecorder(StepSeries):
    """Per-iteration timing/metric rows; saved with --profile
    (ipropagator.hpp:83-87 writes the analogous HDF5 series).
    ``save`` returns whether a file was actually written."""
