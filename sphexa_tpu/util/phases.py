"""The in-graph phase taxonomy: ``jax.named_scope("sphexa/<phase>")``.

Everything a profiler capture should be able to attribute gets its ops
stamped with one of THESE names — the step builders, the gravity solve,
the neighbor machinery and the halo exchange wrap their stages in
``phase_scope``/``@named_phase`` so XLA op *metadata* carries the phase
end-to-end: through fusion, through ``shard_map``, onto the device
timeline. ``sphexa-telemetry trace <dir>`` (telemetry/traceview.py)
aggregates a ``--trace-dir`` capture back into a per-phase device-time
table keyed on exactly this list; the HLO pin test
(tests/test_phase_attr.py) fails any refactor that silently strips a
scope.

The taxonomy mirrors the reference lineage's per-phase breakdowns (the
SPH-EXA ``Timer`` phases; Bédorf et al. 2014's tree-code phase tables,
SURVEY §6) transposed to the fused one-program step: phases are trace
METADATA here, not host-timed barriers — zero runtime cost, visible
only in a profiler capture.

``named_scope`` is pure tracing machinery (it pushes a name onto jax's
name stack; no primitive, no callback, no host boundary), so the
jaxaudit JXA104 host-boundary rule has nothing to flag — pinned by the
audit gate staying at zero findings with every scope below traced.
"""

import functools

import jax

#: every phase name in the taxonomy (docs/OBSERVABILITY.md schema-v4
#: table). Tests and the traceview renderer key on these.
PHASES = (
    "sort",             # SFC keygen + argsort + field permute, box regrow
    "neighbors",        # cell-table build / group windows / pair lists
    "halo-exchange",    # sparse/windowed halo negotiation + serves
    "density",          # std density pair op
    "xmass",            # VE generalized volume elements
    "gradh",            # VE kx / gradh pair op
    "eos",              # equation of state
    "iad",              # integral-approximation-of-derivatives tensor
    "divv-curlv",       # VE velocity divergence / curl (+gradv)
    "av-switches",      # VE artificial-viscosity switches
    "momentum-energy",  # momentum + energy pair op
    "gravity-upsweep",  # multipole upsweep (psum-reduced when sharded)
    "gravity-mac",      # MAC classification + interaction-list compaction
    "gravity-m2p",      # far-field multipole-to-particle evaluation
    "gravity-p2p",      # near-field particle-to-particle evaluation
    "cooling",          # radiative-cooling timestep + source integration
    "turbulence",       # OU stirring accelerations
    "timestep",         # dt candidate min-reduction + limiter attribution
    "dt-bins",          # block-timestep bin assignment, active compaction
    "integrate",        # drift/kick, PBC wrap, smoothing-length nudge
    "ledger",           # in-graph conservation/numerics science ledger
    "snapshot",         # in-graph downsampled field-grid deposit
    "shard-metrics",    # per-shard telemetry pack + gather
)

_PREFIX = "sphexa/"


def phase_scope(phase: str):
    """``jax.named_scope`` context for one taxonomy phase (asserted
    against PHASES so a typo cannot silently open a new bucket)."""
    assert phase in PHASES, f"unknown phase {phase!r} (util/phases.PHASES)"
    return jax.named_scope(_PREFIX + phase)


def named_phase(phase: str):
    """Decorator form: every op the wrapped function traces carries the
    phase. Zero runtime cost outside tracing — the context manager only
    runs while jax is building the jaxpr."""
    assert phase in PHASES, f"unknown phase {phase!r} (util/phases.PHASES)"
    name = _PREFIX + phase

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco
