"""Virtual-CPU-mesh bootstrap shared by the CLI (--cpu-mesh), the
multi-chip dry run and the test conftest.

Multi-device code paths are validated on hosts with one (or zero) real
accelerator by oversubscribing the CPU platform with N virtual devices —
the same strategy as the reference's oversubscribed-mpiexec integration
tests (domain/test/integration_mpi/). The backend choice must land BEFORE
jax's lazy backend init, and on hosts whose sitecustomize pre-imports jax
on an accelerator platform the only reliable lever is jax.config (env vars
are read too early); XLA_FLAGS *is* still read lazily at first backend
init.
"""

import os
import re


def force_cpu_mesh(n_devices: int) -> None:
    """Steer this process to a CPU backend with ``n_devices`` virtual
    devices. Must run before any jax operation initializes a backend;
    raises RuntimeError if the backend is already up or if XLA_FLAGS
    pins a conflicting device count."""
    import jax

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        have = int(m.group(1))
        if have < n_devices:
            raise RuntimeError(
                f"XLA_FLAGS already pins xla_force_host_platform_device_count"
                f"={have} < requested {n_devices}; unset it or raise it"
            )
    else:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    jax.config.update("jax_platforms", "cpu")
    # config.update silently no-ops once a backend is initialized — verify
    # the steer actually took (this also forces the lazy init NOW, on the
    # platform we just selected)
    if jax.default_backend() != "cpu" or len(jax.local_devices()) < n_devices:
        raise RuntimeError(
            f"backend is {jax.default_backend()!r} with "
            f"{len(jax.local_devices())} device(s) after the CPU-mesh "
            f"steer — jax was already initialized before force_cpu_mesh; "
            "set JAX_PLATFORMS=cpu in the environment instead"
        )
