from sphexa_tpu.util.blocking import blocked_map

__all__ = ["blocked_map"]
