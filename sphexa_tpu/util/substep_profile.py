"""In-app per-substep timing: the reference's per-phase Timer printout
(main/src/util/timer.hpp:29-82, hook points ipropagator.hpp:80-87 —
domain::sync / FindNeighbors / Density / IAD / MomentumEnergy ... per
iteration).

The production step is ONE fused jit, so substep walls do not exist
inside it (that fusion is the point of the design). This module times an
EQUIVALENT split execution of the current state — each pipeline stage as
its own jit — at profiling granularity (once per run, not per step).
Numbers are indicative: the fused step overlaps/fuses across these
boundaries, so the split SUM is an upper bound on the fused step time.

Thin adapter over the telemetry registry: pass ``telemetry=`` and every
stage time is recorded as a ``substep_<stage>`` timing plus one
``phases`` event, so a telemetry-enabled run persists the breakdown in
events.jsonl alongside the npz the app writes.
"""

import time
from typing import Dict, Optional

import jax


def _t(fn, *args, iters=3):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return out, (time.perf_counter() - t0) / iters


def substep_breakdown(sim, iters: int = 3,
                      telemetry: Optional[object] = None) -> Dict[str, float]:
    """Per-stage wall times (seconds) of one force pass on the CURRENT
    simulation state. Supports the engine ('pallas') std and ve
    pipelines; other configurations return {} (the coarse per-iteration
    laps in the --profile series still cover them)."""
    out = _substep_breakdown(sim, iters)
    if telemetry is not None and out:
        telemetry.phases(sim.iteration,
                         {f"substep_{k}": v for k, v in out.items()})
    return out


def _substep_breakdown(sim, iters: int = 3) -> Dict[str, float]:
    from sphexa_tpu.propagator import _sort_by_keys
    from sphexa_tpu.sfc.box import make_global_box
    from sphexa_tpu.sph import hydro_std, hydro_ve
    from sphexa_tpu.sph import pallas_pairs as pp

    cfg = sim._cfg
    if (cfg.backend != "pallas" or sim.prop_name not in ("std", "ve")
            or getattr(sim, "_mesh", None) is not None):
        # sharded runs would execute these UNsharded Pallas jits on
        # sharded state (the production multi-chip path exists because
        # Mosaic calls need shard_map) — skip rather than OOM/crash
        return {}
    const, nbr = cfg.const, cfg.nbr
    interp = pp.pallas_interpret()
    box = make_global_box(sim.state.x, sim.state.y, sim.state.z, sim.box)

    out: Dict[str, float] = {}
    (state, keys), out["sort"] = _t(
        jax.jit(lambda s: _sort_by_keys(s, box, cfg.curve)[:2]), sim.state
    )
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m
    vx, vy, vz = state.vx, state.vy, state.vz

    ranges, out["neighbor_prologue"] = _t(
        jax.jit(lambda *a: pp.group_cell_ranges(*a, box, nbr)),
        x, y, z, h, keys,
    )

    if sim.prop_name == "std":
        (rho, _, _), out["density"] = _t(
            jax.jit(lambda *a: pp.pallas_density(
                *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
            x, y, z, h, m,
        )
        (p, c), out["eos"] = _t(
            jax.jit(lambda t, r: hydro_std.compute_eos_std(t, r, const)),
            state.temp, rho,
        )
        (cs, _), out["iad"] = _t(
            jax.jit(lambda *a: pp.pallas_iad(
                *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
            x, y, z, h, m / rho,
        )
        _, out["momentum_energy"] = _t(
            jax.jit(lambda *a: pp.pallas_momentum_energy_std(
                *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
            x, y, z, vx, vy, vz, h, m, rho, p, c, *cs,
        )
        return out

    (xm, nc, _), out["xmass"] = _t(
        jax.jit(lambda *a: pp.pallas_xmass(
            *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
        x, y, z, h, m,
    )
    ((kx, gradh), _), out["ve_def_gradh"] = _t(
        jax.jit(lambda *a: pp.pallas_ve_def_gradh(
            *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
        x, y, z, h, m, xm,
    )
    (prho, c, rho, p), out["eos"] = _t(
        jax.jit(lambda *a: hydro_ve.compute_eos_ve(*a, const)),
        state.temp, m, kx, xm, gradh,
    )
    (cs, _), out["iad"] = _t(
        jax.jit(lambda *a: pp.pallas_iad(
            *a, keys, box, const, nbr, ranges=ranges, interpret=interp)),
        x, y, z, h, xm / kx,
    )
    (dvout, _), out["divv_curlv"] = _t(
        jax.jit(lambda *a: pp.pallas_iad_divv_curlv(
            *a, keys, box, const, nbr, ranges=ranges,
            with_gradv=cfg.av_clean, interpret=interp)),
        x, y, z, vx, vy, vz, h, kx, xm, *cs,
    )
    divv = dvout[0]
    (alpha, _), out["av_switches"] = _t(
        jax.jit(lambda *a: pp.pallas_av_switches(
            *a, keys, box, state.min_dt, const, nbr, ranges=ranges,
            interpret=interp)),
        x, y, z, vx, vy, vz, h, c, kx, xm, divv, state.alpha, *cs,
    )
    gradv = tuple(dvout[2:]) if cfg.av_clean else None
    _, out["momentum_energy"] = _t(
        jax.jit(lambda *a: pp.pallas_momentum_energy_ve(
            *a, keys, box, const, nbr, nc=nc, gradv=gradv, ranges=ranges,
            interpret=interp)),
        x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha, *cs,
    )
    return out
