"""Blocked mapping over particle ranges.

The SPH interaction ops materialize (block, ngmax) gathered neighbor-field
tiles; mapping a block body with lax.map keeps the transient footprint at
``block * ngmax * n_fields * 4`` bytes instead of ``N * ...``, while XLA
still fuses everything inside one block into a single kernel. This plays
the role that target-group tiling plays in the reference's GPU traversal
(cstone/traversal/groups.cuh): bounded on-chip working sets over an
SFC-ordered particle range.
"""

import jax
import jax.numpy as jnp


def blocked_map(body, n: int, block: int):
    """Run ``body(idx_block)`` over ceil(n/block) index blocks; concat results.

    ``body`` receives an int32 index vector of length ``block`` (tail indices
    clamped to n-1; the duplicate rows are discarded) and returns a pytree of
    per-particle arrays with leading dim ``block``.
    """
    num_blocks = -(-n // block)
    idx = jnp.arange(num_blocks * block, dtype=jnp.int32).reshape(num_blocks, block)
    out = jax.lax.map(lambda ib: body(jnp.minimum(ib, n - 1)), idx)
    return jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:])[:n], out)
