"""The unified simulation carry pytree.

``SimState`` is the ONE structure every step of every propagator family
maps onto: the particle slab + box that all families share, plus one
optional aux slot per family extension (turbulence phases, chemistry
fractions, block-timestep bins). Historically the ``Simulation`` driver
threaded an ad-hoc 6-tuple ``(state, box, diagnostics, turb, chem,
bstate)`` with ``None`` padding per family — a shape no tool could
verify and ``jax.vmap`` could not batch. As a registered dataclass the
carry is an explicit pytree: statecheck (devtools/audit/statecheck.py)
locks its per-leaf schema in STATE_SCHEMA.json and proves carry closure
(JXA503), and ensemble serving (ROADMAP item 3) can vmap a member axis
over it under one compile.

Inactive slots hold ``None`` — jax treats ``None`` as an empty subtree,
so a slot flipping ``None``<->array between steps CHANGES the carry's
treedef (a guaranteed retrace). The driver therefore builds the
``SimState`` once from its attributes and only ever *replaces* the
active slot; JXA503 makes that invariant statically checkable.

The module is import-light on purpose (jax + dataclasses only): the
audit registry and the lint layer both touch it without pulling the
physics stack.
"""

import dataclasses
from typing import Any, Optional

import jax

__all__ = ["SimState", "AUX_SLOTS"]

#: family-extension slots, in carry order (turb-ve / std-cooling /
#: blockdt twins); exactly one is non-None for a given propagator family
AUX_SLOTS = ("turb", "chem", "bdt")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimState:
    """Full per-member simulation state: what one step consumes and
    (diagnostics aside) what it produces — structurally closed under
    stepping, per family."""

    particles: Any                 # sph.particles.ParticleState
    box: Any                       # sfc.box.Box
    turb: Optional[Any] = None     # sph.hydro_turb.TurbulenceState
    chem: Optional[Any] = None     # physics.cooling.ChemistryData
    bdt: Optional[Any] = None      # sph.blockdt.BlockDtState

    def with_slot(self, slot: Optional[str], value: Any,
                  particles: Any = None, box: Any = None) -> "SimState":
        """Copy with the named aux slot (and optionally particles/box)
        replaced; ``slot=None`` replaces particles/box only."""
        kw = {}
        if particles is not None:
            kw["particles"] = particles
        if box is not None:
            kw["box"] = box
        if slot is not None:
            kw[slot] = value
        return dataclasses.replace(self, **kw)
