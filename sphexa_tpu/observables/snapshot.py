"""In-graph field snapshots: fixed-shape downsampled field grids deposited
INSIDE the jitted step and fetched at the existing check/flush boundary.

The reference's in-situ leg hands the full mesh to Ascent / ParaView
Catalyst adaptors around the main loop (``main/src/ascent_adaptor.h``,
``catalyst_adaptor.h``; Ayachit et al. 2015, Larsen et al. 2017). The
TPU-era translation of their "reduce on the compute resource, ship only
render-ready extracts" principle is this module: a static, hashable
``SnapshotSpec`` lowers to one scatter-add deposit per step — a
``(F, G, G)`` column projection (or ``(F, G, G, G)`` volume) plus an
optional strided particle subsample — that rides the diagnostics dict
exactly like the PR 6 science ledger (``SNAP_DIAG_KEYS``, the
``SHARD_DIAG_KEYS`` conditionality pattern). The Simulation fetches the
grids in its ONE batched transfer at the check/flush boundary, so
snapshots add ZERO host syncs to a deferred window — unlike the old
``--insitu`` path, which pulled the full state per rendered frame.

Sharding: the deposit runs in the unsharded step tail, so GSPMD turns
the scatter-add over sharded ``(N,)`` fields into per-shard partial
grids psum-reduced into the replicated output — 2-device == 1-device is
pinned (up to float summation order) by tests/test_serve.py.

Collective ordering: the psum'd deposit is one more collective on
XLA:CPU's rendezvous-racing meshes (the PR-5 class), so the deposit
input is chained (``exchange.chain_after``) onto the step's last
collective — the ledger's final min sweep when ``cfg.obs`` is set, the
shard-metrics gather otherwise.
"""

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from sphexa_tpu.util.phases import named_phase

#: snapshot diagnostics the step tail emits whenever a
#: PropagatorConfig.snap spec is set (None = bare steps compile without
#: any snapshot scope; consumers must .get()). ``snap_grid`` is the
#: (F, G, G) (or (F, G, G, G)) deposited field grid, ``snap_min`` /
#: ``snap_max`` the per-field grid extrema, ``snap_pts`` the optional
#: strided particle subsample ((3 + F, ceil(N / stride))).
SNAP_DIAG_KEYS = ("snap_grid", "snap_min", "snap_max", "snap_pts")

#: field names a spec may request: "rho" is the force stage's density
#: (post-step order, the same pairing the ledger uses); the rest are
#: ParticleState attributes
SNAP_FIELDS = ("rho", "m", "temp", "vx", "vy", "vz", "h", "du")


@dataclasses.dataclass(frozen=True)
class SnapshotSpec:
    """Static (hashable) description of the in-graph snapshot — a jit
    compile-time constant like ObservableSpec, so every shape below is
    fixed and ``snap=None`` steps lower with no snapshot ops at all.

    ``fields``: names from SNAP_FIELDS, deposited as scatter weights.
    ``grid``: side G of the deposit grid.
    ``axis``: projection axis for the 2D deposit (2 = project along z
    onto the (x, y) plane, matching ``viz.render_field``).
    ``reduce``: "sum" (column density deposit) or "max" (peak value).
    ``stride``: > 0 ships every stride-th particle's position + fields
    as ``snap_pts`` alongside the grids; 0 = grids only.
    ``volume``: True deposits the full (F, G, G, G) volume instead of
    the axis projection.
    """

    fields: Tuple[str, ...] = ("rho",)
    grid: int = 16
    axis: int = 2
    reduce: str = "sum"
    stride: int = 0
    volume: bool = False

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))
        if not self.fields:
            raise ValueError("SnapshotSpec.fields must name >= 1 field")
        for f in self.fields:
            if f not in SNAP_FIELDS:
                raise ValueError(f"unknown snapshot field {f!r}; "
                                 f"choices: {list(SNAP_FIELDS)}")
        if self.grid < 2:
            raise ValueError("SnapshotSpec.grid must be >= 2")
        if self.axis not in (0, 1, 2):
            raise ValueError("SnapshotSpec.axis must be 0, 1 or 2")
        if self.reduce not in ("sum", "max"):
            raise ValueError("SnapshotSpec.reduce must be 'sum' or 'max'")
        if self.stride < 0:
            raise ValueError("SnapshotSpec.stride must be >= 0")


def _field_values(state, rho, name: str):
    return rho if name == "rho" else getattr(state, name)


@named_phase("snapshot")
def snapshot_diagnostics(state, rho, box,
                         spec: SnapshotSpec,
                         token=None) -> Dict[str, jnp.ndarray]:
    """The in-graph deposit: SNAP_DIAG_KEYS over the post-integration
    state. ``rho`` is the force stage's density in the step's order;
    ``token`` anchors the deposit after the step's last collective
    (defaults to ``state.min_dt``, the ledger/``chain_after`` pattern).

    The whole snapshot lowers to ONE scatter (all fields stacked into a
    (F, N) weight sweep against one flattened cell-index vector) plus
    the per-field extrema reductions over the G-sized grid — under
    sharding that is a single psum'd deposit, keeping the collective
    count flat in F like the ledger's stacked reductions.
    """
    from sphexa_tpu.parallel.exchange import chain_after

    G = spec.grid
    lo = box.lo
    lengths = box.lengths

    def cell_index(coord, d):
        # clip keeps escaped particles (pre-regrow positions) in the
        # boundary cells instead of wrapping the deposit
        u = (coord - lo[d]) / lengths[d]
        return jnp.clip((u * G).astype(jnp.int32), 0, G - 1)

    pos = (state.x, state.y, state.z)
    w = jnp.stack([_field_values(state, rho, f) for f in spec.fields])
    root = state.min_dt if token is None else token
    w = chain_after(w, root)

    if spec.volume:
        i0 = cell_index(pos[0], 0)
        i1 = cell_index(pos[1], 1)
        i2 = cell_index(pos[2], 2)
        flat = (i0 * G + i1) * G + i2
        shape = (len(spec.fields), G, G, G)
    else:
        rem = tuple(d for d in (0, 1, 2) if d != spec.axis)
        # row index = second remaining axis, col = first — the
        # orientation viz.render_field uses for its (y, x) histogram
        rows = cell_index(pos[rem[1]], rem[1])
        cols = cell_index(pos[rem[0]], rem[0])
        flat = rows * G + cols
        shape = (len(spec.fields), G, G)

    F = len(spec.fields)
    if spec.reduce == "sum":
        g = jnp.zeros((F, G ** (3 if spec.volume else 2)),
                      dtype=w.dtype).at[:, flat].add(w)
    else:
        neg = jnp.finfo(w.dtype).min
        g = jnp.full((F, G ** (3 if spec.volume else 2)), neg,
                     dtype=w.dtype).at[:, flat].max(w)
        g = jnp.where(g == neg, jnp.zeros((), w.dtype), g)
    grid = g.reshape(shape)

    out = {
        "snap_grid": grid,
        # extrema over the (replicated) grid: cheap, collective-free
        "snap_min": jnp.min(g, axis=1),
        "snap_max": jnp.max(g, axis=1),
    }
    if spec.stride > 0:
        s = spec.stride
        sub = jnp.stack([chain_after(pos[0], g[0, 0])[::s],
                         pos[1][::s], pos[2][::s]]
                        + [row[::s] for row in w])
        out["snap_pts"] = sub
    return out
