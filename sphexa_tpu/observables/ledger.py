"""In-graph science ledger: conservation + numerics-health reductions
computed INSIDE the jitted step.

The reference computes its science observables in-situ every iteration —
one reduction sweep per step (``conserved_quantities.hpp:40-179``) and
one ``constants.txt`` row (``iobservables.hpp``). The app loop used to
recompute them host-side per step (a second jitted reduction program
over the same state, forcing a device sync per step and going blind
inside deferred-check windows); this module moves the same sums into the
step program so they ride the diagnostics dict (``OBS_DIAG_KEYS`` /
``NUM_DIAG_KEYS``, the ``propagator.SHARD_DIAG_KEYS`` pattern) and are
fetched in the ONE batched transfer at the existing check/flush
boundary — zero added host syncs, a science row for every step even
under ``--check-every N``.

Collective ordering: under a sharded step each reduction lowers to an
all-reduce, and mutually independent collectives rendezvous-race on this
container's XLA:CPU meshes (the PR-5 sparse-exchange class; see
``parallel/exchange.chain_after``). Every ledger reduction is therefore
chained onto its predecessor's result — one total order, free on real
TPU meshes where collectives execute in program order anyway.
"""

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp

from sphexa_tpu.observables.conserved import _acc_dtype
from sphexa_tpu.observables.extras import (
    kh_growth_rate,
    mach_rms,
    wind_bubble_fraction,
)
from sphexa_tpu.util.phases import named_phase

#: conservation-ledger scalars the step tail emits whenever a
#: PropagatorConfig.obs spec is set (the app/bench always set one; bare
#: library steps skip the ledger, the SHARD_DIAG_KEYS conditionality
#: pattern — consumers must .get()). Computed over the POST-integration
#: state, matching the app's former eager recompute; ``obs_extra`` (the
#: case observable) rides along only when the spec names an ``extra``.
OBS_DIAG_KEYS = ("obs_ttot", "obs_etot", "obs_ecin", "obs_eint",
                 "obs_egrav", "obs_linmom", "obs_angmom")

#: numerics-health scalars riding the same ledger: timestep-limiter
#: attribution (``propagator.DT_LIMITERS`` names the index), neighbor-cap
#: clip and h-iteration saturation counts, nonfinite and extrema scalars
#: for rho/h/du. ``dt_limiter`` is produced by the step builders (it
#: needs the dt candidates) and is ALWAYS present — a 5-scalar argmin
#: costs nothing; the O(N) counts/extrema ride the cfg.obs gate with
#: the conservation scalars.
NUM_DIAG_KEYS = ("dt_limiter", "n_nc_clip", "n_h_sat", "n_bad_rho",
                 "n_bad_h", "n_bad_du", "rho_min", "h_min", "du_max")

#: constants.txt column name per case-extra kind (matches the factory
#: observables' ``extra_columns``)
EXTRA_COLUMNS = {"kh": "khGrowthRate", "mach": "machRMS",
                 "wind": "survivorFraction"}


@dataclasses.dataclass(frozen=True)
class ObservableSpec:
    """Static (hashable) selection of the case observable computed
    in-graph — the PropagatorConfig-resident analog of the factory's
    observable objects (``factory.hpp:46-70``). ``extra`` is one of
    ``""`` (energies only), ``"kh"``, ``"mach"``, ``"wind"``; the
    threshold fields are only read by the wind-bubble observable."""

    extra: str = ""
    rho_bubble: float = 0.0
    temp_wind: float = 0.0
    initial_mass: float = 1.0

    def __post_init__(self):
        if self.extra not in ("",) + tuple(EXTRA_COLUMNS):
            raise ValueError(f"unknown observable extra {self.extra!r}; "
                             f"choices: {sorted(EXTRA_COLUMNS)}")


def make_observable_spec(case: str,
                         overrides: Optional[Dict] = None) -> ObservableSpec:
    """ObservableSpec for a test case, derived THROUGH the factory
    observable (``factory.make_observable`` stays the single source of
    truth for case keying, column names and thresholds). A factory
    observable whose extra column has no in-graph implementation raises
    loudly — a silent energies-only fallback would write a constants.txt
    header with more columns than its rows carry."""
    from sphexa_tpu.observables.factory import make_observable

    obs = make_observable(case, overrides=overrides)
    cols = obs.extra_columns
    if not cols:
        return ObservableSpec()
    kinds = {col: kind for kind, col in EXTRA_COLUMNS.items()}
    if len(cols) != 1 or cols[0] not in kinds:
        raise ValueError(
            f"case observable {type(obs).__name__} (columns {cols}) has "
            f"no in-graph ledger implementation; add it to "
            f"observables/ledger.py EXTRA_COLUMNS + ledger_diagnostics")
    kind = kinds[cols[0]]
    if kind == "wind":
        return ObservableSpec(
            extra="wind",
            rho_bubble=float(obs.rho_bubble),
            temp_wind=float(obs.temp_wind),
            initial_mass=float(obs.initial_mass),
        )
    return ObservableSpec(extra=kind)


@named_phase("ledger")
def ledger_diagnostics(state, rho, nc, const, ngmax: int,
                       spec: Optional[ObservableSpec] = None, egrav=0.0,
                       box=None, c=None, smoothing: bool = True,
                       token=None) -> Dict[str, jnp.ndarray]:
    """The per-step science scalars (``OBS_DIAG_KEYS`` + the
    ``NUM_DIAG_KEYS`` this function owns), as in-graph reductions over
    the post-integration state.

    ``rho``/``c`` are the force stage's density/sound speed in the
    step's (sorted) order — the same pairing the app's eager recompute
    used (post-step state + force-stage fields). ``nc`` is the neighbor
    count EXCLUDING self, as the force stage returns it. ``smoothing``
    mirrors ``update_smoothing``: propagators that never iterate h
    (nbody) report zero cap/saturation counts instead of counting every
    particle as off-target. ``token``: optional value produced by the
    force stage's LAST collective (the shard-metrics gather on sharded
    runs) — the ledger's first reduction chains on it so the two
    families of collectives can never become concurrently runnable;
    defaults to ``state.min_dt`` (= dt, which orders after the force
    stage's pmin chain but not its gather).

    The conservation sums are the exact math of
    ``conserved.conserved_quantities`` (f64 accumulation when x64 is on,
    XLA tree reduction in f32 otherwise; the two-sum carry ``temp_lo``
    summed separately) so the in-graph constants.txt row equals the old
    eager one.

    The whole ledger lowers to THREE stacked reductions (one float sum
    over a (9, N) stack, one int sum over (5, N), one min over (3, N)) —
    the PR-5 ``_shard_metrics`` packing pattern: under sharding that is
    three collectives instead of sixteen, which both bounds the SPMD
    partitioner's compile cost across every step program in the suite
    and shrinks the rendezvous-race surface the chaining guards.
    """
    from sphexa_tpu.parallel.exchange import chain_after

    dt = _acc_dtype()
    m = state.m
    x, y, z = state.x, state.y, state.z
    vx, vy, vz = state.vx, state.vy, state.vz

    # one (9, N) float sweep: energies (two-sum carry separate) + the
    # linear/angular momentum components
    frows = jnp.stack([
        m * (vx**2 + vy**2 + vz**2),
        const.cv * state.temp * m,
        const.cv * state.temp_lo * m,
        m * vx, m * vy, m * vz,
        m * (y * vz - z * vy),
        m * (z * vx - x * vz),
        m * (x * vy - y * vx),
    ])
    root = state.min_dt if token is None else token
    fsum = jnp.sum(chain_after(frows, root), axis=1, dtype=dt)
    ekin = 0.5 * fsum[0]
    eint = fsum[1] + fsum[2]
    egrav_s = jnp.asarray(egrav, dtype=ekin.dtype)
    etot = ekin + eint + egrav_s

    out = {
        "obs_ttot": state.ttot,
        "obs_etot": etot,
        "obs_ecin": ekin,
        "obs_eint": eint,
        "obs_egrav": egrav_s,
        "obs_linmom": jnp.sqrt(fsum[3]**2 + fsum[4]**2 + fsum[5]**2),
        "obs_angmom": jnp.sqrt(fsum[6]**2 + fsum[7]**2 + fsum[8]**2),
    }

    # -- numerics health ---------------------------------------------------
    # one (5, N) int sweep: cap-clip + saturation + nonfinite counts.
    # h-iteration saturation: the single-nudge update_h targets ng0
    # neighbors; a count off by more than half the target means the
    # nudge is far from its fixed point (the reference's h iteration
    # would not have converged) — resolution is locally wrong. nc
    # excludes self, so counts use nc + 1 like the reference. Propagators
    # that never iterate h (smoothing=False, nbody) report zeros.
    nc1 = nc + 1
    act = jnp.int32(1 if smoothing else 0)
    irows = jnp.stack([
        (nc1 >= ngmax).astype(jnp.int32) * act,
        (jnp.abs(nc1 - const.ng0) > 0.5 * const.ng0).astype(jnp.int32)
        * act,
        (~jnp.isfinite(rho)).astype(jnp.int32),
        (~jnp.isfinite(state.h)).astype(jnp.int32),
        (~jnp.isfinite(state.du)).astype(jnp.int32),
    ])
    isum = jnp.sum(chain_after(irows, fsum[0]), axis=1)
    out["n_nc_clip"] = isum[0]
    out["n_h_sat"] = isum[1]
    out["n_bad_rho"] = isum[2]
    out["n_bad_h"] = isum[3]
    out["n_bad_du"] = isum[4]

    # one (3, N) min sweep: field extrema (max|du| = -min(-|du|))
    mrows = jnp.stack([rho, state.h, -jnp.abs(state.du)])
    mins = jnp.min(chain_after(mrows, isum[0]), axis=1)
    out["rho_min"] = mins[0]
    out["h_min"] = mins[1]
    out["du_max"] = -mins[2]
    tok = mins[0]

    # -- case observable ---------------------------------------------------
    if spec is not None and spec.extra:
        if spec.extra == "kh":
            vol = m / rho
            out["obs_extra"] = kh_growth_rate(
                state.x, state.y, chain_after(vy, tok), vol, box)
        elif spec.extra == "mach":
            cs = c if c is not None else jnp.full_like(rho, jnp.nan)
            out["obs_extra"] = mach_rms(vx, vy, chain_after(vz, tok), cs)
        else:  # wind
            out["obs_extra"] = wind_bubble_fraction(
                chain_after(rho, tok), state.temp, m, spec.rho_bubble,
                spec.temp_wind, spec.initial_mass)
    return out
