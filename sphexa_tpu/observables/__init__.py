"""Per-step analysis reductions appended to constants.txt.

Counterpart of the reference's ``main/src/observables/``: conserved
quantities every step, plus case-specific observables (KH growth rate,
Mach RMS, wind-bubble survival, gravitational waves) selected by the
factory.
"""

from sphexa_tpu.observables.conserved import conserved_quantities
from sphexa_tpu.observables.extras import (
    gravitational_wave_signal,
    kh_growth_rate,
    mach_rms,
    wind_bubble_fraction,
)
from sphexa_tpu.observables.factory import (
    BASE_COLUMNS,
    ConstantsWriter,
    make_observable,
)

__all__ = [
    "conserved_quantities",
    "kh_growth_rate",
    "mach_rms",
    "wind_bubble_fraction",
    "gravitational_wave_signal",
    "make_observable",
    "ConstantsWriter",
    "BASE_COLUMNS",
]
