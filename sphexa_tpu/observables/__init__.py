from sphexa_tpu.observables.conserved import conserved_quantities

__all__ = ["conserved_quantities"]
