"""Per-step analysis reductions appended to constants.txt.

Counterpart of the reference's ``main/src/observables/``: conserved
quantities every step, plus case-specific observables (KH growth rate,
Mach RMS, wind-bubble survival, gravitational waves) selected by the
factory.
"""

from sphexa_tpu.observables.conserved import conserved_quantities
from sphexa_tpu.observables.extras import (
    gravitational_wave_signal,
    kh_growth_rate,
    mach_rms,
    wind_bubble_fraction,
)
from sphexa_tpu.observables.factory import (
    BASE_COLUMNS,
    ConstantsWriter,
    make_observable,
)
from sphexa_tpu.observables.ledger import (
    NUM_DIAG_KEYS,
    OBS_DIAG_KEYS,
    ObservableSpec,
    ledger_diagnostics,
    make_observable_spec,
)
from sphexa_tpu.observables.snapshot import (
    SNAP_DIAG_KEYS,
    SnapshotSpec,
    snapshot_diagnostics,
)

__all__ = [
    "conserved_quantities",
    "kh_growth_rate",
    "mach_rms",
    "wind_bubble_fraction",
    "gravitational_wave_signal",
    "make_observable",
    "make_observable_spec",
    "ObservableSpec",
    "ledger_diagnostics",
    "ConstantsWriter",
    "BASE_COLUMNS",
    "OBS_DIAG_KEYS",
    "NUM_DIAG_KEYS",
    "SnapshotSpec",
    "snapshot_diagnostics",
    "SNAP_DIAG_KEYS",
]
