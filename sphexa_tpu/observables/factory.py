"""Observable selection + constants.txt output.

Counterpart of the reference's ``main/src/observables/factory.hpp:46-70``
(observable chosen by test-case settings) and ``iobservables.hpp`` (one
row appended to constants.txt per iteration). The base row is
iteration, time, minDt, etot, ecin, eint, egrav; case-specific observables
append their own columns.
"""

import os
from typing import Dict, List, Optional

import numpy as np

from sphexa_tpu.init.wind_shock import wind_shock_constants
from sphexa_tpu.observables.extras import (
    kh_growth_rate,
    mach_rms,
    wind_bubble_fraction,
)
from sphexa_tpu.sph.particles import ideal_gas_cv

BASE_COLUMNS = ["iteration", "time", "minDt", "etot", "ecin", "eint", "egrav"]


class TimeAndEnergy:
    """Default observable: energies only (time_energies.hpp)."""

    extra_columns: List[str] = []
    needs_fields = False

    def compute_extra(self, state, box, fields) -> List[float]:
        return []


class TimeEnergyGrowth:
    """KH growth-rate column (time_energy_growth.hpp)."""

    extra_columns = ["khGrowthRate"]
    needs_fields = True

    def compute_extra(self, state, box, fields) -> List[float]:
        vol = np.asarray(state.m) / fields["rho"]
        return [
            float(kh_growth_rate(state.x, state.y, state.vy, vol, box))
        ]


class TurbulenceMachRMS:
    """RMS Mach number column (turbulence_mach_rms.hpp)."""

    extra_columns = ["machRMS"]
    needs_fields = True

    def compute_extra(self, state, box, fields) -> List[float]:
        return [
            float(mach_rms(state.vx, state.vy, state.vz, fields["c"]))
        ]


class WindBubble:
    """Surviving cloud-mass fraction column (wind_bubble_fraction.hpp)."""

    extra_columns = ["survivorFraction"]
    needs_fields = True

    def __init__(self, settings: Dict[str, float]):
        cv = ideal_gas_cv(settings["mui"], settings["gamma"])
        self.rho_bubble = settings["rhoInt"]
        self.temp_wind = settings["uExt"] / cv
        self.initial_mass = (
            4.0 / 3.0 * np.pi * settings["rSphere"] ** 3 * settings["rhoInt"]
        )

    def compute_extra(self, state, box, fields) -> List[float]:
        return [
            float(
                wind_bubble_fraction(
                    fields["rho"], state.temp, state.m,
                    self.rho_bubble, self.temp_wind, self.initial_mass,
                )
            )
        ]


def make_observable(case: str, overrides: Optional[Dict[str, float]] = None):
    """Observable for a test case, keyed like the reference factory (which
    keys on the marker entries the init settings plant, factory.hpp:46-70:
    'kelvin-helmholtz', 'wind-shock', 'turbulence'). ``overrides`` are the
    case's settings-file overrides, so threshold-bearing observables match
    the actual setup."""
    if case == "kelvin-helmholtz":
        return TimeEnergyGrowth()
    if case == "wind-shock":
        return WindBubble(dict(wind_shock_constants(), **(overrides or {})))
    if case == "turbulence":
        return TurbulenceMachRMS()
    return TimeAndEnergy()


class ConstantsWriter:
    """Append one observable row per iteration to constants.txt
    (iobservables.hpp / fileutils::writeColumns)."""

    def __init__(self, path: str, observable=None, restart_iteration=None):
        self.path = path
        self.observable = observable or TimeAndEnergy()
        # appending to an existing file (restart) must not inject a second
        # header line mid-file
        self._wrote_header = os.path.exists(path) and os.path.getsize(path) > 0
        if restart_iteration is not None and self._wrote_header:
            self._truncate_after(restart_iteration)

    def _truncate_after(self, iteration: int):
        """Drop rows with iteration > the restart point, so resuming from
        an older snapshot (--init dump.h5:-2) leaves a monotonic series
        instead of overlapping row ranges."""
        with open(self.path) as f:
            lines = f.readlines()
        kept = [
            ln for ln in lines
            if ln.startswith("#")
            or not ln.strip()
            or float(ln.split()[0]) <= iteration
        ]
        if len(kept) != len(lines):
            with open(self.path, "w") as f:
                f.writelines(kept)

    def write(
        self,
        iteration: int,
        state,
        box,
        energies: Dict[str, float],
        fields: Optional[Dict[str, np.ndarray]] = None,
    ) -> List[float]:
        row = [
            float(iteration), float(state.ttot), float(state.min_dt),
            float(energies["etot"]), float(energies["ecin"]),
            float(energies["eint"]), float(energies["egrav"]),
        ]
        row += self.observable.compute_extra(state, box, fields)
        return self.write_row(row)

    def write_row(self, values) -> List[float]:
        """Append one pre-computed row (the in-graph ledger path: the
        Simulation already fetched every scalar at its check/flush
        boundary, so this touches no state and triggers no device
        sync). Same header/format as ``write`` — byte-compatible."""
        row = [float(v) for v in values]
        with open(self.path, "a") as f:
            if not self._wrote_header:
                f.write("# " + " ".join(BASE_COLUMNS + self.observable.extra_columns) + "\n")
                self._wrote_header = True
            f.write(" ".join(f"{v:.10g}" for v in row) + "\n")
        return row
