"""Case-specific observables.

Physics-equivalents of the reference's per-case observables
(main/src/observables/): Kelvin-Helmholtz growth rate
(time_energy_growth.hpp:45-110), turbulence Mach RMS
(turbulence_mach_rms.hpp:39-85), wind-bubble survivor fraction
(wind_bubble_fraction.hpp:43-97) and the gravitational-wave quadrupole
signal (grav_waves_calculations.hpp:30-121). All are jnp reductions: under
a sharded step they lower to psum-style collectives.
"""

from typing import Dict, Tuple

import jax.numpy as jnp

# gravitational-wave unit at 10 kpc: G / c^4 / (10 kpc in cm), cgs
# (grav_waves_calculations.hpp:56-58)
_G_CGS = 6.6726e-8
_C_CGS = 2.997924562e10
GW_UNITS = _G_CGS / _C_CGS**4 / 3.08568025e22


def kh_growth_rate(x, y, vy, vol, box) -> jnp.ndarray:
    """Kelvin-Helmholtz instability amplitude growth (McNally et al. 2012
    mode projection; time_energy_growth.hpp:45-70): project vy onto the
    seeded sin(4 pi x) mode, weighted toward the two interfaces."""
    ybox = box.lengths[1]
    aux = jnp.where(
        y < ybox * 0.5,
        jnp.exp(-4.0 * jnp.pi * jnp.abs(y - 0.25)),
        jnp.exp(-4.0 * jnp.pi * jnp.abs(ybox - y - 0.25)),
    )
    w = vy * vol * aux
    # ONE stacked reduction for the three sibling projections: inside
    # the step program (observables/ledger.py) each independent sum
    # would lower to its own collective under sharding, and mutually
    # unordered collectives rendezvous-race on XLA:CPU meshes
    # (parallel/exchange.chain_after)
    s = jnp.sum(jnp.stack([
        w * jnp.sin(4.0 * jnp.pi * x),
        w * jnp.cos(4.0 * jnp.pi * x),
        vol * aux,
    ]), axis=1)
    return 2.0 * jnp.sqrt(s[0]**2 + s[1]**2) / s[2]


def mach_rms(vx, vy, vz, c) -> jnp.ndarray:
    """Root-mean-square Mach number (turbulence_mach_rms.hpp:39-85)."""
    m2 = (vx**2 + vy**2 + vz**2) / (c * c)
    return jnp.sqrt(jnp.mean(m2))


def wind_bubble_fraction(
    rho, temp, m, rho_bubble: float, temp_wind: float, initial_mass: float
) -> jnp.ndarray:
    """Fraction of the initial cloud mass still in the cloud phase: denser
    than 0.64 rho_bubble and cooler than 0.9 T_wind
    (wind_bubble_fraction.hpp:43-57,96)."""
    survive = (rho >= 0.64 * rho_bubble) & (temp <= 0.9 * temp_wind)
    return jnp.sum(jnp.where(survive, m, 0.0)) / initial_mass


def _d2_quadrupole(i, j, pos, vel, acc, m) -> jnp.ndarray:
    """Second time derivative of the traceless quadrupole moment component
    (i, j), from positions/velocities/accelerations
    (grav_waves_calculations.hpp:88-121)."""
    if i == j:
        v2 = vel[0] ** 2 + vel[1] ** 2 + vel[2] ** 2
        rdota = pos[0] * acc[0] + pos[1] * acc[1] + pos[2] * acc[2]
        out = jnp.sum(
            (3.0 * (vel[i] ** 2 + pos[i] * acc[i]) - v2 - rdota) * m
        )
        return out * 2.0 / 3.0
    return jnp.sum(
        (2.0 * vel[i] * vel[j] + acc[i] * pos[j] + pos[i] * acc[j]) * m
    )


def gravitational_wave_signal(
    x, y, z, vx, vy, vz, ax, ay, az, m, theta: float, phi: float
) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """(h+_tt, hx_tt, d2Q components) for an observer at (theta, phi),
    10 kpc, cgs units (gravitational_waves.hpp + computeHtt)."""
    pos, vel, acc = (x, y, z), (vx, vy, vz), (ax, ay, az)
    q = {
        "xx": _d2_quadrupole(0, 0, pos, vel, acc, m),
        "yy": _d2_quadrupole(1, 1, pos, vel, acc, m),
        "zz": _d2_quadrupole(2, 2, pos, vel, acc, m),
        "xy": _d2_quadrupole(0, 1, pos, vel, acc, m),
        "xz": _d2_quadrupole(0, 2, pos, vel, acc, m),
        "yz": _d2_quadrupole(1, 2, pos, vel, acc, m),
    }
    sin2t, sin2p = jnp.sin(2 * theta), jnp.sin(2 * phi)
    cos2p = jnp.cos(2 * phi)
    sint, cost = jnp.sin(theta), jnp.cos(theta)
    sinp, cosp = jnp.sin(phi), jnp.cos(phi)

    ibar_tt = (
        (q["xx"] * cosp**2 + q["yy"] * sinp**2 + q["xy"] * sin2p) * cost**2
        + q["zz"] * sint**2
        - (q["xz"] * cosp + q["yz"] * sinp) * sin2t
    )
    ibar_pp = q["xx"] * sinp**2 + q["yy"] * cosp**2 - q["xy"] * sin2p
    ibar_tp = (
        0.5 * (q["yy"] - q["xx"]) * cost * sin2p
        + q["xy"] * cost * cos2p
        + (q["xz"] * sinp - q["yz"] * cosp) * sint
    )
    htt_plus = (ibar_tt - ibar_pp) * GW_UNITS
    htt_cross = 2.0 * ibar_tp * GW_UNITS
    return htt_plus, htt_cross, q
