"""Conserved-quantity reductions: energies, linear and angular momentum.

Physics-equivalent of the reference's
``main/src/observables/conserved_quantities.hpp:40-179``. The sums are the
framework's conservation diagnostic: they accumulate in float64 when x64
is enabled (the reference reduces in double) and otherwise rely on XLA's
tree reduction in float32; under a sharded step the jnp.sum lowers to a
psum-style collective.
"""

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from sphexa_tpu.sph.particles import ParticleState, SimConstants


def _acc_dtype():
    """float64 accumulation when x64 is enabled (CPU diagnostics runs);
    float32 otherwise (TPU) — XLA's tree reductions keep the f32 error at
    O(sqrt(log N)) ulps, adequate against the 1e-3 drift budget."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# jitted into ONE program: eagerly, each independent sum over a SHARDED
# state is its own collective program, and the CPU backend executes
# cached independent programs concurrently on one thread pool — their
# all-reduce rendezvous interleave and deadlock (observed on the
# 8-virtual-device mesh). One program also matches the reference's single
# reduction sweep (conserved_quantities.hpp:40-179).
@functools.partial(jax.jit, static_argnames=("const",))
def conserved_quantities(
    state: ParticleState, const: SimConstants, egrav=0.0
) -> Dict[str, jnp.ndarray]:
    m = state.m
    dt = _acc_dtype()
    ekin = 0.5 * jnp.sum(m * (state.vx**2 + state.vy**2 + state.vz**2), dtype=dt)
    # temp_lo is the energy update's compensation carry (two-sum,
    # positions.energy_update): the true internal energy includes it.
    # Summed SEPARATELY — added per element the sub-ulp carry would
    # round away again (exactly so in an f32 accumulation)
    eint = (
        jnp.sum(const.cv * state.temp * m, dtype=dt)
        + jnp.sum(const.cv * state.temp_lo * m, dtype=dt)
    )
    etot = ekin + eint + egrav

    linmom_x = jnp.sum(m * state.vx, dtype=dt)
    linmom_y = jnp.sum(m * state.vy, dtype=dt)
    linmom_z = jnp.sum(m * state.vz, dtype=dt)
    angmom_x = jnp.sum(m * (state.y * state.vz - state.z * state.vy), dtype=dt)
    angmom_y = jnp.sum(m * (state.z * state.vx - state.x * state.vz), dtype=dt)
    angmom_z = jnp.sum(m * (state.x * state.vy - state.y * state.vx), dtype=dt)

    return {
        "ecin": ekin,
        "eint": eint,
        "egrav": jnp.asarray(egrav, dtype=ekin.dtype),
        "etot": etot,
        "linmom": jnp.sqrt(linmom_x**2 + linmom_y**2 + linmom_z**2),
        "angmom": jnp.sqrt(angmom_x**2 + angmom_y**2 + angmom_z**2),
    }
