"""Benchmark: particle-updates/sec/chip on the Sedov blast (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The headline metric is std SPH at Sedov BENCH_SIDE^3; "extra" carries the
flagship VE pipeline and VE+gravity (Evrard) throughputs, so every
pipeline the framework ships is pinned by the bench.

Baseline: BASELINE.md's north star is Sedov 100^3 within 2x of sphexa-cuda
per-chip throughput (16xA100 vs v5e-16). The reference publishes no absolute
numbers (BASELINE.md), so the per-chip baseline constant below is the
working estimate of sphexa-cuda on one A100 for this problem size;
vs_baseline = value / BASELINE_UPDATES_PER_SEC.
"""

import json
import os
import sys
import time

# sphexa-cuda per-A100 working estimate for Sedov ~1e6 (no published number)
BASELINE_UPDATES_PER_SEC = 2.0e7

SIDE = int(os.environ.get("BENCH_SIDE", "100"))
WARMUP = 2
STEPS = int(os.environ.get("BENCH_STEPS", "10"))
# auxiliary pipelines are timed at a smaller N to bound bench wall-clock
# (VE ~2.5x the std step cost; gravity adds the tree solve)
AUX_SIDE = int(os.environ.get("BENCH_AUX_SIDE", str(min(SIDE, 80))))
AUX_STEPS = int(os.environ.get("BENCH_AUX_STEPS", "6"))


def _measure(sim, n, steps):
    """Clean reconfigure-free window throughput (updates/s) or None."""
    import jax

    for _ in range(WARMUP):
        sim.step()
    d = sim.flush()
    jax.block_until_ready(sim.state.x)

    # A reconfigure swaps the static jit config: a mid-window one charges
    # a recompile to the clock directly, and one in the PREVIOUS flush
    # makes the next window's first step pay it — a window is clean only
    # when neither happened, else retry with the settled config.
    tainted = d["reconfigured"] > 0.0
    for _attempt in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            sim.step()
        d = sim.flush()
        jax.block_until_ready(sim.state.x)
        elapsed = time.perf_counter() - t0
        if d["reconfigured"] == 0.0 and not tainted:
            return n * steps / elapsed
        tainted = d["reconfigured"] > 0.0
    return None


def _gravity_scale_line(n=1_000_000):
    """Gravity-only throughput at 1M (Plummer, theta=0.5, ~58k-node
    tree): the scale where the dense MAC classification cost matters.
    Standalone solve (no hydro) so the line isolates the tree walk the
    reference benches as its nbody path. The solver shape comes from
    gravity_tuning — the SAME choice Simulation makes — so on TPU this
    line exercises the hierarchical bitmask compaction; the "extra" block
    carries a phase breakdown (multipoles / solve, plus the sort-mode
    solve for comparison when the tuned mode differs) and the compaction
    complexity proxy (compact_width: candidate slots per block's list
    materialization — num_nodes for the flat sort, super_cap for the
    hierarchical kernel)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from sphexa_tpu.gravity.traversal import (
        GravityConfig, compute_gravity, compute_multipoles,
        estimate_gravity_caps, gravity_tuning)
    from sphexa_tpu.gravity.tree import build_gravity_tree
    from sphexa_tpu.init.plummer import sample_plummer
    from sphexa_tpu.sfc.box import BoundaryType, Box
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    x, y, z, m = sample_plummer(n)
    ext = float(np.max(np.abs(np.stack([x, y, z])))) * 1.001
    box = Box.create(-ext, ext, boundary=BoundaryType.open)
    keys = np.asarray(compute_sfc_keys(jnp.asarray(x), jnp.asarray(y),
                                       jnp.asarray(z), box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (jnp.asarray(a[order]) for a in (x, y, z, m))
    skeys = jnp.asarray(keys[order])
    gtree, meta = build_gravity_tree(keys[order], bucket_size=64)
    cfg = estimate_gravity_caps(
        xs, ys, zs, ms, skeys, box, gtree, meta,
        GravityConfig(theta=0.5, bucket_size=64, G=1.0,
                      **gravity_tuning(n, jax.default_backend() == "tpu")),
        margin=1.6)
    hs = jnp.full_like(xs, 1e-3)
    args = (xs, ys, zs, ms, hs, skeys, box, gtree, meta)

    def timed_solve(c):
        out = compute_gravity(*args, c)
        jax.block_until_ready(out)
        out = compute_gravity(*args, c)  # discard post-compile outlier
        jax.block_until_ready(out)
        _ = float(out[3])
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(2):
                out = compute_gravity(*args, c)
            jax.block_until_ready(out)
            _ = float(out[3])
            best = min(best, (time.perf_counter() - t0) / 2)
        return best, out

    best, out = timed_solve(cfg)
    diag = out[4]

    # phase breakdown for the JSON extra block: the two headline terms
    # (shared multipole upsweep vs the classification+lists+eval solve),
    # and the flat-sort solve when the tuned compaction differs — the
    # direct before/after of the bitmask change on this hardware
    # discard the first standalone call: compute_multipoles has only run
    # INLINED inside compute_gravity's jit so far, and its top-level jit
    # compile would otherwise dominate the phase number
    mpc = compute_multipoles(xs, ys, zs, ms, skeys, gtree, meta)
    jax.block_until_ready(mpc)
    t0 = time.perf_counter()
    for _ in range(3):
        mpc = compute_multipoles(xs, ys, zs, ms, skeys, gtree, meta)
    jax.block_until_ready(mpc)
    t_mp = (time.perf_counter() - t0) / 3
    phases = {
        "multipoles_ms": round(t_mp * 1e3, 1),
        "solve_ms": round(best * 1e3, 1),
        "compaction": cfg.compaction,
        "super_factor": cfg.super_factor,
        "compact_width": int(diag["compact_width"]),
        "mac_work_ratio": round(float(diag["mac_work_ratio"]), 5),
    }
    if cfg.compaction != "sort":
        import dataclasses

        t_sort, _ = timed_solve(dataclasses.replace(
            cfg, compaction="sort", super_factor=0))
        phases["solve_sort_ms"] = round(t_sort * 1e3, 1)
    return {
        "gravity_1m_updates_per_sec": round(n / best, 1),
        "gravity_1m_nodes": int(meta.num_nodes),
        "gravity_1m_vs_baseline": round(
            n / best / BASELINE_UPDATES_PER_SEC, 4),
        "gravity_phases": phases,
    }


def main() -> int:
    from sphexa_tpu.init import init_evrard, init_sedov
    from sphexa_tpu.observables import ObservableSpec
    from sphexa_tpu.simulation import Simulation
    from sphexa_tpu.telemetry import Telemetry
    from sphexa_tpu.telemetry.manifest import build_manifest

    # sink-less registry shared by every benched Simulation: counters
    # (retraces/rollbacks) ride into the JSON so a bench line carries its
    # own health record, not just a throughput number
    tel = Telemetry()

    n = SIDE**3
    state, box, const = init_sedov(SIDE)
    # deferred cap-checking: the happy path issues no device->host sync
    # per step (diagnostics checked in one batch at the window end).
    # BENCH_TUNED ("auto" or a table path) routes the non-explicit knobs
    # through the committed tuning table; either way the resolved
    # provenance is stamped into extra.tuning below, so history/diff can
    # attribute a throughput change to a knob change.
    tuned = os.environ.get("BENCH_TUNED") or None
    sim = Simulation(state, box, const, prop="std", block=8192,
                     check_every=STEPS, telemetry=tel,
                     obs_spec=ObservableSpec(),
                     tuned=tuned, workload="sedov")
    tuning_stamp = {k: v for k, v in sim.tuning_provenance.items()
                    if k in ("source", "key", "knobs", "explicit")
                    and v not in (None, [], {})}
    # BENCH_TRACE_DIR: capture a jax.profiler trace of the headline
    # window and stamp its per-phase attribution into the JSON — the
    # chip-harvest workflow (docs/NEXT.md round 8: every bench round
    # carries its phase table, `sphexa-telemetry trace` re-renders it)
    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    phase_attr = None
    if trace_dir:
        import jax

        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
    std_ups = _measure(sim, n, STEPS)
    if trace_dir:
        jax.profiler.stop_trace()
        print(f"bench: profiler trace -> {trace_dir}", file=sys.stderr)
        try:
            from sphexa_tpu.telemetry.traceview import (
                phase_attr_digest,
                summarize_trace,
            )

            phase_attr = phase_attr_digest(summarize_trace(trace_dir))
        except Exception as e:  # attribution must never sink the bench
            print(f"bench: trace attribution failed: {e}", file=sys.stderr)
    if std_ups is None:
        print("bench: no reconfigure-free window in 3 attempts", file=sys.stderr)
        return 1

    extra = {}
    # how the headline run's knobs were chosen (heuristic, or a table
    # entry's key) — existing keys stay byte-compatible, this only adds
    extra["tuning"] = tuning_stamp
    if phase_attr is not None:
        extra["phase_attr"] = phase_attr
    # conservation health of the benched run, free from the in-graph
    # ledger (|etot - etot0| / |etot0| at the last flush): a perf win
    # that leaks energy is not a win, so the bench line carries its own
    # physics evidence next to the throughput number
    if sim.energy_drift is not None:
        import math

        if math.isfinite(sim.energy_drift):
            extra["std_energy_drift"] = float(f"{sim.energy_drift:.3e}")
    try:
        n_aux = AUX_SIDE**3
        state, box, const = init_sedov(AUX_SIDE)
        sim = Simulation(state, box, const, prop="ve", block=8192,
                         check_every=AUX_STEPS, telemetry=tel,
                         obs_spec=ObservableSpec())
        ve_ups = _measure(sim, n_aux, AUX_STEPS)
        if ve_ups:
            extra["ve_updates_per_sec"] = round(ve_ups, 1)
            extra["ve_side"] = AUX_SIDE
            extra["ve_vs_baseline"] = round(ve_ups / BASELINE_UPDATES_PER_SEC, 4)
    except Exception as e:  # aux lines must never sink the headline metric
        print(f"bench: VE line failed: {e}", file=sys.stderr)
    try:
        state, box, const = init_evrard(AUX_SIDE)
        sim = Simulation(state, box, const, prop="ve", block=8192,
                         check_every=AUX_STEPS, telemetry=tel,
                         obs_spec=ObservableSpec())
        nev = int(state.n)
        veg_ups = _measure(sim, nev, AUX_STEPS)
        if veg_ups:
            extra["ve_gravity_updates_per_sec"] = round(veg_ups, 1)
            extra["ve_gravity_n"] = nev
            extra["ve_gravity_vs_baseline"] = round(
                veg_ups / BASELINE_UPDATES_PER_SEC, 4
            )
    except Exception as e:
        print(f"bench: VE+gravity line failed: {e}", file=sys.stderr)
    try:
        # gravity at >=1e6 particles (VERDICT r3 #4): the Barnes-Hut
        # solve alone on a 1M Plummer sphere (the centrally-concentrated
        # distribution that stresses the MAC), dense classification at
        # the coarse target_block the Simulation picks at this N
        gup = _gravity_scale_line()
        if gup:
            extra.update(gup)
    except Exception as e:
        print(f"bench: gravity-scale line failed: {e}", file=sys.stderr)

    # per-run health counters from the shared registry (a clean bench
    # window should show retraces only from first compiles; the
    # reconfigures counter excludes each Simulation's initial sizing)
    extra["telemetry"] = {
        "retraces": int(tel.counters.get("retraces", 0)),
        "rollbacks": int(tel.counters.get("rollbacks", 0)),
        "reconfigures": int(tel.counters.get("reconfigures", 0)),
        # distributed health (schema v2): zero on single-chip benches,
        # nonzero = the mesh run resized halos / tripped the watchdog
        "halo_trips": int(tel.counters.get("halo_trips", 0)),
        "imbalances": int(tel.counters.get("imbalances", 0)),
        # physics health (schema v3): nonzero = a benched sim produced
        # nonfinite rho/h/du (drift watchdog stays off here — benches
        # run without a budget; the drift itself is std_energy_drift)
        "field_health": int(tel.counters.get("field_health", 0)),
    }

    # measured breakdowns/commentary live in docs/NEXT.md, labeled with the
    # hardware + commit they were taken on — repeating them here would
    # assert stale numbers on every future run. The manifest stamp makes
    # bench rounds diffable (`sphexa-telemetry diff BENCH_rA.json
    # BENCH_rB.json`) — existing keys stay byte-compatible.
    print(
        json.dumps(
            {
                "metric": f"particle-updates/sec/chip (Sedov {SIDE}^3, std SPH)",
                "value": round(std_ups, 1),
                "unit": "particles/s",
                "vs_baseline": round(std_ups / BASELINE_UPDATES_PER_SEC, 4),
                "extra": extra,
                "manifest": build_manifest(
                    config={"side": SIDE, "steps": STEPS,
                            "aux_side": AUX_SIDE, "aux_steps": AUX_STEPS,
                            "block": 8192, "prop": "std"},
                    particles=n,
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
