"""Benchmark: particle-updates/sec/chip on the Sedov blast (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: BASELINE.md's north star is Sedov 100^3 within 2x of sphexa-cuda
per-chip throughput (16xA100 vs v5e-16). The reference publishes no absolute
numbers (BASELINE.md), so the per-chip baseline constant below is the
working estimate of sphexa-cuda on one A100 for this problem size;
vs_baseline = value / BASELINE_UPDATES_PER_SEC.
"""

import json
import os
import sys
import time

# sphexa-cuda per-A100 working estimate for Sedov ~1e6 (no published number)
BASELINE_UPDATES_PER_SEC = 2.0e7

SIDE = int(os.environ.get("BENCH_SIDE", "100"))
WARMUP = 2
STEPS = int(os.environ.get("BENCH_STEPS", "10"))


def main() -> int:
    import jax
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    n = SIDE**3
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)

    pending_compile = False
    for _ in range(WARMUP):
        d = sim.step()
        pending_compile = d["reconfigured"] > 0
    jax.block_until_ready(sim.state.x)

    # A mid-loop reconfigure swaps the static jit config and would charge a
    # full recompile to the timed region — drop those steps from the clock.
    # (an overflow retry recompiles within the step; a post-step reconfigure
    # makes the NEXT step pay the compile — drop both)
    recompiles = 0
    elapsed = 0.0
    for _ in range(STEPS):
        t0 = time.perf_counter()
        d = sim.step()
        jax.block_until_ready(sim.state.x)
        dt_wall = time.perf_counter() - t0
        changed = d["reconfigured"] > 0
        if changed or pending_compile:
            recompiles += 1
        else:
            elapsed += dt_wall
        pending_compile = changed

    timed_steps = STEPS - recompiles
    if timed_steps == 0 or elapsed <= 0.0:
        print(
            f"bench: all {STEPS} timed steps hit a reconfigure; no valid sample",
            file=sys.stderr,
        )
        return 1
    updates_per_sec = n * timed_steps / elapsed
    print(
        json.dumps(
            {
                "metric": f"particle-updates/sec/chip (Sedov {SIDE}^3, std SPH)",
                "value": round(updates_per_sec, 1),
                "unit": "particles/s",
                "vs_baseline": round(updates_per_sec / BASELINE_UPDATES_PER_SEC, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
