"""Benchmark: particle-updates/sec/chip on the Sedov blast (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The headline metric is std SPH at Sedov BENCH_SIDE^3; "extra" carries the
flagship VE pipeline and VE+gravity (Evrard) throughputs, so every
pipeline the framework ships is pinned by the bench.

Baseline: BASELINE.md's north star is Sedov 100^3 within 2x of sphexa-cuda
per-chip throughput (16xA100 vs v5e-16). The reference publishes no absolute
numbers (BASELINE.md), so the per-chip baseline constant below is the
working estimate of sphexa-cuda on one A100 for this problem size;
vs_baseline = value / BASELINE_UPDATES_PER_SEC.
"""

import json
import os
import sys
import time

# sphexa-cuda per-A100 working estimate for Sedov ~1e6 (no published number)
BASELINE_UPDATES_PER_SEC = 2.0e7

SIDE = int(os.environ.get("BENCH_SIDE", "100"))
WARMUP = 2
STEPS = int(os.environ.get("BENCH_STEPS", "10"))
# auxiliary pipelines are timed at a smaller N to bound bench wall-clock
# (VE ~2.5x the std step cost; gravity adds the tree solve)
AUX_SIDE = int(os.environ.get("BENCH_AUX_SIDE", str(min(SIDE, 80))))
AUX_STEPS = int(os.environ.get("BENCH_AUX_STEPS", "6"))


def _measure(sim, n, steps):
    """Clean reconfigure-free window throughput (updates/s) or None."""
    import jax

    for _ in range(WARMUP):
        sim.step()
    d = sim.flush()
    jax.block_until_ready(sim.state.x)

    # A reconfigure swaps the static jit config: a mid-window one charges
    # a recompile to the clock directly, and one in the PREVIOUS flush
    # makes the next window's first step pay it — a window is clean only
    # when neither happened, else retry with the settled config.
    tainted = d["reconfigured"] > 0.0
    for _attempt in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            sim.step()
        d = sim.flush()
        jax.block_until_ready(sim.state.x)
        elapsed = time.perf_counter() - t0
        if d["reconfigured"] == 0.0 and not tainted:
            return n * steps / elapsed
        tainted = d["reconfigured"] > 0.0
    return None


def main() -> int:
    from sphexa_tpu.init import init_evrard, init_sedov
    from sphexa_tpu.simulation import Simulation

    n = SIDE**3
    state, box, const = init_sedov(SIDE)
    # deferred cap-checking: the happy path issues no device->host sync
    # per step (diagnostics checked in one batch at the window end)
    sim = Simulation(state, box, const, prop="std", block=8192,
                     check_every=STEPS)
    std_ups = _measure(sim, n, STEPS)
    if std_ups is None:
        print("bench: no reconfigure-free window in 3 attempts", file=sys.stderr)
        return 1

    extra = {}
    try:
        n_aux = AUX_SIDE**3
        state, box, const = init_sedov(AUX_SIDE)
        sim = Simulation(state, box, const, prop="ve", block=8192,
                         check_every=AUX_STEPS)
        ve_ups = _measure(sim, n_aux, AUX_STEPS)
        if ve_ups:
            extra["ve_updates_per_sec"] = round(ve_ups, 1)
            extra["ve_side"] = AUX_SIDE
            extra["ve_vs_baseline"] = round(ve_ups / BASELINE_UPDATES_PER_SEC, 4)
    except Exception as e:  # aux lines must never sink the headline metric
        print(f"bench: VE line failed: {e}", file=sys.stderr)
    try:
        state, box, const = init_evrard(AUX_SIDE)
        sim = Simulation(state, box, const, prop="ve", block=8192,
                         check_every=AUX_STEPS)
        nev = int(state.n)
        veg_ups = _measure(sim, nev, AUX_STEPS)
        if veg_ups:
            extra["ve_gravity_updates_per_sec"] = round(veg_ups, 1)
            extra["ve_gravity_n"] = nev
            extra["ve_gravity_vs_baseline"] = round(
                veg_ups / BASELINE_UPDATES_PER_SEC, 4
            )
    except Exception as e:
        print(f"bench: VE+gravity line failed: {e}", file=sys.stderr)

    # measured breakdowns/commentary live in docs/NEXT.md, labeled with the
    # hardware + commit they were taken on — repeating them here would
    # assert stale numbers on every future run
    print(
        json.dumps(
            {
                "metric": f"particle-updates/sec/chip (Sedov {SIDE}^3, std SPH)",
                "value": round(std_ups, 1),
                "unit": "particles/s",
                "vs_baseline": round(std_ups / BASELINE_UPDATES_PER_SEC, 4),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
