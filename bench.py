"""Benchmark: particle-updates/sec/chip on the Sedov blast (driver contract).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: BASELINE.md's north star is Sedov 100^3 within 2x of sphexa-cuda
per-chip throughput (16xA100 vs v5e-16). The reference publishes no absolute
numbers (BASELINE.md), so the per-chip baseline constant below is the
working estimate of sphexa-cuda on one A100 for this problem size;
vs_baseline = value / BASELINE_UPDATES_PER_SEC.
"""

import json
import os
import sys
import time

# sphexa-cuda per-A100 working estimate for Sedov ~1e6 (no published number)
BASELINE_UPDATES_PER_SEC = 2.0e7

SIDE = int(os.environ.get("BENCH_SIDE", "100"))
WARMUP = 2
STEPS = int(os.environ.get("BENCH_STEPS", "10"))


def main() -> int:
    import jax
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    n = SIDE**3
    state, box, const = init_sedov(SIDE)
    # deferred cap-checking: the happy path issues no device->host sync
    # per step (diagnostics checked in one batch at the window end)
    sim = Simulation(state, box, const, prop="std", block=8192,
                     check_every=STEPS)

    for _ in range(WARMUP):
        sim.step()
    d = sim.flush()
    jax.block_until_ready(sim.state.x)

    # A reconfigure swaps the static jit config: a mid-window one charges
    # a recompile to the clock directly, and one in the PREVIOUS flush
    # makes the next window's first step pay it — a window is clean only
    # when neither happened, else retry with the settled config.
    tainted = d["reconfigured"] > 0.0
    for _attempt in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            sim.step()
        d = sim.flush()
        jax.block_until_ready(sim.state.x)
        elapsed = time.perf_counter() - t0
        if d["reconfigured"] == 0.0 and not tainted:
            break
        tainted = d["reconfigured"] > 0.0
    else:
        print("bench: no reconfigure-free window in 3 attempts", file=sys.stderr)
        return 1
    updates_per_sec = n * STEPS / elapsed
    print(
        json.dumps(
            {
                "metric": f"particle-updates/sec/chip (Sedov {SIDE}^3, std SPH)",
                "value": round(updates_per_sec, 1),
                "unit": "particles/s",
                "vs_baseline": round(updates_per_sec / BASELINE_UPDATES_PER_SEC, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
