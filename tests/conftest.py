"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of simulating multi-node by oversubscribing
one node with mpiexec (SPH-EXA domain/test/integration_mpi/CMakeLists.txt):
here the "ranks" are XLA virtual CPU devices, and the real collectives are
the test double.
"""

import os

# Tests are CPU-only. NOTE: if the axon TPU tunnel is wedged, run pytest as
#   env -u PALLAS_AXON_POOL_IPS python -m pytest ...
# The axon sitecustomize hook registers the TPU PJRT client at interpreter
# boot (before this file runs) whenever that var is set, and a dead tunnel
# then blocks the first jax operation even under JAX_PLATFORMS=cpu.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
