"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of simulating multi-node by oversubscribing
one node with mpiexec (SPH-EXA domain/test/integration_mpi/CMakeLists.txt):
here the "ranks" are XLA virtual CPU devices, and the real collectives are
the test double.
"""

import os

# Tests are CPU-only by default. The axon sitecustomize hook pre-imports
# jax at interpreter boot with JAX_PLATFORMS=axon, so plain env-var
# assignment here is too late for jax's config — override through
# jax.config instead. XLA_FLAGS *is* still read lazily at first backend
# init, so setting it here works as long as no jax op has run yet.
#
# SPHEXA_TPU_TESTS=1 keeps the real TPU backend (for the device-equivalence
# tier, tests/test_pallas_tpu.py).
if not os.environ.get("SPHEXA_TPU_TESTS"):
    from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

    force_cpu_mesh(8)

import numpy as np
import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run the slow tier (heavy CPU-mesh equivalence + e2e runs)",
    )


def pytest_collection_modifyitems(config, items):
    """Default suite = fast tier (<5 min); the slow tier (heavy 8-device
    equivalence runs, e2e shocks, hierarchical-MAC sweeps) runs with
    --runslow or SPHEXA_ALL_TESTS=1 (VERDICT r3 #9 tier split). CI
    recipe: both tiers' results are recorded in TESTS_r{N}.json."""
    if config.getoption("--runslow") or os.environ.get("SPHEXA_ALL_TESTS"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow (or "
                            "SPHEXA_ALL_TESTS=1) to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def run_mesh_subprocess(code: str, timeout: int = 900):
    """Run mesh test code in a FRESH process on a virtual 8-device CPU
    mesh (shared scaffold: after many sharded programs compile in one
    process, the oversubscribed XLA:CPU mesh can cross-route collective
    executables — a harness artifact). ``code`` must print a sentinel;
    callers assert on the returned CompletedProcess."""
    import subprocess
    import sys
    import textwrap

    preamble = textwrap.dedent("""
        import os
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    """)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    return subprocess.run(
        [sys.executable, "-c", preamble + textwrap.dedent(code)],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=timeout,
    )
