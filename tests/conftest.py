"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of simulating multi-node by oversubscribing
one node with mpiexec (SPH-EXA domain/test/integration_mpi/CMakeLists.txt):
here the "ranks" are XLA virtual CPU devices, and the real collectives are
the test double.
"""

import os

# Tests are CPU-only by default. The axon sitecustomize hook pre-imports
# jax at interpreter boot with JAX_PLATFORMS=axon, so plain env-var
# assignment here is too late for jax's config — override through
# jax.config instead. XLA_FLAGS *is* still read lazily at first backend
# init, so setting it here works as long as no jax op has run yet.
#
# SPHEXA_TPU_TESTS=1 keeps the real TPU backend (for the device-equivalence
# tier, tests/test_pallas_tpu.py).
if not os.environ.get("SPHEXA_TPU_TESTS"):
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
