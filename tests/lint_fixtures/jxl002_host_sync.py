"""JXL002 fixture: host syncs in jit-reachable code vs. legal static uses."""

import functools

import numpy as np
import jax
import jax.numpy as jnp


@jax.jit
def bad_item(x):
    return x.item()                          # expect: JXL002


@jax.jit
def bad_conversions(x, n):
    a = float(x)                             # expect: JXL002
    b = int(n + 1)                           # expect: JXL002
    c = bool(x > 0)                          # expect: JXL002
    d = np.asarray(x)                        # expect: JXL002
    return a + b + c + d


@jax.jit
def bad_device_get(x):
    y = jax.device_get(x)                    # expect: JXL002
    x.block_until_ready()                    # expect: JXL002
    return y


@functools.partial(jax.jit, static_argnames=("cfg", "n"))
def ok_static_args(x, cfg, n):
    pad = int(n * cfg.margin)                # ok: both static
    lo = float(cfg.floor)                    # ok: static config
    return x[:pad] + lo


@jax.jit
def ok_shape_math(x):
    rows = int(x.shape[0])                   # ok: shapes are static
    total = float(np.prod(x.shape))          # ok
    k = int(len(x) // 2)                     # ok: len is static
    return x * rows * total + x[k]


def _helper(v, cfg):
    scale = float(cfg.scale)                 # ok: cfg static at call site
    return float(v)                          # expect: JXL002


@functools.partial(jax.jit, static_argnames=("cfg",))
def bad_through_helper(x, cfg):
    return _helper(x, cfg)


def _loop_body(i, carry):
    return carry + int(i)                    # expect: JXL002


def driver(x):
    # lax control flow traces its body even from host code
    total = jax.lax.fori_loop(0, 8, _loop_body, x)
    return float(total)                      # ok: outside any trace


@jax.jit
def suppressed_sync(x):
    # jaxlint: disable=JXL002 -- deliberate: fixture for suppression test
    return x.item()
