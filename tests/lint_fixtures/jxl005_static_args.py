"""JXL005 fixture: jit/shard_map static-argument hazards."""

import functools

import jax

from sphexa_tpu.propagator import shard_map


@functools.partial(jax.jit, static_argnames=("cgf",))   # expect: JXL005, JXL005
def typo_static(x, cfg):
    # the typo'd name is dead AND cfg is silently traced (two findings)
    return x * cfg.scale


@functools.partial(jax.jit, static_argnums=(3,))        # expect: JXL005
def out_of_range(x, y):
    return x + y


@jax.jit
def mutable_default(x, opts=[]):                        # expect: JXL005
    return x if not opts else x + 1


@functools.partial(jax.jit, static_argnames=("table",))
def unhashable_static(x, table={}):                     # expect: JXL005
    return x


@functools.partial(jax.jit, static_argnames=("cfg",))
def ok_static_cfg(x, cfg):                              # ok: repo idiom
    return x * cfg.scale


@functools.partial(jax.jit, static_argnums=(-1,))
def ok_negative_static(x, cfg):                         # ok: cfg static via -1
    return x * float(cfg.scale)


@functools.partial(shard_map, mesh=None, in_specs=(), out_specs=())  # expect: JXL005
def sharded_cfg(x, halo_cfg):
    return x + halo_cfg.width


def plain_helper(x, cfg):                               # ok: not jitted
    return x * cfg.scale
