"""JXL007 fixture: register_dataclass pytree-registration hygiene."""

import dataclasses
from functools import partial
from typing import Any, List, Optional, Tuple

import jax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class UndeclaredStatics:
    x: jax.Array
    mode: str                                           # expect: JXL007
    caps: Tuple[int, int]                               # expect: JXL007
    cfg: "StirConfig"                                   # expect: JXL007


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class UnhashableStatic:
    x: jax.Array
    tags: List[str] = dataclasses.field(                # expect: JXL007
        metadata=dict(static=True), default=()
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MutableDefault:
    x: jax.Array
    history: Any = []                                   # expect: JXL007


@partial(jax.tree_util.register_dataclass,
         data_fields=("lo", "hi"), meta_fields=("kind",))
@dataclasses.dataclass
class CleanMetaFields:
    lo: jax.Array
    hi: jax.Array
    kind: str = "open"                                  # ok: in meta_fields


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CleanExplicit:
    # the Box.boundaries idiom: static-shaped, declared static, hashable
    lo: jax.Array
    kinds: Tuple[str, str, str] = dataclasses.field(
        metadata=dict(static=True), default=("open", "open", "open")
    )
    aux: Optional[Any] = None                           # ok: pytree slot


@dataclasses.dataclass
class PlainDataclass:
    # not registered as a pytree: nothing to declare
    mode: str = "fast"
    history: Any = None
