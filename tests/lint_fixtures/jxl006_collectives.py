"""JXL006 fixture: direct lax collectives vs chain_after-routed ones."""

import jax
from jax import lax

from sphexa_tpu.parallel.exchange import chain_after


def unchained_pair(x, y):
    r = jax.lax.ppermute(x, "p", [(0, 1), (1, 0)])   # expect: JXL006
    s = jax.lax.pmax(y, "p")                          # expect: JXL006
    return r, s


def aliased_import_collective(x):
    return lax.psum(x, "p")                           # expect: JXL006


def chained_pair(x, y):
    r = jax.lax.ppermute(x, "p", [(0, 1), (1, 0)])   # ok: chain token below
    s = jax.lax.pmax(chain_after(y, r), "p")         # ok: order pinned
    return r, s


def outer_chains(x, y):
    r = jax.lax.ppermute(x, "p", [(0, 1), (1, 0)])   # ok: enclosing chains

    def tail(v):
        return jax.lax.psum(v, "p")                  # ok: enclosing chains

    return tail(chain_after(y, r))


def suppressed_upsweep(w):
    # data-chained pyramid: each psum feeds the next, order is total
    a = jax.lax.psum(w, "p")      # jaxlint: disable=JXL006 -- data-chained
    return jax.lax.psum(a, "p")   # jaxlint: disable=JXL006 -- data-chained


def coordinate_read(x):
    return x + jax.lax.axis_index("p")               # ok: no comm, not flagged
