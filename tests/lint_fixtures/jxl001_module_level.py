"""JXL001 fixture: import-time jnp construction (never imported, only
parsed — tests/test_lint.py matches findings against `# expect:` tags)."""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import numpy as jnumpy

KEY_DTYPE = jnp.uint32                      # ok: alias, not a call
KEY_BITS = 10                               # ok: python int
BAD_SCALAR = jnp.uint32(1 << 30)            # expect: JXL001
BAD_TABLE = jnp.zeros((8, 128))             # expect: JXL001
BAD_VIA_FROM = jnumpy.arange(4)             # expect: JXL001
BAD_DEVICE = jax.device_put(np.zeros(3))    # expect: JXL001
OK_NUMPY = np.zeros(3)                      # ok: host constant
OK_LAZY = lambda: jnp.zeros(3)              # ok: deferred


if KEY_BITS > 5:
    BAD_IN_IF = jnp.ones(2)                 # expect: JXL001

try:
    BAD_IN_TRY = jnp.full(3, 1.0)           # expect: JXL001
except Exception:
    pass


class Config:
    BAD_CLASS_ATTR = jnp.array([1.0])       # expect: JXL001
    OK_ALIAS = jnp.float32                  # ok: alias


def bad_default(x, scale=jnp.float32(2.0)):  # expect: JXL001
    return x * scale


def ok_inside():
    return jnp.zeros(3)                     # ok: runs at call time


@functools.partial(jax.jit, static_argnames=())
def ok_decorated(x):                        # ok: jit at import is fine
    return x + 1
