"""JXL004 fixture: Pallas tile shapes off the (8, 128) Mosaic grid."""

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def specs(G, kernel):
    bad_lane = pl.BlockSpec((1, 8, 100), lambda g: (g, 0, 0))      # expect: JXL004
    bad_sublane = pl.BlockSpec((1, 5, 128), lambda g: (g, 0, 0))   # expect: JXL004
    ok_tile = pl.BlockSpec((1, 8, 256), lambda g: (g, 0, 0))       # ok
    ok_row = pl.BlockSpec((1, 1, 128), lambda g: (g, 0, 0))        # ok: sublane 1
    ok_sym = pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))          # ok: symbolic
    ok_any = pl.BlockSpec(memory_space=pl.ANY)                     # ok: untiled
    ok_smem = pl.BlockSpec((1, 1, 3), lambda g: (0, 0, 0),
                           memory_space=pltpu.SMEM)                # ok: scalar mem
    bad_kw = pl.BlockSpec(block_shape=(16, 64),                    # expect: JXL004
                          index_map=lambda g: (g, 0))
    bad_scratch = pltpu.VMEM((2, 3, 128), jnp.float32)             # expect: JXL004
    ok_scratch = pltpu.VMEM((2, 8, 128), jnp.float32)              # ok
    return (bad_lane, bad_sublane, ok_tile, ok_row, ok_sym, ok_any,
            ok_smem, bad_kw, bad_scratch, ok_scratch)
