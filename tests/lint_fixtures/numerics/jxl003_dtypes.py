"""JXL003 fixture: dtype-policy bypasses. Lives under a ``numerics``
directory because the rule is path-scoped to state-constructing modules."""

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.dtypes import COORD_DTYPE, HYDRO_DTYPE, INDEX_DTYPE


def build(n):
    x = jnp.zeros(n, jnp.float32)            # expect: JXL003
    i = jnp.arange(n, dtype=jnp.int32)       # expect: JXL003
    k = jnp.asarray(i, jnp.uint32)           # expect: JXL003
    w = jnp.asarray(x, jnp.float64)          # expect: JXL003
    ok_x = jnp.zeros(n, COORD_DTYPE)         # ok: policy name
    ok_h = jnp.ones(n, HYDRO_DTYPE)          # ok
    ok_i = jnp.arange(n, dtype=INDEX_DTYPE)  # ok
    ok_np = np.zeros(n, np.float32)          # ok: host-side numpy
    return x, i, k, w, ok_x, ok_h, ok_i, ok_np
