"""Gravity-coupled propagator tests.

Mirrors the reference's NbodyProp (main/src/propagator/nbody.hpp) usage:
a Plummer sphere advanced by the gravity-only propagator must (a) produce
step-0 accelerations matching direct summation and (b) conserve total
energy over a few steps with the acceleration-limited timestep.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.gravity import direct_gravity
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.simulation import Simulation
from sphexa_tpu.sph.particles import ParticleState, SimConstants

from test_gravity import plummer


def _plummer_state(n=2000, seed=3):
    x, y, z, m = plummer(n, seed)
    lim = float(np.max(np.abs([x, y, z]))) * 1.01
    box = Box.create(-lim, lim)
    f32 = lambda a: jnp.asarray(a, jnp.float32)
    state = ParticleState.zeros(n)
    import dataclasses

    state = dataclasses.replace(
        state,
        x=f32(x), y=f32(y), z=f32(z),
        h=jnp.full(n, 0.02, jnp.float32), m=f32(m),
        min_dt=jnp.float32(1e-4), min_dt_m1=jnp.float32(1e-4),
    )
    const = SimConstants(g=1.0).normalized()
    return state, box, const


@pytest.mark.slow
class TestNbodyPropagator:
    def test_runs_and_reports_egrav(self):
        state, box, const = _plummer_state()
        sim = Simulation(state, box, const, prop="nbody")
        d = sim.step()
        assert "egrav" in d and d["egrav"] < 0.0
        assert d["dt"] > 0.0
        assert sim.iteration == 1

    def test_energy_conservation_few_steps(self):
        """Total (kinetic + potential) energy drift over 5 steps stays small
        relative to |egrav| — the Barnes-Hut + integrator sanity bound."""
        state, box, const = _plummer_state()
        sim = Simulation(state, box, const, prop="nbody")
        history = []
        for _ in range(5):
            d = sim.step()
            s = sim.state
            ekin = float(0.5 * jnp.sum(s.m * (s.vx**2 + s.vy**2 + s.vz**2)))
            history.append(ekin + d["egrav"])
        drift = abs(history[-1] - history[0]) / abs(history[0])
        assert drift < 5e-2, f"energy drift {drift} over 5 steps: {history}"

    def test_step0_accel_matches_direct(self):
        """One tiny step's velocity change direction must match direct-sum
        gravity (the nbody propagator is the only acceleration source)."""
        state, box, const = _plummer_state(n=1500)
        sim = Simulation(state, box, const, prop="nbody")
        sim.step()
        s = sim.state  # arrays now SFC-sorted
        ax_d, ay_d, az_d, _ = direct_gravity(s.x, s.y, s.z, s.m, s.h)
        dt = float(s.min_dt)
        # velocity after the first step ~ a*(dt + dt_m1/2) per the Press
        # scheme from rest; compare directions via normalized dot product
        v = np.stack([np.asarray(s.vx), np.asarray(s.vy), np.asarray(s.vz)], 1)
        a = np.stack([np.asarray(ax_d), np.asarray(ay_d), np.asarray(az_d)], 1)
        vn = np.linalg.norm(v, axis=1)
        an = np.linalg.norm(a, axis=1)
        ok = (vn > 1e-12) & (an > 1e-12)
        cos = np.sum(v[ok] * a[ok], axis=1) / (vn[ok] * an[ok])
        assert np.quantile(cos, 0.05) > 0.97, "velocities not aligned with gravity"


class TestHydroGravity:
    def test_std_hydro_with_gravity_smoke(self):
        """std-SPH with g != 0 runs and reports egrav (Evrard-style coupling,
        gravity_wrapper.hpp usage inside computeForces)."""
        side = 10
        n = side**3
        rng = np.random.default_rng(0)
        g = (np.arange(side) + 0.5) / side - 0.5
        X, Y, Z = np.meshgrid(g, g, g, indexing="ij")
        x = X.ravel() + rng.normal(0, 1e-3, n)
        y = Y.ravel() + rng.normal(0, 1e-3, n)
        z = Z.ravel() + rng.normal(0, 1e-3, n)
        box = Box.create(-0.5, 0.5)
        import dataclasses

        state = ParticleState.zeros(n)
        state = dataclasses.replace(
            state,
            x=jnp.asarray(x, jnp.float32),
            y=jnp.asarray(y, jnp.float32),
            z=jnp.asarray(z, jnp.float32),
            h=jnp.full(n, 0.15, jnp.float32),
            m=jnp.full(n, 1.0 / n, jnp.float32),
            temp=jnp.full(n, 10.0, jnp.float32),
            min_dt=jnp.float32(1e-6), min_dt_m1=jnp.float32(1e-6),
        )
        const = SimConstants(ng0=50, ngmax=100, g=1.0).normalized()
        sim = Simulation(state, box, const, prop="std")
        d = sim.step()
        assert sim.gravity_on
        assert "egrav" in d and d["egrav"] < 0.0
        d2 = sim.step()
        assert np.isfinite(d2["rho_max"])
