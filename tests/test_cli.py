"""CLI front-end tests: flag vocabulary, output files, wextra triggers,
ascii dumps, duration cutoff, profile series. Mirrors
main/test/io/arg_parser.cpp plus e2e smoke of the sphexa.cpp main loop.
"""

import os

import numpy as np
import pytest

from sphexa_tpu.app.main import build_parser, main


def run_cli(*argv):
    return main(list(argv))


class TestParser:
    def test_defaults(self):
        args = build_parser().parse_args([])
        assert args.init == "sedov"
        assert args.side == 50
        assert args.theta == 0.5
        assert args.grav_constant is None

    def test_unknown_prop_rejected(self, capsys):
        assert run_cli("--prop", "bogus", "-n", "6", "-s", "1") == 2

    def test_unknown_case_rejected(self):
        assert run_cli("--init", "not-a-case", "-n", "6", "-s", "1") == 2


class TestEndToEnd:
    def test_basic_run_writes_constants(self, tmp_path):
        out = str(tmp_path)
        assert run_cli("--init", "sedov", "-n", "6", "-s", "2",
                       "-o", out, "--quiet") == 0
        lines = open(f"{out}/constants.txt").read().strip().split("\n")
        assert len(lines) == 3  # header + 2 rows

    def test_wextra_triggers(self, tmp_path):
        out = str(tmp_path)
        assert run_cli("--init", "sedov", "-n", "6", "-s", "3",
                       "--wextra", "2", "-o", out, "--quiet") == 0
        from sphexa_tpu.io import list_steps

        path = f"{out}/dump_sedov.h5"
        assert os.path.exists(path)
        assert len(list_steps(path)) == 1

    def test_ascii_dump(self, tmp_path):
        out = str(tmp_path)
        assert run_cli("--init", "sedov", "-n", "6", "-s", "2", "-w", "2",
                       "--ascii", "-o", out, "--quiet") == 0
        files = [f for f in os.listdir(out) if f.endswith(".txt") and "dump" in f]
        assert files
        data = np.loadtxt(f"{out}/{files[0]}")
        assert data.shape[0] == 6**3

    def test_profile_series(self, tmp_path):
        out = str(tmp_path)
        assert run_cli("--init", "sedov", "-n", "6", "-s", "2",
                       "--profile", "-o", out, "--quiet") == 0
        prof = np.load(f"{out}/profile.npz")
        assert "step" in prof.files and len(prof["step"]) == 2

    def test_duration_cutoff(self, tmp_path):
        out = str(tmp_path)
        # duration 0: stops after the first iteration, dumps a final snapshot
        assert run_cli("--init", "sedov", "-n", "6", "-s", "50", "-w", "50",
                       "--duration", "0", "-o", out, "--quiet") == 0
        from sphexa_tpu.io import list_steps

        assert list_steps(f"{out}/dump_sedov.h5") == [0]

    def test_deferred_run_keeps_every_constants_row(self, tmp_path):
        """ISSUE-8 acceptance: a --check-every 8 deferred Sedov run
        writes a constants.txt row for EVERY step, matching the synced
        run's columns to reduction-order tolerance (the in-graph ledger
        fetched at the flush boundary — the old eager path skipped rows
        inside deferred windows entirely)."""
        sync, deferred = str(tmp_path / "sync"), str(tmp_path / "def")
        assert run_cli("--init", "sedov", "-n", "6", "-s", "8",
                       "-o", sync, "--quiet") == 0
        assert run_cli("--init", "sedov", "-n", "6", "-s", "8",
                       "--check-every", "8", "-o", deferred,
                       "--quiet") == 0
        a = np.loadtxt(f"{sync}/constants.txt")
        b = np.loadtxt(f"{deferred}/constants.txt")
        assert a.shape == b.shape == (8, 7)
        assert list(b[:, 0]) == list(range(1, 9))  # every iteration
        np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-12)

    def test_drift_budget_flag_emits_watchdog_events(self, tmp_path):
        out = str(tmp_path / "out")
        tdir = str(tmp_path / "tel")
        # a negative budget trips on ANY drift including zero — proves
        # the flag reaches the watchdog without depending on how many
        # ulps a 4-step Sedov wiggles; exit stays 0 (watchdogs report,
        # they don't abort the run)
        assert run_cli("--init", "sedov", "-n", "6", "-s", "4",
                       "--drift-budget=-1.0", "-o", out,
                       "--telemetry-dir", tdir, "--quiet") == 0
        import json

        events = [json.loads(l) for l in open(f"{tdir}/events.jsonl")]
        assert any(e["kind"] == "drift" for e in events)
        from sphexa_tpu.telemetry.cli import main as tcli

        assert tcli(["science", tdir]) == 1  # watchdog fired in-run

    def test_g_override_enables_gravity(self, tmp_path):
        out = str(tmp_path)
        # noh is open-boundary, g=0 by default; --G turns gravity on
        assert run_cli("--init", "noh", "-n", "6", "-s", "1",
                       "--G", "1.0", "-o", out, "--quiet") == 0
        lines = open(f"{out}/constants.txt").read().strip().split("\n")
        egrav = float(lines[1].split()[6])
        assert egrav < 0  # bound sphere has negative gravitational energy
