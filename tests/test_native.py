"""Native C++ host-runtime tests: bit-equality with the jax SFC codec and
the numpy accounting helpers (the native analog of the reference's
CPU/GPU equivalence tier). If the library cannot build, the fallback path
is exercised instead — both paths must produce identical results.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu import native
from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc.box import Box, BoundaryType
from sphexa_tpu.sfc.keys import compute_sfc_keys


@pytest.fixture(scope="module")
def cloud(rng_module=np.random.default_rng(3)):
    n = 5000
    x, y, z = rng_module.uniform(-0.5, 0.5, (3, n)).astype(np.float32)
    return x, y, z


def test_library_builds_and_loads():
    # the image ships g++; the library must build (fallback is for
    # environments without a toolchain)
    assert native.available()


@pytest.mark.parametrize("curve", ["hilbert", "morton"])
def test_keys_match_jax_codec(cloud, curve):
    x, y, z = cloud
    lo = np.array([-0.5] * 3, np.float32)
    ln = np.array([1.0] * 3, np.float32)
    kn = native.compute_keys(x, y, z, lo, ln, curve=curve)
    box = Box.create(-0.5, 0.5, boundary=BoundaryType.open)
    kj = np.asarray(
        compute_sfc_keys(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z),
                         box, curve=curve)
    )
    np.testing.assert_array_equal(kn, kj)


def test_argsort_matches_numpy(cloud):
    x, y, z = cloud
    keys = native.compute_keys(
        x, y, z, np.array([-0.5] * 3, np.float32), np.array([1.0] * 3, np.float32)
    )
    np.testing.assert_array_equal(
        native.argsort_keys(keys), np.argsort(keys, kind="stable")
    )


def test_occupancy_matches_bincount(cloud):
    x, y, z = cloud
    keys = native.compute_keys(
        x, y, z, np.array([-0.5] * 3, np.float32), np.array([1.0] * 3, np.float32)
    )
    sk = np.sort(keys)
    for level in (1, 2, 3, 5):
        shift = 3 * (KEY_BITS - level)
        expect = int(np.bincount((sk >> np.uint32(shift)).astype(np.int64)).max())
        assert native.max_cell_occupancy(sk, level) == expect


def test_group_extents_match_numpy(cloud):
    x, y, z = cloud
    keys = native.compute_keys(
        x, y, z, np.array([-0.5] * 3, np.float32), np.array([1.0] * 3, np.float32)
    )
    order = native.argsort_keys(keys)
    ext = native.group_extents(x, y, z, order, 128)
    n = len(x)
    ng = -(-n // 128)
    pad = ng * 128 - n
    for d, a in enumerate((x, y, z)):
        s = a[order]
        if pad:
            s = np.concatenate([s, np.repeat(s[-1], pad)])
        g = s.reshape(ng, 128)
        assert ext[d] == pytest.approx(float((g.max(1) - g.min(1)).max()), rel=1e-6)


def test_config_pipeline_uses_native(cloud):
    """make_propagator_config runs through the native sizing path and
    produces a working config."""
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_sedov(8)
    sim = Simulation(state, box, const, prop="std", block=256)
    d = sim.step()
    assert np.isfinite(d["dt"])
