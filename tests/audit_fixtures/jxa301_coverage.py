"""JXA301 fixtures: phase-coverage over the cost model's attribution.
The unscoped entry runs all its FLOPs outside any ``sphexa/<phase>``
scope (coverage 0 under the default floor); the off-taxonomy entry
stamps a scope the util/phases.py taxonomy does not know (flagged even
with the floor waived); the scoped twin attributes fully and passes."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint
from sphexa_tpu.util.phases import phase_scope

_N = 4096


def _unscoped(x):
    return jnp.tanh(x) + 1.0


@entrypoint("unscoped_step")  # expect: JXA301
def unscoped_step():
    return EntryCase(fn=_unscoped, args=(jnp.zeros(_N, jnp.float32),))


def _off_taxonomy(x):
    with jax.named_scope("sphexa/warpdrive"):
        return jnp.tanh(x) + 1.0


# floor waived: only the off-taxonomy scope itself is the violation
@entrypoint("off_taxonomy_scope", phase_coverage_min=0.0)  # expect: JXA301
def off_taxonomy_scope():
    return EntryCase(fn=_off_taxonomy, args=(jnp.zeros(_N, jnp.float32),))


def _scoped(x):
    with phase_scope("density"):
        return jnp.tanh(x) + 1.0


@entrypoint("scoped_step")
def scoped_step():
    return EntryCase(fn=_scoped, args=(jnp.zeros(_N, jnp.float32),))
