"""JXA302 fixtures: predicted per-phase ms vs a committed budget file.
The busted entry's sidecar (jxa302_budget.json) pins an absurdly low
density ceiling; the missing-file entry DECLARES a budget that does not
exist (a broken gate must be a finding, not a silent pass); the
unbudgeted twin shares the sidecar but has no entry in it and passes."""

import os

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint
from sphexa_tpu.util.phases import phase_scope

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUDGET = os.path.join(_HERE, "jxa302_budget.json")
_SIDE = 256


def _dense(a, b):
    with phase_scope("density"):
        return a @ b


def _args():
    return (jnp.zeros((_SIDE, _SIDE), jnp.float32),
            jnp.zeros((_SIDE, _SIDE), jnp.float32))


@entrypoint("busted_budget", cost_budget_file=_BUDGET)  # expect: JXA302
def busted_budget():
    return EntryCase(fn=_dense, args=_args())


@entrypoint("missing_budget",  # expect: JXA302
            cost_budget_file=os.path.join(_HERE, "no_such_budget.json"))
def missing_budget():
    return EntryCase(fn=_dense, args=_args())


@entrypoint("unbudgeted_entry", cost_budget_file=_BUDGET)
def unbudgeted_entry():
    return EntryCase(fn=_dense, args=_args())
