"""JXA202 fixtures: the same elementwise program against the same
per-entry HBM budget — without donation the input and output buffers
coexist and bust it; with donation (the aliasing JXA103 verifies) the
output is credited onto the input buffer and the entry fits."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint

_N = 1 << 16                      # 256 KiB of f32
_BYTES = _N * 4
_BUDGET = _BYTES + _BYTES // 2    # fits one buffer + slack, not two


def _shift(x):
    return x + 1.0


@entrypoint("undonated_over_budget", hbm_budget=_BUDGET, phase_coverage_min=0.0)  # expect: JXA202
def undonated_over_budget():
    return EntryCase(fn=_shift, args=(jnp.zeros(_N),))


@entrypoint("donated_within_budget", donate=(0,), hbm_budget=_BUDGET, phase_coverage_min=0.0)
def donated_within_budget():
    jitted = jax.jit(_shift, donate_argnums=0)
    x = jnp.zeros(_N)
    return EntryCase(fn=_shift, args=(x,), lower=lambda: jitted.lower(x))
