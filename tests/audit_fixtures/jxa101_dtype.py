"""JXA101 fixture: deliberate f64 in a traced body.

With x64 disabled jax silently demotes f64 requests, so these entries
opt into ``x64=True`` — the auditor traces them under
``jax.experimental.enable_x64`` (the config a conservation-diagnostics
run would use) where the cast really produces float64.
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("bad_f64_cast", x64=True, phase_coverage_min=0.0)  # expect: JXA101
def bad_f64_cast():
    def fn(x):
        return (x.astype(jnp.float64) * 2.0).sum()

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("clean_f32", x64=True, phase_coverage_min=0.0)
def clean_f32():
    def fn(x):
        return (x * 2.0).sum()

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))
