"""JXA102 fixtures: signature drift across steps / weak-type leaks.

``bad_dtype_carry``: the carried scalar comes back bf16 — step 2's input
signature differs from step 1's and the whole step retraces.
``bad_weak_leak``: a host-fed Python float (weak f32) flows straight to
an output; a caller feeding outputs back (or logging them into state)
inherits the weak/strong flip-flop. ``clean_normalized`` pins the scalar
to the policy dtype at the boundary, so both probes pass.
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("bad_dtype_carry", phase_coverage_min=0.0)  # expect: JXA102, JXA503
def bad_dtype_carry():
    def fn(x, t):
        return x * 2.0, (t + 1.0).astype(jnp.bfloat16)

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(4, jnp.float32), jnp.float32(0.0)),
        carry=lambda a, out: (out[0], out[1]),
    )


@entrypoint("bad_weak_leak", phase_coverage_min=0.0)  # expect: JXA102
def bad_weak_leak():
    def fn(x, s):
        return x.sum(), s * 2.0

    def perturb(args):
        return (args[0], 3.0)  # host-fed Python float: weak f32

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(4, jnp.float32), jnp.float32(3.0)),
        perturb=perturb,
    )


@entrypoint("clean_normalized", phase_coverage_min=0.0)
def clean_normalized():
    def fn(x, s):
        s = jnp.asarray(s, jnp.float32)  # boundary normalization
        return x.sum(), s * 2.0

    def perturb(args):
        return (args[0], 3.0)

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(4, jnp.float32), jnp.float32(3.0)),
        carry=lambda a, out: (a[0], out[1]),
        perturb=perturb,
    )
