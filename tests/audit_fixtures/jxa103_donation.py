"""JXA103 fixtures: a declared-donatable state pytree left undonated
(the double-buffering miss) vs the donated twin pattern."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


def _step(state, k):
    return jax.tree.map(lambda a: a * k, state), k * 1.0


def _state():
    return {"x": jnp.zeros(16), "y": jnp.ones(16)}


@entrypoint("undonated_state", donate=(0,), phase_coverage_min=0.0)  # expect: JXA103
def undonated_state():
    jitted = jax.jit(_step)
    args = (_state(), jnp.float32(2.0))
    return EntryCase(fn=jitted, args=args,
                     lower=lambda: jitted.lower(*args))


@entrypoint("donated_state", donate=(0,), phase_coverage_min=0.0)
def donated_state():
    plain = jax.jit(_step)
    donated = jax.jit(_step, donate_argnums=(0,))
    args = (_state(), jnp.float32(2.0))
    return EntryCase(fn=plain, args=args,
                     lower=lambda: donated.lower(*args))
