"""JXA201 fixtures: two collectives with no data-dependency order (the
PR-5 XLA:CPU rendezvous-race shape — a ppermute and a pmax that XLA may
interleave differently per device) vs the same pair pinned into a total
order with exchange.chain_after."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, EntrySkip, entrypoint


def _stage_fn(chained: bool):
    from jax.sharding import PartitionSpec as P

    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.propagator import shard_map

    if len(jax.devices()) < 2:
        raise EntrySkip("needs >= 2 devices for the fixture mesh")
    mesh = make_mesh(2)

    def stage(x, y):
        from sphexa_tpu.parallel.exchange import chain_after

        r = jax.lax.ppermute(x, "p", [(0, 1), (1, 0)])
        if chained:
            y = chain_after(y, r)
        s = jax.lax.pmax(y, "p")
        return r, s

    return jax.jit(shard_map(
        stage, mesh=mesh, in_specs=(P("p"), P("p")),
        out_specs=(P("p"), P()), check_vma=False,
    ))


@entrypoint("unchained_collectives", mesh_axes=("p",), phase_coverage_min=0.0)  # expect: JXA201
def unchained_collectives():
    return EntryCase(fn=_stage_fn(False),
                     args=(jnp.zeros(8), jnp.zeros(8)))


@entrypoint("chained_collectives", mesh_axes=("p",), phase_coverage_min=0.0)
def chained_collectives():
    return EntryCase(fn=_stage_fn(True),
                     args=(jnp.zeros(8), jnp.zeros(8)))
