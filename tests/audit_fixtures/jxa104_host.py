"""JXA104 fixtures: host-boundary leaks in the traced body (a debug
print left in a hot function, a per-step pure_callback), plus an
inline-suppressed deliberate probe."""

import jax
import jax.numpy as jnp
import numpy as np

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("debug_print_in_body", phase_coverage_min=0.0)  # expect: JXA104
def debug_print_in_body():
    def fn(x):
        jax.debug.print("x0 = {}", x[0])
        return x * 2.0

    return EntryCase(fn=fn, args=(jnp.zeros(4),))


@entrypoint("callback_in_body", phase_coverage_min=0.0)  # expect: JXA104
def callback_in_body():
    def fn(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x,
        )
        return y + 1.0

    return EntryCase(fn=fn, args=(jnp.zeros(4),))


@entrypoint("clean_device_only", phase_coverage_min=0.0)
def clean_device_only():
    def fn(x):
        # np-constant staging (device_put with no target) must NOT fire
        table = jnp.asarray(np.arange(8, dtype=np.float32))
        return x + table.sum()

    return EntryCase(fn=fn, args=(jnp.zeros(4),))


# jaxaudit: disable=JXA104 -- deliberate probe: fixture for the suppression path
@entrypoint("suppressed_debug_print", phase_coverage_min=0.0)
def suppressed_debug_print():
    def fn(x):
        jax.debug.print("probe {}", x[0])
        return x

    return EntryCase(fn=fn, args=(jnp.zeros(4),))
