"""JXA402 fixture: a knob whose declared off value leaks into the
lowering.

The probes are manufactured directly (``lowerdiff.KnobProbe`` over
``fingerprint_callable``) so the fixture exercises the RULE — compare
off vs unset fingerprints, fire on digest drift — without building a
Simulation. The production probe builder
(``lowerdiff.production_knob_probes``) is pinned separately by
tests/test_lowerdiff.py over the real tuning/knobs.py registry.

The firing entry's "off" program carries one extra eqn (the classic
leak: an off-path guard that still lowers a select); the honest twin's
off program is eqn-for-eqn the baseline.
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint
from sphexa_tpu.devtools.audit.lowerdiff import (
    KnobProbe,
    fingerprint_callable,
)

_X = jnp.ones((8,), jnp.float32)


def _base(x):
    return x * 2.0


def _leaky_off(x):
    # the off path leaves a residue: one extra eqn vs never mentioning
    # the knob (a real leak looks like a dead select or an extra
    # convert the "disabled" branch still lowers)
    return x * 2.0 + 0.0


def _leaky_probes():
    return [KnobProbe(
        knob="leaky_gate", off_value=0,
        base=fingerprint_callable(_base, _X),
        off=fingerprint_callable(_leaky_off, _X),
        detail="fixture leaky_gate: off lowers one extra eqn",
    )]


def _inert_probes():
    return [KnobProbe(
        knob="inert_gate", off_value=0,
        base=fingerprint_callable(_base, _X),
        off=fingerprint_callable(_base, _X),
        detail="fixture inert_gate: off is indistinguishable from unset",
    )]


@entrypoint("leaky_off_knob", phase_coverage_min=0.0)  # expect: JXA402
def leaky_off_knob():
    return EntryCase(fn=lambda x: x * 1.0, args=(_X,),
                     knob_probes=_leaky_probes)


@entrypoint("inert_off_knob", phase_coverage_min=0.0)
def inert_off_knob():
    return EntryCase(fn=lambda x: x * 1.0, args=(_X,),
                     knob_probes=_inert_probes)
