"""JXA303 fixtures: a phase DECLARED compute-bound must sit above the
device ridge point. The streaming entry's density phase is a pure
bandwidth-bound elementwise pass (AI << ridge) — the degraded-gather
regression shape; the stale entry declares a phase its program never
stamps; the dense twin's big dot really is compute-bound and passes."""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint
from sphexa_tpu.util.phases import phase_scope

_N = 1 << 16
_SIDE = 768


def _stream(x):
    with phase_scope("density"):
        return x * 2.0 + 1.0


@entrypoint("claims_compute_bound",  # expect: JXA303
            expect_compute_bound=("density",))
def claims_compute_bound():
    return EntryCase(fn=_stream, args=(jnp.zeros(_N, jnp.float32),))


@entrypoint("stale_declaration",  # expect: JXA303
            expect_compute_bound=("gravity-p2p",))
def stale_declaration():
    return EntryCase(fn=_stream, args=(jnp.zeros(_N, jnp.float32),))


def _dense(a, b):
    with phase_scope("density"):
        return a @ b


@entrypoint("really_compute_bound", expect_compute_bound=("density",))
def really_compute_bound():
    return EntryCase(fn=_dense,
                     args=(jnp.zeros((_SIDE, _SIDE), jnp.float32),
                           jnp.zeros((_SIDE, _SIDE), jnp.float32)))
