"""JXA204 fixtures: two-point growth probes over the rescale-exempt
buffer class. The quadratic entry materializes an O(n^2) work buffer
sized to dodge the extensive (slab-multiple) classification — exactly
the superlinear-tree shape the round-10 caution warned JXA202's
traced-size exemption would hide; the linear twin's scratch grows
proportionally to N and passes."""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint

_N, _N_GROWN = 12, 24             # a 2x N probe


def _quad(x):
    n = x.shape[0]
    # n*n+1 elems: indivisible by both n and its pow2 padding, so the
    # buffer lands in the rescale-EXEMPT class while growing O(n^2)
    pair = jnp.zeros((n * n + 1,), jnp.float32) + x.sum()
    return pair.sum() + x.sum()


def _quad_case(n):
    return EntryCase(fn=_quad, args=(jnp.zeros(n, jnp.float32),))


@entrypoint("quadratic_scratch", phase_coverage_min=0.0)  # expect: JXA204
def quadratic_scratch():
    case = _quad_case(_N)
    case.grow = lambda: (_quad_case(_N_GROWN), _N_GROWN / _N)
    return case


def _lin(x):
    n = x.shape[0]
    scratch = jnp.zeros((n + 1,), jnp.float32) + x.sum()
    return scratch.sum() + x.sum()


def _lin_case(n):
    return EntryCase(fn=_lin, args=(jnp.zeros(n, jnp.float32),))


@entrypoint("linear_scratch", phase_coverage_min=0.0)
def linear_scratch():
    case = _lin_case(_N)
    case.grow = lambda: (_lin_case(_N_GROWN), _N_GROWN / _N)
    return case
