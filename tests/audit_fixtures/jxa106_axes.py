"""JXA106 fixtures: a collective whose axis disagrees with the entry's
declared mesh sharding (code says 'p', registration says 'data') vs the
consistent declaration."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, EntrySkip, entrypoint


def _psum_fn():
    from jax.sharding import PartitionSpec as P

    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.propagator import shard_map

    if len(jax.devices()) < 2:
        raise EntrySkip("needs >= 2 devices for the fixture mesh")
    mesh = make_mesh(2)
    return jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "p"),
        mesh=mesh, in_specs=P("p"), out_specs=P(), check_vma=False,
    ))


@entrypoint("wrong_axis_declaration", mesh_axes=("data",), phase_coverage_min=0.0)  # expect: JXA106
def wrong_axis_declaration():
    return EntryCase(fn=_psum_fn(), args=(jnp.zeros(8),))


@entrypoint("matching_axis_declaration", mesh_axes=("p",), phase_coverage_min=0.0)
def matching_axis_declaration():
    return EntryCase(fn=_psum_fn(), args=(jnp.zeros(8),))
