"""JXA401 fixture: unordered float scatter accumulation.

The firing entry accumulates float updates at DUPLICATE indices with
neither ``unique_indices`` nor ``indices_are_sorted`` declared — XLA may
combine the colliding adds in any order, and float addition does not
commute in rounding, so two runs of the same program need not agree
bitwise. The honest twin performs the same accumulation but declares
``indices_are_sorted=True`` (its index vector IS non-decreasing — the
gravity-upsweep pattern from gravity/traversal.py, where the
level-ordered layout fixes the segment order).
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("unordered_scatter_add", phase_coverage_min=0.0)  # expect: JXA401
def unordered_scatter_add():
    # duplicate indices on purpose: rows 0 and 2 each collide
    idx = jnp.array([0, 0, 2, 2], dtype=jnp.int32)

    def fn(acc, upd):
        return acc.at[idx].add(upd)

    return EntryCase(
        fn=fn, args=(jnp.zeros(4, jnp.float32), jnp.ones(4, jnp.float32)))


@entrypoint("sorted_scatter_add", phase_coverage_min=0.0)
def sorted_scatter_add():
    # the SAME colliding accumulation, replay-safe: the index vector is
    # non-decreasing and says so, fixing the combine order
    idx = jnp.array([0, 0, 2, 2], dtype=jnp.int32)

    def fn(acc, upd):
        return acc.at[idx].add(upd, indices_are_sorted=True)

    return EntryCase(
        fn=fn, args=(jnp.zeros(4, jnp.float32), jnp.ones(4, jnp.float32)))
