"""JXA203 fixtures: (a) a particle-shaped operand entering a shard_map
fully replicated (the implicit all-gather the LET program exists to
avoid) vs the same operand sharded; (b) a stage whose collective output
volume busts its declared analytic exchange budget vs one with the
honest budget."""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, EntrySkip, entrypoint

_N = 4096


def _mesh_or_skip():
    from sphexa_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        raise EntrySkip("needs >= 2 devices for the fixture mesh")
    return make_mesh(2)


def _gather_fn(replicate: bool):
    from jax.sharding import PartitionSpec as P

    from sphexa_tpu.propagator import shard_map

    mesh = _mesh_or_skip()

    def stage(xs, tbl):
        return xs + jnp.sum(tbl)

    return jax.jit(shard_map(
        stage, mesh=mesh,
        in_specs=(P("p"), P() if replicate else P("p")),
        out_specs=P("p"), check_vma=False,
    ))


@entrypoint("replicated_particle_operand", mesh_axes=("p",), phase_coverage_min=0.0)  # expect: JXA203
def replicated_particle_operand():
    return EntryCase(fn=_gather_fn(True),
                     args=(jnp.zeros(_N), jnp.zeros(_N)))


@entrypoint("sharded_particle_operand", mesh_axes=("p",), phase_coverage_min=0.0)
def sharded_particle_operand():
    return EntryCase(fn=_gather_fn(False),
                     args=(jnp.zeros(_N), jnp.zeros(_N)))


def _permute_fn():
    from jax.sharding import PartitionSpec as P

    from sphexa_tpu.propagator import shard_map

    mesh = _mesh_or_skip()
    return jax.jit(shard_map(
        lambda x: jax.lax.ppermute(x, "p", [(0, 1), (1, 0)]),
        mesh=mesh, in_specs=P("p"), out_specs=P("p"), check_vma=False,
    ))


@entrypoint("volume_over_budget", mesh_axes=("p",), phase_coverage_min=0.0)  # expect: JXA203
def volume_over_budget():
    # the ppermute ships a full per-shard slab; the declared analytic
    # budget covers an eighth of it, slack included
    return EntryCase(fn=_permute_fn(), args=(jnp.zeros(_N),),
                     exchange_budget_bytes=(_N // 2) * 4 // 8,
                     exchange_slack=2.0)


@entrypoint("volume_within_budget", mesh_axes=("p",), phase_coverage_min=0.0)
def volume_within_budget():
    return EntryCase(fn=_permute_fn(), args=(jnp.zeros(_N),),
                     exchange_budget_bytes=(_N // 2) * 4,
                     exchange_slack=2.0)
