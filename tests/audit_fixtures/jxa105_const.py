"""JXA105 fixtures: an oversized host table baked into the jaxpr by
closure vs the same data passed as an argument."""

import jax.numpy as jnp
import numpy as np

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint

_TABLE = np.arange(4096, dtype=np.float32)  # 16 KiB


@entrypoint("baked_table", const_bytes_limit=1024, phase_coverage_min=0.0)  # expect: JXA105
def baked_table():
    def fn(x):
        return x + jnp.asarray(_TABLE)[: x.shape[0]]

    return EntryCase(fn=fn, args=(jnp.zeros(4),))


@entrypoint("table_as_argument", const_bytes_limit=1024, phase_coverage_min=0.0)
def table_as_argument():
    def fn(x, table):
        return x + table[: x.shape[0]]

    return EntryCase(fn=fn, args=(jnp.zeros(4), jnp.asarray(_TABLE)))
