"""jaxcost: static per-phase roofline cost model (JXA3xx layer).

Covers the cost-model walk (phase attribution, control-flow multipliers,
unknown scopes), the roofline classifier against the device models, the
COST_BUDGET.json schema gate, the cost CLI exit contract, and the
trace --predict calibration band — including the drift direction: a
corrupted per-primitive FLOP rule must FAIL calibration against the
committed capture, not silently re-rank the tuning objective.
"""

import json
import os
import shutil

import jax
import jax.numpy as jnp
import pytest

from sphexa_tpu.devtools.audit import costmodel, registry
from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    entries_from_namespace,
)
from sphexa_tpu.devtools.audit.costcli import main as cost_main
from sphexa_tpu.devtools.audit.costmodel import (
    analyze_jaxpr,
    calibration_join,
    cost_report,
    load_budget,
    load_calibration,
    memory_bound_phases,
    predict,
    validate_budget,
)
from sphexa_tpu.devtools.audit.devices import device_names, get_device
from sphexa_tpu.telemetry.cli import main as telemetry_main
from sphexa_tpu.util.phases import PHASES, phase_scope

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "trace_fixture")
COVERAGE_FIXTURE = os.path.join(
    REPO, "tests", "audit_fixtures", "jxa301_coverage.py")

# The five propagator step builders the phase-attribution pin covers.
STEP_ENTRIES = ("step_std", "step_ve", "step_nbody", "step_turb_ve",
                "step_std_cooling")


def _registry_entry(name):
    entries = {e.name: e for e in entries_from_namespace(vars(registry))}
    return entries[name]


# ---------------------------------------------------------------------------
# jaxpr walk: phase attribution
# ---------------------------------------------------------------------------


class TestAttribution:

    @pytest.mark.parametrize("name", STEP_ENTRIES)
    def test_step_builders_attribute_to_taxonomy(self, name):
        """Every propagator's static FLOPs land in named taxonomy
        phases (>= 0.95 observed; the audit gate floor is 0.7) with no
        off-taxonomy scopes — the invariant every chip-free ranking in
        this repo rests on."""
        entry = _registry_entry(name)
        rep = cost_report(EntryTrace(entry, entry.build()))
        assert rep.unknown_scopes == ()
        assert rep.coverage >= 0.95, (name, rep.coverage)
        assert set(rep.phases) <= set(PHASES)
        assert rep.total_flops > 0

    def test_phase_scope_attribution(self):
        def f(x):
            with phase_scope("density"):
                y = jnp.tanh(x)
            return y + 1.0

        rep = analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(64, jnp.float32)))
        assert "density" in rep.phases
        assert rep.phases["density"].flops > 0
        assert rep.unattributed.flops > 0        # the +1.0 tail
        assert 0.0 < rep.coverage < 1.0

    def test_unknown_scope_surfaces(self):
        def f(x):
            with jax.named_scope("sphexa/warpdrive"):
                return jnp.tanh(x)

        rep = analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(64, jnp.float32)))
        assert rep.unknown_scopes == ("warpdrive",)
        assert rep.coverage == 0.0               # off-taxonomy != attributed

    def test_scan_length_multiplies_flops(self):
        def body(c, _):
            return c * 2.0 + 1.0, None

        def loop(n):
            def f(x):
                y, _ = jax.lax.scan(body, x, None, length=n)
                return y
            return analyze_jaxpr(
                jax.make_jaxpr(f)(jnp.zeros(128, jnp.float32)))

        f4, f8 = loop(4).total_flops, loop(8).total_flops
        assert f4 > 0
        assert f8 == pytest.approx(2.0 * f4)

    def test_empty_jaxpr_coverage_is_one(self):
        rep = analyze_jaxpr(jax.make_jaxpr(lambda x: x)(jnp.zeros(4)))
        assert rep.total_flops == 0
        assert rep.coverage == 1.0


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------


class TestRoofline:

    def test_device_models(self):
        assert {"v5e", "cpu-smoke"} <= set(device_names())
        v5e = get_device("v5e")
        assert v5e.ridge("float32") == pytest.approx(
            v5e.peak_for("float32") / v5e.hbm_bytes_per_s)
        assert 50 < v5e.ridge("float32") < 70
        # bf16 peak doubles-ish the f32 ridge on v5e
        assert v5e.ridge("bfloat16") > v5e.ridge("float32")
        with pytest.raises(ValueError):
            get_device("nope")

    def test_big_dot_is_compute_bound_on_v5e(self):
        def f(a, b):
            with phase_scope("density"):
                return a @ b

        z = jnp.zeros((768, 768), jnp.float32)
        pred = predict(analyze_jaxpr(jax.make_jaxpr(f)(z, z)), "v5e")
        row = pred.row("density")
        assert row is not None and row.bound == "compute"
        assert row.ai > get_device("v5e").ridge("float32")
        assert row.ms > 0
        assert memory_bound_phases(pred) == []

    def test_elementwise_is_memory_bound(self):
        def f(x):
            with phase_scope("density"):
                return x * 2.0 + 1.0

        pred = predict(
            analyze_jaxpr(jax.make_jaxpr(f)(jnp.zeros(1 << 16))), "v5e")
        row = pred.row("density")
        assert row.bound == "memory"
        assert row.ai < get_device("v5e").ridge(row.dtype)
        assert [r.phase for r in memory_bound_phases(pred)] == ["density"]
        # fusion discount: lower bound strictly under the per-eqn sum
        assert row.hbm_lower < row.hbm_upper
        assert row.ms <= row.ms_upper

    def test_ici_bound_bucket(self):
        b = costmodel.PhaseCost(
            phase="halo-exchange", flops=1e6,
            flops_by_dtype={"float32": 1e6},
            hbm_lower=1e3, hbm_upper=1e3, ici_bytes=1e9, eqns=1)
        row = costmodel._predict_bucket(b, get_device("v5e"))
        assert row.bound == "ici"
        assert row.ms == pytest.approx(row.ici_ms)


# ---------------------------------------------------------------------------
# budget schema (JXA302's file contract)
# ---------------------------------------------------------------------------


class TestBudget:

    def test_committed_budget_validates(self):
        doc = load_budget(os.path.join(REPO, "COST_BUDGET.json"))
        assert doc["device"] in device_names()
        assert doc["entries"]

    def test_validate_budget_errors(self):
        assert validate_budget([]) == ["budget document is not a JSON object"]
        errs = validate_budget({"schema": 99, "device": "nope", "entries": {}})
        assert any("schema" in e for e in errs)
        assert any("nope" in e for e in errs)
        assert any("entries" in e for e in errs)
        errs = validate_budget({
            "schema": 1, "device": "v5e",
            "entries": {"e": {"phases": {"density": 0.0}, "total_ms": -1}}})
        assert any("positive" in e for e in errs)
        assert any("total_ms" in e for e in errs)

    def test_load_budget_raises_on_invalid(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"schema": 1}))
        with pytest.raises(ValueError):
            load_budget(str(p))


# ---------------------------------------------------------------------------
# cost CLI exit contract
# ---------------------------------------------------------------------------


class TestCostCli:

    def test_unknown_device_exits_2(self, capsys):
        assert cost_main(["--device", "nope"]) == 2
        assert "nope" in capsys.readouterr().err

    def test_unknown_entry_exits_2(self, capsys):
        assert cost_main([COVERAGE_FIXTURE, "--entries", "nope"]) == 2
        capsys.readouterr()

    def test_clean_entry_exits_0_with_table(self, capsys):
        assert cost_main([COVERAGE_FIXTURE, "--entries", "scoped_step"]) == 0
        out = capsys.readouterr().out
        assert "scoped_step" in out
        assert "density" in out

    def test_json_payload(self, capsys):
        rc = cost_main([COVERAGE_FIXTURE, "--entries", "scoped_step",
                        "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["tool"] == "jaxcost"
        assert doc["device"] == "v5e"
        assert doc["findings"] == []
        (entry,) = doc["entries"]
        assert entry["entry"] == "scoped_step"
        phases = {r["phase"] for r in entry["phases"]}
        assert "density" in phases

    def test_finding_entry_exits_1(self, capsys):
        rc = cost_main([COVERAGE_FIXTURE, "--entries", "unscoped_step"])
        assert rc == 1
        assert "JXA301" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# calibration against the committed capture (trace --predict)
# ---------------------------------------------------------------------------


class TestCalibration:

    @pytest.fixture(autouse=True)
    def _repo_cwd(self, monkeypatch):
        # calibration.json's target path is repo-relative by design (it
        # is a committed file); pin the cwd the gate runs from.
        monkeypatch.chdir(REPO)

    def test_fixture_calibration_in_band(self, capsys):
        assert telemetry_main(["trace", FIXTURE, "--predict"]) == 0
        out = capsys.readouterr().out
        assert "calibration" in out
        assert "out-of-band" not in out

    def test_calibration_join_shape(self):
        calib = load_calibration(FIXTURE)
        assert calib is not None
        from sphexa_tpu.telemetry.traceview import summarize_trace
        joined = calibration_join(summarize_trace(FIXTURE), calib)
        assert joined["ok"], joined["violations"]
        assert {r["phase"] for r in joined["rows"]} == set(calib["phases"])
        for r in joined["rows"]:
            assert r["status"] == "ok"
            lo, hi = r["band"]
            assert lo <= r["ratio"] <= hi

    def test_corrupted_cost_rule_breaks_calibration(self, monkeypatch,
                                                    capsys):
        """The gate's whole point: miscounting a primitive's FLOPs by
        100x must push the measured/predicted ratio out of the declared
        band and fail the run."""
        real = costmodel._dot_general_flops
        monkeypatch.setitem(costmodel.FLOP_RULES, "dot_general",
                            lambda eqn: real(eqn) * 100.0)
        assert telemetry_main(["trace", FIXTURE, "--predict"]) == 1
        err = capsys.readouterr().err
        assert "ratio" in err

    def test_missing_calibration_exits_2(self, tmp_path, capsys):
        d = tmp_path / "capture"
        d.mkdir()
        for f in ("vm.xplane.pb", "vm.trace.json.gz"):
            shutil.copy(os.path.join(FIXTURE, f), d)
        assert telemetry_main(["trace", str(d), "--predict"]) == 2
        assert load_calibration(str(d)) is None
        capsys.readouterr()
