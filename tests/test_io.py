"""Snapshot I/O tests: write/read round trip, multi-step files, restart
continuation. Mirrors the reference's restartability contract: the default
dump contains every conserved field, so any dump can seed a new run
(sphexa.cpp:227-231, file_init.hpp).
"""

import os

import numpy as np
import pytest

from sphexa_tpu.init import init_sedov, make_initializer
from sphexa_tpu.init.file_init import init_from_file, parse_file_spec
from sphexa_tpu.io import list_steps, read_snapshot, write_ascii, write_snapshot
from sphexa_tpu.io.snapshot import CONSERVED_FIELDS
from sphexa_tpu.sfc.box import BoundaryType
from sphexa_tpu.simulation import Simulation


@pytest.fixture(scope="module")
def small_case():
    return init_sedov(8)


@pytest.mark.parametrize("ext", ["h5", "npz"])
def test_round_trip(tmp_path, small_case, ext):
    state, box, const = small_case
    path = str(tmp_path / f"dump.{ext}")
    write_snapshot(path, state, box, const, iteration=7)

    state2, box2, const2, extra = read_snapshot(path)
    for f in CONSERVED_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, f)), np.asarray(getattr(state2, f)), err_msg=f
        )
    assert float(state2.ttot) == float(state.ttot)
    assert float(state2.min_dt) == float(state.min_dt)
    np.testing.assert_array_equal(np.asarray(box.lo), np.asarray(box2.lo))
    assert box2.boundaries == box.boundaries
    assert const2.gamma == pytest.approx(const.gamma)
    assert const2.ng0 == const.ng0
    assert const2.g == const.g
    assert extra == {}


def test_multi_step_and_selection(tmp_path, small_case):
    state, box, const = small_case
    path = str(tmp_path / "dump.h5")
    for i in range(3):
        import dataclasses

        si = dataclasses.replace(state, ttot=state.ttot + i)
        assert write_snapshot(path, si, box, const, iteration=i) == i
    assert list_steps(path) == [0, 1, 2]
    _, _, _, _ = read_snapshot(path, step=1)
    s_last, *_ = read_snapshot(path, step=-1)
    assert float(s_last.ttot) == pytest.approx(float(state.ttot) + 2)
    with pytest.raises(ValueError):
        read_snapshot(path, step=9)
    with pytest.raises(ValueError):
        read_snapshot(path, step=-9)


def test_read_step_attrs(tmp_path, small_case):
    from sphexa_tpu.io.snapshot import read_step_attrs

    state, box, const = small_case
    path = str(tmp_path / "dump.h5")
    write_snapshot(path, state, box, const, iteration=42, case="sedov")
    attrs = read_step_attrs(path)
    assert int(attrs["iteration"]) == 42
    assert float(attrs["gamma"]) == pytest.approx(const.gamma)
    assert np.asarray(attrs["initCase"]).item().decode() == "sedov"
    with pytest.raises(ValueError):
        read_step_attrs(path, step=5)
    with pytest.raises(ValueError):
        read_step_attrs(path, step=-3)


def test_npz_step_selection_validated(tmp_path, small_case):
    state, box, const = small_case
    path = str(tmp_path / "dump.npz")
    write_snapshot(path, state, box, const)
    read_snapshot(path, step=0)
    read_snapshot(path, step=-1)
    with pytest.raises(ValueError):
        read_snapshot(path, step=3)


def test_output_fields_follow_particle_order(small_case):
    """Dumped derived fields must align with the conserved fields in the
    state's own particle order, independent of the internal SFC sort."""
    import dataclasses

    from sphexa_tpu.analysis import compute_output_fields
    from sphexa_tpu.simulation import make_propagator_config

    state, box, const = small_case
    cfg = make_propagator_config(state, box, const, block=256)
    base = compute_output_fields(state, box, cfg)

    perm = np.random.default_rng(3).permutation(state.n)
    shuffled = dataclasses.replace(
        state,
        **{
            f: np.asarray(getattr(state, f))[perm]
            for f in ("x", "y", "z", "vx", "vy", "vz", "h", "m", "temp")
        },
    )
    out = compute_output_fields(shuffled, box, cfg)
    np.testing.assert_allclose(out["rho"], base["rho"][perm], rtol=1e-5)
    np.testing.assert_allclose(out["r"], base["r"][perm], rtol=1e-6)


def test_extra_fields(tmp_path, small_case):
    state, box, const = small_case
    path = str(tmp_path / "dump.h5")
    rho = np.full(state.n, 1.5, np.float32)
    write_snapshot(path, state, box, const, extra_fields={"rho": rho})
    *_, extra = read_snapshot(path)
    np.testing.assert_array_equal(extra["rho"], rho)


def test_parse_file_spec():
    assert parse_file_spec("dump.h5") == ("dump.h5", -1)
    assert parse_file_spec("dump.h5:5") == ("dump.h5", 5)
    assert parse_file_spec("dump.h5:-2") == ("dump.h5", -2)
    assert parse_file_spec("a:b/dump.h5") == ("a:b/dump.h5", -1)


def test_restart_continues_simulation(tmp_path):
    """Run, dump, restore, continue: the restored run must take the same
    next step as the original (bitwise state round trip)."""
    state, box, const = init_sedov(8)
    sim = Simulation(state, box, const, prop="std", block=256)
    for _ in range(3):
        sim.step()
    path = str(tmp_path / "ckpt.h5")
    write_snapshot(path, sim.state, sim.box, const, iteration=sim.iteration)

    state2, box2, const2 = init_from_file(path)
    sim2 = Simulation(state2, box2, const2, prop="std", block=256)
    d_orig = sim.step()
    d_rest = sim2.step()
    assert d_rest["dt"] == pytest.approx(d_orig["dt"], rel=1e-6)
    np.testing.assert_allclose(
        np.asarray(sim2.state.x), np.asarray(sim.state.x), atol=1e-7
    )


def test_make_initializer_file_path(tmp_path, small_case):
    state, box, const = small_case
    path = str(tmp_path / "dump.h5")
    write_snapshot(path, state, box, const)
    init = make_initializer(f"{path}:0")
    s2, b2, c2 = init(None)
    assert s2.n == state.n
    assert b2.boundaries[0] == BoundaryType.periodic


def test_ascii_writer(tmp_path, small_case):
    state, *_ = small_case
    path = str(tmp_path / "dump.txt")
    write_ascii(path, {"x": np.asarray(state.x), "h": np.asarray(state.h)})
    data = np.loadtxt(path)
    assert data.shape == (state.n, 2)


def test_sharded_snapshot_roundtrip(tmp_path):
    """Parallel file-per-shard dump (write_snapshot_sharded, the MPI-IO
    ifile_io_hdf5.cpp role): P part files, no global gather on write,
    transparent reassembly from the BASE path — incl. per-particle
    extras (sliced) and global tables (part-0 verbatim)."""
    import jax

    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.io.snapshot import (
        _find_parts,
        read_snapshot_full,
        write_snapshot_sharded,
    )
    from sphexa_tpu.parallel import make_mesh, shard_state

    state, box, const = init_sedov(16)  # 4096 = 8 * 512
    mesh = make_mesh(8)
    sstate = shard_state(state, mesh)
    path = str(tmp_path / "dump.h5")
    rho = np.arange(state.n, dtype=np.float32)
    tbl = np.asarray([1.0, 2.0, 3.0], np.float32)  # global table extra
    step = write_snapshot_sharded(
        path, sstate, box, const, iteration=5,
        extra_fields={"rho": rho, "modes": tbl}, case="sedov",
    )
    assert step == 0
    parts = _find_parts(path)
    assert len(parts) == 8 and not os.path.exists(path)

    state2, box2, const2, extra, attrs = read_snapshot_full(path)
    assert state2.n == state.n
    np.testing.assert_allclose(np.asarray(state2.x), np.asarray(state.x))
    np.testing.assert_allclose(np.asarray(state2.temp),
                               np.asarray(state.temp))
    np.testing.assert_allclose(extra["rho"], rho)
    np.testing.assert_allclose(extra["modes"], tbl)
    assert int(attrs["iteration"]) == 5

    # single-device states fall back to one plain file
    p2 = str(tmp_path / "single.h5")
    write_snapshot_sharded(p2, state, box, const)
    assert os.path.exists(p2) and not _find_parts(p2)

    # every part file records the GLOBAL particle count (the H5Part
    # convention, ifile_io_hdf5.cpp: global count on every rank) even
    # though its datasets hold only the shard's rows
    import h5py

    for p in parts:
        with h5py.File(p, "r") as f:
            g = f["Step#0"]
            assert int(g.attrs["numParticlesGlobal"]) == state.n
            assert g["x"].shape[0] == state.n // 8


def test_sharded_snapshot_torn_dump_probes(tmp_path):
    """list_steps/read_step_attrs on a sharded base path must reflect the
    steps COMPLETE across all parts — after a torn dump (part 0 one step
    ahead) the extra step is neither listed nor resolvable."""
    import h5py

    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.io.snapshot import (
        _find_parts,
        list_steps,
        read_step_attrs,
        write_snapshot_sharded,
    )
    from sphexa_tpu.parallel import make_mesh, shard_state

    state, box, const = init_sedov(16)
    mesh = make_mesh(8)
    sstate = shard_state(state, mesh)
    path = str(tmp_path / "dump.h5")
    write_snapshot_sharded(path, sstate, box, const, iteration=1)
    write_snapshot_sharded(path, sstate, box, const, iteration=2)
    parts = _find_parts(path)
    # simulate a crash mid-dump: part 0 has Step#2, later parts don't
    with h5py.File(parts[0], "a") as f:
        f.copy("Step#1", "Step#2")
    assert list_steps(path) == [0, 1]
    attrs = read_step_attrs(path, -1)  # newest COMPLETE step
    assert int(attrs["iteration"]) == 2  # iteration attr of Step#1


def test_snapshot_sym_pairs_roundtrip(tmp_path, small_case):
    """The pair-cutoff convention rides in snapshot attrs so a restart
    reproduces the writing run's force convention."""
    import dataclasses as _dc

    from sphexa_tpu.io.snapshot import read_snapshot

    state, box, const = small_case
    path = str(tmp_path / "dump.h5")
    write_snapshot(path, state, box, _dc.replace(const, sym_pairs=False))
    _, _, c2, _ = read_snapshot(path)
    assert c2.sym_pairs is False
    path2 = str(tmp_path / "dump2.h5")
    write_snapshot(path2, state, box, const)
    _, _, c3, _ = read_snapshot(path2)
    assert c3.sym_pairs is True
