"""Telemetry subsystem: registry/sink semantics, the zero-sync
deferred-window guard (the runtime JXA104 analog: no device->host
transfer may ride the happy path), rollback/retrace/replay events as
first-class telemetry, and the sphexa-telemetry CLI contracts
(summary schema validation, diff thresholds + exit codes)."""

import dataclasses
import json

import numpy as np
import pytest

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.propagator import STEP_DIAG_KEYS
from sphexa_tpu.simulation import Simulation
from sphexa_tpu.telemetry import (
    ConsoleSink,
    JsonlSink,
    MemorySink,
    SCHEMA_VERSION,
    Telemetry,
    write_manifest,
)
from sphexa_tpu.telemetry.cli import main as cli_main
from sphexa_tpu.telemetry.registry import validate_event


# ---------------------------------------------------------------------------
# registry + sinks
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counters_gauges_timings(self):
        t = Telemetry()
        t.count("x")
        t.count("x", 2)
        t.gauge("g", 1.5)
        t.timing("p", 0.5)
        t.timing("p", 1.5)
        assert t.counters["x"] == 3
        assert t.gauges["g"] == 1.5
        assert t.timing_mean("p") == 1.0
        assert np.isnan(t.timing_mean("missing"))

    def test_event_envelope_and_seq(self):
        sink = MemorySink()
        t = Telemetry(sinks=[sink])
        t.event("note", msg="a")
        t.event("note", msg="b")
        a, b = sink.events
        assert a["v"] == SCHEMA_VERSION and a["kind"] == "note"
        assert (a["seq"], b["seq"]) == (0, 1)
        assert a["msg"] == "a"
        # counted even without reading the sink
        assert t.counters["events.note"] == 2

    def test_sinkless_event_is_counter_only(self):
        t = Telemetry()
        t.event("step", it=1, wall_s=0.1)  # must not raise, must count
        assert t.counters["events.step"] == 1
        assert t._seq == 0  # no envelope built

    def test_numpy_payloads_json_safe(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        t = Telemetry(sinks=[JsonlSink(path)])
        t.event("note", a=np.float32(1.5), b=np.int64(3))
        t.close()
        (e,) = [json.loads(l) for l in open(path)]
        assert e["a"] == 1.5 and e["b"] == 3

    def test_validate_event(self):
        ok = {"v": SCHEMA_VERSION, "seq": 0, "t": 1.0, "kind": "step",
              "it": 1, "wall_s": 0.1}
        assert validate_event(ok) == []
        assert validate_event({**ok, "v": 99})
        # an unknown kind is NOT a schema problem: it is the
        # forward-compat dimension the summary counts separately
        # (unknown_kinds + strict exit code) — flagging it here too
        # would double-report every future-schema event
        assert validate_event({**ok, "kind": "bogus"}) == []
        # ...but a v2-only kind claiming v1 is writer confusion
        assert validate_event({"v": 1, "seq": 0, "t": 1.0,
                               "kind": "exchange", "it": 1,
                               "shipped_rows": 1, "rows": [1]})
        bad = dict(ok)
        del bad["wall_s"]
        assert any("wall_s" in p for p in validate_event(bad))

    def test_console_sink_and_printer_routing(self):
        lines = []
        sink = ConsoleSink(printer=lines.append)
        t = Telemetry(sinks=[sink])
        t.event("rollback", it=4, steps=3, reason="overflow")
        t.event("launch", it=1)  # not notable: no console line
        assert len(lines) == 1 and "rollback" in lines[0]
        t.console_printer()("raw line")
        assert lines[-1] == "raw line"  # routed through the sink
        assert Telemetry().console_printer(print) is print

    def test_jsonl_round_trip(self, tmp_path):
        from sphexa_tpu.telemetry.cli import load_events

        run = tmp_path / "run"
        t = Telemetry(sinks=[JsonlSink(str(run / "events.jsonl"))])
        t.event("step", it=1, wall_s=0.25, dt=0.1, reconfigured=False)
        t.event("retrace", it=1, delta=2)
        t.close()
        events, problems = load_events(str(run))
        assert problems == []
        assert [e["kind"] for e in events] == ["step", "retrace"]
        assert events[0]["wall_s"] == 0.25 and events[1]["delta"] == 2


# ---------------------------------------------------------------------------
# Simulation wiring
# ---------------------------------------------------------------------------


def _sedov_sim(side=8, telemetry=None, **kw):
    state, box, const = init_sedov(side)
    return Simulation(state, box, const, prop="std", block=4096,
                      telemetry=telemetry, **kw)


class TestSimulationTelemetry:
    def test_step_diag_contract(self):
        sim = _sedov_sim()
        d = sim.step()
        assert set(STEP_DIAG_KEYS) <= set(d)

    def test_sync_steps_emit_step_events(self):
        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]))
        sim.step()
        sim.step()
        steps = sink.of_kind("step")
        assert [e["it"] for e in steps] == [1, 2]
        assert all(e["wall_s"] > 0 and e["dt"] > 0 for e in steps)
        recfg = sink.of_kind("reconfigure")
        assert recfg and recfg[0]["reason"] == "initial"

    def test_deferred_happy_path_is_sync_free(self, tmp_path, monkeypatch):
        """The JXA104-analog runtime guard: with telemetry fully enabled
        (JSONL sink + registry) AND the in-graph observables on (a case
        extra + science rows), deferred-window steps must not issue ANY
        device->host transfer — jax.device_get / block_until_ready are
        poisoned for the whole happy-path window and only restored for
        the flush, which is where the one batched fetch belongs."""
        from sphexa_tpu.observables import ObservableSpec

        sink = JsonlSink(str(tmp_path / "events.jsonl"))
        tel = Telemetry(sinks=[sink])
        sim = _sedov_sim(side=12, telemetry=tel, check_every=4,
                         obs_spec=ObservableSpec(extra="mach"),
                         science_rows=True, drift_budget=1e3)
        # settle compiles + config on a first full window
        for _ in range(4):
            sim.step()

        real_get = jax.device_get

        def boom(*a, **k):
            raise AssertionError(
                "device->host transfer on the deferred happy path"
            )

        monkeypatch.setattr(jax, "device_get", boom)
        monkeypatch.setattr(jax, "block_until_ready", boom)
        for _ in range(3):
            d = sim.step()
            assert d.get("deferred") == 1.0
        monkeypatch.setattr(jax, "device_get", real_get)
        monkeypatch.undo()
        d = sim.flush()
        assert "deferred" not in d or d.get("deferred") != 1.0
        tel.close()

        events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
        kinds = [e["kind"] for e in events]
        # 7 launches (both windows), 2 window flushes, no rollbacks
        assert kinds.count("launch") == 7
        windows = [e for e in events if e["kind"] == "window"]
        assert len(windows) == 2
        assert windows[-1]["steps"] == 3
        assert windows[-1]["per_step_s"] > 0
        assert "rollback" not in kinds
        # the science ledger rode the same fetch: one physics + one
        # numerics event per window, every step's row preserved
        phys = [e for e in events if e["kind"] == "physics"]
        assert [e["steps"] for e in phys] == [4, 3]
        assert phys[-1]["its"] == [5, 6, 7]
        assert all(np.isfinite(v) for e in phys for v in e["etot"])
        assert all(len(e["extra"]) == e["steps"] for e in phys)  # machRMS
        nums = [e for e in events if e["kind"] == "numerics"]
        assert len(nums) == 2 and sum(nums[-1]["limiter"].values()) == 3
        assert "drift" not in kinds and "field_health" not in kinds
        rows = sim.drain_science()
        assert [r["it"] for r in rows] == list(range(1, 8))
        assert all(np.isfinite(r["etot"]) and "extra" in r for r in rows)
        assert sim.drain_science() == []  # drained
        assert sim.energy_drift is not None and sim.energy_drift < 1e-3

    def test_rollback_retrace_replay_events(self):
        """A deferred-detected overflow must surface as first-class
        rollback/replay telemetry (it used to be visible only as
        ``reconfigured`` on one diagnostics dict), and the forced
        reconfigure must trip the retrace watchdog.

        side 12 DELIBERATELY collides with test_simulation_async's
        doctored sedov(12)/block-4096/cap-8 config: under alphabetical
        suite order the global jit caches arrive pre-warmed, the cache
        delta is zero, and the old cache-size-only watchdog reported
        nothing (the order-dependent failure this pins). The watchdog
        now baselines executable signatures PER Simulation
        (_launch_signature), so this run's launches under a config it
        never used count as retraces — warm cache or not."""
        state, box, const = init_sedov(12)
        sink = MemorySink()
        from sphexa_tpu.observables import ObservableSpec

        sim = Simulation(state, box, const, prop="std", block=4096,
                         check_every=3, science_rows=True,
                         obs_spec=ObservableSpec(),
                         telemetry=Telemetry(sinks=[sink]))
        sim._cfg = dataclasses.replace(
            sim._cfg, nbr=dataclasses.replace(sim._cfg.nbr, cap=8)
        )
        for _ in range(3):
            sim.step()
        d = sim.flush() if sim._pending else sim._last_diag
        assert d["reconfigured"] == 1.0
        (rb,) = sink.of_kind("rollback")
        assert rb["reason"] == "overflow"
        assert rb["steps"] == 3 and rb["to_it"] == 0 and rb["bad_index"] == 0
        (rp,) = sink.of_kind("replay")
        assert rp["steps"] == 3
        # the replayed window runs through the checked path: 3 step events
        assert len(sink.of_kind("step")) == 3
        assert any(e["reason"] == "overflow"
                   for e in sink.of_kind("reconfigure"))
        assert sim.telemetry.counters["rollbacks"] == 1
        assert sim.telemetry.counters["retraces"] >= 1
        assert sink.of_kind("retrace")
        # science rows: the rolled-back window wrote NONE of its rows —
        # only the replay's verified steps did, so the constants.txt
        # series stays monotone and complete
        rows = sim.drain_science()
        assert [r["it"] for r in rows] == [1, 2, 3]
        assert len(sink.of_kind("physics")) == 3  # one per replayed step

    def test_run_line_survives_missing_diag_keys(self):
        """Simulation.run's report uses .get() + nan for propagator-
        specific scalars and routes through the console sink."""
        lines = []
        sim = _sedov_sim(
            telemetry=Telemetry(sinks=[ConsoleSink(printer=lines.append)])
        )
        sim.step = lambda: {"reconfigured": 0.0}  # diagnostics-poor step
        sim.run(1, log_every=1, printer=None)  # printer unused: sink wins
        (line,) = [l for l in lines if l.startswith("it ")]
        assert "nan" in line and "rho_max=nan" in line

    def test_run_printer_fallback_without_sink(self):
        lines = []
        sim = _sedov_sim(side=8)
        sim.run(1, log_every=1, printer=lines.append)
        assert len(lines) == 1 and "rho_max=" in lines[0]


# ---------------------------------------------------------------------------
# distributed telemetry (schema v2): sharded no-sync guard, shard events,
# imbalance watchdog, memory snapshots
# ---------------------------------------------------------------------------


class TestDistributedTelemetry:
    def test_sharded_window_sync_free_emits_shard_events(
            self, tmp_path, monkeypatch):
        """Satellite of the JXA104-analog guard, sharded: a 2-virtual-
        device CPU-mesh deferred window with full telemetry must issue
        ZERO device->host transfers on the happy path while still
        producing the schema-v2 ``exchange``/``shard_load`` events at
        the flush. The pre-existing CPU-mesh drain
        (Simulation._drain, a collective-serialization workaround that
        real TPU meshes don't run) is the ONE sanctioned
        block_until_ready — it is re-pointed at the real function so
        everything else stays poisoned."""
        import numpy as np

        from sphexa_tpu.parallel.sizing import device_sparse_halo
        from sphexa_tpu.sfc.box import make_global_box
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        state, box, const = init_sedov(6)  # 216 / 2 devices (audit scale)
        sink = JsonlSink(str(tmp_path / "events.jsonl"))
        tel = Telemetry(sinks=[sink])
        from sphexa_tpu.observables import ObservableSpec

        sim = Simulation(state, box, const, prop="std", block=512,
                         backend="pallas", num_devices=2, check_every=3,
                         obs_spec=ObservableSpec(), telemetry=tel)
        for _ in range(3):  # settle compiles on one full window
            sim.step()

        real_get = jax.device_get
        real_block = jax.block_until_ready

        def boom(*a, **k):
            raise AssertionError(
                "device->host transfer on the sharded deferred happy path"
            )

        # sanction ONLY the drain's block (CPU-mesh artifact guard);
        # any other block/get inside the window is instrumentation debt
        drained = []

        def drain_ok(out):
            drained.append(1)
            real_block([a for a in jax.tree.leaves(out)
                        if hasattr(a, "block_until_ready")])
            return out

        monkeypatch.setattr(jax, "device_get", boom)
        monkeypatch.setattr(jax, "block_until_ready", boom)
        monkeypatch.setattr(sim, "_drain", drain_ok)
        for _ in range(2):
            d = sim.step()
            assert d.get("deferred") == 1.0
        monkeypatch.setattr(jax, "device_get", real_get)
        monkeypatch.setattr(jax, "block_until_ready", real_block)
        monkeypatch.undo()
        sim.flush()
        tel.close()
        assert drained  # the sanctioned drain actually ran

        events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
        by_kind = lambda k: [e for e in events if e["kind"] == k]
        loads = by_kind("shard_load")
        exchanges = by_kind("exchange")
        assert loads and exchanges
        S = state.n // 2
        assert loads[-1]["particles"] == [S, S]
        assert len(exchanges[-1]["rows"]) == 2
        assert exchanges[-1]["shipped_rows"] > 0
        assert exchanges[-1]["mode"] in ("sparse", "windowed")
        # independent size-based check (measure_multichip.py formulas):
        # shipped rows == sum of the sized per-distance caps
        gbox = make_global_box(state.x, state.y, state.z, box)
        keys = compute_sfc_keys(state.x, state.y, state.z, gbox)
        hc = device_sparse_halo(state.x, state.y, state.z, state.h, keys,
                                gbox, sim._cfg.nbr, P=2,
                                margin=sim._halo_margin)
        assert exchanges[-1]["shipped_rows"] == sum(min(c, S) for c in hc)
        mems = by_kind("memory")
        assert {e["point"] for e in mems} >= {"post-compile", "flush"}
        # the science ledger rode the same sharded fetch: its sums
        # lowered to the chained collectives, values stayed finite
        phys = by_kind("physics")
        assert [e["steps"] for e in phys] == [3, 2]
        assert all(np.isfinite(v) for e in phys for v in e["etot"])
        assert all(validate_event(e) == [] for e in events)

    def test_snapshot_rides_flush_sync_free(self, tmp_path, monkeypatch):
        """Schema-v8 satellite of the JXA104-analog guard: with in-graph
        snapshots ON over a 2-virtual-device deferred window, the happy
        path must still issue ZERO device->host transfers — the snapshot
        grid rides the SAME batched fetch as the science ledger, and the
        whole window's due frames (.npz ring + ``snapshot`` events) land
        at the flush boundary."""
        from sphexa_tpu.observables import ObservableSpec, SnapshotSpec

        state, box, const = init_sedov(6)  # 216 / 2 devices (audit scale)
        sink = JsonlSink(str(tmp_path / "events.jsonl"))
        tel = Telemetry(sinks=[sink])
        sim = Simulation(state, box, const, prop="std", block=512,
                         backend="pallas", num_devices=2, check_every=3,
                         obs_spec=ObservableSpec(), telemetry=tel,
                         snap_spec=SnapshotSpec(fields=("rho",), grid=8),
                         snap_dir=str(tmp_path / "snapshots"))
        for _ in range(3):  # settle compiles on one full window
            sim.step()
        sim.drain_snapshots()

        real_get = jax.device_get
        real_block = jax.block_until_ready

        def boom(*a, **k):
            raise AssertionError(
                "device->host transfer on the snapshot deferred happy path"
            )

        def drain_ok(out):  # the ONE sanctioned CPU-mesh drain block
            real_block([a for a in jax.tree.leaves(out)
                        if hasattr(a, "block_until_ready")])
            return out

        monkeypatch.setattr(jax, "device_get", boom)
        monkeypatch.setattr(jax, "block_until_ready", boom)
        monkeypatch.setattr(sim, "_drain", drain_ok)
        for _ in range(2):
            d = sim.step()
            assert d.get("deferred") == 1.0
        monkeypatch.setattr(jax, "device_get", real_get)
        monkeypatch.setattr(jax, "block_until_ready", real_block)
        monkeypatch.undo()
        sim.flush()
        tel.close()

        # the deferred window's frames landed WHOLE at the flush
        frames = sim.drain_snapshots()
        assert [it for it, _ in frames] == [4, 5]
        events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
        snaps = [e for e in events if e["kind"] == "snapshot"]
        assert [e["it"] for e in snaps] == [1, 2, 3, 4, 5]
        assert all(e["v"] == 8 and validate_event(e) == [] for e in snaps)
        for e in snaps:
            z = np.load(e["path"], allow_pickle=False)
            g = np.asarray(z["grid"])
            assert g.shape == (1, 8, 8)
            # the deposit conserves the deposited quantity: cell sums of
            # rho recover the global sum, finite and positive
            assert np.isfinite(g).all() and g.sum() > 0
            assert e["vmax"][0] >= e["vmin"][0] >= 0.0

    def test_imbalance_watchdog_fires_on_skewed_load(self):
        """max/mean of a per-shard metric past the configured ratio is a
        first-class ``imbalance`` event (+ counter), mirroring the
        retrace watchdog — unit-level via a stub mesh so the watchdog
        logic is pinned without a 90-second mesh run."""
        from types import SimpleNamespace

        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]))
        sim._mesh = SimpleNamespace(size=2)
        sim._halo_info = {"mode": "sparse", "shipped_rows": 128,
                          "bytes_per_step": 128 * 18 * 4}
        sim._emit_distributed(
            {"shard_work": np.asarray([300.0, 100.0]),
             "shard_rows": np.asarray([64, 64], np.int32),
             "shard_occ": np.asarray([0.5, 0.5], np.float32),
             "shard_trips": np.asarray([0, 0], np.int32)},
            steps=1,
        )
        (imb,) = sink.of_kind("imbalance")
        assert imb["metric"] == "work"
        assert imb["ratio"] == pytest.approx(1.5)  # 300 / 200
        assert imb["threshold"] == 1.5
        assert sim.telemetry.counters["imbalances"] == 1
        (ex,) = sink.of_kind("exchange")
        assert ex["rows"] == [64, 64] and ex["shipped_rows"] == 128
        (load,) = sink.of_kind("shard_load")
        assert load["work"] == [300.0, 100.0]
        # balanced load below the ratio stays silent
        sim._emit_distributed(
            {"shard_work": np.asarray([100.0, 100.0]),
             "shard_rows": np.asarray([64, 64], np.int32),
             "shard_occ": np.asarray([0.5, 0.5], np.float32),
             "shard_trips": np.asarray([0, 0], np.int32)},
            steps=1,
        )
        assert len(sink.of_kind("imbalance")) == 1

    def test_memory_snapshot_shape_and_event(self):
        from sphexa_tpu.telemetry import (
            device_memory_snapshot,
            emit_memory_event,
        )

        snap = device_memory_snapshot()
        assert len(snap["devices"]) == len(jax.local_devices())
        # CPU has no allocator stats: byte lists empty but PRESENT, so
        # the mesh rehearsal validates the same schema the chip writes
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            assert isinstance(snap[k], list)
        sink = MemorySink()
        tel = Telemetry(sinks=[sink])
        out = emit_memory_event(tel, "manifest")
        assert out is not None
        (e,) = sink.of_kind("memory")
        assert e["point"] == "manifest"
        assert validate_event(e) == []
        # sink-less registry: snapshot skipped entirely (not worth the
        # per-device stat calls for a counter bump)
        assert emit_memory_event(Telemetry(), "manifest") is None


# ---------------------------------------------------------------------------
# physics observability (schema v3): ledger events, drift + field-health
# watchdogs
# ---------------------------------------------------------------------------


class TestScienceTelemetry:
    def test_drift_watchdog_fires_on_energy_leak(self):
        """A seeded energy leak (internal energy doubled mid-run) must
        cross the configured drift budget and surface as a first-class
        ``drift`` event + counter — the conservation contract of long
        unattended runs (Keller et al. 2023)."""
        from sphexa_tpu.observables import ObservableSpec

        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]),
                         drift_budget=0.05, obs_spec=ObservableSpec())
        sim.step()  # establishes etot0
        assert sink.of_kind("drift") == []
        sim.state = dataclasses.replace(sim.state,
                                        temp=sim.state.temp * 2.0)
        sim.step()
        events = sink.of_kind("drift")
        assert events and events[-1]["drift"] > 0.05
        assert events[-1]["budget"] == 0.05
        assert sim.telemetry.counters["drifts"] >= 1
        assert sim.energy_drift > 0.05
        from sphexa_tpu.telemetry.registry import validate_event

        assert all(validate_event(e) == [] for e in sink.events)

    def test_drift_watchdog_fires_on_mid_window_excursion(self):
        """A transient leak that relaxes before the flush must still
        fire: the watchdog gates on the WINDOW MAX drift, matching the
        offline science --budget gate over the full series (unit-level
        via doctored fetched diagnostics, like the imbalance test)."""
        def diag(it, etot):
            return {"obs_ttot": it * 1e-3, "dt": 1e-3, "obs_etot": etot,
                    "obs_ecin": 0.0, "obs_eint": etot, "obs_egrav": 0.0,
                    "obs_linmom": 0.0, "obs_angmom": 0.0}

        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]),
                         drift_budget=0.1)
        # spike at step 2, fully relaxed by the window's last step
        sim._emit_science([diag(1, 1.0), diag(2, 1.5), diag(3, 1.0)],
                          [1, 2, 3])
        (ev,) = sink.of_kind("drift")
        assert ev["it"] == 2 and ev["drift"] == pytest.approx(0.5)
        assert sim.energy_drift == pytest.approx(0.0)  # latest verified

    def test_drift_watchdog_silent_without_budget(self):
        """Default is report-only: no budget, no drift events — but the
        drift itself is still tracked for bench/CLI consumers."""
        from sphexa_tpu.observables import ObservableSpec

        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]),
                         obs_spec=ObservableSpec())
        sim.step()
        sim.state = dataclasses.replace(sim.state,
                                        temp=sim.state.temp * 2.0)
        sim.step()
        assert sink.of_kind("drift") == []
        assert sim.energy_drift > 0.05

    def test_field_health_watchdog_fires_on_seeded_nan(self):
        """A seeded NaN velocity must poison du in the next step and
        surface as a ``field_health`` event naming the bad field —
        with the pointer at --debug-checks for localization."""
        import numpy as np

        from sphexa_tpu.observables import ObservableSpec

        sink = MemorySink()
        sim = _sedov_sim(telemetry=Telemetry(sinks=[sink]),
                         obs_spec=ObservableSpec())
        sim.step()
        assert sink.of_kind("field_health") == []
        vx = np.asarray(sim.state.vx).copy()
        vx[0] = np.nan
        import jax.numpy as jnp

        sim.state = dataclasses.replace(sim.state, vx=jnp.asarray(vx))
        d = sim.step()
        assert int(d["n_bad_du"]) > 0
        (ev,) = sink.of_kind("field_health")
        assert ev["nonfinite"] > 0 and ev["fields"]["du"] > 0
        assert "--debug-checks" in ev["hint"]
        assert sim.telemetry.counters["field_health"] == 1

# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _make_run(tmp_path, name, step_walls, particles=1000):
    d = tmp_path / name
    t = Telemetry(sinks=[JsonlSink(str(d / "events.jsonl"))])
    for i, w in enumerate(step_walls, 1):
        t.event("step", it=i, wall_s=w, dt=0.1, reconfigured=False)
    t.event("retrace", it=1, delta=1)
    t.close()
    write_manifest(str(d), particles=particles, config={"side": 8})
    return str(d)


class TestCli:
    def test_summary_text_and_json(self, tmp_path, capsys):
        run = _make_run(tmp_path, "a", [0.1, 0.2, 0.3])
        assert cli_main(["summary", run]) == 0
        out = capsys.readouterr().out
        assert "step time p50" in out and "retraces" in out
        assert cli_main(["summary", run, "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["steps"] == 3 and s["retraces"] == 1
        assert s["step_time"]["p50_s"] == pytest.approx(0.2)
        assert s["manifest"]["particles"] == 1000

    def test_summary_strict_flags_schema_drift(self, tmp_path, capsys):
        run = _make_run(tmp_path, "a", [0.1])
        with open(f"{run}/events.jsonl", "a") as f:
            f.write('{"v":1,"seq":9,"t":1.0,"kind":"bogus"}\n')
            f.write("not json\n")
            # truncated step/window events (killed run): flagged but must
            # not crash the aggregation
            f.write('{"v":1,"seq":10,"t":1.0,"kind":"step","it":2}\n')
            f.write('{"v":1,"seq":11,"t":1.0,"kind":"window","it":3,'
                    '"steps":2}\n')
        assert cli_main(["summary", run]) == 0  # lax by default
        out = capsys.readouterr().out
        assert "steps" in out
        assert cli_main(["summary", run, "--strict"]) == 1
        assert "schema:" in capsys.readouterr().out

    def test_jsonl_sink_truncates_per_run(self, tmp_path):
        """One sink = one run: re-running into the same --telemetry-dir
        must not merge two runs' events under one manifest."""
        from sphexa_tpu.telemetry.cli import load_events

        path = str(tmp_path / "events.jsonl")
        for it in (1, 2):
            t = Telemetry(sinks=[JsonlSink(path)])
            t.event("step", it=it, wall_s=0.1)
            t.close()
        events, problems = load_events(str(tmp_path))
        assert problems == []
        assert len(events) == 1 and events[0]["it"] == 2

    def test_summary_excludes_initial_configure(self, tmp_path):
        from sphexa_tpu.telemetry.cli import summarize_run

        sim = _sedov_sim(
            telemetry=Telemetry(
                sinks=[JsonlSink(str(tmp_path / "events.jsonl"))])
        )
        sim.step()
        sim.telemetry.close()
        s = summarize_run(str(tmp_path))
        # the construction-time sizing is not a mid-run reconfigure
        assert s["reconfigures"] == 0
        assert sim.telemetry.counters.get("reconfigures", 0) == 0
        assert sim.telemetry.counters["events.reconfigure"] == 1

    def test_summary_missing_run_is_usage_error(self, tmp_path, capsys):
        assert cli_main(["summary", str(tmp_path / "nope")]) == 2
        assert "events.jsonl" in capsys.readouterr().err

    def test_diff_runs_threshold_exit_codes(self, tmp_path, capsys):
        base = _make_run(tmp_path, "base", [0.1] * 5)
        cand = _make_run(tmp_path, "cand", [0.25] * 5)
        # 150% slower: beyond a 50% threshold, within a 200% one
        assert cli_main(["diff", base, cand, "--threshold", "0.5"]) == 1
        assert "REGRESSED" in capsys.readouterr().out
        assert cli_main(["diff", base, cand, "--threshold", "2.0"]) == 0
        # faster candidate is never a step-time regression
        assert cli_main(["diff", cand, base, "--threshold", "0.5"]) == 0

    def test_diff_bench_files(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(
            {"metric": "m", "value": 100.0, "unit": "u",
             "extra": {"ve_updates_per_sec": 70.0}}))
        # driver wrapper shape (BENCH_r*.json): bench line buried in tail
        b.write_text(json.dumps(
            {"n": 5, "rc": 0,
             "tail": "warn\n" + json.dumps(
                 {"metric": "m", "value": 50.0, "unit": "u",
                  "extra": {"ve_updates_per_sec": 90.0}})}))
        assert cli_main(["diff", str(a), str(b)]) == 1  # throughput halved
        capsys.readouterr()
        assert cli_main(["diff", str(b), str(a)]) == 0
        out = capsys.readouterr().out
        assert "updates_per_sec" in out

    def test_diff_run_vs_bench(self, tmp_path):
        # run: 1000 particles / 0.1 s p50 = 1e4 ups vs bench 5e3 -> ok
        run = _make_run(tmp_path, "run", [0.1] * 4, particles=1000)
        bench = tmp_path / "bench.json"
        bench.write_text(json.dumps({"metric": "m", "value": 5e3,
                                     "unit": "u"}))
        assert cli_main(["diff", str(bench), run]) == 0
        # and a bench far above the run's throughput regresses
        bench.write_text(json.dumps({"metric": "m", "value": 5e5,
                                     "unit": "u"}))
        assert cli_main(["diff", str(bench), run]) == 1

    def test_strict_reports_unknown_kind_counts(self, tmp_path, capsys):
        """Forward compat: kinds this reader does not know are COUNTED
        and reported (never silently dropped from the aggregation);
        --strict turns them into exit 1 so CI notices version skew."""
        run = _make_run(tmp_path, "a", [0.1])
        with open(f"{run}/events.jsonl", "a") as f:
            f.write(json.dumps({"v": SCHEMA_VERSION, "seq": 8, "t": 1.0,
                                "kind": "from_the_future", "x": 1}) + "\n")
            f.write(json.dumps({"v": SCHEMA_VERSION, "seq": 9, "t": 1.0,
                                "kind": "from_the_future", "x": 2}) + "\n")
        assert cli_main(["summary", run]) == 0  # lax: reported, not fatal
        out = capsys.readouterr().out
        assert "unknown kind: from_the_future x2" in out
        assert cli_main(["summary", run, "--strict"]) == 1
        capsys.readouterr()
        assert cli_main(["summary", run, "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["unknown_kinds"] == {"from_the_future": 2}

    def test_v1_v2_v3_files_validate_under_v4_reader(self, tmp_path,
                                                     capsys):
        """The version-compat contract: files written by the v1-v3
        schemas (older envelopes, their own kinds) summarize strictly
        clean under this v4 reader; a newer-only kind claiming an older
        version is flagged."""
        d = tmp_path / "v1run"
        d.mkdir()
        with open(d / "events.jsonl", "w") as f:
            f.write('{"v":1,"seq":0,"t":1.0,"kind":"step","it":1,'
                    '"wall_s":0.1}\n')
            f.write('{"v":1,"seq":1,"t":1.0,"kind":"retrace","it":1,'
                    '"delta":1}\n')
            # v2 envelope with a v2 kind: valid under the v4 reader
            f.write('{"v":2,"seq":2,"t":1.0,"kind":"exchange","it":1,'
                    '"shipped_rows":1,"rows":[1]}\n')
            # v3 envelope with a v3 kind: valid too
            f.write('{"v":3,"seq":3,"t":1.0,"kind":"physics","it":1,'
                    '"etot":[1.0]}\n')
            # v4 kinds on a v4 envelope: valid
            f.write('{"v":4,"seq":4,"t":1.0,"kind":"phase_attr",'
                    '"phases":{"density":10.0},"coverage":0.9}\n')
            f.write('{"v":4,"seq":5,"t":1.0,"kind":"crash",'
                    '"reason":"signal SIGTERM"}\n')
        assert cli_main(["summary", str(d), "--strict"]) == 0
        capsys.readouterr()
        with open(d / "events.jsonl", "a") as f:
            f.write('{"v":1,"seq":6,"t":1.0,"kind":"exchange","it":2,'
                    '"shipped_rows":1,"rows":[1]}\n')
        assert cli_main(["summary", str(d), "--strict"]) == 1
        assert "v2-only kind" in capsys.readouterr().out
        with open(d / "events.jsonl", "a") as f:
            f.write('{"v":2,"seq":7,"t":1.0,"kind":"physics","it":3,'
                    '"etot":[1.0]}\n')
        assert cli_main(["summary", str(d), "--strict"]) == 1
        assert "v3-only kind" in capsys.readouterr().out
        # a v4-only kind claiming a v3 envelope is writer confusion
        with open(d / "events.jsonl", "a") as f:
            f.write('{"v":3,"seq":8,"t":1.0,"kind":"crash",'
                    '"reason":"x"}\n')
        assert cli_main(["summary", str(d), "--strict"]) == 1
        assert "v4-only kind" in capsys.readouterr().out

    def _make_shard_run(self, tmp_path):
        d = tmp_path / "mesh"
        t = Telemetry(sinks=[JsonlSink(str(d / "events.jsonl"))])
        for it in (3, 6):
            t.event("shard_load", it=it, steps=3,
                    particles=[256, 256], work=[900.0 + it, 700.0])
            t.event("exchange", it=it, steps=3, mode="sparse",
                    shipped_rows=512, rows=[200 + it, 150],
                    occ=[0.8, 0.6], bytes_per_step=512 * 18 * 4, trips=1)
        t.event("memory", point="flush", it=6, devices=["0", "1"],
                bytes_in_use=[1024, 2048], peak_bytes_in_use=[4096, 8192])
        t.event("imbalance", it=6, metric="work", ratio=1.6,
                threshold=1.5)
        t.close()
        write_manifest(str(d), particles=512, mesh_shape=(2,))
        return str(d)

    def test_shards_view_renders_and_aggregates(self, tmp_path, capsys):
        run = self._make_shard_run(tmp_path)
        assert cli_main(["shards", run]) == 0
        out = capsys.readouterr().out
        assert "halo rows" in out and "occ p95" in out
        assert "sparse" in out and "escape trips" in out
        assert "memory snapshots:" in out
        assert cli_main(["shards", run, "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert [sh["shard"] for sh in s["shards"]] == [0, 1]
        assert s["shards"][0]["particles"] == 256
        assert s["shards"][0]["work_share"] > s["shards"][1]["work_share"]
        assert s["shipped_rows"] == 512 and s["mode"] == "sparse"
        assert s["imbalance_events"] == 1 and s["trips"] == 1
        assert s["memory"][0]["peak_bytes_in_use"] == [4096, 8192]

    def test_shards_view_splits_gravity_stage(self, tmp_path, capsys):
        """Schema v7: a run with BOTH staged exchange records renders
        the SPH columns unchanged plus the gravity serve's columns and
        summary block; the stages never mix (the gravity rows must not
        pollute the SPH halo-rows aggregate)."""
        d = tmp_path / "gmesh"
        t = Telemetry(sinks=[JsonlSink(str(d / "events.jsonl"))])
        for it in (3, 6):
            t.event("shard_load", it=it, steps=3, stage="sph",
                    particles=[256, 256], work=[900.0 + it, 700.0])
            t.event("exchange", it=it, steps=3, mode="sparse",
                    shipped_rows=512, rows=[200 + it, 150],
                    occ=[0.8, 0.6], bytes_per_step=512 * 18 * 4,
                    trips=0, stage="sph")
            t.event("exchange", it=it, steps=3, mode="sparse",
                    shipped_rows=2864, rows=[1000 + it, 900],
                    occ=[0.95, 0.7], bytes_per_step=2864 * 5 * 4,
                    trips=1, stage="gravity")
        t.close()
        write_manifest(str(d), particles=512, mesh_shape=(2,))
        assert cli_main(["shards", str(d)]) == 0
        out = capsys.readouterr().out
        assert "grav rows" in out and "grav occ" in out
        assert "gravity rows/serve" in out and "gravity trips" in out
        assert cli_main(["shards", str(d), "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        # SPH aggregates untouched by the gravity records
        assert s["shipped_rows"] == 512 and s["trips"] == 0
        assert s["shards"][0]["rows_mean"] < 1000
        g = s["gravity"]
        assert g["shipped_rows"] == 2864 and g["trips"] == 1
        assert g["windows"] == 2 and g["mode"] == "sparse"
        assert s["shards"][0]["grav_rows_mean"] > 1000
        assert 0 < s["shards"][1]["grav_occ_p95"] <= 1.0

    def test_shards_exit_1_without_shard_telemetry(self, tmp_path, capsys):
        """The mesh smoke's assertion: a run with no per-shard events
        must FAIL the shards view (exit 1), so check.sh catches a
        silently un-instrumented mesh run."""
        run = _make_run(tmp_path, "plain", [0.1])
        assert cli_main(["shards", run]) == 1
        assert "no per-shard telemetry" in capsys.readouterr().out

    def test_diff_multichip_wrapper(self, tmp_path, capsys):
        """MULTICHIP_r*.json wrapper diffing: the measure_multichip
        --json line buried in a driver-wrapper tail compares with
        threshold exit codes — comm-volume saving is higher-is-better."""
        base = tmp_path / "MULTICHIP_base.json"
        cand = tmp_path / "mc_cand.json"
        line = {"metric": "sparse-halo saving vs replication", "value": 4.0,
                "unit": "x", "extra": {"s16_p8_shipped_frac": 0.5,
                                       "s16_p8_saving": 4.0}}
        base.write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True,
             "tail": "dryrun OK\n" + json.dumps(line)}))
        cand.write_text(json.dumps(line))  # identical candidate
        assert cli_main(["diff", str(base), str(cand)]) == 0
        capsys.readouterr()
        worse = dict(line, value=3.0,
                     extra={"s16_p8_shipped_frac": 0.7,
                            "s16_p8_saving": 3.0})
        cand.write_text(json.dumps(worse))
        # saving dropped 25%: beyond a 5% threshold -> regression exit 1
        assert cli_main(["diff", str(base), str(cand),
                         "--threshold", "0.05"]) == 1
        assert "REGRESSED" in capsys.readouterr().out

    def _make_science_run(self, tmp_path, name, etots, nan_steps=0,
                          watchdogs=()):
        d = tmp_path / name
        t = Telemetry(sinks=[JsonlSink(str(d / "events.jsonl"))])
        n = len(etots)
        t.event("physics", it=n, steps=n, its=list(range(1, n + 1)),
                t_sim=[0.001 * i for i in range(1, n + 1)],
                dt=[0.001] * n, etot=etots, ecin=[0.0] * n,
                eint=etots, egrav=[0.0] * n, linmom=[0.0] * n,
                angmom=[0.0] * n)
        t.event("numerics", it=n, steps=n,
                limiter={"courant": n - 1, "growth": 1},
                nonfinite={"rho": 0, "h": 0, "du": nan_steps},
                nc_clip=0, h_sat=2, rho_min=0.9, rho_max=1.5,
                h_min=0.1, h_max=0.2, du_max=0.3)
        for kind in watchdogs:
            if kind == "drift":
                t.event("drift", it=n, drift=0.5, budget=0.1,
                        etot0=etots[0], etot=etots[-1])
            else:
                t.event("field_health", it=n, nonfinite=nan_steps,
                        fields={"du": nan_steps}, hint="--debug-checks")
        t.close()
        write_manifest(str(d), particles=512)
        return str(d)

    def test_science_renders_and_exit_codes(self, tmp_path, capsys):
        run = self._make_science_run(tmp_path, "clean", [1.0, 1.0, 1.0])
        assert cli_main(["science", run]) == 0
        out = capsys.readouterr().out
        assert "|drift| max" in out and "timestep limiter" in out
        assert "courant" in out and "extrema timeline" in out
        assert cli_main(["science", run, "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["steps"] == 3 and s["drift"]["max"] == 0.0
        assert s["limiter"] == {"courant": 2, "growth": 1}
        # budget gate: 10% drift against a 5% budget fails, 20% passes
        leaky = self._make_science_run(tmp_path, "leaky", [1.0, 1.05, 1.1])
        assert cli_main(["science", leaky, "--budget", "0.05"]) == 1
        capsys.readouterr()
        assert cli_main(["science", leaky, "--budget", "0.2"]) == 0
        capsys.readouterr()
        # without a budget, in-run watchdog events decide the exit code
        fired = self._make_science_run(tmp_path, "fired", [1.0, 1.5],
                                       watchdogs=("drift",))
        assert cli_main(["science", fired]) == 1
        capsys.readouterr()
        sick = self._make_science_run(tmp_path, "sick", [1.0, float("nan")],
                                      nan_steps=3,
                                      watchdogs=("field_health",))
        assert cli_main(["science", sick]) == 1
        out = capsys.readouterr().out
        assert "field-health events" in out

    def test_science_partial_run_no_traceback(self, tmp_path, capsys):
        """Satellite regression: a run that crashed before its first
        flush (launch events only, possibly a truncated trailing line)
        must render partial output from BOTH summary and science — exit
        codes, never tracebacks."""
        d = tmp_path / "crashed"
        t = Telemetry(sinks=[JsonlSink(str(d / "events.jsonl"))])
        t.event("reconfigure", it=0, reason="initial")
        for i in (1, 2, 3):
            t.event("launch", it=i)
        t.close()
        write_manifest(str(d), particles=64)
        with open(d / "events.jsonl", "a") as f:
            f.write('{"v":3,"seq":99,"t":1.0,"kind":"phys')  # killed mid-write
        assert cli_main(["summary", str(d)]) == 0
        out = capsys.readouterr().out
        assert "steps" in out and "schema: line 5" in out
        assert cli_main(["science", str(d)]) == 1  # no ledger: must fail
        assert "no physics telemetry" in capsys.readouterr().out
        # strict still flags the truncated line without crashing
        assert cli_main(["summary", str(d), "--strict"]) == 1

    def test_diff_drift_threshold_exit_codes(self, tmp_path, capsys):
        base = self._make_science_run(tmp_path, "dbase",
                                      [1.0, 1.001, 1.002])  # 0.2% drift
        cand = self._make_science_run(tmp_path, "dcand",
                                      [1.0, 1.005, 1.01])   # 1% drift
        # drift x5 vs baseline: regression beyond a 100% threshold
        assert cli_main(["diff", base, cand, "--drift",
                         "--threshold", "1.0"]) == 1
        assert "energy_drift_max" in capsys.readouterr().out
        assert cli_main(["diff", base, cand, "--drift",
                         "--threshold", "10.0"]) == 0
        capsys.readouterr()
        # improving drift never regresses
        assert cli_main(["diff", cand, base, "--drift",
                         "--threshold", "1.0"]) == 0
        capsys.readouterr()
        # without --drift the drift row informs but cannot regress
        assert cli_main(["diff", base, cand, "--threshold", "1.0"]) == 0
        capsys.readouterr()
        # --drift needs physics telemetry on both sides
        plain = _make_run(tmp_path, "noledger", [0.1])
        assert cli_main(["diff", base, plain, "--drift"]) == 2
        assert "--drift" in capsys.readouterr().err

    def test_app_writes_manifest_and_events(self, tmp_path):
        import os

        from sphexa_tpu.app.main import main as app_main
        from sphexa_tpu.telemetry.cli import summarize_run

        tdir = str(tmp_path / "telemetry")
        rc = app_main(["--init", "sedov", "-n", "6", "-s", "2", "--quiet",
                       "-o", str(tmp_path / "out"), "--telemetry-dir", tdir])
        assert rc == 0
        s = summarize_run(tdir)
        assert s["schema_problems"] == []
        assert s["steps"] == 2
        assert s["manifest"]["particles"] == 216
        assert s["manifest"]["config"]["prop"] == "std"
        assert s["phase_mean_s"]  # Timer laps flowed through as phases
        assert cli_main(["summary", tdir, "--strict"]) == 0
        # the in-graph ledger made it into the record: science renders
        assert cli_main(["science", tdir]) == 0
        # clean exit: the flight recorder disarmed, no blackbox written
        assert not os.path.exists(os.path.join(tdir, "blackbox.json"))
        assert s["crash"] is None


# ---------------------------------------------------------------------------
# cross-run history + the regression lock (schema v4 CLI)
# ---------------------------------------------------------------------------


class TestHistoryAndRegress:
    def _bench_file(self, tmp_path, name, value, ve=None, wrapped=False,
                    extra=None):
        line = {"metric": "particle-updates/sec/chip", "value": value,
                "unit": "particles/s", "vs_baseline": value / 2e7,
                "extra": dict(extra or {})}
        if ve is not None:
            line["extra"]["ve_updates_per_sec"] = ve
        p = tmp_path / name
        if wrapped:
            p.write_text(json.dumps(
                {"n": 5, "rc": 0, "tail": "noise\n" + json.dumps(line)}))
        else:
            p.write_text(json.dumps(line))
        return str(p)

    def test_history_renders_rounds_and_trend(self, tmp_path, capsys):
        self._bench_file(tmp_path, "BENCH_r01.json", 1.0e6, wrapped=True)
        self._bench_file(tmp_path, "BENCH_r02.json", 2.0e6, ve=1.5e6)
        # a committed skipped round keeps its row instead of erroring
        (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "tail": "dry run"}))
        assert cli_main(["history", "--root", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "r01" in out and "r02" in out
        assert "+100.0%" in out  # 1.0 -> 2.0 M/s between rounds
        assert "dry-run ok" in out
        assert "bench trajectory" in out
        assert cli_main(["history", "--root", str(tmp_path),
                         "--format", "json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert [r["round"] for r in rows] == [1, 2, 1]
        assert rows[1]["change"] == pytest.approx(1.0)
        # empty root: nothing to trend is exit 1, not a fake table
        empty = tmp_path / "empty"
        empty.mkdir()
        assert cli_main(["history", "--root", str(empty)]) == 1
        capsys.readouterr()
        # unreadable input is a usage error
        assert cli_main(["history", str(tmp_path / "nope.json")]) == 2
        # an explicit input that is valid JSON but NOT a bench/wrapper
        # file (a manifest, the lock itself, a typo) must exit 2 too,
        # not fabricate a value-less row
        stray = tmp_path / "manifest.json"
        stray.write_text(json.dumps({"schema": 1, "particles": 64}))
        assert cli_main(["history", str(stray)]) == 2
        # a round-NAMED file with non-dict JSON is corrupt, not a dry
        # run: exit 2, no traceback
        corrupt = tmp_path / "BENCH_r09.json"
        corrupt.write_text("[1, 2]")
        assert cli_main(["history", str(corrupt)]) == 2

    def _lock_file(self, tmp_path, value, source="BENCH_r05.json",
                   field="value", threshold=0.05):
        lock = {"schema": 1, "metrics": [
            {"name": "std_updates_per_sec", "source": source,
             "field": field, "value": value, "threshold": threshold,
             "higher_is_better": True}]}
        p = tmp_path / "LOCK.json"
        p.write_text(json.dumps(lock))
        return str(p)

    def test_regress_exit_codes(self, tmp_path, capsys):
        self._bench_file(tmp_path, "BENCH_r05.json", 3.5e6, wrapped=True)
        # holding: committed value matches the lock
        lock = self._lock_file(tmp_path, 3.5e6)
        assert cli_main(["regress", "--lock", lock]) == 0
        assert "all locked metrics hold" in capsys.readouterr().out
        # a doctored lock claiming a higher chip number fails the gate
        lock = self._lock_file(tmp_path, 4.2e6)
        assert cli_main(["regress", "--lock", lock]) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out and "regression vs lock" in out
        # within threshold: 3% below a 5% budget still holds
        lock = self._lock_file(tmp_path, 3.6e6)
        assert cli_main(["regress", "--lock", lock]) == 0
        capsys.readouterr()
        # a missing source/field must FAIL, not silently pass
        lock = self._lock_file(tmp_path, 3.5e6, source="GONE.json")
        assert cli_main(["regress", "--lock", lock]) == 1
        assert "problem:" in capsys.readouterr().out
        lock = self._lock_file(tmp_path, 3.5e6, field="extra.nope")
        assert cli_main(["regress", "--lock", lock]) == 1
        capsys.readouterr()
        # unreadable lock file is a usage error
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert cli_main(["regress", "--lock", str(bad)]) == 2

    def test_regress_candidate_and_write(self, tmp_path, capsys):
        """The harvest-day workflow: gate a FRESH measurement against
        the lock before committing it, then --write to lock it in."""
        self._bench_file(tmp_path, "BENCH_r05.json", 3.5e6, wrapped=True)
        lock = self._lock_file(tmp_path, 3.5e6)
        good = self._bench_file(tmp_path, "fresh.json", 3.8e6)
        worse = self._bench_file(tmp_path, "slow.json", 3.0e6)
        assert cli_main(["regress", "--lock", lock, good]) == 0
        capsys.readouterr()
        assert cli_main(["regress", "--lock", lock, worse]) == 1
        capsys.readouterr()
        # --write + candidate is a usage error: it would silently relock
        # the stale committed values, not the fresh file
        assert cli_main(["regress", "--lock", lock, good, "--write"]) == 2
        capsys.readouterr()
        # --write re-reads the committed source and locks its value
        self._bench_file(tmp_path, "BENCH_r05.json", 3.9e6, wrapped=True)
        assert cli_main(["regress", "--lock", lock, "--write"]) == 0
        capsys.readouterr()
        locked = json.loads(open(lock).read())
        assert locked["metrics"][0]["value"] == pytest.approx(3.9e6)
        assert cli_main(["regress", "--lock", lock]) == 0

    def test_regress_candidate_gates_matching_kind_only(self, tmp_path,
                                                        capsys):
        """A candidate measures ONE kind: its metrics are gated, the
        other kind's locked metrics are skipped (a fresh BENCH says
        nothing about the multichip saving — comparing a throughput
        against a saving ratio was a nonsense verdict either way), and
        a candidate matching NO locked metric fails."""
        self._bench_file(tmp_path, "BENCH_r05.json", 3.5e6, wrapped=True)
        lock = {"schema": 1, "metrics": [
            {"name": "std_updates_per_sec", "source": "BENCH_r05.json",
             "field": "value", "value": 3.5e6, "threshold": 0.05},
            {"name": "multichip_sparse_saving",
             "source": "MULTICHIP_BASELINE.json", "field": "value",
             "value": 1.25, "threshold": 0.05}]}
        lp = tmp_path / "LOCK.json"
        lp.write_text(json.dumps(lock))
        # bench candidate: throughput gated, the saving skipped — worse
        # throughput still fails, a BETTER one passes even though 3.8e6
        # vs the locked 1.25 saving would be nonsense
        good = self._bench_file(tmp_path, "fresh.json", 3.8e6)
        assert cli_main(["regress", "--lock", str(lp), good]) == 0
        out = capsys.readouterr().out
        assert "skipped" in out and "REGRESSED" not in out
        worse = self._bench_file(tmp_path, "slow.json", 3.0e6)
        assert cli_main(["regress", "--lock", str(lp), worse]) == 1
        capsys.readouterr()
        # multichip candidate: only the saving is gated (a fresh saving
        # of 1.3 vs the locked bench 3.5e6 must NOT read as regressed)
        mc = tmp_path / "MULTICHIP_fresh.json"
        mc.write_text(json.dumps(
            {"metric": "sparse saving", "value": 1.3, "unit": "x"}))
        assert cli_main(["regress", "--lock", str(lp), str(mc)]) == 0
        out = capsys.readouterr().out
        assert out.count("skipped") == 1 and "ok" in out
        # a candidate whose kind matches no locked metric gated nothing
        lock["metrics"] = lock["metrics"][:1]  # bench-only lock
        lp.write_text(json.dumps(lock))
        assert cli_main(["regress", "--lock", str(lp), str(mc)]) == 1
        assert "nothing was gated" in capsys.readouterr().out
        # a multichip source NOT named MULTICHIP_* classifies by its
        # CONTENT (saving metric), so a bench candidate skips it
        (tmp_path / "chip_saving.json").write_text(json.dumps(
            {"metric": "sparse-exchange saving", "value": 1.25,
             "unit": "x"}))
        lock["metrics"] = [
            {"name": "saving", "source": "chip_saving.json",
             "field": "value", "value": 1.25, "threshold": 0.05}]
        lp.write_text(json.dumps(lock))
        assert cli_main(["regress", "--lock", str(lp), "--root",
                         str(tmp_path), good]) == 1  # skipped -> nothing gated
        assert "nothing was gated" in capsys.readouterr().out

    def test_committed_lock_holds(self, capsys):
        """The repo's own TELEMETRY_LOCK.json must gate green against
        the committed round files — the check.sh contract."""
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        lock = os.path.join(root, "TELEMETRY_LOCK.json")
        assert cli_main(["regress", "--lock", lock]) == 0
        assert "all locked metrics hold" in capsys.readouterr().out
