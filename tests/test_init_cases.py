"""Init-case tests: field/geometry invariants for every built-in test case
plus short propagator runs. Mirrors the reference's main/test/init/grid.cpp
and the per-case settings in main/src/init/*.hpp.
"""

import numpy as np
import pytest

from sphexa_tpu.init import (
    CASES,
    init_evrard,
    init_gresho_chan,
    init_isobaric_cube,
    init_kelvin_helmholtz,
    init_noh,
    init_wind_shock,
    make_initializer,
)
from sphexa_tpu.sfc.box import BoundaryType
from sphexa_tpu.simulation import Simulation


def _np(state, f):
    return np.asarray(getattr(state, f))


class TestFactory:
    def test_all_cases_registered(self):
        assert set(CASES) == {
            "sedov", "noh", "evrard", "gresho-chan", "isobaric-cube",
            "kelvin-helmholtz", "wind-shock", "turbulence", "evrard-cooling",
        }

    def test_unknown_case_raises(self):
        with pytest.raises(ValueError):
            make_initializer("nope")

    def test_settings_file_overrides(self, tmp_path):
        """'case:settings.json' applies JSON overrides to the case defaults
        (the reference's --init sedov:file path, factory.hpp:47-48)."""
        import json

        path = tmp_path / "s.json"
        path.write_text(json.dumps({"gamma": 1.4, "mTotal": 2.0}))
        state, box, const = make_initializer(f"sedov:{path}")(6)
        assert const.gamma == pytest.approx(1.4)
        np.testing.assert_allclose(np.asarray(state.m).sum(), 2.0, rtol=1e-5)

    def test_sedov_derived_energy_override(self, tmp_path):
        """Overriding energyTotal must re-derive the spike amplitude
        (ener0), not keep the default blast energy."""
        import json

        path = tmp_path / "e.json"
        path.write_text(json.dumps({"energyTotal": 2.0}))
        s1, _, c1 = make_initializer(f"sedov:{path}")(6)
        s0, _, c0 = make_initializer("sedov")(6)
        u1 = (np.asarray(s1.temp) * c1.cv * np.asarray(s1.m)).sum()
        u0 = (np.asarray(s0.temp) * c0.cv * np.asarray(s0.m)).sum()
        assert u1 / u0 == pytest.approx(2.0, rel=1e-3)


class TestNoh:
    def test_geometry_and_velocity(self):
        state, box, const = init_noh(12)
        x, y, z = _np(state, "x"), _np(state, "y"), _np(state, "z")
        r = np.sqrt(x**2 + y**2 + z**2)
        assert state.n > 0.4 * 12**3  # sphere cut keeps pi/6 of the cube
        assert np.all(r <= 0.5 + 1e-6)
        # unit radial inflow
        vdotr = (_np(state, "vx") * x + _np(state, "vy") * y + _np(state, "vz") * z)
        speed = np.sqrt(
            _np(state, "vx") ** 2 + _np(state, "vy") ** 2 + _np(state, "vz") ** 2
        )
        assert np.all(vdotr < 0)
        np.testing.assert_allclose(speed, 1.0, rtol=1e-5)
        assert box.boundaries[0] == BoundaryType.open
        # total mass = mTotal
        np.testing.assert_allclose(_np(state, "m").sum(), 1.0, rtol=1e-5)


class TestEvrard:
    def test_profile_and_h(self):
        state, box, const = init_evrard(12)
        x, y, z = _np(state, "x"), _np(state, "y"), _np(state, "z")
        r = np.sqrt(x**2 + y**2 + z**2)
        assert np.all(r <= 1.0 + 1e-6)
        assert const.g == 1.0
        # rho ~ 1/r: shell mass within r grows ~ r^2 => N(<0.5) ~ 4x N(<0.25)
        n_inner = (r < 0.25).sum()
        n_mid = (r < 0.5).sum()
        assert 2.5 < n_mid / max(n_inner, 1) < 6.0
        # h grows with radius (h ~ r^(1/3))
        h = _np(state, "h")
        assert h[r > 0.8].mean() > h[r < 0.2].mean()


class TestGreshoChan:
    def test_velocity_profile(self):
        state, box, const = init_gresho_chan(12)
        x, y = _np(state, "x"), _np(state, "y")
        psi = np.sqrt(x**2 + y**2) / 0.2
        v = np.sqrt(_np(state, "vx") ** 2 + _np(state, "vy") ** 2)
        np.testing.assert_allclose(v[psi <= 1.0], psi[psi <= 1.0], rtol=1e-4)
        assert np.all(v[psi > 2.0] < 1e-6)
        assert np.all(_np(state, "vz") == 0)
        # azimuthal: v . r == 0
        vdotr = _np(state, "vx") * x + _np(state, "vy") * y
        np.testing.assert_allclose(vdotr, 0.0, atol=1e-5)

    def test_short_run_stays_finite(self):
        state, box, const = init_gresho_chan(10)
        sim = Simulation(state, box, const, prop="std", block=256)
        for _ in range(3):
            sim.step()
        for f in ("x", "vx", "temp", "h"):
            assert np.all(np.isfinite(_np(sim.state, f))), f


class TestIsobaricCube:
    def test_density_contrast(self):
        state, box, const = init_isobaric_cube(14)
        x, y, z = _np(state, "x"), _np(state, "y"), _np(state, "z")
        r = 0.25
        inner = (np.abs(x) < r) & (np.abs(y) < r) & (np.abs(z) < r)
        v_in = (2 * r) ** 3
        v_out = 1.0 - v_in
        ratio = (inner.sum() / v_in) / ((~inner).sum() / v_out)
        assert 5.0 < ratio < 11.0, ratio  # target 8
        # isobaric: temp_in/temp_ext = rhoExt/rhoInt
        t = _np(state, "temp")
        np.testing.assert_allclose(
            t[inner].mean() / t[~inner].mean(), 1.0 / 8.0, rtol=0.05
        )


class TestKelvinHelmholtz:
    def test_band_contrast_and_shear(self):
        state, box, const = init_kelvin_helmholtz(12)
        y = _np(state, "y")
        inner = (y > 0.25) & (y < 0.75)
        ratio = (inner.sum() / 0.5) / ((~inner).sum() / 0.5)
        assert 1.6 < ratio < 2.4, ratio  # target 2
        vx = _np(state, "vx")
        assert vx[(y > 0.35) & (y < 0.65)].mean() < -0.3  # band flows -x
        assert vx[(y < 0.15) | (y > 0.85)].mean() > 0.3  # outside flows +x
        # seeded vy perturbation has the right amplitude
        assert 0.001 < np.abs(_np(state, "vy")).max() <= 0.011


class TestWindShock:
    def test_blob_and_wind(self):
        state, box, const = init_wind_shock(10)
        x, y, z = _np(state, "x"), _np(state, "y"), _np(state, "z")
        r, rs = 0.125, 0.025
        rpos = np.sqrt((x - r) ** 2 + (y - r) ** 2 + (z - r) ** 2)
        cloud = rpos <= rs
        assert cloud.sum() > 5
        vx = _np(state, "vx")
        assert np.all(vx[cloud] == 0)
        np.testing.assert_allclose(vx[~cloud], 2.7, rtol=1e-5)
        # number-density contrast ~ 10
        v_cloud = 4 / 3 * np.pi * rs**3
        v_tot = (8 * r) * (2 * r) * (2 * r)
        ratio = (cloud.sum() / v_cloud) / ((~cloud).sum() / (v_tot - v_cloud))
        assert 5.0 < ratio < 15.0, ratio


class TestEvrardRun:
    def test_gravity_hydro_run(self):
        state, box, const = init_evrard(10)
        sim = Simulation(state, box, const, prop="std", block=256, theta=0.5)
        for _ in range(3):
            sim.step()
        st = sim.state
        for f in ("x", "vx", "temp", "h"):
            assert np.all(np.isfinite(_np(st, f))), f
        # cold sphere must start collapsing: net radial velocity < 0
        x, y, z = _np(st, "x"), _np(st, "y"), _np(st, "z")
        rr = np.maximum(np.sqrt(x**2 + y**2 + z**2), 1e-9)
        vr = (_np(st, "vx") * x + _np(st, "vy") * y + _np(st, "vz") * z) / rr
        assert vr.mean() < 0


def test_generate_glass_template(tmp_path):
    """generate-once + tile: the damped relaxation reduces density
    fluctuations, the saved block round-trips through --glass tiling
    (init/utils.hpp:100-168 pipeline, generation included)."""
    import numpy as np

    from sphexa_tpu.init.glass import (
        generate_glass_template,
        jittered_lattice,
        read_template_block,
        set_glass_template,
        write_template_block,
    )

    x, y, z = generate_glass_template(side=8, relax_steps=8)
    assert len(x) == 512
    assert (x >= 0).all() and (x < 1).all()

    # density uniformity: nearest-neighbor distance spread tightens vs
    # the jittered lattice it started from
    def nn_spread(xs, ys, zs):
        p = np.stack([xs, ys, zs], 1)
        d2 = ((p[:, None, :] - p[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d2, np.inf)
        nn = np.sqrt(d2.min(1))
        return nn.std() / nn.mean()

    x0, y0, z0 = jittered_lattice((0, 0, 0), (1, 1, 1), (8, 8, 8))
    assert nn_spread(x, y, z) < nn_spread(x0, y0, z0)

    path = str(tmp_path / "glass.h5")
    write_template_block(path, x, y, z)
    set_glass_template(path)
    try:
        gx, gy, gz = jittered_lattice((0, 0, 0), (2, 2, 2), (16, 16, 16))
        assert len(gx) == 8 * 512  # 2x2x2 tiles of the 8^3 block
        assert (gx >= 0).all() and (gx < 2).all()
    finally:
        set_glass_template(None)
