"""Ewald periodic-gravity tests + polytropic EOS.

Correctness strategy mirrors ryoanji/test/nbody/ewald_cpu.cpp's intent,
adapted to properties that are exact regardless of tuning: zero net force
(momentum), lattice symmetry, translation invariance, and independence of
the Ewald splitting parameter alpha (real/k-space decomposition must sum
to the same total).
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.gravity.ewald import EwaldConfig, compute_gravity_ewald
from sphexa_tpu.gravity.traversal import GravityConfig, estimate_gravity_caps
from sphexa_tpu.gravity.tree import build_gravity_tree
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.sph.eos import ideal_gas_eos_u, polytropic_eos


def _setup(x, y, z, m, box, theta=0.6, bucket=32):
    """Sort by SFC keys and build the gravity tree (Simulation._configure_gravity)."""
    keys = np.asarray(compute_sfc_keys(x, y, z, box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (jnp.asarray(np.asarray(a)[order]) for a in (x, y, z, m))
    skeys = jnp.asarray(keys[order])
    gtree, meta = build_gravity_tree(keys[order], bucket_size=bucket)
    cfg = estimate_gravity_caps(
        xs, ys, zs, ms, skeys, box, gtree, meta,
        GravityConfig(theta=theta, bucket_size=bucket, G=1.0), margin=2.0,
    )
    return xs, ys, zs, ms, skeys, gtree, meta, cfg


def _ewald_accels(x, y, z, m, box, ecfg=None, **kw):
    xs, ys, zs, ms, skeys, gtree, meta, cfg = _setup(x, y, z, m, box, **kw)
    h = jnp.full_like(xs, 1e-3)
    ax, ay, az, egrav, diag = compute_gravity_ewald(
        xs, ys, zs, ms, h, skeys, box, gtree, meta, cfg,
        ecfg or EwaldConfig(),
    )
    assert int(diag["m2p_max"]) <= cfg.m2p_cap
    assert int(diag["p2p_max"]) <= cfg.p2p_cap
    return (np.asarray(ms), np.asarray(ax), np.asarray(ay), np.asarray(az),
            float(egrav))


@pytest.fixture(scope="module")
def random_config():
    rng = np.random.default_rng(5)
    n = 128
    x, y, z = rng.uniform(-0.5, 0.5, (3, n)).astype(np.float32)
    m = rng.uniform(0.5, 1.5, n).astype(np.float32)
    box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
    return x, y, z, m, box


class TestEwald:
    def test_momentum_conservation(self, random_config):
        x, y, z, m, box = random_config
        ms, ax, ay, az, _ = _ewald_accels(x, y, z, m, box)
        scale = np.sum(ms * np.sqrt(ax**2 + ay**2 + az**2))
        for a in (ax, ay, az):
            assert abs(np.sum(ms * a)) / scale < 0.05

    def test_cubic_lattice_forces_vanish(self):
        # perfectly symmetric periodic lattice: every particle's force ~ 0
        side = 4
        line = (np.arange(side) + 0.5) / side - 0.5
        zz, yy, xx = np.meshgrid(line, line, line, indexing="ij")
        x, y, z = (a.ravel().astype(np.float32) for a in (xx, yy, zz))
        m = np.ones(side**3, np.float32)
        box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
        _, ax, ay, az, _ = _ewald_accels(x, y, z, m, box)
        # compare against the force scale of a single neighbor pair
        pair_scale = 1.0 / (1.0 / side) ** 2
        for a in (ax, ay, az):
            assert np.abs(a).max() / pair_scale < 0.02

    def test_forces_match_particle_level_ewald(self, random_config):
        """The gold test (the role of ryoanji's ewald_cpu.cpp reference
        values): compare against a float64 particle-level Ewald sum."""
        scipy_special = pytest.importorskip("scipy.special")
        x, y, z, m, box = random_config
        x, y, z, m = x[:64], y[:64], z[:64], m[:64]

        def brute(alpha=4.0, nshell=4, kmax=8):
            from itertools import product as iproduct

            pos = np.stack([x, y, z], axis=1).astype(np.float64)
            acc = np.zeros((len(m), 3))
            for nx, ny, nz in iproduct(range(-nshell, nshell + 1), repeat=3):
                R = pos[None, :, :] - pos[:, None, :] + np.array([nx, ny, nz])
                r2 = (R**2).sum(-1)
                if nx == ny == nz == 0:
                    np.fill_diagonal(r2, np.inf)
                r = np.sqrt(r2)
                f = (
                    scipy_special.erfc(alpha * r) / (r * r2)
                    + 2 * alpha / np.sqrt(np.pi) * np.exp(-(alpha**2) * r2) / r2
                )
                acc += (m[None, :, None] * f[:, :, None] * R).sum(axis=1)
            for hx, hy, hz in iproduct(range(-kmax, kmax + 1), repeat=3):
                h2 = hx * hx + hy * hy + hz * hz
                if h2 == 0 or h2 > kmax * kmax:
                    continue
                k = 2 * np.pi * np.array([hx, hy, hz])
                k2 = (k**2).sum()
                sc = (m * np.cos(pos @ k)).sum()
                ss = (m * np.sin(pos @ k)).sum()
                coef = 4 * np.pi / k2 * np.exp(-k2 / (4 * alpha**2))
                ph = pos @ k
                acc += coef * (-np.sin(ph) * sc + np.cos(ph) * ss)[:, None] * k[None, :]
            return acc

        from sphexa_tpu.sfc.keys import compute_sfc_keys

        keys = np.asarray(compute_sfc_keys(x, y, z, box))
        order = np.argsort(keys)
        a_ref = brute()[order]
        _, ax, ay, az, _ = _ewald_accels(x, y, z, m, box)
        a_ours = np.stack([ax, ay, az], axis=1)
        scale = np.linalg.norm(a_ref, axis=1).mean()
        err = np.linalg.norm(a_ours - a_ref, axis=1) / scale
        assert err.mean() < 0.01, err.mean()
        assert err.max() < 0.05, err.max()

    def test_translation_invariance_of_forces(self, random_config):
        """The force field is translation invariant (the potential's Ewald
        constant is window-dependent at quadrupole truncation — same
        property as the reference — so only forces are compared)."""
        x, y, z, m, box = random_config
        _, ax0, ay0, az0, _ = _ewald_accels(x, y, z, m, box)
        shift = np.float32(0.2371)
        xs = ((x + shift + 0.5) % 1.0) - 0.5
        ys = ((y - shift + 0.5) % 1.0) - 0.5
        _, ax1, ay1, az1, _ = _ewald_accels(xs, ys, z, m, box)
        # particle identity is lost to the internal sort; compare the
        # sorted force-magnitude spectrum
        f0 = np.sort(np.sqrt(ax0**2 + ay0**2 + az0**2))
        f1 = np.sort(np.sqrt(ax1**2 + ay1**2 + az1**2))
        np.testing.assert_allclose(f1, f0, rtol=5e-2, atol=3e-2 * f0.max())

    def test_alpha_independence(self, random_config):
        """The real/k-space split must not change the force field: run with
        two different splitting parameters and compare."""
        x, y, z, m, box = random_config
        e_a = EwaldConfig(alpha_scale=2.0, lcut=2.6, hcut=2.8)
        e_b = EwaldConfig(alpha_scale=2.5, lcut=3.2, hcut=3.4)
        _, ax0, ay0, az0, _ = _ewald_accels(x, y, z, m, box, ecfg=e_a)
        _, ax1, ay1, az1, _ = _ewald_accels(x, y, z, m, box, ecfg=e_b)
        scale = np.abs(ax0).max()
        np.testing.assert_allclose(ax1, ax0, atol=2e-2 * scale)
        np.testing.assert_allclose(az1, az0, atol=2e-2 * scale)

    def test_periodic_differs_from_open(self, random_config):
        """Periodic images must contribute: Ewald forces differ from the
        open-boundary Barnes-Hut forces of the same configuration."""
        from sphexa_tpu.gravity.traversal import compute_gravity

        x, y, z, m, box = random_config
        xs, ys, zs, ms, skeys, gtree, meta, cfg = _setup(x, y, z, m, box)
        h = jnp.full_like(xs, 1e-3)
        ax_o, *_ = compute_gravity(xs, ys, zs, ms, h, skeys, box, gtree, meta, cfg)
        _, ax_e, *_ = _ewald_accels(x, y, z, m, box)
        assert np.abs(ax_e - np.asarray(ax_o)).max() > 1e-3 * np.abs(ax_o).max()


class TestSedovGravityEwald:
    def test_periodic_gravity_run(self):
        """A periodic case with gravity enabled now runs through the Ewald
        path (previously NotImplementedError)."""
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(8, overrides={"gravConstant": 0.5})
        import dataclasses as dc

        sim = Simulation(state, box, const, prop="std", block=256)
        assert sim.ewald_on
        d = sim.step()
        # egrav's sign is convention-dependent for a near-uniform periodic
        # box (window-dependent Ewald constant); finiteness + stability are
        # the contract here
        assert np.isfinite(d["egrav"])
        assert np.all(np.isfinite(np.asarray(sim.state.vx)))
        assert float(d["dt"]) > 0


class TestPolytropicEOS:
    def test_values(self):
        rho = jnp.array([1e6, 2e6])
        p, c = polytropic_eos(rho)
        from sphexa_tpu.sph.eos import GAMMA_POL, KPOL_NS

        assert float(p[0]) == pytest.approx(KPOL_NS * 1e18, rel=1e-5)
        assert float(p[1]) / float(p[0]) == pytest.approx(8.0, rel=1e-5)
        assert float(c[0]) == pytest.approx(
            np.sqrt(GAMMA_POL * KPOL_NS * 1e18 / 1e6), rel=1e-5
        )

    def test_ideal_gas_u(self):
        p, c = ideal_gas_eos_u(jnp.array([1.5]), jnp.array([2.0]), 5.0 / 3.0)
        assert float(p[0]) == pytest.approx(2.0)
        assert float(c[0]) == pytest.approx(np.sqrt(5.0 / 3.0 * 1.0), rel=1e-6)
