"""util/timer.py coverage: Timer lap accumulation, ProfileRecorder
save/summary NaN-padding for ragged rows, and the thin-adapter contract
over the telemetry registry (laps mirrored into a shared Telemetry)."""

import os
import time

import numpy as np

from sphexa_tpu.telemetry import MemorySink, Telemetry
from sphexa_tpu.util.timer import ProfileRecorder, Timer


class TestTimer:
    def test_step_accumulates_and_pop_clears(self):
        t = Timer()
        t.start()
        e1 = t.step("a")
        e2 = t.step("a")
        t.step("b")
        assert e1 >= 0.0 and e2 >= 0.0
        laps = t.pop()
        assert set(laps) == {"a", "b"}
        # two laps under the same name accumulate (timer.hpp:46)
        assert laps["a"] >= e1 + e2 - 1e-9
        assert t.pop() == {}  # pop clears

    def test_step_measures_elapsed(self):
        t = Timer()
        t.start()
        time.sleep(0.01)
        assert t.step("sleep") >= 0.009

    def test_start_resets_mark(self):
        t = Timer()
        time.sleep(0.01)
        t.start()
        assert t.step("a") < 0.009

    def test_laps_mirror_into_telemetry(self):
        tel = Telemetry()
        t = Timer(telemetry=tel)
        t.start()
        t.step("phase")
        t.step("phase")
        assert tel.phase_counts["phase"] == 2
        assert tel.phase_totals["phase"] >= 0.0
        assert tel.timing_mean("phase") >= 0.0


class TestProfileRecorder:
    def test_save_empty_writes_nothing(self, tmp_path):
        p = ProfileRecorder()
        path = str(tmp_path / "profile.npz")
        assert p.save(path) is False
        assert not os.path.exists(path)

    def test_save_substeps_only_still_writes(self, tmp_path):
        p = ProfileRecorder()
        path = str(tmp_path / "profile.npz")
        assert p.save(path, substeps={"density": 0.5}) is True
        data = np.load(path)
        assert float(data["substep_density"]) == 0.5

    def test_ragged_rows_nan_padded(self, tmp_path):
        p = ProfileRecorder()
        p.record(1, {"step": 0.5}, dt=0.1)
        p.record(2, {"step": 0.7, "output": 0.2}, dt=0.3)
        path = str(tmp_path / "profile.npz")
        assert p.save(path) is True
        data = np.load(path)
        np.testing.assert_array_equal(data["iteration"], [1.0, 2.0])
        np.testing.assert_allclose(data["step"], [0.5, 0.7])
        # 'output' missing from row 1 -> NaN, not a shape error
        assert np.isnan(data["output"][0]) and data["output"][1] == 0.2

    def test_summary_nanmean_skips_missing(self):
        p = ProfileRecorder()
        p.record(1, {"step": 0.5})
        p.record(2, {"step": 0.7, "output": 0.2})
        s = p.summary()
        assert s["step"] == np.float64(0.6).item()
        assert s["output"] == 0.2  # mean over present rows only
        assert "iteration" not in s

    def test_summary_empty(self):
        assert ProfileRecorder().summary() == {}

    def test_rows_emit_phases_events(self):
        sink = MemorySink()
        p = ProfileRecorder(telemetry=Telemetry(sinks=[sink]))
        p.record(3, {"step": 0.5}, dt=0.1)
        (e,) = sink.of_kind("phases")
        assert e["it"] == 3 and e["step"] == 0.5 and e["dt"] == 0.1
