"""End-to-end Sedov blast regression: the minimum viable slice of the whole
framework (SURVEY.md §7 stage 3). Mirrors the role of the reference's
ReFrame e2e CI (sphexa --init sedov): run real steps, assert physical
sanity and conservation.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import conserved_quantities
from sphexa_tpu.simulation import Simulation


@pytest.fixture(scope="module")
def sedov_run():
    state, box, const = init_sedov(20)
    sim = Simulation(state, box, const, prop="std", block=512)
    e0 = conserved_quantities(sim.state, const)
    diags = [sim.step() for _ in range(10)]
    e1 = conserved_quantities(sim.state, const)
    return sim, const, e0, e1, diags


@pytest.mark.slow
class TestSedovE2E:
    def test_runs_without_nans(self, sedov_run):
        sim, *_ = sedov_run
        for f in ("x", "vx", "temp", "h", "du"):
            assert np.all(np.isfinite(np.asarray(getattr(sim.state, f)))), f

    def test_energy_conservation(self, sedov_run):
        _, _, e0, e1, _ = sedov_run
        drift = abs(float(e1["etot"]) - float(e0["etot"])) / abs(float(e0["etot"]))
        assert drift < 1e-3, f"energy drift {drift}"

    def test_momentum_stays_zero(self, sedov_run):
        # symmetric blast: net momentum must remain ~0
        _, _, e0, e1, _ = sedov_run
        assert float(e1["linmom"]) < 1e-4

    def test_energy_converts_internal_to_kinetic(self, sedov_run):
        _, _, e0, e1, _ = sedov_run
        assert float(e1["ecin"]) > float(e0["ecin"])

    def test_neighbor_counts_sane(self, sedov_run):
        *_, diags = sedov_run
        nc = diags[-1]["nc_mean"]
        assert 50 < nc < 200, nc  # target ng0=100

    def test_timestep_growth_capped(self, sedov_run):
        *_, diags = sedov_run
        dts = [d["dt"] for d in diags]
        for a, b in zip(dts, dts[1:]):
            assert b <= a * 1.1 * (1 + 1e-5)

    def test_blast_expands_outward(self, sedov_run):
        sim, *_ = sedov_run
        st = sim.state
        r = np.sqrt(np.asarray(st.x) ** 2 + np.asarray(st.y) ** 2 + np.asarray(st.z) ** 2)
        vr = (np.asarray(st.vx) * np.asarray(st.x) + np.asarray(st.vy) * np.asarray(st.y)
              + np.asarray(st.vz) * np.asarray(st.z)) / np.maximum(r, 1e-9)
        inner = r < 0.15
        assert vr[inner].mean() > 0, "blast region should move outward"
