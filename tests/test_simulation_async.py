"""Deferred cap-checking (Simulation.check_every > 1): the async happy
path must produce bit-identical trajectories to the synchronous checked
path, and a deferred-detected overflow must roll back and replay so that
overflow never corrupts state (the late-checked analog of the reference's
halo-sanity MPI_Abort + restart, halos/halos.hpp:73-105)."""

import dataclasses

import numpy as np
import pytest

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation


def _final_state(sim, steps):
    for _ in range(steps):
        sim.step()
    sim.flush()
    return sim.state


def test_async_matches_sync():
    state, box, const = init_sedov(12)
    s_sync = Simulation(state, box, const, prop="std", block=4096)
    s_async = Simulation(state, box, const, prop="std", block=4096,
                         check_every=4)
    a = _final_state(s_sync, 6)
    b = _final_state(s_async, 6)
    np.testing.assert_array_equal(np.asarray(a.x), np.asarray(b.x))
    np.testing.assert_array_equal(np.asarray(a.temp), np.asarray(b.temp))
    assert s_sync.iteration == s_async.iteration == 6


def test_deferred_overflow_rolls_back_and_replays():
    state, box, const = init_sedov(12)
    ref = Simulation(state, box, const, prop="std", block=4096)
    ref_state = _final_state(ref, 5)

    sim = Simulation(state, box, const, prop="std", block=4096,
                     check_every=5)
    # sabotage the cap so every cell overflows: the deferred check must
    # detect it, roll back, reconfigure and replay without corrupting state
    good_nbr = sim._cfg.nbr
    sim._cfg = dataclasses.replace(
        sim._cfg, nbr=dataclasses.replace(good_nbr, cap=8)
    )
    d = None
    for _ in range(5):
        d = sim.step()
    d = sim.flush()
    assert d["reconfigured"] == 1.0
    assert sim.iteration == 5
    assert sim._cfg.nbr.cap > 8  # re-sized
    np.testing.assert_allclose(
        np.asarray(sim.state.x), np.asarray(ref_state.x), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(sim.state.temp), np.asarray(ref_state.temp), rtol=1e-6
    )


def test_flush_idempotent_and_deferred_flag():
    state, box, const = init_sedov(10)
    sim = Simulation(state, box, const, prop="std", block=4096,
                     check_every=8)
    d1 = sim.step()
    assert d1.get("deferred") == 1.0
    d2 = sim.flush()
    assert "deferred" not in d2 or d2.get("deferred") != 1.0
    assert sim.flush() is d2 or sim.flush() == d2  # nothing pending


def test_deferred_h_outgrows_cell_mid_window():
    """VERDICT r4 weak #7 pin: under check_every > 1 the 2h-vs-cell-edge
    freshness check only runs at flush — so a smoothing length that has
    outgrown the configured search window can run up to check_every
    unchecked steps. The in-step window_ok guard must encode that as the
    occupancy sentinel, and flush must roll the whole window back and
    replay it through the checked path (which reconfigures first), ending
    in the same state a synchronous run from the same ICs produces."""
    import jax.numpy as jnp

    # 32^3: the grid has window < ncell — a 4x h growth genuinely cannot
    # be covered by the configured window (a tiny grid would fall into
    # the fold-mode escape hatch, which handles any h correctly and
    # defeats the point of the test)
    state, box, const = init_sedov(32)
    sim = Simulation(state, box, const, prop="std", block=4096,
                     check_every=4)
    assert sim._cfg.nbr.window < (1 << sim._cfg.nbr.level)
    # h outgrows the cell grid AFTER configuration, BEFORE the window:
    # every deferred step runs with a too-small search window
    sim.state = dataclasses.replace(
        sim.state, h=jnp.asarray(sim.state.h) * 4.0
    )
    for _ in range(3):
        d = sim.step()      # stale steps run unchecked (happy path)
        assert d.get("deferred") == 1.0
    d = sim.step()          # 4th step drains the window: detect + replay
    assert d["reconfigured"] == 1.0
    assert sim.iteration == 4
    assert int(d["occupancy"]) <= sim._cfg.nbr.cap
    assert np.all(np.isfinite(np.asarray(sim.state.x)))

    # equivalence: a synchronous run whose config was sized for the
    # grown h from the start
    gstate = dataclasses.replace(state, h=jnp.asarray(state.h) * 4.0)
    ref = Simulation(gstate, box, const, prop="std", block=4096)
    for _ in range(4):
        ref.step()
    np.testing.assert_allclose(
        np.asarray(sim.state.x), np.asarray(ref.state.x),
        rtol=1e-6, atol=1e-8,
    )
    np.testing.assert_allclose(
        np.asarray(sim.state.temp), np.asarray(ref.state.temp), rtol=1e-5
    )
