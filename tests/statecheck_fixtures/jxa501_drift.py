"""JXA501 fixtures: schema drift vs a doctored committed lock.

``drifting_schema``'s row in the sibling ``jxa501_schema.json`` records
its scalar output as float64 — the live trace produces float32, so the
drift rule fires with a per-leaf diff. ``stable_schema``'s row matches
exactly and stays clean; ``unlocked_schema`` has NO row, which is the
CLI's missing-from-lock business, never a rule finding.

Run by tests/test_statecheck.py with the audit context's
``state_schema_path`` pointed at the doctored lock (the committed
STATE_SCHEMA.json knows nothing about fixture entries, so these are
invisible to the package gate).
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("drifting_schema", phase_coverage_min=0.0)  # expect: JXA501
def drifting_schema():
    def fn(x):
        return x * 2.0, x.sum()

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("stable_schema", phase_coverage_min=0.0)
def stable_schema():
    def fn(x):
        return x + 1.0

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("unlocked_schema", phase_coverage_min=0.0)
def unlocked_schema():
    def fn(x):
        return x - 1.0

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))
