"""JXA502 fixtures: entries that break or degrade under jax.vmap.

``vmap_trace_break``: an optimization_barrier fence has no batching
rule in this jax — the vmapped trace raises, captured as a finding.
``vmap_callback``: a debug print lowers to debug_callback, which under
vmap serializes per member. ``vmap_serialized``: a sequential_vmap
custom-batched inner fn — the batch rule is an explicit member loop, so
the vmapped jaxpr gains a scan the base jaxpr does not have.
``vmap_clean`` is the honest twin: plain elementwise math batches into
one fused program.

Run by tests/test_statecheck.py with ``vmap_members=2`` set on the
audit context (the rule is off at the default ``vmap_members=0``, so
these entries are invisible to the package gate).
"""

import jax
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("vmap_trace_break", phase_coverage_min=0.0)  # expect: JXA502
def vmap_trace_break():
    def fn(x):
        return jax.lax.optimization_barrier(x * 2.0)

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("vmap_callback", phase_coverage_min=0.0)  # expect: JXA502
def vmap_callback():
    def fn(x):
        jax.debug.print("x0={v}", v=x[0])
        return x * 2.0

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("vmap_serialized", phase_coverage_min=0.0)  # expect: JXA502
def vmap_serialized():
    @jax.custom_batching.sequential_vmap
    def inner(x):
        return x * 2.0

    def fn(x):
        return inner(x)

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))


@entrypoint("vmap_clean", phase_coverage_min=0.0)
def vmap_clean():
    def fn(x):
        return jnp.sin(x) * 2.0, x.sum()

    return EntryCase(fn=fn, args=(jnp.zeros(8, jnp.float32),))
