"""JXA503 fixtures: carries not closed under the step.

``none_flip_carry`` is the structural break the unified SimState carry
exists to catch: an aux slot that is ``None`` on step 1 comes back as
an array on step 2 — the treedef itself changes, scan rejects it, and
a flat leaf zip (JXA102's view) cannot anchor the break to a path.
``aval_drift_carry`` keeps the structure but widens a leaf's rank —
the per-leaf closure layer fires. ``closed_carry`` is the honest twin:
outputs rearrange into step-2 args with identical treedef and avals.

Run by tests/test_statecheck.py under ``select=["JXA503"]`` (in the
full-rule package audit JXA102 co-fires on aval drift by design — the
two rules report different consequences of the same break).
"""

import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("none_flip_carry", phase_coverage_min=0.0)  # expect: JXA503
def none_flip_carry():
    def fn(x, aux):
        del aux  # step 1 runs with the slot empty...
        return x * 2.0, x.sum()

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(8, jnp.float32), None),
        # ...but the carry writes the scalar INTO the slot: None on
        # step 1, array on step 2 — the treedef flips
        carry=lambda a, out: (out[0], out[1]),
    )


@entrypoint("aval_drift_carry", phase_coverage_min=0.0)  # expect: JXA503
def aval_drift_carry():
    def fn(x):
        return jnp.stack([x, x])

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(8, jnp.float32),),
        carry=lambda a, out: (out,),  # f32[8] in, f32[2,8] back
    )


@entrypoint("closed_carry", phase_coverage_min=0.0)
def closed_carry():
    def fn(x, s):
        return x * 2.0, s + 1.0

    return EntryCase(
        fn=fn,
        args=(jnp.zeros(8, jnp.float32), jnp.float32(0.0)),
        carry=lambda a, out: (out[0], out[1]),
    )
