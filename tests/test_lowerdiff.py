"""jaxdiff: the canonical lowering fingerprint, the lock, the
structural differ, and the JXA402 knob-inertness probes.

The fingerprint's value is its stability contract: same program ->
same digest, across retraces in one process (jax's pretty-print var
counter must not leak in) and across processes (no object addresses, no
hash-randomized iteration). tests here pin the contract on toy
programs; tests/test_parallel.py keeps the ONE raw ``as_text()``
byte-identity pin that guards the canonicalizer itself, and
scripts/check.sh verifies the committed LOWERING_LOCK.json across a
process boundary every run.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sphexa_tpu.devtools.audit.lowerdiff import (
    DEFAULT_LOCK_PATH,
    LOCK_VERSION,
    UNATTRIBUTED,
    KnobProbe,
    LockError,
    fingerprint_callable,
    load_lock,
    main as lowering_main,
    production_knob_probes,
    structural_diff,
    write_lock,
)
from sphexa_tpu.util.phases import phase_scope

REPO_ROOT = Path(__file__).resolve().parent.parent


def _double(x):
    return x * 2.0


class TestFingerprint:
    def test_deterministic_across_retraces(self):
        # jax's global pretty-print var counter advances with every
        # trace; an unrelated trace in between must not move the digest
        fp1 = fingerprint_callable(_double, jnp.ones(4))
        fingerprint_callable(lambda y: jnp.sin(y).sum(), jnp.ones((3, 3)))
        fp2 = fingerprint_callable(_double, jnp.ones(4))
        assert fp1.digest == fp2.digest
        assert fp1.eqn_hashes == fp2.eqn_hashes

    def test_alpha_invariance_vs_real_change(self):
        # a re-created lambda with identical structure collides; a
        # different literal does not
        fp_a = fingerprint_callable(lambda x: x * 2.0 + 1.0, jnp.ones(4))
        fp_b = fingerprint_callable(lambda x: x * 2.0 + 1.0, jnp.ones(4))
        fp_c = fingerprint_callable(lambda x: x * 3.0 + 1.0, jnp.ones(4))
        assert fp_a.digest == fp_b.digest
        assert fp_a.digest != fp_c.digest

    def test_jitted_and_inner_jaxprs(self):
        # a jitted callable traces to one pjit eqn whose body the walk
        # expands inline — the eqn count must see the body, not the call
        fp = fingerprint_callable(jax.jit(_double), jnp.ones(4))
        assert fp.eqns >= 2  # the pjit call + at least the mul
        assert any("pjit" in ln for ln in fp.lines)

    def test_phase_attribution(self):
        def fn(x):
            with phase_scope("density"):
                d = x * x
            with phase_scope("eos"):
                p = jnp.sqrt(d)
            return p + 1.0  # outside every scope

        fp = fingerprint_callable(fn, jnp.ones(8))
        assert fp.phases["density"].eqns >= 1
        assert fp.phases["eos"].eqns >= 1
        assert fp.phases[UNATTRIBUTED].eqns >= 1
        assert sum(p.eqns for p in fp.phases.values()) == fp.eqns

    def test_consts_move_the_digest(self):
        # same eqn structure, different baked const value: the global
        # digest must move even though the eqn-hash stream is identical
        w1 = np.arange(4, dtype=np.float32)
        w2 = np.arange(4, dtype=np.float32) + 1.0

        fp1 = fingerprint_callable(jax.jit(lambda x: x * jnp.asarray(w1)),
                                   jnp.ones(4))
        fp2 = fingerprint_callable(jax.jit(lambda x: x * jnp.asarray(w2)),
                                   jnp.ones(4))
        assert fp1.consts_digest != fp2.consts_digest
        assert fp1.digest != fp2.digest

    def test_collective_count(self):
        mesh = jax.make_mesh((2,), ("p",))
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        @partial(shard_map, mesh=mesh, in_specs=P("p"), out_specs=P())
        def fn(x):
            return jax.lax.psum(x.sum(), "p")[None]

        fp = fingerprint_callable(fn, jnp.ones(8))
        assert fp.collectives == 1


class TestLockIO:
    def test_roundtrip(self, tmp_path):
        fp = fingerprint_callable(_double, jnp.ones(4))
        path = tmp_path / "lock.json"
        write_lock(path, {"toy": fp.lock_payload()})
        entries = load_lock(path)
        assert entries["toy"]["digest"] == fp.digest
        assert entries["toy"]["eqns"] == fp.eqns
        assert json.loads(path.read_text())["version"] == LOCK_VERSION

    def test_corrupt_and_wrong_version_raise(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(LockError):
            load_lock(bad)
        versioned = tmp_path / "old.json"
        versioned.write_text(json.dumps({"version": 999, "entries": {}}))
        with pytest.raises(LockError):
            load_lock(versioned)
        with pytest.raises(LockError):
            load_lock(tmp_path / "missing.json")


class TestStructuralDiff:
    def test_first_divergence_and_phase_rows(self):
        def base(x):
            with phase_scope("density"):
                return (x * 2.0).sum()

        def changed(x):
            with phase_scope("density"):
                return (x * 2.0 + 1.0).sum()

        fp_base = fingerprint_callable(base, jnp.ones(4))
        fp_new = fingerprint_callable(changed, jnp.ones(4))
        report = "\n".join(
            structural_diff("toy", fp_base.lock_payload(), fp_new))
        assert "first divergence: eqn #" in report
        assert "phase density" in report
        assert "density" in report.split("phases:")[-1]

    def test_const_only_change_reports_no_eqn_divergence(self):
        w1 = np.arange(4, dtype=np.float32)
        w2 = np.arange(4, dtype=np.float32) + 1.0
        fp1 = fingerprint_callable(jax.jit(lambda x: x * jnp.asarray(w1)),
                                   jnp.ones(4))
        fp2 = fingerprint_callable(jax.jit(lambda x: x * jnp.asarray(w2)),
                                   jnp.ones(4))
        report = "\n".join(
            structural_diff("toy", fp1.lock_payload(), fp2))
        assert "no per-eqn divergence" in report


_TOY_REGISTRY = '''
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("toy_a", phase_coverage_min=0.0)
def toy_a():
    return EntryCase(fn=lambda x: x * 2.0, args=(jnp.ones(4),))


@entrypoint("toy_b", phase_coverage_min=0.0)
def toy_b():
    return EntryCase(fn=lambda x: x.sum(), args=(jnp.ones(4),))
'''


class TestCli:
    @pytest.fixture()
    def toy(self, tmp_path):
        reg = tmp_path / "toy_registry.py"
        reg.write_text(_TOY_REGISTRY)
        lock = tmp_path / "lock.json"
        rc = lowering_main([str(reg), "--lock", str(lock), "--write",
                            "--cpu-devices", "0"])
        assert rc == 0 and lock.exists()
        return reg, lock

    def test_write_then_verify(self, toy, capsys):
        reg, lock = toy
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--cpu-devices", "0"])
        assert rc == 0
        assert "2/2 entries match" in capsys.readouterr().out

    def test_doctored_digest_exits_1_with_diff(self, toy, capsys):
        reg, lock = toy
        payload = json.loads(lock.read_text())
        payload["entries"]["toy_a"]["digest"] = "0" * 32
        stream = payload["entries"]["toy_a"]["eqn_hashes"]
        payload["entries"]["toy_a"]["eqn_hashes"] = "deadbeef" + stream[8:]
        lock.write_text(json.dumps(payload))
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--cpu-devices", "0"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "toy_a: lowering drifted" in out
        assert "first divergence: eqn #0" in out

    def test_corrupt_lock_exits_2(self, toy):
        reg, lock = toy
        lock.write_text("{not json")
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--cpu-devices", "0"])
        assert rc == 2

    def test_unknown_entry_exits_2(self, toy):
        reg, lock = toy
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--entries", "no_such_entry",
                            "--cpu-devices", "0"])
        assert rc == 2

    def test_stale_and_missing_rows_exit_1(self, toy, capsys):
        reg, lock = toy
        payload = json.loads(lock.read_text())
        payload["entries"]["ghost"] = payload["entries"].pop("toy_b")
        lock.write_text(json.dumps(payload))
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--cpu-devices", "0"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "ghost" in out  # stale row flagged
        assert "toy_b" in out  # unlocked entry flagged
        # an --entries-filtered run must NOT flag staleness
        rc = lowering_main([str(reg), "--lock", str(lock),
                            "--entries", "toy_a", "--cpu-devices", "0"])
        assert rc == 0

    def test_json_payload(self, toy, capsys):
        reg, lock = toy
        rc = lowering_main([str(reg), "--lock", str(lock), "--json",
                            "--cpu-devices", "0"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["tool"] == "jaxdiff"
        assert {e["entry"] for e in payload["entries"]} == {"toy_a", "toy_b"}
        assert all(e["match"] for e in payload["entries"])
        assert payload["mismatched"] == []
        assert payload["errors"] == []


class TestKnobProbes:
    def test_production_probes_cover_every_off_sentinel(self):
        from sphexa_tpu.tuning.knobs import off_sentinel_knobs

        probes = production_knob_probes()
        assert [p.knob for p in probes] == \
            [s.name for s in off_sentinel_knobs()]
        assert len(probes) >= 7  # incl. dt_bins, grav_window, donate
        leaky = [p.knob for p in probes if p.off.digest != p.base.digest]
        assert not leaky, f"off sentinels perturb the lowering: {leaky}"

    def test_validate_off_sentinels_catches_renamed_site(self, monkeypatch):
        import sphexa_tpu.simulation as sim_mod
        from sphexa_tpu.tuning.knobs import validate_off_sentinels

        monkeypatch.setattr(
            sim_mod, "CONSUMED_KNOBS",
            tuple(k for k in sim_mod.CONSUMED_KNOBS if k != "dt_bins"))
        with pytest.raises(RuntimeError, match="dt_bins"):
            validate_off_sentinels()

    def test_jxa402_fires_on_manufactured_leak(self):
        # the rule itself, without a Simulation: a probe whose off
        # program lowers one extra eqn must produce exactly one finding
        from sphexa_tpu.devtools.audit.core import (
            EntryCase,
            EntryTrace,
            entrypoint,
        )
        from sphexa_tpu.devtools.audit.rules.jxa402_knob_inertness import (
            check,
        )

        probes = [KnobProbe(
            knob="leak", off_value=0,
            base=fingerprint_callable(lambda x: x * 2.0, jnp.ones(4)),
            off=fingerprint_callable(lambda x: x * 2.0 + 0.0, jnp.ones(4)),
        )]

        @entrypoint("manufactured", phase_coverage_min=0.0)
        def manufactured():
            return EntryCase(fn=lambda x: x, args=(jnp.ones(4),),
                             knob_probes=lambda: probes)

        # the decorator binding IS the EntryPoint
        findings = check(EntryTrace(manufactured, manufactured.build()))
        assert len(findings) == 1
        assert "leak" in findings[0].message


@pytest.mark.slow
class TestCommittedLock:
    def test_package_lock_verifies(self):
        """The committed LOWERING_LOCK.json must hold against the
        committed sources over the full registry (the check.sh gate,
        repeated here so the slow tier catches it without bash)."""
        rc = lowering_main([
            "--lock", str(REPO_ROOT / DEFAULT_LOCK_PATH),
            "--cpu-devices", "8"])
        assert rc == 0
