"""Precision pins for the 400^3 target scale (VERDICT r2 weak #8 /
SURVEY §7): the integer-key + f32-coordinate policy must resolve the
target problem's particle spacing with margin."""

import numpy as np

import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sfc.keys import compute_sfc_keys

SIDE = 400  # BASELINE.json target configuration (v5e-16, 64M particles)


def test_f32_coordinates_resolve_400cubed_spacing():
    """f32 position quantum is ~4 decades below the lattice spacing."""
    spacing = 1.0 / SIDE
    worst = np.max(np.abs(np.float64(np.float32(0.5)) - 0.5) + np.spacing(
        np.float32(0.5)
    ))
    assert worst < 1e-4 * spacing


def test_key_grid_finer_than_400cubed_spacing():
    """The 30-bit key grid (level 10) subdivides the target spacing, so
    the SFC sort fully orders a 400^3 lattice (cell edge 1/1024 < 1/400)
    and level <= 10 covers any occupancy-chosen search grid."""
    assert (1 << KEY_BITS) > SIDE


def test_keys_order_consistently_with_f64_at_scale():
    """Hilbert keys computed from f32 coordinates reproduce the f64 cell
    assignment for ~1e5 samples of the 400^3-scale box."""
    rng = np.random.default_rng(0)
    n = 100_000
    pos64 = rng.uniform(-0.5, 0.5, (n, 3))
    # snap to the 400^3 lattice +- 10% jitter (the IC geometry)
    pos64 = np.round(pos64 * SIDE) / SIDE + rng.uniform(
        -0.1 / SIDE, 0.1 / SIDE, (n, 3)
    )
    box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
    k32 = np.asarray(compute_sfc_keys(
        jnp.asarray(pos64[:, 0], jnp.float32),
        jnp.asarray(pos64[:, 1], jnp.float32),
        jnp.asarray(pos64[:, 2], jnp.float32), box,
    ))
    # f64 reference: quantize in float64 then encode the same grid cells
    lo, lengths = -0.5, 1.0
    ncell = 1 << KEY_BITS
    cells64 = np.clip(
        ((pos64 - lo) / lengths * ncell).astype(np.int64), 0, ncell - 1
    )
    cells32 = np.clip(
        ((np.float32(pos64).astype(np.float64) - lo) / lengths * ncell
         ).astype(np.int64), 0, ncell - 1,
    )
    # f32 rounding may shift a coordinate across a cell edge only within
    # one quantum — never more than one cell, and for <0.1% of samples
    diff = np.abs(cells64 - cells32)
    assert diff.max() <= 1
    assert (diff > 0).mean() < 1e-3
    assert np.unique(k32).size > 0.9 * n  # keys resolve distinct cells
