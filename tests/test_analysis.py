"""Tests for the semi-analytic Sedov/Noh solutions and L1 comparison.

Mirrors the role of the reference's ReFrame e2e checks
(.jenkins/reframe_ci.py:349-371): the analytic solvers are validated
against known exact values, then short simulation runs are compared via L1.
"""

import numpy as np
import pytest

from sphexa_tpu.analysis import (
    compute_output_fields,
    l1_error,
    noh_solution,
    sedov_solution,
)
from sphexa_tpu.analysis.sedov import _energy_alpha, _exponents


def _alpha(gamma, xgeom=3.0, omega=0.0):
    expo, coef, xg2 = _exponents(xgeom, omega, gamma)
    return _energy_alpha(expo, coef, xgeom, omega, gamma, xg2)


class TestSedovSolution:
    def test_alpha_gamma_14(self):
        # known value for the spherical gamma=1.4 standard case (Kamm 2000)
        assert abs(_alpha(1.4) - 0.8510719) < 1e-4

    def test_post_shock_density_ratio(self):
        gamma = 5.0 / 3.0
        sol = sedov_solution(np.array([1e-6]), time=0.05, gamma=gamma)
        r2 = sol["r_shock"]
        just_in = sedov_solution(np.array([r2 * 0.9999]), time=0.05, gamma=gamma)
        ratio = just_in["rho"][0]  # rho0 = 1
        assert abs(ratio - (gamma + 1) / (gamma - 1)) < 0.05  # -> 4

    def test_energy_self_consistency(self):
        # integrate the profile's total energy: must return eblast
        gamma, t, eblast = 5.0 / 3.0, 0.05, 1.0
        sol0 = sedov_solution(np.array([1.0]), time=t, gamma=gamma, eblast=eblast)
        r2 = sol0["r_shock"]
        r = np.linspace(1e-6, r2 * (1 - 1e-9), 20000)
        s = sedov_solution(r, time=t, gamma=gamma, eblast=eblast)
        e_density = 0.5 * s["rho"] * s["vel"] ** 2 + s["p"] / (gamma - 1.0)
        e_tot = np.trapezoid(e_density * 4 * np.pi * r**2, r)
        assert abs(e_tot - eblast) < 0.02 * eblast

    def test_density_vanishes_at_origin(self):
        sol = sedov_solution(np.array([1e-8, 1e-3]), time=0.05)
        assert sol["rho"][0] < 1e-3

    def test_upstream_state(self):
        sol = sedov_solution(np.array([10.0]), time=0.05, rho0=2.0, p0=0.5)
        assert sol["rho"][0] == 2.0
        assert sol["p"][0] == 0.5
        assert sol["vel"][0] == 0.0

    def test_shock_radius_scaling(self):
        # r2 ~ t^(2/5)
        r2a = sedov_solution(np.array([1.0]), time=0.01)["r_shock"]
        r2b = sedov_solution(np.array([1.0]), time=0.32)["r_shock"]
        assert abs(r2b / r2a - 32 ** (2.0 / 5.0)) < 1e-6


class TestNohSolution:
    def test_post_shock_density(self):
        gamma = 5.0 / 3.0
        sol = noh_solution(np.array([1e-4]), time=0.1, gamma=gamma)
        assert abs(sol["rho"][0] - ((gamma + 1) / (gamma - 1)) ** 3) < 1e-9  # 64

    def test_shock_front(self):
        sol = noh_solution(np.array([1.0]), time=0.3)
        assert abs(sol["r_shock"] - 0.5 * (2.0 / 3.0) * 0.3) < 1e-12

    def test_upstream_pileup(self):
        # free-falling upstream gas: rho = rho0 (1 + t/r)^2
        t, r = 0.1, 0.4
        sol = noh_solution(np.array([r]), time=t)
        assert abs(sol["rho"][0] - (1 + t / r) ** 2) < 1e-12
        assert sol["vel"][0] == 1.0

    def test_post_shock_at_rest(self):
        sol = noh_solution(np.array([1e-4]), time=0.3)
        assert sol["vel"][0] == 0.0
        assert sol["u"][0] == 0.5


class TestL1:
    def test_l1_zero_for_exact(self):
        a = np.linspace(0, 1, 100)
        assert l1_error(a, a) == 0.0

    def test_l1_scale(self):
        assert abs(l1_error(np.zeros(10), np.full(10, 2.0)) - 2.0) < 1e-12


@pytest.mark.parametrize("case", ["sedov"])
@pytest.mark.slow
def test_sedov_e2e_l1(case):
    """Short Sedov run tracked against the analytic solution — the same
    comparison the reference CI asserts at -n 50 -s 200 (L1_rho = 0.138);
    at this tiny scale (16^3, ~60 steps) we assert loose sanity bounds."""
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_sedov(16)
    sim = Simulation(state, box, const, prop="std", block=512)
    for _ in range(120):
        sim.step()
    t = float(sim.state.ttot)

    fields = compute_output_fields(sim.state, sim.box, sim._cfg)
    sol = sedov_solution(fields["r"], time=t, eblast=1.0, gamma=const.gamma)
    l1_rho = l1_error(fields["rho"], sol["rho"])
    # shock has formed and the sim tracks the solution to first order
    # (measured 0.32 at this 16^3 resolution; reference CI gets 0.138 at 50^3)
    assert np.isfinite(l1_rho)
    assert l1_rho < 0.6, l1_rho
    # a density peak forms (smoothed well below the analytic 4x jump at 16^3)
    assert 1.3 < fields["rho"].max() < 8.0
