"""Autotuner tier (sphexa_tpu/tuning/): knob registry drift, table
round-trip + resolution precedence, the deterministic search driver
over a fake measurement, replay-from-manifest, schema-v5 events, and
the CLI exit-code contracts (docs/TUNING.md)."""

import json
import os

import pytest

from sphexa_tpu.tuning import knobs as knobs_mod
from sphexa_tpu.tuning.knobs import (
    BLOCKDT_KNOBS,
    GRAVITY_KNOBS,
    KNOBS,
    NEIGHBOR_KNOBS,
    SIMULATION_KNOBS,
    KnobSpec,
    knob_names,
    validate_registry,
)
from sphexa_tpu.tuning.replay import (
    ReplaySpec,
    measure_candidate,
    spec_from_manifest,
)
from sphexa_tpu.tuning.search import domains_for, run_sweep
from sphexa_tpu.tuning.table import (
    TABLE_SCHEMA,
    coverage,
    load_table,
    make_entry,
    n_bucket,
    new_table,
    resolve_entry,
    resolve_knobs,
    save_table,
    upsert_entry,
    validate_table,
)
from sphexa_tpu.telemetry import MemorySink, Telemetry, write_manifest
from sphexa_tpu.telemetry.registry import (
    KIND_SINCE,
    SCHEMA_VERSION,
    validate_event,
)


def _entry(knobs, workload="sedov", n=1000, p=1, backend="xla",
           provenance=None):
    return make_entry(workload, n, p, backend, knobs,
                      provenance or {"source_run": "test"})


class TestKnobRegistry:
    def test_registry_matches_live_configs(self):
        # the import-time drift gate, run explicitly: every KnobSpec
        # must still name a real field on its owning dataclass/signature
        validate_registry()

    def test_drifted_spec_raises(self, monkeypatch):
        monkeypatch.setitem(
            knobs_mod.KNOBS, "target_block",
            KnobSpec("target_block", "GravityConfig", "renamed_away",
                     (64,), knobs_mod.COST_RECONFIGURE))
        with pytest.raises(RuntimeError, match="target_block"):
            validate_registry()

    def test_unknown_owner_raises(self, monkeypatch):
        monkeypatch.setitem(
            knobs_mod.KNOBS, "bogus",
            KnobSpec("bogus", "NoSuchConfig", "bogus", (1,),
                     knobs_mod.COST_STATIC))
        with pytest.raises(RuntimeError, match="unknown owner"):
            validate_registry()

    def test_groupings_cover_registry(self):
        grouped = set(GRAVITY_KNOBS) | set(NEIGHBOR_KNOBS) | set(
            SIMULATION_KNOBS) | set(BLOCKDT_KNOBS)
        assert grouped == set(knob_names())
        # domains are non-empty and lead with the production default
        for spec in KNOBS.values():
            assert spec.domain, spec.name


class TestTable:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "t.json")
        table = upsert_entry(new_table(), _entry({"gap": 128}))
        save_table(path, table)
        loaded = load_table(path)
        assert loaded["schema"] == TABLE_SCHEMA
        assert validate_table(loaded) == []
        assert loaded["entries"][0]["knobs"] == {"gap": 128}

    def test_load_errors(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_table(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text('{"not": "a table"}')
        with pytest.raises(ValueError, match="entries"):
            load_table(str(bad))

    def test_validate_flags_stale_knob_and_dupes(self):
        table = new_table()
        e = _entry({"gap": 128})
        e["knobs"]["ye_olde_knob"] = 1
        table["entries"] = [e, _entry({"gap": 256})]  # same key twice
        problems = validate_table(table)
        assert any("stale knob 'ye_olde_knob'" in p for p in problems)
        assert any("duplicate key" in p for p in problems)

    def test_make_entry_rejects_unregistered(self):
        with pytest.raises(ValueError, match="unregistered"):
            _entry({"warp_speed": 9})

    def test_n_bucket_decades(self):
        assert n_bucket(125) == "1e2"
        assert n_bucket(999) == "1e2"
        assert n_bucket(1000) == "1e3"
        assert n_bucket(500_000) == "1e5"

    def test_resolve_entry_prefers_exact_over_generic(self):
        table = new_table()
        upsert_entry(table, _entry({"gap": 128}, workload="generic"))
        upsert_entry(table, _entry({"gap": 512}, workload="sedov"))
        assert resolve_entry(table, "sedov", 1000, 1,
                             "xla")["knobs"] == {"gap": 512}
        assert resolve_entry(table, "noh", 1000, 1,
                             "xla")["knobs"] == {"gap": 128}
        assert resolve_entry(table, "sedov", 1000, 4, "xla") is None

    def test_coverage(self):
        table = upsert_entry(new_table(), _entry({"gap": 128}))
        assert coverage(table) == {
            "sedov/xla": {"n_buckets": ["1e3"], "p": [1]}}


class TestResolveKnobs:
    def test_precedence_explicit_beats_table(self, tmp_path):
        path = str(tmp_path / "t.json")
        save_table(path, upsert_entry(
            new_table(), _entry({"gap": 512, "cell_target": 64})))
        ov, prov = resolve_knobs(path, "sedov", 1000, 1, "xla",
                                 explicit={"gap": 999})
        # explicit kwarg wins: the table's gap never reaches overrides
        assert ov == {"cell_target": 64}
        assert prov["source"] == "table"
        assert prov["explicit"] == ["gap"]
        assert prov["key"]["n_bucket"] == "1e3"

    def test_fully_masked_entry_is_explicit(self, tmp_path):
        path = str(tmp_path / "t.json")
        save_table(path, upsert_entry(new_table(), _entry({"gap": 512})))
        ov, prov = resolve_knobs(path, "sedov", 1000, 1, "xla",
                                 explicit={"gap": 999})
        assert ov == {} and prov["source"] == "explicit"

    def test_none_is_heuristic_even_with_kwargs(self):
        # tuned=None must NEVER report "explicit": the app/bench always
        # pass kwargs, and a tuning event per ordinary run is noise
        ov, prov = resolve_knobs(None, "sedov", 1000, 1, "xla",
                                 explicit={"gap": 999})
        assert ov == {} and prov["source"] == "heuristic"

    def test_direct_dict_source(self):
        ov, prov = resolve_knobs({"gap": 256}, "sedov", 1000, 1, "xla",
                                 explicit={})
        assert ov == {"gap": 256} and prov["source"] == "direct"
        with pytest.raises(ValueError, match="unregistered"):
            resolve_knobs({"warp_speed": 9}, "sedov", 1000, 1, "xla",
                          explicit={})

    def test_table_miss_is_heuristic(self, tmp_path):
        path = str(tmp_path / "t.json")
        save_table(path, upsert_entry(new_table(), _entry({"gap": 512})))
        ov, prov = resolve_knobs(path, "evrard", 1000, 1, "xla",
                                 explicit={})
        assert ov == {} and prov["source"] == "heuristic"

    def test_simulation_consumes_table(self, tmp_path):
        # Simulation-level precedence at tiny N: table applies, an
        # explicit kwarg masks its knob, and provenance says so
        from sphexa_tpu.init import make_initializer
        from sphexa_tpu.simulation import Simulation

        path = str(tmp_path / "t.json")
        save_table(path, upsert_entry(new_table(), _entry(
            {"gap": 128, "check_every": 4}, n=125)))
        state, box, const = make_initializer("sedov")(5)
        sim = Simulation(state, box, const, backend="xla",
                         tuned=path, workload="sedov")
        assert sim.tuning_provenance["source"] == "table"
        assert sim.check_every == 4
        sim2 = Simulation(state, box, const, backend="xla",
                          tuned=path, workload="sedov", check_every=2)
        assert sim2.check_every == 2
        assert sim2.tuning_provenance["explicit"] == ["check_every"]

    def test_simulation_emits_tuning_event_only_when_tuned(self):
        from sphexa_tpu.init import make_initializer
        from sphexa_tpu.simulation import Simulation

        state, box, const = make_initializer("sedov")(5)
        mem = MemorySink()
        Simulation(state, box, const, backend="xla",
                   telemetry=Telemetry(sinks=[mem]))
        assert mem.of_kind("tuning") == []
        mem2 = MemorySink()
        Simulation(state, box, const, backend="xla",
                   tuned={"gap": 128}, workload="sedov",
                   telemetry=Telemetry(sinks=[mem2]))
        evs = mem2.of_kind("tuning")
        assert len(evs) == 1 and evs[0]["source"] == "direct"
        assert validate_event(evs[0]) == []


class TestSearch:
    def test_domains_for(self):
        d = domains_for(["gap", "cell_target"])
        # registry order, not argument order
        assert list(d) == ["cell_target", "gap"]
        with pytest.raises(KeyError, match="warp_speed"):
            domains_for(["warp_speed"])

    def test_deterministic_sweep(self):
        # fake measurement: gap=256 is the unique optimum, one value
        # crashes — the sweep must record it as failed and move on
        def measure(knobs):
            if knobs.get("gap") == 512:
                raise RuntimeError("boom")
            cost = {None: 10.0, 128: 9.0, 256: 7.0, 384: 8.0}
            return {"status": "ok", "value": cost[knobs.get("gap")]}

        mem = MemorySink()
        out = run_sweep(measure, {"gap": (384, 128, 256, 512)},
                        budget=16, telemetry=Telemetry(sinks=[mem]))
        assert out["baseline"]["value"] == 10.0
        assert out["best"] == {"knobs": {"gap": 256}, "value": 7.0}
        assert out["improved"]
        failed = [r for r in out["history"] if r["status"] == "failed"]
        assert failed and all("boom" in f["error"] for f in failed)
        # every attempt (incl. the dead one) is a valid v5 sweep event
        evs = mem.of_kind("sweep")
        assert len(evs) == out["candidates"] == len(out["history"])
        assert all(validate_event(e) == [] for e in evs)
        assert all(e["v"] == SCHEMA_VERSION for e in evs)
        # identical inputs -> identical trajectory (pure driver)
        again = run_sweep(measure, {"gap": (384, 128, 256, 512)},
                          budget=16)
        assert [r["knobs"] for r in again["history"]] == [
            r["knobs"] for r in out["history"]]

    def test_budget_respected_and_baseline_only(self):
        calls = []

        def measure(knobs):
            calls.append(knobs)
            return {"status": "ok", "value": 1.0}

        out = run_sweep(measure, {"gap": (384, 128, 256, 512)}, budget=2)
        assert out["candidates"] == 2 == len(calls)
        assert out["best"]["knobs"] == {}  # nothing beat the baseline
        assert not out["improved"]

    def test_overflow_never_becomes_incumbent(self):
        def measure(knobs):
            if knobs:
                return {"status": "overflow", "value": 0.001}
            return {"status": "ok", "value": 1.0}

        out = run_sweep(measure, {"gap": (384, 128)}, budget=4)
        assert out["best"]["knobs"] == {}


class TestReplay:
    def test_spec_from_manifest_round_trip(self, tmp_path):
        run = str(tmp_path / "run")
        write_manifest(run, config={"side": 5, "backend": "xla",
                                    "theta": 0.6},
                       particles=125,
                       extra={"case": "sedov", "prop": "std"})
        spec = spec_from_manifest(run)
        assert spec == ReplaySpec(case="sedov", side=5, prop="std",
                                  backend="xla", theta=0.6)
        assert spec.n == 125

    def test_spec_from_manifest_errors(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            spec_from_manifest(str(tmp_path / "nope"))
        run = str(tmp_path / "bad")
        write_manifest(run, config={}, extra={"case": "sedov"})
        with pytest.raises(ValueError, match="case/side"):
            spec_from_manifest(run)
        run2 = str(tmp_path / "snap")
        write_manifest(run2, config={"side": 5},
                       extra={"case": "snapshot.npz"})
        with pytest.raises(ValueError, match="snapshot"):
            spec_from_manifest(run2)

    def test_measure_candidate_from_manifest(self, tmp_path):
        # e2e at tiny N: manifest -> spec -> one measured candidate
        run = str(tmp_path / "run")
        write_manifest(run, config={"side": 5, "backend": "xla"},
                       particles=125, extra={"case": "sedov"})
        spec = spec_from_manifest(run)
        r = measure_candidate(spec, {"gap": 128}, steps=2, warmup=1)
        assert r["status"] == "ok"
        assert r["steps"] >= 2 and r["per_step_s"] > 0
        assert r["value"] == r["per_step_s"]


class TestSchemaV5:
    def test_v5_kinds_registered(self):
        assert KIND_SINCE["sweep"] == 5
        assert KIND_SINCE["tuning"] == 5

    def test_v5_events_validate(self):
        ok = {"v": 5, "seq": 0, "t": 1.0, "kind": "sweep",
              "candidate": 0, "knobs": {}, "status": "ok"}
        assert validate_event(ok) == []
        assert any("missing field 'status'" in p for p in validate_event(
            {"v": 5, "seq": 0, "t": 1.0, "kind": "sweep",
             "candidate": 0, "knobs": {}}))
        tuning = {"v": 5, "seq": 1, "t": 1.0, "kind": "tuning",
                  "source": "table"}
        assert validate_event(tuning) == []

    def test_v5_kind_on_older_version_flagged(self):
        bad = {"v": 4, "seq": 0, "t": 1.0, "kind": "sweep",
               "candidate": 0, "knobs": {}, "status": "ok"}
        assert any("v5-only" in p for p in validate_event(bad))

    def test_older_versions_still_clean(self):
        # one representative kind per older schema version keeps
        # validating (the compatibility promise of SUPPORTED_VERSIONS)
        for v, kind, payload in (
                (1, "step", {"it": 0, "wall_s": 0.1}),
                (2, "exchange", {"it": 0, "shipped_rows": 1, "rows": 1}),
                (3, "physics", {"it": 0, "etot": 1.0}),
                (4, "crash", {"reason": "test"}),
                (5, "sweep", {"candidate": 0, "knobs": {},
                              "status": "ok"})):
            e = {"v": v, "seq": 0, "t": 1.0, "kind": kind, **payload}
            assert validate_event(e) == [], (v, kind)


class TestSchemaV6:
    def test_v6_kind_registered(self):
        assert KIND_SINCE["dt_bins"] == 6

    def test_v6_event_validates(self):
        ok = {"v": 6, "seq": 0, "t": 1.0, "kind": "dt_bins", "it": 3,
              "pop": [100, 50, 25, 337], "updates": 512,
              "updates_full": 4096}
        assert validate_event(ok) == []
        assert any("missing field 'pop'" in p for p in validate_event(
            {"v": 6, "seq": 0, "t": 1.0, "kind": "dt_bins", "it": 3,
             "updates": 1, "updates_full": 1}))

    def test_v6_kind_on_older_version_flagged(self):
        bad = {"v": 5, "seq": 0, "t": 1.0, "kind": "dt_bins", "it": 0,
               "pop": [1], "updates": 1, "updates_full": 1}
        assert any("v6-only" in p for p in validate_event(bad))


class TestSchemaV7:
    def test_v7_keeps_no_kinds(self):
        # v7 adds the optional staged-exchange payload, no new kinds: no
        # KIND_SINCE entry may claim 7 (v8 added the snapshot kind —
        # tests/test_serve.py pins the current version)
        assert SCHEMA_VERSION == 8
        assert 7 not in KIND_SINCE.values()

    def test_v7_staged_exchange_validates(self):
        for stage in ("sph", "gravity"):
            ok = {"v": 7, "seq": 0, "t": 1.0, "kind": "exchange", "it": 1,
                  "shipped_rows": 460, "rows": [460, 460],
                  "stage": stage}
            assert validate_event(ok) == []

    def test_v6_exchange_without_stage_still_validates(self):
        # pre-v7 writers never staged; the field stays optional
        ok = {"v": 6, "seq": 0, "t": 1.0, "kind": "exchange", "it": 1,
              "shipped_rows": 10, "rows": [10]}
        assert validate_event(ok) == []


class TestCli:
    def test_tune_unknown_case_exits_2(self, tmp_path, capsys):
        from sphexa_tpu.tuning.cli import main

        rc = main(["--case", "warpdrive", "--out",
                   str(tmp_path / "out")])
        assert rc == 2

    def test_tune_unknown_knob_exits_2(self, tmp_path):
        from sphexa_tpu.tuning.cli import main

        rc = main(["--case", "sedov", "--side", "5",
                   "--knobs", "warp_speed",
                   "--out", str(tmp_path / "out")])
        assert rc == 2

    def test_telemetry_tuning_no_table_exits_2(self, tmp_path):
        from sphexa_tpu.telemetry.cli import main

        assert main(["tuning", str(tmp_path / "missing.json")]) == 2

    def test_telemetry_tuning_stale_knob_exits_1(self, tmp_path,
                                                 capsys):
        from sphexa_tpu.telemetry.cli import main

        path = tmp_path / "t.json"
        table = upsert_entry(new_table(), _entry({"gap": 128}))
        table["entries"][0]["knobs"] = {"ye_olde_knob": 1}
        path.write_text(json.dumps(table))
        assert main(["tuning", str(path)]) == 1
        assert "stale knob" in capsys.readouterr().out

    def test_telemetry_tuning_coverage_gap_exits_1(self, tmp_path,
                                                   capsys):
        from sphexa_tpu.telemetry.cli import main

        path = tmp_path / "t.json"
        save_table(str(path), upsert_entry(new_table(),
                                           _entry({"gap": 128})))
        assert main(["tuning", str(path)]) == 0
        assert main(["tuning", str(path),
                     "--require", "sedov,1000,1,xla"]) == 0
        assert main(["tuning", str(path),
                     "--require", "noh,1000000,16,pallas"]) == 1

    def test_committed_table_is_valid(self):
        # the repo-root TUNING_TABLE.json must stay registry-clean
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        table = load_table(os.path.join(root, "TUNING_TABLE.json"))
        assert validate_table(table) == []


class TestStaticCostObjective:
    """static-cost:<phase> — the chip-free sweep objective (jaxcost)."""

    def test_candidate_scores_without_running_steps(self):
        from sphexa_tpu.tuning import static_cost_candidate

        spec = ReplaySpec(case="sedov", side=6, prop="std",
                          backend="auto", theta=0.5, devices=None)
        rec = static_cost_candidate(spec, {"target_block": 64},
                                    "density", device="v5e")
        assert rec["status"] == "ok"
        assert rec["objective"] == "static-cost:density"
        assert rec["value"] > 0
        assert rec["value"] == rec["predicted_ms"]
        assert rec["bound"] in ("compute", "memory", "ici")
        assert rec["steps"] == 0          # nothing executed, only traced

    def test_unknown_phase_raises(self):
        from sphexa_tpu.tuning import static_cost_candidate

        spec = ReplaySpec(case="sedov", side=6, prop="std",
                          backend="auto", theta=0.5, devices=None)
        with pytest.raises(ValueError):
            static_cost_candidate(spec, {}, "warpdrive")

    def test_cli_micro_sweep_emits_valid_v5_events(self, tmp_path):
        from sphexa_tpu.tuning.cli import main

        out = tmp_path / "sweep"
        rc = main(["--case", "sedov", "--side", "6",
                   "--knobs", "target_block", "--budget", "2",
                   "--objective", "static-cost:density",
                   "--out", str(out), "--quiet"])
        assert rc == 0
        events = [json.loads(line) for line in
                  (out / "events.jsonl").read_text().splitlines()]
        sweeps = [e for e in events if e.get("kind") == "sweep"]
        assert len(sweeps) == 2
        for e in sweeps:
            assert validate_event(e) == []
            assert e["status"] == "ok"
            assert e["objective"] == "static-cost:density"
            assert e["value"] > 0
