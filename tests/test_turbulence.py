"""Turbulence stirring tests: mode table, OU statistics, Helmholtz
projection, stirring accelerations, and the stirred propagator end to end.
Mirrors the reference's sph/test/turbulence/ coverage.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sphexa_tpu.sph.hydro_turb import (
    compute_phases,
    create_stirring_modes,
    drive_turbulence,
    st_calc_accel,
    turbulence_state_from_fields,
    turbulence_state_to_fields,
    update_noise,
)


@pytest.fixture(scope="module")
def turb():
    return create_stirring_modes(lbox=1.0)


class TestModes:
    def test_mode_band(self, turb):
        cfg, state = turb
        k = np.linalg.norm(np.asarray(state.modes), axis=1)
        twopi = 2 * np.pi
        assert np.all(k >= twopi * (1 - 1e-6))
        assert np.all(k <= 3 * twopi * (1 + 1e-6))
        assert cfg.num_modes == state.modes.shape[0]
        assert state.amplitudes.shape == (cfg.num_modes,)

    def test_mirrored_modes_present(self, turb):
        _, state = turb
        modes = np.asarray(state.modes)
        # for every mode with ky>0 and kz>0, the mirrored ones exist
        m0 = modes[(modes[:, 1] > 0) & (modes[:, 2] > 0)][0]
        for sy, sz in [(1, -1), (-1, 1), (-1, -1)]:
            target = m0 * np.array([1, sy, sz])
            assert np.any(np.all(np.isclose(modes, target), axis=1))

    def test_parabolic_amplitude_peak(self, turb):
        cfg, state = turb
        k = np.linalg.norm(np.asarray(state.modes), axis=1)
        amp = np.asarray(state.amplitudes)
        kc = 0.5 * (2 * np.pi + 6 * np.pi)
        # weighted amplitude peaks at the band center
        raw = amp / (kc / k) ** 1.0  # undo the (kc/k)^(ndim-1)/2 tilt
        assert abs(k[np.argmax(raw)] - kc) < 2 * np.pi


class TestOUProcess:
    def test_stationary_variance(self, turb):
        cfg, state = turb
        # many steps at dt << ts: RMS should hold near cfg.variance
        s = state
        for _ in range(50):
            s = update_noise(s, 0.05 * cfg.decay_time, cfg)
        rms = float(jnp.sqrt(jnp.mean(s.phases**2)))
        assert 0.5 * cfg.variance < rms < 2.0 * cfg.variance

    def test_damping_limit(self, turb):
        cfg, state = turb
        # dt >> ts: the old phases are fully forgotten, new ~ N(0, variance)
        s = update_noise(state, 1000.0 * cfg.decay_time, cfg)
        corr = float(jnp.mean(s.phases * state.phases)) / cfg.variance**2
        assert abs(corr) < 0.1

    def test_key_advances(self, turb):
        cfg, state = turb
        s = update_noise(state, 0.1, cfg)
        assert not np.array_equal(np.asarray(s.key), np.asarray(state.key))


class TestProjection:
    def test_solenoidal_projection_divergence_free(self, turb):
        cfg, state = turb
        import dataclasses

        cfg_sol = dataclasses.replace(cfg, sol_weight=1.0)
        pr, pi = compute_phases(state, cfg_sol)
        # divergence-free: k . P = 0 per mode, both parts
        k = np.asarray(state.modes)
        assert np.abs((k * np.asarray(pr)).sum(axis=1)).max() < 1e-4
        assert np.abs((k * np.asarray(pi)).sum(axis=1)).max() < 1e-4

    def test_compressive_projection_parallel(self, turb):
        cfg, state = turb
        import dataclasses

        cfg_comp = dataclasses.replace(cfg, sol_weight=0.0)
        pr, pi = compute_phases(state, cfg_comp)
        # fully compressive: P is parallel to k -> cross product vanishes
        k = np.asarray(state.modes)
        cross = np.cross(k, np.asarray(pr))
        knorm = np.linalg.norm(k, axis=1) * (np.linalg.norm(np.asarray(pr), axis=1) + 1e-30)
        assert (np.linalg.norm(cross, axis=1) / knorm).max() < 1e-3


class TestStirring:
    def test_accel_shape_and_finiteness(self, turb):
        cfg, state = turb
        rng = np.random.default_rng(0)
        n = 500
        x, y, z = [jnp.asarray(rng.uniform(-0.5, 0.5, n)) for _ in range(3)]
        pr, pi = compute_phases(state, cfg)
        ax, ay, az = st_calc_accel(x, y, z, state, cfg, pr, pi)
        assert ax.shape == (n,)
        assert np.all(np.isfinite(ax)) and np.all(np.isfinite(az))
        # nonzero forcing
        assert float(jnp.abs(ax).max()) > 0

    def test_drive_advances_state(self, turb):
        cfg, state = turb
        n = 100
        zero = jnp.zeros(n)
        x = jnp.linspace(-0.5, 0.5, n)
        ax, ay, az, new_state = drive_turbulence(
            x, zero, zero, zero, zero, zero, jnp.float32(1e-3), state, cfg
        )
        assert not np.array_equal(np.asarray(new_state.phases), np.asarray(state.phases))


class TestCheckpoint:
    def test_round_trip(self, turb):
        cfg, state = turb
        fields = turbulence_state_to_fields(state, cfg)
        back, back_cfg = turbulence_state_from_fields(fields)
        np.testing.assert_array_equal(np.asarray(back.modes), np.asarray(state.modes))
        np.testing.assert_array_equal(np.asarray(back.phases), np.asarray(state.phases))
        np.testing.assert_array_equal(np.asarray(back.key), np.asarray(state.key))
        # the forcing config resumes identically (not rebuilt defaults)
        assert back_cfg.variance == pytest.approx(cfg.variance)
        assert back_cfg.decay_time == pytest.approx(cfg.decay_time)
        assert back_cfg.sol_weight == cfg.sol_weight
        assert back_cfg.num_modes == cfg.num_modes


class TestTurbVePropagator:
    def test_box_gains_kinetic_energy(self):
        from sphexa_tpu.init import init_turbulence
        from sphexa_tpu.observables import conserved_quantities
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_turbulence(10)
        sim = Simulation(state, box, const, prop="turb-ve", block=256)
        e0 = conserved_quantities(sim.state, const)
        for _ in range(5):
            d = sim.step()
        e1 = conserved_quantities(sim.state, const)
        # stirring injects kinetic energy into the initially static box
        assert float(e1["ecin"]) > float(e0["ecin"])
        assert float(e1["ecin"]) > 0
        for f in ("x", "vx", "temp", "h"):
            assert np.all(np.isfinite(np.asarray(getattr(sim.state, f)))), f


def test_spect_form_2_power_law_modes():
    """stSpectForm=2: power-law random-angle shell sampling
    (create_modes.hpp:179-238)."""
    from sphexa_tpu.sph.hydro_turb import create_stirring_modes

    cfg, st = create_stirring_modes(
        lbox=1.0, spect_form=2, seed=251299,
        power_law_exp=5.0 / 3.0, angles_exp=2.0,
    )
    m = np.asarray(st.modes)
    a = np.asarray(st.amplitudes)
    assert m.shape[0] > 10
    k = np.sqrt((m**2).sum(axis=1))
    twopi = 2.0 * np.pi
    assert (k >= twopi * (1 - 1e-6)).all() and (k <= 3 * twopi * (1 + 1e-6)).all()
    assert (a > 0).all() and np.isfinite(a).all()
    # amplitudes follow the power law trend modulo the angle correction:
    # higher-k shells are sampled, none degenerate
    assert np.unique(np.round(k / twopi).astype(int)).size >= 2


def test_spect_form_2_runs_a_step():
    from sphexa_tpu.init import init_turbulence
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_turbulence(8)
    sim = Simulation(state, box, const, prop="turb-ve",
                     turb_settings={"stSpectForm": 2}, block=512)
    d = sim.step()
    assert np.isfinite(np.asarray(sim.state.vx)).all()
