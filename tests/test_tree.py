"""Cornerstone tree invariant tests, mirroring the reference's
domain/test/unit/tree/csarray.cpp and unit/domain/domaindecomp.cpp.
"""

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc import Box, BoundaryType, compute_sfc_keys
from sphexa_tpu.tree import (
    compute_node_counts,
    compute_octree,
    make_root_tree,
    make_uniform_tree,
    make_sfc_assignment,
    node_levels,
    uniform_bins,
)

KEY_RANGE = 1 << (3 * KEY_BITS)


def random_keys(rng, n):
    return np.sort(rng.integers(0, KEY_RANGE, n).astype(np.uint64))


def check_invariants(tree, keys, bucket_size):
    tree = np.asarray(tree)
    assert tree[0] == 0 and tree[-1] == KEY_RANGE
    assert np.all(np.diff(tree.astype(np.int64)) > 0)
    spans = np.diff(tree)
    # power-of-8 spans aligned to their own size (cornerstone invariant)
    assert np.all((spans & (spans - 1)) == 0)
    assert np.all(np.log2(spans.astype(float)) % 3 == 0)
    assert np.all(tree[:-1] % spans == 0)
    counts = compute_node_counts(tree, keys)
    assert counts.sum() == len(keys)
    # converged: no leaf over-full unless at max depth
    levels = node_levels(tree)
    assert np.all((counts <= bucket_size) | (levels == KEY_BITS))


class TestCsarray:
    def test_root_and_uniform(self):
        assert list(make_root_tree()) == [0, KEY_RANGE]
        t = make_uniform_tree(2)
        assert len(t) == 65
        assert np.all(node_levels(t) == 2)

    def test_counts(self, rng):
        keys = random_keys(rng, 1000)
        tree = make_uniform_tree(1)
        counts = compute_node_counts(tree, keys)
        assert counts.sum() == 1000
        # roughly uniform distribution over 8 octants
        assert counts.min() > 50

    def test_build_random(self, rng):
        keys = random_keys(rng, 20000)
        tree, counts = compute_octree(keys, bucket_size=64)
        check_invariants(tree, keys, 64)

    def test_build_clustered(self, rng):
        # strongly clustered keys exercise deep refinement + coarse siblings
        a = rng.integers(0, KEY_RANGE // 1000, 5000)
        b = rng.integers(KEY_RANGE - 500, KEY_RANGE, 5000)
        keys = np.sort(np.concatenate([a, b]).astype(np.uint64))
        tree, counts = compute_octree(keys, bucket_size=32)
        check_invariants(tree, keys, 32)

    def test_rebuild_is_stable(self, rng):
        keys = random_keys(rng, 5000)
        tree, _ = compute_octree(keys, bucket_size=64)
        tree2, _ = compute_octree(keys, bucket_size=64)
        np.testing.assert_array_equal(tree, tree2)


class TestDecomposition:
    def test_uniform_bins_balance(self, rng):
        keys = random_keys(rng, 50000)
        tree, counts = compute_octree(keys, bucket_size=64)
        bins = uniform_bins(tree, counts, 8)
        assert len(bins) == 9
        assert bins[0] == 0 and bins[-1] == KEY_RANGE
        edges = np.searchsorted(keys, bins)
        per_rank = np.diff(edges)
        assert per_rank.sum() == len(keys)
        # equal-count split within bucket granularity
        assert per_rank.max() - per_rank.min() < 3 * 64

    def test_assignment_covers_all(self, rng):
        box = Box.create(-1, 1, boundary=BoundaryType.periodic)
        pos = [jnp.asarray(rng.uniform(-1, 1, 4096), jnp.float32) for _ in range(3)]
        keys = np.sort(np.asarray(compute_sfc_keys(*pos, box)))
        bins, per_rank = make_sfc_assignment(keys, 4)
        assert per_rank.sum() == 4096
        assert per_rank.min() > 0


class TestContinuumTree:
    """Octree from an analytic density (cstone/tree/continuum.hpp)."""

    def test_uniform_density_balanced(self):
        from sphexa_tpu.tree.continuum import compute_continuum_octree

        tree, counts = compute_continuum_octree(
            lambda x, y, z: np.ones_like(x),
            (0.0, 0.0, 0.0), (1.0, 1.0, 1.0),
            n_total=8**4, bucket_size=64,
        )
        from sphexa_tpu.tree.csarray import node_levels

        levels = node_levels(tree)
        # uniform density -> uniform refinement, all counts <= bucket
        assert levels.min() == levels.max()
        assert counts.max() <= 64

    def test_peaked_density_refines_centrally(self):
        from sphexa_tpu.tree.continuum import compute_continuum_octree
        from sphexa_tpu.tree.csarray import node_levels

        def rho(x, y, z):
            r2 = (x - 0.5) ** 2 + (y - 0.5) ** 2 + (z - 0.5) ** 2
            return np.exp(-r2 / 0.01)

        tree, counts = compute_continuum_octree(
            rho, (0.0, 0.0, 0.0), (1.0, 1.0, 1.0),
            n_total=100000, bucket_size=64,
        )
        levels = node_levels(tree)
        # the density peak demands deeper leaves than the empty corners
        assert levels.max() - levels.min() >= 2
        assert counts.max() <= 64 * 2  # rounding slack


class TestInjectKeys:
    """Mandatory-resolution key injection (cstone/focus/inject.hpp)."""

    def test_injected_keys_become_boundaries(self):
        from sphexa_tpu.tree.csarray import KEY_RANGE, make_uniform_tree, node_levels
        from sphexa_tpu.tree.inject import inject_keys

        tree = make_uniform_tree(1)  # 8 leaves
        want = np.array([KEY_RANGE // 64 * 3, KEY_RANGE // 512 * 100],
                        dtype=np.uint64)
        out = inject_keys(tree, want)
        assert set(want.tolist()) <= set(out.tolist())
        # invariant: every leaf spans an aligned power-of-8 range
        spans = np.diff(out.astype(np.uint64))
        levels = node_levels(out)
        assert (out[:-1] % spans == 0).all()
        # spans must be exact powers of 8
        l = np.log2(spans.astype(np.float64)) / 3.0
        assert np.allclose(l, np.round(l))

    def test_existing_boundary_noop(self):
        from sphexa_tpu.tree.csarray import make_uniform_tree
        from sphexa_tpu.tree.inject import inject_keys

        tree = make_uniform_tree(2)
        out = inject_keys(tree, tree[3:5])
        np.testing.assert_array_equal(out, tree)
