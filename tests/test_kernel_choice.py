"""Kernel-family selection (the reference's SphKernelType enum,
sph_kernel_tables.hpp:122-160, plus the Wendland C6 non-sinc family)."""

import dataclasses

import numpy as np
import pytest

from sphexa_tpu.sph.kernels import (
    KERNEL_CHOICES,
    _kernel_samples,
    kernel_dterh_coeffs,
    kernel_norm_3d,
    kernel_poly_coeffs,
    sinc_kernel_u,
    sinc_poly_eval,
)


@pytest.mark.parametrize("kind", KERNEL_CHOICES)
def test_poly_fit_accuracy(kind):
    """The Horner fit tracks the exact kernel to f32-comparable error."""
    v = np.linspace(0.0, 2.0, 2001)
    exact = _kernel_samples(v, 6.0, kind)
    approx = np.asarray(sinc_kernel_u(np.asarray(v * v, np.float32), 6.0, kind))
    assert np.abs(approx - exact).max() < 5e-6, kind


@pytest.mark.parametrize("kind", KERNEL_CHOICES)
def test_normalization(kind):
    """K makes the 3D kernel integral unity."""
    K = kernel_norm_3d(6.0, kind)
    r = np.linspace(0.0, 2.0, 40001)
    w = _kernel_samples(r, 6.0, kind)
    integral = np.trapezoid(4.0 * np.pi * r**2 * K * w, r)
    assert abs(integral - 1.0) < 1e-5, kind


@pytest.mark.parametrize("kind", KERNEL_CHOICES)
def test_dterh_consistency(kind):
    """dterh = -(3W + v dW/dv) via finite differences of the W fit."""
    v = np.linspace(0.05, 1.95, 500)
    u = v * v
    eps = 1e-3
    wc = kernel_poly_coeffs(6.0, kind)
    w = np.asarray(sinc_poly_eval(u, wc), np.float64)
    wp = np.asarray(sinc_poly_eval((v + eps) ** 2, wc), np.float64)
    wm = np.asarray(sinc_poly_eval((v - eps) ** 2, wc), np.float64)
    dwdv = (wp - wm) / (2 * eps)
    expect = -(3.0 * w + v * dwdv)
    dc = kernel_dterh_coeffs(6.0, kind)
    s = np.clip(u * 0.5 - 1.0, -1.0, 1.0)
    got = np.full_like(s, dc[-1])
    for c in dc[-2::-1]:
        got = got * s + c
    assert np.abs(got - expect).max() < 2e-3, kind


@pytest.mark.parametrize("kind", KERNEL_CHOICES)
def test_density_unity_on_lattice(kind):
    """A uniform lattice at unit density must sum rho ~= 1 for EVERY
    kernel family (normalization + pipeline consistency end-to-end)."""
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.propagator import step_hydro_std
    from sphexa_tpu.simulation import make_propagator_config
    from sphexa_tpu.sph.kernels import kernel_norm_3d as knorm

    state, box, const = init_sedov(12)
    const = dataclasses.replace(
        const, kernel_choice=kind, kernel_norm=knorm(const.sinc_index, kind)
    )
    cfg = make_propagator_config(state, box, const, block=512)
    _, _, diag = step_hydro_std(state, box, cfg)
    assert 0.8 < float(diag["rho_max"]) < 1.3, kind


def test_cli_kernel_flag(tmp_path):
    from sphexa_tpu.app.main import main

    rc = main(["--init", "sedov", "-n", "10", "-s", "2", "--quiet",
               "--kernel", "wendland-c6", "-o", str(tmp_path)])
    assert rc == 0

    rc = main(["--init", "sedov", "-n", "8", "-s", "1", "--quiet",
               "--kernel", "nope", "-o", str(tmp_path)])
    assert rc == 2


def test_kernel_choice_survives_restart(tmp_path):
    """A checkpointed non-default kernel family must come back from the
    snapshot (silent reversion to sinc would be a physics discontinuity
    at the restart boundary)."""
    import dataclasses

    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.io.snapshot import read_snapshot_full, write_snapshot
    from sphexa_tpu.sph.kernels import kernel_norm_3d

    state, box, const = init_sedov(8)
    const = dataclasses.replace(
        const, kernel_choice="wendland-c6",
        kernel_norm=kernel_norm_3d(const.sinc_index, "wendland-c6"),
    )
    path = str(tmp_path / "dump.h5")
    write_snapshot(path, state, box, const, iteration=3)
    _, _, const2, _, _ = read_snapshot_full(path, -1)
    assert const2.kernel_choice == "wendland-c6"
    np.testing.assert_allclose(const2.K, const.K, rtol=1e-6)
