"""Generalized volume-element (VE) pipeline regression.

Mirrors the role of the reference's `sphexa --init sedov --prop ve` CI run
and the sph/test/ve.cpp kernel-consistency checks: VE and std pipelines
must agree on a uniform gas, and the VE Sedov run must conserve energy.
"""

import dataclasses

import numpy as np
import pytest

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import conserved_quantities
from sphexa_tpu.simulation import Simulation


@pytest.fixture(scope="module")
def ve_run():
    state, box, const = init_sedov(20)
    sim = Simulation(state, box, const, prop="ve", block=512)
    e0 = conserved_quantities(sim.state, const)
    diags = [sim.step() for _ in range(8)]
    e1 = conserved_quantities(sim.state, const)
    return sim, const, e0, e1, diags


@pytest.mark.slow
class TestVeE2E:
    def test_runs_without_nans(self, ve_run):
        sim, *_ = ve_run
        for f in ("x", "vx", "temp", "h", "du", "alpha"):
            assert np.all(np.isfinite(np.asarray(getattr(sim.state, f)))), f

    def test_energy_conservation(self, ve_run):
        _, _, e0, e1, _ = ve_run
        drift = abs(float(e1["etot"]) - float(e0["etot"])) / abs(float(e0["etot"]))
        assert drift < 1e-3, f"energy drift {drift}"

    def test_momentum_stays_zero(self, ve_run):
        _, _, _, e1, _ = ve_run
        assert float(e1["linmom"]) < 1e-4

    def test_alpha_switch_activates_at_shock(self, ve_run):
        # the blast center is compressing: AV alpha must have grown above
        # the floor somewhere (full ramp to alphamax takes ~100s of steps)
        sim, const, *_ = ve_run
        alpha = np.asarray(sim.state.alpha)
        assert alpha.max() > 1.2 * const.alphamin
        assert alpha.min() >= const.alphamin - 1e-6
        assert alpha.max() <= const.alphamax + 1e-6

    def test_blast_expands_outward(self, ve_run):
        sim, *_ = ve_run
        st = sim.state
        r = np.sqrt(np.asarray(st.x) ** 2 + np.asarray(st.y) ** 2 + np.asarray(st.z) ** 2)
        vr = (np.asarray(st.vx) * np.asarray(st.x) + np.asarray(st.vy) * np.asarray(st.y)
              + np.asarray(st.vz) * np.asarray(st.z)) / np.maximum(r, 1e-9)
        assert vr[r < 0.15].mean() > 0


def test_ve_avclean_runs():
    """avClean variant (momentum_energy_kern.hpp avRvCorrection) executes
    and stays finite."""
    state, box, const = init_sedov(16)
    sim = Simulation(state, box, const, prop="ve", block=512, av_clean=True)
    for _ in range(3):
        d = sim.step()
    assert np.isfinite(d["dt"]) and d["dt"] > 0
    assert np.all(np.isfinite(np.asarray(sim.state.vx)))


@pytest.mark.slow
def test_ve_matches_std_on_uniform_gas():
    """On a uniform-density periodic gas with no perturbation, VE and std
    formulations reduce to the same physics: densities agree to O(1e-3)
    and accelerations are ~0 in both."""
    from sphexa_tpu.init.sedov import init_sedov as _init

    state, box, const = _init(12, {"ener0": 0.0, "u0": 1.0})
    sim_std = Simulation(state, box, const, prop="std", block=512)
    sim_ve = Simulation(
        dataclasses.replace(state), box, const, prop="ve", block=512
    )
    d_std = sim_std.step()
    d_ve = sim_ve.step()
    assert abs(d_std["rho_max"] - d_ve["rho_max"]) / d_std["rho_max"] < 1e-2
    # uniform gas: velocities stay tiny relative to sound speed
    c_sound = float(np.sqrt(const.cv * np.asarray(state.temp).max()
                            * (const.gamma - 1.0)))
    for sim in (sim_std, sim_ve):
        vmax = float(np.abs(np.asarray(sim.state.vx)).max())
        assert vmax < 1e-2 * c_sound
