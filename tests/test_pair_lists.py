"""Persistent-list engine (sph/pair_lists.py + the list-walk engine in
sph/pallas_pairs.py) equivalence vs the streaming engine, INTERPRET mode.

The list-walk path must reproduce the streaming engine's pair SET exactly
(the compaction only removes lanes outside the skin-inflated group bbox,
a superset of every 2h_i sphere), so results match up to f32 summation
order. Drift robustness: after particles move by less than skin/2 the
STALE lists must still produce results matching a fresh streaming pass
on the moved positions — the Verlet-skin contract the steady steps rely
on (cstone rebuilds per step, find_neighbors.cuh; lists amortize that)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov, init_noh
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import pallas_pairs as pp
from sphexa_tpu.sph.pair_lists import (
    build_pair_lists,
    estimate_slot_cap,
    lists_valid,
)


def _setup(init, side):
    state, box, const = init(side)
    cfg = make_propagator_config(state, box, const, block=4096,
                                 backend="pallas")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    return ss, keys, box, const, cfg.nbr


# noh 16^3: open boundaries, real (non-fold) shift path.
# sedov 30^3: periodic with a real grid (fold mode would reject lists).
CASES = [(init_noh, 16), (init_sedov, 30)]


@pytest.fixture(scope="module", params=CASES, ids=["noh", "sedov"])
def case(request):
    init, side = request.param
    return _setup(init, side)


@pytest.fixture(scope="module")
def built(case):
    ss, keys, box, const, nbr = case
    skin = 0.2 * float(jnp.max(ss.h))
    scap = estimate_slot_cap(ss.x, ss.y, ss.z, ss.h, keys, box, nbr, skin)
    lists = build_pair_lists(
        ss.x, ss.y, ss.z, ss.h, keys, box, nbr, skin, scap, interpret=True
    )
    return lists, skin, scap


def test_build_structure(case, built):
    ss, keys, box, const, nbr = case
    lists, skin, scap = built
    assert int(lists.overflow) == 0
    # the compacted lane total must be bounded by the streamed lanes and
    # must cover at least every true neighbor pair
    cnt = np.asarray(lists.cnt)
    assert (cnt >= 0).all() and (cnt <= 128).all()
    assert bool(lists_valid(ss.x, ss.y, ss.z, ss.h, lists))
    # staging bookkeeping is self-consistent
    csum = np.cumsum(cnt, axis=1)
    np.testing.assert_array_equal(np.asarray(lists.tail), csum[:, -1] % 128)


def test_density_lists_match_streaming(case, built):
    ss, keys, box, const, nbr = case
    lists, _, _ = built
    rho0, nc0, _ = pp.pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, keys, box, const, nbr, interpret=True
    )
    rho1, nc1, _ = pp.pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, None, box, const, nbr,
        interpret=True, lists=lists,
    )
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0),
                               rtol=2e-6)


def test_momentum_std_lists_match_streaming(case, built):
    ss, keys, box, const, nbr = case
    lists, _, _ = built
    x, y, z, h, m = ss.x, ss.y, ss.z, ss.h, ss.m
    rho, _, _ = pp.pallas_density(x, y, z, h, m, keys, box, const, nbr,
                                  interpret=True)
    from sphexa_tpu.sph.hydro_std import compute_eos_std

    p, c = compute_eos_std(ss.temp, rho, const)
    cs, _ = pp.pallas_iad(x, y, z, h, m / rho, keys, box, const, nbr,
                          interpret=True)
    args = (x, y, z, ss.vx, ss.vy, ss.vz, h, m, rho, p, c, *cs)
    ax0, ay0, az0, du0, dt0, _ = pp.pallas_momentum_energy_std(
        *args, keys, box, const, nbr, interpret=True
    )
    cs1, _ = pp.pallas_iad(x, y, z, h, m / rho, None, box, const, nbr,
                           interpret=True, lists=lists)
    # off-diagonal components are ~0 on near-uniform lattices (pure
    # cancellation noise), so the atol scales with the TENSOR magnitude
    csc = max(float(np.abs(np.asarray(b)).max()) for b in cs)
    for a, b in zip(cs1, cs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6 * csc)
    ax1, ay1, az1, du1, dt1, _ = pp.pallas_momentum_energy_std(
        *args, None, box, const, nbr, interpret=True, lists=lists
    )
    scale = float(jnp.max(jnp.abs(ax0)))
    for a, b in zip((ax1, ay1, az1), (ax0, ay0, az0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5 * scale)
    np.testing.assert_allclose(np.asarray(du1), np.asarray(du0), rtol=1e-4,
                               atol=1e-6 * float(jnp.max(jnp.abs(du0))))
    np.testing.assert_allclose(float(dt1), float(dt0), rtol=1e-5)


def test_momentum_ve_lists_match_streaming(case, built):
    ss, keys, box, const, nbr = case
    lists, _, _ = built
    x, y, z, h, m = ss.x, ss.y, ss.z, ss.h, ss.m
    xm, nc, _ = pp.pallas_xmass(x, y, z, h, m, keys, box, const, nbr,
                                interpret=True)
    (kx, gradh), _ = pp.pallas_ve_def_gradh(
        x, y, z, h, m, xm, keys, box, const, nbr, interpret=True
    )
    from sphexa_tpu.sph.hydro_ve import compute_eos_ve

    prho, c, rho, p = compute_eos_ve(ss.temp, m, kx, xm, gradh, const)
    cs, _ = pp.pallas_iad(x, y, z, h, xm / kx, keys, box, const, nbr,
                          interpret=True)
    alpha = ss.alpha
    args = (x, y, z, ss.vx, ss.vy, ss.vz, h, m, prho, c, kx, xm, alpha,
            *cs)
    ax0, ay0, az0, du0, dt0, _ = pp.pallas_momentum_energy_ve(
        *args, keys, box, const, nbr, nc=nc, interpret=True
    )
    # list path for xmass/gradh/divv/av too (full VE op coverage)
    xm1, nc1, _ = pp.pallas_xmass(x, y, z, h, m, None, box, const, nbr,
                                  interpret=True, lists=lists)
    np.testing.assert_allclose(np.asarray(xm1), np.asarray(xm), rtol=2e-6)
    (kx1, gradh1), _ = pp.pallas_ve_def_gradh(
        x, y, z, h, m, xm, None, box, const, nbr, interpret=True,
        lists=lists,
    )
    np.testing.assert_allclose(np.asarray(kx1), np.asarray(kx), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(gradh1), np.asarray(gradh),
                               rtol=2e-4, atol=2e-6)
    dv0, _ = pp.pallas_iad_divv_curlv(
        x, y, z, ss.vx, ss.vy, ss.vz, h, kx, xm, *cs, keys, box, const,
        nbr, interpret=True,
    )
    dv1, _ = pp.pallas_iad_divv_curlv(
        x, y, z, ss.vx, ss.vy, ss.vz, h, kx, xm, *cs, None, box, const,
        nbr, interpret=True, lists=lists,
    )
    sc = float(jnp.max(jnp.abs(dv0[0])))
    np.testing.assert_allclose(np.asarray(dv1[0]), np.asarray(dv0[0]),
                               rtol=1e-4, atol=1e-5 * sc)
    a0, _ = pp.pallas_av_switches(
        x, y, z, ss.vx, ss.vy, ss.vz, h, c, kx, xm, dv0[0], alpha, *cs,
        keys, box, ss.min_dt, const, nbr, interpret=True,
    )
    a1, _ = pp.pallas_av_switches(
        x, y, z, ss.vx, ss.vy, ss.vz, h, c, kx, xm, dv0[0], alpha, *cs,
        None, box, ss.min_dt, const, nbr, interpret=True, lists=lists,
    )
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), rtol=1e-4,
                               atol=1e-6)
    ax1, ay1, az1, du1, dt1, _ = pp.pallas_momentum_energy_ve(
        *args, None, box, const, nbr, nc=nc, interpret=True, lists=lists
    )
    scale = float(jnp.max(jnp.abs(ax0)))
    for a, b in zip((ax1, ay1, az1), (ax0, ay0, az0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5 * scale)
    np.testing.assert_allclose(np.asarray(du1), np.asarray(du0), rtol=1e-4,
                               atol=1e-6 * float(jnp.max(jnp.abs(du0))))


def test_stale_lists_cover_drifted_positions(case, built):
    """Verlet contract: after drift < skin/2 the STALE lists still yield
    the same density as a FRESH streaming pass on the moved positions."""
    ss, keys, box, const, nbr = case
    lists, skin, _ = built
    rng = np.random.RandomState(3)
    amp = 0.45 * skin / np.sqrt(3.0)
    dx = jnp.asarray(rng.uniform(-amp, amp, ss.n), jnp.float32)
    dy = jnp.asarray(rng.uniform(-amp, amp, ss.n), jnp.float32)
    dz = jnp.asarray(rng.uniform(-amp, amp, ss.n), jnp.float32)
    x2, y2, z2 = ss.x + dx, ss.y + dy, ss.z + dz
    assert bool(lists_valid(x2, y2, z2, ss.h, lists))

    # fresh streaming pass: new sort + ranges on the moved positions
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    keys2 = compute_sfc_keys(x2, y2, z2, box, curve="hilbert")
    order = jnp.argsort(keys2)
    rho0, nc0, _ = pp.pallas_density(
        x2[order], y2[order], z2[order], ss.h[order], ss.m[order],
        keys2[order], box, const, nbr, interpret=True,
    )
    inv = jnp.argsort(order)
    rho0, nc0 = rho0[inv], nc0[inv]

    # stale lists on the frozen build order
    rho1, nc1, _ = pp.pallas_density(
        x2, y2, z2, ss.h, ss.m, None, box, const, nbr,
        interpret=True, lists=lists,
    )
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0),
                               rtol=2e-5)


def test_validity_detects_excess_drift(case, built):
    ss, keys, box, const, nbr = case
    lists, skin, _ = built
    x2 = ss.x.at[0].add(0.6 * skin)
    assert not bool(lists_valid(x2, ss.y, ss.z, ss.h, lists))
    h2 = ss.h.at[0].mul(1.0 + skin)  # h growth alone must also trip it
    assert not bool(lists_valid(ss.x, ss.y, ss.z, h2 + 0.51 * skin, lists))


def _run_sim(use_lists: bool, steps: int, check_every: int = 1):
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_noh(14)
    sim = Simulation(state, box, const, prop="std", block=4096,
                     backend="pallas", use_lists=use_lists,
                     check_every=check_every)
    diags = [sim.step() for _ in range(steps)]
    sim.flush()
    return sim, diags


def test_simulation_list_mode_matches_streaming():
    """Full Simulation in list mode vs per-step streaming: identical
    physics trajectory (physical quantities match after re-ordering; the
    list mode freezes the sort order between rebuilds)."""
    sim0, _ = _run_sim(False, 4)
    sim1, d1 = _run_sim(True, 4)
    assert sim1._use_lists and sim1._lists is not None
    assert any("list_slack" in d for d in d1)
    s0, s1 = sim0.state, sim1.state
    np.testing.assert_allclose(float(s0.ttot), float(s1.ttot), rtol=1e-6)
    # order-insensitive per-particle comparison: sort both by position
    for a, b, tol in ((s0.x, s1.x, 2e-6), (s0.temp, s1.temp, 1e-4),
                      (s0.vx, s1.vx, 1e-4)):
        np.testing.assert_allclose(np.sort(np.asarray(a)),
                                   np.sort(np.asarray(b)), rtol=tol,
                                   atol=1e-7)


def test_simulation_list_rebuild_on_expiry():
    """Drive enough steps that drift eats the skin: the driver must
    rebuild (proactively or by discard) and keep stepping correctly."""
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_noh(14)
    # a tiny skin forces frequent expiry, exercising both the proactive
    # and the discard-and-replay recovery paths
    sim = Simulation(state, box, const, prop="std", block=4096,
                     backend="pallas", use_lists=True, check_every=3,
                     list_skin_rel=0.05)
    rebuilds = 0
    orig = sim._rebuild_lists

    def counting():
        nonlocal rebuilds
        rebuilds += 1
        orig()

    sim._rebuild_lists = counting
    diags = [sim.step() for _ in range(12)]
    sim.flush()
    assert sim._lists is not None
    slacks = [d.get("list_slack") for d in diags if "list_slack" in d]
    assert slacks, "no list diagnostics surfaced"
    # noh piston flow drifts ~0.2 h_min/step: a 0.05*2h skin cannot
    # survive 12 steps — the rebuild machinery must actually have fired
    # beyond the initial build
    assert rebuilds >= 2, f"expected expiry rebuilds, got {rebuilds}"
    # and the run stayed physical
    assert np.isfinite(float(sim.state.ttot))
    assert float(sim.state.ttot) > 0


def test_slot_cap_overflow_sentinel(case):
    ss, keys, box, const, nbr = case
    skin = 0.2 * float(jnp.max(ss.h))
    lists = build_pair_lists(
        ss.x, ss.y, ss.z, ss.h, keys, box, nbr, skin, slot_cap=2,
        interpret=True,
    )
    assert int(lists.overflow) == 1
