"""In-situ viz hook (the Ascent/Catalyst adaptor role, ascent_adaptor.h)."""

import numpy as np
import pytest

from sphexa_tpu.init import init_sedov
from sphexa_tpu.viz import InsituViz, _png_bytes, render_field


def test_png_encoder_valid_signature():
    img = np.zeros((4, 4, 3), np.uint8)
    data = _png_bytes(img)
    assert data[:8] == b"\x89PNG\r\n\x1a\n"
    assert b"IHDR" in data and b"IDAT" in data and data.endswith(
        b"IEND" + (0xAE426082).to_bytes(4, "big")
    )


def test_render_field_shape_and_range():
    rng = np.random.default_rng(0)
    x, y = rng.uniform(0, 1, 1000), rng.uniform(0, 1, 1000)
    img = render_field(x, y, np.ones(1000), (0, 1, 0, 1), resolution=64)
    assert img.shape == (64, 64, 3) and img.dtype == np.uint8


def test_adaptor_writes_frames(tmp_path):
    state, box, const = init_sedov(8)
    viz = InsituViz(str(tmp_path), mode="projection", every=2, resolution=32)
    viz.init()
    paths = [viz.execute(state, box, it) for it in range(4)]
    assert paths[0] is not None and paths[1] is None  # every=2
    assert viz.finalize() == 2
    data = open(paths[0], "rb").read()
    assert data[:8] == b"\x89PNG\r\n\x1a\n"


def test_adaptor_stub_writer(tmp_path):
    """The writer seam lets a test (or an external in-situ sink) capture
    frames without touching the filesystem — the stub the VERDICT asks
    to test against."""
    captured = {}
    state, box, const = init_sedov(8)
    viz = InsituViz(str(tmp_path), mode="slice", every=1, resolution=16,
                    writer=lambda path, data: captured.setdefault(path, data))
    viz.init()
    p = viz.execute(state, box, 0)
    assert p in captured and captured[p][:8] == b"\x89PNG\r\n\x1a\n"


def test_bad_mode_rejected(tmp_path):
    with pytest.raises(ValueError):
        InsituViz(str(tmp_path), mode="volume")


def test_cli_insitu_flag(tmp_path):
    import glob

    from sphexa_tpu.app.main import main

    rc = main(["--init", "sedov", "-n", "8", "-s", "2", "--quiet",
               "--insitu", "projection", "-o", str(tmp_path)])
    assert rc == 0
    assert len(glob.glob(str(tmp_path / "insitu_projection_*.png"))) == 2
