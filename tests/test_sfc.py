"""SFC codec tests, mirroring the reference's unit/sfc/{morton,hilbert}.cpp:
round-trip bijectivity, prefix (hierarchy) property, locality, and key order
consistency with float coordinates.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc import (
    Box,
    BoundaryType,
    apply_pbc,
    compute_sfc_keys,
    hilbert_decode,
    hilbert_encode,
    make_global_box,
    morton_decode,
    morton_encode,
    put_in_box,
)


def random_coords(rng, n, bits=KEY_BITS):
    return [jnp.asarray(rng.integers(0, 1 << bits, n, dtype=np.uint32)) for _ in range(3)]


class TestMorton:
    def test_known_values(self):
        # x is the most significant dimension: (1,0,0) at the deepest level -> 4
        assert int(morton_encode(jnp.uint32(1), jnp.uint32(0), jnp.uint32(0))) == 4
        assert int(morton_encode(jnp.uint32(0), jnp.uint32(1), jnp.uint32(0))) == 2
        assert int(morton_encode(jnp.uint32(0), jnp.uint32(0), jnp.uint32(1))) == 1
        # top-level octant: high bit of each coordinate -> key octant digit
        top = 1 << (KEY_BITS - 1)
        key = morton_encode(jnp.uint32(top), jnp.uint32(top), jnp.uint32(top))
        assert int(key) >> (3 * (KEY_BITS - 1)) == 7

    def test_roundtrip(self, rng):
        ix, iy, iz = random_coords(rng, 1000)
        jx, jy, jz = morton_decode(morton_encode(ix, iy, iz))
        np.testing.assert_array_equal(np.asarray(jx), np.asarray(ix))
        np.testing.assert_array_equal(np.asarray(jy), np.asarray(iy))
        np.testing.assert_array_equal(np.asarray(jz), np.asarray(iz))

    def test_prefix_property(self, rng):
        ix, iy, iz = random_coords(rng, 500)
        full = morton_encode(ix, iy, iz)
        for level in (1, 3, 7):
            shift = KEY_BITS - level
            coarse = morton_encode(ix >> shift, iy >> shift, iz >> shift, bits=level)
            np.testing.assert_array_equal(
                np.asarray(full >> jnp.uint32(3 * shift)), np.asarray(coarse)
            )


class TestHilbert:
    def test_roundtrip(self, rng):
        ix, iy, iz = random_coords(rng, 1000)
        jx, jy, jz = hilbert_decode(hilbert_encode(ix, iy, iz))
        np.testing.assert_array_equal(np.asarray(jx), np.asarray(ix))
        np.testing.assert_array_equal(np.asarray(jy), np.asarray(iy))
        np.testing.assert_array_equal(np.asarray(jz), np.asarray(iz))

    def test_bijective_small(self):
        # exhaustive check at 2 levels: all 64 cells map to 64 distinct keys
        g = np.arange(4, dtype=np.uint32)
        ix, iy, iz = np.meshgrid(g, g, g, indexing="ij")
        keys = hilbert_encode(
            jnp.asarray(ix.ravel()), jnp.asarray(iy.ravel()), jnp.asarray(iz.ravel()), bits=2
        )
        assert len(np.unique(np.asarray(keys))) == 64
        assert int(jnp.max(keys)) == 63

    def test_continuity(self):
        # consecutive keys decode to adjacent cells (the defining Hilbert property)
        bits = 4
        keys = jnp.arange(1 << (3 * bits), dtype=jnp.uint32)
        x, y, z = hilbert_decode(keys, bits=bits)
        coords = np.stack([np.asarray(x), np.asarray(y), np.asarray(z)], axis=1).astype(np.int64)
        steps = np.abs(np.diff(coords, axis=0)).sum(axis=1)
        np.testing.assert_array_equal(steps, np.ones(len(steps)))

    def test_prefix_property(self, rng):
        """Top 3L bits of a deep key == level-L key of the containing cell.

        The neighbor-search cell-range lookup depends on this hierarchy.
        """
        ix, iy, iz = random_coords(rng, 500)
        full = hilbert_encode(ix, iy, iz)
        for level in (1, 2, 5, 9):
            shift = KEY_BITS - level
            coarse = hilbert_encode(ix >> shift, iy >> shift, iz >> shift, bits=level)
            np.testing.assert_array_equal(
                np.asarray(full >> jnp.uint32(3 * shift)), np.asarray(coarse)
            )


class TestKeys:
    def test_sfc_key_ordering_matches_grid(self, rng):
        box = Box.create(-1.0, 1.0, boundary=BoundaryType.periodic)
        x = jnp.asarray(rng.uniform(-1, 1, 200), dtype=jnp.float32)
        y = jnp.asarray(rng.uniform(-1, 1, 200), dtype=jnp.float32)
        z = jnp.asarray(rng.uniform(-1, 1, 200), dtype=jnp.float32)
        keys_h = compute_sfc_keys(x, y, z, box)
        keys_m = compute_sfc_keys(x, y, z, box, curve="morton")
        assert int(keys_h.max()) < (1 << 30)
        # same grid cell <=> same key under either curve
        same_h = np.asarray(keys_h)[:, None] == np.asarray(keys_h)[None, :]
        same_m = np.asarray(keys_m)[:, None] == np.asarray(keys_m)[None, :]
        np.testing.assert_array_equal(same_h, same_m)


class TestBox:
    def test_apply_pbc(self):
        box = Box.create(0.0, 1.0, boundary=BoundaryType.periodic)
        d = jnp.array([[0.9, -0.9, 0.4]])
        folded = apply_pbc(box, d)
        np.testing.assert_allclose(np.asarray(folded), [[-0.1, 0.1, 0.4]], atol=1e-6)

    def test_apply_pbc_mixed(self):
        box = Box.create(
            0.0, 1.0, 0.0, 1.0, 0.0, 1.0,
            boundary=(BoundaryType.periodic, BoundaryType.open, BoundaryType.open),
        )
        d = jnp.array([[0.9, 0.9, 0.9]])
        folded = apply_pbc(box, d)
        np.testing.assert_allclose(np.asarray(folded), [[-0.1, 0.9, 0.9]], atol=1e-6)

    def test_put_in_box(self):
        box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
        p = jnp.array([[0.6, -0.7, 0.0]])
        np.testing.assert_allclose(
            np.asarray(put_in_box(box, p)), [[-0.4, 0.3, 0.0]], atol=1e-6
        )

    def test_make_global_box_grows_open_only(self):
        prev = Box.create(
            -1.0, 1.0, -1.0, 1.0, -1.0, 1.0,
            boundary=(BoundaryType.periodic, BoundaryType.open, BoundaryType.open),
        )
        x = jnp.array([-3.0, 2.0])
        y = jnp.array([-2.0, 0.5])
        z = jnp.array([0.0, 0.1])
        box = make_global_box(x, y, z, prev)
        np.testing.assert_allclose(np.asarray(box.lo), [-1.0, -2.0, -1.0])
        np.testing.assert_allclose(np.asarray(box.hi), [1.0, 1.0, 1.0])
