"""Reference-configuration L1 regression: the EXACT runs the reference CI
asserts (.jenkins/reframe_ci.py:286-287,350-362 — ``--init sedov -s 200
-n 50`` and ``--init noh -s 200 -n 50``), with 200-step energy-drift
checks.

Reference values and what they mean:

- Sedov L1_rho = 0.138 +-1.5% (1x P100). This is a true like-for-like
  metric (sim rho vs analytic rho at each particle radius). We measure
  0.166 at the same config and pin a window around that. The gap vs the
  reference's 0.138 is NOT IC (init_sedov uses the same regularGrid
  layout), NOT the pair-cutoff convention (sym_pairs off: 0.1665) and
  NOT precision (full f64: 0.1663) — each bounded <0.2% by
  scripts/probe_l1_gap.py (BASELINE.md round-5 notes); the residual is
  a formulation/metric-convention difference.
  NOTE the reference's published "L1_p = 0.902" and "L1_vel = 0.915"
  compare p and |v| against the analytic DENSITY curve
  (compare_solutions.py:115,126 passes solution["rho"] as ySol) — they
  are not physical parity targets; we assert the honest metrics instead.
- Noh L1_rho = 0.955 (same CI; 10.42 in the container variant,
  .gitlab/rfm.py:47 — the metric is strongly setup-dependent). The noh
  IC itself fixes mTotal=1 inside the r=0.5 sphere (noh_init.hpp:74),
  i.e. mean density mTotal/V = 1.9099, while the comparison assumes
  rho0 = 1 — so the raw L1 is dominated by that normalization offset
  and by the reached t_200. We assert (a) a pinned window on the raw
  metric at OUR t_200 and (b) the physics via the normalization-
  corrected profile (sim rho / 1.9099 vs analytic).

These run 200 steps at 50^3 (~65-125k particles) — minutes on TPU, far
slower on the CPU test mesh — so they are gated like the TPU tier.

Run manually:  SPHEXA_TPU_TESTS=1 python -m pytest tests/test_l1_reference.py -q
"""

import numpy as np
import pytest

import jax

if jax.default_backend() != "tpu":  # pragma: no cover
    pytest.skip(
        "reference-config L1 runs are TPU-gated (200 steps at 50^3)",
        allow_module_level=True,
    )

from sphexa_tpu.analysis.compare import compute_output_fields, l1_error
from sphexa_tpu.analysis.noh import noh_solution
from sphexa_tpu.analysis.sedov import sedov_solution
from sphexa_tpu.init import init_noh, init_sedov
from sphexa_tpu.observables import conserved_quantities
from sphexa_tpu.simulation import Simulation

STEPS = 200


def _run(init, side, prop="std", **kw):
    state, box, const = init(side)
    sim = Simulation(state, box, const, prop=prop, block=8192,
                     check_every=10, **kw)
    e0 = float(conserved_quantities(sim.state, const)["etot"])
    for _ in range(STEPS):
        sim.step()
    sim.flush()
    e1 = float(conserved_quantities(sim.state, const)["etot"])
    drift = abs(e1 - e0) / max(abs(e0), 1e-30)
    fields = compute_output_fields(sim.state, sim.box, sim._cfg)
    return sim, fields, drift


def test_sedov_reference_config():
    sim, fields, drift = _run(init_sedov, 50)
    t = float(sim.state.ttot)
    sol = sedov_solution(fields["r"], time=t, eblast=1.0,
                         gamma=sim.const.gamma)
    l1_rho = l1_error(fields["rho"], sol["rho"])
    l1_p = l1_error(fields["p"], sol["p"])
    l1_vel = l1_error(fields["vel"], sol["vel"])
    # measured 0.166 (reference CI: 0.138 +-1.5% in f64 with its own IC);
    # the window guards regressions of OUR pipeline
    assert 0.13 < l1_rho < 0.20, l1_rho
    # honest pressure/velocity parity (see module docstring)
    assert l1_p < 0.30, l1_p
    assert l1_vel < 0.20, l1_vel
    # Drift history: 2.2e-3 with the reference-parity one-sided pair
    # cutoff; the min-h symmetric cutoff (SimConstants.sym_pairs —
    # restores exact pairwise antisymmetry the gather search breaks)
    # drops it to a measured 2.1e-4. The <1e-3 north star (BASELINE.md)
    # is MET; the pin guards it with margin.
    assert drift < 1e-3, drift


def test_sedov_ve_reference_config():
    """The flagship VE pipeline at the reference configuration (the
    reference CI's ``sedov --ve`` run, .jenkins/reframe_ci.py:220-249),
    with the 200-step conservation pin.

    Drift history: 1.22e-3 with the reference-parity one-sided pair
    cutoff — localized (scripts/probe_du_precision.py) to the gather
    search keeping pairs with 2h_j < d < 2h_i that j never sees, a
    dt- and precision-INDEPENDENT one-sided force. The min-h symmetric
    cutoff (SimConstants.sym_pairs) restores exact pairwise antisymmetry
    and measures 7.9e-6 — the <1e-3 north star (BASELINE.json) is MET
    with two orders of margin.
    L1_rho measures 0.354 (std: 0.166): the AV-switch scheme starting
    from alpha_min under-dissipates the initial blast; the reference CI
    asserts no VE L1 reference either (its --ve runs are smoke-only).
    """
    sim, fields, drift = _run(init_sedov, 50, prop="ve")
    t = float(sim.state.ttot)
    sol = sedov_solution(fields["r"], time=t, eblast=1.0,
                         gamma=sim.const.gamma)
    l1_rho = l1_error(fields["rho"], sol["rho"])
    assert 0.25 < l1_rho < 0.45, l1_rho
    assert drift < 1e-4, drift


def test_noh_reference_config():
    sim, fields, drift = _run(init_noh, 50)
    t = float(sim.state.ttot)
    sol = noh_solution(fields["r"], time=t, gamma=sim.const.gamma)
    l1_raw = l1_error(fields["rho"], sol["rho"])
    # raw metric at our t_200 ~ 0.147 (measured 5.24; dominated by the
    # rho0-normalization offset, see module docstring)
    assert 3.0 < l1_raw < 7.0, l1_raw
    # physics: normalization-corrected profile tracks the solution
    rho0_actual = 1.0 / (4.0 * np.pi / 3.0 * 0.5**3)  # mTotal / V_sphere
    l1_norm = l1_error(fields["rho"] / rho0_actual, sol["rho"])
    assert l1_norm < 2.5, l1_norm
    # post-shock plateau: analytic jump ((gamma+1)/(gamma-1))^3 = 64x
    # over the actual mean density; measured peak 54.4 = ~45% of it at
    # 50^3 smoothing — guard at 40%
    assert fields["rho"].max() > 0.4 * 64.0 * rho0_actual
    # measured 2.2e-5 with the symmetric pair cutoff (was ~8e-4 one-sided)
    assert drift < 2e-4, drift
