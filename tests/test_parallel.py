"""Multi-device tests on the virtual 8-device CPU mesh: the sharded step
must (a) run with real cross-device shardings and (b) agree with the
single-device step bit-for-bit-ish. The analog of the reference's
oversubscribed-mpiexec integration tests (domain/test/integration_mpi/).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sphexa_tpu.init import init_sedov
from sphexa_tpu.parallel import make_mesh, make_sharded_step, shard_state
from sphexa_tpu.propagator import step_hydro_std
from sphexa_tpu.simulation import make_propagator_config


def make_cfg(state, box, const, block=512):
    return make_propagator_config(state, box, const, block=block)


class TestShardedStep:
    def test_eight_device_step_matches_single(self):
        assert jax.device_count() >= 8, "conftest should provide 8 CPU devices"
        state, box, const = init_sedov(16)  # 4096 particles / 8 devices
        cfg = make_cfg(state, box, const)

        # single-device reference
        ref_state, ref_box, ref_diag = step_hydro_std(state, box, cfg)

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg)
        out_state, out_box, out_diag = step(sstate, box)

        # the sharded result is the same physics
        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x), rtol=1e-5, atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )

    def test_sharded_arrays_stay_sharded(self):
        state, box, const = init_sedov(16)
        cfg = make_cfg(state, box, const)
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg)
        out_state, _, _ = step(sstate, box)
        # a replicated array also spans 8 devices — assert the per-device
        # shard really is 1/8th of the rows
        shard_rows = out_state.x.addressable_shards[0].data.shape[0]
        assert shard_rows == out_state.x.shape[0] // 8, "output lost its 8-way sharding"

    def test_multiple_steps_stable(self):
        state, box, const = init_sedov(16)
        cfg = make_cfg(state, box, const)
        mesh = make_mesh(8)
        s = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg)
        for _ in range(3):
            s, box, d = step(s, box)
        assert np.all(np.isfinite(np.asarray(s.x)))
        assert float(d["dt"]) > 0

    def test_indivisible_count_rejected(self):
        state, box, const = init_sedov(15)  # 3375 not divisible by 8
        mesh = make_mesh(8)
        with pytest.raises(ValueError, match="not divisible"):
            shard_state(state, mesh)


@pytest.mark.slow
class TestShardedPallas:
    """The multi-chip FAST path: Mosaic engine per shard under shard_map
    (interpret mode on the CPU mesh), vs the single-device pallas step."""

    def test_sharded_pallas_matches_single(self):
        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, ref_diag = step_hydro_std(state, box, cfg)

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg)
        out_state, _, out_diag = step(sstate, box)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")

        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x),
            rtol=1e-5, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )
        assert int(out_diag["nc_max"]) == int(ref_diag["nc_max"])

    def test_sharded_pallas_multiple_steps(self):
        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg)
        sbox = box
        for _ in range(3):
            sstate, sbox, diag = step(sstate, sbox)
        assert np.isfinite(np.asarray(sstate.x)).all()
        assert float(diag["dt"]) > 0.0


@pytest.mark.slow
class TestShardedGravity:
    """Self-gravity under the sharded step (GSPMD partitioning; the
    replicated coarse tree matches the reference's replicated global
    octree, assignment.hpp:51-53)."""

    def test_sharded_gravity_matches_single(self):
        import dataclasses
        import jax.numpy as jnp

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.propagator import step_hydro_ve
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(16)
        # trim the sphere cut to a mesh multiple (test-only)
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )

        sim = Simulation(state, box, const, prop="ve", block=512)
        ref_state, _, ref_diag = sim._launch()[:3]

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, sim._cfg, step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box, sim._gtree)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=5e-4, atol=5e-7,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-5
        )


@pytest.mark.slow
class TestHaloExchange:
    """The windowed all_to_all halo exchange (parallel/exchange.py):
    per-peer row windows instead of full-array replication — the
    exchange_halos.hpp analog, with comm volume asserted."""

    def test_measured_window_matches_full_slab_result(self):
        import numpy as np

        from sphexa_tpu.parallel import exchange as ex
        from sphexa_tpu.propagator import _sort_by_keys, step_hydro_std
        from sphexa_tpu.sfc.box import make_global_box

        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, _ = step_hydro_std(state, box, cfg)

        gbox = make_global_box(state.x, state.y, state.z, box)
        sstate0, keys, _ = _sort_by_keys(state, gbox, cfg.curve)
        wmax = ex.estimate_halo_window(
            sstate0.x, sstate0.y, sstate0.z, sstate0.h, keys, gbox,
            cfg.nbr, P=8,
        )
        S = state.n // 8
        assert 0 < wmax <= S

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, halo_window=wmax)
        out_state, _, out_diag = step(sstate, box)
        # exchanged rows per shard = (P-1) * wmax, never more than the
        # all_gather-equivalent; physics identical to the single-device step
        assert int(out_diag["occupancy"]) <= cfg.nbr.cap
        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x),
            rtol=1e-5, atol=1e-7,
        )

    def test_too_small_window_trips_sentinel(self):
        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        # a 64-row window cannot cover the candidate runs at this size:
        # the escape guard must flip the occupancy sentinel rather than
        # silently truncate
        step = make_sharded_step(mesh, cfg, halo_window=64)
        _, _, diag = step(sstate, box)
        assert int(diag["occupancy"]) > cfg.nbr.cap

    def test_window_scaling_shrinks_with_cell_depth(self):
        """The discovery produces windows that shrink relative to the
        slab as the grid refines (the O(surface) scaling property of the
        reference's halo lists, halos/halos.hpp)."""
        import dataclasses

        import numpy as np

        from sphexa_tpu.parallel import exchange as ex
        from sphexa_tpu.propagator import _sort_by_keys
        from sphexa_tpu.sfc.box import make_global_box

        state, box, const = init_sedov(24)
        cfg = make_propagator_config(state, box, const, block=512)
        gbox = make_global_box(state.x, state.y, state.z, box)
        sstate0, keys, _ = _sort_by_keys(state, gbox, cfg.curve)

        widths = []
        for level in (2, 3):
            nbr = dataclasses.replace(
                cfg.nbr, level=level, cap=4096, window=4, run_cap=0, gap=0,
            )
            widths.append(ex.estimate_halo_window(
                sstate0.x, sstate0.y, sstate0.z, sstate0.h, keys, gbox,
                nbr, P=8, margin=1.0, quantum=1,
            ))
        assert widths[1] <= widths[0]


@pytest.mark.slow
class TestShardedVE:
    """The flagship VE pipeline on the multi-chip fast path (VERDICT r2 #3):
    per-shard Mosaic kernels with windowed halos for the whole
    xmass->gradh->IAD->divv->AV->momentum sequence."""

    def test_sharded_ve_pallas_matches_single(self):
        import numpy as np

        from sphexa_tpu.propagator import step_hydro_ve

        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, ref_diag = step_hydro_ve(state, box, cfg)

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x),
            rtol=1e-5, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.alpha), np.asarray(ref_state.alpha),
            rtol=1e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )

    def test_sharded_ve_avclean_matches_single(self):
        import numpy as np

        from sphexa_tpu.propagator import step_hydro_ve

        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas", av_clean=True)
        ref_state, _, _ = step_hydro_ve(state, box, cfg)
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, step_fn=step_hydro_ve)
        out_state, _, _ = step(sstate, box)
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-4, atol=1e-6,
        )

    def test_sharded_turb_ve_matches_single(self):
        """turb-ve through the sharded stepper (VERDICT r3 #5): the VE
        force stage runs per-shard Mosaic kernels, the OU stirring is
        GSPMD-partitioned XLA, and the advanced TurbulenceState pytree is
        threaded through (turb_ve.hpp:53 runs under the full domain)."""
        from sphexa_tpu.propagator import step_turb_ve
        from sphexa_tpu.sph.hydro_turb import create_stirring_modes

        state, box, const = init_sedov(16)
        tcfg, turb = create_stirring_modes(lbox=1.0, st_max_modes=200)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, ref_diag, ref_turb = step_turb_ve(
            state, box, cfg, None, turb, tcfg
        )

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, step_fn=step_turb_ve,
                                 aux_cfg=tcfg)
        out_state, _, out_diag, out_turb = step(sstate, box, None, turb)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-4, atol=1e-6,
        )
        # the OU phase advance must agree exactly (same dt, same RNG path)
        np.testing.assert_allclose(
            np.asarray(out_turb.phases), np.asarray(ref_turb.phases),
            rtol=1e-6, atol=1e-9,
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )

    def test_sharded_std_cooling_matches_single(self):
        """std-cooling through the sharded stepper (VERDICT r3 #5): the
        per-particle ChemistryData rides the slab sharding and the
        in-step SFC sort (std_hydro_grackle.hpp:56)."""
        from sphexa_tpu.physics.cooling import ChemistryData, CoolingConfig
        from sphexa_tpu.propagator import step_hydro_std_cooling

        state, box, const = init_sedov(16)
        ccfg = CoolingConfig(gamma=const.gamma)
        chem = ChemistryData.ionized(state.n)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, ref_diag, ref_chem = step_hydro_std_cooling(
            state, box, cfg, None, chem, ccfg
        )

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        schem = shard_state(chem, mesh)
        step = make_sharded_step(mesh, cfg, step_fn=step_hydro_std_cooling,
                                 aux_cfg=ccfg)
        out_state, _, out_diag, out_chem = step(sstate, box, None, schem)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp),
            rtol=1e-4, atol=1e-7,
        )
        # chemistry stays aligned with the sorted state and slab-sharded
        assert out_chem.hi.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_chem.hi), np.asarray(ref_chem.hi),
            rtol=1e-5, atol=1e-8,
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )


@pytest.mark.slow
class TestShardedNbody:
    """Gravity-only N-body under the sharded step (the sharded-nbody
    coverage flagged in VERDICT r2 'What's weak' #9)."""

    def test_sharded_nbody_matches_single(self):
        import numpy as np

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.propagator import step_nbody
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(16, overrides={"G": 1.0})
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )
        sim = Simulation(state, box, const, prop="nbody", block=512)
        ref_state, _, ref_diag = sim._launch()[:3]

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, sim._cfg, step_fn=step_nbody)
        out_state, _, out_diag = step(sstate, box, sim._gtree)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=5e-4, atol=5e-7,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-5
        )


@pytest.mark.slow
class TestShardedGravityFastPath:
    """Distributed gravity on the Pallas fast path: psum multipole
    upsweep (global_multipole.hpp analog) + near field through the
    windowed halo exchange — no particle-array replication."""

    def test_sharded_ve_gravity_pallas_matches_single(self):
        import numpy as np

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.propagator import step_hydro_ve
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(16)
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )
        sim = Simulation(state, box, const, prop="ve", block=512,
                         backend="pallas")
        ref_state, _, ref_diag = sim._launch()[:3]

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, sim._cfg, step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box, sim._gtree)
        assert out_state.x.sharding.spec == jax.sharding.PartitionSpec("p")
        # the distributed upsweep sums leaf payloads in a different f32
        # order than the single-device pass; MAC-marginal nodes can flip
        # between M2P and descend, shifting a few particles' forces by
        # up to the theta-truncation error (~0.5% relative; measured
        # max |dvx| 2.6e-4 here). Energies and list sizes agree tightly.
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-2, atol=5e-4,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-4
        )
        # per-shard slabs end in PARTIAL tail blocks (mostly-duplicated
        # rows -> point-like bboxes) that legitimately accept more nodes
        # than any full single-device block — assert cap-boundedness (the
        # production overflow contract), not closeness
        assert int(out_diag["m2p_max"]) <= sim._cfg.gravity.m2p_cap
        assert int(out_diag["p2p_max"]) <= sim._cfg.gravity.p2p_cap

    def test_sharded_gravity_let_matches_single(self):
        """LET analog (VERDICT r4 #5): sharded solve classifying against
        the per-shard slab-bbox essential set (GravityConfig.let_cap)
        must match the full-tree sharded solve AND genuinely prune."""
        import dataclasses as dc

        import numpy as np

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.propagator import step_hydro_ve
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(16)
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )
        sim = Simulation(state, box, const, prop="ve", block=512,
                         backend="pallas")
        ref_state, _, ref_diag = sim._launch()[:3]

        num_nodes = sim._cfg.grav_meta.num_nodes
        cfg_let = dc.replace(
            sim._cfg,
            gravity=dc.replace(sim._cfg.gravity, let_cap=num_nodes),
        )
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg_let, step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box, sim._gtree)
        # the essential set is ACTIVE (at this tiny tree the slab bbox
        # opens everything, so it equals the full tree; the at-scale
        # pruning is measured by scripts/measure_let.py: 2-3.4x at 1-4M)
        assert 0 < int(out_diag["let_max"]) <= num_nodes
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-2, atol=5e-4,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-4
        )

    def test_sharded_gravity_let_bitmask_matches_single(self):
        """ISSUE-1 sharded coverage: the let_cap path feeding the
        hierarchical bitmask-rank compaction (superblock pre-pass
        classifying against the slab essential list, per-block lists
        from gravity/pallas_compact.py) must match the single-device
        dense-sort solve within the same MAC-marginal tolerance as the
        sort-based sharded paths."""
        import dataclasses as dc

        import numpy as np

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.propagator import step_hydro_ve
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(16)
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )
        sim = Simulation(state, box, const, prop="ve", block=512,
                         backend="pallas")
        ref_state, _, ref_diag = sim._launch()[:3]

        num_nodes = sim._cfg.grav_meta.num_nodes
        cfg_bm = dc.replace(
            sim._cfg,
            gravity=dc.replace(sim._cfg.gravity, let_cap=num_nodes,
                               compaction="bitmask", super_factor=2,
                               super_cap=num_nodes),
        )
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg_bm, step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box, sim._gtree)
        assert 0 < int(out_diag["let_max"]) <= num_nodes
        assert 0 < int(out_diag["c_max"]) <= num_nodes
        assert int(out_diag["compact_width"]) == num_nodes
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-2, atol=5e-4,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-4
        )


@pytest.mark.slow
class TestShardedEwaldSpherical:
    """VERDICT r3 #7: periodic (Ewald) gravity and spherical order-P
    multipoles on the sharded fast path — psum upsweep + windowed
    near-field halos (full-slab windows), equivalent to the
    single-device solves."""

    def _sharded_gravity(self, xs, ys, zs, ms, hs, skeys, box, gtree,
                         meta, cfg, ecfg=None, order=0):
        import dataclasses as dc
        import functools

        from jax.sharding import PartitionSpec as P

        from sphexa_tpu.gravity.ewald import compute_gravity_ewald
        from sphexa_tpu.gravity.traversal import (
            compute_gravity,
            compute_multipoles_sharded,
        )
        from sphexa_tpu.propagator import shard_map  # version-compat shim

        mesh = make_mesh(8)
        Pn = 8
        S = xs.shape[0] // Pn
        gcfg = dc.replace(cfg, use_pallas=True, multipole_order=order)

        def stage(x, y, z, m, h, keys):
            if ecfg is not None:
                gx, gy, gz, egrav, diag = compute_gravity_ewald(
                    x, y, z, m, h, keys, box, gtree, meta, gcfg, ecfg,
                    shard=("p", Pn, S),
                )
            else:
                mpc = compute_multipoles_sharded(
                    x, y, z, m, keys, gtree, meta, "p", order=order
                )
                gx, gy, gz, egrav, diag = compute_gravity(
                    x, y, z, m, h, keys, box, gtree, meta, gcfg,
                    mp_cache=mpc, shard=("p", Pn, S),
                )
            egrav = jax.lax.psum(egrav, "p")
            diag = {k: jax.lax.pmax(v, "p") for k, v in diag.items()}
            return gx, gy, gz, egrav, diag

        diag_keys = (
            ["m2p_max", "p2p_max", "leaf_occ", "c_max", "let_max",
             "compact_width"]
            if ecfg is not None
            else ["m2p_max", "p2p_max", "leaf_occ", "c_max", "let_max",
                  "compact_width", "mac_work_ratio"]
        )
        Pp, Pr = P("p"), P()
        fn = shard_map(
            stage, mesh=mesh,
            in_specs=(Pp, Pp, Pp, Pp, Pp, Pp),
            out_specs=(Pp, Pp, Pp, Pr, {k: Pr for k in diag_keys}),
            check_vma=False,
        )
        # under an outer jit like the production stepper: shard_map's
        # EAGER impl trips on a stale nested-jit cache entry when a
        # previous test traced compute_gravity inside another jit (JAX
        # "non-shard_map tracers" quirk; jitted programs are unaffected)
        return jax.jit(fn)(xs, ys, zs, ms, hs, skeys)

    def _random_setup(self, periodic, n=512, seed=7):
        import dataclasses as dc

        from sphexa_tpu.gravity.traversal import (
            GravityConfig,
            estimate_gravity_caps,
        )
        from sphexa_tpu.gravity.tree import build_gravity_tree
        from sphexa_tpu.sfc.box import BoundaryType, Box
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        rng = np.random.default_rng(seed)
        x, y, z = rng.uniform(-0.5, 0.5, (3, n)).astype(np.float32)
        m = rng.uniform(0.5, 1.5, n).astype(np.float32)
        bt = BoundaryType.periodic if periodic else BoundaryType.open
        box = Box.create(-0.5, 0.5, boundary=bt)
        keys = np.asarray(compute_sfc_keys(x, y, z, box))
        order = np.argsort(keys)
        xs, ys, zs, ms = (
            jnp.asarray(np.asarray(a)[order]) for a in (x, y, z, m)
        )
        skeys = jnp.asarray(keys[order])
        gtree, meta = build_gravity_tree(keys[order], bucket_size=32)
        cfg = estimate_gravity_caps(
            xs, ys, zs, ms, skeys, box, gtree, meta,
            GravityConfig(theta=0.6, bucket_size=32, G=1.0), margin=2.0,
        )
        hs = jnp.full_like(xs, 1e-3)
        return xs, ys, zs, ms, hs, skeys, box, gtree, meta, cfg

    def test_sharded_ewald_matches_single(self):
        import dataclasses as dc

        from sphexa_tpu.gravity.ewald import (
            EwaldConfig,
            compute_gravity_ewald,
        )

        (xs, ys, zs, ms, hs, skeys, box, gtree, meta,
         cfg) = self._random_setup(periodic=True)
        ecfg = EwaldConfig()
        # single-device reference on the same engine path (interpret)
        rcfg = dc.replace(cfg, use_pallas=True)
        rax, ray, raz, regrav, _ = compute_gravity_ewald(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, rcfg, ecfg
        )
        ax, ay, az, egrav, diag = self._sharded_gravity(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, cfg, ecfg=ecfg
        )
        # psum upsweep reorders f32 leaf sums: MAC-marginal flips bound
        # the tolerance (same argument as TestShardedGravityFastPath)
        np.testing.assert_allclose(
            np.asarray(ax), np.asarray(rax), rtol=1e-2, atol=2e-3 * float(
                jnp.max(jnp.abs(rax)))
        )
        np.testing.assert_allclose(
            float(egrav), float(regrav), rtol=1e-4
        )
        assert int(diag["p2p_max"]) <= cfg.p2p_cap

    def test_sharded_spherical_matches_single(self):
        import dataclasses as dc

        from sphexa_tpu.gravity.traversal import compute_gravity

        (xs, ys, zs, ms, hs, skeys, box, gtree, meta,
         cfg) = self._random_setup(periodic=False)
        order = 4
        rcfg = dc.replace(cfg, use_pallas=True, multipole_order=order)
        rax, ray, raz, regrav, _ = compute_gravity(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, rcfg
        )
        ax, ay, az, egrav, diag = self._sharded_gravity(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, cfg, order=order
        )
        np.testing.assert_allclose(
            np.asarray(ax), np.asarray(rax), rtol=1e-2, atol=2e-3 * float(
                jnp.max(jnp.abs(rax)))
        )
        np.testing.assert_allclose(
            float(egrav), float(regrav), rtol=1e-4
        )
        assert int(diag["m2p_max"]) <= cfg.m2p_cap


@pytest.mark.slow
class TestGravityMacWindows:
    """r13 gravity comm diet: the MAC-need-sized sparse near-field serve
    (sizing.device_gravity_halo feeding compute_gravity's cell-granular
    exchange through cfg.grav_cells) pinned equal to the single-device
    solve — std and ve open-boundary runs at P=2/P=4, plus the periodic
    Ewald path — with the same MAC-marginal f32 tolerance as the
    round-3 LET tests. grav_cells=() (the grav_window=0 fallback) must
    stay byte-identical to the pre-sizing full-slab lowering."""

    @staticmethod
    def _evrard_sim(prop, theta=0.8):
        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(20)
        n16 = (state.n // 16) * 16
        state = jax.tree.map(
            lambda a: a[:n16] if getattr(a, "ndim", 0) == 1 else a, state
        )
        # theta=0.8: the first MAC where the per-distance needs are
        # genuinely partial at this size (caps (1048, 768, 1048) vs the
        # full-slab 3*1048 at P=4 — docs/NEXT.md round 13); tighter
        # thetas open every remote leaf and the test would silently
        # degenerate to full slabs
        sim = Simulation(state, box, const, prop=prop, block=512,
                         backend="pallas", theta=theta)
        return state, sim

    @staticmethod
    def _mac_cells(state, sim, P, shifts=None):
        from sphexa_tpu.parallel.sizing import device_gravity_halo
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        keys = compute_sfc_keys(state.x, state.y, state.z, sim.box,
                                curve=sim.curve)
        order = jnp.argsort(keys)
        xs, ys, zs, ms = (
            a[order] for a in (state.x, state.y, state.z, state.m)
        )
        return device_gravity_halo(
            xs, ys, zs, ms, keys[order], sim.box, sim._gtree,
            sim._cfg.grav_meta, theta=sim.theta, P=P, shifts=shifts,
        )

    @pytest.mark.parametrize("P", [2, 4])
    @pytest.mark.parametrize("prop", ["std", "ve"])
    def test_sparse_near_field_matches_single(self, P, prop):
        from sphexa_tpu.propagator import step_hydro_std, step_hydro_ve

        step_fn = step_hydro_ve if prop == "ve" else step_hydro_std
        state, sim = self._evrard_sim(prop)
        ref_state, _, ref_diag = sim._launch()[:3]

        cells = self._mac_cells(state, sim, P)
        S = state.n // P
        assert len(cells) == P - 1
        if P == 4:
            # regime check: the serve must ship strictly less than the
            # retired full-slab exchange, or the test proves nothing
            assert sum(cells) < (P - 1) * S, (cells, S)
        mesh = make_mesh(P)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, sim._cfg, step_fn=step_fn,
                                 grav_cells=cells)
        out_state, _, out_diag = step(sstate, sim.box, sim._gtree)
        # cap-bounded, NOT cap+1: the MAC-sized caps were sufficient and
        # the escape sentinel stayed quiet (the monotone-MAC guarantee)
        assert int(out_diag["p2p_max"]) <= sim._cfg.gravity.p2p_cap
        np.testing.assert_allclose(
            np.asarray(out_state.vx), np.asarray(ref_state.vx),
            rtol=1e-2, atol=5e-4,
        )
        np.testing.assert_allclose(
            float(out_diag["egrav"]), float(ref_diag["egrav"]), rtol=1e-4
        )

    @pytest.mark.parametrize("P", [2, 4])
    def test_sparse_ewald_matches_single(self, P):
        """Periodic path: the sized caps must union the opened set over
        the Ewald replica shells (a shifted target slab reaches
        wrap-around leaves the base pass never opens), so the sparse
        serve under compute_gravity_ewald stays equal to the
        single-device Ewald solve."""
        import dataclasses as dc
        from itertools import product

        from sphexa_tpu.gravity.ewald import (
            EwaldConfig,
            compute_gravity_ewald,
        )
        from sphexa_tpu.parallel.sizing import device_gravity_halo
        from sphexa_tpu.propagator import shard_map

        from jax.sharding import PartitionSpec as PSpec

        helper = TestShardedEwaldSpherical()
        (xs, ys, zs, ms, hs, skeys, box, gtree, meta,
         cfg) = helper._random_setup(periodic=True)
        ecfg = EwaldConfig()
        r = ecfg.num_replica_shells
        shells = np.array(
            [sh for sh in product(range(-r, r + 1), repeat=3)], np.float32
        )
        shifts = jnp.asarray(shells) * box.lengths[0]
        cells = device_gravity_halo(
            xs, ys, zs, ms, skeys, box, gtree, meta,
            theta=cfg.theta, P=P, shifts=shifts,
        )
        S = xs.shape[0] // P
        assert len(cells) == P - 1 and max(cells) <= S

        rcfg = dc.replace(cfg, use_pallas=True)
        rax, _, _, regrav, _ = compute_gravity_ewald(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, rcfg, ecfg
        )

        mesh = make_mesh(P)

        def stage(x, y, z, m, hh, keys):
            gx, gy, gz, egrav, diag = compute_gravity_ewald(
                x, y, z, m, hh, keys, box, gtree, meta, rcfg, ecfg,
                shard=("p", P, tuple(cells)),
            )
            # per-shard serve telemetry is the driver's concern, not this
            # equality pin
            diag.pop("halo_rows", None)
            diag.pop("halo_occ", None)
            egrav = jax.lax.psum(egrav, "p")
            diag = {k: jax.lax.pmax(v, "p") for k, v in diag.items()}
            return gx, gy, gz, egrav, diag

        diag_keys = ["m2p_max", "p2p_max", "leaf_occ", "c_max",
                     "let_max", "compact_width"]
        Pp, Pr = PSpec("p"), PSpec()
        fn = shard_map(
            stage, mesh=mesh,
            in_specs=(Pp, Pp, Pp, Pp, Pp, Pp),
            out_specs=(Pp, Pp, Pp, Pr, {k: Pr for k in diag_keys}),
            check_vma=False,
        )
        ax, ay, az, egrav, diag = jax.jit(fn)(xs, ys, zs, ms, hs, skeys)
        assert int(diag["p2p_max"]) <= cfg.p2p_cap
        np.testing.assert_allclose(
            np.asarray(ax), np.asarray(rax), rtol=1e-2,
            atol=2e-3 * float(jnp.max(jnp.abs(rax))),
        )
        np.testing.assert_allclose(float(egrav), float(regrav), rtol=1e-4)

    def test_full_slab_lowering_byte_identical(self):
        """The grav_window=0 contract: an empty grav_cells lowers the
        sharded step to byte-identical StableHLO as a config that never
        saw the sizing pass (win stays the int S full-slab window), while
        a sparse cap tuple genuinely changes the program.

        The raw ``as_text()`` comparison here is THE canonicalizer
        guard: every other lowering-identity pin in the repo (this
        class included, below) goes through the jaxdiff fingerprint,
        and this one byte-level assert is what proves the fingerprint
        is not hashing away a real difference.
        """
        from sphexa_tpu.devtools.audit.lowerdiff import fingerprint_callable
        from sphexa_tpu.propagator import step_hydro_ve

        state, sim = self._evrard_sim("ve")
        mesh = make_mesh(4)
        sstate = shard_state(state, mesh)
        base = make_sharded_step(mesh, sim._cfg, step_fn=step_hydro_ve)
        zero = make_sharded_step(mesh, sim._cfg, step_fn=step_hydro_ve,
                                 grav_cells=())
        lower = lambda st: st._jitted.lower(
            sstate, sim.box, sim._gtree, None).as_text()
        text_base = lower(base)
        text_zero = lower(zero)
        assert text_base == text_zero
        # the fingerprint helper must agree with the byte-level verdict
        # in both directions: identical programs collide, a genuinely
        # different program (sparse caps) does not
        fprint = lambda st: fingerprint_callable(
            st._jitted, sstate, sim.box, sim._gtree, None)
        fp_base = fprint(base)
        assert fprint(zero).digest == fp_base.digest
        cells = self._mac_cells(state, sim, 4)
        sparse = make_sharded_step(mesh, sim._cfg, step_fn=step_hydro_ve,
                                   grav_cells=cells)
        assert lower(sparse) != text_base
        assert fprint(sparse).digest != fp_base.digest


@pytest.mark.slow
class TestSimulationMesh:
    """Multi-chip through the Simulation driver (num_devices): the same
    loop, reconfiguration and overflow recovery as single-chip, with the
    halo window sized and escalated like the neighbor caps."""

    def test_simulation_num_devices_matches_single(self):
        """Runs in a SUBPROCESS: after many sharded programs have been
        compiled in one process, the oversubscribed XLA:CPU mesh can
        cross-route collective executables (buffer-count mismatch) — a
        test-harness artifact; a fresh process shows the real behavior
        (jax.clear_caches() does not clear the collective registry)."""
        from conftest import run_mesh_subprocess

        code = """
            import numpy as np

            from sphexa_tpu.init import init_sedov
            from sphexa_tpu.simulation import Simulation

            state, box, const = init_sedov(16)
            ref = Simulation(state, box, const, prop="std", block=512,
                             backend="pallas")
            for _ in range(3):
                ref.step()

            sim = Simulation(state, box, const, prop="std", block=512,
                             backend="pallas", num_devices=8)
            assert sim._mesh is not None and sim._mesh.size == 8
            for _ in range(3):
                d = sim.step()
            assert d["reconfigured"] == 0.0
            np.testing.assert_allclose(
                np.asarray(sim.state.x), np.asarray(ref.state.x),
                rtol=1e-5, atol=1e-7,
            )
            rows = sim.state.x.addressable_shards[0].data.shape[0]
            assert rows == state.n // 8
            print("SIM-MESH-OK")
        """
        out = run_mesh_subprocess(code, timeout=600)
        assert "SIM-MESH-OK" in out.stdout, out.stderr[-2000:]

    def test_undersized_grav_window_sentinel_retries_to_full(self):
        """Seeded under-sized gravity window: the sparse serve's escape
        sentinel (p2p_cap + 1, the shared overflow contract) must fire,
        the driver must regrow the MAC-need margin and replay the step,
        and the retry must converge to the full-slab ceiling — a wrong
        window surfaces as a reconfigure, never as wrong physics."""
        from conftest import run_mesh_subprocess

        code = """
            import numpy as np
            import jax

            from sphexa_tpu.init import init_evrard
            from sphexa_tpu.simulation import Simulation

            state, box, const = init_evrard(12)
            n8 = (state.n // 8) * 8
            state = jax.tree.map(
                lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a,
                state)
            sim = Simulation(state, box, const, prop="ve", block=512,
                             backend="pallas", num_devices=2,
                             grav_window=64)
            # undersize the MAC-need margin far below 1 and reconfigure:
            # the serve must escape, not silently drop remote rows
            sim._grav_halo_margin = 0.05
            sim._configure(reason="test-undersize")
            S = state.n // 2
            assert max(sim._grav_cells) < S, sim._grav_cells
            d = sim.step()
            trips = sim.telemetry.counters.get("grav_halo_trips", 0)
            assert trips >= 1, trips
            assert d["reconfigured"] == 1.0
            assert max(sim._grav_cells) == S, (sim._grav_cells, S)
            ref = Simulation(state, box, const, prop="ve", block=512,
                             backend="pallas")
            ref.step()
            np.testing.assert_allclose(
                np.asarray(sim.state.vx), np.asarray(ref.state.vx),
                rtol=1e-2, atol=5e-4)
            print("GRAV-SENTINEL-OK")
        """
        out = run_mesh_subprocess(code, timeout=900)
        assert "GRAV-SENTINEL-OK" in out.stdout, out.stderr[-2000:]

    def test_simulation_num_devices_indivisible_rejected(self):
        import pytest

        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(15)  # 3375 % 8 != 0
        with pytest.raises(ValueError, match="not divisible"):
            Simulation(state, box, const, num_devices=8)


class TestDeviceSizing:
    """O(N/P) reconfiguration (VERDICT r3 #3): multi-device sizing runs as
    jitted device reductions; only scalars, O(#cells) histograms and
    O(tree) arrays reach the host. The reference's counterpart is the
    allreduce-incremental tree count (update_mpi.hpp:26-106) + rank-local
    assignment (assignment.hpp:84-122)."""

    def test_pyramid_tree_matches_host_build(self):
        from sphexa_tpu.parallel.sizing import leaf_array_from_device_keys
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        from sphexa_tpu.tree.csarray import compute_octree

        state, box, const = init_sedov(16)
        keys = compute_sfc_keys(state.x, state.y, state.z, box)
        ref, _ = compute_octree(
            np.sort(np.asarray(keys, np.uint64)), bucket_size=64
        )
        # unsorted device keys: the histogram build never needs the sort
        got = leaf_array_from_device_keys(keys, bucket_size=64)
        np.testing.assert_array_equal(got, ref)

    def test_pyramid_tree_matches_host_build_clustered(self):
        # deep drill-down coverage: a tight cluster forces refinement well
        # past the base histogram level
        from sphexa_tpu.parallel.sizing import leaf_array_from_device_keys
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        from sphexa_tpu.tree.csarray import compute_octree
        import jax.numpy as jnp

        rng = np.random.default_rng(7)
        n = 20000
        # half uniform, half in a 1e-3-wide cluster
        pts = np.concatenate([
            rng.uniform(0, 1, (n // 2, 3)),
            0.5 + 1e-3 * rng.uniform(0, 1, (n // 2, 3)),
        ])
        state, box, const = init_sedov(8)
        keys = compute_sfc_keys(
            jnp.asarray(pts[:, 0], jnp.float32),
            jnp.asarray(pts[:, 1], jnp.float32),
            jnp.asarray(pts[:, 2], jnp.float32), box)
        ref, _ = compute_octree(
            np.sort(np.asarray(keys, np.uint64)), bucket_size=64
        )
        got = leaf_array_from_device_keys(keys, bucket_size=64)
        np.testing.assert_array_equal(got, ref)

    def test_pyramid_tree_matches_host_build_evrard_wrap_outlier(self):
        """Evrard-shaped centrally-condensed keys PLUS particles pinned
        to both box corners: the far corner's key is the curve maximum —
        the Hilbert wrap case where the last drill-down bucket's upper
        edge is the end of key space. Device build must equal the host
        oracle exactly: leaf array AND the full linkage/geometry the
        driver's (now device-only) _configure_gravity consumes."""
        from sphexa_tpu.gravity.tree import (
            build_gravity_tree,
            linkage_from_leaves,
        )
        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.parallel.sizing import leaf_array_from_device_keys
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        import jax.numpy as jnp

        state, box, const = init_evrard(12)
        x = np.asarray(state.x).copy()
        y = np.asarray(state.y).copy()
        z = np.asarray(state.z).copy()
        lo = np.asarray(box.lo)
        hi = lo + np.asarray(box.lengths)
        x[0], y[0], z[0] = lo
        x[1], y[1], z[1] = hi
        keys = compute_sfc_keys(
            jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
            jnp.asarray(z, jnp.float32), box)
        ref_tree, ref_meta = build_gravity_tree(
            np.sort(np.asarray(keys, np.uint64)), bucket_size=64
        )
        leaf = leaf_array_from_device_keys(keys, bucket_size=64)
        got_tree, got_meta = linkage_from_leaves(leaf)
        assert got_meta == ref_meta
        for f in ("leaf_keys", "parent", "is_leaf", "leaf_of_node",
                  "node_of_leaf", "center_frac", "halfsize_frac"):
            np.testing.assert_array_equal(
                np.asarray(getattr(got_tree, f)),
                np.asarray(getattr(ref_tree, f)), err_msg=f)

    def test_simulation_tree_build_matches_host_oracle(self):
        """The driver's ONLY gravity-tree build is the device pyramid
        (r13, single- and multi-device alike): its configured tree must
        equal the host-numpy build_gravity_tree oracle on the same keys."""
        from sphexa_tpu.gravity.tree import build_gravity_tree
        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(12, overrides={"G": 1.0})
        sim = Simulation(state, box, const, prop="nbody", backend="xla")
        keys = compute_sfc_keys(state.x, state.y, state.z, sim.box,
                                curve=sim.curve)
        ref_tree, ref_meta = build_gravity_tree(
            np.sort(np.asarray(keys, np.uint64)),
            bucket_size=sim.grav_bucket, curve=sim.curve)
        assert sim._cfg.grav_meta == ref_meta
        np.testing.assert_array_equal(
            np.asarray(sim._gtree.leaf_keys),
            np.asarray(ref_tree.leaf_keys))
        np.testing.assert_array_equal(
            np.asarray(sim._gtree.parent), np.asarray(ref_tree.parent))

    def test_single_device_ignores_grav_window(self):
        """The grav_window knob only gates the multi-device sizing pass:
        a single-device run must size no gravity halo caps and launch
        the identical executable whatever its value."""
        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(12, overrides={"G": 1.0})
        a = Simulation(state, box, const, prop="nbody", backend="xla",
                       grav_window=0)
        b = Simulation(state, box, const, prop="nbody", backend="xla",
                       grav_window=512)
        assert a._grav_cells == () and b._grav_cells == ()
        assert a._launch_signature(False) == b._launch_signature(False)

    def test_sizing_stats_matches_host(self):
        from sphexa_tpu.parallel import sizing
        from sphexa_tpu import native
        from sphexa_tpu.neighbors.cell_list import pad_cap

        state, box, const = init_sedov(12)
        level, group = 3, 64
        occ, ext = jax.device_get(sizing.sizing_stats(
            state.x, state.y, state.z, box, level, group
        ))
        xa, ya, za = (np.asarray(a) for a in (state.x, state.y, state.z))
        keys = native.compute_keys(
            xa, ya, za, np.asarray(box.lo), np.asarray(box.lengths),
            "hilbert")
        order = native.argsort_keys(keys)
        assert int(occ) == native.max_cell_occupancy(keys[order], level)
        ref_ext = native.group_extents(xa, ya, za, order, group)
        np.testing.assert_allclose(np.asarray(ext), ref_ext, rtol=1e-6)

    def test_device_halo_window_matches_host(self):
        from sphexa_tpu.parallel.exchange import estimate_halo_window
        from sphexa_tpu.parallel.sizing import device_halo_window
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        from sphexa_tpu.simulation import make_propagator_config

        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512)
        keys = compute_sfc_keys(state.x, state.y, state.z, box)
        order = np.argsort(np.asarray(keys))
        xs = jnp.asarray(np.asarray(state.x)[order])
        ys = jnp.asarray(np.asarray(state.y)[order])
        zs = jnp.asarray(np.asarray(state.z)[order])
        hs = jnp.asarray(np.asarray(state.h)[order])
        sk = jnp.asarray(np.asarray(keys)[order])
        ref = estimate_halo_window(xs, ys, zs, hs, sk, box, cfg.nbr, P=8)
        got = device_halo_window(state.x, state.y, state.z, state.h,
                                 keys, box, cfg.nbr, P=8)
        assert got == ref

    def test_mesh_configure_transfers_o_n_over_p(self):
        """The VERDICT 'Done' gate: a num_devices=8 gravity run's
        (re)configure moves O(N/P) bytes to the host — asserted with the
        sizing transfer counter, under a device-to-host transfer guard so
        any stray implicit full-array gather fails the test."""
        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.parallel import sizing
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_evrard(12, overrides={"G": 1.0})
        n8 = (state.n // 8) * 8
        state = jax.tree.map(
            lambda a: a[:n8] if getattr(a, "ndim", 0) == 1 else a, state
        )
        sizing.reset_transfer_bytes()
        # tripwire: on the CPU mesh the jax transfer guard is inert
        # (host arrays are zero-copy), so catch unmetered full-array
        # gathers by intercepting numpy coercion of large jax arrays —
        # every legitimate fetch in the device-sizing path goes through
        # sizing.fetch (which yields numpy before np.asarray sees it)
        import unittest.mock as mock

        real_asarray = np.asarray
        limit = state.x.nbytes // 4  # anything >= N/4 rows is a gather

        def guarded(a, *args, **kw):
            if isinstance(a, jax.Array) and a.nbytes >= limit:
                raise AssertionError(
                    f"unmetered device->host gather of {a.nbytes} bytes"
                )
            return real_asarray(a, *args, **kw)

        with mock.patch("numpy.asarray", side_effect=guarded), \
                jax.transfer_guard_device_to_host("disallow"):
            sim = Simulation(state, box, const, prop="nbody",
                             num_devices=8, backend="xla")
        state_bytes = sum(
            a.nbytes for a in jax.tree.leaves(sim.state)
            if hasattr(a, "nbytes")
        )
        # O(N/P) + O(#cells + tree): generous constant, but far below the
        # full-state gather the host path would need
        budget = state_bytes // 8 + 2_000_000
        assert sizing.TRANSFER_BYTES < budget, (
            sizing.TRANSFER_BYTES, budget
        )


class TestSparseHaloExchange:
    """Sparse cell-granular halo exchange (shard_halo_stage_sparse): comm
    volume tracks the halo SURFACE via per-distance ppermute buffers — the
    exchangeHalos analog (exchange_halos.hpp:43-119) replacing the
    contiguous windows that measured degenerate (Wmax = S at every size,
    docs/NEXT.md round-4). These tests run at 40^3 where the per-distance
    needs are genuinely partial (VERDICT r4 weak #5): max cap < S and the
    total is ~5.6 slabs vs the windowed path's degenerate 7."""

    @staticmethod
    def _sparse_caps(state, box, nbr, P=8):
        from sphexa_tpu.parallel.sizing import device_sparse_halo
        from sphexa_tpu.sfc.box import make_global_box
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        gbox = make_global_box(state.x, state.y, state.z, box)
        keys = compute_sfc_keys(state.x, state.y, state.z, gbox)
        return device_sparse_halo(
            state.x, state.y, state.z, state.h, keys, gbox, nbr, P=P
        )

    def test_sizing_volume_tracks_surface(self):
        """The sized per-distance caps ship strictly less than the
        all_gather-equivalent volume, with at least one genuinely
        partial distance — the regime the windowed path never reached."""
        state, box, const = init_sedov(40)  # 64000 / 8
        cfg = make_cfg(state, box, const)
        hc = self._sparse_caps(state, box, cfg.nbr)
        S = -(-state.n // 8)
        assert len(hc) == 7
        assert sum(hc) < 0.85 * 7 * S, (hc, S)
        assert min(hc) < 0.6 * S, (hc, S)

    def test_sparse_std_matches_single_partial_windows(self):
        """One std step, 8 shards, sparse exchange in the partial-cap
        regime vs the single-device step.

        Also the regression pin for the XLA:CPU collective-rendezvous
        race (this container's jax 0.4.x): the sparse stage issues ~P^2
        mutually independent collectives (P-1 ppermutes per serve x 3
        serves + gathers/psums), and unchained they could rendezvous in
        different orders across the oversubscribed virtual devices —
        every shard's coverage/need then collapsed to shard 0's values,
        tripping the escape sentinel with ZERO drift (occupancy ==
        cap+1, the historical failure of this test) and NaN-ing the
        positions. exchange.chain_after now pins one total order; the
        per-shard telemetry assertions below would fail first under any
        recurrence (the race's signature: all shards reporting shard
        0's need row)."""
        import dataclasses

        from sphexa_tpu.parallel import sizing
        from sphexa_tpu.propagator import step_hydro_std
        from sphexa_tpu.sfc.box import make_global_box
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        state, box, const = init_sedov(40)
        cfg = make_propagator_config(state, box, const, backend="pallas")
        ref_state, _, ref_diag = step_hydro_std(state, box, cfg)

        hc = self._sparse_caps(state, box, cfg.nbr)
        S = -(-state.n // 8)
        assert max(hc) < S, "regime check: caps must be partial"
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, halo_cells=hc)
        out_state, _, out_diag = step(sstate, box)
        assert int(out_diag["occupancy"]) <= cfg.nbr.cap
        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x),
            rtol=1e-5, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp),
            rtol=1e-4,
        )
        np.testing.assert_allclose(
            float(out_diag["dt"]), float(ref_diag["dt"]), rtol=1e-5
        )
        # per-shard exchange telemetry (SHARD_DIAG_KEYS) vs the sizing
        # pass's independently computed need matrix — the schema-v2
        # exchange-event acceptance check AND the rendezvous-race canary
        gbox = make_global_box(state.x, state.y, state.z, box)
        keys = compute_sfc_keys(state.x, state.y, state.z, gbox)
        nbr = cfg.nbr
        if nbr.run_cap > S:
            nbr = dataclasses.replace(nbr, run_cap=S)
        need = np.asarray(jax.device_get(sizing.sparse_need_matrix(
            state.x, state.y, state.z, state.h, keys, gbox, nbr, 8)))
        expected_rows = [int(need[k].sum() - need[k, k]) for k in range(8)]
        rows = np.asarray(out_diag["shard_rows"])
        assert rows.tolist() == expected_rows
        assert len(set(rows.tolist())) > 1  # genuinely per-shard
        occ = np.asarray(out_diag["shard_occ"])
        assert occ.shape == (8,) and float(occ.max()) <= 1.0 + 1e-6
        work = np.asarray(out_diag["shard_work"])
        assert work.shape == (8,) and (work > 0).all()
        assert np.asarray(out_diag["shard_trips"]).sum() == 0

    def test_sparse_escape_sentinel_trips(self):
        """Undersized per-distance caps must surface as the occupancy
        cap+1 sentinel (the shared overflow contract), not wrong physics."""
        from sphexa_tpu.propagator import step_hydro_std

        state, box, const = init_sedov(16)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, halo_cells=(64,) * 7)
        _, _, diag = step(sstate, box)
        assert int(diag["occupancy"]) == cfg.nbr.cap + 1

    @pytest.mark.slow
    def test_sparse_ve_matches_single_512k(self):
        """VERDICT r4 next #2 'Done' gate: equivalence AND exchanged-row
        volume in a genuinely-partial regime at 512k/8 (the size where
        the sparse need measured 1.27 slabs and shrinking)."""
        from sphexa_tpu.propagator import step_hydro_ve

        state, box, const = init_sedov(80)  # 512000 / 8
        cfg = make_propagator_config(state, box, const, backend="pallas")
        hc = self._sparse_caps(state, box, cfg.nbr)
        S = -(-state.n // 8)
        # volume: the padded total must stay well under all_gather volume
        # (measured 2.50 slabs vs 7 at this size)
        assert sum(hc) < 0.45 * 7 * S, (hc, S)
        ref_state, _, _ = step_hydro_ve(state, box, cfg)
        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        step = make_sharded_step(mesh, cfg, halo_cells=hc,
                                 step_fn=step_hydro_ve)
        out_state, _, out_diag = step(sstate, box)
        assert int(out_diag["occupancy"]) <= cfg.nbr.cap
        np.testing.assert_allclose(
            np.asarray(out_state.x), np.asarray(ref_state.x),
            rtol=1e-5, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp),
            rtol=1e-3, atol=1e-6,
        )


class TestShardedEvolvedChemistry:
    """VERDICT r4 #6 'Done' gate: the 6-species network evolves INSIDE
    the sharded std-cooling step (cooler.cpp solve_chemistry under the
    full domain) and matches the single-device run."""

    def test_sharded_evolved_species_match_single(self):
        from sphexa_tpu.physics.cooling import ChemistryData, CoolingConfig
        from sphexa_tpu.propagator import step_hydro_std_cooling

        state, box, const = init_sedov(16)
        ccfg = CoolingConfig(gamma=const.gamma, evolve_species=True)
        chem = ChemistryData.ionized(state.n)
        cfg = make_propagator_config(state, box, const, block=512,
                                     backend="pallas")
        ref_state, _, _, ref_chem = step_hydro_std_cooling(
            state, box, cfg, None, chem, ccfg
        )
        # the network actually moved the fractions off the ionized IC
        assert float(jnp.max(jnp.abs(ref_chem.hi - chem.hi))) > 0.0

        mesh = make_mesh(8)
        sstate = shard_state(state, mesh)
        schem = shard_state(chem, mesh)
        step = make_sharded_step(mesh, cfg, step_fn=step_hydro_std_cooling,
                                 aux_cfg=ccfg)
        out_state, _, _, out_chem = step(sstate, box, None, schem)
        assert out_chem.hi.sharding.spec == jax.sharding.PartitionSpec("p")
        np.testing.assert_allclose(
            np.asarray(out_chem.hi), np.asarray(ref_chem.hi),
            rtol=1e-5, atol=1e-8,
        )
        np.testing.assert_allclose(
            np.asarray(out_chem.e), np.asarray(ref_chem.e),
            rtol=1e-5, atol=1e-8,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.temp), np.asarray(ref_state.temp),
            rtol=1e-4, atol=1e-7,
        )
