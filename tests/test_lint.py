"""jaxlint: rule fixtures, suppressions, baseline round-trip, CLI, and
the tier-1 gate that keeps sphexa_tpu/ clean.

Fixture contract: every file under tests/lint_fixtures/ carries
``# expect: JXLxxx`` markers on the lines that must produce findings
(repeat the code for multiple findings on one line); the test fails on
both missed findings AND unexpected ones, so rule false positives break
CI the same way false negatives do.
"""

import json
import re
from pathlib import Path

import pytest

from sphexa_tpu.devtools.lint import Analyzer, Baseline, all_rules
from sphexa_tpu.devtools.lint.cli import main as lint_main
from sphexa_tpu.devtools.lint.core import _DISABLE_RE, ModuleInfo

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)")


def expected_findings(path: Path):
    """[(line, rule)] from # expect: markers."""
    out = []
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for code in m.group(1).split(","):
                out.append((i, code.strip()))
    return sorted(out)


def run_file(path: Path):
    return Analyzer().run_module(ModuleInfo.from_file(str(path)))


FIXTURE_FILES = sorted(
    p.relative_to(FIXTURES).as_posix() for p in FIXTURES.rglob("*.py")
)


def test_rule_registry_complete():
    rules = all_rules()
    assert sorted(rules) == ["JXL001", "JXL002", "JXL003", "JXL004",
                             "JXL005", "JXL006", "JXL007"]
    for rule in rules.values():
        assert rule.description


@pytest.mark.parametrize("rel", FIXTURE_FILES)
def test_fixture_findings_exact(rel):
    """Each fixture's active findings == its # expect: markers, exactly."""
    path = FIXTURES / rel
    active, _suppressed = run_file(path)
    actual = sorted((f.line, f.rule) for f in active)
    expected = expected_findings(path)
    assert actual == expected, (
        f"{rel}: findings disagree with markers\n"
        f"  unexpected: {sorted(set(actual) - set(expected))}\n"
        f"  missed:     {sorted(set(expected) - set(actual))}\n"
        + "\n".join(f.format() for f in active)
    )


def test_inline_suppression_swallows_finding():
    active, suppressed = run_file(FIXTURES / "jxl002_host_sync.py")
    sup_lines = [(f.rule, "item()" in f.snippet) for f in suppressed]
    assert ("JXL002", True) in sup_lines, (
        "the # jaxlint: disable=JXL002 item() sync should be suppressed, "
        f"got suppressed={sup_lines}"
    )
    # and it must NOT be double-reported as active
    assert all("suppressed_sync" not in f.snippet for f in active)


def test_same_line_and_file_wide_suppression(tmp_path):
    src = (
        "import jax.numpy as jnp\n"
        "A = jnp.zeros(3)  # jaxlint: disable=JXL001 -- test constant\n"
        "B = jnp.ones(3)\n"
    )
    p = tmp_path / "m.py"
    p.write_text(src)
    active, suppressed = run_file(p)
    assert [f.line for f in active] == [3]
    assert [f.line for f in suppressed] == [2]

    p.write_text("# jaxlint: disable-file=JXL001 -- generated module\n"
                 + src.replace("  # jaxlint: disable=JXL001 -- test constant",
                               ""))
    active, suppressed = run_file(p)
    assert active == []
    assert len(suppressed) == 2


def test_suppression_survives_intervening_plain_comment(tmp_path):
    p = tmp_path / "m.py"
    p.write_text(
        "import jax.numpy as jnp\n"
        "# jaxlint: disable=JXL001 -- deliberate import-time table\n"
        "# (precomputed here on purpose; see docs)\n"
        "TABLE = jnp.zeros(3)\n"
    )
    active, suppressed = run_file(p)
    assert active == [] and [f.line for f in suppressed] == [4]


def test_unknown_rule_selection_rejected():
    with pytest.raises(ValueError):
        Analyzer(select=["JXL999"])


def test_select_limits_rules():
    active, _sup, _err = Analyzer(select=["JXL001"]).run_paths(
        [str(FIXTURES / "jxl002_host_sync.py")]
    )
    # JXL001 alone finds nothing in the host-sync fixture
    assert active == []


def test_baseline_roundtrip(tmp_path):
    fixture = FIXTURES / "jxl001_module_level.py"
    active, _, _ = Analyzer().run_paths([str(fixture)])
    assert active, "fixture must produce findings for this test"

    bl_path = tmp_path / "baseline.json"
    Baseline.from_findings(active).save(str(bl_path))
    loaded = Baseline.load(str(bl_path))
    new, grandfathered = loaded.filter_new(active)
    assert new == [] and len(grandfathered) == len(active)

    # a brand-new finding is NOT absorbed by the baseline
    extra = tmp_path / "extra.py"
    extra.write_text("import jax.numpy as jnp\nC = jnp.zeros(4)\n")
    active2, _, _ = Analyzer().run_paths([str(fixture), str(extra)])
    new2, _ = loaded.filter_new(active2)
    assert [f.path for f in new2] == [extra.as_posix()]

    # consuming semantics: a DUPLICATE of a baselined line is new
    dup = tmp_path / "dup.py"
    line = "K = jnp.uint32(1 << 30)\n"
    dup.write_text("import jax.numpy as jnp\n" + line + line)
    active3, _, _ = Analyzer().run_paths([str(dup)])
    assert len(active3) == 2
    bl3 = Baseline.from_findings(active3[:1])  # grandfather ONE copy
    new3, old3 = bl3.filter_new(active3)
    assert len(new3) == 1 and len(old3) == 1


def test_baseline_missing_file_is_empty(tmp_path):
    bl = Baseline.load(str(tmp_path / "nope.json"))
    assert bl.entries == {}


def test_cli_text_json_and_exit_codes(tmp_path, capsys):
    dirty = FIXTURES / "jxl001_module_level.py"
    clean = tmp_path / "clean.py"
    clean.write_text("import numpy as np\nA = np.zeros(3)\n")

    assert lint_main([str(clean)]) == 0
    assert lint_main([str(dirty)]) == 1
    capsys.readouterr()

    assert lint_main([str(dirty), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] and payload["errors"] == []
    assert {f["rule"] for f in payload["findings"]} == {"JXL001"}

    # baseline workflow through the CLI: grandfather, then gate passes
    bl = tmp_path / "bl.json"
    assert lint_main([str(dirty), "--baseline", str(bl),
                      "--update-baseline"]) == 0
    capsys.readouterr()
    assert lint_main([str(dirty), "--baseline", str(bl)]) == 0

    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JXL001" in out and "JXL005" in out


def test_cli_reports_parse_errors(tmp_path, capsys):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert lint_main([str(broken)]) == 1
    assert "JXL000" in capsys.readouterr().out


def test_cli_usage_errors(tmp_path):
    assert lint_main(["--select", "NOPE1", "x.py"]) == 2
    assert lint_main(["--update-baseline", "x.py"]) == 2
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    assert lint_main([str(FIXTURES), "--baseline", str(corrupt)]) == 2


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------


def test_package_is_lint_clean():
    """sphexa_tpu/ must stay free of non-suppressed findings — the
    acceptance gate. Fix the finding, or (for a deliberate pattern) add
    `# jaxlint: disable=JXLxxx -- reason` on the line."""
    active, _suppressed, errors = Analyzer().run_paths(
        [str(REPO_ROOT / "sphexa_tpu")]
    )
    msgs = "\n".join(f.format() + ("\n    " + f.snippet if f.snippet else "")
                     for f in errors + active)
    assert not errors and not active, (
        f"jaxlint found {len(active)} finding(s) / {len(errors)} parse "
        f"error(s) in sphexa_tpu/:\n{msgs}"
    )


def test_suppressions_in_package_carry_reasons():
    """Every inline disable in the package must say WHY (-- reason)."""
    bad = []
    for p in (REPO_ROOT / "sphexa_tpu").rglob("*.py"):
        for i, line in enumerate(p.read_text().splitlines(), start=1):
            m = _DISABLE_RE.search(line)
            if m and not (m.group("reason") or "").strip():
                bad.append(f"{p}:{i}: {line.strip()}")
    assert not bad, "suppressions without a reason:\n" + "\n".join(bad)
