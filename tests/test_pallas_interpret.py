"""Pallas engine equivalence vs the XLA gather path in INTERPRET mode.

Runs on the plain CPU test mesh on every suite run, so the engine's
cell-range/DMA-offset/masking logic is exercised without TPU hardware
(the device tier, tests/test_pallas_tpu.py, stays the Mosaic-lowering
check). Mirrors the reference's CPU/GPU equivalence strategy
(domain/test/unit_cuda/) with ``interpret=True`` standing in for the GPU.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov, init_noh
from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp


def _setup(init, side):
    state, box, const = init(side)
    cfg = make_propagator_config(state, box, const, block=4096, backend="pallas")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    return ss, keys, box, const, cfg.nbr


# sedov 14^3 is periodic+tiny -> exercises the per-pair fold path;
# noh has open boundaries -> exercises the per-cell shift path + window
# sliding at the grid edge
CASES = [(init_sedov, 14), (init_noh, 12)]


@pytest.fixture(scope="module", params=CASES, ids=["sedov", "noh"])
def case(request):
    init, side = request.param
    return _setup(init, side)


def test_density_matches_xla_interpret(case):
    ss, keys, box, const, nbr = case
    nidx, nmask, nc0, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    rho0 = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    rho1, nc1, occ = pp.pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, keys, box, const, nbr, interpret=True
    )
    assert int(occ) <= nbr.cap
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0), rtol=1e-5)


@pytest.mark.slow
def test_pipeline_matches_xla_interpret(case):
    ss, keys, box, const, nbr = case
    nidx, nmask, _, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    rho = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    p, c = hydro_std.compute_eos_std(ss.temp, rho, const)
    cs0 = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, nidx, nmask, box, const, 4096
    )
    cs1, _ = pp.pallas_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, keys, box, const, nbr,
        interpret=True,
    )
    # IAD diagonals match relatively; off-diagonals are ~0 on the lattice
    # (catastrophic cancellation), so compare on the diagonal scale — same
    # criterion as the TPU device tier
    scale = float(jnp.max(jnp.abs(cs0[0])))
    for a, b in zip(cs1, cs0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5 * scale
        )

    out0 = hydro_std.compute_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs0, nidx, nmask, box, const, 4096,
    )
    out1 = pp.pallas_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs0, keys, box, const, nbr, interpret=True,
    )
    names = ["ax", "ay", "az", "du"]
    for name, a, b in zip(names, out1[:4], out0[:4]):
        s = float(jnp.max(jnp.abs(np.asarray(b)))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=5e-6 * s,
            err_msg=name,
        )
    assert float(out1[4]) == pytest.approx(float(out0[4]), rel=1e-5)


@pytest.mark.parametrize("av_clean", [False, True], ids=["plain", "avclean"])
@pytest.mark.slow
def test_ve_pipeline_matches_xla_interpret(case, av_clean):
    from sphexa_tpu.sph import hydro_ve

    ss, keys, box, const, nbr = case
    nidx, nmask, nc, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    args = (ss.x, ss.y, ss.z, ss.h, ss.m)

    xm0 = hydro_ve.compute_xmass(*args, nidx, nmask, box, const, 4096)
    xm1, nc1, _ = pp.pallas_xmass(*args, keys, box, const, nbr, interpret=True)
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc))
    np.testing.assert_allclose(np.asarray(xm1), np.asarray(xm0), rtol=1e-5)

    kx0, gradh0 = hydro_ve.compute_ve_def_gradh(
        *args, xm0, nidx, nmask, box, const, 4096
    )
    (kx1, gradh1), _ = pp.pallas_ve_def_gradh(
        *args, xm0, keys, box, const, nbr, interpret=True
    )
    np.testing.assert_allclose(np.asarray(kx1), np.asarray(kx0), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gradh1), np.asarray(gradh0), rtol=5e-4, atol=1e-5
    )

    prho, c, rho, p = hydro_ve.compute_eos_ve(
        ss.temp, ss.m, kx0, xm0, gradh0, const
    )
    cs = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, xm0 / kx0, nidx, nmask, box, const, 4096
    )

    dv0 = hydro_ve.compute_iad_divv_curlv(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, kx0, xm0, *cs,
        nidx, nmask, box, const, 4096, with_gradv=av_clean,
    )
    dv1, _ = pp.pallas_iad_divv_curlv(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, kx0, xm0, *cs,
        keys, box, const, nbr, with_gradv=av_clean, interpret=True,
    )
    # divv/curlv are ~0 on the initial lattice (cancellation): absolute
    # tolerance on the kernel-sum scale
    for a, b in zip(dv1, dv0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=5e-4
        )
    divv = dv0[0]
    gradv = tuple(dv0[2:]) if av_clean else None

    alpha0 = hydro_ve.compute_av_switches(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, c, kx0, xm0, divv,
        ss.alpha, *cs, nidx, nmask, box, ss.min_dt, const, 4096,
    )
    alpha1, _ = pp.pallas_av_switches(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, c, kx0, xm0, divv,
        ss.alpha, *cs, keys, box, ss.min_dt, const, nbr, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(alpha1), np.asarray(alpha0), rtol=1e-4, atol=1e-6
    )

    me0 = hydro_ve.compute_momentum_energy_ve(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, prho, c,
        kx0, xm0, alpha0, *cs, nidx, nmask, nc, box, const, 4096,
        gradv=gradv,
    )
    *me1, _ = pp.pallas_momentum_energy_ve(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, prho, c,
        kx0, xm0, alpha0, *cs, keys, box, const, nbr, nc=nc,
        gradv=gradv, interpret=True,
    )
    for name, a, b in zip(["ax", "ay", "az", "du"], me1[:4], me0[:4]):
        s = float(np.max(np.abs(np.asarray(b)))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5 * s,
            err_msg=name,
        )
    assert float(me1[4]) == pytest.approx(float(me0[4]), rel=1e-4)


def test_gravity_compact_kernel_interpret():
    """Bitmask+popcount-rank compaction kernel (gravity/pallas_compact.py)
    vs a numpy reference: candidate-order lists, true (unclipped) counts,
    cap truncation, 128-lane staging wrap, and tail padding — the
    interpret-mode smoke that rides the tier-1 CPU gate."""
    from sphexa_tpu.gravity import pallas_compact as pc

    rng = np.random.default_rng(7)
    # (B, C, cap0, cap1): non-multiple-of-128 caps/widths exercise the
    # pad/trim paths; cap < count exercises truncation + the unclipped
    # count contract; C < 128 exercises the single-chunk tail
    for B, C, cap0, cap1 in ((4, 1000, 192, 64), (1, 90, 8, 8),
                             (3, 513, 256, 48)):
        cls = rng.integers(0, 3, size=(B, C))
        vals = rng.integers(0, 1 << 20, size=(B, C))
        packed = jnp.asarray((cls << pc.IDX_BITS) | vals, jnp.int32)
        l0, n0, l1, n1 = pc.compact_class_lists(
            packed, cap0, cap1, interpret=True
        )
        for b in range(B):
            for lst, cnt, cap, k in ((l0, n0, cap0, 0), (l1, n1, cap1, 1)):
                exp = vals[b][cls[b] == k]
                assert int(cnt[b]) == len(exp)
                kept = min(len(exp), cap)
                np.testing.assert_array_equal(
                    np.asarray(lst[b][:kept]), exp[:kept]
                )
                # slots beyond the count stay zeroed (masked by callers)
                assert np.all(np.asarray(lst[b][kept:]) == 0)


def test_gravity_p2p_pallas_matches_xla_interpret():
    """Streamed near-field P2P (gravity/traversal._pallas_p2p) vs the XLA
    gather formulation, both through compute_gravity."""
    import dataclasses

    from sphexa_tpu.gravity.traversal import (
        GravityConfig,
        compute_gravity,
        estimate_gravity_caps,
    )
    from sphexa_tpu.gravity.tree import build_gravity_tree
    from sphexa_tpu.init import init_evrard
    from sphexa_tpu.sfc.box import make_global_box

    state, box, const = init_evrard(16)
    box = make_global_box(state.x, state.y, state.z, box)
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    gtree, meta = build_gravity_tree(np.asarray(keys), bucket_size=64)
    cfg0 = estimate_gravity_caps(
        ss.x, ss.y, ss.z, ss.m, keys, box, gtree, meta,
        GravityConfig(theta=0.5, G=1.0),
    )
    out0 = compute_gravity(
        ss.x, ss.y, ss.z, ss.m, ss.h, keys, box, gtree, meta, cfg0
    )
    cfg1 = dataclasses.replace(cfg0, use_pallas=True)
    out1 = compute_gravity(
        ss.x, ss.y, ss.z, ss.m, ss.h, keys, box, gtree, meta, cfg1
    )
    for name, a, b in zip(("ax", "ay", "az", "egrav"), out1[:4], out0[:4]):
        sa, sb = np.asarray(a), np.asarray(b)
        scale = np.max(np.abs(sb)) + 1e-12
        np.testing.assert_allclose(sa, sb, atol=1e-6 * scale, rtol=1e-4,
                                   err_msg=name)
