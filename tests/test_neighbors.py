"""Neighbor-search correctness vs brute-force all-pairs reference, mirroring
the reference's unit/neighbors/findneighbors.cpp + all_to_all.hpp strategy.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.sfc import Box, BoundaryType, compute_sfc_keys
from sphexa_tpu.neighbors import (
    NeighborConfig,
    choose_grid_level,
    estimate_cell_cap,
    find_neighbors,
)
from sphexa_tpu.neighbors.cell_list import estimate_group_window


def make_config(x, y, z, h, keys, box, level, cap, ngmax, block=256):
    window = estimate_group_window(
        x, y, z, h, np.asarray(box.lengths), level, group=64
    )
    return NeighborConfig(
        level=level, cap=cap, ngmax=ngmax, block=block, window=window
    )


def brute_force_neighbors(x, y, z, h, box: Box):
    """All-pairs reference (mirrors unit/neighbors/all_to_all.hpp)."""
    pos = np.stack([x, y, z], axis=1).astype(np.float64)
    d = pos[:, None, :] - pos[None, :, :]
    L = np.asarray(box.lengths, dtype=np.float64)
    per = np.asarray(box.periodic_mask)
    d = np.where(per, d - L * np.round(d / L), d)
    d2 = (d**2).sum(-1)
    r2 = (2.0 * np.asarray(h, dtype=np.float64)) ** 2
    hit = d2 < r2[:, None]
    np.fill_diagonal(hit, False)
    return hit


def setup_case(rng, n, boundary, h_val=0.08):
    box = Box.create(-0.5, 0.5, boundary=boundary)
    x = rng.uniform(-0.5, 0.5, n).astype(np.float32)
    y = rng.uniform(-0.5, 0.5, n).astype(np.float32)
    z = rng.uniform(-0.5, 0.5, n).astype(np.float32)
    h = np.full(n, h_val, np.float32)
    keys = np.asarray(compute_sfc_keys(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys, kind="stable")
    return box, x[order], y[order], z[order], h[order], np.sort(keys)


def run_and_compare(rng, n, boundary, h_val=0.08):
    box, x, y, z, h, keys = setup_case(rng, n, boundary, h_val)
    level = choose_grid_level(np.asarray(box.lengths), h.max())
    cap = estimate_cell_cap(keys, level)
    cfg = make_config(x, y, z, h, keys, box, level, cap, ngmax=200)
    nidx, nmask, nc, occ = find_neighbors(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), jnp.asarray(h),
        jnp.asarray(keys), box, cfg,
    )
    assert int(occ) <= cap, "cell cap overflow"
    ref = brute_force_neighbors(x, y, z, h, box)

    nidx, nmask, nc = np.asarray(nidx), np.asarray(nmask), np.asarray(nc)
    np.testing.assert_array_equal(nc, ref.sum(1), err_msg="neighbor counts differ")
    for i in range(n):
        got = set(nidx[i][nmask[i]])
        expect = set(np.flatnonzero(ref[i]))
        assert got == expect, f"particle {i}: missing {expect-got}, extra {got-expect}"


class TestFindNeighbors:
    def test_periodic_box(self, rng):
        run_and_compare(rng, 500, BoundaryType.periodic)

    def test_open_box(self, rng):
        run_and_compare(rng, 500, BoundaryType.open)

    def test_large_h_coarse_grid(self, rng):
        # big search radius -> level 1 grid, stencil covers whole box
        run_and_compare(rng, 200, BoundaryType.periodic, h_val=0.2)

    def test_varying_h(self, rng):
        box, x, y, z, h, keys = setup_case(rng, 400, BoundaryType.periodic)
        h = (0.04 + 0.04 * rng.uniform(size=400)).astype(np.float32)
        level = choose_grid_level(np.asarray(box.lengths), h.max())
        cap = estimate_cell_cap(keys, level)
        cfg = make_config(x, y, z, h, keys, box, level, cap, ngmax=300, block=128)
        nidx, nmask, nc, occ = find_neighbors(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), jnp.asarray(h),
            jnp.asarray(keys), box, cfg,
        )
        ref = brute_force_neighbors(x, y, z, h, box)
        np.testing.assert_array_equal(np.asarray(nc), ref.sum(1))

    def test_ngmax_truncation(self, rng):
        """Truncation semantics follow the reference CPU search
        (findneighbors.hpp): nc reports the true count, the list keeps the
        first ngmax found (no distance sort)."""
        box, x, y, z, h, keys = setup_case(rng, 300, BoundaryType.periodic, h_val=0.15)
        level = choose_grid_level(np.asarray(box.lengths), h.max())
        cap = estimate_cell_cap(keys, level)
        cfg = make_config(x, y, z, h, keys, box, level, cap, ngmax=10, block=64)
        nidx, nmask, nc, _ = find_neighbors(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), jnp.asarray(h),
            jnp.asarray(keys), box, cfg,
        )
        nidx, nmask, nc = np.asarray(nidx), np.asarray(nmask), np.asarray(nc)
        ref = brute_force_neighbors(x, y, z, h, box)
        # counts still report the true (untruncated) number
        np.testing.assert_array_equal(nc, ref.sum(1))
        for i in range(0, 300, 17):
            got = set(nidx[i][nmask[i]])
            expect = set(np.flatnonzero(ref[i]))
            # kept neighbors are true neighbors, exactly min(nc, ngmax) many
            assert got <= expect, f"particle {i}: spurious {got - expect}"
            assert len(got) == min(nc[i], 10)

    def test_empty_regions(self, rng):
        # particles only in one octant; empty cells must not break anything
        box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
        x = rng.uniform(-0.5, -0.3, 200).astype(np.float32)
        y = rng.uniform(-0.5, -0.3, 200).astype(np.float32)
        z = rng.uniform(-0.5, -0.3, 200).astype(np.float32)
        h = np.full(200, 0.03, np.float32)
        keys = np.asarray(compute_sfc_keys(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
        order = np.argsort(keys, kind="stable")
        x, y, z, h, keys = x[order], y[order], z[order], h[order], np.sort(keys)
        level = choose_grid_level(np.asarray(box.lengths), h.max())
        cap = estimate_cell_cap(keys, level)
        cfg = make_config(x, y, z, h, keys, box, level, cap, ngmax=100, block=64)
        nidx, nmask, nc, _ = find_neighbors(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), jnp.asarray(h),
            jnp.asarray(keys), box, cfg,
        )
        ref = brute_force_neighbors(x, y, z, h, box)
        np.testing.assert_array_equal(np.asarray(nc), ref.sum(1))
