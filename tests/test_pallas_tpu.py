"""Pallas engine vs XLA path equivalence — runs only on real TPU hardware
(the Mosaic kernels don't lower on the CPU test mesh). The CPU suite
covers the XLA path; this file is the device-equivalence tier, mirroring
the reference's CPU/GPU equivalence tests (domain/test/unit_cuda/).

Run manually on TPU:  python -m pytest tests/test_pallas_tpu.py -q
"""

import numpy as np
import pytest

import jax

if jax.default_backend() != "tpu":  # pragma: no cover
    pytest.skip("pallas TPU kernels need real TPU hardware", allow_module_level=True)

import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph.pallas_pairs import (
    group_cell_ranges,
    pallas_density,
    pallas_iad,
    pallas_momentum_energy_std,
)


@pytest.fixture(scope="module")
def case():
    state, box, const = init_sedov(20)
    cfg = make_propagator_config(state, box, const, block=4096, backend="pallas")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    return ss, keys, box, const, cfg


def test_density_matches_xla(case):
    ss, keys, box, const, cfg = case
    nidx, nmask, nc0, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    rho0 = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    rho1, nc1, occ = pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, keys, box, const, cfg.nbr
    )
    assert int(occ) <= cfg.nbr.cap
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))


def test_full_pipeline_matches_xla(case):
    ss, keys, box, const, cfg = case
    nidx, nmask, _, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    rho = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    p, c = hydro_std.compute_eos_std(ss.temp, rho, const)
    cs0 = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, nidx, nmask, box, const, 4096
    )
    me0 = hydro_std.compute_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs0, nidx, nmask, box, const, 4096,
    )

    ranges = group_cell_ranges(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    cs1, _ = pallas_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, keys, box, const, cfg.nbr,
        ranges=ranges,
    )
    *me1, _ = pallas_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs1, keys, box, const, cfg.nbr, ranges=ranges,
    )
    # IAD diagonal terms match relatively; off-diagonals are ~0 so compare
    # on the diagonal scale
    scale = float(jnp.max(jnp.abs(cs0[0])))
    for a, b in zip(cs0, cs1):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5 * scale, rtol=1e-4
        )
    for a, b in zip(me0[:4], me1[:4]):
        s = float(jnp.max(jnp.abs(a))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-6 * s, rtol=1e-4
        )
    assert float(me1[4]) == pytest.approx(float(me0[4]), rel=1e-5)


@pytest.mark.parametrize("av_clean", [False, True], ids=["plain", "avclean"])
def test_ve_pipeline_matches_xla_tpu(case, av_clean):
    """Mosaic-lowering check for the six VE engine ops (the interpret tier
    covers the logic; this tier covers the TPU compile + execution),
    including the avClean variant's bigger kernel (9 accumulators,
    nf_pad=32 packing)."""
    from sphexa_tpu.sph import hydro_ve
    from sphexa_tpu.sph.pallas_pairs import (
        pallas_av_switches,
        pallas_iad_divv_curlv,
        pallas_momentum_energy_ve,
        pallas_ve_def_gradh,
        pallas_xmass,
    )

    ss, keys, box, const, cfg = case
    nbr = cfg.nbr
    nidx, nmask, nc, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    args = (ss.x, ss.y, ss.z, ss.h, ss.m)

    xm0 = hydro_ve.compute_xmass(*args, nidx, nmask, box, const, 4096)
    xm1, nc1, _ = pallas_xmass(*args, keys, box, const, nbr)
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc))
    np.testing.assert_allclose(np.asarray(xm1), np.asarray(xm0), rtol=1e-5)

    kx0, gradh0 = hydro_ve.compute_ve_def_gradh(
        *args, xm0, nidx, nmask, box, const, 4096
    )
    (kx1, gradh1), _ = pallas_ve_def_gradh(*args, xm0, keys, box, const, nbr)
    np.testing.assert_allclose(np.asarray(kx1), np.asarray(kx0), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gradh1), np.asarray(gradh0), rtol=5e-4, atol=1e-5
    )

    prho, c, rho, p = hydro_ve.compute_eos_ve(
        ss.temp, ss.m, kx0, xm0, gradh0, const
    )
    cs = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, xm0 / kx0, nidx, nmask, box, const, 4096
    )
    dv0 = hydro_ve.compute_iad_divv_curlv(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, kx0, xm0, *cs,
        nidx, nmask, box, const, 4096, with_gradv=av_clean,
    )
    dv1, _ = pallas_iad_divv_curlv(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, kx0, xm0, *cs,
        keys, box, const, nbr, with_gradv=av_clean,
    )
    for a, b in zip(dv1, dv0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=5e-4
        )
    divv = dv0[0]
    gradv = tuple(dv0[2:]) if av_clean else None

    alpha0 = hydro_ve.compute_av_switches(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, c, kx0, xm0, divv,
        ss.alpha, *cs, nidx, nmask, box, ss.min_dt, const, 4096,
    )
    alpha1, _ = pallas_av_switches(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, c, kx0, xm0, divv,
        ss.alpha, *cs, keys, box, ss.min_dt, const, nbr,
    )
    np.testing.assert_allclose(
        np.asarray(alpha1), np.asarray(alpha0), rtol=1e-4, atol=1e-6
    )

    me0 = hydro_ve.compute_momentum_energy_ve(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, prho, c,
        kx0, xm0, alpha0, *cs, nidx, nmask, nc, box, const, 4096,
        gradv=gradv,
    )
    *me1, _ = pallas_momentum_energy_ve(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, prho, c,
        kx0, xm0, alpha0, *cs, keys, box, const, nbr, nc=nc, gradv=gradv,
    )
    for name, a, b in zip(["ax", "ay", "az", "du"], me1[:4], me0[:4]):
        s = float(np.max(np.abs(np.asarray(b)))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5 * s,
            err_msg=name,
        )
