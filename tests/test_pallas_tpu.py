"""Pallas engine vs XLA path equivalence — runs only on real TPU hardware
(the Mosaic kernels don't lower on the CPU test mesh). The CPU suite
covers the XLA path; this file is the device-equivalence tier, mirroring
the reference's CPU/GPU equivalence tests (domain/test/unit_cuda/).

Run manually on TPU:  python -m pytest tests/test_pallas_tpu.py -q
"""

import numpy as np
import pytest

import jax

if jax.default_backend() != "tpu":  # pragma: no cover
    pytest.skip("pallas TPU kernels need real TPU hardware", allow_module_level=True)

import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph.pallas_pairs import (
    group_cell_ranges,
    pallas_density,
    pallas_iad,
    pallas_momentum_energy_std,
)


@pytest.fixture(scope="module")
def case():
    state, box, const = init_sedov(20)
    cfg = make_propagator_config(state, box, const, block=4096, backend="pallas")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    return ss, keys, box, const, cfg


def test_density_matches_xla(case):
    ss, keys, box, const, cfg = case
    nidx, nmask, nc0, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    rho0 = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    rho1, nc1, occ = pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, keys, box, const, cfg.nbr
    )
    assert int(occ) <= cfg.nbr.cap
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))


def test_full_pipeline_matches_xla(case):
    ss, keys, box, const, cfg = case
    nidx, nmask, _, _ = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    rho = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )
    p, c = hydro_std.compute_eos_std(ss.temp, rho, const)
    cs0 = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, nidx, nmask, box, const, 4096
    )
    me0 = hydro_std.compute_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs0, nidx, nmask, box, const, 4096,
    )

    ranges = group_cell_ranges(ss.x, ss.y, ss.z, ss.h, keys, box, cfg.nbr)
    cs1, _ = pallas_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho, keys, box, const, cfg.nbr,
        ranges=ranges,
    )
    *me1, _ = pallas_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho, p, c,
        *cs1, keys, box, const, cfg.nbr, ranges=ranges,
    )
    # IAD diagonal terms match relatively; off-diagonals are ~0 so compare
    # on the diagonal scale
    scale = float(jnp.max(jnp.abs(cs0[0])))
    for a, b in zip(cs0, cs1):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5 * scale, rtol=1e-4
        )
    for a, b in zip(me0[:4], me1[:4]):
        s = float(jnp.max(jnp.abs(a))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-6 * s, rtol=1e-4
        )
    assert float(me1[4]) == pytest.approx(float(me0[4]), rel=1e-5)
