"""Gravity solver tests: multipole identities + Barnes-Hut vs direct sum.

Mirrors the reference's test strategy (SURVEY.md §4): ryoanji validates
multipole consistency (test/nbody/kernel.cpp, cartesian_qpole.cpp) and the
full tree solver against direct summation on a Plummer sphere
(test/nbody/traversal_cpu.cpp, coord_samples/plummer.hpp).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.gravity import (
    GravityConfig,
    build_gravity_tree,
    compute_gravity,
    direct_gravity,
    estimate_gravity_caps,
)
from sphexa_tpu.gravity.traversal import compute_multipoles
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sfc.keys import compute_sfc_keys


def plummer(n, seed=42, a=1.0):
    """Plummer sphere sample (domain/test/coord_samples/plummer.hpp)."""
    rng = np.random.default_rng(seed)
    u = rng.uniform(size=n)
    r = a / np.sqrt(u ** (-2.0 / 3.0) - 1.0)
    r = np.minimum(r, 20.0 * a)
    cost = rng.uniform(-1.0, 1.0, size=n)
    sint = np.sqrt(1.0 - cost**2)
    phi = rng.uniform(0.0, 2.0 * np.pi, size=n)
    x = r * sint * np.cos(phi)
    y = r * sint * np.sin(phi)
    z = r * cost
    m = np.full(n, 1.0 / n)
    return x, y, z, m


def _sorted_system(n=5000, seed=42):
    x, y, z, m = plummer(n, seed)
    lim = float(np.max(np.abs([x, y, z]))) * 1.001
    box = Box.create(-lim, lim)
    keys = np.asarray(compute_sfc_keys(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys)
    x, y, z, m, keys = x[order], y[order], z[order], m[order], keys[order]
    h = np.full(n, 0.02)
    return (
        jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.asarray(z, jnp.float32), jnp.asarray(m, jnp.float32),
        jnp.asarray(h, jnp.float32), jnp.asarray(keys), box,
    )


class TestMultipoles:
    def test_root_monopole_and_com(self):
        """Root node mass/com must equal the whole system's."""
        x, y, z, m, h, keys, box = _sorted_system(3000)
        tree, meta = build_gravity_tree(np.asarray(keys), bucket_size=32)
        nm, com, q, edges = compute_multipoles(x, y, z, m, keys, tree, meta)
        assert np.isclose(float(nm[0]), float(jnp.sum(m)), rtol=1e-5)
        mref = np.array(
            [np.sum(np.asarray(m) * np.asarray(c)) for c in (x, y, z)]
        ) / float(jnp.sum(m))
        np.testing.assert_allclose(np.asarray(com[0]), mref, atol=1e-4)

    def test_root_quadrupole_matches_p2m_from_scratch(self):
        """M2M upsweep == direct P2M of all particles about the root com.

        The reference asserts the same identity in
        ryoanji/test/nbody/upsweep_cpu.cpp.
        """
        x, y, z, m, h, keys, box = _sorted_system(2000)
        tree, meta = build_gravity_tree(np.asarray(keys), bucket_size=32)
        nm, com, q, edges = compute_multipoles(x, y, z, m, keys, tree, meta)

        xa, ya, za, ma = (np.asarray(v, np.float64) for v in (x, y, z, m))
        cx, cy, cz = (np.asarray(com[0], np.float64)[i] for i in range(3))
        dx, dy, dz = xa - cx, ya - cy, za - cz
        raw = np.array(
            [np.sum(ma * dx * dx), np.sum(ma * dx * dy), np.sum(ma * dx * dz),
             np.sum(ma * dy * dy), np.sum(ma * dy * dz), np.sum(ma * dz * dz)]
        )
        tr = raw[0] + raw[3] + raw[5]
        ref = np.array([3 * raw[0] - tr, 3 * raw[1], 3 * raw[2],
                        3 * raw[3] - tr, 3 * raw[4], 3 * raw[5] - tr, tr])
        scale = max(1.0, np.abs(ref).max())
        np.testing.assert_allclose(np.asarray(q[0]) / scale, ref / scale, atol=2e-3)

    def test_leaf_edges_partition_particles(self):
        x, y, z, m, h, keys, box = _sorted_system(1000)
        tree, meta = build_gravity_tree(np.asarray(keys), bucket_size=16)
        nm, com, q, edges = compute_multipoles(x, y, z, m, keys, tree, meta)
        e = np.asarray(edges)
        assert e[0] == 0 and e[-1] == 1000
        assert np.all(np.diff(e) >= 0)


class TestTreeVsDirect:
    @pytest.mark.parametrize("theta", [0.5, 0.8])
    def test_plummer_accelerations(self, theta):
        """Relative force error vs direct sum; tolerance mirrors the
        reference's traversal_cpu.cpp direct-sum comparison."""
        x, y, z, m, h, keys, box = _sorted_system(5000)
        cfg = GravityConfig(theta=theta, bucket_size=64)
        tree, meta = build_gravity_tree(np.asarray(keys), cfg.bucket_size)
        cfg = estimate_gravity_caps(x, y, z, m, keys, box, tree, meta, cfg)
        ax, ay, az, egrav, diag = compute_gravity(
            x, y, z, m, h, keys, box, tree, meta, cfg
        )
        assert int(diag["m2p_max"]) <= cfg.m2p_cap, "m2p cap overflow"
        assert int(diag["p2p_max"]) <= cfg.p2p_cap, "p2p cap overflow"
        assert int(diag["leaf_occ"]) <= cfg.leaf_cap, "leaf cap overflow"

        dax, day, daz, degrav = direct_gravity(x, y, z, m, h)
        a_err = np.sqrt(
            np.asarray((ax - dax) ** 2 + (ay - day) ** 2 + (az - daz) ** 2)
        )
        a_ref = np.sqrt(np.asarray(dax**2 + day**2 + daz**2))
        rel = a_err / np.maximum(a_ref, 1e-6)
        # rms relative error well below 1%, worst-case particles < 10%
        assert np.sqrt(np.mean(rel**2)) < (0.01 if theta <= 0.5 else 0.03)
        assert np.percentile(rel, 99) < (0.05 if theta <= 0.5 else 0.15)
        assert np.isclose(float(egrav), float(degrav), rtol=2e-3)

    def test_energy_sign_and_scale(self):
        """Bound Plummer sphere: egrav ~ -3*pi/32 * GM^2/a for a=1."""
        x, y, z, m, h, keys, box = _sorted_system(4000)
        cfg = GravityConfig(theta=0.5)
        tree, meta = build_gravity_tree(np.asarray(keys), cfg.bucket_size)
        cfg = estimate_gravity_caps(x, y, z, m, keys, box, tree, meta, cfg)
        _, _, _, egrav, _ = compute_gravity(x, y, z, m, h, keys, box, tree, meta, cfg)
        assert float(egrav) < 0
        assert -0.6 < float(egrav) < -0.1  # ideal: -3*pi/32 ~ -0.295

    def test_two_bodies_far_apart(self):
        """Monopole limit: two distant points attract like Newton."""
        x = jnp.asarray([0.0, 10.0], jnp.float32)
        y = jnp.asarray([0.0, 0.0], jnp.float32)
        z = jnp.asarray([0.0, 0.0], jnp.float32)
        m = jnp.asarray([2.0, 3.0], jnp.float32)
        h = jnp.asarray([0.1, 0.1], jnp.float32)
        box = Box.create(-11.0, 11.0)
        keys = compute_sfc_keys(x, y, z, box)
        order = jnp.argsort(keys)
        x, y, z, m, h, keys = x[order], y[order], z[order], m[order], h[order], keys[order]
        cfg = GravityConfig(theta=0.5, bucket_size=1, target_block=2, leaf_cap=8,
                            m2p_cap=8, p2p_cap=8)
        tree, meta = build_gravity_tree(np.asarray(keys), cfg.bucket_size)
        ax, ay, az, egrav, _ = compute_gravity(x, y, z, m, h, keys, box, tree, meta, cfg)
        xs = np.asarray(x)
        ms = np.asarray(m)
        # force magnitude m1*m2/r^2, acceleration = m_other/r^2
        for i, j in ((0, 1), (1, 0)):
            expect = ms[j] / (xs[j] - xs[i]) ** 2 * np.sign(xs[j] - xs[i])
            assert np.isclose(float(ax[i]), expect, rtol=1e-4)
        assert np.isclose(float(egrav), -ms[0] * ms[1] / 10.0, rtol=1e-4)


@pytest.fixture(scope="module")
def bitmask_system():
    """One shared 4000-particle Plummer system + sized caps + the dense
    sort-path reference solve (the class below only asserts against it,
    so build it once)."""
    import dataclasses

    x, y, z, m, h, keys, box = _sorted_system(4000)
    cfg = GravityConfig(theta=0.5, bucket_size=64)
    tree, meta = build_gravity_tree(np.asarray(keys), cfg.bucket_size)
    cfg = estimate_gravity_caps(x, y, z, m, keys, box, tree, meta, cfg)
    args = (x, y, z, m, h, keys, box, tree, meta)
    return dataclasses, args, cfg, meta


class TestBitmaskCompaction:
    """Hierarchical bitmask-rank compaction (compaction="bitmask",
    gravity/pallas_compact.py) vs the dense 3-class sort: the ISSUE-1
    acceptance pin is EXACT equivalence — same accepted M2P/P2P sets in
    the same slots, same first-accepted-ancestor classes — so the
    accelerations must match BITWISE, not within a tolerance."""

    def test_dense_bitmask_matches_sort_exactly(self, bitmask_system):
        dc, args, cfg, meta = bitmask_system
        out_s = compute_gravity(*args, cfg)
        out_b = compute_gravity(
            *args, dc.replace(cfg, compaction="bitmask")
        )
        for name, a, b in zip(("ax", "ay", "az", "egrav"),
                              out_s[:4], out_b[:4]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name
            )
        for k in ("m2p_max", "p2p_max", "leaf_occ"):
            assert int(out_s[4][k]) == int(out_b[4][k]), k
        assert int(out_b[4]["compact_width"]) == meta.num_nodes

    def test_hierarchical_bitmask_matches_dense_sort_exactly(
            self, bitmask_system):
        """Two-level superblock pre-pass + kernel compaction vs the
        dense sweep: identical lists (the super candidate cut is
        ancestor-closed and super-accept implies block-accept)."""
        dc, args, cfg, meta = bitmask_system
        out_s = compute_gravity(*args, cfg)
        cfg_h = dc.replace(cfg, compaction="bitmask", super_factor=8,
                           super_cap=meta.num_nodes)
        out_h = compute_gravity(*args, cfg_h)
        for name, a, b in zip(("ax", "ay", "az", "egrav"),
                              out_s[:4], out_h[:4]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name
            )
        d = out_h[4]
        assert int(d["m2p_max"]) == int(out_s[4]["m2p_max"])
        assert int(d["p2p_max"]) == int(out_s[4]["p2p_max"])
        # the pre-pass candidate cut is live and cap-guarded
        assert 0 < int(d["c_max"]) <= meta.num_nodes
        assert int(d["compact_width"]) == min(cfg_h.super_cap,
                                              meta.num_nodes)

    def test_cap_overflow_diagnostic_fires_not_silent(self, bitmask_system):
        """Deliberately undersized caps: both compactions must truncate
        to the SAME prefix (no silent divergence) and the m2p/p2p
        high-water diagnostics must exceed the caps so the Simulation
        driver regrows instead of silently dropping nodes."""
        dc, args, cfg, _meta = bitmask_system
        small = dc.replace(cfg, m2p_cap=32, p2p_cap=8)
        out_s = compute_gravity(*args, small)
        out_b = compute_gravity(
            *args, dc.replace(small, compaction="bitmask")
        )
        assert int(out_b[4]["m2p_max"]) > small.m2p_cap
        assert int(out_b[4]["p2p_max"]) > small.p2p_cap
        assert int(out_b[4]["m2p_max"]) == int(out_s[4]["m2p_max"])
        assert int(out_b[4]["p2p_max"]) == int(out_s[4]["p2p_max"])
        for name, a, b in zip(("ax", "ay", "az"), out_s[:3], out_b[:3]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name
            )
        # the driver-side guard sees these as an overflow and re-sizes
        from sphexa_tpu.simulation import Simulation

        diag = {k: np.asarray(v) for k, v in out_b[4].items()}
        fake = type("S", (), {"gravity_on": True})()
        fake._cfg = type("C", (), {"gravity": small})()
        assert Simulation._gravity_overflowed(fake, diag)

    def test_far_replica_root_accept_bitmask(self, bitmask_system):
        """A far replica shift makes the ROOT pass the MAC; the
        parent-geometry anc re-evaluation must not let the root count as
        its own accepted ancestor (root's parent is itself)."""
        import jax.numpy as jnp

        dc, args, cfg, meta = bitmask_system
        shift = jnp.asarray([50.0, 0.0, 0.0])
        kw = dict(shift=shift, allow_self=jnp.asarray(True))
        out_s = compute_gravity(*args, cfg, **kw)
        cfg_h = dc.replace(cfg, compaction="bitmask", super_factor=8,
                           super_cap=meta.num_nodes)
        out_b = compute_gravity(*args, cfg_h, **kw)
        assert float(out_s[3]) != 0.0
        for name, a, b in zip(("ax", "ay", "az", "egrav"),
                              out_s[:4], out_b[:4]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name
            )
        assert int(out_b[4]["m2p_max"]) == int(out_s[4]["m2p_max"]) >= 1


@pytest.mark.slow
def test_hierarchical_mac_matches_dense():
    """The two-level superblock classification must reproduce the dense
    blocks-x-nodes sweep EXACTLY (super-accept implies block-accept, and
    the candidate list is ancestor-closed), while evaluating far fewer
    MAC tests (VERDICT r2 #4a done-criterion)."""
    import dataclasses

    import jax.numpy as jnp

    from sphexa_tpu.init import init_evrard
    from sphexa_tpu.propagator import _sort_by_keys
    from sphexa_tpu.sfc.box import make_global_box
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_evrard(24, overrides={"G": 1.0})
    sim = Simulation(state, box, const, prop="nbody", block=512)
    cfg = sim._cfg
    gbox = make_global_box(state.x, state.y, state.z, box)
    sstate, keys, _ = _sort_by_keys(state, gbox, cfg.curve)

    def solve(sf):
        # super_cap was estimated for the sf=0 default; size it for the
        # hierarchical run (the c_max <= super_cap guard is what the
        # Simulation driver checks when resizing)
        g = dataclasses.replace(cfg.gravity, G=1.0, super_factor=sf,
                                super_cap=cfg.grav_meta.num_nodes,
                                use_pallas=False)
        return compute_gravity(
            sstate.x, sstate.y, sstate.z, sstate.m, sstate.h, keys, gbox,
            sim._gtree, cfg.grav_meta, g,
        )

    axd, ayd, azd, egd, dd = solve(0)
    axh, ayh, azh, egh, dh = solve(8)
    np.testing.assert_allclose(np.asarray(axh), np.asarray(axd),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(float(egh), float(egd), rtol=1e-6)
    # identical interaction lists -> identical high-water diagnostics
    assert int(dh["m2p_max"]) == int(dd["m2p_max"])
    assert int(dh["p2p_max"]) == int(dd["p2p_max"])
    # the candidate list respects its cap (the overflow guard's domain);
    # the eval-count WIN only appears at large trees (see GravityConfig
    # super_factor notes) — at this toy size the dense sweep is cheaper,
    # which is why super_factor defaults to 0
    assert 0 < int(dh["c_max"]) <= cfg.grav_meta.num_nodes
    assert 0.0 < float(dh["mac_work_ratio"]) <= 1.0


@pytest.mark.slow
def test_hierarchical_mac_far_replica_root_accept():
    """A far replica shift makes the ROOT pass the MAC; the hierarchical
    downsweep must not let the root count as its own accepted ancestor
    (which would silently zero the whole interaction)."""
    import dataclasses

    import jax.numpy as jnp

    from sphexa_tpu.init import init_evrard
    from sphexa_tpu.propagator import _sort_by_keys
    from sphexa_tpu.sfc.box import make_global_box
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_evrard(12, overrides={"G": 1.0})
    sim = Simulation(state, box, const, prop="nbody", block=512)
    cfg = sim._cfg
    gbox = make_global_box(state.x, state.y, state.z, box)
    sstate, keys, _ = _sort_by_keys(state, gbox, cfg.curve)
    shift = jnp.asarray([50.0, 0.0, 0.0])

    def solve(sf):
        g = dataclasses.replace(cfg.gravity, G=1.0, super_factor=sf,
                                super_cap=cfg.grav_meta.num_nodes,
                                use_pallas=False)
        return compute_gravity(
            sstate.x, sstate.y, sstate.z, sstate.m, sstate.h, keys, gbox,
            sim._gtree, cfg.grav_meta, g,
            shift=shift, allow_self=jnp.asarray(True),
        )

    axd, _, _, egd, dd = solve(0)
    axh, _, _, egh, dh = solve(8)
    assert float(egd) != 0.0
    np.testing.assert_allclose(float(egh), float(egd), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(axh), np.asarray(axd),
                               rtol=1e-5, atol=1e-9)
    assert int(dh["m2p_max"]) == int(dd["m2p_max"]) >= 1


def test_let_classification_equivalence_at_scale():
    """LET correctness where the essential set is a STRICT subset of the
    tree (VERDICT r4 #5; at tiny CI trees the slab bbox opens everything
    and the sharded-equivalence tests cannot see a pruning bug): the
    per-block m2p/p2p sets classified THROUGH the slab essential list
    must equal the dense full-tree classification, node for node."""
    import numpy as np

    from sphexa_tpu.gravity.traversal import compute_multipoles
    from sphexa_tpu.gravity.tree import build_gravity_tree
    from sphexa_tpu.init.plummer import sample_plummer
    from sphexa_tpu.sfc.box import BoundaryType, Box
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    import jax.numpy as jnp

    n = 200_000
    x, y, z, m = sample_plummer(n)
    r = float(np.max(np.abs(np.stack([x, y, z])))) * 1.001
    box = Box.create(-r, r, boundary=BoundaryType.open)
    keys = np.asarray(compute_sfc_keys(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (a[order] for a in (x, y, z, m))
    tree, meta = build_gravity_tree(keys[order], bucket_size=64)
    num_n = meta.num_nodes
    parent = np.asarray(tree.parent)
    is_leaf = np.asarray(tree.is_leaf)

    nm, com, _, _ = (np.asarray(a) for a in compute_multipoles(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs),
        jnp.asarray(ms), jnp.asarray(keys[order]), tree, meta))
    valid = nm > 0.0
    lengths = np.asarray(box.lengths)
    lo = np.asarray([box.lo[0], box.lo[1], box.lo[2]], np.float64)
    geo_center = lo[None, :] + np.asarray(tree.center_frac) * lengths[None, :]
    geo_size = np.asarray(tree.halfsize_frac)[:, None] * lengths[None, :]
    l_node = 2.0 * geo_size.max(axis=1)
    s_off = np.linalg.norm(com - geo_center, axis=1)
    smax = np.where(valid, s_off, 0.0)
    BIG = 1e15
    com_lo = np.where(valid[:, None], com, BIG)
    com_hi = np.where(valid[:, None], com, -BIG)
    for s, e in reversed(meta.level_ranges[1:]):
        np.maximum.at(smax, parent[s:e], smax[s:e])
        np.minimum.at(com_lo, parent[s:e], com_lo[s:e])
        np.maximum.at(com_hi, parent[s:e], com_hi[s:e])
    ccenter = np.where(valid[:, None], 0.5 * (com_lo + com_hi), BIG)
    chalf = np.where(valid[:, None],
                     np.maximum(0.5 * (com_hi - com_lo), 0.0), 0.0)
    mac2 = (l_node / 0.5 + smax) ** 2
    self_parent = parent == np.arange(num_n)

    def accept_of(bc, bs):
        d = np.maximum(
            np.abs(bc[None, :] - ccenter) - bs[None, :] - chalf, 0.0)
        return valid & ((d * d).sum(axis=1) >= mac2)

    # shard 3 of 8: slab essential set (the LET list)
    P, k = 8, 3
    S = n // P
    sl = slice(k * S, (k + 1) * S)
    pmin = np.array([xs[sl].min(), ys[sl].min(), zs[sl].min()])
    pmax = np.array([xs[sl].max(), ys[sl].max(), zs[sl].max()])
    acc_s = accept_of((pmax + pmin) / 2, (pmax - pmin) / 2)
    anc_s = np.where(self_parent, False, acc_s[parent])
    cand = ~anc_s
    assert 0 < cand.sum() < num_n, "needs a strictly pruned set"
    cidx = np.flatnonzero(cand)
    pos_of = np.full(num_n, -1)
    pos_of[cidx] = np.arange(len(cidx))
    # ancestor-closure: every listed node's parent is listed
    assert np.all(pos_of[parent[cidx]] >= 0)

    rng = np.random.default_rng(1)
    blk = 256
    for b in rng.integers(k * S // blk, (k + 1) * S // blk, 16):
        rows = slice(b * blk, (b + 1) * blk)
        bmin = np.array([xs[rows].min(), ys[rows].min(), zs[rows].min()])
        bmax = np.array([xs[rows].max(), ys[rows].max(), zs[rows].max()])
        acc = accept_of((bmax + bmin) / 2, (bmax - bmin) / 2)
        anc = np.where(self_parent, False, acc[parent])
        m2p_dense = np.flatnonzero(acc & ~anc)
        p2p_dense = np.flatnonzero(is_leaf & valid & ~acc)

        # through the LET list (the traversal.py list-branch semantics)
        acc_l = acc[cidx]
        ppos = pos_of[parent[cidx]]
        not_self = cidx[ppos] != cidx
        anc_l = acc_l[ppos] & not_self
        m2p_let = cidx[acc_l & ~anc_l]
        p2p_let = cidx[is_leaf[cidx] & valid[cidx] & ~acc_l]
        np.testing.assert_array_equal(np.sort(m2p_let), m2p_dense)
        np.testing.assert_array_equal(np.sort(p2p_let), p2p_dense)
