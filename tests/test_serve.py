"""Live science surface (schema v8): the in-graph snapshot event
round-trip, the sharded==single grid-equality pin, the snap=None
lowering-neutrality pin, and the jax-free fleet dashboard
(``sphexa-telemetry serve`` / ``fleet``) contracts — discovery, exit
codes, self-contained HTML, and the committed 2-run mini-fixture with
one blackboxed member."""

import hashlib
import json
import os

import numpy as np
import pytest

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import SnapshotSpec, snapshot_diagnostics
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.telemetry import JsonlSink, MemorySink, Telemetry
from sphexa_tpu.telemetry.cli import main as cli_main
from sphexa_tpu.telemetry.registry import (
    KIND_SINCE,
    SCHEMA_VERSION,
    validate_event,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "serve_fixture")


# ---------------------------------------------------------------------------
# schema v8: the snapshot event
# ---------------------------------------------------------------------------


class TestSnapshotSchema:
    def test_v8_snapshot_event_round_trip(self, tmp_path):
        """A Simulation with a SnapshotSpec emits strict-clean schema-v8
        ``snapshot`` events whose .npz sidecars carry the grid + meta."""
        sink = MemorySink()
        state, box, const = init_sedov(6)
        sim = Simulation(state, box, const, prop="std", block=512,
                         telemetry=Telemetry(sinks=[sink]),
                         snap_spec=SnapshotSpec(fields=("rho", "temp"),
                                                grid=8, stride=7),
                         snap_dir=str(tmp_path / "snapshots"))
        sim.step()
        sim.step()
        snaps = sink.of_kind("snapshot")
        assert [e["it"] for e in snaps] == [1, 2]
        for e in snaps:
            assert e["v"] == SCHEMA_VERSION == 8
            assert validate_event(e) == []
            assert e["fields"] == ["rho", "temp"] and e["grid"] == 8
            z = np.load(e["path"], allow_pickle=False)
            assert np.asarray(z["grid"]).shape == (2, 8, 8)
            assert list(z["fields"]) == ["rho", "temp"]
            pts = np.asarray(z["pts"])  # xyz + one row per field
            assert pts.shape[0] == 5 and pts.shape[1] > 0
        # frames drain in iteration order, once
        assert [it for it, _ in sim.drain_snapshots()] == [1, 2]
        assert sim.drain_snapshots() == []

    def test_snapshot_is_v8_only_and_old_versions_validate(self):
        """v8 only ADDS the snapshot kind: every pre-v8 kind keeps its
        introduction version, so v1..v7 files stay strictly clean under
        the v8 reader (the fixture runs below re-check this end to
        end)."""
        assert KIND_SINCE["snapshot"] == 8
        assert all(v < 8 for k, v in KIND_SINCE.items() if k != "snapshot")
        # a v7 writer never emitted snapshots; its events validate as-is
        old = {"v": 7, "seq": 1, "t": 0.0, "kind": "step", "it": 1,
               "wall_s": 0.1, "dt": 1e-3, "reconfigured": False}
        assert validate_event(old) == []
        # a snapshot stamped pre-v8 is the anachronism the gate catches
        bad = {"v": 7, "seq": 2, "t": 0.0, "kind": "snapshot", "it": 1,
               "fields": ["rho"], "grid": 8}
        assert validate_event(bad) != []


# ---------------------------------------------------------------------------
# the deposit itself: sharded equivalence + lowering neutrality
# ---------------------------------------------------------------------------


class TestSnapshotDeposit:
    def test_sharded_equals_single_device_grid(self):
        """The stacked scatter-add deposit must be partition-invariant:
        the same particles on a 2-device mesh produce the same grid (one
        psum over per-shard partial grids) as single-device, up to
        float-sum rounding."""
        from jax.sharding import NamedSharding, PartitionSpec

        from sphexa_tpu.parallel import make_mesh, shard_state

        state, box, const = init_sedov(6)
        spec = SnapshotSpec(fields=("rho", "m"), grid=8)
        rho = jax.numpy.ones_like(state.m)
        single = jax.jit(
            lambda s, r, b: snapshot_diagnostics(s, r, b, spec)
        )(state, rho, box)
        mesh = make_mesh(2)
        sstate = shard_state(state, mesh)
        srho = jax.device_put(rho, NamedSharding(mesh, PartitionSpec("p")))
        sharded = jax.jit(
            lambda s, r, b: snapshot_diagnostics(s, r, b, spec)
        )(sstate, srho, box)
        g0 = np.asarray(single["snap_grid"])
        g1 = np.asarray(sharded["snap_grid"])
        assert g0.shape == g1.shape == (2, 8, 8)
        np.testing.assert_allclose(g1, g0, rtol=1e-6, atol=1e-12)
        # total deposited mass is conserved through the deposit
        np.testing.assert_allclose(g1[1].sum(), np.asarray(state.m).sum(),
                                   rtol=1e-6)

    def test_snap_none_lowering_has_no_snapshot_scope(self):
        """The conditionality pin (the dt_bins pattern): a step built
        with ``snap=None`` must contain NO sphexa/snapshot phase and no
        snap_ output — the committed LOWERING_LOCK digests rely on unset
        snapshots being byte-invisible."""
        import dataclasses

        from sphexa_tpu import propagator as prop
        from sphexa_tpu.devtools.audit.lowerdiff import fingerprint_callable

        state, box, const = init_sedov(6)
        cfg = make_propagator_config(state, box, const, block=512)
        assert cfg.snap is None
        fp = fingerprint_callable(
            lambda s, b: prop.step_hydro_std(s, b, cfg, None), state, box)
        assert not any("snapshot" in ph for ph in fp.phases)
        # and turning the spec ON surfaces the scope (the same program
        # otherwise — this is what rides the production step when set)
        cfg_on = dataclasses.replace(
            cfg, snap=SnapshotSpec(fields=("rho",), grid=8))
        fp_on = fingerprint_callable(
            lambda s, b: prop.step_hydro_std(s, b, cfg_on, None), state, box)
        assert any("snapshot" in ph for ph in fp_on.phases)


# ---------------------------------------------------------------------------
# render_grid golden
# ---------------------------------------------------------------------------


class TestRenderGrid:
    # sha256 of the rendered (32, 32, 3) uint8 pixel array for the
    # arange ramp below — pins the log/clip/colormap/upsample treatment
    # (pixel content, not PNG bytes: zlib output may vary by version)
    GOLDEN = "a1e34d4640f0f2f376c0de578b8366a3d5aba243f5f3e827fcd5b16fd255a08b"

    def test_pixel_golden_and_png_container(self):
        from sphexa_tpu.viz import _png_bytes, render_grid

        img = render_grid(np.arange(64, dtype=np.float64).reshape(8, 8),
                          upsample=4)
        assert img.shape == (32, 32, 3) and img.dtype == np.uint8
        assert hashlib.sha256(img.tobytes()).hexdigest() == self.GOLDEN
        png = _png_bytes(img)
        assert png[:8] == b"\x89PNG\r\n\x1a\n" and b"IEND" in png


# ---------------------------------------------------------------------------
# serve / fleet over the committed mini-fixture
# ---------------------------------------------------------------------------


class TestServeFixture:
    def test_fixture_validates_strict_under_v8(self):
        """The committed runs (one clean, one blackboxed) are strict-
        clean under the current reader — the forward-compat contract."""
        for name in ("run_clean", "run_crashed"):
            path = os.path.join(FIXTURE, name, "events.jsonl")
            events = [json.loads(l) for l in open(path)]
            assert events, name
            for e in events:
                assert validate_event(e) == [], (name, e["kind"])
            assert any(e["kind"] == "snapshot" for e in events)

    def test_serve_once_renders_fleet_html(self, tmp_path, capsys):
        out = str(tmp_path / "dash.html")
        rc = cli_main(["serve", os.path.join(FIXTURE, "run_*"),
                       "--once", "--out", out])
        assert rc == 0
        html = open(out).read()
        # self-contained: both members, an inline PNG frame (no external
        # fetches), the crashed member's red CRASH block
        assert "run_clean" in html and "run_crashed" in html
        assert "data:image/png;base64," in html
        assert "CRASHED" in html and "doctored fixture crash" in html
        assert "http://" not in html.split("<body>")[-1]  # no remote refs
        # --once with no --refresh loop: no meta-refresh tag
        assert 'http-equiv="refresh"' not in html

    def test_fleet_table_and_json(self, capsys):
        rc = cli_main(["fleet", os.path.join(FIXTURE, "run_*")])
        assert rc == 0
        text = capsys.readouterr().out
        assert "run_clean" in text and "run_crashed" in text
        assert "CRASHED" in text
        rc = cli_main(["fleet", os.path.join(FIXTURE, "run_*"),
                       "--format", "json"])
        assert rc == 0
        rows = json.loads(capsys.readouterr().out)
        by_run = {r["name"]: r for r in rows}
        assert by_run["run_crashed"]["status"] == "CRASHED"
        assert by_run["run_crashed"]["error"] is None  # readable, not corrupt
        assert by_run["run_clean"]["status"] in ("ok", "watchdog")
        assert by_run["run_clean"]["snapshots"] >= 1

    def test_exit_codes(self, tmp_path, capsys):
        # 1: nothing matched
        assert cli_main(["serve", str(tmp_path / "nope_*"), "--once"]) == 1
        # 2: every matched run unreadable (corrupt events.jsonl)
        bad = tmp_path / "bad_run"
        bad.mkdir()
        (bad / "events.jsonl").write_text("{not json\n")
        out = str(tmp_path / "dash.html")
        assert cli_main(["serve", str(bad), "--once", "--out", out]) == 2
        # 0 with a partial fleet: the corrupt member renders UNREADABLE
        # next to the committed clean one instead of taking serve down
        both = tmp_path / "mix"
        both.mkdir()
        os.symlink(os.path.join(FIXTURE, "run_clean"), both / "run_clean")
        os.symlink(str(bad), both / "bad_run")
        assert cli_main(["serve", str(both), "--once", "--out", out]) == 0
        html = open(out).read()
        assert "UNREADABLE" in html and "run_clean" in html

    def test_frame_fallback_uses_fixture_relative_paths(self):
        """Event-recorded absolute paths from the generating machine are
        stale in a committed fixture; the frame lookup must fall back to
        ``<run>/snapshots/<basename>`` so the dashboard still renders."""
        from sphexa_tpu.telemetry.serve import build_run_card

        card = build_run_card(os.path.join(FIXTURE, "run_clean"))
        assert card.get("error") is None
        assert card["frame"] is not None
        assert card["frame"]["png"][:8] == b"\x89PNG\r\n\x1a\n"
        assert card["frame"]["path"].startswith(FIXTURE)  # local fallback
        assert card["snapshots"] >= 1
