"""Observable tests: KH growth rate, Mach RMS, wind-bubble fraction,
gravitational waves, constants.txt writer, and the in-graph science
ledger (observables/ledger.py — the step-resident mirror of
conserved_quantities that rides the diagnostics dict). Mirrors
main/test/observables/gravitational_waves.cpp plus hand-checkable
constructions for the reductions.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sphexa_tpu.observables import (
    ConstantsWriter,
    ObservableSpec,
    conserved_quantities,
    gravitational_wave_signal,
    kh_growth_rate,
    ledger_diagnostics,
    mach_rms,
    make_observable,
    make_observable_spec,
    wind_bubble_fraction,
)
from sphexa_tpu.observables.extras import GW_UNITS
from sphexa_tpu.observables.factory import (
    TimeAndEnergy,
    TimeEnergyGrowth,
    TurbulenceMachRMS,
    WindBubble,
)
from sphexa_tpu.sfc.box import BoundaryType, Box


class TestMachRMS:
    def test_uniform_mach(self):
        n = 100
        v = jnp.full(n, 2.0)
        zero = jnp.zeros(n)
        c = jnp.full(n, 1.0)
        assert float(mach_rms(v, zero, zero, c)) == pytest.approx(2.0)

    def test_mixed(self):
        vx = jnp.array([1.0, 0.0])
        zero = jnp.zeros(2)
        c = jnp.array([1.0, 1.0])
        assert float(mach_rms(vx, zero, zero, c)) == pytest.approx(
            np.sqrt(0.5), rel=1e-6
        )


class TestWindBubble:
    def test_fraction(self):
        rho = jnp.array([10.0, 10.0, 1.0, 10.0])
        temp = jnp.array([1.0, 1.0, 1.0, 100.0])  # last: heated -> lost
        m = jnp.full(4, 0.5)
        # cloud particles: dense AND cool -> first two qualify
        frac = wind_bubble_fraction(
            rho, temp, m, rho_bubble=10.0, temp_wind=50.0, initial_mass=2.0
        )
        assert float(frac) == pytest.approx(0.5)


class TestKHGrowth:
    def test_pure_seeded_mode(self):
        # vy = A sin(4 pi x) exactly at the lower interface: projection
        # returns 2*A*|si|/di -> 2A * <sin^2>/<1> = A
        n = 4000
        rng = np.random.default_rng(0)
        x = rng.uniform(0, 1, n)
        y = np.full(n, 0.25)
        amp = 0.01
        vy = amp * np.sin(4 * np.pi * x)
        vol = np.full(n, 1.0)
        box = Box.create(0, 1, 0, 1, 0, 0.0625, boundary=BoundaryType.periodic)
        rate = float(kh_growth_rate(jnp.asarray(x), jnp.asarray(y),
                                    jnp.asarray(vy), jnp.asarray(vol), box))
        assert rate == pytest.approx(amp, rel=0.05)

    def test_no_mode_no_growth(self):
        n = 1000
        rng = np.random.default_rng(1)
        x = rng.uniform(0, 1, n)
        y = rng.uniform(0, 1, n)
        vy = np.zeros(n)
        box = Box.create(0, 1, 0, 1, 0, 0.0625, boundary=BoundaryType.periodic)
        rate = float(kh_growth_rate(jnp.asarray(x), jnp.asarray(y),
                                    jnp.asarray(vy), jnp.ones(n), box))
        assert rate == 0.0


class TestGravWaves:
    def test_static_system_silent(self):
        n = 10
        rng = np.random.default_rng(2)
        pos = [jnp.asarray(rng.normal(size=n)) for _ in range(3)]
        zero = jnp.zeros(n)
        m = jnp.ones(n)
        hp, hc, q = gravitational_wave_signal(
            *pos, zero, zero, zero, zero, zero, zero, m, 0.0, 0.0
        )
        assert float(hp) == 0.0 and float(hc) == 0.0

    def test_single_particle_known_value(self):
        # one unit-mass particle on the x axis with ax=1: d2Q_xx = 2/3*(3*x*ax - x*ax)*m
        x = jnp.array([2.0])
        zero = jnp.zeros(1)
        ax = jnp.array([1.0])
        m = jnp.ones(1)
        hp, hc, q = gravitational_wave_signal(
            x, zero, zero, zero, zero, zero, ax, zero, zero, m, 0.0, 0.0
        )
        assert float(q["xx"]) == pytest.approx(2.0 / 3.0 * (3 * 2.0 - 2.0))
        assert float(q["yy"]) == pytest.approx(-2.0 / 3.0 * 2.0)
        # observer on z axis (theta=0, phi=0): h+ ~ (Qxx - Qyy) * units
        assert float(hp) == pytest.approx(
            (float(q["xx"]) - float(q["yy"])) * GW_UNITS, rel=1e-6
        )

    def test_accels_surface_through_diagnostics(self):
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(8)
        sim = Simulation(state, box, const, prop="std", block=256,
                         keep_accels=True)
        d = sim.step()
        assert d["ax"].shape == (state.n,)
        hp, hc, q = gravitational_wave_signal(
            sim.state.x, sim.state.y, sim.state.z,
            sim.state.vx, sim.state.vy, sim.state.vz,
            d["ax"], d["ay"], d["az"], sim.state.m, 0.5, 0.5,
        )
        assert np.isfinite(float(hp)) and np.isfinite(float(hc))


class TestLedger:
    """The in-graph science ledger: same sums as the eager
    conserved_quantities, riding the step diagnostics (OBS_DIAG_KEYS /
    NUM_DIAG_KEYS) so deferred windows keep every step's row."""

    def test_step_diag_carries_ledger_keys(self):
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.propagator import NUM_DIAG_KEYS, OBS_DIAG_KEYS
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(6)
        sim = Simulation(state, box, const, prop="std", block=512,
                         obs_spec=ObservableSpec())
        d = sim.step()
        assert set(OBS_DIAG_KEYS) <= set(d)
        assert set(NUM_DIAG_KEYS) <= set(d)

    def test_ledger_matches_eager_conserved(self):
        """The diag ledger of a real step equals the app's former eager
        recompute over the post-step state — the constants.txt column
        contract."""
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(6)
        sim = Simulation(state, box, const, prop="std", block=512,
                         obs_spec=ObservableSpec())
        d = sim.step()
        e = conserved_quantities(sim.state, const,
                                 egrav=d.get("egrav", 0.0))
        for k in ("etot", "ecin", "eint", "egrav", "linmom", "angmom"):
            assert float(d[f"obs_{k}"]) == pytest.approx(
                float(e[k]), rel=1e-6, abs=1e-30), k
        assert float(d["obs_ttot"]) == pytest.approx(
            float(sim.state.ttot), rel=1e-7)

    def test_ledger_sharded_matches_single_device(self):
        """2-device GSPMD reductions equal single-device values to
        reduction-order tolerance — the ledger's sharding contract (the
        chained collectives must not corrupt the sums, the PR-5 XLA:CPU
        rendezvous class)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.parallel import make_mesh, shard_state

        state, box, const = init_sedov(6)
        rho = jnp.abs(state.x) + 0.5
        nc = (jnp.arange(state.n) % 120).astype(jnp.int32)

        fn = jax.jit(lambda st, r, n: ledger_diagnostics(
            st, r, n, const, 150))
        single = jax.device_get(fn(state, rho, nc))

        mesh = make_mesh(2)
        pspec = NamedSharding(mesh, PartitionSpec("p"))
        sstate = shard_state(state, mesh)
        srho = jax.device_put(rho, pspec)
        snc = jax.device_put(nc, pspec)
        sharded = jax.device_get(fn(sstate, srho, snc))

        assert set(single) == set(sharded)
        for k in single:
            np.testing.assert_allclose(
                sharded[k], single[k], rtol=1e-6, atol=1e-12,
                err_msg=k)

    def test_numerics_counts_hand_checked(self):
        """Clip/saturation/nonfinite counts on a doctored state."""
        from sphexa_tpu.init import init_sedov

        state, box, const = init_sedov(4)
        n = state.n
        nc = jnp.full((n,), const.ng0 - 1, jnp.int32)  # on target
        nc = nc.at[0].set(200)   # >= ngmax: clipped AND saturated
        nc = nc.at[1].set(3)     # far below ng0: saturated
        import dataclasses

        h = np.asarray(state.h).copy()
        h[2] = np.nan
        state = dataclasses.replace(state, h=jnp.asarray(h))
        rho = jnp.ones((n,))
        d = ledger_diagnostics(state, rho, nc, const, ngmax=150)
        assert int(d["n_nc_clip"]) == 1
        assert int(d["n_h_sat"]) == 2
        assert int(d["n_bad_h"]) == 1
        assert int(d["n_bad_rho"]) == 0
        assert float(d["rho_min"]) == 1.0

    def test_dt_limiter_attribution(self):
        from sphexa_tpu.propagator import DT_LIMITERS, _dt_limiter
        from sphexa_tpu.sph.particles import SimConstants

        const = SimConstants()
        prev = jnp.float32(1.0)  # growth cap = 1.1
        lim = lambda **kw: DT_LIMITERS[int(_dt_limiter(prev, const, **kw))]
        assert lim(courant=2.0) == "growth"
        assert lim(courant=0.5) == "courant"
        assert lim(courant=0.5, rho=0.2) == "rho"
        assert lim(courant=0.5, rho=0.2, cool=0.1) == "cool"
        assert lim(courant=0.5, accel=0.01) == "accel"

    def test_make_observable_spec_matches_factory(self):
        assert make_observable_spec("sedov") == ObservableSpec()
        assert make_observable_spec("kelvin-helmholtz").extra == "kh"
        assert make_observable_spec("turbulence").extra == "mach"
        wind = make_observable_spec("wind-shock")
        ref = make_observable("wind-shock")
        assert wind.extra == "wind"
        assert wind.rho_bubble == pytest.approx(ref.rho_bubble)
        assert wind.temp_wind == pytest.approx(ref.temp_wind)
        assert wind.initial_mass == pytest.approx(ref.initial_mass)
        with pytest.raises(ValueError):
            ObservableSpec(extra="bogus")

    def test_ledger_extra_wind_matches_reduction(self):
        from sphexa_tpu.init import init_sedov

        state, box, const = init_sedov(4)
        rho = jnp.abs(state.x) + 0.5
        spec = ObservableSpec(extra="wind", rho_bubble=1.0,
                              temp_wind=2.0, initial_mass=3.0)
        nc = jnp.zeros((state.n,), jnp.int32)
        d = ledger_diagnostics(state, rho, nc, const, 150, spec=spec,
                               box=box)
        ref = wind_bubble_fraction(rho, state.temp, state.m, 1.0, 2.0, 3.0)
        assert float(d["obs_extra"]) == pytest.approx(float(ref), rel=1e-6)


class TestFactoryAndWriter:
    def test_factory_selection(self):
        assert isinstance(make_observable("sedov"), TimeAndEnergy)
        assert isinstance(make_observable("kelvin-helmholtz"), TimeEnergyGrowth)
        assert isinstance(make_observable("wind-shock"), WindBubble)
        assert isinstance(make_observable("turbulence"), TurbulenceMachRMS)

    def test_constants_writer(self, tmp_path):
        from sphexa_tpu.init import init_sedov

        state, box, const = init_sedov(6)
        e = conserved_quantities(state, const)
        path = str(tmp_path / "constants.txt")
        w = ConstantsWriter(path)
        w.write(1, state, box, e)
        w.write(2, state, box, e)
        lines = open(path).read().strip().split("\n")
        assert lines[0].startswith("# iteration time minDt etot")
        assert len(lines) == 3
        row = [float(v) for v in lines[1].split()]
        assert row[0] == 1.0
        assert row[3] == pytest.approx(float(e["etot"]), rel=1e-6)

    def test_write_row_byte_compatible_with_write(self, tmp_path):
        """The ledger path (write_row on pre-fetched scalars) must
        produce the identical bytes the state-reading write() did."""
        from sphexa_tpu.init import init_sedov

        state, box, const = init_sedov(4)
        e = conserved_quantities(state, const)
        a, b = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
        row = ConstantsWriter(a).write(3, state, box, e)
        ConstantsWriter(b).write_row(row)
        assert open(a).read() == open(b).read()
