"""Observable tests: KH growth rate, Mach RMS, wind-bubble fraction,
gravitational waves, constants.txt writer. Mirrors
main/test/observables/gravitational_waves.cpp plus hand-checkable
constructions for the reductions.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.observables import (
    ConstantsWriter,
    conserved_quantities,
    gravitational_wave_signal,
    kh_growth_rate,
    mach_rms,
    make_observable,
    wind_bubble_fraction,
)
from sphexa_tpu.observables.extras import GW_UNITS
from sphexa_tpu.observables.factory import (
    TimeAndEnergy,
    TimeEnergyGrowth,
    TurbulenceMachRMS,
    WindBubble,
)
from sphexa_tpu.sfc.box import BoundaryType, Box


class TestMachRMS:
    def test_uniform_mach(self):
        n = 100
        v = jnp.full(n, 2.0)
        zero = jnp.zeros(n)
        c = jnp.full(n, 1.0)
        assert float(mach_rms(v, zero, zero, c)) == pytest.approx(2.0)

    def test_mixed(self):
        vx = jnp.array([1.0, 0.0])
        zero = jnp.zeros(2)
        c = jnp.array([1.0, 1.0])
        assert float(mach_rms(vx, zero, zero, c)) == pytest.approx(
            np.sqrt(0.5), rel=1e-6
        )


class TestWindBubble:
    def test_fraction(self):
        rho = jnp.array([10.0, 10.0, 1.0, 10.0])
        temp = jnp.array([1.0, 1.0, 1.0, 100.0])  # last: heated -> lost
        m = jnp.full(4, 0.5)
        # cloud particles: dense AND cool -> first two qualify
        frac = wind_bubble_fraction(
            rho, temp, m, rho_bubble=10.0, temp_wind=50.0, initial_mass=2.0
        )
        assert float(frac) == pytest.approx(0.5)


class TestKHGrowth:
    def test_pure_seeded_mode(self):
        # vy = A sin(4 pi x) exactly at the lower interface: projection
        # returns 2*A*|si|/di -> 2A * <sin^2>/<1> = A
        n = 4000
        rng = np.random.default_rng(0)
        x = rng.uniform(0, 1, n)
        y = np.full(n, 0.25)
        amp = 0.01
        vy = amp * np.sin(4 * np.pi * x)
        vol = np.full(n, 1.0)
        box = Box.create(0, 1, 0, 1, 0, 0.0625, boundary=BoundaryType.periodic)
        rate = float(kh_growth_rate(jnp.asarray(x), jnp.asarray(y),
                                    jnp.asarray(vy), jnp.asarray(vol), box))
        assert rate == pytest.approx(amp, rel=0.05)

    def test_no_mode_no_growth(self):
        n = 1000
        rng = np.random.default_rng(1)
        x = rng.uniform(0, 1, n)
        y = rng.uniform(0, 1, n)
        vy = np.zeros(n)
        box = Box.create(0, 1, 0, 1, 0, 0.0625, boundary=BoundaryType.periodic)
        rate = float(kh_growth_rate(jnp.asarray(x), jnp.asarray(y),
                                    jnp.asarray(vy), jnp.ones(n), box))
        assert rate == 0.0


class TestGravWaves:
    def test_static_system_silent(self):
        n = 10
        rng = np.random.default_rng(2)
        pos = [jnp.asarray(rng.normal(size=n)) for _ in range(3)]
        zero = jnp.zeros(n)
        m = jnp.ones(n)
        hp, hc, q = gravitational_wave_signal(
            *pos, zero, zero, zero, zero, zero, zero, m, 0.0, 0.0
        )
        assert float(hp) == 0.0 and float(hc) == 0.0

    def test_single_particle_known_value(self):
        # one unit-mass particle on the x axis with ax=1: d2Q_xx = 2/3*(3*x*ax - x*ax)*m
        x = jnp.array([2.0])
        zero = jnp.zeros(1)
        ax = jnp.array([1.0])
        m = jnp.ones(1)
        hp, hc, q = gravitational_wave_signal(
            x, zero, zero, zero, zero, zero, ax, zero, zero, m, 0.0, 0.0
        )
        assert float(q["xx"]) == pytest.approx(2.0 / 3.0 * (3 * 2.0 - 2.0))
        assert float(q["yy"]) == pytest.approx(-2.0 / 3.0 * 2.0)
        # observer on z axis (theta=0, phi=0): h+ ~ (Qxx - Qyy) * units
        assert float(hp) == pytest.approx(
            (float(q["xx"]) - float(q["yy"])) * GW_UNITS, rel=1e-6
        )

    def test_accels_surface_through_diagnostics(self):
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(8)
        sim = Simulation(state, box, const, prop="std", block=256,
                         keep_accels=True)
        d = sim.step()
        assert d["ax"].shape == (state.n,)
        hp, hc, q = gravitational_wave_signal(
            sim.state.x, sim.state.y, sim.state.z,
            sim.state.vx, sim.state.vy, sim.state.vz,
            d["ax"], d["ay"], d["az"], sim.state.m, 0.5, 0.5,
        )
        assert np.isfinite(float(hp)) and np.isfinite(float(hc))


class TestFactoryAndWriter:
    def test_factory_selection(self):
        assert isinstance(make_observable("sedov"), TimeAndEnergy)
        assert isinstance(make_observable("kelvin-helmholtz"), TimeEnergyGrowth)
        assert isinstance(make_observable("wind-shock"), WindBubble)
        assert isinstance(make_observable("turbulence"), TurbulenceMachRMS)

    def test_constants_writer(self, tmp_path):
        from sphexa_tpu.init import init_sedov

        state, box, const = init_sedov(6)
        e = conserved_quantities(state, const)
        path = str(tmp_path / "constants.txt")
        w = ConstantsWriter(path)
        w.write(1, state, box, e)
        w.write(2, state, box, e)
        lines = open(path).read().strip().split("\n")
        assert lines[0].startswith("# iteration time minDt etot")
        assert len(lines) == 3
        row = [float(v) for v in lines[1].split()]
        assert row[0] == 1.0
        assert row[3] == pytest.approx(float(e["etot"]), rel=1e-6)
