"""Hierarchical block time steps (sph/blockdt.py + the *_blockdt step
builders): scheme unit tests, the dt_bins=1 bitwise pin against the
global-dt path, the two-scale update-reduction proxy with its
conservation budget, the dt_bins=None lowering guard, telemetry/resort
counters, and (slow) sharded==single-device bin assignment at P=2."""

import numpy as np
import pytest

import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import ObservableSpec
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sph import blockdt as bdt
from sphexa_tpu.telemetry import MemorySink, Telemetry
from sphexa_tpu.telemetry.registry import SCHEMA_VERSION, validate_event

#: every integrator-visible ParticleState field the blockdt tail writes —
#: the dt_bins=1 pin below asserts BITWISE equality on all of them
_PINNED_FIELDS = (
    "x", "y", "z", "x_m1", "y_m1", "z_m1",
    "vx", "vy", "vz", "h", "temp", "du", "du_m1",
    "ttot", "min_dt", "min_dt_m1",
)


class TestScheme:
    """Pure-math unit tests of the bin scheme."""

    def test_due_schedule(self):
        B = 4
        C = bdt.cycle_length(B)
        assert C == 8
        bins = jnp.arange(B, dtype=jnp.int32)
        for s in range(C):
            due = np.asarray(bdt.due_mask(bins, jnp.int32(s)))
            expect = [(s + 1) % (1 << k) == 0 for k in range(B)]
            assert due.tolist() == expect, f"substep {s}"
        # bin 0 fires every substep; the cycle end synchronizes ALL bins
        assert np.asarray(bdt.due_mask(bins, jnp.int32(C - 1))).all()

    def test_assign_bins_clips_and_saturates(self):
        dt_min = jnp.float32(1e-4)
        cand = jnp.asarray([1e-4, 2.5e-4, 9e-4, 1e2, np.inf, 5e-5],
                           jnp.float32)
        k = np.asarray(bdt.assign_bins(cand, dt_min, 4))
        # 1x -> 0; 2.5x -> 1; 9x -> 3; huge and inf saturate at nbins-1;
        # below dt_min clamps to 0 (never a negative bin)
        assert k.tolist() == [0, 1, 3, 3, 3, 0]

    def test_fold_key_spatial_major_bin_minor(self):
        keys = jnp.asarray([5, 5, 4, 6], dtype=jnp.uint32)
        bins = jnp.asarray([3, 0, 9, 1], jnp.int32)  # 9 saturates in fold
        folded = np.asarray(bdt.fold_bin_key(keys, bins))
        order = np.argsort(folded, kind="stable")
        # spatial key dominates; the equal-key pair is grouped by bin
        assert order.tolist() == [2, 1, 0, 3]
        # fold stays in uint32 and is invertible back to the spatial key
        assert (folded >> bdt.FOLD_BITS == np.asarray(keys)).all()

    def test_compact_active_kernel_matches_argsort(self):
        rng = np.random.default_rng(0)
        due = jnp.asarray(rng.random(512) < 0.3)
        idx_x, n_x = bdt.compact_active(due, use_kernel=False)
        idx_k, n_k = bdt.compact_active(due, use_kernel=True,
                                        interpret=True)
        n_ref = int(np.asarray(due).sum())
        assert int(n_x) == int(n_k) == n_ref
        np.testing.assert_array_equal(
            np.sort(np.asarray(idx_x)[:n_ref]),
            np.sort(np.asarray(idx_k)[:n_ref]))
        # both paths put ACTIVE rows first
        assert np.asarray(due)[np.asarray(idx_k)[:n_ref]].all()
        assert np.asarray(due)[np.asarray(idx_x)[:n_ref]].all()


class TestBitwisePin:
    """dt_bins=1 must reproduce the global-dt path to the bit, for every
    step builder the blockdt mode touches (acceptance pin)."""

    @pytest.mark.parametrize("prop", ["std", "ve"])
    def test_dt_bins_1_matches_global(self, prop):
        state, box, const = init_sedov(8)
        ref = Simulation(state, box, const, prop=prop, block=512)
        one = Simulation(state, box, const, prop=prop, block=512,
                         dt_bins=1)
        for _ in range(3):
            ref.step()
            one.step()
        for f in _PINNED_FIELDS:
            a, b = getattr(ref.state, f), getattr(one.state, f)
            if a is None:
                assert b is None, f
                continue
            assert np.array_equal(np.asarray(a), np.asarray(b)), f

    def test_dt_bins_none_lowering_untouched(self):
        # the opt-out guard: a default config must lower without ANY
        # block-timestep scope. Pinned on the jaxdiff canonical
        # fingerprint (the shared helper the LOWERING_LOCK and the
        # JXA402 knob probes use) instead of an ad-hoc HLO text scan:
        # the phase table must have no dt-bins scope and no canonical
        # eqn may reference a bdt_ helper
        from sphexa_tpu import propagator as prop
        from sphexa_tpu.devtools.audit.lowerdiff import fingerprint_callable

        state, box, const = init_sedov(6)
        cfg = make_propagator_config(state, box, const, block=512)
        assert cfg.dt_bins is None
        fp = fingerprint_callable(
            lambda s, b: prop.step_hydro_std(s, b, cfg, None), state, box)
        assert not any("dt-bins" in ph for ph in fp.phases)
        assert not any("bdt_" in ln for ln in fp.lines)
        # and the fingerprint is reproducible within a process — the
        # property the committed LOWERING_LOCK.json relies on
        fp2 = fingerprint_callable(
            lambda s, b: prop.step_hydro_std(s, b, cfg, None), state, box)
        assert fp2.digest == fp.digest


class TestTwoScaleProxy:
    """Sedov is the two-scale case: a hot injected core (small Courant
    dt) inside a cold quiet ambient whose candidates are orders larger —
    the ambient lands in the deep bins and the updates-saved factor is
    the bin-occupancy complexity proxy recorded in docs/NEXT.md."""

    def test_update_reduction_and_conservation(self):
        state, box, const = init_sedov(8)
        spec = ObservableSpec()
        ref = Simulation(state, box, const, prop="std", block=512,
                         obs_spec=spec)
        blk = Simulation(state, box, const, prop="std", block=512,
                         dt_bins=4, obs_spec=spec)
        steps = 2 * bdt.cycle_length(4)
        for _ in range(steps):
            ref.step()
            blk.step()
        # the acceptance pin: >= 5x fewer particle-updates than the
        # global-dt equivalent of the same substep span
        assert blk.bdt_updates_full == steps * state.n
        assert blk.bdt_updates > 0
        factor = blk.bdt_updates_full / blk.bdt_updates
        assert factor >= 5.0, f"updates-saved factor {factor:.2f} < 5"
        # conservation stays inside the e2e drift budget on both paths
        assert blk.energy_drift is not None
        assert blk.energy_drift <= 1e-5
        assert ref.energy_drift is not None and ref.energy_drift <= 1e-5


class TestTelemetryAndResort:
    def test_dt_bins_event_and_resort_counters(self):
        sink = MemorySink()
        state, box, const = init_sedov(8)
        sim = Simulation(state, box, const, prop="ve", block=512,
                         dt_bins=4, bin_resort_drift=0.01, check_every=4,
                         telemetry=Telemetry(sinks=[sink]))
        for _ in range(8):
            sim.step()
        sim.flush()
        evs = sink.of_kind("dt_bins")
        assert evs, "no dt_bins event at the flush boundary"
        for e in evs:
            # the dt_bins kind arrived in v6; the envelope stamps the
            # writer's current schema version
            assert e["v"] == SCHEMA_VERSION >= 6
            assert validate_event(e) == []
        last = evs[-1]
        assert len(last["pop"]) == 4
        assert sum(last["pop"]) == state.n
        assert 0 < last["updates"] <= last["updates_full"]
        # drift-aware resort: the decision counters cover the window
        assert sim.bdt_resorts + sim.bdt_keeps == 8
        assert sim.bdt_keeps >= 1, "threshold 0.01 should keep sometimes"

    def test_tuned_dict_resolves_blockdt_knobs(self):
        state, box, const = init_sedov(6)
        sim = Simulation(state, box, const, prop="std", block=512,
                         tuned={"dt_bins": 2, "bin_sync_every": 2})
        assert sim.dt_bins == 2 and sim.bin_sync_every == 2
        sim.step()  # engages the blockdt step builder
        assert sim.bdt_updates_full == state.n

    def test_rejects_unsupported_propagator(self):
        state, box, const = init_sedov(6)
        with pytest.raises(ValueError, match="dt_bins"):
            Simulation(state, box, const, prop="nbody", dt_bins=2)

    def test_rejects_bad_knob_values(self):
        state, box, const = init_sedov(6)
        with pytest.raises(ValueError):
            Simulation(state, box, const, prop="std", dt_bins=0)
        with pytest.raises(ValueError):
            Simulation(state, box, const, prop="std", dt_bins=2,
                       bin_sync_every=0)


@pytest.mark.slow
class TestShardedBins:
    """P=2 sharded run must assign the SAME bins as single-device (the
    blockdt math runs outside shard_map, GSPMD-partitioned)."""

    def test_bin_assignment_matches_single_device(self):
        state, box, const = init_sedov(8)
        single = Simulation(state, box, const, prop="std", block=512,
                            backend="pallas", dt_bins=4)
        shard = Simulation(state, box, const, prop="std", block=512,
                           backend="pallas", num_devices=2, dt_bins=4)
        for _ in range(2):
            single.step()
            shard.step()
        np.testing.assert_array_equal(np.asarray(single._bstate.bins),
                                      np.asarray(shard._bstate.bins))
        assert int(shard._bstate.substep) == int(single._bstate.substep)
        assert np.float32(shard._bstate.dt_min) == np.float32(
            single._bstate.dt_min)
