"""statecheck: symbolic schema inference, the STATE_SCHEMA.json lock,
the JXA5xx rules, the CLI, and the ensemble-mode seed.

The schema's value is the same stability contract jaxdiff pins for the
lowering: same program -> same rows, across processes (the committed
lock is verified cross-process by scripts/check.sh and the slow tier
here), with axis polynomials fitted EXACTLY (rational arithmetic) from
the registry's two-point grow probes. The JXA5xx fixtures live in
tests/statecheck_fixtures/ because they need a controlled context
(doctored lock path, vmap_members on) that the shared
tests/audit_fixtures runner does not set.
"""

import dataclasses
import importlib.util
import json
import re
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sphexa_tpu.devtools.audit.core import (
    Auditor,
    EntryCase,
    EntryTrace,
    audit_context,
    entries_from_namespace,
    entrypoint,
    set_audit_context,
)
from sphexa_tpu.devtools.audit.statecheck import (
    DEFAULT_SCHEMA_PATH,
    SCHEMA_VERSION,
    LockError,
    _fit_axes,
    entry_schema,
    format_axes,
    load_lock,
    main as schema_main,
    schema_diff,
    vmap_probe,
    write_lock,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "statecheck_fixtures"

_EXPECT_RE = re.compile(
    r"#\s*expect:\s*([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
)


def expected_findings(path: Path):
    out = []
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for code in m.group(1).split(","):
                out.append((i, code.strip()))
    return sorted(out)


def load_fixture_entries(name: str):
    path = FIXTURES / name
    spec = importlib.util.spec_from_file_location(
        f"statecheck_fixture_{path.stem}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return entries_from_namespace(vars(mod))


# ---------------------------------------------------------------------------
# axis-polynomial fits
# ---------------------------------------------------------------------------


class TestAxisFit:
    def test_const_extensive_affine(self):
        axes = _fit_axes((216, 216, 220, 648), (512, 512, 516, 1536),
                         216, 512)
        assert axes[0] == {"kind": "const", "dim": 216} or \
            axes[0]["kind"] == "extensive"
        # d == n at both points: extensive with unit slope
        assert axes[1] == {"kind": "extensive", "per_n": "1"}
        # d == n + 4: affine with integral offset
        assert axes[2] == {"kind": "affine", "per_n": "1", "offset": 4}
        # d == 3n: extensive with slope 3
        assert axes[3] == {"kind": "extensive", "per_n": "3"}

    def test_unchanged_dim_is_const(self):
        assert _fit_axes((7,), (7,), 216, 512) == \
            [{"kind": "const", "dim": 7}]

    def test_capacity_padded_pow2_is_data(self):
        # pow2 capacity of N=12 -> 16 and N=21 -> 32 fits no integral
        # affine polynomial: stays raw data with both observations
        axes = _fit_axes((16,), (32,), 12, 21)
        assert axes == [{"kind": "data", "observed": [16, 32]}]

    def test_format_axes_renders_every_kind(self):
        s = format_axes([
            {"kind": "const", "dim": 3},
            {"kind": "extensive", "per_n": "1"},
            {"kind": "extensive", "per_n": "4/3"},
            {"kind": "affine", "per_n": "1", "offset": 4},
            {"kind": "data", "observed": [16, 32]},
        ])
        assert s == "[3, N, 4/3N, N+4, data(16..32)]"


# ---------------------------------------------------------------------------
# schema inference on toy entries
# ---------------------------------------------------------------------------


def _toy_grow_entry():
    """A toy with an extensive leaf, a const leaf, an O(tree)-style
    capacity leaf (pow2 of N), and a scalar — plus a grow probe."""

    def make(n):
        cap = 1 << (n - 1).bit_length()

        def fn(x):
            return x * 2.0, jnp.zeros(cap), jnp.float32(1.0)

        return EntryCase(fn=fn, args=(jnp.zeros(n, jnp.float32),))

    @entrypoint("toy_grow", phase_coverage_min=0.0)
    def toy_grow():
        case = make(12)
        return dataclasses.replace(
            case, grow=lambda: (make(21), 21 / 12))

    return toy_grow


class TestEntrySchema:
    def test_rows_and_kinds(self):
        entry = _toy_grow_entry()
        trace = EntryTrace(entry, entry.build())
        row = entry_schema(trace)
        assert row["n_base"] == 12
        assert row["grow"] == "7/4"
        leaves = row["leaves"]
        assert leaves["[0]"]["shape"] == \
            [{"kind": "extensive", "per_n": "1"}]
        assert leaves["[1]"]["shape"] == \
            [{"kind": "data", "observed": [16, 32]}]
        assert leaves["[2]"]["shape"] == []
        assert all(leaf["dtype"] == "float32" for leaf in leaves.values())
        # cached: the second call returns the same object, no retrace
        assert entry_schema(trace) is row

    def test_no_grow_means_const_axes(self):
        @entrypoint("toy_static", phase_coverage_min=0.0)
        def toy_static():
            return EntryCase(fn=lambda x: x @ x.T,
                             args=(jnp.zeros((4, 3)),))

        trace = EntryTrace(toy_static, toy_static.build())
        row = entry_schema(trace)
        assert row["grow"] is None
        assert row["leaves"][""]["shape"] == \
            [{"kind": "const", "dim": 4}, {"kind": "const", "dim": 4}]

    def test_weak_type_recorded(self):
        @entrypoint("toy_weak", phase_coverage_min=0.0)
        def toy_weak():
            # a bare Python-float product leaks a weak-typed output
            return EntryCase(fn=lambda x: (x, x.sum() * 2.0),
                             args=(jnp.zeros(4, jnp.float32),))

        row = entry_schema(EntryTrace(toy_weak, toy_weak.build()))
        weak = {p: leaf["weak_type"] for p, leaf in row["leaves"].items()}
        assert weak == {"[0]": False, "[1]": False}

    def test_schema_diff_names_paths(self):
        entry = _toy_grow_entry()
        row = entry_schema(EntryTrace(entry, entry.build()))
        doctored = json.loads(json.dumps(row))
        doctored["leaves"]["[0]"]["dtype"] = "float64"
        del doctored["leaves"]["[2]"]
        doctored["leaves"]["[9]"] = doctored["leaves"]["[1]"]
        lines = "\n".join(schema_diff("toy_grow", doctored, row))
        assert "~ [0]: float64[N] -> float32[N]" in lines
        assert "+ [2]" in lines and "- [9]" in lines
        assert "+1 -1 ~1 leaves" in lines


# ---------------------------------------------------------------------------
# lock IO
# ---------------------------------------------------------------------------


class TestLockIO:
    def test_roundtrip(self, tmp_path):
        entry = _toy_grow_entry()
        row = entry_schema(EntryTrace(entry, entry.build()))
        path = tmp_path / "schema.json"
        write_lock(path, {"toy_grow": row})
        entries = load_lock(path)
        assert entries["toy_grow"] == row
        assert json.loads(path.read_text())["version"] == SCHEMA_VERSION

    def test_corrupt_and_wrong_version_raise(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(LockError):
            load_lock(bad)
        versioned = tmp_path / "old.json"
        versioned.write_text(json.dumps({"version": 999, "entries": {}}))
        with pytest.raises(LockError):
            load_lock(versioned)
        with pytest.raises(LockError):
            load_lock(tmp_path / "missing.json")


# ---------------------------------------------------------------------------
# the JXA5xx firing fixtures (exact-marker contract, controlled context)
# ---------------------------------------------------------------------------


def _run_with_context(fixture: str, select, **ctx_overrides):
    prev = set_audit_context(
        dataclasses.replace(audit_context(), **ctx_overrides))
    try:
        return Auditor(select=select).run_entries(
            load_fixture_entries(fixture))
    finally:
        set_audit_context(prev)


class TestRuleFixtures:
    def test_jxa501_fires_on_drift_only(self):
        active, _sup, errors, skipped = _run_with_context(
            "jxa501_drift.py", ["JXA501"],
            state_schema_path=str(FIXTURES / "jxa501_schema.json"))
        assert not errors and not skipped
        actual = sorted((f.line, f.rule) for f in active)
        assert actual == expected_findings(FIXTURES / "jxa501_drift.py")
        assert "float64" in active[0].message  # the locked-side aval

    def test_jxa501_skips_when_lock_absent(self, tmp_path):
        active, _sup, errors, _sk = _run_with_context(
            "jxa501_drift.py", ["JXA501"],
            state_schema_path=str(tmp_path / "nonexistent.json"))
        assert not active and not errors

    def test_jxa501_flags_corrupt_lock(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        active, _sup, errors, _sk = _run_with_context(
            "jxa501_drift.py", ["JXA501"], state_schema_path=str(bad))
        assert not errors
        assert {f.rule for f in active} == {"JXA501"}
        assert all("unreadable" in f.message for f in active)

    def test_jxa502_fires_under_vmap_context(self):
        active, _sup, errors, skipped = _run_with_context(
            "jxa502_vmap.py", ["JXA502"], vmap_members=2)
        assert not errors and not skipped
        actual = sorted((f.line, f.rule) for f in active)
        assert actual == expected_findings(FIXTURES / "jxa502_vmap.py")
        msgs = " ".join(f.message for f in active)
        assert "does not trace" in msgs          # vmap_trace_break
        assert "debug_callback" in msgs          # vmap_callback
        assert "serialized loops" in msgs        # vmap_serialized

    def test_jxa502_off_by_default(self):
        active, _sup, errors, _sk = _run_with_context(
            "jxa502_vmap.py", ["JXA502"])  # vmap_members stays 0
        assert not active and not errors

    def test_jxa503_fires_on_open_carries(self):
        active, _sup, errors, skipped = _run_with_context(
            "jxa503_carry.py", ["JXA503"])
        assert not errors and not skipped
        actual = sorted((f.line, f.rule) for f in active)
        assert actual == expected_findings(FIXTURES / "jxa503_carry.py")
        msgs = " ".join(f.message for f in active)
        assert "STRUCTURE" in msgs               # the None<->array flip
        assert "float32[2,8]" in msgs            # the aval drift


class TestVmapProbe:
    def test_clean_entry_report(self):
        @entrypoint("probe_clean", phase_coverage_min=0.0)
        def probe_clean():
            return EntryCase(fn=lambda x: jnp.sin(x),
                             args=(jnp.zeros(8),))

        trace = EntryTrace(probe_clean, probe_clean.build())
        report = vmap_probe(trace, 3)
        assert report["error"] is None
        assert report["callbacks"] == []
        assert report["vmap_loops"] == report["base_loops"] == 0
        # cached per (trace, members)
        assert vmap_probe(trace, 3) is report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


_TOY_REGISTRY = '''
import jax.numpy as jnp

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint


@entrypoint("toy_a", phase_coverage_min=0.0)
def toy_a():
    return EntryCase(fn=lambda x: x * 2.0, args=(jnp.ones(4),))


@entrypoint("toy_b", phase_coverage_min=0.0)
def toy_b():
    return EntryCase(
        fn=lambda x, s: (x + s, s),
        args=(jnp.ones(4), jnp.float32(0.0)),
        carry=lambda a, out: (a[0], out[1]),
    )
'''


class TestCli:
    @pytest.fixture()
    def toy(self, tmp_path):
        reg = tmp_path / "toy_registry.py"
        reg.write_text(_TOY_REGISTRY)
        lock = tmp_path / "schema.json"
        rc = schema_main([str(reg), "--lock", str(lock), "--write",
                          "--cpu-devices", "0"])
        assert rc == 0 and lock.exists()
        return reg, lock

    def test_write_then_verify(self, toy, capsys):
        reg, lock = toy
        rc = schema_main([str(reg), "--lock", str(lock),
                          "--cpu-devices", "0"])
        assert rc == 0
        assert "2/2 entries match" in capsys.readouterr().out

    def test_doctored_dtype_exits_1_with_diff(self, toy, capsys):
        reg, lock = toy
        payload = json.loads(lock.read_text())
        leaf = payload["entries"]["toy_a"]["leaves"][""]
        leaf["dtype"] = "float64"
        lock.write_text(json.dumps(payload))
        rc = schema_main([str(reg), "--lock", str(lock),
                          "--cpu-devices", "0"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "toy_a: state schema drifted" in out
        assert "float64[4] -> float32[4]" in out

    def test_corrupt_lock_exits_2(self, toy):
        reg, lock = toy
        lock.write_text("{not json")
        assert schema_main([str(reg), "--lock", str(lock),
                            "--cpu-devices", "0"]) == 2

    def test_unknown_entry_exits_2(self, toy):
        reg, lock = toy
        assert schema_main([str(reg), "--lock", str(lock),
                            "--entries", "no_such_entry",
                            "--cpu-devices", "0"]) == 2

    def test_stale_and_missing_rows_exit_1(self, toy, capsys):
        reg, lock = toy
        payload = json.loads(lock.read_text())
        payload["entries"]["ghost"] = payload["entries"].pop("toy_b")
        lock.write_text(json.dumps(payload))
        rc = schema_main([str(reg), "--lock", str(lock),
                          "--cpu-devices", "0"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "ghost" in out   # stale row flagged
        assert "toy_b" in out   # unlocked entry flagged
        # an --entries-filtered run must NOT flag staleness
        assert schema_main([str(reg), "--lock", str(lock),
                            "--entries", "toy_a",
                            "--cpu-devices", "0"]) == 0

    def test_mesh_mismatch_rows_are_skipped(self, toy, capsys):
        reg, lock = toy
        payload = json.loads(lock.read_text())
        payload["entries"]["toy_a"]["mesh"] = 99
        lock.write_text(json.dumps(payload))
        rc = schema_main([str(reg), "--lock", str(lock),
                          "--cpu-devices", "0"])
        assert rc == 0  # locked at another mesh: neither drift nor stale
        assert "mesh-skipped" in capsys.readouterr().err

    def test_json_payload(self, toy, capsys):
        reg, lock = toy
        rc = schema_main([str(reg), "--lock", str(lock), "--json",
                          "--cpu-devices", "0"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["tool"] == "statecheck"
        assert {e["entry"] for e in payload["entries"]} == \
            {"toy_a", "toy_b"}
        assert all(e["match"] for e in payload["entries"])
        assert payload["findings"] == []
        assert payload["errors"] == []

    def test_vmap_flag_reports_clean_toys(self, toy, capsys):
        reg, lock = toy
        rc = schema_main([str(reg), "--lock", str(lock), "--vmap",
                          "--members", "3", "--cpu-devices", "0"])
        assert rc == 0
        assert "2/2 single-device entries batch clean over 3 members" \
            in capsys.readouterr().out

    def test_open_carry_fails_via_jxa503(self, tmp_path, capsys):
        reg = tmp_path / "bad_registry.py"
        # feed the f32[4] output back into the SCALAR carry slot
        reg.write_text(_TOY_REGISTRY.replace(
            "carry=lambda a, out: (a[0], out[1])",
            "carry=lambda a, out: (a[0], out[0])"))
        lock = tmp_path / "schema.json"
        assert schema_main([str(reg), "--lock", str(lock), "--write",
                            "--cpu-devices", "0"]) == 0
        rc = schema_main([str(reg), "--lock", str(lock),
                          "--cpu-devices", "0"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "JXA503" in out

    def test_subcommand_reachable_from_audit_cli(self, toy):
        from sphexa_tpu.devtools.audit.cli import main as audit_main

        reg, lock = toy
        assert audit_main(["schema", str(reg), "--lock", str(lock),
                           "--cpu-devices", "0"]) == 0


# ---------------------------------------------------------------------------
# ensemble-mode seed: the vmapped SimState step (ROADMAP item 3)
# ---------------------------------------------------------------------------


class TestEnsembleSeed:
    def test_two_member_sedov_member0_bitwise(self):
        """A 2-member ensemble stepped as ONE vmapped SimState program:
        member 0 (unperturbed) must be bitwise-identical to the plain
        unvmapped step, and the perturbed member must actually diverge —
        the seed the JXA502 gate keeps admissible."""
        from sphexa_tpu import propagator
        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import make_propagator_config
        from sphexa_tpu.state import SimState

        state, box, const = init_sedov(6)
        cfg = make_propagator_config(state, box, const)

        def step(sim):
            return propagator.step_sim_state(
                propagator.step_hydro_std, sim, cfg, None)

        sim0 = SimState(particles=state, box=box)
        out_single, diag_single = step(sim0)

        member1 = SimState(
            particles=dataclasses.replace(state, temp=state.temp * 1.01),
            box=box)
        batched = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                               sim0, member1)
        out, diag = jax.vmap(step)(batched)

        for name in ("x", "y", "z", "vx", "vy", "vz", "temp", "du", "h"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out_single.particles, name)),
                np.asarray(getattr(out.particles, name))[0],
                err_msg=f"member 0 diverges from the unvmapped run: {name}")
        np.testing.assert_array_equal(
            np.asarray(out_single.box.lo), np.asarray(out.box.lo)[0])
        assert not np.array_equal(np.asarray(out.particles.temp)[0],
                                  np.asarray(out.particles.temp)[1]), \
            "perturbed member did not diverge — the ensemble is degenerate"
        assert set(diag) == set(diag_single)
        # aux slots stay empty through the batched step (carry closure)
        assert out.turb is None and out.chem is None and out.bdt is None


# ---------------------------------------------------------------------------
# the committed lock (slow tier; check.sh repeats this cross-process)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCommittedLock:
    def test_package_schema_verifies(self):
        rc = schema_main([
            "--lock", str(REPO_ROOT / DEFAULT_SCHEMA_PATH),
            "--cpu-devices", "0"])
        assert rc == 0
