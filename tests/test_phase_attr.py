"""Chip-harvest observability: the in-graph phase-attribution contract.

Three pins, one per leg of the time-and-history stack (schema v4):

- named-scope presence: every propagator's lowered step IR must carry
  the expected ``sphexa/<phase>`` scope paths in its op locations, so a
  refactor cannot silently strip the attribution a chip capture relies
  on (the HLO pin the traceview renderer points at);
- traceview parsing: the committed miniature capture fixture
  (tests/trace_fixture: one xplane.pb + one perfetto dump from a tiny
  3-scope program) must attribute through the generic protobuf walk —
  scope maps, computation inheritance, base-name fallback, coverage
  gate exit codes;
- crash flight recorder: blackbox.json + the first-class ``crash``
  event on abnormal exit, including a genuinely killed child process.
"""

import io
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from sphexa_tpu.util.phases import PHASES, named_phase, phase_scope

FIXTURE = os.path.join(os.path.dirname(__file__), "trace_fixture")


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------


class TestTaxonomy:
    def test_phases_unique_and_wellformed(self):
        assert len(PHASES) == len(set(PHASES))
        from sphexa_tpu.telemetry.traceview import PHASE_RE

        for p in PHASES:
            m = PHASE_RE.search(f"jit(step)/jit(main)/sphexa/{p}/op")
            assert m and m.group(1) == p  # the renderer can key on it

    def test_unknown_phase_rejected(self):
        with pytest.raises(AssertionError):
            phase_scope("not-a-phase")
        with pytest.raises(AssertionError):
            named_phase("bogus")


# ---------------------------------------------------------------------------
# named-scope presence in lowered step IR (one per propagator)
# ---------------------------------------------------------------------------

#: phases every SPH step must stamp
_COMMON = ("sort", "neighbors", "eos", "iad", "momentum-energy",
           "timestep", "integrate", "ledger")
_EXPECT = {
    "std": _COMMON + ("density",),
    "ve": _COMMON + ("xmass", "gradh", "divv-curlv", "av-switches"),
    "turb-ve": _COMMON + ("xmass", "gradh", "divv-curlv", "av-switches",
                          "turbulence"),
    "std-cooling": _COMMON + ("density", "cooling"),
    "nbody": ("sort", "gravity-upsweep", "gravity-mac", "gravity-m2p",
              "gravity-p2p", "timestep", "integrate", "ledger"),
}


def _lowered_ir(prop):
    """Debug-info StableHLO text of one lowered (NOT compiled) step of
    ``prop`` at audit scale (side 6), built through the real Simulation
    machinery so the lowered program IS the production one."""
    import dataclasses as dc

    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.observables import ObservableSpec
    from sphexa_tpu.simulation import _PROPAGATORS, Simulation

    state, box, const = init_sedov(6)
    if prop == "nbody":
        const = dc.replace(const, g=1.0)
    sim = Simulation(state, box, const, prop=prop, block=512,
                     obs_spec=ObservableSpec())
    fn = _PROPAGATORS[prop]
    if prop == "turb-ve":
        aux = (sim.turb_state, sim.turb_cfg)
    elif prop == "std-cooling":
        aux = (sim.chem, sim.cooling_cfg)
    else:
        aux = ()
    lowered = fn.lower(sim.state, sim.box, sim._cfg, sim._gtree, *aux)
    buf = io.StringIO()
    lowered.compiler_ir(dialect="stablehlo").operation.print(
        file=buf, enable_debug_info=True)
    return buf.getvalue()


class TestNamedScopePins:
    @pytest.mark.parametrize("prop", sorted(_EXPECT))
    def test_step_ir_carries_phase_scopes(self, prop):
        """A refactor that drops a stage's named scope strips the chip
        capture's attribution without failing any numeric test — THIS
        is the test that fails instead."""
        ir = _lowered_ir(prop)
        missing = [p for p in _EXPECT[prop] if f"sphexa/{p}" not in ir]
        assert not missing, (
            f"{prop} step lost named scopes for {missing} "
            f"(util/phases.py taxonomy; wrap the stage again)")
        # and nothing outside the taxonomy leaked in
        import re

        seen = set(re.findall(r"sphexa/([A-Za-z0-9_.:+-]+?)[/\"]", ir))
        assert seen <= set(PHASES), f"unknown phases stamped: " \
                                    f"{seen - set(PHASES)}"


# ---------------------------------------------------------------------------
# traceview over the committed fixture
# ---------------------------------------------------------------------------


class TestTraceview:
    def test_fixture_attributes_phases(self):
        from sphexa_tpu.telemetry.traceview import summarize_trace

        s = summarize_trace(FIXTURE)
        assert s["device_op_events"] > 0
        assert s["total_device_us"] > 0
        phases = {p["phase"] for p in s["phases"]}
        assert {"density", "momentum-energy", "neighbors"} <= phases
        # the fixture's cumsum lowers to a metadata-less reduce-window:
        # computation inheritance must still attribute the neighbors bulk
        nb = next(p for p in s["phases"] if p["phase"] == "neighbors")
        assert nb["us"] > 0
        assert s["coverage"] > 0.5
        assert abs(sum(p["share"] for p in s["phases"])
                   - s["coverage"]) < 1e-9

    def test_cli_exit_codes(self, tmp_path, capsys):
        from sphexa_tpu.telemetry.cli import main as cli_main

        assert cli_main(["trace", FIXTURE]) == 0
        out = capsys.readouterr().out
        assert "density" in out and "attributed:" in out
        # the chip-harvest gate: coverage below the floor fails
        assert cli_main(["trace", FIXTURE, "--min-coverage", "0.999"]) == 1
        capsys.readouterr()
        assert cli_main(["trace", FIXTURE, "--format", "json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["coverage"] > 0.5
        # no capture at all is a usage error, not a silent pass
        assert cli_main(["trace", str(tmp_path / "nope")]) == 2

    def test_json_fallback_without_xplane(self, tmp_path, capsys):
        """A dir holding only the perfetto dump parses through the json
        fallback: device ops are found, but without the xplane's HLO
        metadata nothing attributes — and the CLI must FAIL (exit 1)
        instead of blessing an unattributable capture."""
        import shutil

        from sphexa_tpu.telemetry.cli import main as cli_main
        from sphexa_tpu.telemetry.traceview import summarize_trace

        d = tmp_path / "jsononly"
        d.mkdir()
        shutil.copy(os.path.join(FIXTURE, "vm.trace.json.gz"), d)
        s = summarize_trace(str(d))
        assert s["device_op_events"] > 0
        assert s["phases"] == []
        assert cli_main(["trace", str(d)]) == 1
        assert "no sphexa/ phases" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# crash flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_dump_writes_blackbox_and_crash_event(self, tmp_path):
        from sphexa_tpu.telemetry import (
            FlightRecorder,
            JsonlSink,
            Telemetry,
            read_blackbox,
        )
        from sphexa_tpu.telemetry.registry import validate_event

        run = str(tmp_path)
        rec = FlightRecorder(run, capacity=3, telemetry=None)
        tel = Telemetry(sinks=[JsonlSink(os.path.join(run, "events.jsonl")),
                               rec.sink])
        rec.telemetry = tel
        for i in range(5):
            tel.event("launch", it=i)
        path = rec.dump(reason="unit-test crash", tb="Traceback: boom")
        assert path and os.path.exists(path)
        box = read_blackbox(run)
        assert box["reason"] == "unit-test crash"
        assert len(box["events"]) == 3  # ring capacity, newest kept
        assert box["events"][-1]["it"] == 4
        assert box["watchdogs"]["events_total"] == 5
        # first cause wins: a later cascade must not overwrite it
        assert rec.dump(reason="second") is None
        assert read_blackbox(run)["reason"] == "unit-test crash"
        # the crash landed as a first-class v4 event in the stream
        events = [json.loads(l)
                  for l in open(os.path.join(run, "events.jsonl"))]
        crash = [e for e in events if e["kind"] == "crash"]
        assert len(crash) == 1
        assert crash[0]["reason"] == "unit-test crash"
        assert validate_event(crash[0]) == []
        # the crash event continues the run's REAL seq (monotone-per-run
        # envelope contract), not the ring-buffer length
        assert crash[0]["seq"] == events[-2]["seq"] + 1 == 5

    def test_summary_and_science_explain_the_crash(self, tmp_path, capsys):
        from sphexa_tpu.telemetry import (
            FlightRecorder,
            JsonlSink,
            Telemetry,
        )
        from sphexa_tpu.telemetry.cli import main as cli_main
        from sphexa_tpu.telemetry.manifest import write_manifest

        run = str(tmp_path)
        rec = FlightRecorder(run, telemetry=None)
        tel = Telemetry(sinks=[JsonlSink(os.path.join(run, "events.jsonl")),
                               rec.sink])
        rec.telemetry = tel
        tel.event("step", it=1, wall_s=0.1)
        tel.count("rollbacks", 2)
        rec.dump(reason="signal SIGTERM (15)", tb="fake stack")
        write_manifest(run, particles=64)
        assert cli_main(["summary", run]) == 0
        out = capsys.readouterr().out
        assert "CRASH: signal SIGTERM (15)" in out
        assert "rollbacks=2" in out
        assert cli_main(["science", run]) == 1  # still no physics events
        assert "CRASH:" in capsys.readouterr().out
        # --strict: the appended crash event is schema-valid v4
        assert cli_main(["summary", run, "--strict"]) == 0

    def test_close_disarms_cleanly(self, tmp_path):
        from sphexa_tpu.telemetry import FlightRecorder

        rec = FlightRecorder(str(tmp_path))
        rec.install()
        assert rec._installed
        rec.close()
        assert not rec._installed
        rec._on_atexit()  # even a stray atexit call stays silent now
        assert not os.path.exists(tmp_path / "blackbox.json")
        # nothing faulted: the empty fault.log is tidied away too
        assert not os.path.exists(tmp_path / "fault.log")

    def test_ignored_signal_stays_ignored(self, tmp_path):
        """A deliberately-ignored signal (nohup's SIGHUP) must not be
        hooked: it would fabricate a crash record in a run that then
        survives; and install/close must round-trip the original
        disposition for hooked signals."""
        import signal as _signal

        from sphexa_tpu.telemetry import FlightRecorder

        prev_hup = _signal.signal(_signal.SIGHUP, _signal.SIG_IGN)
        try:
            rec = FlightRecorder(str(tmp_path))
            rec.install()
            assert _signal.getsignal(_signal.SIGHUP) is _signal.SIG_IGN
            assert _signal.SIGHUP not in rec._prev_signals
            assert _signal.getsignal(_signal.SIGTERM) == rec._on_signal
            rec.close()
            assert not os.path.exists(tmp_path / "blackbox.json")
        finally:
            _signal.signal(_signal.SIGHUP, prev_hup)

    def test_killed_child_leaves_blackbox(self, tmp_path):
        """The real contract: a child process running a flight-recorded
        event loop is SIGTERMed mid-run and must leave blackbox.json +
        the crash event, with the buffered tail intact. jax-free child
        (the telemetry package contract), so the spawn is cheap."""
        run = str(tmp_path / "run")
        script = textwrap.dedent(f"""
            import os, sys, time
            from sphexa_tpu.telemetry import (FlightRecorder, JsonlSink,
                                              Telemetry)
            run = {run!r}
            rec = FlightRecorder(run, capacity=50, telemetry=None)
            tel = Telemetry(sinks=[
                JsonlSink(os.path.join(run, "events.jsonl")), rec.sink])
            rec.telemetry = tel
            rec.install()
            tel.event("launch", it=0)
            print("READY", flush=True)
            for i in range(1, 10**9):
                tel.event("launch", it=i)
                time.sleep(0.01)
        """)
        env = dict(os.environ, PYTHONPATH=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        proc = subprocess.Popen([sys.executable, "-c", script],
                                stdout=subprocess.PIPE, env=env, text=True)
        try:
            line = proc.stdout.readline()
            assert "READY" in line
            time.sleep(0.3)  # let a few events buffer
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert rc != 0  # died by signal, conventional nonzero status
        from sphexa_tpu.telemetry import read_blackbox

        box = read_blackbox(run)
        assert box is not None
        assert "SIGTERM" in box["reason"]
        assert box["events"] and box["events"][-1]["kind"] == "launch"
        events = [json.loads(l)
                  for l in open(os.path.join(run, "events.jsonl"))]
        assert events[-1]["kind"] == "crash"
        assert "SIGTERM" in events[-1]["reason"]
