"""App-layer long tail: file-split up-sampling, glass-block templates,
chemistry checkpointing, the evrard/gresho-chan comparators, and the
restart bookkeeping fixes (dump naming, constants.txt truncation,
float -w catch-up)."""

import json
import os

import numpy as np
import pytest

from sphexa_tpu.app.main import main as app_main
from sphexa_tpu.init import make_initializer
from sphexa_tpu.init.file_init import init_file_split, parse_split_spec
from sphexa_tpu.init.glass import (
    assemble_glass_cuboid,
    read_template_block,
    set_glass_template,
)
from sphexa_tpu.init.sedov import init_sedov
from sphexa_tpu.io import write_snapshot


@pytest.fixture
def small_dump(tmp_path):
    state, box, const = init_sedov(8)
    path = str(tmp_path / "dump_small.h5")
    write_snapshot(path, state, box, const, iteration=3, case="sedov")
    return path, state, box, const


class TestFileSplit:
    def test_parse(self):
        assert parse_split_spec("dump.h5,4") == ("dump.h5", 4)
        assert parse_split_spec("dump.h5") is None
        assert parse_split_spec("dump.h5,0") is None
        assert parse_split_spec("dump.h5,x") is None

    def test_split_conserves_mass_and_scales_h(self, small_dump):
        path, state, _, _ = small_dump
        new_state, box, const = init_file_split(path, 4)
        assert new_state.n == 4 * state.n
        np.testing.assert_allclose(
            float(np.sum(np.asarray(new_state.m))),
            float(np.sum(np.asarray(state.m))), rtol=1e-5,
        )
        # h scaled by N^(-1/3) (file_init.hpp:222)
        np.testing.assert_allclose(
            np.asarray(new_state.h).max(),
            np.asarray(state.h).max() * 4 ** (-1 / 3), rtol=1e-5,
        )
        # clock restarted, dt reduced 100*N
        assert float(new_state.ttot) == 0.0
        assert float(new_state.min_dt) == pytest.approx(
            float(state.min_dt) / 400.0
        )
        # interpolated positions stay inside the box
        for a, d in (("x", 0), ("y", 1), ("z", 2)):
            v = np.asarray(getattr(new_state, a))
            assert v.min() >= float(box.lo[d]) - 1e-6
            assert v.max() <= float(box.hi[d]) + 1e-6

    def test_split_factory_and_steps(self, small_dump):
        from sphexa_tpu.simulation import Simulation

        path, state, _, _ = small_dump
        init = make_initializer(f"{path},2")
        new_state, box, const = init(None)
        assert new_state.n == 2 * state.n
        sim = Simulation(new_state, box, const, prop="std", block=512)
        d = sim.step()
        assert np.isfinite(d["dt"]) and d["dt"] > 0


class TestGlass:
    def _template(self, tmp_path, n=5):
        import h5py

        from sphexa_tpu.init.glass import jittered_lattice

        x, y, z = jittered_lattice((0, 0, 0), (1, 1, 1), (n, n, n), seed=7)
        path = str(tmp_path / "glass.h5")
        with h5py.File(path, "w") as f:
            f["x"], f["y"], f["z"] = x, y, z
        return path

    def test_read_and_tile(self, tmp_path):
        path = self._template(tmp_path)
        tpl = read_template_block(path)
        for v in tpl:
            assert v.min() >= 0.0 and v.max() < 1.0
        x, y, z = assemble_glass_cuboid(tpl, (-1, -1, -1), (1, 1, 1),
                                        (10, 10, 10))
        assert len(x) == 125 * 8  # 5^3 template tiled 2x2x2
        assert x.min() >= -1.0 and x.max() < 1.0

    def test_template_drives_cases(self, tmp_path):
        path = self._template(tmp_path)
        set_glass_template(path)
        try:
            state, box, const = init_sedov(10)
        finally:
            set_glass_template(None)
        assert state.n == 1000  # 5^3 x 2^3
        # and the clean lattice returns without the template
        state2, _, _ = init_sedov(10)
        assert state2.n == 1000


class TestChemistryCheckpoint:
    def test_round_trip(self):
        from sphexa_tpu.physics.cooling import (
            ChemistryData,
            chemistry_from_fields,
            chemistry_to_fields,
        )

        chem = ChemistryData.ionized(32)
        fields = chemistry_to_fields(chem)
        assert set(fields) == {
            "chem_hi", "chem_hii", "chem_hei", "chem_heii", "chem_heiii",
            "chem_e", "chem_metal",
        }
        back = chemistry_from_fields(fields)
        np.testing.assert_array_equal(np.asarray(back.hii),
                                      np.asarray(chem.hii))


class TestComparators:
    def test_gresho_profile_zero_error_on_exact(self):
        from sphexa_tpu.analysis.gresho_chan import (
            gresho_chan_l1,
            gresho_chan_vphi,
        )

        rng = np.random.default_rng(0)
        x = rng.uniform(-0.5, 0.5, 4000)
        y = rng.uniform(-0.5, 0.5, 4000)
        r = np.sqrt(x * x + y * y)
        vphi = gresho_chan_vphi(r)
        vx = -vphi * y / np.maximum(r, 1e-12)
        vy = vphi * x / np.maximum(r, 1e-12)
        assert gresho_chan_l1(x, y, vx, vy) < 1e-12

    def test_gresho_ic_matches_analytic(self):
        from sphexa_tpu.analysis.gresho_chan import gresho_chan_l1
        from sphexa_tpu.init.gresho_chan import init_gresho_chan

        state, box, const = init_gresho_chan(16)
        l1 = gresho_chan_l1(state.x, state.y, state.vx, state.vy)
        assert l1 < 1e-5, l1

    def test_evrard_norms(self):
        from sphexa_tpu.analysis.evrard import (
            evrard_normalized_profiles,
            evrard_norms,
        )

        n = evrard_norms(R=1.0, M=1.0, G=1.0)
        assert n["time"] == pytest.approx(np.sqrt(np.pi**2 / 8.0))
        assert n["rho"] == pytest.approx(3.0 / (4 * np.pi))
        fields = {
            "r": np.linspace(0.01, 1.0, 500),
            "rho": np.full(500, n["rho"]),
            "u": np.full(500, 0.05),
            "vel": np.zeros(500),
        }
        prof = evrard_normalized_profiles(fields, time=0.0)
        assert prof["t_norm"] == 0.0
        mask = prof["rho_profile"] > 0
        np.testing.assert_allclose(prof["rho_profile"][mask], 1.0, rtol=1e-6)


class TestRestartBookkeeping:
    def test_restart_appends_to_case_dump_and_truncates_constants(
        self, tmp_path
    ):
        import h5py

        out = str(tmp_path)
        rc = app_main(["--init", "sedov", "-n", "8", "-s", "4", "-w", "2",
                       "-o", out, "--quiet"])
        assert rc in (0, None)
        dump = f"{out}/dump_sedov.h5"
        assert os.path.exists(dump)
        with h5py.File(dump, "r") as f:
            steps_before = sorted(f.keys())

        rows_before = open(f"{out}/constants.txt").readlines()

        # restart from step 0 (iteration 2): the dump must gain Step#n
        # groups under the SAME name, and constants.txt must drop rows
        # beyond the restart point
        rc = app_main(["--init", f"{dump}:0", "-s", "6", "-w", "2",
                       "-o", out, "--quiet"])
        assert rc in (0, None)
        with h5py.File(dump, "r") as f:
            steps_after = sorted(f.keys())
        assert len(steps_after) > len(steps_before)
        assert not [p for p in os.listdir(out)
                    if p.startswith("dump_") and p != "dump_sedov.h5"
                    and not p.endswith(".txt")]

        rows = [ln for ln in open(f"{out}/constants.txt")
                if not ln.startswith("#")]
        its = [int(float(ln.split()[0])) for ln in rows]
        assert its == sorted(its), "constants.txt iterations not monotonic"

    def test_float_w_schedule_catches_up(self, tmp_path):
        # a single step crossing several -w intervals must advance the
        # schedule past t_now (one dump, not a burst of redundant ones)
        out = str(tmp_path)
        rc = app_main(["--init", "sedov", "-n", "8", "-s", "3",
                       "-w", "1e-9", "-o", out, "--quiet"])
        assert rc in (0, None)
        import h5py

        with h5py.File(f"{out}/dump_sedov.h5", "r") as f:
            # every step crosses many 1e-9 intervals; exactly one dump per
            # iteration (3) + none extra
            assert len([k for k in f.keys() if k.startswith("Step#")]) <= 4


def test_profile_substep_breakdown(tmp_path):
    """--profile writes the per-substep breakdown (the reference's
    per-phase Timer, util/timer.hpp) alongside the iteration series."""
    import numpy as np

    from sphexa_tpu.app.main import main

    rc = main(["--init", "sedov", "-n", "10", "-s", "2", "--quiet",
               "--profile", "-o", str(tmp_path)])
    assert rc == 0
    data = np.load(str(tmp_path / "profile.npz"))
    subs = [k for k in data.files if k.startswith("substep_")]
    # the pallas engine path reports the pipeline stages; the xla path
    # (CPU default suite) reports none but must not crash
    import jax

    if jax.default_backend() == "tpu":
        assert "substep_momentum_energy" in subs


@pytest.mark.slow
def test_substep_breakdown_ve_pallas():
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation
    from sphexa_tpu.util.substep_profile import substep_breakdown

    state, box, const = init_sedov(10)
    sim = Simulation(state, box, const, prop="ve", block=512,
                     backend="pallas")
    sim.step()
    sub = substep_breakdown(sim, iters=1)
    for key in ("sort", "neighbor_prologue", "xmass", "ve_def_gradh",
                "eos", "iad", "divv_curlv", "av_switches",
                "momentum_energy"):
        assert key in sub and sub[key] >= 0.0


@pytest.mark.slow
def test_sharded_dump_restart_cli(tmp_path):
    """CLI round trip of the parallel file-per-shard snapshots: a mesh
    run dumps P part files (no base file), a restart from the BASE path
    reassembles them, CONTINUES the iteration count and appends new
    part dumps; a fresh run into the same out_dir removes the stale
    part set. Fresh subprocess via conftest.run_mesh_subprocess."""
    from conftest import run_mesh_subprocess

    out = str(tmp_path)
    code = f"""
        import glob, os
        from sphexa_tpu.app.main import main as app_main
        from sphexa_tpu.io.snapshot import read_step_attrs

        out = {out!r}
        rc = app_main(["--init", "sedov", "-n", "16", "-s", "2", "-w", "1",
                       "-o", out, "--devices", "8", "--quiet"])
        assert rc in (0, None), rc
        base = f"{{out}}/dump_sedov.h5"
        parts = sorted(glob.glob(f"{{out}}/dump_sedov.part*of*.h5"))
        assert len(parts) == 8 and not os.path.exists(base), parts

        # restart from the sharded BASE path: continues the iteration
        # count and appends new part dumps (verified via the snapshot
        # attrs, not just the exit code)
        rc = app_main(["--init", base, "-s", "4", "-w", "1", "-o", out,
                       "--devices", "8", "--quiet"])
        assert rc in (0, None), rc
        attrs = read_step_attrs(base, step=-1)
        assert int(attrs["iteration"]) == 4, attrs["iteration"]

        # a FRESH (non-restart) run must clear the stale part set first
        rc = app_main(["--init", "sedov", "-n", "16", "-s", "1", "-w", "1",
                       "-o", out, "--devices", "8", "--quiet"])
        assert rc in (0, None), rc
        import h5py
        with h5py.File(sorted(glob.glob(
                f"{{out}}/dump_sedov.part*of*.h5"))[0], "r") as f:
            # fresh run: exactly the new dumps, no appended old steps
            assert len([k for k in f.keys() if k.startswith("Step#")]) <= 2
        print("SHARDED-DUMP-OK")
    """
    r = run_mesh_subprocess(code)
    assert "SHARDED-DUMP-OK" in r.stdout, r.stderr[-2000:]
