"""Golden-data kernel fixtures (VERDICT r3 missing #7): every core SPH
pair kernel pinned against an INDEPENDENT pure-numpy f64 oracle computed
directly from the published formulas with the TRUE sinc kernel — the
analog of the reference's hard-coded 125-particle fixtures
(sph/test/ve.cpp:26-80 + example_data.txt), re-derived rather than
copied.

Independence: the oracle below shares NOTHING with sphexa_tpu's op
implementations — brute-force O(N^2) f64 pair loops, analytic
sin(pi v/2)^n kernel (not the polynomial fit the ops evaluate), its own
minimum-image fold. A correlated bug in both backends (XLA and Pallas
agree with each other by the interpret-equivalence tests) would still
fail here. The tolerance budget is the W poly-fit accuracy (~1e-5
relative), so these tests double as fit-accuracy pins.
"""

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import hydro_std


# --------------------------------------------------------------------------
# pure-numpy f64 oracle (true sinc kernel, brute-force pairs)
# --------------------------------------------------------------------------


def W_true(v, n, K, h):
    """3-D sinc^n kernel W(v)/h^3, v = r/h in [0, 2), analytic form."""
    with np.errstate(invalid="ignore", divide="ignore"):
        x = np.pi * v / 2.0
        s = np.where(v > 0, np.sin(x) / np.where(x > 0, x, 1.0), 1.0)
    w = np.where(v < 2.0, s ** n, 0.0)
    return K * w / h ** 3


def fold(d, L):
    return d - L * np.round(d / L)


class Oracle:
    """All-pairs f64 evaluation of the std pipeline on a small config."""

    def __init__(self, x, y, z, h, m, vx, vy, vz, temp, const, L):
        self.x, self.y, self.z, self.h, self.m = x, y, z, h, m
        self.vx, self.vy, self.vz = vx, vy, vz
        self.temp, self.const, self.L = temp, const, L
        n = len(x)
        rx = fold(x[:, None] - x[None, :], L)
        ry = fold(y[:, None] - y[None, :], L)
        rz = fold(z[:, None] - z[None, :], L)
        d = np.sqrt(rx * rx + ry * ry + rz * rz)
        self.rx, self.ry, self.rz, self.d = rx, ry, rz, d
        self.n = n
        K, sn = float(const.K), float(const.sinc_index)
        vi = d / h[:, None]
        self.pair_i = (d < 2.0 * h[:, None]) & ~np.eye(n, dtype=bool)
        # min-h symmetric momentum mask (SimConstants.sym_pairs semantics)
        self.pair_sym = self.pair_i & (d < 2.0 * h[None, :])
        self.Wi = W_true(vi, sn, K, h[:, None])  # W(|r|/h_i)/h_i^3
        self.Wj = W_true(d / h[None, :], sn, K, h[None, :])

    def density(self):
        c = self.const
        W_self = W_true(np.zeros(self.n), float(c.sinc_index), float(c.K),
                        self.h)
        rho = self.m * W_self + np.sum(
            np.where(self.pair_i, self.m[None, :] * self.Wi, 0.0), axis=1)
        return rho

    def iad(self, rho):
        vol = self.m / rho
        out = []
        for a, b in ((self.rx, self.rx), (self.rx, self.ry),
                     (self.rx, self.rz), (self.ry, self.ry),
                     (self.ry, self.rz), (self.rz, self.rz)):
            out.append(np.sum(np.where(
                self.pair_i, a * b * vol[None, :] * self.Wi, 0.0), axis=1))
        t11, t12, t13, t22, t23, t33 = out
        # direct 3x3 inverse per particle (the ops renormalize exponents;
        # the inverse is the same)
        C = np.zeros((self.n, 6))
        for i in range(self.n):
            T = np.array([[t11[i], t12[i], t13[i]],
                          [t12[i], t22[i], t23[i]],
                          [t13[i], t23[i], t33[i]]])
            Ti = np.linalg.inv(T)
            C[i] = (Ti[0, 0], Ti[0, 1], Ti[0, 2], Ti[1, 1], Ti[1, 2],
                    Ti[2, 2])
        return C

    def momentum_energy_std(self, rho, p, c_s, C):
        """momentum_energy_kern.hpp (std): symmetrized IAD-projected
        pressure gradient + constant-alpha AV, min-h symmetric pairs."""
        n = self.n
        m, h = self.m, self.h
        vx, vy, vz = self.vx, self.vy, self.vz
        ax = np.zeros(n); ay = np.zeros(n); az = np.zeros(n)
        du = np.zeros(n)
        for i in range(n):
            js = np.nonzero(self.pair_sym[i])[0]
            if len(js) == 0:
                continue
            rxi, ryi, rzi = self.rx[i, js], self.ry[i, js], self.rz[i, js]
            dij = self.d[i, js]
            Wi = self.Wi[i, js]
            Wj = self.Wj[i, js]
            vxij = vx[i] - vx[js]
            vyij = vy[i] - vy[js]
            vzij = vz[i] - vz[js]
            rv = rxi * vxij + ryi * vyij + rzi * vzij
            wij = rv / dij
            visc = 0.5 * np.where(
                wij < 0.0, -(0.5 * (c_s[i] + c_s[js]) - 2.0 * wij) * wij,
                0.0)
            tAi = np.stack([
                C[i, 0] * rxi + C[i, 1] * ryi + C[i, 2] * rzi,
                C[i, 1] * rxi + C[i, 3] * ryi + C[i, 4] * rzi,
                C[i, 2] * rxi + C[i, 4] * ryi + C[i, 5] * rzi])
            tAj = np.stack([
                C[js, 0] * rxi + C[js, 1] * ryi + C[js, 2] * rzi,
                C[js, 1] * rxi + C[js, 3] * ryi + C[js, 4] * rzi,
                C[js, 2] * rxi + C[js, 4] * ryi + C[js, 5] * rzi])
            mj = m[js]
            a = Wi * (mj * p[i] / rho[i] ** 2 + visc * m[i] / rho[i])
            b = mj / rho[js] * Wj * (p[js] / rho[js] + visc)
            ax[i] = np.sum(a * tAi[0] + b * tAj[0])
            ay[i] = np.sum(a * tAi[1] + b * tAj[1])
            az[i] = np.sum(a * tAi[2] + b * tAj[2])
            a_e = Wi * (2.0 * mj * p[i] / rho[i] ** 2 + visc * m[i] / rho[i])
            b_e = visc * mj / rho[js] * Wj
            du[i] = -0.5 * np.sum(
                vxij * (a_e * tAi[0] + b_e * tAj[0])
                + vyij * (a_e * tAi[1] + b_e * tAj[1])
                + vzij * (a_e * tAi[2] + b_e * tAj[2]))
        return ax, ay, az, du


def _config(seed=11, side=5):
    """Deterministic jittered-lattice fixture inside a unit periodic box."""
    rng = np.random.default_rng(seed)
    n = side ** 3
    lin = (np.arange(side) + 0.5) / side - 0.5
    zz, yy, xx = np.meshgrid(lin, lin, lin, indexing="ij")
    dx = 1.0 / side
    x = (xx.ravel() + rng.uniform(-0.2, 0.2, n) * dx).astype(np.float64)
    y = (yy.ravel() + rng.uniform(-0.2, 0.2, n) * dx).astype(np.float64)
    z = (zz.ravel() + rng.uniform(-0.2, 0.2, n) * dx).astype(np.float64)
    h = (dx * (1.4 + 0.25 * rng.uniform(0, 1, n))).astype(np.float64)
    m = (1.0 / n * (1.0 + 0.1 * rng.uniform(-1, 1, n))).astype(np.float64)
    vx, vy, vz = (rng.normal(0, 0.1, n) for _ in range(3))
    temp = np.abs(rng.normal(1.0, 0.2, n))
    return x, y, z, h, m, vx, vy, vz, temp


def _run_ops(x, y, z, h, m, vx, vy, vz, temp):
    """Drive the XLA ops exactly as step_hydro_std does (the
    interpret-equivalence tests pin Pallas == XLA, closing the
    triangle oracle == XLA == Pallas)."""
    from sphexa_tpu.sfc.box import BoundaryType, Box

    box = Box.create(-0.5, 0.5, boundary=BoundaryType.periodic)
    f32 = lambda a: jnp.asarray(a, jnp.float32)

    keys = np.asarray(compute_sfc_keys(f32(x), f32(y), f32(z), box))
    order = np.argsort(keys)
    sx, sy, sz, sh, sm = (f32(np.asarray(a)[order])
                          for a in (x, y, z, h, m))
    svx, svy, svz, stemp = (f32(np.asarray(a)[order])
                            for a in (vx, vy, vz, temp))
    skeys = jnp.asarray(keys[order])

    import types

    st = types.SimpleNamespace(n=len(x), x=sx, y=sy, z=sz, h=sh)
    cfg = make_propagator_config(st, box, CONST, block=512, backend="xla",
                                 ngmax=150)
    nbr = cfg.nbr
    nidx, nmask, nc, occ = find_neighbors(sx, sy, sz, sh, skeys, box, nbr)
    assert int(occ) <= nbr.cap
    rho = hydro_std.compute_density(sx, sy, sz, sh, sm, nidx, nmask,
                                    box, CONST, 512)
    p, c_s = hydro_std.compute_eos_std(stemp, rho, CONST)
    cs6 = hydro_std.compute_iad(sx, sy, sz, sh, sm / rho, nidx, nmask,
                                box, CONST, 512)
    ax, ay, az, du, _ = hydro_std.compute_momentum_energy_std(
        sx, sy, sz, svx, svy, svz, sh, sm, rho, p, c_s, *cs6,
        nidx, nmask, box, CONST, 512)
    inv = np.argsort(order)
    back = lambda a: np.asarray(a, np.float64)[inv]
    return (back(rho), back(p), back(c_s),
            tuple(back(a) for a in cs6),
            back(ax), back(ay), back(az), back(du))


CONST = None


def setup_module(module):
    global CONST
    _, _, const = init_sedov(4)
    CONST = const


def test_density_matches_f64_oracle():
    x, y, z, h, m, vx, vy, vz, temp = _config()
    o = Oracle(x, y, z, h, m, vx, vy, vz, temp, CONST, 1.0)
    rho_g = o.density()
    rho, *_ = _run_ops(x, y, z, h, m, vx, vy, vz, temp)
    np.testing.assert_allclose(rho, rho_g, rtol=5e-5)


def test_iad_matches_f64_oracle():
    x, y, z, h, m, vx, vy, vz, temp = _config()
    o = Oracle(x, y, z, h, m, vx, vy, vz, temp, CONST, 1.0)
    rho_g = o.density()
    C_g = o.iad(rho_g)
    _, _, _, cs6, *_ = _run_ops(x, y, z, h, m, vx, vy, vz, temp)
    # op order: c11, c12, c13, c22, c23, c33
    for k in range(6):
        np.testing.assert_allclose(cs6[k], C_g[:, k], rtol=2e-3,
                                   atol=2e-3 * np.abs(C_g[:, k]).max())


def test_momentum_energy_matches_f64_oracle():
    x, y, z, h, m, vx, vy, vz, temp = _config()
    o = Oracle(x, y, z, h, m, vx, vy, vz, temp, CONST, 1.0)
    rho_g = o.density()
    C_g = o.iad(rho_g)
    gamma, cv = float(CONST.gamma), float(CONST.cv)
    u = cv * temp
    p_g = rho_g * (gamma - 1.0) * u
    c_g = np.sqrt(gamma * (gamma - 1.0) * u)
    axg, ayg, azg, dug = o.momentum_energy_std(rho_g, p_g, c_g, C_g)
    _, p, c_s, _, ax, ay, az, du = _run_ops(x, y, z, h, m, vx, vy, vz,
                                            temp)
    np.testing.assert_allclose(p, p_g, rtol=1e-4)
    scale = np.abs(axg).max()
    for got, want in ((ax, axg), (ay, ayg), (az, azg)):
        np.testing.assert_allclose(got, want, rtol=5e-3,
                                   atol=2e-3 * scale)
    np.testing.assert_allclose(du, dug, rtol=5e-3,
                               atol=2e-3 * np.abs(dug).max())


def test_oracle_pairwise_energy_identity():
    """The oracle itself must satisfy Sum m (du + v.a) = 0 exactly (the
    antisymmetry the sym_pairs cutoff restores) — guards the ORACLE.

    EQUAL masses: the std AV term (momentum_energy_kern.hpp's
    visc*m_i/rho_i + visc*m_j/rho_j pairing) conserves pairwise only
    for m_i = m_j — the reference's operating assumption for std runs
    (the VE form conserves for any masses)."""
    x, y, z, h, m, vx, vy, vz, temp = _config()
    m = np.full_like(m, float(m.mean()))
    o = Oracle(x, y, z, h, m, vx, vy, vz, temp, CONST, 1.0)
    rho_g = o.density()
    C_g = o.iad(rho_g)
    gamma, cv = float(CONST.gamma), float(CONST.cv)
    u = cv * temp
    p_g = rho_g * (gamma - 1.0) * u
    c_g = np.sqrt(gamma * (gamma - 1.0) * u)
    axg, ayg, azg, dug = o.momentum_energy_std(rho_g, p_g, c_g, C_g)
    work = np.sum(m * (vx * axg + vy * ayg + vz * azg))
    heat = np.sum(m * dug)
    scale = max(abs(work), abs(heat), 1e-300)
    assert abs(work + heat) / scale < 1e-12
