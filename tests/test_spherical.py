"""Spherical multipoles with selectable order P (the reference's EXAFMM
accuracy knob, ryoanji/nbody/kernel.hpp): operator identities + the
order-4-beats-quadrupole accuracy pin vs direct summation."""

import numpy as np
import pytest

import jax.numpy as jnp

from sphexa_tpu.gravity import spherical as sp


def _cloud(n=64, seed=0, spread=0.3):
    rng = np.random.default_rng(seed)
    pos = rng.normal(0, spread, (n, 3))
    m = rng.uniform(0.5, 1.5, n)
    return (jnp.asarray(pos[:, 0]), jnp.asarray(pos[:, 1]),
            jnp.asarray(pos[:, 2]), jnp.asarray(m))


def _direct_phi(x, y, z, m, px, py, pz):
    dx = px - np.asarray(x)
    dy = py - np.asarray(y)
    dz = pz - np.asarray(z)
    return float(np.sum(np.asarray(m) / np.sqrt(dx**2 + dy**2 + dz**2)))


@pytest.mark.parametrize("p", [2, 3, 4, 5])
def test_expansion_converges_to_direct(p):
    """phi from P2M+M2P converges to the direct sum with growing P."""
    x, y, z, m = _cloud()
    edges = jnp.asarray([0, 64], jnp.int32)
    center = jnp.zeros((1, 3))
    M = sp.p2m(x, y, z, m, center, edges, p)
    target = (2.0, 1.5, 1.8)
    phi = float(sp.potential(
        jnp.asarray([target[0]]), jnp.asarray([target[1]]),
        jnp.asarray([target[2]]), M[0], p,
    )[0])
    exact = _direct_phi(x, y, z, m, *target)
    rel = abs(phi - exact) / abs(exact)
    # geometric convergence in (spread/r)^P
    assert rel < (0.45) ** (p - 1), (p, rel)


def test_m2m_preserves_far_potential():
    """Translating the expansion center must not change the far field."""
    p = 4
    x, y, z, m = _cloud(seed=3)
    edges = jnp.asarray([0, 64], jnp.int32)
    c1 = jnp.zeros((1, 3))
    M1 = sp.p2m(x, y, z, m, c1, edges, p)
    # rebuild about a shifted center directly, and via M2M translation
    c2 = jnp.asarray([[0.2, -0.1, 0.15]])
    M2_direct = sp.p2m(x, y, z, m, c2, edges, p)
    d = c1 - c2  # child center - parent center
    M2_trans = sp.m2m(M1, d, p)
    tx = jnp.asarray([3.0])
    ty = jnp.asarray([0.5])
    tz = jnp.asarray([-2.0])
    phi_a = float(sp.potential(tx - c2[0, 0], ty - c2[0, 1], tz - c2[0, 2],
                               M2_direct[0], p)[0])
    phi_b = float(sp.potential(tx - c2[0, 0], ty - c2[0, 1], tz - c2[0, 2],
                               M2_trans[0], p)[0])
    np.testing.assert_allclose(phi_b, phi_a, rtol=2e-5)


def test_m2p_autodiff_force_matches_fd():
    p = 4
    x, y, z, m = _cloud(seed=5)
    edges = jnp.asarray([0, 64], jnp.int32)
    center = jnp.zeros((1, 3))
    M = sp.p2m(x, y, z, m, center, edges, p)
    mask = jnp.asarray([True])
    tx, ty, tz = jnp.asarray([2.2]), jnp.asarray([-1.1]), jnp.asarray([1.4])
    ax, ay, az, phi = sp.m2p(tx, ty, tz, center, M, mask, p)
    eps = 1e-3
    phi_p = sp.m2p(tx + eps, ty, tz, center, M, mask, p)[3]
    phi_m = sp.m2p(tx - eps, ty, tz, center, M, mask, p)[3]
    fd = -(float(phi_p[0]) - float(phi_m[0])) / (2 * eps)
    np.testing.assert_allclose(float(ax[0]), fd, rtol=1e-3)


@pytest.mark.slow
def test_order4_beats_quadrupole_in_gravity_solver():
    """End-to-end accuracy knob: Barnes-Hut forces at equal theta with
    spherical order-4 multipoles come closer to direct summation than
    the cartesian quadrupole (VERDICT r2 #6 done-criterion)."""
    import dataclasses

    import jax

    from sphexa_tpu.gravity.direct import direct_gravity
    from sphexa_tpu.gravity.traversal import GravityConfig, compute_gravity
    from sphexa_tpu.init import init_evrard
    from sphexa_tpu.propagator import _sort_by_keys
    from sphexa_tpu.sfc.box import make_global_box
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_evrard(12, overrides={"G": 1.0})
    sim = Simulation(state, box, const, prop="nbody", block=512)
    cfg = sim._cfg
    gbox = make_global_box(state.x, state.y, state.z, box)
    sstate, keys, _ = _sort_by_keys(state, gbox, cfg.curve)

    adx, ady, adz, _ = direct_gravity(
        sstate.x, sstate.y, sstate.z, sstate.m, sstate.h
    )
    aref = np.sqrt(np.asarray(adx)**2 + np.asarray(ady)**2
                   + np.asarray(adz)**2)

    def err(order):
        gcfg = dataclasses.replace(
            cfg.gravity, G=1.0, theta=0.9, multipole_order=order,
            use_pallas=False,
        )
        ax, ay, az, _, _ = compute_gravity(
            sstate.x, sstate.y, sstate.z, sstate.m, sstate.h, keys, gbox,
            sim._gtree, cfg.grav_meta, gcfg,
        )
        dx = np.asarray(ax) - np.asarray(adx)
        dy = np.asarray(ay) - np.asarray(ady)
        dz = np.asarray(az) - np.asarray(adz)
        return float(np.mean(np.sqrt(dx**2 + dy**2 + dz**2) / (aref + 1e-12)))

    e_quad = err(0)  # cartesian quadrupole path
    e_p4 = err(4)
    e_p6 = err(6)
    assert e_p4 < e_quad, (e_p4, e_quad)
    assert e_p6 < e_p4, (e_p6, e_p4)
