"""jaxaudit: rule fixtures, suppressions, CLI, the tier-1 package gate,
the donation/debug-checks runtime guards, and the zero-retrace pin.

Fixture contract (mirrors tests/lint_fixtures): every file under
tests/audit_fixtures/ registers ``@entrypoint`` builders and carries
``# expect: JXA10x`` markers on the registration lines that must produce
findings; the test fails on both missed findings AND unexpected ones, so
rule false positives break CI the same way false negatives do.
"""

import dataclasses
import importlib.util
import json
import re
from pathlib import Path

import numpy as np
import pytest

from sphexa_tpu.devtools.audit import (
    Auditor,
    all_rules,
    entries_from_namespace,
)
from sphexa_tpu.devtools.audit.cli import main as audit_main
from sphexa_tpu.devtools.audit.core import _DISABLE_RE
from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "audit_fixtures"

_EXPECT_RE = re.compile(
    r"#\s*expect:\s*([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
)

ALL_RULE_IDS = ["JXA101", "JXA102", "JXA103", "JXA104", "JXA105", "JXA106",
                "JXA201", "JXA202", "JXA203", "JXA204",
                "JXA301", "JXA302", "JXA303",
                "JXA401", "JXA402",
                "JXA501", "JXA502", "JXA503"]

# the JXA5xx statecheck fixtures need a controlled context (doctored
# schema lock path, vmap_members on) so they live in their own dir with
# their own runner (tests/test_statecheck.py); the firing-fixture
# acceptance scan below covers both dirs
STATECHECK_FIXTURES = Path(__file__).resolve().parent / "statecheck_fixtures"


def expected_findings(path: Path):
    """[(line, rule)] from # expect: markers."""
    out = []
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for code in m.group(1).split(","):
                out.append((i, code.strip()))
    return sorted(out)


def load_fixture(rel: str):
    path = FIXTURES / rel
    spec = importlib.util.spec_from_file_location(
        f"audit_fixture_{path.stem}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_fixture(rel: str):
    entries = entries_from_namespace(vars(load_fixture(rel)))
    return Auditor().run_entries(entries)


FIXTURE_FILES = sorted(
    p.relative_to(FIXTURES).as_posix() for p in FIXTURES.rglob("*.py")
)


def test_rule_registry_complete():
    rules = all_rules()
    assert sorted(rules) == ALL_RULE_IDS
    for rule in rules.values():
        assert rule.description


@pytest.mark.parametrize("rel", FIXTURE_FILES)
def test_fixture_findings_exact(rel):
    """Each fixture's active findings == its # expect: markers, exactly."""
    active, _suppressed, errors, skipped = run_fixture(rel)
    assert not errors, "\n".join(f.format() for f in errors)
    assert not skipped, skipped  # conftest provides the 8-device CPU mesh
    actual = sorted((f.line, f.rule) for f in active)
    expected = expected_findings(FIXTURES / rel)
    assert actual == expected, (
        f"{rel}: findings disagree with markers\n"
        f"  unexpected: {sorted(set(actual) - set(expected))}\n"
        f"  missed:     {sorted(set(expected) - set(actual))}\n"
        + "\n".join(f.format() for f in active)
    )


def test_every_rule_has_a_firing_fixture():
    """The acceptance contract: each JXA rule is PROVEN to fire."""
    fired = set()
    for rel in FIXTURE_FILES:
        fired |= {rule for _line, rule in expected_findings(FIXTURES / rel)}
    for p in sorted(STATECHECK_FIXTURES.rglob("*.py")):
        fired |= {rule for _line, rule in expected_findings(p)}
    assert fired == set(ALL_RULE_IDS), (
        f"rules without a firing fixture: {set(ALL_RULE_IDS) - fired}"
    )


def test_inline_suppression_swallows_finding():
    active, suppressed, _errors, _skipped = run_fixture("jxa104_host.py")
    sup = [(f.rule, "suppressed_debug_print" in f.message)
           for f in suppressed]
    assert ("JXA104", True) in sup, f"suppressed={sup}"
    assert all("suppressed_debug_print" not in f.message for f in active)


def test_entry_build_failure_is_jxa000(tmp_path):
    src = (
        "from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint\n"
        "@entrypoint('boom')\n"
        "def boom():\n"
        "    raise RuntimeError('broken builder')\n"
    )
    p = tmp_path / "broken_registry.py"
    p.write_text(src)
    spec = importlib.util.spec_from_file_location("audit_fixture_broken", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    active, _sup, errors, _skipped = Auditor().run_entries(
        entries_from_namespace(vars(mod))
    )
    assert not active
    assert len(errors) == 1 and errors[0].rule == "JXA000"
    assert "broken builder" in errors[0].message


def test_unknown_rule_selection_rejected():
    with pytest.raises(ValueError):
        Auditor(select=["JXA999"])


def test_cli_exit_codes_and_json(capsys, tmp_path):
    bad = str(FIXTURES / "jxa105_const.py")
    # --cpu-devices 0: the in-process backend is already up (conftest)
    assert audit_main([bad, "--cpu-devices", "0"]) == 1
    capsys.readouterr()

    assert audit_main([bad, "--cpu-devices", "0", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in payload["findings"]} == {"JXA105"}
    assert payload["errors"] == []

    # baseline workflow: grandfather, then the gate passes
    bl = tmp_path / "bl.json"
    assert audit_main([bad, "--cpu-devices", "0", "--baseline", str(bl),
                       "--update-baseline"]) == 0
    capsys.readouterr()
    assert audit_main([bad, "--cpu-devices", "0",
                       "--baseline", str(bl)]) == 0
    capsys.readouterr()

    assert audit_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JXA101" in out and "JXA106" in out

    assert audit_main([bad, "--cpu-devices", "0", "--list-entries"]) == 0
    out = capsys.readouterr().out
    assert "baked_table" in out


def test_cli_usage_errors(tmp_path):
    assert audit_main(["--select", "NOPE1",
                       str(FIXTURES / "jxa105_const.py"),
                       "--cpu-devices", "0"]) == 2
    assert audit_main(["--update-baseline", "--cpu-devices", "0",
                       str(FIXTURES / "jxa105_const.py")]) == 2
    assert audit_main(["no_such_module_xyz", "--cpu-devices", "0"]) == 2
    assert audit_main([str(FIXTURES / "jxa105_const.py"),
                       "--cpu-devices", "0",
                       "--entries", "nope"]) == 2


# ---------------------------------------------------------------------------
# preflight (the JXA2xx campaign gate: sphexa-audit preflight)
# ---------------------------------------------------------------------------


def test_preflight_package_clean_at_p4(capsys):
    """The campaign acceptance gate: the package registry preflights
    clean on a P=4 CPU mesh — all three shardcheck rules active, zero
    findings, zero suppressions — and the table renders the campaign
    peak-HBM column for the sharded step."""
    from sphexa_tpu.devtools.audit.preflight import main as preflight_main

    assert preflight_main(["--mesh", "4"]) == 0
    out = capsys.readouterr().out
    for col in ("entry", "coll", "chain", "peak/dev", "replicated",
                "exchange"):
        assert col in out
    assert "step_std_sharded" in out and "gravity_sharded" in out
    assert "RACE" not in out
    assert "suppressed" not in out


def test_preflight_flags_unchained_collectives(capsys):
    """The PR-5 rendezvous-race shape must fail preflight (exit 1) and
    show up as RACE in the chain column."""
    from sphexa_tpu.devtools.audit.preflight import main as preflight_main

    assert preflight_main([str(FIXTURES / "jxa201_order.py"),
                           "--mesh", "2"]) == 1
    out = capsys.readouterr().out
    assert "RACE" in out
    assert "JXA201" in out


def test_preflight_usage_errors():
    from sphexa_tpu.devtools.audit.preflight import main as preflight_main

    assert preflight_main(["--mesh", "1"]) == 2
    assert preflight_main(["no_such_module_xyz", "--mesh", "2"]) == 2
    assert preflight_main(["--update-baseline", "--mesh", "2"]) == 2


def test_preflight_campaign_budget_flags_propagate(capsys):
    """--hbm-budget reaches the JXA202 gate: an absurdly low budget
    must fail the sharded step's campaign estimate."""
    from sphexa_tpu.devtools.audit.preflight import main as preflight_main

    rc = preflight_main(["--mesh", "2", "--entries", "step_std_sharded",
                         "--hbm-budget", str(1 << 20)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "JXA202" in out


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------


def test_package_audit_clean():
    """The registered hot entry points of sphexa_tpu/ must trace clean —
    the acceptance gate: >= 6 entries (incl. >= 1 sharded on the CPU
    mesh), zero findings, zero errors, zero skips."""
    from sphexa_tpu.devtools.audit import registry

    entries = entries_from_namespace(vars(registry))
    assert len(entries) >= 6
    assert any(e.mesh_axes for e in entries), "no sharded entry registered"
    active, _suppressed, errors, skipped = Auditor().run_entries(entries)
    msgs = "\n".join(f.format() for f in errors + active)
    assert not errors and not active and not skipped, (
        f"jaxaudit found {len(active)} finding(s) / {len(errors)} entry "
        f"error(s) / skipped={skipped} in the package registry:\n{msgs}"
    )


def test_audit_suppressions_in_package_carry_reasons():
    """Every inline jaxaudit disable in the package must say WHY."""
    bad = []
    for p in (REPO_ROOT / "sphexa_tpu").rglob("*.py"):
        for i, line in enumerate(p.read_text().splitlines(), start=1):
            m = _DISABLE_RE.search(line)
            if m and not (m.group("reason") or "").strip():
                bad.append(f"{p}:{i}: {line.strip()}")
    assert not bad, "suppressions without a reason:\n" + "\n".join(bad)


def test_std_engine_two_steps_compile_once():
    """Zero retraces across two Simulation.step calls of the std engine
    (the JXA102 invariant, pinned at the driver level): the second step
    reuses the first step's executable."""
    from sphexa_tpu import propagator

    state, box, const = init_sedov(7)  # side unique to this test
    sim = Simulation(state, box, const, prop="std")
    c0 = propagator.step_hydro_std._cache_size()
    sim.step()
    c1 = propagator.step_hydro_std._cache_size()
    sim.step()
    c2 = propagator.step_hydro_std._cache_size()
    assert c1 - c0 <= 1, "first step compiled more than one executable"
    assert c2 == c1, "second std step RETRACED (signature drift)"


# ---------------------------------------------------------------------------
# runtime sanitizer (checkify) smoke
# ---------------------------------------------------------------------------


def test_debug_checks_clean_and_seeded_nan():
    import jax.numpy as jnp

    state, box, const = init_sedov(6)
    sim = Simulation(state, box, const, prop="std", debug_checks=True)
    d = sim.step()
    assert d["check_error"] == ""

    bad = np.asarray(sim.state.temp).copy()
    bad[3] = np.nan  # seed a NaN: du goes NaN through EOS/momentum
    sim.state = dataclasses.replace(sim.state, temp=jnp.asarray(bad))
    d = sim.step()
    assert "nan" in d["check_error"].lower(), d["check_error"]


def test_debug_checks_rejects_mesh():
    state, box, const = init_sedov(6)
    with pytest.raises(ValueError):
        Simulation(state, box, const, prop="std", debug_checks=True,
                   num_devices=8)


# ---------------------------------------------------------------------------
# donation guards
# ---------------------------------------------------------------------------


def test_donate_auto_stays_off_on_cpu():
    """tier-1 guard: 'auto' must not engage on CPU (CPU honors donation,
    and the checked path's discard-and-replay reuses inputs)."""
    state, box, const = init_sedov(6)
    sim = Simulation(state, box, const, prop="std", check_every=2)
    assert not sim._donate_active
    sim.step()
    sim.step()
    sim.flush()
    assert not np.any(np.isnan(np.asarray(sim.state.x)))


def test_donated_twin_really_donates():
    """The donated jit consumes its input state (CPU honors donation in
    this jax) — the property JXA103 certifies."""
    from sphexa_tpu import propagator
    from sphexa_tpu.simulation import make_propagator_config

    state, box, const = init_sedov(6)
    cfg = make_propagator_config(state, box, const)
    state = dataclasses.replace(state)  # fresh pytree, caller-owned leaves
    sim_state, _, _ = propagator.step_hydro_std(state, box, cfg, None)
    assert not state.x.is_deleted()  # plain twin keeps inputs alive
    out_state, _, _ = propagator.step_hydro_std_donated(
        sim_state, box, cfg, None
    )
    assert sim_state.x.is_deleted()
    assert not np.any(np.isnan(np.asarray(out_state.x)))


def test_donate_deferred_matches_sync_and_keeps_caller_state():
    state, box, const = init_sedov(8)
    s_don = Simulation(state, box, const, prop="std", check_every=2,
                       donate=True)
    for _ in range(4):
        s_don.step()
    s_don.flush()
    # the caller's arrays survive (construction-time ownership copy)
    s_sync = Simulation(state, box, const, prop="std")
    for _ in range(4):
        s_sync.step()
    np.testing.assert_array_equal(
        np.asarray(s_sync.state.x), np.asarray(s_don.state.x)
    )
    np.testing.assert_array_equal(
        np.asarray(s_sync.state.temp), np.asarray(s_don.state.temp)
    )
    assert s_don.iteration == s_sync.iteration == 4


def test_donate_rollback_replays_from_pinned_copy():
    """A deferred-detected overflow under donation must roll back to the
    pinned window-start COPY and replay on the undonated path."""
    state, box, const = init_sedov(8)
    ref = Simulation(state, box, const, prop="std")
    for _ in range(3):
        ref.step()
    sim = Simulation(state, box, const, prop="std", check_every=3,
                     donate=True)
    sim._cfg = dataclasses.replace(
        sim._cfg, nbr=dataclasses.replace(sim._cfg.nbr, cap=8)
    )
    for _ in range(3):
        sim.step()
    d = sim.flush()
    assert d["reconfigured"] == 1.0
    assert sim.iteration == 3
    np.testing.assert_allclose(
        np.asarray(sim.state.x), np.asarray(ref.state.x), rtol=1e-6
    )
