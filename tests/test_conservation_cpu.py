"""CPU-tier conservation + physics guard: a 30-50x smaller variant of the
reference-configuration L1 regression (tests/test_l1_reference.py) that
runs in the DEFAULT suite, so conservation regressions surface before the
TPU tier (VERDICT r2 weak #6)."""

import numpy as np

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import conserved_quantities
from sphexa_tpu.simulation import Simulation

STEPS = 40


def _drift(prop):
    state, box, const = init_sedov(20)  # 8000 particles
    sim = Simulation(state, box, const, prop=prop, block=2048,
                     check_every=10)
    e0 = float(conserved_quantities(sim.state, const)["etot"])
    for _ in range(STEPS):
        sim.step()
    sim.flush()
    e1 = float(conserved_quantities(sim.state, const)["etot"])
    assert np.isfinite(np.asarray(sim.state.x)).all()
    return abs(e1 - e0) / max(abs(e0), 1e-30)


def test_sedov_std_energy_drift_cpu_tier():
    # measured ~6e-5 at this size/length; the window guards regressions
    assert _drift("std") < 5e-4


def test_sedov_ve_energy_drift_cpu_tier():
    """The reference CI's CPU smoke runs ``sedov --ve`` (reframe_ci.py:
    220-249); this adds the conservation assertion on top."""
    assert _drift("ve") < 5e-4
