"""Radiative-cooling tests: unit conversions, cooling curve, rate signs,
implicit integrator stability, timestep limiter, and the std-cooling
propagator end to end. Mirrors the coupling contract of
std_hydro_grackle.hpp + eos_cooling.hpp.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.physics.cooling import (
    ChemistryData,
    CoolingConfig,
    _lambda_cie,
    cool_particles,
    cooling_rate,
    cooling_timestep,
    eos_cooling,
    temp_to_u,
    u_to_temp,
)


@pytest.fixture(scope="module")
def cfg():
    return CoolingConfig()


@pytest.fixture(scope="module")
def chem():
    return ChemistryData.ionized(4)


class TestUnits:
    def test_u_temp_round_trip(self, cfg):
        u = jnp.array([0.05, 1.0, 10.0])
        mu = jnp.float32(0.6)
        t = u_to_temp(u, mu, cfg)
        back = temp_to_u(t, mu, cfg)
        np.testing.assert_allclose(np.asarray(back), np.asarray(u), rtol=1e-5)

    def test_evrard_units_give_astro_temperatures(self, cfg):
        # u0 = 0.05 in the evrard-cooling unit system is a ~1e6 K halo
        t = float(u_to_temp(jnp.float32(0.05), jnp.float32(0.6), cfg))
        assert 1e5 < t < 1e8

    def test_mu_ionized(self, chem):
        mu = np.asarray(chem.mean_molecular_weight())
        assert np.all((0.55 < mu) & (mu < 0.65))  # ionized solar ~ 0.6


class TestCoolingCurve:
    def test_peak_magnitude(self, cfg):
        lam = float(_lambda_cie(jnp.float32(1e5), cfg))
        assert 1e-22 < lam < 1e-20  # line-cooling peak

    def test_cold_gas_does_not_cool(self, cfg):
        lam = float(_lambda_cie(jnp.float32(1000.0), cfg))
        assert lam < 1e-30

    def test_bremsstrahlung_tail_flat(self, cfg):
        l7 = float(_lambda_cie(jnp.float32(1e7), cfg))
        l8 = float(_lambda_cie(jnp.float32(1e8), cfg))
        assert 0.1 < l8 / l7 < 10.0


class TestRates:
    def test_hot_gas_cools(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)  # ~1e6 K
        dudt = np.asarray(cooling_rate(rho, u, chem, cfg))
        assert np.all(dudt < 0)

    def test_heating_dominates_at_low_density(self, chem):
        cfg = CoolingConfig(heating_rate=1e-24)
        rho = jnp.full(4, 1e-12)  # vanishing n_H^2 term
        u = jnp.full(4, 0.05)
        dudt = np.asarray(cooling_rate(rho, u, chem, cfg))
        assert np.all(dudt > 0)

    def test_rate_scales_with_density(self, cfg, chem):
        u = jnp.full(4, 0.05)
        r1 = float(cooling_rate(jnp.full(4, 1.0), u, chem, cfg)[0])
        r2 = float(cooling_rate(jnp.full(4, 2.0), u, chem, cfg)[0])
        # du/dt ~ n^2 / rho ~ rho
        assert r2 / r1 == pytest.approx(2.0, rel=0.01)


class TestIntegrator:
    def test_positivity_for_huge_dt(self, cfg, chem):
        rho = jnp.full(4, 100.0)
        u = jnp.full(4, 0.05)
        # dt far beyond the cooling time: u must stay positive
        du = cool_particles(jnp.float32(1e3), rho, u, chem, cfg)
        u_new = np.asarray(u + du * 1e3)
        assert np.all(u_new > 0)

    def test_mild_cooling_matches_explicit(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)
        dudt = float(cooling_rate(rho, u, chem, cfg)[0])
        dt = 0.001 * abs(float(u[0]) / dudt)  # << cooling time
        du = float(cool_particles(jnp.float32(dt), rho, u, chem, cfg)[0])
        assert du == pytest.approx(dudt, rel=0.05)

    def test_timestep_limiter(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)
        dt_c = float(cooling_timestep(rho, u, chem, cfg))
        dudt = float(cooling_rate(rho, u, chem, cfg)[0])
        assert dt_c == pytest.approx(cfg.ct_crit * abs(float(u[0]) / dudt), rel=1e-4)

    def test_eos(self, cfg, chem):
        rho = jnp.full(4, 2.0)
        u = jnp.full(4, 0.05)
        p, c = eos_cooling(rho, u, chem, cfg)
        assert float(p[0]) == pytest.approx((cfg.gamma - 1) * 2.0 * 0.05)
        assert float(c[0]) == pytest.approx(
            np.sqrt(cfg.gamma * float(p[0]) / 2.0), rel=1e-5
        )


class TestChemAlignment:
    def test_chem_rides_the_sfc_sort(self):
        """Per-particle chemistry must stay aligned with the particles
        through the step's internal SFC sort: tag each particle's metal
        fraction with its initial x-coordinate rank and check the pairing
        survives a step."""
        import dataclasses as dc

        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(8)
        n = state.n
        # shuffle the particle order so the step's SFC sort is a
        # nontrivial permutation
        perm = np.random.default_rng(7).permutation(n)
        state = dc.replace(
            state,
            **{f: jnp.asarray(np.asarray(getattr(state, f))[perm])
               for f in ("x", "y", "z", "vx", "vy", "vz", "h", "m", "temp")},
        )
        # tag: affine in the (pre-step) position; from rest, two tiny steps
        # move particles by ~dt^2, so the relation survives if and only if
        # chem rides the same permutation as the coordinates
        tag = 0.01 + 0.005 * (np.asarray(state.x) + 0.5)
        chem = ChemistryData.ionized(n)
        chem = dc.replace(chem, metal=jnp.asarray(tag.astype(np.float32)))

        sim = Simulation(state, box, const, prop="std-cooling", block=256,
                         chem=chem)
        sim.step()
        sim.step()
        x_now = np.asarray(sim.state.x)
        metal_now = np.asarray(sim.chem.metal)
        np.testing.assert_allclose(
            metal_now, 0.01 + 0.005 * (x_now + 0.5), atol=1e-5
        )


class TestCoolingPropagator:
    def test_evrard_cooling_run(self):
        from sphexa_tpu.init import make_initializer
        from sphexa_tpu.observables import conserved_quantities
        from sphexa_tpu.simulation import Simulation

        state, box, const = make_initializer("evrard-cooling")(10)
        sim = Simulation(state, box, const, prop="std-cooling", block=256)
        e0 = conserved_quantities(sim.state, const)
        for _ in range(3):
            d = sim.step()
        e1 = conserved_quantities(sim.state, const)
        assert np.all(np.isfinite(np.asarray(sim.state.temp)))
        assert float(d["dt"]) > 0
        assert "dt_cool" in d
        # radiative losses: internal energy decreases relative to the
        # adiabatic run (collapse heating is tiny after 3 steps)
        assert float(e1["eint"]) < float(e0["eint"]) * 1.001


class TestPrimordialNetwork:
    """Evolved 6-species primordial chemistry (physics/primordial.py) —
    the cooler.cpp:313 solve_chemistry role (VERDICT r4 #6). The CIE
    equilibrium-limit pins come from the analytic ionization balance
    (rate-coefficient ratios; density cancels)."""

    @staticmethod
    def _cfg(**kw):
        from sphexa_tpu.physics.cooling import KPC, MH, CoolingConfig

        # unit scales chosen so n_H [cm^-3] == rho_code and the rates are
        # fast in code time (t_code ~ 3e15 s): equilibrium in a few calls
        l_cm = KPC
        return CoolingConfig(
            m_code_g=MH * l_cm**3, l_code_cm=l_cm, substeps=32,
            evolve_species=True, **kw,
        )

    @staticmethod
    def _neutral(n, x=0.76, seed=1e-4):
        """Near-neutral IC with a TINY ionized seed: the collisional
        network's rates all carry a factor y_e, so exactly-zero
        electrons is a (unphysical) frozen fixed point — real ICs are
        never exactly neutral."""
        import jax.numpy as jnp

        from sphexa_tpu.physics.cooling import ChemistryData

        f = lambda v: jnp.full(n, v, jnp.float32)
        return ChemistryData(hi=f(x - seed), hii=f(seed), hei=f(1.0 - x),
                            heii=f(0.0), heiii=f(0.0), e=f(seed),
                            metal=f(0.0))

    def _relax(self, T, rho=1.0):
        """Species-only relaxation at fixed temperature (the coupled
        solver would cool the gas off T within one call at these fast
        units — the CIE limit is a statement about fractions at GIVEN T)."""
        import jax.numpy as jnp

        from sphexa_tpu.physics import primordial as pn

        cfg = self._cfg()
        chem = self._neutral(4)
        rho_a = jnp.full(4, rho, jnp.float32)
        T_a = jnp.full(4, T, jnp.float32)
        chem = pn.relax_to_equilibrium(T_a, rho_a, chem, cfg,
                                       dt_sub=0.02, steps=4096)
        return chem, cfg

    def test_equilibrium_matches_analytic_cie(self):
        """The relaxed network must sit on the analytic CIE balance
        (y_HII/y_HI = k1/k2 etc.) across the ionization range."""
        import numpy as np

        from sphexa_tpu.physics import primordial as pn

        for T in (2.0e4, 6.0e4, 2.0e5):
            chem, _ = self._relax(T)
            eq = pn.equilibrium_fractions(np.float64(T), 0.76, 0.24)
            got_hii = float(chem.hii[0])
            want_hii = float(eq["hii"])
            assert abs(got_hii - want_hii) < 0.05 * max(want_hii, 1e-3), (
                T, got_hii, want_hii)
            got_heiii = float(chem.heiii[0])           # mass fraction
            want_heiii = float(eq["heiii"]) * 4.0      # number -> mass
            assert abs(got_heiii - want_heiii) < 0.08 * max(want_heiii, 4e-3), (
                T, got_heiii, want_heiii)

    def test_equilibrium_cooling_recovers_cie_shape(self):
        """Species-resolved cooling at the relaxed fractions follows the
        canonical primordial CIE shape: line peak near 1e5 K, orders of
        magnitude drop below 1e4 K, bremsstrahlung tail at 1e7 K."""
        import numpy as np

        from sphexa_tpu.physics import primordial as pn

        def rate(T):
            eq = pn.equilibrium_fractions(np.float64(T), 0.76, 0.24)
            return float(pn.species_cooling24(np.float64(T), eq))

        r8e3, r1e5, r1e7 = rate(8e3), rate(1.2e5), rate(1e7)
        assert r1e5 > 30 * r8e3, (r8e3, r1e5)
        assert r1e5 > 3 * r1e7, (r1e5, r1e7)
        assert r1e7 > 0.0

    def test_conservation_and_positivity(self):
        """Element totals and charge balance are exact closures; a huge
        dt must not produce negative fractions or NaNs."""
        import jax.numpy as jnp
        import numpy as np

        from sphexa_tpu.physics import primordial as pn
        from sphexa_tpu.physics.cooling import temp_to_u

        cfg = self._cfg()
        chem = self._neutral(8)
        rho = jnp.full(8, 10.0, jnp.float32)
        u = temp_to_u(jnp.full(8, 3e5, jnp.float32),
                      chem.mean_molecular_weight(), cfg)
        du, out = pn.evolve_primordial(1e4, rho, u, chem, cfg)
        for a in (out.hi, out.hii, out.hei, out.heii, out.heiii, out.e):
            arr = np.asarray(a)
            assert np.all(np.isfinite(arr)) and np.all(arr >= 0.0)
        np.testing.assert_allclose(
            np.asarray(out.hi + out.hii), 0.76, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(out.hei + out.heii + out.heiii), 0.24, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(out.e),
            np.asarray(out.hii + out.heii / 4.0 + 2.0 * out.heiii / 4.0),
            rtol=1e-4, atol=1e-7,
        )
        assert np.all(np.isfinite(np.asarray(du)))

    def test_propagator_evolves_species(self):
        """std-cooling with evolve_species: the network runs inside the
        jitted sharded-capable step and the fractions actually move
        (cooler.cpp solve_chemistry per step)."""
        import numpy as np

        from sphexa_tpu.init import init_evrard
        from sphexa_tpu.physics.cooling import ChemistryData
        from sphexa_tpu.propagator import step_hydro_std_cooling
        from sphexa_tpu.simulation import make_propagator_config

        state, box, const = init_evrard(10)
        cfg = make_propagator_config(state, box, const)
        ccfg = self._cfg(gamma=const.gamma)
        chem = ChemistryData.ionized(state.n, metallicity=0.0)
        s, b, _, chem1 = step_hydro_std_cooling(state, box, cfg, None,
                                                chem, ccfg)
        _, _, d2, chem2 = step_hydro_std_cooling(s, b, cfg, None, chem1,
                                                 ccfg)
        hi1 = np.asarray(chem2.hi)
        assert np.all(np.isfinite(hi1))
        # recombination out of the fully-ionized IC must move HI off zero
        assert float(np.max(hi1)) > 0.0
        np.testing.assert_allclose(np.asarray(chem2.hi + chem2.hii),
                                   0.76, rtol=1e-4)
        assert float(d2["dt"]) > 0.0

    def test_checkpoint_round_trip_evolved(self):
        """Evolved fractions survive the snapshot field round-trip
        (std_hydro_grackle.hpp:89-106 contract)."""
        import numpy as np

        from sphexa_tpu.physics.cooling import (
            chemistry_from_fields, chemistry_to_fields,
        )

        chem, _ = self._relax(6.0e4)
        back = chemistry_from_fields(chemistry_to_fields(chem))
        for f in ("hi", "hii", "hei", "heii", "heiii", "e", "metal"):
            np.testing.assert_array_equal(
                np.asarray(getattr(chem, f)), np.asarray(getattr(back, f)))

    def test_metal_channel_residual(self):
        """Metal-line cooling in evolve mode: the CIE-table residual over
        the network's equilibrium, linear in Z (the GRACKLE network +
        metal-table decomposition) — present at solar Z, zero at Z=0,
        and strongest in the metal-line band (~2e5 K)."""
        import numpy as np

        from sphexa_tpu.physics import primordial as pn

        cfg = self._cfg()
        z_sun = 0.0122
        at = lambda T, z: float(pn.metal_cooling24(
            np.float64(T), np.float64(z), cfg))
        assert at(2e5, 0.0) == 0.0
        assert at(2e5, z_sun) > 0.0
        np.testing.assert_allclose(at(2e5, z_sun / 2), at(2e5, z_sun) / 2,
                                   rtol=1e-6)
        # metal lines dominate the band between the H/He peak and brems
        assert at(2e5, z_sun) > at(2e7, z_sun)

    def test_metal_channel_uses_config_hydrogen_fraction(self):
        """ADVICE round-5 regression: metal_cooling24 used to hard-code
        x_h=0.76, so a non-default composition got the WRONG n_H^2
        conversion of the table rate. The default must now track
        cfg.hydrogen_fraction exactly (explicit x_h still wins)."""
        import dataclasses

        import numpy as np

        from sphexa_tpu.physics import primordial as pn
        from sphexa_tpu.physics.cooling import CoolingConfig

        base = self._cfg()
        lean = dataclasses.replace(base, hydrogen_fraction=0.6)
        assert isinstance(lean, CoolingConfig)
        T, z = np.float64(2e5), np.float64(0.0122)
        # default == explicit cfg fraction, for BOTH compositions
        np.testing.assert_allclose(
            float(pn.metal_cooling24(T, z, lean)),
            float(pn.metal_cooling24(T, z, lean, x_h=0.6)), rtol=0)
        np.testing.assert_allclose(
            float(pn.metal_cooling24(T, z, base)),
            float(pn.metal_cooling24(T, z, base,
                                     x_h=base.hydrogen_fraction)), rtol=0)
        # and a leaner composition is NOT the 0.76 number (the old bug)
        assert float(pn.metal_cooling24(T, z, lean)) != float(
            pn.metal_cooling24(T, z, lean, x_h=0.76))
