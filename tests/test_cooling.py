"""Radiative-cooling tests: unit conversions, cooling curve, rate signs,
implicit integrator stability, timestep limiter, and the std-cooling
propagator end to end. Mirrors the coupling contract of
std_hydro_grackle.hpp + eos_cooling.hpp.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from sphexa_tpu.physics.cooling import (
    ChemistryData,
    CoolingConfig,
    _lambda_cie,
    cool_particles,
    cooling_rate,
    cooling_timestep,
    eos_cooling,
    temp_to_u,
    u_to_temp,
)


@pytest.fixture(scope="module")
def cfg():
    return CoolingConfig()


@pytest.fixture(scope="module")
def chem():
    return ChemistryData.ionized(4)


class TestUnits:
    def test_u_temp_round_trip(self, cfg):
        u = jnp.array([0.05, 1.0, 10.0])
        mu = jnp.float32(0.6)
        t = u_to_temp(u, mu, cfg)
        back = temp_to_u(t, mu, cfg)
        np.testing.assert_allclose(np.asarray(back), np.asarray(u), rtol=1e-5)

    def test_evrard_units_give_astro_temperatures(self, cfg):
        # u0 = 0.05 in the evrard-cooling unit system is a ~1e6 K halo
        t = float(u_to_temp(jnp.float32(0.05), jnp.float32(0.6), cfg))
        assert 1e5 < t < 1e8

    def test_mu_ionized(self, chem):
        mu = np.asarray(chem.mean_molecular_weight())
        assert np.all((0.55 < mu) & (mu < 0.65))  # ionized solar ~ 0.6


class TestCoolingCurve:
    def test_peak_magnitude(self, cfg):
        lam = float(_lambda_cie(jnp.float32(1e5), cfg))
        assert 1e-22 < lam < 1e-20  # line-cooling peak

    def test_cold_gas_does_not_cool(self, cfg):
        lam = float(_lambda_cie(jnp.float32(1000.0), cfg))
        assert lam < 1e-30

    def test_bremsstrahlung_tail_flat(self, cfg):
        l7 = float(_lambda_cie(jnp.float32(1e7), cfg))
        l8 = float(_lambda_cie(jnp.float32(1e8), cfg))
        assert 0.1 < l8 / l7 < 10.0


class TestRates:
    def test_hot_gas_cools(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)  # ~1e6 K
        dudt = np.asarray(cooling_rate(rho, u, chem, cfg))
        assert np.all(dudt < 0)

    def test_heating_dominates_at_low_density(self, chem):
        cfg = CoolingConfig(heating_rate=1e-24)
        rho = jnp.full(4, 1e-12)  # vanishing n_H^2 term
        u = jnp.full(4, 0.05)
        dudt = np.asarray(cooling_rate(rho, u, chem, cfg))
        assert np.all(dudt > 0)

    def test_rate_scales_with_density(self, cfg, chem):
        u = jnp.full(4, 0.05)
        r1 = float(cooling_rate(jnp.full(4, 1.0), u, chem, cfg)[0])
        r2 = float(cooling_rate(jnp.full(4, 2.0), u, chem, cfg)[0])
        # du/dt ~ n^2 / rho ~ rho
        assert r2 / r1 == pytest.approx(2.0, rel=0.01)


class TestIntegrator:
    def test_positivity_for_huge_dt(self, cfg, chem):
        rho = jnp.full(4, 100.0)
        u = jnp.full(4, 0.05)
        # dt far beyond the cooling time: u must stay positive
        du = cool_particles(jnp.float32(1e3), rho, u, chem, cfg)
        u_new = np.asarray(u + du * 1e3)
        assert np.all(u_new > 0)

    def test_mild_cooling_matches_explicit(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)
        dudt = float(cooling_rate(rho, u, chem, cfg)[0])
        dt = 0.001 * abs(float(u[0]) / dudt)  # << cooling time
        du = float(cool_particles(jnp.float32(dt), rho, u, chem, cfg)[0])
        assert du == pytest.approx(dudt, rel=0.05)

    def test_timestep_limiter(self, cfg, chem):
        rho = jnp.full(4, 1.0)
        u = jnp.full(4, 0.05)
        dt_c = float(cooling_timestep(rho, u, chem, cfg))
        dudt = float(cooling_rate(rho, u, chem, cfg)[0])
        assert dt_c == pytest.approx(cfg.ct_crit * abs(float(u[0]) / dudt), rel=1e-4)

    def test_eos(self, cfg, chem):
        rho = jnp.full(4, 2.0)
        u = jnp.full(4, 0.05)
        p, c = eos_cooling(rho, u, chem, cfg)
        assert float(p[0]) == pytest.approx((cfg.gamma - 1) * 2.0 * 0.05)
        assert float(c[0]) == pytest.approx(
            np.sqrt(cfg.gamma * float(p[0]) / 2.0), rel=1e-5
        )


class TestChemAlignment:
    def test_chem_rides_the_sfc_sort(self):
        """Per-particle chemistry must stay aligned with the particles
        through the step's internal SFC sort: tag each particle's metal
        fraction with its initial x-coordinate rank and check the pairing
        survives a step."""
        import dataclasses as dc

        from sphexa_tpu.init import init_sedov
        from sphexa_tpu.simulation import Simulation

        state, box, const = init_sedov(8)
        n = state.n
        # shuffle the particle order so the step's SFC sort is a
        # nontrivial permutation
        perm = np.random.default_rng(7).permutation(n)
        state = dc.replace(
            state,
            **{f: jnp.asarray(np.asarray(getattr(state, f))[perm])
               for f in ("x", "y", "z", "vx", "vy", "vz", "h", "m", "temp")},
        )
        # tag: affine in the (pre-step) position; from rest, two tiny steps
        # move particles by ~dt^2, so the relation survives if and only if
        # chem rides the same permutation as the coordinates
        tag = 0.01 + 0.005 * (np.asarray(state.x) + 0.5)
        chem = ChemistryData.ionized(n)
        chem = dc.replace(chem, metal=jnp.asarray(tag.astype(np.float32)))

        sim = Simulation(state, box, const, prop="std-cooling", block=256,
                         chem=chem)
        sim.step()
        sim.step()
        x_now = np.asarray(sim.state.x)
        metal_now = np.asarray(sim.chem.metal)
        np.testing.assert_allclose(
            metal_now, 0.01 + 0.005 * (x_now + 0.5), atol=1e-5
        )


class TestCoolingPropagator:
    def test_evrard_cooling_run(self):
        from sphexa_tpu.init import make_initializer
        from sphexa_tpu.observables import conserved_quantities
        from sphexa_tpu.simulation import Simulation

        state, box, const = make_initializer("evrard-cooling")(10)
        sim = Simulation(state, box, const, prop="std-cooling", block=256)
        e0 = conserved_quantities(sim.state, const)
        for _ in range(3):
            d = sim.step()
        e1 = conserved_quantities(sim.state, const)
        assert np.all(np.isfinite(np.asarray(sim.state.temp)))
        assert float(d["dt"]) > 0
        assert "dt_cool" in d
        # radiative losses: internal energy decreases relative to the
        # adiabatic run (collapse heating is tiny after 3 steps)
        assert float(e1["eint"]) < float(e0["eint"]) * 1.001
