"""Ablation timing of the std pallas pipeline: ONE jitted program per
variant (sort+prologue+ops), so axon dispatch overhead cancels and per-op
cost = full - variant_without_op.

Usage: [PROF_SIDE=100] [PROF_ARGS='...'] python scripts/profile_ablate.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))
ITERS = int(os.environ.get("PROF_ITERS", "5"))


def parse_args():
    kw = dict(cell_target=128, run_cap=1536, gap=384, group=64)
    for part in os.environ.get("PROF_ARGS", "").split(","):
        if "=" in part:
            k, v = part.split("=")
            kw[k.strip()] = int(v)
    return kw


def main():
    kw = parse_args()
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    for _ in range(2):
        sim.step()
    state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, _, _ = _sort_by_keys(state, box, "hilbert")
    n = state.n

    cfg = make_propagator_config(
        state, box, const, block=8192, backend="pallas", **kw)
    nbr = cfg.nbr
    print(f"n={n} level={nbr.level} cap={nbr.cap} win={nbr.window} "
          f"group={nbr.group} run_cap={nbr.run_cap} gap={nbr.gap}",
          flush=True)

    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m
    args = (x, y, z, h, m, state.temp, state.vx, state.vy, state.vz)

    def build(with_sort=True, with_pro=True, with_den=True, with_iad=True,
              with_mom=True):
        @jax.jit
        def pipe(x, y, z, h, m, temp, vx, vy, vz):
            acc = jnp.zeros_like(x)
            keys = compute_sfc_keys(x, y, z, box)
            if with_sort:
                order = jnp.argsort(keys)
                keys = keys[order]
                mat = jnp.stack([x, y, z, h, m, temp, vx, vy, vz], 1)[order]
                x2, y2, z2, h2, m2, temp2, vx2, vy2, vz2 = (
                    mat[:, i] for i in range(9))
            else:
                keys = jnp.sort(keys)
                x2, y2, z2, h2, m2, temp2, vx2, vy2, vz2 = (
                    x, y, z, h, m, temp, vx, vy, vz)
            if with_pro:
                ranges = pp.group_cell_ranges(x2, y2, z2, h2, keys, box, nbr)
                acc = acc + ranges.lens.sum()
            else:
                return acc
            if with_den:
                rho, nc, occ = pp.pallas_density(
                    x2, y2, z2, h2, m2, keys, box, const, nbr, ranges=ranges)
                acc = acc + rho
            else:
                rho = m2 / (h2 * h2 * h2)
            p, c = hydro_std.compute_eos_std(temp2, rho, const)
            if with_iad:
                cs, _ = pp.pallas_iad(
                    x2, y2, z2, h2, m2 / rho, keys, box, const, nbr,
                    ranges=ranges)
                acc = acc + cs[0]
            else:
                zz = jnp.zeros_like(x)
                cs = (1.0 / (h2 * h2), zz, zz, 1.0 / (h2 * h2), zz,
                      1.0 / (h2 * h2))
            if with_mom:
                out = pp.pallas_momentum_energy_std(
                    x2, y2, z2, vx2, vy2, vz2, h2, m2, rho, p, c, *cs,
                    keys, box, const, nbr, ranges=ranges)
                acc = acc + out[0]
            return acc

        return pipe

    def timev(name, **kwv):
        pipe = build(**kwv)
        # warmup: compile + 2 discarded batches (first post-compile run is
        # a ~1.5x outlier on axon)
        for _ in range(3):
            out = pipe(*args)
            jax.block_until_ready(out)
            _ = float(jnp.sum(out))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = pipe(*args)
            jax.block_until_ready(out)
            _ = float(jnp.sum(out))
            best = min(best, (time.perf_counter() - t0) / ITERS)
        print(f"{name:14s} {best*1e3:8.2f} ms", flush=True)
        return best

    t_full = timev("full")
    t_nosort = timev("-sort", with_sort=False)
    t_nden = timev("-density", with_den=False)
    t_niad = timev("-iad", with_iad=False)
    t_nmom = timev("-momentum", with_mom=False)
    t_pro = timev("sort+prologue", with_den=False, with_iad=False,
                  with_mom=False)
    t_sort = timev("sort only", with_pro=False)

    print(f"\nderived: sort~{t_sort*1e3:.1f} pro~{(t_pro-t_sort)*1e3:.1f} "
          f"den~{(t_full-t_nden)*1e3:.1f} iad~{(t_full-t_niad)*1e3:.1f} "
          f"mom~{(t_full-t_nmom)*1e3:.1f} "
          f"sortperm~{(t_full-t_nosort)*1e3:.1f}")
    print(f"full pipeline: {t_full*1e3:.1f} ms -> "
          f"{n/t_full/1e6:.2f}M updates/s (hydro only)")


if __name__ == "__main__":
    main()
