"""Decisive rounding probe for the conservation gap (docs/NEXT.md):
recompute one shock-phase VE force evaluation through the XLA pipeline at
f32 AND f64 and compare Sum m*du. If dt * |S32 - S64| ~ 6e-6 * e0 (the
measured per-step drift), f32 pair-sum rounding drives the drift and
compensated engine accumulation closes it; if it is far smaller, the
drift is inherent scheme truncation at the Courant-limited shock.

save mode (TPU):  python scripts/probe_du_precision.py save
cmp mode (CPU):   JAX_PLATFORMS=cpu python scripts/probe_du_precision.py cmp
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MODE = sys.argv[1] if len(sys.argv) > 1 else "save"
STATES = "/tmp/du_probe_states.npz"


def save():
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_sedov(50)
    sim = Simulation(state, box, const, prop="ve", block=8192,
                     check_every=10)
    out = {}
    for s in range(151):
        if s in (100, 150):
            st = sim.state
            for f in ("x", "y", "z", "vx", "vy", "vz", "h", "m", "temp",
                      "alpha"):
                out[f"{f}_{s}"] = np.asarray(getattr(st, f))
            out[f"min_dt_{s}"] = float(st.min_dt)
        sim.step()
    np.savez(STATES, **out)
    print("saved", STATES, flush=True)


def cmp_mode():
    import jax
    # the axon sitecustomize pre-imports jax with the TPU platform; the
    # env var is too late — route through jax.config like tests/conftest
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.neighbors.cell_list import find_neighbors
    from sphexa_tpu.sfc.keys import compute_sfc_keys
    from sphexa_tpu.simulation import make_propagator_config
    from sphexa_tpu.sph import hydro_std, hydro_ve

    _, box, const = init_sedov(50)
    d = np.load(STATES)

    for s in (100, 150):
        xs = {f: d[f"{f}_{s}"] for f in ("x", "y", "z", "vx", "vy", "vz",
                                         "h", "m", "temp", "alpha")}
        dt = float(d[f"min_dt_{s}"])
        keys = np.asarray(compute_sfc_keys(
            jnp.asarray(xs["x"]), jnp.asarray(xs["y"]),
            jnp.asarray(xs["z"]), box))
        order = np.argsort(keys, kind="stable")
        xs = {k: v[order] for k, v in xs.items()}
        skeys = jnp.asarray(keys[order])

        class St:  # minimal state shim for make_propagator_config
            n = xs["x"].shape[0]
            x = jnp.asarray(xs["x"]); y = jnp.asarray(xs["y"])
            z = jnp.asarray(xs["z"]); h = jnp.asarray(xs["h"])

        cfg = make_propagator_config(St, box, const, block=8192,
                                     backend="xla", ngmax=300)
        nbr = cfg.nbr

        def du_sum(dtype):
            f = lambda k: jnp.asarray(xs[k], dtype)
            x, y, z, h, m = f("x"), f("y"), f("z"), f("h"), f("m")
            vx, vy, vz = f("vx"), f("vy"), f("vz")
            temp, alpha = f("temp"), f("alpha")
            nidx, nmask, nc, occ = find_neighbors(
                x.astype(jnp.float32), y.astype(jnp.float32),
                z.astype(jnp.float32), h.astype(jnp.float32), skeys, box,
                nbr)
            assert int(occ) <= nbr.cap, int(occ)
            assert int(jnp.max(nc)) < nbr.ngmax, int(jnp.max(nc))
            blk = cfg.block
            xm = hydro_ve.compute_xmass(x, y, z, h, m, nidx, nmask, box,
                                        const, blk)
            kx, gradh = hydro_ve.compute_ve_def_gradh(
                x, y, z, h, m, xm, nidx, nmask, box, const, blk)
            prho, c, rho, p = hydro_ve.compute_eos_ve(temp, m, kx, xm,
                                                      gradh, const)
            cs = hydro_std.compute_iad(x, y, z, h, xm / kx, nidx, nmask,
                                       box, const, blk)
            dvout = hydro_ve.compute_iad_divv_curlv(
                x, y, z, vx, vy, vz, h, kx, xm, *cs, nidx, nmask, box,
                const, blk)
            divv = dvout[0]
            alpha2 = hydro_ve.compute_av_switches(
                x, y, z, vx, vy, vz, h, c, kx, xm, divv, alpha, *cs,
                nidx, nmask, box, jnp.asarray(dt, dtype), const, blk)
            ax, ay, az, du, _ = hydro_ve.compute_momentum_energy_ve(
                x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha2, *cs,
                nidx, nmask, nc, box, const, blk)
            m64 = np.asarray(m, np.float64)
            return (float(np.sum(m64 * np.asarray(du, np.float64))),
                    float(np.sum(m64 * (np.asarray(vx, np.float64)
                                        * np.asarray(ax, np.float64)
                                        + np.asarray(vy, np.float64)
                                        * np.asarray(ay, np.float64)
                                        + np.asarray(vz, np.float64)
                                        * np.asarray(az, np.float64)))))

        s32, w32 = du_sum(jnp.float32)
        s64, w64 = du_sum(jnp.float64)
        print(f"step {s}: dt={dt:.3e}")
        print(f"  Sum m du   f32={s32:+.6e} f64={s64:+.6e} "
              f"dt*diff={dt*(s32-s64):+.3e}")
        print(f"  Sum m v.a  f32={w32:+.6e} f64={w64:+.6e}")
        print(f"  closure f32 (heat+work)*dt = {dt*(s32+w32):+.3e}")
        print(f"  closure f64 (heat+work)*dt = {dt*(s64+w64):+.3e}",
              flush=True)


if MODE == "save":
    save()
else:
    cmp_mode()
