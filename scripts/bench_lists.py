"""Measure the list-walk engine vs the streaming engine per op on real
TPU hardware (Sedov 100^3 by default) plus the list-build cost.

Timing follows the axon rules from docs/NEXT.md: chain a data dependency
across repeats and discard the first post-compile batch.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import pallas_pairs as pp
from sphexa_tpu.sph.hydro_std import compute_eos_std
from sphexa_tpu.sph.pair_lists import build_pair_lists, estimate_slot_cap


def _barrier(out):
    """axon: block_until_ready can return before device completion; a
    DEPENDENT scalar fetch is the reliable barrier (docs/NEXT.md)."""
    leaf = jax.tree.leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32) if leaf.dtype != jnp.float32
                  else leaf))


def timed(fn, *args, reps=10, **kw):
    out = fn(*args, **kw)           # compile
    _barrier(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    _barrier(out)
    return (time.perf_counter() - t0) / reps, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=100)
    ap.add_argument("--skin-rel", type=float, default=0.2,
                    help="skin as a fraction of 2*h_max")
    ap.add_argument("--ve", action="store_true",
                    help="also measure the VE ops walk-vs-skip")
    args = ap.parse_args()

    state, box, const = init_sedov(args.n)
    cfg = make_propagator_config(state, box, const, backend="pallas")
    nbr = cfg.nbr
    print(f"N={state.n}  level={nbr.level} cap={nbr.cap} "
          f"window={nbr.window} run_cap={nbr.run_cap}")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    x, y, z, h, m = ss.x, ss.y, ss.z, ss.h, ss.m

    h_max = float(jnp.max(h))
    skin = args.skin_rel * 2.0 * h_max
    scap = estimate_slot_cap(x, y, z, h, keys, box, nbr, skin)
    print(f"skin={skin:.5f} ({args.skin_rel} x 2h_max)  slot_cap={scap}")

    build = jax.jit(lambda *a: build_pair_lists(*a, box, nbr, skin, scap))
    t_build, lists = timed(build, x, y, z, h, keys)
    assert int(lists.overflow) == 0
    lanes = float(lists.lanes_total) / state.n
    print(f"list build: {t_build*1e3:7.1f} ms   lanes/target={lanes:.0f}")

    t_rng, ranges = timed(
        jax.jit(lambda *a: pp.group_cell_ranges(*a, box, nbr)),
        x, y, z, h, keys)
    print(f"prologue  : {t_rng*1e3:7.1f} ms")

    # ---- density
    f_s = jax.jit(lambda rng, *a: pp.pallas_density(*a, box, const, nbr,
                                                    ranges=rng))
    f_l = jax.jit(lambda ls, *a: pp.pallas_density(*a, box, const, nbr,
                                                   lists=ls))
    t0, (rho0, nc0, _) = timed(f_s, ranges, x, y, z, h, m, keys)
    t1, (rho1, nc1, _) = timed(f_l, lists, x, y, z, h, m, None)
    ok = np.array_equal(np.asarray(nc0), np.asarray(nc1))
    dr = float(jnp.max(jnp.abs(rho0 - rho1) / rho0))
    print(f"density   : stream {t0*1e3:7.1f} ms  lists {t1*1e3:7.1f} ms  "
          f"x{t0/t1:.2f}  nc_eq={ok} drho={dr:.2e}")
    rho = rho0

    # ---- IAD
    p, c = compute_eos_std(ss.temp, rho, const)
    vol = m / rho
    f_s = jax.jit(lambda rng, *a: pp.pallas_iad(*a, box, const, nbr,
                                                ranges=rng))
    f_l = jax.jit(lambda ls, *a: pp.pallas_iad(*a, box, const, nbr,
                                               lists=ls))
    t0, (cs0, _) = timed(f_s, ranges, x, y, z, h, vol, keys)
    t1, (cs1, _) = timed(f_l, lists, x, y, z, h, vol, None)
    sc = float(jnp.max(jnp.abs(cs0[0])))
    dc = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(cs0, cs1)) / sc
    print(f"iad       : stream {t0*1e3:7.1f} ms  lists {t1*1e3:7.1f} ms  "
          f"x{t0/t1:.2f}  dC={dc:.2e}")

    # ---- momentum
    margs = (x, y, z, ss.vx, ss.vy, ss.vz, h, m, rho, p, c, *cs0)
    f_s = jax.jit(lambda rng, *a: pp.pallas_momentum_energy_std(
        *a, keys, box, const, nbr, ranges=rng))
    f_l = jax.jit(lambda ls, *a: pp.pallas_momentum_energy_std(
        *a, None, box, const, nbr, lists=ls))
    t0, o0 = timed(f_s, ranges, *margs)
    t1, o1 = timed(f_l, lists, *margs)
    sc = float(jnp.max(jnp.abs(o0[0])))
    da = float(jnp.max(jnp.abs(o0[0] - o1[0]))) / sc
    print(f"momentum  : stream {t0*1e3:7.1f} ms  lists {t1*1e3:7.1f} ms  "
          f"x{t0/t1:.2f}  dax={da:.2e}")

    if not args.ve:
        return

    # ---- VE ops: walk vs chunk-skip list modes
    from sphexa_tpu.sph.hydro_ve import compute_eos_ve

    t_xm, (xm, _, _) = timed(
        jax.jit(lambda ls, *a: pp.pallas_xmass(*a, None, box, const, nbr,
                                               lists=ls)),
        lists, x, y, z, h, m)
    (kx, gradh), _ = pp.pallas_ve_def_gradh(x, y, z, h, m, xm, None, box,
                                            const, nbr, lists=lists)
    prho, cve, rhove, pve = compute_eos_ve(ss.temp, m, kx, xm, gradh, const)
    dv_args = (x, y, z, ss.vx, ss.vy, ss.vz, h, kx, xm, *cs0)
    f_w = jax.jit(lambda ls, *a: pp.pallas_iad_divv_curlv(
        *a, None, box, const, nbr, lists=ls, list_walk=True))
    f_k = jax.jit(lambda ls, *a: pp.pallas_iad_divv_curlv(
        *a, None, box, const, nbr, lists=ls, list_walk=False))
    tw, ow = timed(f_w, lists, *dv_args)
    tk, ok_ = timed(f_k, lists, *dv_args)
    dd = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(ow[0], ok_[0]))
    print(f"divv_curlv: skip   {tk*1e3:7.1f} ms  walk  {tw*1e3:7.1f} ms  "
          f"x{tk/tw:.2f}  d={dd:.2e}")

    divv, curlv = ow[0][0], ow[0][1]
    av_args = (x, y, z, ss.vx, ss.vy, ss.vz, h, cve, kx, xm, divv,
               ss.alpha, *cs0)
    f_w = jax.jit(lambda ls, *a: pp.pallas_av_switches(
        *a, None, box, 1e-5, const, nbr, lists=ls, list_walk=True))
    f_k = jax.jit(lambda ls, *a: pp.pallas_av_switches(
        *a, None, box, 1e-5, const, nbr, lists=ls, list_walk=False))
    tw, aw = timed(f_w, lists, *av_args)
    tk, ak = timed(f_k, lists, *av_args)
    dd = float(jnp.max(jnp.abs(aw[0] - ak[0])))
    print(f"av_switch : skip   {tk*1e3:7.1f} ms  walk  {tw*1e3:7.1f} ms  "
          f"x{tk/tw:.2f}  d={dd:.2e}")


if __name__ == "__main__":
    main()
