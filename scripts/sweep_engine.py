"""Sweep the pallas engine's static config on the current device
(cell_target x run_cap x gap x group) and report per-op times.

Usage: [PROF_SIDE=100] python scripts/sweep_engine.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))
ITERS = 5


def time_config(state, box, const, cell_target, run_cap, gap, group):
    cfg = make_propagator_config(
        state, box, const, block=8192, backend="pallas",
        cell_target=cell_target, run_cap=run_cap, gap=gap,
    )
    nbr = cfg.nbr
    if group != nbr.group:
        import dataclasses
        nbr = dataclasses.replace(nbr, group=group)

    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m

    @jax.jit
    def pipeline(x, y, z, h, m, temp, vx, vy, vz, keys):
        ranges = pp.group_cell_ranges(x, y, z, h, keys, box, nbr)
        rho, nc, occ = pp.pallas_density(
            x, y, z, h, m, keys, box, const, nbr, ranges=ranges)
        p, c = hydro_std.compute_eos_std(temp, rho, const)
        cs, _ = pp.pallas_iad(
            x, y, z, h, m / rho, keys, box, const, nbr, ranges=ranges)
        out = pp.pallas_momentum_energy_std(
            x, y, z, vx, vy, vz, h, m, rho, p, c, *cs,
            keys, box, const, nbr, ranges=ranges)
        return rho, nc, occ, out[0], ranges.ncells

    from sphexa_tpu.sfc.keys import compute_sfc_keys
    keys = compute_sfc_keys(x, y, z, box)
    skeys = jnp.sort(keys)
    args = (x, y, z, h, m, state.temp, state.vx, state.vy, state.vz, skeys)
    out = pipeline(*args)
    jax.block_until_ready(out)
    occ = int(out[2])
    if occ > nbr.cap:
        print(f"  ct={cell_target:4d} rc={run_cap:4d} gap={gap:3d} g={group:3d}"
              f"  OVERFLOW occ={occ} cap={nbr.cap}")
        return
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = pipeline(*args)
    jax.block_until_ready(out)
    _ = float(jnp.sum(out[3]))  # device_get: force real completion (axon)
    dt = (time.perf_counter() - t0) / ITERS
    nrun = float(jnp.mean(out[4].astype(jnp.float32)))
    print(f"  ct={cell_target:4d} rc={run_cap:4d} gap={gap:3d} g={group:3d}"
          f"  lvl={nbr.level} cap={nbr.cap} win={nbr.window}"
          f"  runs~{nrun:5.1f}  {dt*1e3:8.2f} ms")


def main():
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    for _ in range(2):
        sim.step()
    state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, _, _ = _sort_by_keys(state, box, "hilbert")

    for group in (64, 128, 256):
        for cell_target in (128, 256):
            for run_cap, gap in ((1536, 384), (2048, 512), (1024, 256)):
                try:
                    time_config(state, box, const, cell_target, run_cap, gap, group)
                except Exception as e:  # noqa
                    print(f"  ct={cell_target} rc={run_cap} gap={gap} g={group} FAILED: {type(e).__name__}: {e}"[:160])


if __name__ == "__main__":
    main()
