"""Sweep the pallas engine's static config (cell_target x run_cap x
gap x group) on the current device — now a thin wrapper over the
autotuner's replay harness (sphexa_tpu/tuning), so the sweep times the
REAL stepped pipeline with the sync-free window clock, every candidate
lands as a schema-v5 ``sweep`` event in <out>/events.jsonl, and the
winner can be committed straight into TUNING_TABLE.json (--write-table
via SWEEP_TABLE). The old hand-built jitted pipeline + ad-hoc
time.perf_counter loop lives on only in git history.

Usage: [PROF_SIDE=100] [SWEEP_BUDGET=18] [SWEEP_TABLE=TUNING_TABLE.json]
       python scripts/sweep_engine.py [sweep-out-dir]
"""

import os
import sys

from sphexa_tpu.tuning.cli import main

if __name__ == "__main__":
    argv = [
        "--case", "sedov",
        "--side", os.environ.get("PROF_SIDE", "100"),
        "--backend", "pallas",
        "--knobs", "cell_target,run_cap,gap,group",
        "--budget", os.environ.get("SWEEP_BUDGET", "18"),
        "--steps", "3", "--warmup", "1",
        "--out", sys.argv[1] if len(sys.argv) > 1 else "sweep-engine-out",
        "--format", "json",
    ]
    table = os.environ.get("SWEEP_TABLE")
    if table:
        argv += ["--write-table", table]
    sys.exit(main(argv))
