"""Step-time breakdown of the std Pallas pipeline (perf work harness).

Times each stage of the hot loop separately on the current default device:
SFC keygen+argsort, the group cell-range prologue, and each pallas op.
The analog of the reference's per-substep Timer printout
(main/src/util/timer.hpp:46-52) for offline perf work.

Usage: [PROF_SIDE=100] [PROF_ITERS=5] python scripts/profile_step.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))
ITERS = int(os.environ.get("PROF_ITERS", "5"))


def timeit(name, fn, *args, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"  {name:28s} {dt * 1e3:9.2f} ms")
    return out, dt


def main():
    n = SIDE**3
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    # settle the config with a couple of real steps
    for _ in range(2):
        sim.step()
    state, box, cfg = sim.state, sim.box, sim._cfg
    nbr = cfg.nbr
    print(f"n={n}  level={nbr.level} cap={nbr.cap} window={nbr.window} "
          f"backend={cfg.backend}")

    box = make_global_box(state.x, state.y, state.z, box)

    total = 0.0

    from sphexa_tpu.propagator import _sort_by_keys

    @jax.jit
    def sort_state(state):
        return _sort_by_keys(state, box, cfg.curve)[:2]

    (state, skeys), dt = timeit("keygen+sort+gather", sort_state, state)
    total += dt
    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m

    ranges_fn = jax.jit(
        lambda x, y, z, h, k: pp.group_cell_ranges(x, y, z, h, k, box, nbr)
    )
    ranges, dt = timeit("group_cell_ranges", ranges_fn, x, y, z, h, skeys)
    total += dt

    dens = jax.jit(
        lambda *a: pp.pallas_density(*a, box, const, nbr, ranges=ranges)
    )
    (rho, nc, occ), dt = timeit("pallas_density", dens, x, y, z, h, m, skeys)
    total += dt

    eos = jax.jit(lambda t, r: hydro_std.compute_eos_std(t, r, const))
    (p, c), dt = timeit("eos", eos, state.temp, rho)
    total += dt

    iad = jax.jit(
        lambda *a: pp.pallas_iad(*a, box, const, nbr, ranges=ranges)
    )
    (cij, _), dt = timeit("pallas_iad", iad, x, y, z, h, m / rho, skeys)
    total += dt

    mom = jax.jit(
        lambda *a: pp.pallas_momentum_energy_std(
            *a, skeys, box, const, nbr, ranges=ranges
        )
    )
    out, dt = timeit(
        "pallas_momentum", mom, x, y, z, state.vx, state.vy, state.vz,
        h, m, rho, p, c, *cij,
    )
    total += dt

    print(f"  {'SUM of stages':28s} {total * 1e3:9.2f} ms")

    t0 = time.perf_counter()
    for _ in range(3):
        sim.step()
    jax.block_until_ready(sim.state.x)
    full = (time.perf_counter() - t0) / 3
    print(f"  {'full Simulation.step':28s} {full * 1e3:9.2f} ms")
    print(f"  updates/s: {n / full:,.0f}")


if __name__ == "__main__":
    main()
