#!/usr/bin/env bash
# Repo gate: jaxlint (AST) -> jaxaudit (trace) -> telemetry smoke ->
# tier-1 tests — what CI (and a pre-push hook) runs.
#
#   scripts/check.sh                  # lint + audit + telemetry + fast tier
#   scripts/check.sh --lint-only
#   scripts/check.sh --audit-only
#   scripts/check.sh --telemetry-only
set -uo pipefail

cd "$(dirname "$0")/.."

run_lint() {
    echo "== jaxlint (sphexa_tpu/, baseline: jaxlint_baseline.json) =="
    python -m sphexa_tpu.devtools.lint sphexa_tpu \
        --baseline jaxlint_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxlint failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxlint: disable=JXLxxx -- reason' (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_audit() {
    echo "== jaxaudit (entry registry, baseline: jaxaudit_baseline.json) =="
    python -m sphexa_tpu.devtools.audit sphexa_tpu \
        --baseline jaxaudit_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxaudit failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxaudit: disable=JXAxxx -- reason' on the entry"
        echo "registration (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_telemetry() {
    echo "== telemetry smoke (5-step run -> sphexa-telemetry summary --strict) =="
    local dir rc
    dir=$(mktemp -d)
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --telemetry-dir "$dir/run" -o "$dir/out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "telemetry smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # --strict: every event must validate against the schema (v3; v1/v2
    # files keep validating via SUPPORTED_VERSIONS, pinned in tests)
    python -m sphexa_tpu.telemetry summary "$dir/run" --strict
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry summary failed (rc=$rc); schema drift or"
        echo "missing events — see docs/OBSERVABILITY.md."
        exit $rc
    fi
    # science must RENDER the in-graph ledger (exit 1 = no physics
    # events: the step-tail ledger or its fetch wiring broke)
    python -m sphexa_tpu.telemetry science "$dir/run"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry science failed (rc=$rc): no physics"
        echo "telemetry or a watchdog fired — the conservation ledger"
        echo "wiring broke (docs/OBSERVABILITY.md, schema v3)."
        exit $rc
    fi

    echo "== distributed telemetry smoke (2-device CPU mesh -> shards view) =="
    # sparse halo exchange + schema-v2 shard events on a forced
    # 2-virtual-device mesh: the CPU rehearsal of the v5e-16 campaign's
    # day-one instrumentation (exchange/shard_load/memory events)
    python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --devices 2 --cpu-mesh --backend pallas --check-every 5 \
        --telemetry-dir "$dir/mesh" -o "$dir/mesh_out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "2-device mesh smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # shards must RENDER per-shard telemetry (exit 1 = events missing)
    python -m sphexa_tpu.telemetry shards "$dir/mesh"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry shards failed (rc=$rc): the mesh run wrote"
        echo "no per-shard telemetry — exchange/shard_load wiring broke."
        exit $rc
    fi
    # science on the DEFERRED mesh run: every step of the --check-every 5
    # window must have kept its ledger row
    python -m sphexa_tpu.telemetry science "$dir/mesh"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry science failed on the mesh run (rc=$rc):"
        echo "the deferred window lost its physics rows."
        exit $rc
    fi
    python -m sphexa_tpu.telemetry summary "$dir/mesh" --strict
    rc=$?
    rm -rf "$dir"
    if [ $rc -ne 0 ]; then
        echo "strict schema validation failed on the mesh run (rc=$rc)"
        exit $rc
    fi
}

run_multichip_diff() {
    echo "== multi-chip comm-volume gate (measure_multichip --quick vs baseline) =="
    local tmp rc
    tmp=$(mktemp -d)
    env JAX_PLATFORMS=cpu python scripts/measure_multichip.py \
        --quick --json > "$tmp/multichip.json"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "measure_multichip --quick failed (rc=$rc)"
        rm -rf "$tmp"
        exit $rc
    fi
    # threshold exit codes over the MULTICHIP wrapper shape: headline is
    # the sparse-exchange saving vs replication (higher = better); a
    # candidate shipping >5% more rows than the committed baseline fails
    python -m sphexa_tpu.telemetry diff MULTICHIP_BASELINE.json \
        "$tmp/multichip.json" --threshold 0.05
    rc=$?
    rm -rf "$tmp"
    if [ $rc -ne 0 ]; then
        echo "multi-chip comm volume regressed vs MULTICHIP_BASELINE.json"
        echo "(rc=$rc); if intentional, regenerate the baseline:"
        echo "  scripts/measure_multichip.py --quick --json  (wrap in the"
        echo "  {n_devices, rc, tail} driver shape, see the current file)"
        exit $rc
    fi
}

case "${1:-}" in
    --lint-only)
        run_lint
        exit 0
        ;;
    --audit-only)
        run_audit
        exit 0
        ;;
    --telemetry-only)
        run_telemetry
        exit 0
        ;;
esac

run_lint
run_audit
run_telemetry
run_multichip_diff

echo "== tier-1 tests (fast tier, CPU) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
