#!/usr/bin/env bash
# Repo gate: jaxlint (AST) -> jaxaudit (trace) -> telemetry smoke ->
# history/regression lock -> tier-1 tests — what CI (and a pre-push
# hook) runs.
#
#   scripts/check.sh                  # lint + audit + preflight + cost + telemetry + history + tuning + fast tier
#   scripts/check.sh --lint-only
#   scripts/check.sh --audit-only
#   scripts/check.sh --preflight-only
#   scripts/check.sh --cost-only
#   scripts/check.sh --telemetry-only
#   scripts/check.sh --history-only
#   scripts/check.sh --tuning-only
#   scripts/check.sh --serve-only
#   scripts/check.sh --lowering-only
#   scripts/check.sh --schema-only
set -uo pipefail

cd "$(dirname "$0")/.."

run_lint() {
    echo "== jaxlint (sphexa_tpu/, baseline: jaxlint_baseline.json) =="
    python -m sphexa_tpu.devtools.lint sphexa_tpu \
        --baseline jaxlint_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxlint failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxlint: disable=JXLxxx -- reason' (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_audit() {
    echo "== jaxaudit (entry registry, baseline: jaxaudit_baseline.json) =="
    python -m sphexa_tpu.devtools.audit sphexa_tpu \
        --baseline jaxaudit_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxaudit failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxaudit: disable=JXAxxx -- reason' on the entry"
        echo "registration (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_preflight() {
    echo "== shardcheck preflight (campaign-shaped SPMD audit, mesh 4) =="
    # the JXA2xx gate at campaign shapes: collective order, donation-aware
    # peak HBM rescaled to 64M/16 vs the 16 GiB budget, sharding
    # propagation + exchange volume — all by tracing only, no compile
    python -m sphexa_tpu.devtools.audit preflight --mesh 4
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "preflight failed (rc=$rc); a sharded entry has an order race,"
        echo "busts the per-device HBM budget at campaign N, or ships more"
        echo "than its exchange budget (docs/STATIC_ANALYSIS.md, JXA2xx)."
        exit $rc
    fi
}

run_cost() {
    echo "== jaxcost (static roofline audit, budget gate, calibration band) =="
    local rc
    # the JXA3xx gate: every registry entry's static per-phase cost vs
    # the committed COST_BUDGET.json, phase coverage, bound declarations
    python -m sphexa_tpu.devtools.audit cost
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "sphexa-audit cost failed (rc=$rc): an entry busted its"
        echo "COST_BUDGET.json phase ceiling, lost phase coverage, or a"
        echo "declared-compute-bound phase went memory-bound"
        echo "(docs/STATIC_ANALYSIS.md, JXA3xx)."
        exit $rc
    fi
    # the committed budget file itself must stay schema-valid
    python - <<'EOF'
from sphexa_tpu.devtools.audit.costmodel import load_budget
load_budget("COST_BUDGET.json")
EOF
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "COST_BUDGET.json failed schema validation (rc=$rc)"
        exit $rc
    fi
    # calibration band: the static prediction of the committed fixture
    # target must sit inside the band calibration.json declares against
    # the committed capture — a drifted per-primitive cost rule fails
    # HERE before it silently re-ranks any static-cost sweep
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.telemetry trace \
        tests/trace_fixture --predict
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "trace --predict calibration failed (rc=$rc): the cost"
        echo "model drifted from the committed capture; fix the rules or"
        echo "regenerate with scripts/make_trace_fixture.py"
        echo "(docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_telemetry() {
    echo "== telemetry smoke (5-step run -> sphexa-telemetry summary --strict) =="
    local dir rc
    dir=$(mktemp -d)
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --telemetry-dir "$dir/run" --trace-dir "$dir/trace" -o "$dir/out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "telemetry smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # phase attribution (schema v4, the chip-harvest acceptance gate):
    # >= 80% of the capture's device-op time must land in named
    # sphexa/<phase> scopes — a refactor that strips the named scopes,
    # or a traceview regression, fails HERE on the CPU profiler
    python -m sphexa_tpu.telemetry trace "$dir/trace" --min-coverage 0.8
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry trace failed (rc=$rc): phase attribution"
        echo "below 80% or no sphexa/ scopes in the capture"
        echo "(util/phases.py, tests/test_phase_attr.py)."
        exit $rc
    fi
    # a clean run must leave NO crash blackbox (the flight recorder
    # disarms on close; a dump here means an exit path skipped it)
    if [ -f "$dir/run/blackbox.json" ]; then
        echo "clean smoke run left a blackbox.json — the flight recorder"
        echo "was not disarmed on the clean-exit path (telemetry/flightrec.py)"
        rm -rf "$dir"
        exit 1
    fi
    # --strict: every event must validate against the schema (v4; v1-v3
    # files keep validating via SUPPORTED_VERSIONS, pinned in tests)
    python -m sphexa_tpu.telemetry summary "$dir/run" --strict
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry summary failed (rc=$rc); schema drift or"
        echo "missing events — see docs/OBSERVABILITY.md."
        exit $rc
    fi
    # science must RENDER the in-graph ledger (exit 1 = no physics
    # events: the step-tail ledger or its fetch wiring broke)
    python -m sphexa_tpu.telemetry science "$dir/run"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry science failed (rc=$rc): no physics"
        echo "telemetry or a watchdog fired — the conservation ledger"
        echo "wiring broke (docs/OBSERVABILITY.md, schema v3)."
        exit $rc
    fi

    echo "== distributed telemetry smoke (2-device CPU mesh -> shards view) =="
    # sparse halo exchange + schema-v2 shard events on a forced
    # 2-virtual-device mesh: the CPU rehearsal of the v5e-16 campaign's
    # day-one instrumentation (exchange/shard_load/memory events)
    python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --devices 2 --cpu-mesh --backend pallas --check-every 5 \
        --telemetry-dir "$dir/mesh" -o "$dir/mesh_out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "2-device mesh smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # shards must RENDER per-shard telemetry (exit 1 = events missing)
    python -m sphexa_tpu.telemetry shards "$dir/mesh"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry shards failed (rc=$rc): the mesh run wrote"
        echo "no per-shard telemetry — exchange/shard_load wiring broke."
        exit $rc
    fi
    # science on the DEFERRED mesh run: every step of the --check-every 5
    # window must have kept its ledger row
    python -m sphexa_tpu.telemetry science "$dir/mesh"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry science failed on the mesh run (rc=$rc):"
        echo "the deferred window lost its physics rows."
        exit $rc
    fi
    python -m sphexa_tpu.telemetry summary "$dir/mesh" --strict
    rc=$?
    rm -rf "$dir"
    if [ $rc -ne 0 ]; then
        echo "strict schema validation failed on the mesh run (rc=$rc)"
        exit $rc
    fi
}

run_history() {
    echo "== history + regression lock (trend render, TELEMETRY_LOCK gate) =="
    local tmp rc
    # the committed rounds must render as one trend (exit 0)
    python -m sphexa_tpu.telemetry history
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "sphexa-telemetry history failed (rc=$rc) over the committed"
        echo "BENCH_r*/MULTICHIP_r* rounds (telemetry/history.py)."
        exit $rc
    fi
    # the committed lock must HOLD against the committed sources: a
    # chip-less PR cannot regress a locked, chip-measured number
    python -m sphexa_tpu.telemetry regress --lock TELEMETRY_LOCK.json
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "regression vs TELEMETRY_LOCK.json (rc=$rc): a locked,"
        echo "chip-measured metric regressed or its source went missing."
        echo "If a relock is intentional (new chip round committed):"
        echo "  sphexa-telemetry regress --lock TELEMETRY_LOCK.json --write"
        exit $rc
    fi
    # exit-code contract smoke: a doctored lock (impossible chip number)
    # must fail with 1, an unreadable lock with 2 — the gate's teeth
    tmp=$(mktemp -d)
    python - "$tmp" <<'EOF'
import json, sys
lock = json.load(open("TELEMETRY_LOCK.json"))
lock["metrics"][0]["value"] *= 100.0
json.dump(lock, open(sys.argv[1] + "/doctored.json", "w"))
open(sys.argv[1] + "/corrupt.json", "w").write("{not json")
EOF
    python -m sphexa_tpu.telemetry regress \
        --lock "$tmp/doctored.json" --root . >/dev/null
    if [ $? -ne 1 ]; then
        echo "regress failed to flag a doctored lock (expected exit 1)"
        rm -rf "$tmp"
        exit 1
    fi
    python -m sphexa_tpu.telemetry regress \
        --lock "$tmp/corrupt.json" --root . 2>/dev/null
    if [ $? -ne 2 ]; then
        echo "regress failed to reject a corrupt lock (expected exit 2)"
        rm -rf "$tmp"
        exit 1
    fi
    rm -rf "$tmp"
}

run_tuning() {
    echo "== tuning gate (table validation, CPU micro-sweep, table consumption) =="
    local tmp rc
    # the COMMITTED table must stay schema- and registry-valid: a knob
    # rename that strands TUNING_TABLE.json entries fails HERE (exit 1)
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.telemetry tuning \
        TUNING_TABLE.json
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "sphexa-telemetry tuning failed on the committed table"
        echo "(rc=$rc): stale knob names or schema drift — re-sweep or"
        echo "fix TUNING_TABLE.json (docs/TUNING.md)."
        exit $rc
    fi
    # close the observe->decide loop on CPU: a 2-candidate micro-sweep
    # over a tiny sedov must complete, commit its winner to a scratch
    # table, and every candidate must land as a strict-valid v5 sweep
    # event in the sweep run's events.jsonl
    tmp=$(mktemp -d)
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.tuning.cli \
        --case sedov --side 5 --backend xla --knobs gap --budget 2 \
        --steps 2 --warmup 1 --quiet --commit best \
        --out "$tmp/sweep" --write-table "$tmp/table.json"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "sphexa-tune micro-sweep failed (rc=$rc): no candidate"
        echo "measured cleanly (sphexa_tpu/tuning/replay.py)."
        rm -rf "$tmp"
        exit $rc
    fi
    python -m sphexa_tpu.telemetry summary "$tmp/sweep" --strict
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "strict validation failed on the sweep run (rc=$rc): the"
        echo "autotuner emitted schema-invalid sweep/tuning events."
        rm -rf "$tmp"
        exit $rc
    fi
    # the replay harness's output must be CONSUMABLE: a Simulation built
    # with tuned=<table> must resolve its knobs from the entry we just
    # committed (provenance source == "table")
    env JAX_PLATFORMS=cpu python - "$tmp/table.json" <<'EOF'
import sys
from sphexa_tpu.init import make_initializer
from sphexa_tpu.simulation import Simulation
state, box, const = make_initializer("sedov")(5)
sim = Simulation(state, box, const, backend="xla",
                 tuned=sys.argv[1], workload="sedov")
prov = sim.tuning_provenance
assert prov["source"] == "table", prov
assert prov["knobs"], prov
EOF
    rc=$?
    rm -rf "$tmp"
    if [ $rc -ne 0 ]; then
        echo "tuned=<table> consumption failed (rc=$rc): Simulation did"
        echo "not resolve knobs from the freshly committed entry"
        echo "(sphexa_tpu/tuning/table.py resolve_knobs)."
        exit $rc
    fi
}

run_blockdt() {
    echo "== block-dt smoke (5-step two-scale run -> schema-v6 dt_bins gate) =="
    local dir rc
    dir=$(mktemp -d)
    # sedov IS the two-scale case (hot core, cold ambient); a full B=4
    # cycle (8 substeps) so the deep bins come due and the updates-saved
    # factor is well-defined in the flush event
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 8 --quiet \
        --dt-bins 4 --bin-resort-drift 0.01 --check-every 4 \
        --telemetry-dir "$dir/run" -o "$dir/out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "block-dt smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # --strict: the v6 dt_bins events must validate against the schema
    python -m sphexa_tpu.telemetry summary "$dir/run" --strict
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "strict schema validation failed on the block-dt run"
        echo "(rc=$rc): the schema-v6 dt_bins event drifted from the"
        echo "registry (docs/OBSERVABILITY.md, telemetry/registry.py)."
        exit $rc
    fi
    # the science view must RENDER the bin histogram (grep is the gate:
    # science exits 0 on any physics rows, the table is v6-specific)
    python -m sphexa_tpu.telemetry science "$dir/run" | tee "$dir/sci.txt"
    rc=$?
    if [ $rc -ne 0 ] || ! grep -q "dt bins" "$dir/sci.txt"; then
        rm -rf "$dir"
        echo "sphexa-telemetry science lost the dt-bins histogram"
        echo "(rc=$rc): the dt_bins flush event or its science view"
        echo "broke (simulation._emit_blockdt, telemetry/cli.py)."
        exit 1
    fi
    rm -rf "$dir"
}

run_serve() {
    echo "== live science surface (snapshot ring -> sphexa-telemetry serve) =="
    local dir rc
    dir=$(mktemp -d)
    # 5-step 2-virtual-device deferred run with in-graph snapshots ON:
    # the schema-v8 smoke — snapshot events + .npz ring frames must land
    # at the flush boundary and validate strictly
    python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --devices 2 --cpu-mesh --backend pallas --check-every 5 \
        --snap rho --snap-grid 16 \
        --telemetry-dir "$dir/fleet/run_a" -o "$dir/out_a"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "snapshot smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    if ! ls "$dir/fleet/run_a/snapshots/"*.npz >/dev/null 2>&1; then
        echo "the snapshot run wrote no .npz ring frames"
        echo "(observables/snapshot.py, simulation._emit_snapshot)."
        rm -rf "$dir"
        exit 1
    fi
    python -m sphexa_tpu.telemetry summary "$dir/fleet/run_a" --strict
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "strict schema validation failed on the snapshot run"
        echo "(rc=$rc): the schema-v8 snapshot event drifted from the"
        echo "registry (docs/OBSERVABILITY.md, telemetry/registry.py)."
        exit $rc
    fi
    # doctored crash member: a second run whose flight recorder dumped —
    # the fleet page must render it as a CRASH card, not hide it
    env JAX_PLATFORMS=cpu python - "$dir/fleet/run_b" <<'EOF'
import sys

from sphexa_tpu.init import make_initializer
from sphexa_tpu.observables import SnapshotSpec
from sphexa_tpu.simulation import Simulation
from sphexa_tpu.telemetry import FlightRecorder, JsonlSink, Telemetry

d = sys.argv[1]
tel = Telemetry(sinks=[JsonlSink(d + "/events.jsonl")])
rec = FlightRecorder(d, telemetry=tel)
tel.sinks.append(rec.sink)
state, box, const = make_initializer("sedov")(6)
sim = Simulation(state, box, const, prop="std", block=512, telemetry=tel,
                 snap_spec=SnapshotSpec(fields=("rho",), grid=16),
                 snap_dir=d + "/snapshots")
sim.step()
rec.dump(reason="check.sh doctored crash: SIGKILL rehearsal")
rec.close()
tel.close()
EOF
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "doctored-crash member build failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # serve --once over the 2-run fleet: ONE self-contained HTML page
    # with both members, an inline frame, and the crash rendered red
    python -m sphexa_tpu.telemetry serve "$dir/fleet" \
        --once --out "$dir/dash.html"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry serve --once failed (rc=$rc) on a"
        echo "readable 2-run fleet (telemetry/serve.py)."
        exit $rc
    fi
    if ! grep -q "run_a" "$dir/dash.html" \
            || ! grep -q "run_b" "$dir/dash.html" \
            || ! grep -q "data:image/png;base64," "$dir/dash.html" \
            || ! grep -q "CRASH" "$dir/dash.html"; then
        echo "the fleet page lost a member, the inline ring frame, or"
        echo "the CRASH section (telemetry/serve.py render pipeline)."
        rm -rf "$dir"
        exit 1
    fi
    # fleet table over the same dirs (the one-line-per-run view)
    python -m sphexa_tpu.telemetry fleet "$dir/fleet" >/dev/null
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$dir"
        echo "sphexa-telemetry fleet failed (rc=$rc) on a readable fleet"
        exit $rc
    fi
    # exit-code contract smokes: nothing matched = 1, all-corrupt = 2
    python -m sphexa_tpu.telemetry serve "$dir/no_such_*" --once \
        --out "$dir/none.html" 2>/dev/null
    if [ $? -ne 1 ]; then
        echo "serve failed to exit 1 when no run dirs matched"
        rm -rf "$dir"
        exit 1
    fi
    mkdir -p "$dir/corrupt_run"
    echo "{not json" > "$dir/corrupt_run/events.jsonl"
    python -m sphexa_tpu.telemetry serve "$dir/corrupt_run" --once \
        --out "$dir/corrupt.html" 2>/dev/null
    if [ $? -ne 2 ]; then
        echo "serve failed to exit 2 when every matched run is unreadable"
        rm -rf "$dir"
        exit 1
    fi
    rm -rf "$dir"
}

run_lowering() {
    echo "== jaxdiff lowering lock (fingerprint verify vs LOWERING_LOCK.json) =="
    local tmp rc
    # the committed lock must HOLD against the committed sources: every
    # registry entry's canonical lowering fingerprint, verified at the
    # same 8-virtual-device mesh the lock was written at — a silent
    # lowering drift fails HERE before it reaches a chip round
    env SPHEXA_AUDIT_DEVICES=8 python -m sphexa_tpu.devtools.audit \
        lowering --cpu-devices 8
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "lowering lock verification failed (rc=$rc): an entry's"
        echo "jaxpr drifted from LOWERING_LOCK.json. Review the"
        echo "structural diff above; if the change is intentional:"
        echo "  sphexa-audit lowering --write --cpu-devices 8"
        echo "(docs/STATIC_ANALYSIS.md, jaxdiff)."
        exit $rc
    fi
    # exit-code contract smoke: a doctored digest must fail with 1, an
    # unreadable lock with 2 — the gate's teeth (same pattern as the
    # TELEMETRY_LOCK smoke in run_history)
    tmp=$(mktemp -d)
    python - "$tmp" <<'EOF'
import json, sys
lock = json.load(open("LOWERING_LOCK.json"))
lock["entries"]["step_std"]["digest"] = "0" * 32
json.dump(lock, open(sys.argv[1] + "/doctored.json", "w"))
open(sys.argv[1] + "/corrupt.json", "w").write("{not json")
EOF
    python -m sphexa_tpu.devtools.audit lowering --entries step_std \
        --lock "$tmp/doctored.json" >/dev/null
    if [ $? -ne 1 ]; then
        echo "lowering failed to flag a doctored lock (expected exit 1)"
        rm -rf "$tmp"
        exit 1
    fi
    python -m sphexa_tpu.devtools.audit lowering --entries step_std \
        --lock "$tmp/corrupt.json" 2>/dev/null
    if [ $? -ne 2 ]; then
        echo "lowering failed to reject a corrupt lock (expected exit 2)"
        rm -rf "$tmp"
        exit 1
    fi
    rm -rf "$tmp"
}

run_schema() {
    echo "== statecheck schema lock (symbolic state schema vs STATE_SCHEMA.json) =="
    local tmp rc
    # the committed lock must HOLD against the committed sources: every
    # registry entry's carry/output schema (pytree paths, dtype,
    # weak_type, axis polynomials in N), verified cross-process at the
    # default mesh the lock was written at — a carry change that would
    # break the ensemble server or the restart format fails HERE
    python -m sphexa_tpu.devtools.audit schema
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "state schema verification failed (rc=$rc): an entry's"
        echo "carry/output schema drifted from STATE_SCHEMA.json, or a"
        echo "carry is not closed (JXA503). Review the per-leaf diff"
        echo "above; if the change is intentional:"
        echo "  sphexa-audit schema --write"
        echo "(docs/STATIC_ANALYSIS.md, statecheck)."
        exit $rc
    fi
    # exit-code contract smoke: a doctored leaf dtype must fail with 1,
    # an unreadable lock with 2 — the gate's teeth (same pattern as the
    # TELEMETRY_LOCK and LOWERING_LOCK smokes)
    tmp=$(mktemp -d)
    python - "$tmp" <<'EOF'
import json, sys
lock = json.load(open("STATE_SCHEMA.json"))
for leaf in lock["entries"]["step_std"]["leaves"].values():
    leaf["dtype"] = "float64"
json.dump(lock, open(sys.argv[1] + "/doctored.json", "w"))
open(sys.argv[1] + "/corrupt.json", "w").write("{not json")
EOF
    python -m sphexa_tpu.devtools.audit schema --entries step_std \
        --lock "$tmp/doctored.json" >/dev/null
    if [ $? -ne 1 ]; then
        echo "schema failed to flag a doctored lock (expected exit 1)"
        rm -rf "$tmp"
        exit 1
    fi
    python -m sphexa_tpu.devtools.audit schema --entries step_std \
        --lock "$tmp/corrupt.json" 2>/dev/null
    if [ $? -ne 2 ]; then
        echo "schema failed to reject a corrupt lock (expected exit 2)"
        rm -rf "$tmp"
        exit 1
    fi
    rm -rf "$tmp"
}

run_multichip_diff() {
    echo "== multi-chip comm-volume gate (measure_multichip --quick vs baseline) =="
    local tmp rc
    tmp=$(mktemp -d)
    env JAX_PLATFORMS=cpu python scripts/measure_multichip.py \
        --quick --json > "$tmp/multichip.json"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "measure_multichip --quick failed (rc=$rc)"
        rm -rf "$tmp"
        exit $rc
    fi
    # threshold exit codes over the MULTICHIP wrapper shape: headline is
    # the sparse-exchange saving vs replication (higher = better); a
    # candidate shipping >5% more rows than the committed baseline fails
    python -m sphexa_tpu.telemetry diff MULTICHIP_BASELINE.json \
        "$tmp/multichip.json" --threshold 0.05
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -rf "$tmp"
        echo "multi-chip comm volume regressed vs MULTICHIP_BASELINE.json"
        echo "(rc=$rc); if intentional, regenerate the baseline:"
        echo "  scripts/measure_multichip.py --quick --json  (wrap in the"
        echo "  {n_devices, rc, tail} driver shape, see the current file)"
        exit $rc
    fi
    # the gravity comm diet must keep paying: MAC-need rows strictly
    # below the retired full-slab exchange at the largest quick row
    python - "$tmp/multichip.json" <<'EOF'
import json, sys
extra = json.load(open(sys.argv[1]))["extra"]
saving = extra["s40_p8_grav_saving"]
assert saving > 1.0, f"gravity MAC-need saving {saving} <= 1 (full slab)"
print(f"gravity MAC-need saving vs full slab: {saving}x")
EOF
    rc=$?
    rm -rf "$tmp"
    if [ $rc -ne 0 ]; then
        echo "gravity MAC-need sizing lost its saving vs the full-slab"
        echo "exchange (rc=$rc): sizing.gravity_need_matrix or the serve"
        echo "sizing regressed (docs/NEXT.md round 13)."
        exit $rc
    fi
}

case "${1:-}" in
    --lint-only)
        run_lint
        exit 0
        ;;
    --audit-only)
        run_audit
        exit 0
        ;;
    --preflight-only)
        run_preflight
        exit 0
        ;;
    --cost-only)
        run_cost
        exit 0
        ;;
    --telemetry-only)
        run_telemetry
        exit 0
        ;;
    --history-only)
        run_history
        exit 0
        ;;
    --tuning-only)
        run_tuning
        exit 0
        ;;
    --blockdt-only)
        run_blockdt
        exit 0
        ;;
    --serve-only)
        run_serve
        exit 0
        ;;
    --lowering-only)
        run_lowering
        exit 0
        ;;
    --schema-only)
        run_schema
        exit 0
        ;;
esac

run_lint
run_audit
run_preflight
run_cost
run_telemetry
run_history
run_tuning
run_blockdt
run_serve
run_lowering
run_schema
run_multichip_diff

echo "== tier-1 tests (fast tier, CPU) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
