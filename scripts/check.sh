#!/usr/bin/env bash
# Repo gate: jaxlint (AST) -> jaxaudit (trace) -> telemetry smoke ->
# tier-1 tests — what CI (and a pre-push hook) runs.
#
#   scripts/check.sh                  # lint + audit + telemetry + fast tier
#   scripts/check.sh --lint-only
#   scripts/check.sh --audit-only
#   scripts/check.sh --telemetry-only
set -uo pipefail

cd "$(dirname "$0")/.."

run_lint() {
    echo "== jaxlint (sphexa_tpu/, baseline: jaxlint_baseline.json) =="
    python -m sphexa_tpu.devtools.lint sphexa_tpu \
        --baseline jaxlint_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxlint failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxlint: disable=JXLxxx -- reason' (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_audit() {
    echo "== jaxaudit (entry registry, baseline: jaxaudit_baseline.json) =="
    python -m sphexa_tpu.devtools.audit sphexa_tpu \
        --baseline jaxaudit_baseline.json
    local rc=$?
    if [ $rc -ne 0 ]; then
        echo "jaxaudit failed (rc=$rc); fix the findings or add an inline"
        echo "'# jaxaudit: disable=JXAxxx -- reason' on the entry"
        echo "registration (docs/STATIC_ANALYSIS.md)."
        exit $rc
    fi
}

run_telemetry() {
    echo "== telemetry smoke (5-step run -> sphexa-telemetry summary --strict) =="
    local dir rc
    dir=$(mktemp -d)
    env JAX_PLATFORMS=cpu python -m sphexa_tpu.app.main \
        --init sedov -n 8 -s 5 --quiet \
        --telemetry-dir "$dir/run" -o "$dir/out"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "telemetry smoke run failed (rc=$rc)"
        rm -rf "$dir"
        exit $rc
    fi
    # --strict: every event must validate against the v1 schema
    python -m sphexa_tpu.telemetry summary "$dir/run" --strict
    rc=$?
    rm -rf "$dir"
    if [ $rc -ne 0 ]; then
        echo "sphexa-telemetry summary failed (rc=$rc); schema drift or"
        echo "missing events — see docs/OBSERVABILITY.md."
        exit $rc
    fi
}

case "${1:-}" in
    --lint-only)
        run_lint
        exit 0
        ;;
    --audit-only)
        run_audit
        exit 0
        ;;
    --telemetry-only)
        run_telemetry
        exit 0
        ;;
esac

run_lint
run_audit
run_telemetry

echo "== tier-1 tests (fast tier, CPU) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
