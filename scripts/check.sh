#!/usr/bin/env bash
# Repo gate: jaxlint + tier-1 tests — what CI (and a pre-push hook) runs.
#
#   scripts/check.sh            # lint + fast tier
#   scripts/check.sh --lint-only
set -uo pipefail

cd "$(dirname "$0")/.."

echo "== jaxlint (sphexa_tpu/, baseline: jaxlint_baseline.json) =="
python -m sphexa_tpu.devtools.lint sphexa_tpu \
    --baseline jaxlint_baseline.json
lint_rc=$?
if [ $lint_rc -ne 0 ]; then
    echo "jaxlint failed (rc=$lint_rc); fix the findings or add an inline"
    echo "'# jaxlint: disable=JXLxxx -- reason' (docs/STATIC_ANALYSIS.md)."
    exit $lint_rc
fi

if [ "${1:-}" = "--lint-only" ]; then
    exit 0
fi

echo "== tier-1 tests (fast tier, CPU) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
