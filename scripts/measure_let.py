"""Measure the per-shard essential-set (LET) reduction vs the replicated
tree (VERDICT r4 #5 'Done' gate): |E_k| / num_nodes at 1M and 4M on 8
and 16 shards — the classification work and list-sort sizes each shard
carries under GravityConfig.let_cap.

Pure sizing (numpy classify, no solve): mirrors estimate_gravity_caps'
monotone-MAC classification with the slab bbox as the target.

Usage: JAX_PLATFORMS=cpu python scripts/measure_let.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp

from sphexa_tpu.gravity.traversal import compute_multipoles
from sphexa_tpu.gravity.tree import build_gravity_tree
from sphexa_tpu.init.plummer import sample_plummer as plummer
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sfc.keys import compute_sfc_keys

THETA = 0.5


def essential_sizes(n, shards=(8, 16)):
    x, y, z, m = plummer(n)
    r = float(np.max(np.abs(np.stack([x, y, z])))) * 1.001
    box = Box.create(-r, r, boundary=BoundaryType.open)
    keys = np.asarray(compute_sfc_keys(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (a[order] for a in (x, y, z, m))
    tree, meta = build_gravity_tree(keys[order], bucket_size=64)
    num_n = meta.num_nodes

    nm, com, _, _ = (np.asarray(a) for a in compute_multipoles(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs), jnp.asarray(ms),
        jnp.asarray(keys[order]), tree, meta))
    valid = nm > 0.0
    parent = np.asarray(tree.parent)
    lengths = np.asarray(box.lengths)
    lo = np.asarray([box.lo[0], box.lo[1], box.lo[2]], np.float64)
    geo_center = lo[None, :] + np.asarray(tree.center_frac) * lengths[None, :]
    geo_size = np.asarray(tree.halfsize_frac)[:, None] * lengths[None, :]
    l_node = 2.0 * geo_size.max(axis=1)
    s_off = np.linalg.norm(com - geo_center, axis=1)
    smax = np.where(valid, s_off, 0.0)
    BIG = 1e15
    com_lo = np.where(valid[:, None], com, BIG)
    com_hi = np.where(valid[:, None], com, -BIG)
    for s, e in reversed(meta.level_ranges[1:]):
        np.maximum.at(smax, parent[s:e], smax[s:e])
        np.minimum.at(com_lo, parent[s:e], com_lo[s:e])
        np.maximum.at(com_hi, parent[s:e], com_hi[s:e])
    ccenter = np.where(valid[:, None], 0.5 * (com_lo + com_hi), BIG)
    chalf = np.where(valid[:, None],
                     np.maximum(0.5 * (com_hi - com_lo), 0.0), 0.0)
    mac2 = (l_node / THETA + smax) ** 2
    self_parent = parent == np.arange(num_n)

    print(f"N={n}  nodes={num_n}  leaves={meta.num_leaves}")
    for P in shards:
        S = n // P
        sizes = []
        for k in range(P):
            sl = slice(k * S, (k + 1) * S)
            pmin = np.array([xs[sl].min(), ys[sl].min(), zs[sl].min()])
            pmax = np.array([xs[sl].max(), ys[sl].max(), zs[sl].max()])
            bc, bs = (pmax + pmin) / 2, (pmax - pmin) / 2
            d = np.maximum(
                np.abs(bc[None, :] - ccenter) - bs[None, :] - chalf, 0.0)
            accept = valid & ((d * d).sum(axis=1) >= mac2)
            anc = np.where(self_parent, False, accept[parent])
            sizes.append(int((~anc).sum()))
        sizes = np.asarray(sizes)
        print(f"  P={P:3d}: |E_k| mean={sizes.mean():8.0f} "
              f"max={sizes.max():8d}  vs nodes {num_n}  "
              f"reduction x{num_n / sizes.max():.2f} (max) "
              f"x{num_n / sizes.mean():.2f} (mean)")


def main():
    for n in (1_000_000, 4_000_000):
        essential_sizes(n)


if __name__ == "__main__":
    main()
