"""Peak-HBM audit of the VE step at increasing single-chip N
(VERDICT r3 #8): measure device peak_bytes_in_use after a settled step,
derive bytes/particle, and extrapolate to the 400^3 / 16-chip target
(64M particles -> 4M/chip).

Built on the shared HBM accounting surface (telemetry/memory.py): the
same per-device ``memory_stats()`` snapshot the runtime ``memory``
events stamp at manifest/post-compile/flush, so this script's numbers
and a run's events.jsonl are the same quantity. ``--profile-dir`` also
dumps a ``jax.profiler`` device-memory profile (pprof) per size — the
allocation-site breakdown behind a surprising peak.

Usage: [HBM_SIDES=100,126,159] python scripts/measure_hbm.py
       [--devices N] [--profile-dir DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation
from sphexa_tpu.telemetry.memory import (
    device_memory_snapshot,
    save_memory_profile,
)

SIDES = [int(s) for s in os.environ.get("HBM_SIDES", "100,126,159,200").split(",")]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=None,
                    help="shard over N devices (per-device peaks reported)")
    ap.add_argument("--profile-dir", default=None, dest="profile_dir",
                    help="write a device-memory profile (pprof) per size")
    args = ap.parse_args(argv)
    if args.profile_dir:
        os.makedirs(args.profile_dir, exist_ok=True)
    for side in SIDES:
        n = side ** 3
        try:
            state, box, const = init_sedov(side)
            if args.devices and n % args.devices:
                keep = (n // args.devices) * args.devices
                state = jax.tree.map(
                    lambda a: a[:keep] if getattr(a, "ndim", 0) == 1 else a,
                    state)
                n = keep
            sim = Simulation(state, box, const, prop="ve", block=8192,
                             check_every=5, num_devices=args.devices)
            for _ in range(5):
                sim.step()
            sim.flush()
            jax.block_until_ready(sim.state.x)
            snap = device_memory_snapshot()
            peaks = snap["peak_bytes_in_use"]
            lives = snap["bytes_in_use"]
            if not peaks:
                print(f"side={side} n={n} (backend reports no "
                      f"memory_stats — CPU?)", flush=True)
            else:
                peak, cur = max(peaks), max(lives)
                per_dev = "" if len(peaks) == 1 else (
                    "  per-dev peaks: "
                    + " ".join(f"{p/2**30:.2f}" for p in peaks))
                print(f"side={side} n={n} peak={peak/2**30:.2f} GiB "
                      f"({sum(peaks)/n:.0f} B/particle) "
                      f"live={cur/2**30:.2f} GiB{per_dev}", flush=True)
            if args.profile_dir:
                path = os.path.join(args.profile_dir, f"hbm_s{side}.pprof")
                if save_memory_profile(path):
                    print(f"  memory profile -> {path}", flush=True)
            del sim, state
        except Exception as e:
            print(f"side={side} n={n} FAILED: {type(e).__name__}: {e}"[:160],
                  flush=True)
            break
    # extrapolation guide printed for BASELINE.md
    print("target: 64M/16 chips = 4.0M particles/chip; v5e HBM = 16 GiB",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
