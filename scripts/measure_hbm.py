"""Peak-HBM audit of the VE step at increasing single-chip N
(VERDICT r3 #8): measure device peak_bytes_in_use after a settled step,
derive bytes/particle, and extrapolate to the 400^3 / 16-chip target
(64M particles -> 4M/chip).

Built on the shared HBM accounting surface (telemetry/memory.py): the
same per-device ``memory_stats()`` snapshot the runtime ``memory``
events stamp at manifest/post-compile/flush, so this script's numbers
and a run's events.jsonl are the same quantity. ``--profile-dir`` also
dumps a ``jax.profiler`` device-memory profile (pprof) per size — the
allocation-site breakdown behind a surprising peak.

``--calibrate`` prints the static JXA202 liveness estimate (the same
model ``sphexa-audit preflight`` gates the campaign on, evaluated at the
measured N — no rescale) next to each measured peak and exits 1 when
they diverge by more than 20%: the check that keeps the preflight gate
honest against real allocator behavior. On backends without
``memory_stats()`` (CPU) it prints the estimate alone and exits 0.

Usage: [HBM_SIDES=100,126,159] python scripts/measure_hbm.py
       [--devices N] [--profile-dir DIR] [--calibrate]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation
from sphexa_tpu.telemetry.memory import (
    device_memory_snapshot,
    save_memory_profile,
)

SIDES = [int(s) for s in os.environ.get("HBM_SIDES", "100,126,159,200").split(",")]


def _static_estimate(sim, n):
    """The JXA202 liveness model on the step this sim actually runs:
    per-device peak bytes at the measured N (ratio 0 = no campaign
    rescale), donation credited only when the sim donates."""
    import dataclasses

    from sphexa_tpu import propagator as prop
    from sphexa_tpu.devtools.audit.spmd import _peak_liveness

    cfg = sim._cfg
    P = 1
    if sim._mesh is not None:
        P = sim._mesh.size
        hi = sim._halo_info or {}
        cfg = dataclasses.replace(
            cfg, mesh=sim._mesh, shard_axis="p",
            halo_window=hi.get("wmax", 0),
            halo_cells=tuple(hi.get("caps", ())),
        )
    closed = jax.make_jaxpr(
        lambda s, b: prop.step_hydro_ve(s, b, cfg, None)
    )(sim.state, sim.box)
    donated = set()
    if sim._donate_active:
        donated = set(range(len(jax.tree_util.tree_leaves(sim.state))))
    peak, _ = _peak_liveness(closed.jaxpr, P, n // P, 0.0, donated)
    return peak


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=None,
                    help="shard over N devices (per-device peaks reported)")
    ap.add_argument("--profile-dir", default=None, dest="profile_dir",
                    help="write a device-memory profile (pprof) per size")
    ap.add_argument("--calibrate", action="store_true",
                    help="print the JXA202 static liveness estimate next "
                         "to each measured peak; exit 1 on >20% divergence")
    args = ap.parse_args(argv)
    if args.profile_dir:
        os.makedirs(args.profile_dir, exist_ok=True)
    worst_divergence = 0.0
    for side in SIDES:
        n = side ** 3
        try:
            state, box, const = init_sedov(side)
            if args.devices and n % args.devices:
                keep = (n // args.devices) * args.devices
                state = jax.tree.map(
                    lambda a: a[:keep] if getattr(a, "ndim", 0) == 1 else a,
                    state)
                n = keep
            sim = Simulation(state, box, const, prop="ve", block=8192,
                             check_every=5, num_devices=args.devices)
            for _ in range(5):
                sim.step()
            sim.flush()
            jax.block_until_ready(sim.state.x)
            snap = device_memory_snapshot()
            peaks = snap["peak_bytes_in_use"]
            lives = snap["bytes_in_use"]
            est = _static_estimate(sim, n) if args.calibrate else None
            if not peaks:
                suffix = ""
                if est is not None:
                    suffix = (f"  static estimate={est/2**30:.2f} GiB/dev "
                              f"(no measurement to calibrate against)")
                print(f"side={side} n={n} (backend reports no "
                      f"memory_stats — CPU?){suffix}", flush=True)
            else:
                peak, cur = max(peaks), max(lives)
                per_dev = "" if len(peaks) == 1 else (
                    "  per-dev peaks: "
                    + " ".join(f"{p/2**30:.2f}" for p in peaks))
                cal = ""
                if est is not None:
                    div = abs(est - peak) / peak
                    worst_divergence = max(worst_divergence, div)
                    cal = (f"  static={est/2**30:.2f} GiB "
                           f"(divergence {div:+.0%})")
                print(f"side={side} n={n} peak={peak/2**30:.2f} GiB "
                      f"({sum(peaks)/n:.0f} B/particle) "
                      f"live={cur/2**30:.2f} GiB{per_dev}{cal}", flush=True)
            if args.profile_dir:
                path = os.path.join(args.profile_dir, f"hbm_s{side}.pprof")
                if save_memory_profile(path):
                    print(f"  memory profile -> {path}", flush=True)
            del sim, state
        except Exception as e:
            print(f"side={side} n={n} FAILED: {type(e).__name__}: {e}"[:160],
                  flush=True)
            break
    # extrapolation guide printed for BASELINE.md
    print("target: 64M/16 chips = 4.0M particles/chip; v5e HBM = 16 GiB",
          flush=True)
    if args.calibrate and worst_divergence > 0.20:
        print(f"CALIBRATION FAILED: static estimate diverges "
              f"{worst_divergence:.0%} from measured peak (>20%) — "
              f"re-derive the JXA202 liveness model before trusting "
              f"preflight", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
