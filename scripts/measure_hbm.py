"""Peak-HBM audit of the VE step at increasing single-chip N
(VERDICT r3 #8): measure device peak_bytes_in_use after a settled step,
derive bytes/particle, and extrapolate to the 400^3 / 16-chip target
(64M particles -> 4M/chip).

Usage: [HBM_SIDES=100,126,159] python scripts/measure_hbm.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation

SIDES = [int(s) for s in os.environ.get("HBM_SIDES", "100,126,159,200").split(",")]


def peak_bytes():
    st = jax.local_devices()[0].memory_stats() or {}
    return st.get("peak_bytes_in_use", 0), st.get("bytes_in_use", 0)


def main():
    for side in SIDES:
        n = side ** 3
        try:
            state, box, const = init_sedov(side)
            sim = Simulation(state, box, const, prop="ve", block=8192,
                             check_every=5)
            for _ in range(5):
                sim.step()
            sim.flush()
            jax.block_until_ready(sim.state.x)
            peak, cur = peak_bytes()
            print(f"side={side} n={n} peak={peak/2**30:.2f} GiB "
                  f"({peak/n:.0f} B/particle) live={cur/2**30:.2f} GiB",
                  flush=True)
            del sim, state
        except Exception as e:
            print(f"side={side} n={n} FAILED: {type(e).__name__}: {e}"[:160],
                  flush=True)
            break
    # extrapolation guide printed for BASELINE.md
    print("target: 64M/16 chips = 4.0M particles/chip; v5e HBM = 16 GiB",
          flush=True)


if __name__ == "__main__":
    main()
