"""Hierarchical (superblock) MAC at the tree scale it exists for
(VERDICT r3 #4): a synthetic Plummer sphere at N >= 1e6 builds a
>=1e5-node tree; the dense blocks-x-nodes classification is compared
against the two-level super_factor path (GravityConfig.super_factor),
with mac_work_ratio and end-to-end solve throughput reported.

Usage: [N_PARTS=4000000] [THETA=0.5] python scripts/bench_gravity_scale.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.gravity.traversal import (
    GravityConfig,
    compute_gravity,
    estimate_gravity_caps,
)
from sphexa_tpu.gravity.tree import build_gravity_tree
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sfc.keys import compute_sfc_keys

N = int(os.environ.get("N_PARTS", "4000000"))
THETA = float(os.environ.get("THETA", "0.5"))
BUCKET = int(os.environ.get("BUCKET", "64"))
SUPER = int(os.environ.get("SUPER", "8"))


from sphexa_tpu.init.plummer import sample_plummer as plummer


def time_solve(tag, args, cfg, iters=3):
    out = compute_gravity(*args, cfg)
    jax.block_until_ready(out)
    # warmup batch (first post-compile run is an outlier on axon)
    out = compute_gravity(*args, cfg)
    jax.block_until_ready(out)
    _ = float(out[3])
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compute_gravity(*args, cfg)
        jax.block_until_ready(out)
        _ = float(out[3])
        best = min(best, (time.perf_counter() - t0) / iters)
    d = {k: float(v) for k, v in out[4].items()}
    print(f"{tag}: {best*1e3:9.1f} ms  {N/best/1e6:6.2f}M parts/s  "
          f"egrav={float(out[3]):+.6e}  mac_work_ratio={d['mac_work_ratio']:.4f} "
          f"m2p={int(d['m2p_max'])} p2p={int(d['p2p_max'])} "
          f"c_max={int(d['c_max'])}", flush=True)
    return best, out


def main():
    x, y, z, m = plummer(N)
    r = float(np.max(np.abs(np.stack([x, y, z])))) * 1.001
    box = Box.create(-r, r, boundary=BoundaryType.open)
    keys = np.asarray(compute_sfc_keys(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (jnp.asarray(a[order]) for a in (x, y, z, m))
    skeys = jnp.asarray(keys[order])
    t0 = time.perf_counter()
    gtree, meta = build_gravity_tree(keys[order], bucket_size=BUCKET)
    print(f"N={N} tree: {meta.num_nodes} nodes / {meta.num_leaves} leaves "
          f"({time.perf_counter()-t0:.1f}s host build)", flush=True)
    hs = jnp.full_like(xs, 1e-3)

    args = (xs, ys, zs, ms, hs, skeys, box, gtree, meta)
    results = {}
    compaction = os.environ.get("COMPACT", "sort")  # sort | bitmask
    # hierarchical pre-pass factor: the SAME env name the sibling
    # profile_gravity_phases.py reads; 0 keeps the flat sweep
    sf_env = SUPER if compaction == "bitmask" else 0
    for tb in (64, 128, 256, 512):
        base = GravityConfig(theta=THETA, bucket_size=BUCKET, G=1.0,
                             target_block=tb,
                             blocks_per_chunk=max(4, 2048 // tb),
                             compaction=compaction, super_factor=sf_env,
                             use_pallas=jax.default_backend() == "tpu")
        cfg0 = estimate_gravity_caps(xs, ys, zs, ms, skeys, box, gtree,
                                     meta, base, margin=1.6)
        print(f"tb={tb}: caps m2p={cfg0.m2p_cap} p2p={cfg0.p2p_cap} "
              f"leaf={cfg0.leaf_cap}", flush=True)
        try:
            results[tb] = time_solve(f"dense tb={tb:4d}", args, cfg0)
        except Exception as e:
            print(f"tb={tb} FAILED: {type(e).__name__}: {e}"[:160],
                  flush=True)
    tbs = sorted(results)
    if len(tbs) >= 2:
        a0 = np.asarray(results[tbs[0]][1][0])
        a1 = np.asarray(results[tbs[-1]][1][0])
        scale = np.max(np.abs(a0))
        print(f"max|da|/max|a| (tb {tbs[0]} vs {tbs[-1]}) = "
              f"{np.max(np.abs(a0-a1))/scale:.3e}", flush=True)


if __name__ == "__main__":
    main()
