"""Quick interpret-mode equivalence check of the pallas engine vs the XLA
gather path (CPU, small Sedov). Dev harness; the CI version is
tests/test_pallas_interpret.py."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.simulation import make_propagator_config
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp


def main():
    state, box, const = init_sedov(14)
    cfg = make_propagator_config(state, box, const, block=4096, backend="pallas")
    ss, keys, _ = _sort_by_keys(state, box, "hilbert")
    nbr = cfg.nbr
    print(f"n={state.n} level={nbr.level} cap={nbr.cap} window={nbr.window}")

    nidx, nmask, nc0, occ0 = find_neighbors(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    rho0 = hydro_std.compute_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, nidx, nmask, box, const, 4096
    )

    ranges = pp.group_cell_ranges(ss.x, ss.y, ss.z, ss.h, keys, box, nbr)
    print("ncells mean/max:", float(jnp.mean(ranges.ncells.astype(jnp.float32))),
          int(jnp.max(ranges.ncells)), "of", nbr.window ** 3,
          "occ", int(ranges.occupancy))
    rho1, nc1, occ = pp.pallas_density(
        ss.x, ss.y, ss.z, ss.h, ss.m, keys, box, const, nbr,
        ranges=ranges, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(nc1), np.asarray(nc0))
    np.testing.assert_allclose(np.asarray(rho1), np.asarray(rho0), rtol=1e-5)
    print("density OK")

    p, c = hydro_std.compute_eos_std(ss.temp, rho0, const)
    cs0 = hydro_std.compute_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho0, nidx, nmask, box, const, 4096
    )
    cs1, _ = pp.pallas_iad(
        ss.x, ss.y, ss.z, ss.h, ss.m / rho0, keys, box, const, nbr,
        ranges=ranges, interpret=True,
    )
    scale = float(jnp.max(jnp.abs(cs0[0])))
    for a, b in zip(cs0, cs1):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5 * scale, rtol=1e-4
        )
    print("iad OK")

    me0 = hydro_std.compute_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho0, p, c,
        *cs0, nidx, nmask, box, const, 4096,
    )
    *me1, _ = pp.pallas_momentum_energy_std(
        ss.x, ss.y, ss.z, ss.vx, ss.vy, ss.vz, ss.h, ss.m, rho0, p, c,
        *cs1, keys, box, const, nbr, ranges=ranges, interpret=True,
    )
    for a, b in zip(me0[:4], me1[:4]):
        s = float(jnp.max(jnp.abs(a))) + 1e-12
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-6 * s, rtol=1e-4
        )
    assert abs(float(me1[4]) - float(me0[4])) < 1e-5 * abs(float(me0[4]))
    print("momentum OK")


if __name__ == "__main__":
    main()
