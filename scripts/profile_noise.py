"""Run-to-run variance probe: time the SAME fused std pipeline 8 times."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))


def main():
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    for _ in range(2):
        sim.step()
    state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, _, _ = _sort_by_keys(state, box, "hilbert")

    cfg = make_propagator_config(state, box, const, block=8192,
                                 backend="pallas")
    nbr = cfg.nbr

    @jax.jit
    def pipe(x, y, z, h, m, temp, vx, vy, vz):
        keys = jnp.sort(compute_sfc_keys(x, y, z, box))
        ranges = pp.group_cell_ranges(x, y, z, h, keys, box, nbr)
        rho, nc, occ = pp.pallas_density(
            x, y, z, h, m, keys, box, const, nbr, ranges=ranges)
        p, c = hydro_std.compute_eos_std(temp, rho, const)
        cs, _ = pp.pallas_iad(
            x, y, z, h, m / rho, keys, box, const, nbr, ranges=ranges)
        out = pp.pallas_momentum_energy_std(
            x, y, z, vx, vy, vz, h, m, rho, p, c, *cs,
            keys, box, const, nbr, ranges=ranges)
        return out[0]

    args = (state.x, state.y, state.z, state.h, state.m, state.temp,
            state.vx, state.vy, state.vz)
    out = pipe(*args)
    jax.block_until_ready(out)
    for r in range(8):
        t0 = time.perf_counter()
        for _ in range(3):
            out = pipe(*args)
        jax.block_until_ready(out)
        _ = float(jnp.sum(out))
        dt = (time.perf_counter() - t0) / 3
        print(f"run {r}: {dt*1e3:8.2f} ms", flush=True)


if __name__ == "__main__":
    main()
