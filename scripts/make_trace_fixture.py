#!/usr/bin/env python
"""Regenerate tests/trace_fixture — the committed miniature capture the
traceview and calibration tests pin against.

The fixture is one jit program with three ``sphexa/<phase>`` scopes
(density: a dot + tanh; neighbors: a cumsum, whose CPU lowering
exercises the metadata-less computation-inheritance path; momentum-
energy: elementwise) plus a deliberately UNSCOPED tail dot, so the
capture's coverage sits strictly between the 0.5 and 0.999 gates the
fixture tests pin.

The same program is exported as ``@entrypoint("trace_fixture")`` so it
is also the CALIBRATION TARGET: ``calibration.json`` records, per
phase, the measured-us / statically-predicted-us ratio of this exact
capture at the cpu-smoke device model. ``sphexa-telemetry trace
tests/trace_fixture --predict`` re-predicts (pure arithmetic — fully
deterministic) and fails when a fresh ratio leaves the recorded band:
a per-primitive cost rule drifting silently is exactly what it catches.

Usage (from the repo root; writes tests/trace_fixture/*):

    JAX_PLATFORMS=cpu python scripts/make_trace_fixture.py
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python scripts/...` from anywhere
    sys.path.insert(0, _REPO)

from sphexa_tpu.devtools.audit.core import EntryCase, entrypoint  # noqa: E402
_DEST = os.path.join(_REPO, "tests", "trace_fixture")
#: repo-relative target recorded in calibration.json (resolved by
#: sphexa-audit's _load_target, so --predict must run from the root)
_TARGET = "scripts/make_trace_fixture.py::trace_fixture"
_DEVICE = "cpu-smoke"
_TOLERANCE = 2.0
_PHASES = ("density", "momentum-energy", "neighbors")

_SIDE = 384          # density dot M=N=K
_ROWS, _COLS = 4096, 256


def _arrays():
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((_SIDE, _SIDE)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((_SIDE, _SIDE)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((_ROWS, _COLS)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((_COLS, 64)), jnp.float32)
    return a, b, v, w


def _step(a, b, v, w):
    import jax.numpy as jnp

    from sphexa_tpu.util.phases import phase_scope

    with phase_scope("density"):
        d = jnp.tanh(a @ b) + a
    with phase_scope("neighbors"):
        nb = jnp.cumsum(v, axis=0)
    with phase_scope("momentum-energy"):
        m = nb * 0.5 + jnp.sin(nb)
    # deliberately UNSCOPED tail: keeps the capture's coverage below
    # the 0.999 gate the fixture tests pin (and above 0.5)
    return d.sum() + (m @ w).sum()


@entrypoint("trace_fixture")
def trace_fixture():
    return EntryCase(fn=_step, args=_arrays())


def _flatten_capture(tmp: str) -> None:
    """Copy the newest xplane + perfetto dump flat into tests/
    trace_fixture under the committed names."""
    xp = sorted(glob.glob(os.path.join(tmp, "**", "*.xplane.pb"),
                          recursive=True), key=os.path.getmtime)
    tj = sorted(glob.glob(os.path.join(tmp, "**", "*.trace.json.gz"),
                          recursive=True), key=os.path.getmtime)
    if not xp or not tj:
        raise SystemExit(f"profiler produced no capture under {tmp} "
                         f"(xplanes={xp}, traces={tj})")
    os.makedirs(_DEST, exist_ok=True)
    shutil.copy(xp[-1], os.path.join(_DEST, "vm.xplane.pb"))
    shutil.copy(tj[-1], os.path.join(_DEST, "vm.trace.json.gz"))


def main() -> int:
    import jax

    from sphexa_tpu.devtools.audit.costmodel import (
        CALIBRATION_FILE,
        predict_for_target,
    )
    from sphexa_tpu.telemetry.traceview import summarize_trace

    case = trace_fixture.build()
    step = jax.jit(case.fn)
    step(*case.args).block_until_ready()  # compile OUTSIDE the capture

    with tempfile.TemporaryDirectory() as tmp:
        with jax.profiler.trace(tmp):
            for _ in range(3):
                step(*case.args).block_until_ready()
        _flatten_capture(tmp)

    s = summarize_trace(_DEST)
    phases = {p["phase"]: p["us"] for p in s["phases"]}
    print(f"capture: {s['device_op_events']} device ops, "
          f"{s['total_device_us']:.1f}us, coverage {s['coverage']:.4f}")
    for ph, us in sorted(phases.items(), key=lambda kv: -kv[1]):
        print(f"  {ph:18s} {us:10.1f}us")
    missing = [p for p in _PHASES if phases.get(p, 0) <= 0]
    if missing:
        raise SystemExit(f"fixture lost phases {missing} — the capture "
                         f"does not satisfy the test pins; not writing "
                         f"calibration")
    if not 0.5 < s["coverage"] < 0.999:
        raise SystemExit(f"coverage {s['coverage']:.4f} outside the "
                         f"(0.5, 0.999) band the fixture tests pin")

    pred = predict_for_target(_TARGET, _DEVICE)
    doc = {
        "schema": 1,
        "target": _TARGET,
        "device": _DEVICE,
        "tolerance": _TOLERANCE,
        "phases": {},
    }
    for ph in _PHASES:
        row = pred.row(ph)
        if row is None or row.ms <= 0:
            raise SystemExit(f"no static prediction for phase {ph!r}")
        doc["phases"][ph] = {
            "ratio": phases[ph] / (row.ms * 1e3),
            "measured_us": phases[ph],
            "predicted_us": row.ms * 1e3,
        }
    path = os.path.join(_DEST, CALIBRATION_FILE)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"wrote {path}")
    for ph, spec in sorted(doc["phases"].items()):
        print(f"  {ph:18s} ratio {spec['ratio']:10.3f}  "
              f"(measured {spec['measured_us']:.1f}us / predicted "
              f"{spec['predicted_us']:.3f}us)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
